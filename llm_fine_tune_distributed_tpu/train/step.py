"""The jit-compiled train/eval steps — the hot loop the reference delegates to
TRL/HF (``trainer.train()``, reference ``training.py:300``; loop anatomy in
SURVEY.md §3.1). One XLA program per optimizer step:

  scan over grad-accum microbatches (fwd+bwd, remat'd blocks)
  -> mean grads -> clip(1.0) -> AdamW on trainable subset -> new state

Gradient synchronization across data-parallel devices is NOT explicit: the
loss averages over the (sharded) global microbatch, so jax.grad's psum is
emitted by XLA from the sharding annotations — the compiler-native equivalent
of DDP's bucketed NCCL all-reduce (reference ``docs/architecture-diagram.md:119-135``).
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import optax

from llm_fine_tune_distributed_tpu.config import ModelConfig, TrainConfig, str_to_dtype
from llm_fine_tune_distributed_tpu.models.transformer import forward, unembed
from llm_fine_tune_distributed_tpu.train.state import TrainState
from llm_fine_tune_distributed_tpu.utils.tree import merge_flat


def chunked_ce_sum(params, hidden, targets, mask, model_config: ModelConfig, chunk_size: int, compute_dtype, mesh=None, extra_mask=None):
    """Masked cross-entropy SUM computed in sequence chunks.

    Unembeds ``chunk_size`` positions at a time (each chunk rematerialized on
    backward) so peak HBM holds one [batch, chunk, vocab] f32 tile instead of
    the full [batch, seq, vocab] logits — what makes 128k-vocab models
    trainable on a 16GB chip at seq 1024.

    ``extra_mask``: optional second mask — returns (sum, extra_sum) from ONE
    streamed unembed (the answer-only eval metric must not double the eval
    pause it exists to diagnose).
    """
    b, s, h = hidden.shape
    pad = (-s) % chunk_size
    if pad:
        hidden = jnp.pad(hidden, ((0, 0), (0, pad), (0, 0)))
        targets = jnp.pad(targets, ((0, 0), (0, pad)))
        mask = jnp.pad(mask, ((0, 0), (0, pad)))
        if extra_mask is not None:
            extra_mask = jnp.pad(extra_mask, ((0, 0), (0, pad)))
    n = (s + pad) // chunk_size
    # [n_chunks, batch, chunk, ...] so lax.map scans over chunks
    hc = hidden.reshape(b, n, chunk_size, h).transpose(1, 0, 2, 3)
    tc = targets.reshape(b, n, chunk_size).transpose(1, 0, 2)
    masks = (mask,) if extra_mask is None else (mask, extra_mask)
    mcs = tuple(m.reshape(b, n, chunk_size).transpose(1, 0, 2) for m in masks)

    @jax.checkpoint
    def one_chunk(args):
        h_c, t_c, m_cs = args
        logits = unembed(params, h_c, model_config, compute_dtype=compute_dtype, mesh=mesh)
        ce = optax.softmax_cross_entropy_with_integer_labels(logits, t_c)
        return jnp.stack([(ce * m).sum() for m in m_cs])

    sums = jax.lax.map(one_chunk, (hc, tc, mcs)).sum(axis=0)
    if extra_mask is None:
        return sums[0]
    return sums[0], sums[1]


def vocab_chunked_ce_sum(params, hidden, targets, mask, model_config: ModelConfig,
                         vocab_chunk: int, compute_dtype, mesh=None, extra_mask=None):
    """Masked cross-entropy SUM streamed over VOCAB chunks (online logsumexp).

    The full-logits path materializes a [batch, seq, vocab] float32 tensor
    (~1 GB at the flagship's microbatch) and re-reads it through logsumexp,
    gather, and the softmax backward. Here the unembed runs one
    [hidden, vocab_chunk] slice at a time carrying (running max, running
    exp-sum, gold logit) — the logits tensor never exists in fwd OR bwd
    (the chunk body is rematerialized on backward: one extra matmul per
    chunk instead of the f32 logits residual). Measured 2.5-3x faster than
    the full path at flagship shapes in isolation (BASELINE.md perf ledger).
    """
    V = model_config.vocab_size
    if V % vocab_chunk:
        raise ValueError(
            f"vocab_size {V} not divisible by loss_vocab_chunk {vocab_chunk}"
        )
    n = V // vocab_chunk
    b, s, h = hidden.shape
    x = hidden.astype(compute_dtype).reshape(b * s, h)
    flat_targets = targets.reshape(-1)

    tied = model_config.tie_word_embeddings
    table = (
        params["model"]["embed_tokens"]["weight"]
        if tied
        else params["lm_head"]["kernel"]
    )
    if mesh is not None:
        # same layout treatment unembed() applies: vocab over tensor, hidden
        # gathered — without it GSPMD reshards the activations (and their
        # cotangents) through a replicate-then-repartition fallback on every
        # scan iteration's slice
        from llm_fine_tune_distributed_tpu.models.transformer import (
            _lookup_table_constraint,
        )

        table = _lookup_table_constraint(table, mesh, vocab_dim=0 if tied else 1)

    @jax.checkpoint
    def body(carry, i):
        m, acc, gold = carry
        if tied:  # [V, H] slice -> logits via x @ Wc^T
            wc = jax.lax.dynamic_slice(
                table, (i * vocab_chunk, 0), (vocab_chunk, h)
            ).astype(compute_dtype)
            lg = (x @ wc.T).astype(jnp.float32)
        else:  # [H, V] slice
            wc = jax.lax.dynamic_slice(
                table, (0, i * vocab_chunk), (h, vocab_chunk)
            ).astype(compute_dtype)
            lg = (x @ wc).astype(jnp.float32)
        if model_config.final_logit_softcap is not None:
            from llm_fine_tune_distributed_tpu.ops.attention import softcap

            # Gemma2 softcap is elementwise per logit, so it streams
            lg = softcap(lg, model_config.final_logit_softcap)
        m_new = jnp.maximum(m, lg.max(axis=-1))
        acc = acc * jnp.exp(m - m_new) + jnp.exp(lg - m_new[:, None]).sum(-1)
        loc = flat_targets - i * vocab_chunk
        hit = (loc >= 0) & (loc < vocab_chunk)
        g = jnp.take_along_axis(
            lg, jnp.clip(loc, 0, vocab_chunk - 1)[:, None], axis=-1
        )[:, 0]
        return (m_new, acc, jnp.where(hit, g, gold)), None

    init = (
        jnp.full((b * s,), -1e30, jnp.float32),
        jnp.zeros((b * s,), jnp.float32),
        jnp.zeros((b * s,), jnp.float32),
    )
    (m, acc, gold), _ = jax.lax.scan(body, init, jnp.arange(n))
    ce = m + jnp.log(acc) - gold  # == logsumexp(logits) - logits[target]
    ce = ce.reshape(b, s)
    if extra_mask is not None:
        # per-token ce already materialized — the second metric is one more
        # masked reduction, no extra unembed streaming
        return (ce * mask).sum(), (ce * extra_mask).sum()
    return (ce * mask).sum()


def static_seq_parallel_size(
    model_config: ModelConfig, train_config: TrainConfig, mesh
) -> int:
    """The seq-axis sharding factor that will ACTUALLY apply at runtime, as
    far as it is statically decidable — the auto remat policy keys on
    per-chip sequence length, and a provisioned-but-unused (or fallen-back)
    seq axis must count as full per-chip seq or auto under-remats and OOMs
    at long context (ADVICE r4). Mirrors the trainer's seq_sharded predicate
    plus the static half of seq_parallel_preconditions
    (parallel/ring_attention.py); the batch-divisibility precondition is
    satisfied by construction for trainer-built batches
    (global batch = per_device_batch_size * data * fsdp)."""
    from llm_fine_tune_distributed_tpu.parallel.ring_attention import (
        seq_parallel_static_preconditions,
    )
    from llm_fine_tune_distributed_tpu.parallel.ulysses import (
        ulysses_static_preconditions,
    )

    if mesh is None or train_config.attention_impl not in ("ring", "ulysses"):
        return 1
    n = mesh.shape.get("seq", 1)
    if n <= 1:
        return 1
    if not seq_parallel_static_preconditions(
        train_config.max_seq_length, model_config.num_heads,
        model_config.num_kv_heads, mesh,
        sliding_window=model_config.sliding_window,
    ):
        return 1  # runtime fallback -> full per-chip sequence
    if train_config.attention_impl == "ulysses" and not ulysses_static_preconditions(
        model_config.num_heads, model_config.num_kv_heads, mesh
    ):
        return 1
    return n


def make_loss_fn(model_config: ModelConfig, train_config: TrainConfig, activation_sharding=None,
                 quant_impl: Optional[str] = None, include_router_aux: bool = True,
                 frozen_layers: int = 0):
    compute_dtype = str_to_dtype(train_config.compute_dtype)
    _mesh = getattr(activation_sharding, "mesh", None)
    seq_parallel = static_seq_parallel_size(model_config, train_config, _mesh)
    remat_policy = train_config.resolved_remat_policy(model_config, seq_parallel)
    chunk = train_config.loss_chunk_size
    vocab_chunk = getattr(train_config, "loss_vocab_chunk", None)
    if chunk is not None and vocab_chunk is not None:
        raise ValueError(
            "loss_chunk_size (sequence chunking) and loss_vocab_chunk "
            "(vocab streaming) are mutually exclusive"
        )
    quant_impl = quant_impl or train_config.quant_matmul_impl
    # Frozen-trunk fast path (ISSUE 20): frozen_layers is the trainable
    # boundary (parallel/freeze.frozen_trunk_boundary) the trainer computed
    # from the freeze mask; forward() runs those leading layers w8a8 with a
    # boundary stop_gradient when frozen_compute="int8". The default "bf16"
    # (or boundary 0 — lora/qlora/full fine-tune) leaves forward untouched.
    frozen_compute = getattr(train_config, "frozen_compute", "bf16")
    if frozen_compute not in ("bf16", "int8"):
        raise ValueError(
            f"unknown frozen_compute {frozen_compute!r} (expected 'bf16' or 'int8')"
        )
    # MoE: add the load-balancing aux loss to the TRAIN objective only (eval
    # loss stays pure CE so perplexity/best-model tracking is comparable with
    # dense runs). Dense models skip the plumbing entirely.
    want_aux = include_router_aux and model_config.num_experts > 0

    def loss_fn(trainable, frozen, batch):
        """Masked next-token cross-entropy (token-mean within the batch) —
        the SFT objective TRL computes for packing=False full-sequence LM
        loss (reference ``training.py:282-283``). Returns (loss, token_count).

        When the batch additionally carries a ``completion_mask`` (eval
        batches only — trainer._prepare_data), returns
        (loss, tokens, answer_ce_sum, answer_tokens): the completion-span CE
        computed from the SAME forward pass, so the answer-only eval metric
        (VERDICT r4 #4 — the full-sequence eval_loss is dominated by the
        constant system prompt) costs one extra masked reduction on the
        full-logits path (and one extra streamed unembed on the chunked
        paths, which rematerialize per-mask)."""
        params = merge_flat(trainable, frozen)
        packed_kw = {}
        if "segment_ids" in batch:  # packing=True path (data/packing.py)
            packed_kw = {
                "segment_ids": batch["segment_ids"],
                "positions": batch["positions"],
            }
        result = forward(
            params,
            batch["input_ids"],
            model_config,
            padding_mask=batch["attention_mask"],
            **packed_kw,
            attention_impl=train_config.attention_impl,
            compute_dtype=compute_dtype,
            remat=train_config.gradient_checkpointing,
            remat_policy=remat_policy,
            activation_sharding=activation_sharding,
            logits_dtype=jnp.float32,
            output_hidden=chunk is not None or vocab_chunk is not None,
            quant_impl=quant_impl,
            return_aux=want_aux,
            frozen_layers=frozen_layers,
            frozen_compute=frozen_compute,
        )
        out = result[0]
        targets = batch["input_ids"][:, 1:]
        mask = batch["loss_mask"][:, 1:].astype(jnp.float32)
        tokens = jnp.maximum(mask.sum(), 1.0)
        _mesh_kw = getattr(activation_sharding, "mesh", None)
        amask = None
        if "completion_mask" in batch:
            amask = batch["completion_mask"][:, 1:].astype(jnp.float32)
        # ce_fn(mask) -> sum; ce_fn(mask, extra) -> (sum, extra_sum) from a
        # SINGLE unembed on every path
        if vocab_chunk is not None:
            ce_fn = lambda m, e=None: vocab_chunked_ce_sum(
                params, out[:, :-1], targets, m, model_config, vocab_chunk,
                compute_dtype, mesh=_mesh_kw, extra_mask=e,
            )
        elif chunk is not None:
            ce_fn = lambda m, e=None: chunked_ce_sum(
                params, out[:, :-1], targets, m, model_config, chunk,
                compute_dtype, mesh=_mesh_kw, extra_mask=e,
            )
        else:
            ce = optax.softmax_cross_entropy_with_integer_labels(out[:, :-1], targets)
            ce_fn = lambda m, e=None: (
                (ce * m).sum() if e is None else ((ce * m).sum(), (ce * e).sum())
            )
        if amask is not None:
            ce_sum, ans_sum = ce_fn(mask, amask)
        else:
            ce_sum = ce_fn(mask)
        loss = ce_sum / tokens
        if want_aux:
            # layer-MEAN of the per-layer aux (forward returns the sum), so
            # router_aux_coef is depth-independent — matching the effective
            # scale of HF Mixtral's router_aux_loss_coef rather than growing
            # the balancing pressure 32x on a 32-layer model
            aux = result[2] / model_config.num_layers
            loss = loss + model_config.router_aux_coef * aux
        if amask is not None:
            return loss, tokens, ans_sum, amask.sum()
        return loss, tokens

    return loss_fn


def build_train_step(
    model_config: ModelConfig,
    train_config: TrainConfig,
    optimizer: optax.GradientTransformation,
    activation_sharding=None,
    quant_impl: Optional[str] = None,
    frozen_layers: int = 0,
) -> Callable:
    """Returns train_step(state, batch) -> (state, metrics).

    ``batch`` arrays are [grad_accum, per_device_or_host_batch, seq]; the
    accumulation loop is a lax.scan so XLA compiles ONE program regardless of
    the accumulation factor (reference ``gradient_accumulation_steps=4``,
    ``training.py:262``).
    """
    loss_fn = make_loss_fn(
        model_config, train_config, activation_sharding, quant_impl,
        frozen_layers=frozen_layers,
    )
    accum = train_config.gradient_accumulation_steps

    def train_step(state: TrainState, batch):
        grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

        def micro_step(carry, micro):
            g_acc, loss_acc = carry
            (loss, _tokens), grads = grad_fn(state.trainable, state.frozen, micro)
            g_acc = jax.tree.map(jnp.add, g_acc, grads)
            return (g_acc, loss_acc + loss), None

        zeros = jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), state.trainable)
        (g_sum, loss_sum), _ = jax.lax.scan(micro_step, (zeros, jnp.float32(0.0)), batch)

        # Mean over accumulation steps (HF semantics: mean of microbatch means).
        grads = jax.tree.map(lambda g: g / accum, g_sum)
        loss = loss_sum / accum

        grad_norm = optax.global_norm(grads)  # pre-clip, matches HF's logged grad_norm
        updates, new_opt_state = optimizer.update(grads, state.opt_state, state.trainable)
        new_trainable = optax.apply_updates(state.trainable, updates)

        new_state = state.replace(
            step=state.step + 1,
            trainable=new_trainable,
            opt_state=new_opt_state,
        )
        metrics = {
            "loss": loss,
            "grad_norm": grad_norm,
        }
        return new_state, metrics

    return train_step


def build_eval_step(
    model_config: ModelConfig,
    train_config: TrainConfig,
    activation_sharding=None,
    quant_impl: Optional[str] = None,
    frozen_layers: int = 0,
) -> Callable:
    """eval_step(state, batch[b, s]) -> (sum_ce, token_count), or
    (sum_ce, tokens, answer_sum_ce, answer_tokens) when the batch carries a
    ``completion_mask`` (the answer-only eval metric, VERDICT r4 #4).

    Returns sums (not means) so the caller aggregates a token-weighted eval
    loss over the whole validation set — the quantity behind
    ``eval_loss``/best-model tracking (reference ``training.py:273-275``)."""
    loss_fn = make_loss_fn(
        model_config, train_config, activation_sharding, quant_impl,
        include_router_aux=False, frozen_layers=frozen_layers,
    )

    def eval_step(state: TrainState, batch):
        out = loss_fn(state.trainable, state.frozen, batch)
        if len(out) == 4:
            loss, tokens, ans_ce, ans_tokens = out
            return loss * tokens, tokens, ans_ce, ans_tokens
        loss, tokens = out
        return loss * tokens, tokens

    return eval_step


def jit_train_step(train_step, donate_state: bool = True):
    """Jit with state donation — the step's output state reuses the input
    buffers (param + opt-state memory is not duplicated during the update)."""
    return jax.jit(train_step, donate_argnums=(0,) if donate_state else ())
