"""Training control plane: live trainer introspection + anomaly sentinels.

The serving stack has been deeply observable for a while (/metrics,
/v1/stats, flight recorder, SLO burn rates); the trainer was log lines and
a JSON dump at exit. This module gives a *running* training job the same
surface, served from a primary-host-only HTTP thread that never touches
the step hot path:

- ``GET /metrics`` — Prometheus text exposition (``training_*`` prefix):
  loss/grad-norm/lr gauges, throughput, the per-step phase histograms
  (data_wait / step / checkpoint), compile-ledger counters, roofline
  MFU / HBM-BW gauges, the preemption flag, and
  ``training_anomalies_total{kind=...}``.
- ``GET /v1/train/status`` — step/epoch/ETA, last + best eval, checkpoint
  and publish history, anomaly summary.
- ``GET /v1/train/flight`` — the trainer-owned FlightRecorder ring: step
  milestones, evals, checkpoint save/restore, publishes, watchdog events,
  SIGTERM/preemption.
- ``POST /v1/train/profile`` — on-demand ``jax.profiler`` capture
  (observe/xla.ProfilerCapture), one at a time.

**Anomaly sentinels** watch the per-step metric stream host-side: a hard
non-finite detector (NaN/Inf loss or grad norm) plus EWMA-band detectors
for loss spikes and grad-norm explosions. Every firing lands as a flight
event and a ``training_anomalies_total{kind=}`` increment, and gates
publication: a checkpoint whose trailing window contains an anomaly is
published with ``anomaly_clean: false`` (or skipped outright under
``publish_require_clean``), so the serving side can refuse to promote a
checkpoint cut mid-divergence.

Hot-path discipline: the trainer feeds the sentinels and the status dict
ONLY at its existing log/eval/save boundaries, where the metric scalars
have already been synced to the host — zero extra clock reads or device
syncs ride the per-step loop. Everything here is host-side bookkeeping
read by HTTP handler threads under a lock.
"""

from __future__ import annotations

import hashlib
import json
import math
import threading
import uuid
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional

from llm_fine_tune_distributed_tpu.observe.metrics import (
    PROMETHEUS_CONTENT_TYPE,
    prometheus_exposition,
)
from llm_fine_tune_distributed_tpu.observe.tracing import FlightRecorder
from llm_fine_tune_distributed_tpu.runtime.distributed import is_primary_host

__all__ = [
    "ANOMALY_KINDS",
    "TRAIN_COUNTERS",
    "TRAIN_GAUGES",
    "TRAIN_HIST_KEYS",
    "AnomalySentinels",
    "TrainTelemetry",
    "TrainControlPlane",
    "hparams_digest",
    "new_run_id",
    "trainer_exposition",
]

# Sentinel taxonomy. The exposition seeds every kind at 0 unconditionally
# so the metric schema is identical on a healthy run (the same
# load-independence contract the serving shed-tier counter keeps).
ANOMALY_KINDS = ("non_finite", "loss_spike", "grad_explosion")

# Monotonic trainer counters -> ``training_<name>_total``. "anomalies" is
# deliberately NOT here: it is emitted kind-labelled (plus an unlabelled
# aggregate) by trainer_exposition itself.
TRAIN_COUNTERS = (
    "evals",
    "checkpoints_saved",
    "publishes",
    "publishes_skipped_dirty",
    "watchdog_trips",
)

# Gauge key set of the exposition — seeded at 0 so the schema never
# depends on how far the run has progressed.
TRAIN_GAUGES = (
    "step",
    "total_steps",
    "epoch",
    "epochs",
    "loss",
    "learning_rate",
    "grad_norm",
    "eval_loss",
    "best_eval",
    "samples_per_second",
    "samples_per_second_per_chip",
    "steps_per_second",
    "tokens_per_second_per_chip",
    "real_tokens_per_second_per_chip",
    "packing_efficiency",
    "preempted",
    "model_flops_utilization",
    "hbm_bandwidth_utilization",
)

# Trainer phase histograms (train loop phase_hist keys) -> exposition
# names; the _s suffix becomes _seconds via metrics._prom_name.
TRAIN_HIST_KEYS = ("data_wait", "step", "checkpoint")


def new_run_id() -> str:
    """Short, collision-safe identity of one training run — the key that
    ties serving-side weight generations back to this trainer (manifest
    ``run_id``, ``GET /v1/lineage``)."""
    return uuid.uuid4().hex[:12]


def hparams_digest(hparams: Dict[str, Any]) -> str:
    """16-hex identity of a run's hyperparameters (the flattened config
    dict the trainer already hands to the metric sinks). Two runs with the
    same digest trained with the same knobs — the lineage answer to "was
    generation N trained like generation M?"."""
    try:
        blob = json.dumps(hparams, sort_keys=True, default=str)
    except (TypeError, ValueError):
        blob = repr(sorted(hparams.items(), key=lambda kv: str(kv[0])))
    return hashlib.sha256(blob.encode("utf-8")).hexdigest()[:16]


class _Ewma:
    """Exponentially-weighted mean + variance of a scalar stream (host
    floats only — the values arrive already synced at log boundaries)."""

    def __init__(self, alpha: float):
        self.alpha = float(alpha)
        self.n = 0
        self.mean = 0.0
        self.var = 0.0

    def update(self, x: float) -> None:
        if self.n == 0:
            self.mean = x
            self.var = 0.0
        else:
            d = x - self.mean
            self.mean += self.alpha * d
            # EW variance (West 1979 form): decays old surprise, folds new
            self.var = (1.0 - self.alpha) * (self.var + self.alpha * d * d)
        self.n += 1

    @property
    def std(self) -> float:
        return math.sqrt(max(self.var, 0.0))


class AnomalySentinels:
    """Host-side rolling-window detectors over the per-step metric stream.

    - ``non_finite``: NaN/Inf loss or grad norm — the hard sentinel, fires
      from observation one.
    - ``loss_spike``: loss above the EWMA mean by more than ``band_sigma``
      EW standard deviations, after ``warmup`` finite observations.
    - ``grad_explosion``: the same band on the grad norm.

    Anomalous values are NOT folded into the band (a divergence must not
    widen the band that detects it). ``clean_since(step)`` answers the
    publish gate: has any sentinel fired at or after ``step``?
    """

    def __init__(
        self,
        *,
        band_sigma: float = 6.0,
        warmup: int = 8,
        ewma_alpha: float = 0.1,
        on_anomaly=None,
    ):
        if band_sigma <= 0:
            raise ValueError(f"band_sigma must be positive, got {band_sigma}")
        self.band_sigma = float(band_sigma)
        self.warmup = max(1, int(warmup))
        self._on_anomaly = on_anomaly
        self._loss = _Ewma(ewma_alpha)
        self._grad = _Ewma(ewma_alpha)
        self._lock = threading.Lock()
        self.counts: Dict[str, int] = {k: 0 for k in ANOMALY_KINDS}
        self.last_step: Dict[str, Optional[int]] = {k: None for k in ANOMALY_KINDS}
        self.last_anomaly_step: Optional[int] = None

    def _fire(self, kind: str, step: int, **fields) -> None:
        self.counts[kind] += 1
        self.last_step[kind] = step
        self.last_anomaly_step = (
            step
            if self.last_anomaly_step is None
            else max(self.last_anomaly_step, step)
        )
        if self._on_anomaly is not None:
            try:
                self._on_anomaly(kind, step, **fields)
            except Exception:
                pass  # telemetry must never take down the train loop

    def _band_check(
        self, kind: str, ewma: _Ewma, value: float, step: int
    ) -> bool:
        if ewma.n >= self.warmup:
            # std floor: a perfectly flat warmup (synthetic data, tiny lr)
            # must not make ANY movement a 6-sigma event
            floor = max(ewma.std, 1e-3 * max(1.0, abs(ewma.mean)))
            if value - ewma.mean > self.band_sigma * floor:
                self._fire(
                    kind, step,
                    value=round(value, 6),
                    band_mean=round(ewma.mean, 6),
                    band_std=round(floor, 6),
                )
                return True
        ewma.update(value)
        return False

    def observe(
        self,
        step: int,
        loss: Optional[float] = None,
        grad_norm: Optional[float] = None,
    ) -> List[str]:
        """Feed one step's already-host-side scalars; returns the kinds
        that fired (empty on a clean step)."""
        fired: List[str] = []
        with self._lock:
            for name, value in (("loss", loss), ("grad_norm", grad_norm)):
                if value is None:
                    continue
                value = float(value)
                if not math.isfinite(value):
                    self._fire("non_finite", step, signal=name, value=str(value))
                    fired.append("non_finite")
                    continue
                if name == "loss":
                    if self._band_check("loss_spike", self._loss, value, step):
                        fired.append("loss_spike")
                else:
                    if self._band_check(
                        "grad_explosion", self._grad, value, step
                    ):
                        fired.append("grad_explosion")
        return fired

    def clean_since(self, step_lo: int) -> bool:
        """True when no sentinel fired at step >= ``step_lo`` — the
        publish gate's trailing-window cleanliness check."""
        with self._lock:
            return (
                self.last_anomaly_step is None
                or self.last_anomaly_step < step_lo
            )

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "counts": dict(self.counts),
                "last_step": dict(self.last_step),
                "last_anomaly_step": self.last_anomaly_step,
                "total": sum(self.counts.values()),
            }


class TrainTelemetry:
    """The trainer's shared observability state: flight recorder, anomaly
    sentinels, monotonic counters, and the status dict the control plane
    serves. The trainer mutates it at log/eval/save boundaries (where the
    scalars are already host floats); HTTP handler threads read snapshots
    under the lock."""

    def __init__(
        self,
        *,
        run_id: Optional[str] = None,
        hparams: Optional[Dict[str, Any]] = None,
        flight_capacity: int = 2048,
        band_sigma: float = 6.0,
        anomaly_window_steps: int = 100,
        sentinel_warmup: int = 8,
    ):
        self.run_id = run_id or new_run_id()
        self.hparams_digest = hparams_digest(hparams or {})
        self.recorder = FlightRecorder(flight_capacity)
        self.anomaly_window_steps = max(1, int(anomaly_window_steps))
        self.sentinels = AnomalySentinels(
            band_sigma=band_sigma,
            warmup=sentinel_warmup,
            on_anomaly=self._on_anomaly,
        )
        self._lock = threading.Lock()
        self._counters: Dict[str, int] = {k: 0 for k in TRAIN_COUNTERS}
        self._status: Dict[str, Any] = {
            "run_id": self.run_id,
            "hparams_digest": self.hparams_digest,
            "state": "initializing",
            "step": 0,
            "total_steps": 0,
            "epoch": 0.0,
            "epochs": 0,
            "preempted": False,
        }
        self._checkpoints: deque = deque(maxlen=64)
        self._publishes: deque = deque(maxlen=64)
        # attached live objects (read-only from the HTTP side)
        self.phase_hist: Optional[Dict[str, Any]] = None
        self.compile_ledger = None

    # ------------------------------------------------------------- wiring

    def attach(self, *, phase_hist=None, compile_ledger=None) -> None:
        """Hand the control plane references to the train loop's live
        phase histograms and compile ledger (both already thread-safe to
        read)."""
        if phase_hist is not None:
            self.phase_hist = phase_hist
        if compile_ledger is not None:
            self.compile_ledger = compile_ledger

    # ----------------------------------------------------------- mutation

    def _on_anomaly(self, kind: str, step: int, **fields) -> None:
        self.recorder.record("anomaly", anomaly=kind, step=step, **fields)

    def incr(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + n

    def set_counter(self, name: str, value: int) -> None:
        """Absolute update for counters owned elsewhere (the watchdog's
        monotonic ``trips``); folded in at log boundaries."""
        with self._lock:
            self._counters[name] = max(self._counters.get(name, 0), int(value))

    def update(self, **fields) -> None:
        with self._lock:
            self._status.update(fields)

    def on_step(self, step: int, logs: Dict[str, Any]) -> List[str]:
        """Boundary hook: fold one log record (already host floats) into
        the sentinels, the flight timeline, and the status dict. Returns
        the anomaly kinds that fired."""
        fired = self.sentinels.observe(
            step, loss=logs.get("loss"), grad_norm=logs.get("grad_norm")
        )
        numeric = {
            k: float(v)
            for k, v in logs.items()
            if isinstance(v, (int, float)) and not isinstance(v, bool)
        }
        with self._lock:
            self._status["step"] = int(step)
            self._status.update(numeric)
            self._status["state"] = "training"
        event = {"step": step}
        for key in ("loss", "grad_norm", "learning_rate"):
            if key in numeric:
                event[key] = round(numeric[key], 6) if math.isfinite(
                    numeric[key]
                ) else str(numeric[key])
        self.recorder.record("step", **event)
        if "eval_loss" in logs:
            self.incr("evals")
            ev = logs["eval_loss"]
            self.recorder.record(
                "eval", step=step,
                eval_loss=round(float(ev), 6) if math.isfinite(float(ev))
                else str(ev),
            )
        return fired

    def note_checkpoint(self, step: int, duration_s: float) -> None:
        self.incr("checkpoints_saved")
        self.recorder.record(
            "checkpoint_save", step=step, duration_s=round(duration_s, 4)
        )
        with self._lock:
            self._checkpoints.append(
                {"step": int(step), "duration_s": round(duration_s, 4)}
            )

    def note_restore(self, step: int) -> None:
        self.recorder.record("checkpoint_restore", step=step)

    def note_publish(
        self,
        step: int,
        *,
        clean: bool,
        skipped: bool = False,
        fingerprint: Optional[str] = None,
    ) -> None:
        self.incr("publishes_skipped_dirty" if skipped else "publishes")
        self.recorder.record(
            "publish_skipped_dirty" if skipped else "publish",
            step=step, anomaly_clean=clean, fingerprint=fingerprint,
        )
        with self._lock:
            self._publishes.append({
                "step": int(step),
                "anomaly_clean": bool(clean),
                "skipped": bool(skipped),
                "fingerprint": fingerprint,
            })

    def publish_clean(self, step: int) -> bool:
        """Is the trailing ``anomaly_window_steps`` window ending at
        ``step`` free of sentinel firings? Stamped into the manifest as
        ``anomaly_clean`` and enforced by ``publish_require_clean``."""
        return self.sentinels.clean_since(step - self.anomaly_window_steps + 1)

    # ------------------------------------------------------------ reading

    def counters_snapshot(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counters)

    def status(self) -> Dict[str, Any]:
        """One coherent JSON-ready view (``GET /v1/train/status``)."""
        with self._lock:
            out = dict(self._status)
            counters = dict(self._counters)
            out["checkpoints"] = list(self._checkpoints)
            out["publishes"] = list(self._publishes)
        out["counters"] = counters
        out["anomalies"] = self.sentinels.snapshot()
        # ETA from the meter's steady step rate over the remaining steps —
        # computed here on the HTTP thread, never on the step path
        sps = float(out.get("steps_per_second") or 0.0)
        total = int(out.get("total_steps") or 0)
        step = int(out.get("step") or 0)
        out["eta_s"] = (
            round((total - step) / sps, 1) if sps > 0 and total > step else None
        )
        out["flight_events"] = len(self.recorder)
        return out


def trainer_exposition(telemetry: TrainTelemetry, memory=None) -> str:
    """Render the trainer's telemetry as Prometheus text (prefix
    ``training_``), through the same exposition machinery the serving
    stack scrapes: pinned gauge set, counter set, compile-ledger samples,
    phase histograms, per-device HBM gauges, and the kind-labelled anomaly
    counter. ``memory`` defaults to a live ``device_memory_report()``."""
    status = telemetry.status()
    snap: Dict[str, Any] = {key: 0.0 for key in TRAIN_GAUGES}
    for key in TRAIN_GAUGES:
        value = status.get(key)
        if isinstance(value, bool):
            snap[key] = int(value)
        elif isinstance(value, (int, float)):
            snap[key] = value
    snap.update(telemetry.counters_snapshot())
    for key in TRAIN_COUNTERS:
        snap.setdefault(key, 0)
    snap["run_id"] = telemetry.run_id
    snap["hparams_digest"] = telemetry.hparams_digest
    snap["state"] = str(status.get("state", "unknown"))
    if telemetry.compile_ledger is not None:
        snap["compile"] = telemetry.compile_ledger.snapshot()
        # roofline utilization of the train step: ledger cost analysis over
        # the mean observed step time (0.0 on CPU / unknown hardware)
        hist = (telemetry.phase_hist or {}).get("step")
        total = int(getattr(hist, "total", 0) or 0) if hist is not None else 0
        if total > 0:
            from llm_fine_tune_distributed_tpu.observe.xla import (
                device_peak_specs,
                utilization_from_cost,
            )

            flops, nbytes = telemetry.compile_ledger.cost_for(("train_step",))
            peak_flops, peak_bw = device_peak_specs()
            mfu, bw = utilization_from_cost(
                flops, nbytes, float(hist.sum) / total, peak_flops, peak_bw
            )
            snap["model_flops_utilization"] = mfu
            snap["hbm_bandwidth_utilization"] = bw
    hists = {
        f"{key}_s": (telemetry.phase_hist or {}).get(key)
        for key in TRAIN_HIST_KEYS
        if (telemetry.phase_hist or {}).get(key) is not None
    }
    if memory is None:
        from llm_fine_tune_distributed_tpu.observe.profiler import (
            device_memory_report,
        )

        memory = device_memory_report()
    text = prometheus_exposition(
        snap, hists or None, memory=memory, prefix="training",
        counters=set(TRAIN_COUNTERS),
    )
    # kind-labelled anomaly counter, every kind seeded (schema must not
    # depend on whether the run has misbehaved yet)
    counts = telemetry.sentinels.snapshot()["counts"]
    lines = ["# TYPE training_anomalies_total counter"]
    for kind in ANOMALY_KINDS:
        lines.append(
            f'training_anomalies_total{{kind="{kind}"}} '
            f"{int(counts.get(kind, 0))}"
        )
    return text + "\n".join(lines) + "\n"


class TrainControlPlane:
    """Primary-host-only HTTP server over a ``TrainTelemetry`` (same
    ``ThreadingHTTPServer`` pattern as infer/server.py). ``port`` 0 binds
    an ephemeral port (tests, benches); read it back from ``.port`` after
    ``start()``. Non-primary hosts no-op entirely: ``start()`` returns
    False and opens no socket."""

    def __init__(
        self,
        telemetry: TrainTelemetry,
        port: int,
        *,
        host: str = "0.0.0.0",
        profile_dir: Optional[str] = None,
    ):
        self.telemetry = telemetry
        self.host = host
        self.port = int(port)
        self.profile_dir = profile_dir
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._capture = None

    def start(self) -> bool:
        if not is_primary_host():
            return False
        if self._server is not None:
            return True
        if self.profile_dir:
            from llm_fine_tune_distributed_tpu.observe.xla import (
                ProfilerCapture,
            )

            self._capture = ProfilerCapture(
                self.profile_dir, on_event=self.telemetry.recorder.record
            )
        telemetry = self.telemetry
        capture = self._capture

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def _send(self, code, payload, content_type=None):
                body = (
                    payload if isinstance(payload, str) else json.dumps(payload)
                ).encode()
                self.send_response(code)
                self.send_header(
                    "Content-Type",
                    content_type
                    or (
                        "text/plain"
                        if isinstance(payload, str)
                        else "application/json"
                    ),
                )
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802 (stdlib casing)
                path, _, query = self.path.partition("?")
                if path == "/metrics":
                    self._send(
                        200,
                        trainer_exposition(telemetry),
                        content_type=PROMETHEUS_CONTENT_TYPE,
                    )
                elif path == "/v1/train/status":
                    self._send(200, telemetry.status())
                elif path == "/v1/train/flight":
                    from urllib.parse import parse_qs

                    qs = parse_qs(query)
                    try:
                        limit = int((qs.get("limit") or [256])[0])
                        if limit <= 0:
                            raise ValueError
                    except ValueError:
                        self._send(400, {
                            "error": "'limit' must be a positive integer",
                        })
                        return
                    self._send(
                        200,
                        {"events": telemetry.recorder.events()[-limit:]},
                    )
                else:
                    self._send(404, {"error": "not found"})

            def do_POST(self):  # noqa: N802
                if self.path != "/v1/train/profile":
                    self._send(404, {"error": "not found"})
                    return
                if capture is None:
                    self._send(404, {
                        "error": "profiling disabled; start training with "
                                 "profile_dir / PROFILE_DIR set",
                    })
                    return
                from llm_fine_tune_distributed_tpu.observe.xla import (
                    CaptureBusyError,
                )

                try:
                    length = int(self.headers.get("Content-Length", "0"))
                    req = json.loads(self.rfile.read(length) or b"{}")
                    if not isinstance(req, dict):
                        raise TypeError("body must be a JSON object")
                    duration_s = float(req.get("duration_s", 3.0))
                    trace_dir = capture.start(duration_s)
                except CaptureBusyError as e:
                    self._send(409, {"error": str(e)})
                    return
                except (ValueError, TypeError) as e:
                    self._send(400, {"error": f"bad request: {e}"})
                    return
                self._send(200, {
                    "profiling": True,
                    "trace_dir": trace_dir,
                    "duration_s": duration_s,
                })

            def log_message(self, fmt, *args):
                pass  # scrapes must not spam the training log

        self._server = ThreadingHTTPServer((self.host, self.port), Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name="train-control-plane",
            daemon=True,
        )
        self._thread.start()
        self.telemetry.recorder.record("control_plane_start", port=self.port)
        return True

    def stop(self) -> None:
        if self._capture is not None:
            self._capture.stop()
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
