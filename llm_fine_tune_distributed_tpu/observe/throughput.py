"""Samples/sec/chip instrumentation — first-class because it IS the
north-star metric (BASELINE.json; the reference only surfaces HF's
``train_samples_per_second`` in Aim, ``docs/AIM_WORKFLOW.md:42-43``).

Two figures per snapshot: the cumulative rate (includes jit compile and
eval pauses — honest wall-clock accounting) and a steady-state rate over a
sliding window of recent steps, which is the number comparable to
``bench.py`` (short runs are otherwise dominated by the one-off compile)."""

from __future__ import annotations

import time
from collections import deque
from typing import Dict, Optional

_WINDOW_STEPS = 16


class ThroughputMeter:
    def __init__(self, n_chips: int, tokens_per_sample: Optional[int] = None):
        self.n_chips = max(n_chips, 1)
        self.tokens_per_sample = tokens_per_sample
        self.reset()

    def reset(self) -> None:
        self._t0 = time.perf_counter()
        self._samples = 0
        self._steps = 0
        self._real_tokens = 0
        # (timestamp, cumulative_samples) ring for the steady-state window;
        # seeded with t0 so the first window spans step 1..N and the compile
        # falls out of the window once _WINDOW_STEPS+1 entries exist
        self._window = deque([(self._t0, 0)], maxlen=_WINDOW_STEPS + 1)

    def update(self, samples: int, steps: int = 1, real_tokens: int = 0) -> None:
        """Stamp ``samples`` (and ``steps`` optimizer steps) completed since
        the previous stamp. Callers that sync the device only at logging
        boundaries pass the accumulated interval; the window stores
        cumulative samples, so per-interval rates stay correct.
        ``real_tokens`` is the attention-mask-weighted token count of the
        interval — non-pad tokens, the honest numerator for throughput
        (``tokens_per_second_per_chip`` counts padded slots)."""
        self._samples += samples
        self._steps += steps
        self._real_tokens += real_tokens
        self._window.append((time.perf_counter(), self._samples))

    def rebase(self) -> None:
        """Restart the current steady-state interval at NOW — called after a
        known pause (eval sweep, checkpoint save) so the pause lands in no
        window interval. The cumulative rate keeps counting the pause
        (honest wall-clock); only the steady median excludes it."""
        if self._window:
            self._window[-1] = (time.perf_counter(), self._window[-1][1])

    def snapshot(self) -> Dict[str, float]:
        dt = max(time.perf_counter() - self._t0, 1e-9)
        sps = self._samples / dt
        out = {
            "samples_per_second": sps,
            "samples_per_second_per_chip": sps / self.n_chips,
            "steps_per_second": self._steps / dt,
            "elapsed_seconds": dt,
        }
        if len(self._window) >= 3:
            # steady state: MEDIAN of recent per-step rates — robust to the
            # occasional slow span (compile, an eval pass, a checkpoint
            # save) landing inside the window, not just the oldest one
            pairs = list(self._window)
            rates = [
                (s_b - s_a) / (t_b - t_a)
                for (t_a, s_a), (t_b, s_b) in zip(pairs, pairs[1:])
                if t_b > t_a and s_b > s_a
            ]
            if rates:
                rates.sort()
                mid = len(rates) // 2
                median = (
                    rates[mid]
                    if len(rates) % 2
                    else 0.5 * (rates[mid - 1] + rates[mid])
                )
                out["samples_per_second_per_chip_steady"] = median / self.n_chips
        if self.tokens_per_sample:
            out["tokens_per_second_per_chip"] = sps * self.tokens_per_sample / self.n_chips
        if self._real_tokens:
            # real (non-pad) token throughput + packing efficiency: how much
            # of each padded [batch, seq] slab carries actual data. A low
            # ratio says the win is in the loader (packing / bucketing),
            # not the step — the attribution the padded rate hides.
            out["real_tokens_per_second_per_chip"] = (
                self._real_tokens / dt / self.n_chips
            )
            if self.tokens_per_sample and self._samples:
                out["packing_efficiency"] = self._real_tokens / (
                    self._samples * self.tokens_per_sample
                )
        return out
