"""Samples/sec/chip instrumentation — first-class because it IS the
north-star metric (BASELINE.json; the reference only surfaces HF's
``train_samples_per_second`` in Aim, ``docs/AIM_WORKFLOW.md:42-43``)."""

from __future__ import annotations

import time
from typing import Dict, Optional


class ThroughputMeter:
    def __init__(self, n_chips: int, tokens_per_sample: Optional[int] = None):
        self.n_chips = max(n_chips, 1)
        self.tokens_per_sample = tokens_per_sample
        self.reset()

    def reset(self) -> None:
        self._t0 = time.perf_counter()
        self._samples = 0
        self._steps = 0

    def update(self, samples: int) -> None:
        self._samples += samples
        self._steps += 1

    def snapshot(self) -> Dict[str, float]:
        dt = max(time.perf_counter() - self._t0, 1e-9)
        sps = self._samples / dt
        out = {
            "samples_per_second": sps,
            "samples_per_second_per_chip": sps / self.n_chips,
            "steps_per_second": self._steps / dt,
            "elapsed_seconds": dt,
        }
        if self.tokens_per_sample:
            out["tokens_per_second_per_chip"] = sps * self.tokens_per_sample / self.n_chips
        return out
