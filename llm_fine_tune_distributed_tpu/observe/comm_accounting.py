"""Compiled-HLO collective accounting: the communication half of the scaling
story, measured from the artifact that actually runs.

The reference's communication cost is whatever NCCL does for DDP's bucketed
all-reduce (reference ``training.py:285``, ``deploy/pytorchjob.yaml:51-64``) —
opaque, measurable only on the cluster. On TPU the collectives are *compiled
into the program*: XLA emits them from sharding annotations, so the exact
per-step communication volume of any mesh is readable from the optimized HLO
without running a single step. This module does that read:

  compiled = jax.jit(step).lower(abstract_args).compile()
  report   = account_compiled(compiled, mesh)

and returns every collective instruction with

- its **execution count** per step (collectives inside ``lax.scan``/``while``
  bodies run once per iteration; XLA records ``known_trip_count`` in the
  loop's backend config, and nested loops multiply),
- its **mesh-axis attribution** (replica groups are decoded to concrete
  device groups and matched against the partitions induced by each mesh-axis
  subset — so "this all-reduce rides the ``data`` axis" is a fact, not a
  guess), and
- its **wire bytes** under the standard bidirectional-ring cost model
  (`scaling-book <https://jax-ml.github.io/scaling-book>`_ conventions):

    =================  =============================================
    all-gather          out_bytes × (g-1)/g
    reduce-scatter      out_bytes × (g-1)        (= full × (g-1)/g)
    all-reduce          2 × bytes × (g-1)/g      (RS + AG)
    all-to-all          bytes × (g-1)/g
    collective-permute  bytes                    (each device sends its shard)
    =================  =============================================

``tests/test_comm_accounting.py`` pins these volumes against analytic
expectations per target mesh; ``benchmarks/project_scaling.py`` feeds them
into the v5e-16 throughput projection in BASELINE.md.

Works on any backend whose compiled text is HLO (CPU, TPU). The parser
understands sync collectives and the ``-start``/``-done`` async pairs.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from itertools import combinations
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

_DTYPE_BYTES = {
    "f64": 8, "f32": 4, "f16": 2, "bf16": 2,
    "s64": 8, "u64": 8, "s32": 4, "u32": 4, "s16": 2, "u16": 2,
    "s8": 1, "u8": 1, "pred": 1,
    "f8e5m2": 1, "f8e4m3fn": 1, "f8e4m3": 1, "f8e5m2fnuz": 1, "f8e4m3fnuz": 1,
    "c64": 8, "c128": 16,
}

# sync name -> canonical kind; -start variants are normalized to these
KINDS = ("all-reduce", "all-gather", "reduce-scatter", "all-to-all", "collective-permute")

_SHAPE_RE = re.compile(r"(\w+)\[([\d,]*)\]")


def _shape_bytes(type_str: str) -> int:
    """Total bytes of every array shape mentioned in an HLO type string
    (tuples sum their elements)."""
    total = 0
    for dtype, dims in _SHAPE_RE.findall(type_str):
        if dtype not in _DTYPE_BYTES:
            continue  # token[], opaque[] etc. carry no payload
        numel = 1
        for d in dims.split(","):
            if d:
                numel *= int(d)
        total += numel * _DTYPE_BYTES[dtype]
    return total


def _parse_replica_groups(attrs: str) -> Optional[List[List[int]]]:
    """Decode ``replica_groups=...`` — explicit ``{{0,1},{2,3}}`` or iota
    ``[ng,gs]<=[dims]`` with an optional ``T(perm)`` transpose."""
    m = re.search(r"replica_groups=\{\{([\d,{} ]*)\}\}", attrs)
    if m:
        return [
            [int(x) for x in grp.split(",") if x.strip()]
            for grp in m.group(1).split("},{")
        ]
    m = re.search(
        r"replica_groups=\[(\d+),(\d+)\]<=\[([\d,]+)\](?:T\(([\d,]+)\))?", attrs
    )
    if m:
        ng, gs = int(m.group(1)), int(m.group(2))
        dims = [int(x) for x in m.group(3).split(",")]
        ids = np.arange(int(np.prod(dims))).reshape(dims)
        if m.group(4):
            ids = ids.transpose([int(x) for x in m.group(4).split(",")])
        return ids.reshape(ng, gs).tolist()
    return None


def _parse_pairs(attrs: str) -> Optional[List[Tuple[int, int]]]:
    m = re.search(r"source_target_pairs=\{(.*?)\}\}", attrs)
    if not m:
        return None
    return [
        tuple(int(x) for x in p.split(","))
        for p in m.group(1).strip("{}").split("},{")
    ]


@dataclass
class Collective:
    kind: str                 # canonical (sync) opcode
    computation: str          # enclosing HLO computation
    result_bytes: int         # bytes of the (per-device) result shape(s)
    group_size: int
    axes: Tuple[str, ...]     # mesh axes the groups ride ("?" if unmatched)
    count: int                # executions per step (loop trip products)
    op_name: str = ""         # jax op_name metadata (for attribution reading)

    @property
    def wire_bytes_once(self) -> float:
        """Per-device bytes on the wire for ONE execution (ring model)."""
        g = self.group_size
        if g <= 1:
            return 0.0
        b = self.result_bytes
        if self.kind == "all-gather":
            return b * (g - 1) / g
        if self.kind == "reduce-scatter":
            return b * (g - 1)          # result is the 1/g shard
        if self.kind == "all-reduce":
            return 2 * b * (g - 1) / g
        if self.kind == "all-to-all":
            return b * (g - 1) / g
        if self.kind == "collective-permute":
            return b
        return 0.0

    @property
    def wire_bytes(self) -> float:
        return self.wire_bytes_once * self.count


@dataclass
class CommReport:
    collectives: List[Collective] = field(default_factory=list)

    def total_wire_bytes(self) -> float:
        return sum(c.wire_bytes for c in self.collectives)

    def wire_bytes_by_axis(self) -> Dict[Tuple[str, ...], float]:
        out: Dict[Tuple[str, ...], float] = {}
        for c in self.collectives:
            out[c.axes] = out.get(c.axes, 0.0) + c.wire_bytes
        return out

    def wire_bytes_by_kind(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for c in self.collectives:
            out[c.kind] = out.get(c.kind, 0.0) + c.wire_bytes
        return out

    def filter(self, kind: Optional[str] = None, axes: Optional[Sequence[str]] = None) -> "CommReport":
        sel = self.collectives
        if kind is not None:
            sel = [c for c in sel if c.kind == kind]
        if axes is not None:
            sel = [c for c in sel if c.axes == tuple(axes)]
        return CommReport(list(sel))

    def table(self) -> str:
        rows = ["kind               axes              count  result_MB  wire_MB  where"]
        for c in sorted(self.collectives, key=lambda c: -c.wire_bytes):
            rows.append(
                f"{c.kind:<18} {'x'.join(c.axes) or '-':<17} {c.count:>5}  "
                f"{c.result_bytes/1e6:>9.3f}  {c.wire_bytes/1e6:>7.3f}  {c.op_name[:60]}"
            )
        rows.append(f"TOTAL wire: {self.total_wire_bytes()/1e6:.3f} MB/step/device")
        return "\n".join(rows)


# --------------------------------------------------------------------- parse


def _split_computations(text: str) -> Dict[str, List[str]]:
    """Map computation name -> its instruction lines. Computation headers sit
    at column 0 (``ENTRY`` marks the entry); bodies are indented."""
    comps: Dict[str, List[str]] = {}
    cur: Optional[str] = None
    for line in text.splitlines():
        if line and not line[0].isspace():
            m = re.match(r"(?:ENTRY\s+)?%?([\w\.\-]+)\s*\(.*->", line)
            if m and line.rstrip().endswith("{"):
                cur = m.group(1)
                if line.startswith("ENTRY"):
                    cur = "__ENTRY__:" + cur
                comps[cur] = []
                continue
            cur = None
        elif cur is not None:
            comps[cur].append(line.strip())
    return comps


_REF_ATTRS = (
    ("body=", None),          # trip count resolved from backend_config
    ("condition=", 1),
    ("calls=", 1),
    ("to_apply=", 1),
    ("true_computation=", 1),
    ("false_computation=", 1),
)


def _comp_multipliers(comps: Dict[str, List[str]]) -> Dict[str, int]:
    """Executions per step of each computation: product of enclosing loop trip
    counts, propagated from ENTRY through the call graph (a DAG)."""
    entry = next(k for k in comps if k.startswith("__ENTRY__:"))
    edges: Dict[str, List[Tuple[str, int]]] = {k: [] for k in comps}
    for name, lines in comps.items():
        for line in lines:
            trip = 1
            mt = re.search(r'known_trip_count\":\{\"n\":\"(\d+)\"', line)
            if mt:
                trip = int(mt.group(1))
            for attr, mult in _REF_ATTRS:
                for m in re.finditer(re.escape(attr) + r"\(?%?([\w\.\-]+)", line):
                    callee = m.group(1)
                    n = trip if attr == "body=" else (mult or 1)
                    if callee in comps:
                        edges[name].append((callee, n))
            m = re.search(r"branch_computations=\{([^}]*)\}", line)
            if m:
                for callee in re.findall(r"%?([\w\.\-]+)", m.group(1)):
                    if callee in comps:
                        edges[name].append((callee, 1))
    mults = {k: 0 for k in comps}
    mults[entry] = 1
    # Topological accumulation over the call DAG: a callee executes the SUM
    # over call sites of caller_multiplier x per-site count. (A max-relaxation
    # would count a computation invoked once from each of two call sites as
    # one execution — ADVICE r3.) Deliberate upper-bound semantics for
    # conditionals: sibling branches are mutually exclusive per invocation,
    # so a helper reachable from BOTH arms is credited twice — accounting
    # reports bound bytes from above, and undercounting is the unsafe
    # direction (branch probabilities are unknowable statically).
    from collections import deque

    indeg = {k: 0 for k in comps}
    for out in edges.values():
        for callee, _ in out:
            indeg[callee] += 1
    ready = deque(k for k, d in indeg.items() if d == 0)
    while ready:
        name = ready.popleft()
        for callee, n in edges[name]:
            mults[callee] += mults[name] * n
            indeg[callee] -= 1
            if indeg[callee] == 0:
                ready.append(callee)
    return mults


def _device_id_grid(mesh) -> np.ndarray:
    return np.vectorize(lambda d: d.id)(mesh.devices)


def _axis_partition(grid: np.ndarray, axis_names, subset) -> frozenset:
    """The partition of device ids induced by grouping along ``subset`` axes."""
    order = [i for i, a in enumerate(axis_names) if a not in subset] + [
        i for i, a in enumerate(axis_names) if a in subset
    ]
    gsz = int(np.prod([grid.shape[i] for i, a in enumerate(axis_names) if a in subset]))
    rows = grid.transpose(order).reshape(-1, gsz)
    return frozenset(frozenset(int(x) for x in row) for row in rows)


def _attribute_axes(groups: List[List[int]], mesh) -> Tuple[str, ...]:
    """Find the smallest mesh-axis subset whose induced grouping matches."""
    grid = _device_id_grid(mesh)
    names = list(mesh.axis_names)
    observed = frozenset(frozenset(g) for g in groups)
    live = [a for a in names if mesh.shape[a] > 1]
    for r in range(1, len(live) + 1):
        for subset in combinations(live, r):
            if _axis_partition(grid, names, set(subset)) == observed:
                return subset
    return ("?",)


def _attribute_pairs(pairs: List[Tuple[int, int]], mesh) -> Tuple[str, ...]:
    """A permute rides axis A if every (src, dst) differs only in A's coord."""
    grid = _device_id_grid(mesh)
    names = list(mesh.axis_names)
    coord = {int(grid[idx]): idx for idx in np.ndindex(grid.shape)}
    for i, a in enumerate(names):
        if mesh.shape[a] <= 1:
            continue
        if all(
            s in coord and t in coord
            and all(cs == ct for j, (cs, ct) in enumerate(zip(coord[s], coord[t])) if j != i)
            and coord[s][i] != coord[t][i]
            for s, t in pairs
        ):
            return (a,)
    return ("?",)


def account_text(text: str, mesh) -> CommReport:
    """Parse optimized-HLO text into a per-step communication report."""
    comps = _split_computations(text)
    mults = _comp_multipliers(comps)
    report = CommReport()
    for name, lines in comps.items():
        count = mults.get(name, 0)
        if count == 0:
            continue
        for line in lines:
            kind = None
            for k in KINDS:
                if re.search(rf"(?<![\w-]){k}(-start)?\(", line):
                    kind = k
                    break
            if kind is None:
                continue
            is_start = f"{k}-start(" in line
            head = line.split(f" {k}{'-start' if is_start else ''}(", 1)[0]
            type_str = head.split("=", 1)[1] if "=" in head else head
            result_bytes = _shape_bytes(type_str)
            if is_start and kind == "all-gather":
                # start op's result tuple is (operand, output): keep the output
                shapes = [
                    _shape_bytes(f"{d}[{dims}]")
                    for d, dims in _SHAPE_RE.findall(type_str)
                ]
                result_bytes = max(shapes) if shapes else 0
            elif is_start:
                # (operand, result) alias tuple doubles the payload
                result_bytes //= 2
            mo = re.search(r'op_name="([^"]*)"', line)
            if kind == "collective-permute":
                pairs = _parse_pairs(line) or []
                axes = _attribute_pairs(pairs, mesh) if pairs else ("?",)
                gsz = 2 if pairs else 1  # pairwise sends; wire model uses bytes directly
            else:
                groups = _parse_replica_groups(line)
                if not groups or len(groups[0]) <= 1:
                    continue
                gsz = len(groups[0])
                axes = _attribute_axes(groups, mesh)
            report.collectives.append(
                Collective(
                    kind=kind,
                    computation=name.replace("__ENTRY__:", ""),
                    result_bytes=result_bytes,
                    group_size=gsz,
                    axes=axes,
                    count=count,
                    op_name=mo.group(1) if mo else "",
                )
            )
    return report


def account_compiled(compiled, mesh) -> CommReport:
    """Account a ``jax.stages.Compiled`` (from ``jit(f).lower(...).compile()``)."""
    return account_text(compiled.as_text(), mesh)
