"""Metric logging — the three-sink design of the reference (SURVEY.md §5.5):

1. stdout stage prints (kubectl-logs consumption, reference ``README.md:31-40``);
2. accumulated in-memory history -> ``training_history.json``
   (TrainingHistoryCallback parity, reference ``training.py:215-221,315-316``);
3. Aim experiment tracker when available (AimCallback parity, reference
   ``training.py:240-241``) with the same naming contract: HF
   ``train_loss``/``eval_loss`` become Aim metric ``loss`` with
   ``context.subset in {train, eval}`` (reference ``docs/AIM_WORKFLOW.md:334-337``);
   plus an always-on JSONL fallback sink with the same schema, so runs are
   inspectable even where Aim isn't installed.

Perplexity injection (``exp(loss)``/``exp(eval_loss)``) reproduces
PerplexityCallback (reference ``training.py:224-234``). Only host 0 writes.
"""

from __future__ import annotations

import json
import math
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from llm_fine_tune_distributed_tpu.observe.tracing import Histogram
from llm_fine_tune_distributed_tpu.runtime.distributed import is_primary_host


class ServingStats:
    """Thread-safe serving-side counters and gauges (`GET /v1/stats`).

    The continuous-batching engine (infer/engine.py) updates these from its
    scheduler thread; HTTP handler threads read snapshots concurrently. All
    mutation goes through one lock — the quantities are tiny (a handful of
    ints per token batch), so contention is irrelevant next to a decode step.

    Counters (monotonic): ``tokens_served``, ``requests_admitted``,
    ``requests_completed``, ``requests_abandoned``, ``decode_steps``; the
    paged engine adds ``prompt_tokens`` (prompt tokens admitted),
    ``prefix_tokens_reused`` (of those, served from the prefix cache
    without a forward pass) and ``prefill_chunks``. Supervision adds
    ``engine_restarts`` (in-process worker recoveries),
    ``requests_failed`` (resolved with an error — includes shed and
    recovery casualties), ``requests_shed_overflow`` (429s from the
    bounded queue) and ``requests_shed_deadline`` (queue-wait deadline
    expiries). Speculative decoding adds ``draft_tokens_proposed`` /
    ``draft_tokens_accepted`` (engine-lifetime draft totals across all
    requests); the snapshot derives ``draft_acceptance_rate`` =
    accepted / proposed and ``mean_tokens_per_step`` = tokens_served /
    decode_steps — the verified-tokens-per-forward number speculation
    exists to raise above 1.0. Multi-tenant LoRA serving adds
    ``adapter_loads`` (hot-loads from disk), ``adapter_evictions`` (LRU
    slot reclaims) and ``requests_shed_tenant_quota`` (per-tenant 429s),
    plus a ``per_tenant`` map in the snapshot — one
    ``{requests, tokens, queue_depth}`` record per tenant that has ever
    been admitted (``tenant_incr``).
    Gauges (instantaneous): ``queue_depth``, ``live_slots``,
    ``engine_generation`` (restart epoch), ``adapters_resident``
    (tenant adapters warm in the pool), plus paged
    ``blocks_in_use`` / ``peak_blocks_in_use`` / ``prefix_cache_blocks``.
    ``slots`` is the engine's capacity and ``total_blocks`` the usable pool
    size; the snapshot derives ``slot_occupancy`` = live_slots / slots —
    the "is the decode batch actually full?" number continuous batching
    exists to maximize — and, when a pool exists, ``block_pool_occupancy``,
    ``peak_block_pool_occupancy`` and ``prefix_hit_rate`` =
    prefix_tokens_reused / prompt_tokens.
    """

    COUNTERS = (
        "tokens_served", "requests_admitted", "requests_completed",
        "requests_abandoned", "decode_steps",
        "prompt_tokens", "prefix_tokens_reused", "prefill_chunks",
        "engine_restarts", "requests_failed",
        "requests_shed_overflow", "requests_shed_deadline",
        "draft_tokens_proposed", "draft_tokens_accepted",
        "adapter_loads", "adapter_evictions", "requests_shed_tenant_quota",
        # live deployment (infer/deploy.py): checkpoint hot-swaps applied at
        # a tick boundary, and rollbacks to the previous weight buffer
        "weight_swaps", "weight_rollbacks",
        # overload control (infer/engine.py): slots reclaimed from a
        # lower-tier request to admit a higher-tier one, and requests
        # cancelled mid-decode by an expired client deadline (the
        # pre-prefill expiry stays in requests_shed_deadline)
        "preemptions", "requests_shed_deadline_decode",
        # capacity observatory (observe/capacity.py): emitted tokens whose
        # request settled successfully — the numerator of goodput_fraction.
        # Tokens that were emitted but thrown away land in the
        # reason-labelled waste map (``wasted_tokens_by_reason``) instead.
        "goodput_tokens",
        # tiered KV (infer/paged.HostBlockTier): prefix/banked blocks that
        # made it into the host tier on eviction vs. vanished the old way;
        # host->device restores that extended an admission's shared run vs.
        # fell back to re-prefill; requests live-migrated onto this engine
        "prefix_blocks_spilled", "prefix_blocks_discarded",
        "host_tier_restore_hits", "host_tier_restore_misses",
        "slots_migrated",
        # disaggregated serving: token attribution split by stage.
        # prefill_tokens counts prompt positions a prefill forward actually
        # ingested (prefix-cache hits don't count — they create no prefill
        # demand); decode_tokens counts tokens emitted by decode ticks.
        # tokens_served stays the user-facing total (first token included).
        # requests_handed_off counts prefill->decode handoffs that left
        # this replica; requests_handoff_failed counts handoffs that
        # degraded to decode-in-place.
        "prefill_tokens", "decode_tokens",
        "requests_handed_off", "requests_handoff_failed",
    )
    GAUGES = (
        "queue_depth", "live_slots", "engine_generation",
        "blocks_in_use", "peak_blocks_in_use", "prefix_cache_blocks",
        "adapters_resident",
        # monotonically increasing weight generation: bumped by every applied
        # hot-swap (rollbacks included — a rollback is a swap to the previous
        # buffer, not a counter rewind)
        "weight_generation",
        # staged degradation under pressure (0 = healthy .. 3 = shedding
        # best_effort); a fleet reports the max across replicas
        "brownout_stage",
        # resident HBM accounting (engine.memory_breakdown): weight bytes
        # cover the serving tree in whatever precision is resident (bf16 or
        # --quantize-weights int8/nf4 codes + scales); kv_pool_bytes covers
        # the k/v pools only — the per-block int8 scales ride in the
        # /v1/stats breakdown, not here
        "weight_bytes", "kv_pool_bytes",
        # bytes resident in the shared host-RAM block tier (one pool per
        # process: fleet aggregation takes the max, not the sum)
        "host_tier_bytes",
    )
    # tier-labelled shed counters (``requests_shed_by_tier`` in the
    # snapshot): every priority tier is always present so the /v1/stats and
    # /metrics schemas are identical with zero sheds. Mirrors
    # infer/batching.PRIORITY_TIERS (kept literal here so observe/ stays
    # import-independent of infer/).
    SHED_TIERS = ("interactive", "batch", "best_effort")
    # reason-labelled wasted-token counters (``wasted_tokens_by_reason`` in
    # the snapshot): decode work the device performed whose tokens never
    # counted as goodput. Every reason is always present so the /v1/stats
    # and /metrics schemas are identical with zero waste.
    #   deadline  — cancelled mid-decode (or at prefill) by an expired
    #               client deadline; the 504 carries the partial tokens
    #   abandoned — the waiter gave up (client timeout/disconnect) after
    #               tokens had been emitted, including preempted-then-
    #               abandoned requests whose banked tokens died with them
    #   failover  — tokens emitted on a replica that crashed/drained before
    #               settle; the request re-ran elsewhere, so these are
    #               duplicate work
    #   shed      — tokens banked by a preempted request that was then shed
    #               (displacement/overflow) instead of resumed
    WASTE_REASONS = ("deadline", "abandoned", "failover", "shed")
    # the per-tenant record's exact key set (pinned by
    # tests/test_metrics_schema.py so the /v1/stats schema cannot drift)
    TENANT_KEYS = ("requests", "tokens", "queue_depth")
    # latency/shape histograms owned alongside the counters — fixed log
    # buckets so restart generations and fleet replicas stay mergeable.
    # spec_run_len is the accepted-run length per drafting slot per tick
    # (0..K), a count, so it gets linear unit buckets.
    HISTOGRAM_SPECS = (
        "ttft_s", "inter_token_s", "queue_wait_s",
        "decode_tick_s", "prefill_chunk_s", "spec_run_len",
    )
    # per-tenant latency histograms (tenant-labelled series in /metrics):
    # only the tails a tenant actually feels — TTFT and inter-token gaps.
    # Lazily created on a tenant's first observation so the base-model
    # path pays nothing.
    TENANT_HIST_SPECS = ("ttft_s", "inter_token_s")

    def __init__(self, slots: int = 0, total_blocks: int = 0):
        self._lock = threading.Lock()
        self.slots = int(slots)
        self.total_blocks = int(total_blocks)
        self._values: Dict[str, int] = {
            k: 0 for k in self.COUNTERS + self.GAUGES
        }
        # per-tenant multi-tenant counters: tenant -> {TENANT_KEYS: int}
        self._tenants: Dict[str, Dict[str, int]] = {}
        # per-tenant latency histograms: tenant -> {TENANT_HIST_SPECS: Histogram}
        self._tenant_hist: Dict[str, Dict[str, Histogram]] = {}
        # tier-labelled sheds (overflow + brownout + displacement), every
        # tier always present (schema stability with zero sheds)
        self._tier_shed: Dict[str, int] = {t: 0 for t in self.SHED_TIERS}
        # reason-labelled wasted tokens, every reason always present
        # (schema stability with zero waste)
        self._waste: Dict[str, int] = {r: 0 for r in self.WASTE_REASONS}
        self.hist: Dict[str, Histogram] = {
            name: (
                Histogram.linear(0.0, 16.0, 1.0)
                if name == "spec_run_len"
                else Histogram.exponential()
            )
            for name in self.HISTOGRAM_SPECS
        }
        self.started_at = time.monotonic()
        # windowed throughput EWMA (~1 min time constant), advanced lazily
        # at snapshot time so the token hot path never touches a clock here
        self._rate_t = self.started_at
        self._rate_tokens = 0
        self._rate_ewma: Optional[float] = None

    def incr(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._values[name] += n

    def gauge(self, name: str, value: int) -> None:
        with self._lock:
            self._values[name] = int(value)

    def gauge_max(self, name: str, value: int) -> None:
        """Ratcheting gauge: keep the high-water mark (peak pool pressure)."""
        with self._lock:
            self._values[name] = max(self._values[name], int(value))

    def tenant_incr(self, tenant: str, name: str, n: int = 1) -> None:
        """Bump one tenant's counter (``TENANT_KEYS``). queue_depth is the
        only key that also decrements (-1 at settle); it is floored at 0 so
        a double-release can never report negative depth."""
        with self._lock:
            rec = self._tenants.setdefault(
                tenant, {k: 0 for k in self.TENANT_KEYS}
            )
            rec[name] = max(rec[name] + n, 0)

    def tenant_merge(self, per_tenant: Dict[str, Dict[str, int]]) -> None:
        """Fold another snapshot's ``per_tenant`` map into this one (fleet
        aggregation: replica tenant counts sum)."""
        with self._lock:
            for tenant, rec in per_tenant.items():
                mine = self._tenants.setdefault(
                    tenant, {k: 0 for k in self.TENANT_KEYS}
                )
                for k in self.TENANT_KEYS:
                    mine[k] += int(rec.get(k, 0))

    def tier_shed_incr(self, tier: str, n: int = 1) -> None:
        """Bump one priority tier's shed counter (overflow, brownout, or
        displacement — anything resolved with a tier-labelled 429)."""
        with self._lock:
            self._tier_shed[tier] = self._tier_shed.get(tier, 0) + n

    def tier_shed_merge(self, by_tier: Dict[str, int]) -> None:
        """Fold another snapshot's ``requests_shed_by_tier`` map into this
        one (fleet aggregation: replica shed counts sum)."""
        with self._lock:
            for tier, n in by_tier.items():
                self._tier_shed[tier] = self._tier_shed.get(tier, 0) + int(n)

    def waste_incr(self, reason: str, n: int) -> None:
        """Charge ``n`` emitted-but-discarded tokens to one waste reason
        (``WASTE_REASONS``) — the engine calls this from its single settle
        point, so every emitted token lands in exactly one of
        ``goodput_tokens`` or this map."""
        with self._lock:
            self._waste[reason] = self._waste.get(reason, 0) + n

    def waste_merge(self, by_reason: Dict[str, int]) -> None:
        """Fold another snapshot's ``wasted_tokens_by_reason`` map into
        this one (fleet aggregation: replica waste counts sum)."""
        with self._lock:
            for reason, n in by_reason.items():
                self._waste[reason] = self._waste.get(reason, 0) + int(n)

    def observe(self, name: str, value: float) -> None:
        """Record one histogram observation (histograms carry their own
        locks, so this does not contend with the counter lock)."""
        self.hist[name].observe(value)

    def tenant_observe(self, tenant: str, name: str, value: float) -> None:
        """Record one observation into a tenant's latency histogram
        (``TENANT_HIST_SPECS``), creating the tenant's set on first use.
        The counter lock only guards the (rare) dict insert; the observe
        itself rides the histogram's own lock."""
        with self._lock:
            hists = self._tenant_hist.get(tenant)
            if hists is None:
                hists = self._tenant_hist[tenant] = {
                    k: Histogram.exponential() for k in self.TENANT_HIST_SPECS
                }
        hists[name].observe(value)

    def tenant_histograms(self) -> Dict[str, Dict[str, Histogram]]:
        """Shallow copy of the per-tenant latency histogram map (the
        Histogram objects themselves are shared and internally locked —
        exposition reads them live)."""
        with self._lock:
            return {t: dict(h) for t, h in self._tenant_hist.items()}

    def values(self, names) -> Dict[str, int]:
        """One consistent read of several counters/gauges (the MetricRing
        sampler's entry point — one lock acquisition per sample, not per
        name)."""
        with self._lock:
            return {n: self._values.get(n, 0) for n in names}

    def _tokens_rate(self, now: float, tokens_served: int) -> float:
        # irregular-interval EWMA: weight = 1 - exp(-dt/60s), so the gauge
        # decays toward the instantaneous rate with a ~1 min time constant
        # regardless of how often /v1/stats is polled. Sub-200ms polls
        # reuse the last value instead of amplifying quantization noise.
        dt = now - self._rate_t
        if dt >= 0.2:
            inst = max(0, tokens_served - self._rate_tokens) / dt
            w = 1.0 - math.exp(-dt / 60.0)
            self._rate_ewma = (
                inst
                if self._rate_ewma is None
                else (1.0 - w) * self._rate_ewma + w * inst
            )
            self._rate_t = now
            self._rate_tokens = tokens_served
        return self._rate_ewma if self._rate_ewma is not None else 0.0

    def snapshot(self) -> Dict[str, Any]:
        now = time.monotonic()
        with self._lock:
            out: Dict[str, Any] = dict(self._values)
            out["tokens_per_s_1m"] = self._tokens_rate(now, out["tokens_served"])
            out["per_tenant"] = {
                tenant: dict(rec) for tenant, rec in self._tenants.items()
            }
            out["requests_shed_by_tier"] = dict(self._tier_shed)
            out["wasted_tokens_by_reason"] = dict(self._waste)
        wasted = sum(out["wasted_tokens_by_reason"].values())
        emitted = out["goodput_tokens"] + wasted
        # 1.0 at zero traffic: "no waste yet" is the healthy reading
        out["goodput_fraction"] = (
            out["goodput_tokens"] / emitted if emitted else 1.0
        )
        out["uptime_s"] = now - self.started_at
        out["slots"] = self.slots
        out["slot_occupancy"] = (
            out["live_slots"] / self.slots if self.slots else 0.0
        )
        if self.total_blocks:
            out["total_blocks"] = self.total_blocks
            out["block_pool_occupancy"] = out["blocks_in_use"] / self.total_blocks
            out["peak_block_pool_occupancy"] = (
                out["peak_blocks_in_use"] / self.total_blocks
            )
        out["prefix_hit_rate"] = (
            out["prefix_tokens_reused"] / out["prompt_tokens"]
            if out["prompt_tokens"]
            else 0.0
        )
        out["draft_acceptance_rate"] = (
            out["draft_tokens_accepted"] / out["draft_tokens_proposed"]
            if out["draft_tokens_proposed"]
            else 0.0
        )
        out["mean_tokens_per_step"] = (
            out["tokens_served"] / out["decode_steps"]
            if out["decode_steps"]
            else 0.0
        )
        out["histograms"] = {
            name: h.summary() for name, h in self.hist.items()
        }
        return out


def _prom_name(key: str, prefix: str) -> str:
    # Prometheus convention wants base-unit suffixes spelled out
    base = key[:-2] + "_seconds" if key.endswith("_s") else key
    return f"{prefix}_{base}"


# Router-level monotonic counters the fleet snapshot adds on top of
# ServingStats.COUNTERS (infer/fleet.EngineFleet.ROUTER_COUNTERS mirrors
# this list); the exposition must type them ``counter``, not gauge.
FLEET_COUNTERS = (
    "requests_routed_prefix_affinity",
    "requests_routed_adapter_affinity",
    "requests_routed_least_loaded",
    "requests_routed_round_robin",
    "requests_failed_over",
    "requests_rerouted_overflow",
    "requests_shed_fleet_saturated",
    "requests_shed_fleet_brownout",
)


def prometheus_exposition(
    snap: Dict[str, Any],
    histograms: Optional[Dict[str, Histogram]] = None,
    memory: Optional[Dict[str, Dict[str, Optional[int]]]] = None,
    prefix: str = "serving",
    replicas: Optional[
        List[Tuple[str, Dict[str, Any], Optional[Dict[str, Histogram]]]]
    ] = None,
    tenant_histograms: Optional[Dict[str, Dict[str, Histogram]]] = None,
    counters: Optional[set] = None,
) -> str:
    """Render a ``ServingStats.snapshot()`` (plus the live histogram
    objects and an optional ``device_memory_report()``) as Prometheus text
    exposition (format version 0.0.4).

    Counter keys (``ServingStats.COUNTERS`` + ``FLEET_COUNTERS``) get the
    ``_total`` suffix and ``# TYPE counter``; every other numeric value is
    a gauge; string values (engine kind, circuit state) collapse into one
    ``<prefix>_info{...} 1`` info-style line; trailing ``_s`` becomes
    ``_seconds``. Histograms emit cumulative ``le`` buckets straight from
    the live ``Histogram`` objects, not the snapshot summaries.

    ``replicas`` — a fleet's per-replica view: ``(label, snapshot,
    histograms)`` triples. Each aggregate sample is followed by the same
    metric with a ``replica="<label>"`` label per replica (ONE ``# TYPE``
    per metric name, all samples grouped under it, as the format
    requires); per-replica string values collapse into one
    ``<prefix>_replica_info{replica=...} 1`` line each.

    ``counters`` — override the counter-typed key set; defaults to the
    serving union above. The trainer exposition passes its own set.
    """
    if counters is None:
        counters = set(ServingStats.COUNTERS) | set(FLEET_COUNTERS)
    replicas = replicas or []
    lines: List[str] = []
    labels = []
    for key in sorted(snap):
        value = snap[key]
        if isinstance(value, str):
            labels.append(f'{key}="{value}"')
    if labels:
        name = f"{prefix}_info"
        lines.append(f"# TYPE {name} gauge")
        lines.append(f'{name}{{{",".join(labels)}}} 1')
    if replicas:
        name = f"{prefix}_replica_info"
        lines.append(f"# TYPE {name} gauge")
        for label, rsnap, _ in replicas:
            rlabels = [f'replica="{label}"'] + [
                f'{key}="{rsnap[key]}"'
                for key in sorted(rsnap)
                if isinstance(rsnap[key], str)
            ]
            lines.append(f'{name}{{{",".join(rlabels)}}} 1')
    for key in snap:
        value = snap[key]
        if isinstance(value, bool):
            value = int(value)
        if not isinstance(value, (int, float)):
            continue
        name = _prom_name(key, prefix)
        if key in counters:
            name += "_total"
            lines.append(f"# TYPE {name} counter")
        else:
            lines.append(f"# TYPE {name} gauge")
        lines.append(f"{name} {value:.10g}")
        for label, rsnap, _ in replicas:
            rvalue = rsnap.get(key)
            if isinstance(rvalue, bool):
                rvalue = int(rvalue)
            if isinstance(rvalue, (int, float)):
                lines.append(f'{name}{{replica="{label}"}} {rvalue:.10g}')
    # multi-tenant samples: ``per_tenant`` is a dict value (skipped by the
    # numeric loop above), so its metrics are emitted explicitly with a
    # ``tenant`` label. TYPE lines are UNCONDITIONAL so the exposition
    # schema is identical with zero tenants (tests/test_metrics_schema.py).
    per_tenant = snap.get("per_tenant") or {}
    for key, kind in (
        ("requests", "counter"), ("tokens", "counter"),
        ("queue_depth", "gauge"),
    ):
        name = f"{prefix}_tenant_{key}"
        if kind == "counter":
            name += "_total"
        lines.append(f"# TYPE {name} {kind}")
        for tenant in sorted(per_tenant):
            lines.append(
                f'{name}{{tenant="{tenant}"}} '
                f"{int(per_tenant[tenant].get(key, 0))}"
            )
    # tier-labelled shed samples: ``requests_shed_by_tier`` is a dict value
    # (skipped by the numeric loop), emitted explicitly with a ``tier``
    # label. TYPE is UNCONDITIONAL and every known tier always has a sample
    # (ServingStats seeds all tiers at 0), so the schema cannot drift with
    # load. Snapshots without the key (window engine) emit the bare TYPE.
    by_tier = snap.get("requests_shed_by_tier") or {}
    name = f"{prefix}_requests_shed_tier_total"
    lines.append(f"# TYPE {name} counter")
    for tier in sorted(by_tier):
        lines.append(f'{name}{{tier="{tier}"}} {int(by_tier[tier])}')
    # capacity observatory: reason-labelled wasted-token counters and the
    # fleet replica-count gauge. ``wasted_tokens_by_reason`` is a dict
    # value (skipped by the numeric loop), emitted with a ``reason`` label
    # — every known reason always has a sample (ServingStats seeds all
    # reasons at 0), so the schema cannot drift with load. Gated on the
    # key so trainer/window snapshots (no ServingStats) stay unchanged.
    wasted = snap.get("wasted_tokens_by_reason")
    if wasted is not None:
        name = f"{prefix}_wasted_tokens_total"
        lines.append(f"# TYPE {name} counter")
        for reason in sorted(wasted):
            lines.append(f'{name}{{reason="{reason}"}} {int(wasted[reason])}')
            for label, rsnap, _ in replicas:
                rw = rsnap.get("wasted_tokens_by_reason") or {}
                if reason in rw:
                    lines.append(
                        f'{name}{{replica="{label}",reason="{reason}"}} '
                        f"{int(rw[reason])}"
                    )
        # elastic fleet: current replica count as its own gauge (a single
        # engine is a fleet of one); distinct from the fleet-only
        # ``serving_replicas`` so the series exists at every scale
        name = f"{prefix}_replica_count"
        lines.append(f"# TYPE {name} gauge")
        lines.append(f"{name} {int(snap.get('replicas', 1))}")
    # tiered KV: the hit/miss restore counters also roll up into one
    # result-labelled series (the dashboard's restore-hit-rate query works
    # off a single metric name). Gated on the snapshot key so window/
    # trainer snapshots stay unchanged; both labels always present.
    if "host_tier_restore_hits" in snap:
        name = f"{prefix}_host_tier_restores_total"
        lines.append(f"# TYPE {name} counter")
        for result, key in (
            ("hit", "host_tier_restore_hits"),
            ("miss", "host_tier_restore_misses"),
        ):
            lines.append(
                f'{name}{{result="{result}"}} {int(snap.get(key, 0))}'
            )
            for label, rsnap, _ in replicas:
                if key in rsnap:
                    lines.append(
                        f'{name}{{replica="{label}",result="{result}"}} '
                        f"{int(rsnap[key])}"
                    )
    # disaggregated serving: the fleet's stage-split token totals grouped
    # by replica role (``tokens_by_role`` is a dict value, skipped by the
    # numeric loop), emitted with a ``role`` label. Gated on the key (only
    # fleet aggregates carry it); TYPE lines are then UNCONDITIONAL so the
    # schema is identical for homogeneous and disaggregated fleets.
    by_role = snap.get("tokens_by_role")
    if by_role is not None:
        for key, kind in (
            ("prefill_tokens", "counter"),
            ("decode_tokens", "counter"),
            ("replicas", "gauge"),
        ):
            name = f"{prefix}_role_{key}"
            if kind == "counter":
                name += "_total"
            lines.append(f"# TYPE {name} {kind}")
            for role in sorted(by_role):
                lines.append(
                    f'{name}{{role="{role}"}} '
                    f"{int(by_role[role].get(key, 0))}"
                )
    # compile-ledger samples: ``compile`` is a nested dict (skipped by the
    # numeric loop), so per-program compile counts/seconds are emitted
    # explicitly with a ``program`` label. TYPE lines are UNCONDITIONAL so
    # the exposition schema is identical with an empty ledger — or with
    # snapshots that have no ``compile`` key at all (window-engine
    # fallback).
    compile_snap = snap.get("compile") or {}
    programs = compile_snap.get("programs") or {}
    name = f"{prefix}_compiles_total"
    lines.append(f"# TYPE {name} counter")
    for prog in sorted(programs):
        lines.append(
            f'{name}{{program="{prog}"}} {int(programs[prog]["compiles"])}'
        )
    name = f"{prefix}_compile_seconds_total"
    lines.append(f"# TYPE {name} counter")
    for prog in sorted(programs):
        lines.append(
            f'{name}{{program="{prog}"}} '
            f'{float(programs[prog]["compile_s"]):.10g}'
        )
    name = f"{prefix}_recompiles_after_warmup_total"
    lines.append(f"# TYPE {name} counter")
    lines.append(
        f"{name} {int(compile_snap.get('recompiles_after_warmup', 0))}"
    )
    # SLO burn-rate samples: ``slo`` is a nested report dict (skipped by
    # the numeric loop), emitted explicitly as one compliance gauge and
    # one burn-rate gauge per {objective, window}. TYPE lines are
    # UNCONDITIONAL when the snapshot carries the key, so the schema is
    # identical with an idle ring (window-engine fallback has no key and
    # emits nothing — same contract as ``compile``).
    slo = snap.get("slo")
    if slo is not None:
        name = f"{prefix}_slo_compliant"
        lines.append(f"# TYPE {name} gauge")
        lines.append(f"{name} {int(bool(slo.get('compliant', True)))}")
        name = f"{prefix}_slo_burn_rate"
        lines.append(f"# TYPE {name} gauge")
        for obj in sorted(slo.get("objectives") or {}):
            for label, w in sorted(
                (slo["objectives"][obj].get("windows") or {}).items()
            ):
                lines.append(
                    f'{name}{{objective="{obj}",window="{label}"}} '
                    f'{float(w.get("burn_rate", 0.0)):.10g}'
                )
    # per-weight-generation slices: settled-request counts and latency
    # p99s labelled by the generation the request resolved under — the
    # series a deploy's tail-latency story is read from.
    per_gen = snap.get("per_generation")
    if per_gen is not None:
        gen_series = (
            ("generation_requests_completed_total", "counter",
             lambda rec: int(rec.get("completed", 0))),
            ("generation_requests_failed_total", "counter",
             lambda rec: int(rec.get("failed", 0))),
            ("generation_ttft_p99_seconds", "gauge",
             lambda rec: float((rec.get("ttft") or {}).get("p99", 0.0))),
            ("generation_inter_token_p99_seconds", "gauge",
             lambda rec: float((rec.get("inter_token") or {}).get("p99", 0.0))),
        )
        for base, kind, get in gen_series:
            name = f"{prefix}_{base}"
            lines.append(f"# TYPE {name} {kind}")
            for gen in sorted(per_gen, key=lambda g: int(g)):
                lines.append(
                    f'{name}{{generation="{gen}"}} {get(per_gen[gen]):.10g}'
                )
    # per-tenant latency histograms: tenant-labelled bucket series for
    # the tails each tenant actually feels. TYPE lines are UNCONDITIONAL
    # whenever the caller passes a map (possibly empty) so the schema is
    # identical with zero tenants; the window-engine fallback passes
    # None and emits nothing.
    if tenant_histograms is not None:
        for key in ServingStats.TENANT_HIST_SPECS:
            name = _prom_name(f"tenant_{key}", prefix)
            lines.append(f"# TYPE {name} histogram")
            for tenant in sorted(tenant_histograms):
                h = tenant_histograms[tenant].get(key)
                if h is None:
                    continue
                lines.extend(
                    h.prometheus_lines(
                        name, labels=f'tenant="{tenant}"', include_type=False
                    )
                )
    for key in histograms or {}:
        name = _prom_name(key, prefix)
        lines.extend(histograms[key].prometheus_lines(name))
        for label, _, rhists in replicas:
            if rhists and key in rhists:
                lines.extend(
                    rhists[key].prometheus_lines(
                        name, labels=f'replica="{label}"', include_type=False
                    )
                )
    if memory:
        by_field = {
            "bytes_in_use": "device_hbm_bytes_in_use",
            "peak_bytes_in_use": "device_hbm_peak_bytes_in_use",
            "bytes_limit": "device_hbm_bytes_limit",
        }
        for field, name in by_field.items():
            emitted_type = False
            for dev in sorted(memory):
                value = memory[dev].get(field)
                if value is None:
                    continue
                if not emitted_type:
                    lines.append(f"# TYPE {name} gauge")
                    emitted_type = True
                lines.append(f'{name}{{device="{dev}"}} {int(value)}')
    return "\n".join(lines) + "\n"


PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def inject_perplexity(logs: Dict[str, float]) -> Dict[str, float]:
    """Add perplexity next to any loss, capped like the reference caps
    overflow (exp of large losses) via math.exp guarded at 700."""
    out = dict(logs)
    if "loss" in out:
        out["perplexity"] = math.exp(min(out["loss"], 700.0))
    if "eval_loss" in out:
        out["eval_perplexity"] = math.exp(min(out["eval_loss"], 700.0))
    return out


class _AimSink:
    def __init__(self, repo: str, experiment: str):
        from aim import Run  # optional dep, gated by caller

        self.run = Run(repo=repo, experiment=experiment)

    def set_params(self, params: Dict) -> None:
        # run-level hparams: what makes "Color by run.hparams.learning_rate"
        # and AimQL filters (docs/aim-workflow.md "Comparing runs") work
        self.run["hparams"] = params

    def log(self, step: int, epoch: float, logs: Dict[str, float]) -> None:
        for key, value in logs.items():
            if not isinstance(value, (int, float)):
                continue
            # naming contract: train_/eval_ prefixes become context.subset
            if key.startswith("eval_"):
                name, ctx = key[len("eval_") :], {"subset": "eval"}
            else:
                name, ctx = key, {"subset": "train"}
            self.run.track(value, name=name, step=step, epoch=int(epoch), context=ctx)

    def close(self) -> None:
        self.run.close()


class _JsonlSink:
    """Aim-schema-compatible flat-file sink (one JSON object per log event)."""

    def __init__(self, path: str):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._f = open(path, "a")

    def log(self, step: int, epoch: float, logs: Dict[str, float]) -> None:
        self._f.write(json.dumps({"step": step, "epoch": epoch, **logs}) + "\n")
        self._f.flush()

    def set_params(self, params: Dict) -> None:
        self._f.write(json.dumps({"hparams": params}) + "\n")
        self._f.flush()

    def close(self) -> None:
        self._f.close()


class MetricLogger:
    def __init__(
        self,
        output_dir: str,
        aim_repo: Optional[str] = None,
        experiment: str = "experiment",
        stdout: bool = True,
    ):
        self.history: List[Dict[str, float]] = []
        self.stdout = stdout
        self.primary = is_primary_host()
        self.sinks = []
        if self.primary:
            self.sinks.append(_JsonlSink(os.path.join(output_dir, "metrics.jsonl")))
            if aim_repo:
                try:
                    self.sinks.append(_AimSink(aim_repo, experiment))
                except ImportError:
                    print("[metrics] aim not installed; falling back to JSONL sink only")

    def set_params(self, params: Dict) -> None:
        """Record run-level hyperparameters on every sink that supports them
        (Aim run['hparams']; the JSONL sink writes one {'hparams': ...}
        record). Call once at trainer construction."""
        if not self.primary:
            return
        for sink in self.sinks:
            if hasattr(sink, "set_params"):
                sink.set_params(params)

    def log(self, step: int, epoch: float, logs: Dict[str, float]) -> None:
        logs = inject_perplexity(logs)
        record = {"step": step, "epoch": round(epoch, 4), **logs}
        self.history.append(record)
        if not self.primary:
            return
        for sink in self.sinks:
            sink.log(step, epoch, logs)
        if self.stdout:
            rendered = ", ".join(
                f"{k}={v:.6g}" if isinstance(v, float) else f"{k}={v}"
                for k, v in record.items()
            )
            print(f"[train] {rendered}", flush=True)

    def save_history(self, path: str) -> None:
        """``training_history.json`` artifact (reference ``training.py:315-316``).

        Written atomically (tmp + rename) because the trainer now flushes
        it at every eval/checkpoint boundary, not just at exit — a crash
        or preemption mid-write must never leave a truncated file where
        the previous good history used to be. Primary host only.
        """
        if not self.primary:
            return
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(self.history, f, indent=2)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def close(self) -> None:
        for sink in self.sinks:
            sink.close()
