"""Capacity observatory: turn the serving telemetry the stack already
collects into replica-count decisions (ROADMAP "elastic scaling").

Three layers, measurement before actuation:

- ``LoadForecaster`` — rides the engine's MetricRing tick clock (one
  ``update`` per SLO sample, ZERO new clock reads on the token hot path)
  and fits short/long-horizon irregular-interval EWMAs plus a trend over
  arrival rate, admission rate, and token throughput, alongside queue
  depth / queue-wait / live-slot smoothing. ``forecast(horizon_s)``
  extrapolates demand along the trend.
- ``SaturationModel`` — estimates one replica's sustainable token
  throughput from its MEASURED decode-tick time and slot count (tokens
  per tick per live slot x slots / tick seconds), mildly derated when the
  PR 9 roofline gauges (MFU / HBM-bandwidth utilization) say the device
  is already near its ceiling — headroom read from the device, not from
  a config constant.
- ``recommend_replicas`` + ``capacity_report`` — pure decision functions:
  demand outside the ``[down, up]`` utilization hysteresis band moves the
  recommendation to ``ceil(demand / (target x per_replica))``; inside the
  band the recommendation holds. ``down < target < up`` guarantees the
  recommendation crosses each band exactly once per load direction (no
  flapping at a plateau — tests/test_capacity.py pins ramp/burst/decay).

``Autoscaler`` closes the loop against an ``EngineFleet``: bounded by
min/max replicas and a per-action cooldown, one replica step per decision,
with a ``dry-run`` mode (the observability-first default) that records
would-be decisions as ``scale_decision`` flight events without acting.
Decision history is bounded and rides ``GET /v1/capacity``.

Everything here is host-side arithmetic over numbers the stats layer
already maintains — nothing touches the device or the token hot path.
"""

from __future__ import annotations

import math
import threading
from collections import deque
from typing import Any, Dict, List, Optional, Sequence


class LoadForecaster:
    """Short/long-horizon EWMA + trend over the serving load signals.

    Fed cumulative counters (arrivals, admissions, tokens served) plus
    instantaneous gauges once per MetricRing sample; converts counters to
    rates over the irregular sample interval and blends with
    ``w = 1 - exp(-dt/tau)`` so the horizons are real time constants no
    matter how the tick cadence wobbles. The trend is the smoothed slope
    of the SHORT token-rate EWMA — the signal ``forecast`` extrapolates.

    Pure host arithmetic, explicit ``now`` everywhere: deterministic under
    synthetic clocks (tests/test_capacity.py drives ramps and bursts with
    a fake timeline).
    """

    RATES = (
        "arrival_rate", "admit_rate", "token_rate",
        # disaggregation split of token_rate: prompt positions ingested by
        # prefill forwards vs tokens emitted by decode ticks — the two
        # demand axes the prefill:decode ratio autoscaler balances
        "prefill_token_rate", "decode_token_rate",
    )

    def __init__(self, short_tau_s: float = 30.0, long_tau_s: float = 300.0):
        self.short_tau_s = float(short_tau_s)
        self.long_tau_s = float(long_tau_s)
        self._t: Optional[float] = None
        self._counters: Dict[str, int] = {}
        self._short: Dict[str, float] = {}
        self._long: Dict[str, float] = {}
        # smoothed d(short token_rate)/dt, tokens/s per second
        self._trend: Optional[float] = None
        self.queue_depth = 0.0
        self.queue_wait_s = 0.0
        self.live_slots_mean = 0.0
        self.samples = 0

    def update(
        self,
        now: float,
        *,
        arrivals: int,
        admitted: int,
        tokens: int,
        queue_depth: int = 0,
        queue_wait_s: float = 0.0,
        live_slots: int = 0,
        prefill_tokens: int = 0,
        decode_tokens: int = 0,
    ) -> None:
        """One sample: cumulative ``arrivals``/``admitted``/``tokens``
        totals plus instantaneous gauges, stamped ``now`` (the caller's
        tick clock). ``prefill_tokens``/``decode_tokens`` are the
        cumulative stage-split counters; callers that don't track the
        split may omit them (the split rates then read 0)."""
        if self._t is None:
            self._t = now
            self._counters = {
                "arrival_rate": int(arrivals),
                "admit_rate": int(admitted),
                "token_rate": int(tokens),
                "prefill_token_rate": int(prefill_tokens),
                "decode_token_rate": int(decode_tokens),
            }
            return
        dt = now - self._t
        if dt <= 1e-6:
            return
        self._t = now
        w_s = 1.0 - math.exp(-dt / self.short_tau_s)
        w_l = 1.0 - math.exp(-dt / self.long_tau_s)
        totals = {
            "arrival_rate": int(arrivals),
            "admit_rate": int(admitted),
            "token_rate": int(tokens),
            "prefill_token_rate": int(prefill_tokens),
            "decode_token_rate": int(decode_tokens),
        }
        prev_token_short = self._short.get("token_rate")
        for name, total in totals.items():
            inst = max(0, total - self._counters.get(name, total)) / dt
            self._counters[name] = total
            self._short[name] = (
                inst if name not in self._short
                else (1.0 - w_s) * self._short[name] + w_s * inst
            )
            self._long[name] = (
                inst if name not in self._long
                else (1.0 - w_l) * self._long[name] + w_l * inst
            )
        if prev_token_short is not None:
            slope = (self._short["token_rate"] - prev_token_short) / dt
            self._trend = (
                slope if self._trend is None
                else (1.0 - w_l) * self._trend + w_l * slope
            )
        self.queue_depth += w_s * (float(queue_depth) - self.queue_depth)
        self.queue_wait_s += w_s * (float(queue_wait_s) - self.queue_wait_s)
        self.live_slots_mean += w_s * (float(live_slots) - self.live_slots_mean)
        self.samples += 1

    @property
    def trend_tokens_per_s2(self) -> float:
        return self._trend or 0.0

    def _staleness(self, now: Optional[float], tau: float) -> float:
        """Read-side decay factor for a stale forecaster. ``update`` only
        runs when the engine ticks, so an idle replica's EWMAs freeze at
        whatever rate the last busy tick measured — on a starved runner
        that frozen peak kept the fleet's demand estimate high through a
        quiet phase and the scale-DOWN band never fired (the PR 17
        SERVE_ELASTIC failure). Decaying by ``exp(-(now - last)/tau)`` at
        read is exactly the continuous limit of feeding zero-rate samples
        over the gap, so a silent forecaster reads the same as one that
        kept sampling an idle engine."""
        if now is None or self._t is None:
            return 1.0
        gap = now - self._t
        if gap <= 0.0:
            return 1.0
        return math.exp(-gap / tau)

    def rate(
        self, name: str, horizon: str = "short", now: Optional[float] = None
    ) -> float:
        table = self._short if horizon == "short" else self._long
        tau = self.short_tau_s if horizon == "short" else self.long_tau_s
        return table.get(name, 0.0) * self._staleness(now, tau)

    def forecast(self, horizon_s: float, now: Optional[float] = None) -> float:
        """Projected token demand ``horizon_s`` ahead: the short-horizon
        rate extrapolated along the smoothed trend, floored at the long-
        horizon baseline's decay toward zero (never negative)."""
        base = self.rate("token_rate", "short", now=now)
        trend = self.trend_tokens_per_s2 * self._staleness(now, self.long_tau_s)
        return max(0.0, base + trend * float(horizon_s))

    def snapshot(self, now: Optional[float] = None) -> Dict[str, Any]:
        """Decision-ready view. Pass ``now`` (the reader's clock) to apply
        staleness decay; omit it for the raw last-sample EWMAs."""
        decay_s = self._staleness(now, self.short_tau_s)
        return {
            "samples": self.samples,
            "short_tau_s": self.short_tau_s,
            "long_tau_s": self.long_tau_s,
            "rates_short": {
                n: self.rate(n, "short", now=now) for n in self.RATES
            },
            "rates_long": {
                n: self.rate(n, "long", now=now) for n in self.RATES
            },
            "trend_tokens_per_s2":
                self.trend_tokens_per_s2
                * self._staleness(now, self.long_tau_s),
            "queue_depth": self.queue_depth * decay_s,
            "queue_wait_s": self.queue_wait_s * decay_s,
            "live_slots_mean": self.live_slots_mean * decay_s,
        }


class SaturationModel:
    """Per-replica sustainable token throughput from measured decode ticks.

    One replica at full slots serves ``slots x tokens-per-tick-per-live-
    slot`` tokens per tick (the per-slot rate is 1.0 for plain decode,
    above 1.0 with accepted speculation), and a tick takes the MEASURED
    mean ``decode_tick_s``. When the roofline gauges say the device is
    already past ``derate_above`` utilization, the estimate is shaved
    linearly — a device at its bandwidth ceiling cannot be assumed to
    scale its tick rate with more resident slots.

    Returns 0.0 while no tick has been timed (cold replica): "unknown",
    which the report treats as no-signal rather than zero capacity.
    """

    def __init__(self, derate_above: float = 0.8):
        self.derate_above = float(derate_above)

    def sustainable_tokens_per_s(
        self,
        *,
        slots: int,
        mean_decode_tick_s: float,
        mean_tokens_per_step: float = 0.0,
        live_slots_mean: float = 0.0,
        mfu: float = 0.0,
        hbm_bw_util: float = 0.0,
    ) -> float:
        if mean_decode_tick_s <= 0.0 or slots <= 0:
            return 0.0
        per_slot = (
            mean_tokens_per_step / live_slots_mean
            if live_slots_mean > 0.0 and mean_tokens_per_step > 0.0
            else 1.0
        )
        per_slot = max(1.0, per_slot)  # plain decode floor: 1 token/tick
        rate = slots * per_slot / mean_decode_tick_s
        util = max(float(mfu), float(hbm_bw_util))
        if util > self.derate_above:
            rate *= max(0.0, 1.0 - (util - self.derate_above))
        return rate


def recommend_replicas(
    demand_tokens_per_s: float,
    per_replica_tokens_per_s: float,
    current: int,
    *,
    up: float = 0.85,
    down: float = 0.45,
    target: float = 0.65,
    role: Optional[str] = None,
) -> int:
    """Hysteresis-banded replica recommendation (pure).

    ``role`` scopes the recommendation to one stage of a disaggregated
    fleet: the demand/capacity arguments are then that role's share (the
    prefill-tokens/s or decode-tokens/s axis and the role-capable replica
    count) rather than fleet totals. The band math is identical either
    way — the label exists so per-role calls read as what they are.

    Utilization ``demand / (current x per_replica)`` inside ``[down, up]``
    holds the current count. Above ``up`` the recommendation jumps to
    ``ceil(demand / (target x per_replica))`` (always > current because
    ``up > target``); below ``down`` it shrinks to the same target (never
    below one) — and only if the shrunken fleet would still sit at or
    under ``up``, so a scale-down can never trigger an immediate
    scale-up, and a steady load crosses each band exactly once. The
    Autoscaler paces actuation at one replica step per tick regardless of
    how far the recommendation jumps.
    """
    if current <= 0:
        return max(1, current)
    if per_replica_tokens_per_s <= 0.0:
        return current  # capacity unknown: no signal, no move
    cap = current * per_replica_tokens_per_s
    if demand_tokens_per_s > up * cap:
        return max(
            current + 1,
            math.ceil(demand_tokens_per_s / (target * per_replica_tokens_per_s)),
        )
    if demand_tokens_per_s < down * cap and current > 1:
        n = min(
            current - 1,
            max(1, math.ceil(
                demand_tokens_per_s / (target * per_replica_tokens_per_s)
            )),
        )
        if demand_tokens_per_s <= up * n * per_replica_tokens_per_s:
            return n
    return current


def capacity_report(
    forecasts: Sequence[Dict[str, Any]],
    replica_capacities: Sequence[float],
    current_replicas: int,
    *,
    horizon_s: float = 60.0,
    up: float = 0.85,
    down: float = 0.45,
    target: float = 0.65,
    min_replicas: int = 1,
    max_replicas: Optional[int] = None,
) -> Dict[str, Any]:
    """One decision-ready report (pure) from per-replica forecaster
    snapshots and per-replica sustainable-throughput estimates.

    Fleet load is the SUM of replica rates (the router spreads arrivals,
    so replica arrival rates partition the fleet's); queue signals take
    the worst replica. Demand inflates the measured token rate by the
    backlog factor — a saturated fleet's token rate equals its capacity
    by definition, so the queue is where unmet demand is visible. Unknown
    capacity (no replica has timed a tick yet) recommends no change.
    """
    fleet_arrival = sum(
        f.get("rates_short", {}).get("arrival_rate", 0.0) for f in forecasts
    )
    fleet_admit = sum(
        f.get("rates_short", {}).get("admit_rate", 0.0) for f in forecasts
    )
    fleet_tokens = sum(
        f.get("rates_short", {}).get("token_rate", 0.0) for f in forecasts
    )
    fleet_trend = sum(
        f.get("trend_tokens_per_s2", 0.0) for f in forecasts
    )
    queue_depth = sum(f.get("queue_depth", 0.0) for f in forecasts)
    queue_wait_s = max(
        [f.get("queue_wait_s", 0.0) for f in forecasts], default=0.0
    )
    live_slots = sum(f.get("live_slots_mean", 0.0) for f in forecasts)
    # backlog inflation: queued work per busy slot beyond ~one queued
    # request per slot means the token rate understates offered load
    backlog_factor = 1.0 + max(
        0.0, (queue_depth - live_slots) / max(1.0, live_slots)
    ) if queue_depth > 0 else 1.0
    demand_now = fleet_tokens * backlog_factor
    forecast_demand = max(
        0.0, demand_now + fleet_trend * float(horizon_s)
    )
    known = [c for c in replica_capacities if c > 0.0]
    per_replica = sum(known) / len(known) if known else 0.0
    total_capacity = per_replica * current_replicas
    recommended = recommend_replicas(
        forecast_demand, per_replica, current_replicas,
        up=up, down=down, target=target,
    )
    lo = max(1, int(min_replicas))
    # no ceiling configured -> the recommendation stays unclamped above:
    # even a deployment that cannot grow should SEE the scale-up signal
    hi = int(max_replicas) if max_replicas else None
    recommended = max(recommended, lo)
    if hi is not None:
        recommended = min(recommended, max(hi, lo))
    return {
        "replicas": current_replicas,
        "current_load": {
            "arrival_rate": fleet_arrival,
            "admit_rate": fleet_admit,
            "token_rate": fleet_tokens,
            "queue_depth": queue_depth,
            "queue_wait_s": queue_wait_s,
            "live_slots_mean": live_slots,
            "backlog_factor": backlog_factor,
            "demand_tokens_per_s": demand_now,
        },
        "forecast": {
            "horizon_s": float(horizon_s),
            "demand_tokens_per_s": forecast_demand,
            "trend_tokens_per_s2": fleet_trend,
        },
        "capacity": {
            "per_replica_tokens_per_s": per_replica,
            "total_tokens_per_s": total_capacity,
            "replicas_measured": len(known),
        },
        "headroom": {
            "tokens_per_s": total_capacity - forecast_demand,
            "utilization": (
                forecast_demand / total_capacity if total_capacity else 0.0
            ),
        },
        "recommended_replicas": recommended,
        "bands": {"up": up, "down": down, "target": target},
        "bounds": {"min_replicas": lo, "max_replicas": hi},
    }


def role_sections(
    roles: Sequence[str],
    forecasts: Sequence[Dict[str, Any]],
    replica_capacities: Sequence[float],
    *,
    growth: float = 1.0,
    up: float = 0.85,
    down: float = 0.45,
    target: float = 0.65,
) -> Dict[str, Dict[str, Any]]:
    """Per-role demand/capacity/headroom view of a disaggregated fleet
    (pure). ``roles``/``forecasts``/``replica_capacities`` are parallel
    per-replica sequences; ``growth`` is the fleet forecast-to-now demand
    ratio, applied to each role's measured demand so the role forecasts
    sum to the fleet forecast.

    Demand per stage is summed over EVERY replica (a mixed replica
    contributes to both axes — its prefill tokens are prefill demand no
    matter who served them). Capacity per stage counts the replicas
    CAPABLE of that stage (dedicated + mixed) times the fleet's mean
    per-replica throughput, and the recommendation applies the same
    hysteresis bands as the fleet-level one to the role-scoped numbers.
    """
    known = [c for c in replica_capacities if c > 0.0]
    per_replica = sum(known) / len(known) if known else 0.0
    rate_key = {"prefill": "prefill_token_rate", "decode": "decode_token_rate"}
    out: Dict[str, Dict[str, Any]] = {}
    for stage in ("prefill", "decode"):
        capable = [
            i for i, r in enumerate(roles)
            if r == stage or r == "mixed"
        ]
        dedicated = sum(1 for r in roles if r == stage)
        demand_now = sum(
            f.get("rates_short", {}).get(rate_key[stage], 0.0)
            for f in forecasts
        )
        demand_fc = max(0.0, demand_now * growth)
        capacity = per_replica * len(capable)
        out[stage] = {
            "replicas": len(capable),
            "dedicated_replicas": dedicated,
            "demand_tokens_per_s": demand_now,
            "forecast_demand_tokens_per_s": demand_fc,
            "capacity_tokens_per_s": capacity,
            "headroom_tokens_per_s": capacity - demand_fc,
            "utilization": demand_fc / capacity if capacity else 0.0,
            "recommended_replicas": recommend_replicas(
                demand_fc, per_replica, len(capable),
                up=up, down=down, target=target, role=stage,
            ),
        }
    return out


def report_from_capacity_snapshots(
    snapshots: Sequence[Dict[str, Any]],
    current_replicas: int,
    *,
    model: Optional[SaturationModel] = None,
    horizon_s: float = 60.0,
    min_replicas: int = 1,
    max_replicas: Optional[int] = None,
) -> Dict[str, Any]:
    """``capacity_report`` straight from engine ``capacity_snapshot()``
    dicts: maps each snapshot through the saturation model and hands the
    forecaster views over. Shared by the fleet (N snapshots) and the
    single-engine ``/v1/capacity`` path (one snapshot, a fleet of one).
    Snapshots carrying a ``role`` add a per-role ``roles`` section —
    prefill vs decode demand, capacity, headroom, and a role-scoped
    recommendation — the ratio signal the role-aware Autoscaler acts on."""
    model = model or SaturationModel()
    forecasts = [s.get("forecaster") or {} for s in snapshots]
    capacities = [
        model.sustainable_tokens_per_s(
            slots=int(s.get("slots", 0)),
            mean_decode_tick_s=float(s.get("mean_decode_tick_s", 0.0)),
            mean_tokens_per_step=float(s.get("mean_tokens_per_step", 0.0)),
            live_slots_mean=float(s.get("live_slots_mean", 0.0)),
            mfu=float(s.get("model_flops_utilization", 0.0)),
            hbm_bw_util=float(s.get("hbm_bandwidth_utilization", 0.0)),
        )
        for s in snapshots
    ]
    report = capacity_report(
        forecasts,
        capacities,
        current_replicas,
        horizon_s=horizon_s,
        min_replicas=min_replicas,
        max_replicas=max_replicas,
    )
    roles = [str(s.get("role", "mixed")) for s in snapshots]
    demand_now = report["current_load"]["demand_tokens_per_s"]
    growth = (
        report["forecast"]["demand_tokens_per_s"] / demand_now
        if demand_now > 0.0 else 1.0
    )
    report["roles"] = role_sections(
        roles, forecasts, capacities,
        growth=growth,
        up=report["bands"]["up"],
        down=report["bands"]["down"],
        target=report["bands"]["target"],
    )
    return report


class Autoscaler:
    """Signal-driven elastic fleet control loop.

    ``tick(now)`` computes the fleet's ``capacity_report`` and, when the
    recommendation differs from the live replica count, takes ONE replica
    step toward it — bounded by ``[min_replicas, max_replicas]`` and a
    per-action ``cooldown_s`` (measured from the last APPLIED action, so
    a burst cannot ladder the fleet up faster than replicas warm).

    Modes: ``dry-run`` (default) records every would-be decision as a
    ``scale_decision`` flight event and in the bounded history without
    touching the fleet — run this first, read ``GET /v1/capacity``, then
    flip to ``on``. ``on`` additionally applies the step. ``off`` does
    nothing at all.

    ``tick`` is the deterministic test surface (explicit ``now``);
    ``start``/``stop`` run it on a daemon thread for the server.
    """

    MODES = ("dry-run", "on", "off")

    def __init__(
        self,
        fleet,
        mode: str = "dry-run",
        min_replicas: int = 1,
        max_replicas: int = 1,
        cooldown_s: float = 30.0,
        interval_s: float = 2.0,
        horizon_s: float = 60.0,
        history: int = 64,
        retire_timeout_s: float = 60.0,
        migrate_on_retire: Optional[bool] = None,
        ratio: bool = False,
    ):
        if mode not in self.MODES:
            raise ValueError(
                f"unknown autoscale mode {mode!r} (expected one of {self.MODES})"
            )
        self.fleet = fleet
        self.mode = mode
        # ratio mode (--autoscale-ratio): the prefill:decode ratio becomes
        # a scaling dimension. Scale-ups grow the most-pressured role,
        # scale-downs retire from the least-pressured one, and a role
        # imbalance with totals in-band still moves (grow the starved
        # role, or trade a surplus replica away when already at max).
        self.ratio = bool(ratio)
        self.min_replicas = max(1, int(min_replicas))
        self.max_replicas = max(self.min_replicas, int(max_replicas))
        self.cooldown_s = max(0.0, float(cooldown_s))
        self.interval_s = max(0.05, float(interval_s))
        self.horizon_s = float(horizon_s)
        self.retire_timeout_s = float(retire_timeout_s)
        # None defers to the fleet's own migrate_on_retire default; a bool
        # forces scale-down retirement to (not) live-migrate its streams
        self.migrate_on_retire = migrate_on_retire
        self._last_action_t: Optional[float] = None
        self._decisions: "deque[Dict[str, Any]]" = deque(maxlen=int(history))
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def tick(self, now: float) -> Optional[Dict[str, Any]]:
        """One control step. Returns the decision record when the
        recommendation called for a change (acted on or dry-run), else
        None. Safe to call concurrently with traffic."""
        if self.mode == "off":
            return None
        report = self.fleet.capacity_report(
            horizon_s=self.horizon_s,
            min_replicas=self.min_replicas,
            max_replicas=self.max_replicas,
        )
        current = int(report["replicas"])
        recommended = int(report["recommended_replicas"])
        role: Optional[str] = None
        if recommended != current:
            direction = "up" if recommended > current else "down"
            role = self._pick_role(report, direction)
        else:
            ratio_move = self._ratio_move(report, current)
            if ratio_move is None:
                return None
            direction, role, recommended = ratio_move
        in_cooldown = (
            self._last_action_t is not None
            and (now - self._last_action_t) < self.cooldown_s
        )
        decision: Dict[str, Any] = {
            "t": now,
            "mode": self.mode,
            "replicas": current,
            "recommended_replicas": recommended,
            "direction": direction,
            "demand_tokens_per_s":
                report["forecast"]["demand_tokens_per_s"],
            "per_replica_tokens_per_s":
                report["capacity"]["per_replica_tokens_per_s"],
            "cooldown": bool(in_cooldown),
            "applied": False,
        }
        if self.ratio:
            decision["role"] = role
            roles = report.get("roles") or {}
            if roles:
                decision["role_demand_tokens_per_s"] = {
                    r: s["demand_tokens_per_s"] for r, s in roles.items()
                }
        if not in_cooldown and self.mode == "on":
            try:
                kwargs: Dict[str, Any] = {}
                if role is not None:
                    # only role-aware fleets see the kwarg: scripted stub
                    # fleets with the old signatures keep working when
                    # ratio mode is off
                    kwargs["role"] = role
                if direction == "up":
                    self.fleet.add_replica(**kwargs)
                else:
                    kwargs["timeout_s"] = self.retire_timeout_s
                    if self.migrate_on_retire is not None:
                        kwargs["migrate"] = self.migrate_on_retire
                    self.fleet.retire_replica(**kwargs)
                decision["applied"] = True
                self._last_action_t = now
            except Exception as e:  # fleet at bounds / factory failure
                decision["error"] = f"{type(e).__name__}: {e}"
        recorder = getattr(self.fleet, "recorder", None)
        if recorder is not None:
            recorder.record(
                "scale_decision",
                **{k: v for k, v in decision.items() if k != "t"},
            )
        with self._lock:
            self._decisions.append(decision)
        return decision

    def _pick_role(self, report: Dict[str, Any], direction: str) -> Optional[str]:
        """Which role a count-driven step should touch (None = fleet
        default, i.e. a mixed replica). Scale-ups grow the most-pressured
        stage; scale-downs give back a dedicated replica of the
        least-pressured stage, or defer to the fleet default when neither
        stage has a dedicated replica to spare."""
        roles = report.get("roles") or {}
        if not self.ratio or not roles:
            return None
        if direction == "up":
            return max(roles, key=lambda r: roles[r]["utilization"])
        cands = [r for r in roles if roles[r]["dedicated_replicas"] > 0]
        if not cands:
            return None
        return min(cands, key=lambda r: roles[r]["utilization"])

    def _ratio_move(
        self, report: Dict[str, Any], current: int
    ) -> Optional[tuple]:
        """A ratio-only step when the fleet total is already in-band:
        (direction, role, recommended) or None. A role whose scoped
        recommendation exceeds its capable count is starved — grow it if
        the fleet has headroom, otherwise trade away a dedicated replica
        of an over-provisioned role so the next tick's count recovery
        re-adds capacity where it's needed."""
        if not self.ratio:
            return None
        roles = report.get("roles") or {}
        if not roles or report["capacity"]["per_replica_tokens_per_s"] <= 0.0:
            return None
        over = [
            r for r, s in roles.items()
            if s["recommended_replicas"] > s["replicas"]
        ]
        under = [
            r for r, s in roles.items()
            if s["recommended_replicas"] < s["replicas"]
            and s["dedicated_replicas"] > 0
        ]
        if not over:
            return None
        starved = max(over, key=lambda r: roles[r]["utilization"])
        if current < self.max_replicas:
            return ("up", starved, current + 1)
        if under and current > self.min_replicas:
            surplus = min(under, key=lambda r: roles[r]["utilization"])
            return ("down", surplus, current - 1)
        return None

    def decisions(self, limit: int = 64) -> List[Dict[str, Any]]:
        """Most recent decisions, newest last (bounded history for
        ``GET /v1/capacity``)."""
        with self._lock:
            out = list(self._decisions)
        return out[-max(1, int(limit)):]

    # -------------------------------------------------- background loop

    def start(self) -> None:
        """Run ``tick`` every ``interval_s`` on a daemon thread (server
        mode; tests call ``tick`` directly)."""
        if self._thread is not None:
            return
        import time as _time

        def _loop() -> None:
            while not self._stop.wait(self.interval_s):
                try:
                    self.tick(_time.monotonic())
                except Exception:  # never kill the loop on a bad sample
                    pass

        self._thread = threading.Thread(target=_loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.interval_s * 4)
            self._thread = None
