from llm_fine_tune_distributed_tpu.observe.metrics import (  # noqa: F401
    MetricLogger,
    ServingStats,
    prometheus_exposition,
)
from llm_fine_tune_distributed_tpu.observe.throughput import ThroughputMeter  # noqa: F401
from llm_fine_tune_distributed_tpu.observe.tracing import (  # noqa: F401
    FlightRecorder,
    Histogram,
    RequestTrace,
    TraceJsonlWriter,
)
