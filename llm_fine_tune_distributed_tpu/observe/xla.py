"""XLA runtime introspection: compile ledger, cost-analysis utilization
gauges, and on-demand profiler capture.

The serving engines and the trainer dispatch a small set of jitted
programs (decode tick, prefill chunk, speculative verify, draft step,
train step). Steady-state behaviour is: every program compiles exactly
once per shape bucket during warmup, then never again — a retrace in
steady state silently costs seconds per occurrence and is always a bug
(a stray shape bucket, a weak-type flip, a donated-buffer mismatch).
This module makes that contract observable:

- ``CompileLedger`` records every compilation (program name, abstract
  arg shapes, wall compile seconds, engine generation), deduplicates by
  (program, shapes), and exposes ``recompiles_after_warmup`` — the
  number that must stay zero once ``mark_warm()`` has been called.
  Listeners (the engines' flight recorders) are notified of post-warmup
  recompiles as they happen.
- ``instrument()`` wraps a jitted callable so its first call registers
  with the ledger. For engine hot-path programs (``aot=True``) the
  first call goes through ``fn.lower(...).compile()`` — exact compile
  wall time plus ``cost_analysis()`` FLOPs / bytes-accessed — and the
  AOT executable becomes the dispatch target (one compile, not two).
  Any AOT failure falls back permanently to the plain jit callable with
  first-call wall timing (an upper bound on compile time).
- ``device_peak_specs()`` + ``utilization_from_cost()`` turn the cost
  analysis and the ``decode_tick_s`` histogram into
  ``model_flops_utilization`` and ``hbm_bandwidth_utilization`` gauges
  (batched decode is bandwidth-bound; the BW gauge is the one that
  should sit near its roofline).
- ``ProfilerCapture`` guards ``jax.profiler`` traces for the serving
  ``POST /v1/profile`` endpoint: one capture at a time, auto-stop after
  the requested duration, a fresh subdirectory per capture.
- ``annotate()`` yields ``jax.profiler.TraceAnnotation`` spans so tick
  phases (admit/prefill/verify/sample) line up with captured traces.
"""

from __future__ import annotations

import contextlib
import itertools
import os
import threading
import time
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

import jax

__all__ = [
    "CaptureBusyError",
    "CompileLedger",
    "ProfilerCapture",
    "annotate",
    "device_peak_specs",
    "instrument",
    "utilization_from_cost",
]


# ------------------------------------------------------------------ ledger


class CompileLedger:
    """Thread-safe registry of XLA compilations, deduplicated by
    (program, abstract shapes). Re-recording an already-seen signature
    bumps its compile count (a cache rebuild), and any record after
    ``mark_warm()`` increments ``recompiles_after_warmup`` and notifies
    listeners — steady-state recompile is a bug, and this is the counter
    that proves its absence.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._entries: Dict[Tuple[str, str], Dict[str, Any]] = {}
        self._seq = 0
        self._warmed = False
        self.recompiles_after_warmup = 0
        # engines stamp their supervisor generation here so ledger entries
        # attribute to the engine incarnation that compiled them (replicas
        # sharing one Generator share one ledger; the stamp is best-effort)
        self.current_generation = 0
        self._listeners: List[Callable[..., None]] = []

    def record(
        self,
        program: str,
        shapes: Any,
        compile_s: float,
        flops: Optional[float] = None,
        bytes_accessed: Optional[float] = None,
    ) -> None:
        sig = shapes if isinstance(shapes, str) else str(tuple(shapes)) if isinstance(shapes, (list, tuple)) else str(shapes)
        with self._lock:
            self._seq += 1
            entry = self._entries.get((program, sig))
            if entry is None:
                entry = {
                    "compiles": 0,
                    "compile_s": 0.0,
                    "flops": None,
                    "bytes_accessed": None,
                    "generation": self.current_generation,
                }
                self._entries[(program, sig)] = entry
            entry["compiles"] += 1
            entry["compile_s"] += float(compile_s)
            entry["seq"] = self._seq
            entry["generation"] = self.current_generation
            if flops is not None:
                entry["flops"] = float(flops)
            if bytes_accessed is not None:
                entry["bytes_accessed"] = float(bytes_accessed)
            after_warmup = self._warmed
            if after_warmup:
                self.recompiles_after_warmup += 1
            listeners = list(self._listeners)
        if after_warmup:
            for fn in listeners:
                try:
                    fn(program, sig, float(compile_s), self.current_generation)
                except Exception:
                    pass  # a broken listener must never fail a dispatch

    def mark_warm(self) -> None:
        """Declare warmup over: every record from here on is a recompile."""
        with self._lock:
            self._warmed = True

    @property
    def warmed(self) -> bool:
        return self._warmed

    def add_listener(self, fn: Callable[..., None]) -> None:
        """``fn(program, shapes, compile_s, generation)`` on every
        post-warmup record."""
        with self._lock:
            self._listeners.append(fn)

    def cost_for(self, programs: Iterable[str]) -> Tuple[float, float]:
        """(flops, bytes_accessed) of the most recently compiled entry
        among ``programs`` that carries cost analysis; (0, 0) if none."""
        names = set(programs)
        best = None
        with self._lock:
            for (name, _), e in self._entries.items():
                if name in names and e.get("flops") is not None:
                    if best is None or e["seq"] > best["seq"]:
                        best = e
        if best is None:
            return 0.0, 0.0
        return float(best["flops"] or 0.0), float(best["bytes_accessed"] or 0.0)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            programs: Dict[str, Dict[str, float]] = {}
            for (name, _), e in self._entries.items():
                p = programs.setdefault(name, {"compiles": 0, "compile_s": 0.0})
                p["compiles"] += e["compiles"]
                p["compile_s"] += e["compile_s"]
            for p in programs.values():
                p["compile_s"] = round(p["compile_s"], 6)
            return {
                "programs": programs,
                "total_compiles": sum(p["compiles"] for p in programs.values()),
                "total_compile_s": round(
                    sum(p["compile_s"] for p in programs.values()), 6
                ),
                "recompiles_after_warmup": self.recompiles_after_warmup,
                "warmed": self._warmed,
            }

    @staticmethod
    def merge(ledgers: Iterable["CompileLedger"]) -> Dict[str, Any]:
        """Snapshot-shaped union over DISTINCT ledgers (fleet replicas
        sharing one Generator share one ledger object — dedup by
        identity so shared compilations are not double-counted)."""
        seen: Dict[int, CompileLedger] = {}
        for led in ledgers:
            if led is not None:
                seen.setdefault(id(led), led)
        programs: Dict[str, Dict[str, float]] = {}
        recompiles = 0
        warmed = bool(seen)
        for led in seen.values():
            snap = led.snapshot()
            for name, p in snap["programs"].items():
                agg = programs.setdefault(name, {"compiles": 0, "compile_s": 0.0})
                agg["compiles"] += p["compiles"]
                agg["compile_s"] += p["compile_s"]
            recompiles += snap["recompiles_after_warmup"]
            warmed = warmed and snap["warmed"]
        for p in programs.values():
            p["compile_s"] = round(p["compile_s"], 6)
        return {
            "programs": programs,
            "total_compiles": sum(p["compiles"] for p in programs.values()),
            "total_compile_s": round(
                sum(p["compile_s"] for p in programs.values()), 6
            ),
            "recompiles_after_warmup": recompiles,
            "warmed": warmed,
        }


# ----------------------------------------------------- program instrumenting


def _abstract_shapes(args: Any, kwargs: Any = None) -> str:
    """Compact abstract-shape signature of a call's arguments. Large
    pytrees (a train step's parameter forest) are summarized rather than
    enumerated — the signature only needs to be stable per shape bucket."""
    leaves = jax.tree_util.tree_leaves((args, kwargs or {}))
    parts = []
    for leaf in leaves:
        shape = getattr(leaf, "shape", None)
        if shape is not None:
            parts.append(f"{getattr(leaf, 'dtype', '?')}{tuple(shape)}")
        else:
            parts.append(type(leaf).__name__)
    if len(parts) > 8:
        # summarize, but keep the tail's information: the head of a train
        # step's leaf list is all parameters (identical across calls) while
        # the distinguishing shapes (cache width, batch bucket) sit deeper,
        # so a plain prefix-truncation would alias genuinely different
        # signatures — and the signature dispatches AOT executables
        digest = hash(tuple(parts)) & 0xFFFFFFFF
        parts = parts[:4] + [f"...{len(parts)}leaves:{digest:08x}"]
    return "(" + ",".join(parts) + ")"


def _extract_cost(compiled: Any) -> Tuple[Optional[float], Optional[float]]:
    """FLOPs / bytes-accessed from ``Compiled.cost_analysis()``, which
    returns a dict on recent JAX and a one-element list on older ones."""
    try:
        ca = compiled.cost_analysis()
    except Exception:
        return None, None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    if not isinstance(ca, dict):
        return None, None
    return (
        float(ca.get("flops", 0.0) or 0.0),
        float(ca.get("bytes accessed", 0.0) or 0.0),
    )


class _InstrumentedProgram:
    """Wraps a jitted callable so every NEW call signature registers with
    the ledger. ``aot=True`` compiles ahead-of-time per signature (exact
    compile seconds + cost analysis) and dispatches later same-shape
    calls straight to that executable; an AOT failure (python-scalar
    args, donation quirks, old JAX) falls back to the plain jit callable
    for that signature, timing its first call as an upper bound on
    compile time. Dispatch is keyed by the abstract shapes of the actual
    call, NOT the owner's cache key: a Generator's jit-cache key doesn't
    fully determine shapes (two engines with different slot counts share
    one Generator, so one ``slot_prefill`` bucket entry sees two cache
    widths) and an AOT executable — unlike plain jit — cannot absorb a
    new shape silently. First calls are serialized so two threads racing
    a cold signature produce one ledger entry. Non-``__call__`` attributes
    (``lower``, ``eval_shape``, ...) proxy to the wrapped callable."""

    __slots__ = ("_program", "_fn", "_ledger", "_shapes", "_aot", "_lock", "_calls")

    def __init__(self, program, fn, ledger, shapes=None, aot=True):
        self._program = program
        self._fn = fn
        self._ledger = ledger
        self._shapes = shapes
        self._aot = aot
        self._lock = threading.Lock()
        self._calls: dict = {}  # signature -> AOT executable or plain jit fn

    def __getattr__(self, name):
        if name.startswith("_"):  # never proxy slot misses back into _fn
            raise AttributeError(name)
        return getattr(self._fn, name)

    def __call__(self, *args, **kwargs):
        sig = _abstract_shapes(args, kwargs)
        call = self._calls.get(sig)
        if call is not None:
            return call(*args, **kwargs)
        with self._lock:
            call = self._calls.get(sig)
            if call is not None:
                return call(*args, **kwargs)
            return self._first_call(sig, args, kwargs)

    def _first_call(self, sig, args, kwargs):
        shapes = sig if self._shapes is None else f"{self._shapes}{sig}"
        if self._aot:
            try:
                t0 = time.perf_counter()
                compiled = self._fn.lower(*args, **kwargs).compile()
                dt = time.perf_counter() - t0
                flops, nbytes = _extract_cost(compiled)
                out = compiled(*args, **kwargs)
                # record only after a successful execute: if the AOT
                # artifact can't even run, the plain-jit retry below must
                # own the ledger entry
                self._ledger.record(self._program, shapes, dt, flops, nbytes)
                self._calls[sig] = compiled
                return out
            except Exception:
                pass
        t0 = time.perf_counter()
        out = self._fn(*args, **kwargs)
        dt = time.perf_counter() - t0
        self._ledger.record(self._program, shapes, dt)
        self._calls[sig] = self._fn
        return out


def instrument(program, fn, ledger, shapes=None, aot=True):
    """Ledger-wrap a jitted callable (see ``_InstrumentedProgram``)."""
    return _InstrumentedProgram(program, fn, ledger, shapes=shapes, aot=aot)


# -------------------------------------------------- utilization from cost


# (peak dense bf16 FLOP/s, peak HBM bytes/s) per chip, matched by
# substring against ``device_kind``. Marketing peaks — the gauges they
# feed are roofline fractions, not absolute truth.
_DEVICE_PEAKS: Tuple[Tuple[str, Tuple[float, float]], ...] = (
    ("v6e", (918e12, 1.64e12)),
    ("v5p", (459e12, 2.765e12)),
    ("v5e", (197e12, 8.19e11)),
    ("v5lite", (197e12, 8.19e11)),
    ("v4", (275e12, 1.2288e12)),
    ("v3", (123e12, 9.0e11)),
    ("v2", (46e12, 7.0e11)),
)


def device_peak_specs(device=None) -> Tuple[float, float]:
    """(peak FLOP/s, peak HBM bytes/s) for the given (default: first)
    device. (0, 0) on CPU or unknown hardware — downstream gauges read
    0.0 rather than invent a roofline. Overridable via SERVE_PEAK_FLOPS
    and SERVE_PEAK_HBM_BPS for chips not in the table."""
    env_f = os.environ.get("SERVE_PEAK_FLOPS")
    env_b = os.environ.get("SERVE_PEAK_HBM_BPS")
    if env_f or env_b:
        return float(env_f or 0.0), float(env_b or 0.0)
    if device is None:
        try:
            device = jax.devices()[0]
        except Exception:
            return 0.0, 0.0
    kind = str(getattr(device, "device_kind", "")).lower()
    for sub, peaks in _DEVICE_PEAKS:
        if sub in kind:
            return peaks
    return 0.0, 0.0


def utilization_from_cost(
    flops: float,
    bytes_accessed: float,
    mean_step_s: float,
    peak_flops: float,
    peak_bw: float,
) -> Tuple[float, float]:
    """(model_flops_utilization, hbm_bandwidth_utilization) for a program
    whose cost analysis says it does ``flops`` / ``bytes_accessed`` per
    dispatch and whose measured mean dispatch time is ``mean_step_s``.
    Clamped to [0, 1]; 0.0 whenever any input is unknown."""

    def ratio(work, peak):
        if work <= 0.0 or peak <= 0.0 or mean_step_s <= 0.0:
            return 0.0
        return min(1.0, work / (mean_step_s * peak))

    return ratio(flops, peak_flops), ratio(bytes_accessed, peak_bw)


# ------------------------------------------------------- profiler capture


class CaptureBusyError(RuntimeError):
    """A profiler capture is already running (one at a time)."""


class ProfilerCapture:
    """On-demand ``jax.profiler`` trace for the serving ``/v1/profile``
    endpoint: one capture at a time, a fresh ``capture_NNNN``
    subdirectory per capture, auto-stop after the requested duration.
    ``on_event(kind, **fields)`` (the engine flight recorder) sees
    profile_start / profile_stop."""

    def __init__(self, base_dir: str, on_event: Optional[Callable[..., None]] = None):
        self.base_dir = base_dir
        self._on_event = on_event
        self._lock = threading.Lock()
        self._active: Optional[str] = None
        self._timer: Optional[threading.Timer] = None
        self._seq = itertools.count(1)

    @property
    def active(self) -> Optional[str]:
        return self._active

    def start(self, duration_s: float) -> str:
        """Begin a capture; returns its directory. Raises
        ``CaptureBusyError`` if one is already running."""
        if duration_s <= 0:
            raise ValueError(f"duration_s must be positive, got {duration_s}")
        with self._lock:
            if self._active is not None:
                raise CaptureBusyError(
                    f"capture already running in {self._active}"
                )
            trace_dir = os.path.join(self.base_dir, f"capture_{next(self._seq):04d}")
            os.makedirs(trace_dir, exist_ok=True)
            jax.profiler.start_trace(trace_dir)
            self._active = trace_dir
            self._timer = threading.Timer(duration_s, self.stop)
            self._timer.daemon = True
            self._timer.start()
        self._event("profile_start", dir=trace_dir, duration_s=duration_s)
        return trace_dir

    def stop(self) -> Optional[str]:
        """Stop the running capture (idempotent); returns its directory."""
        with self._lock:
            if self._active is None:
                return None
            trace_dir = self._active
            if self._timer is not None:
                self._timer.cancel()
                self._timer = None
            try:
                jax.profiler.stop_trace()
            except Exception:
                pass  # already stopped underneath us; the dir still counts
            # the event must land before `active` reads None: pollers treat
            # active=None as "capture fully finished" (on_event only appends
            # to a recorder deque, so holding the lock here is safe)
            self._event("profile_stop", dir=trace_dir)
            self._active = None
        return trace_dir

    def _event(self, kind: str, **fields) -> None:
        if self._on_event is not None:
            try:
                self._on_event(kind, **fields)
            except Exception:
                pass


def annotate(name: str):
    """``jax.profiler.TraceAnnotation`` span (nullcontext when the
    profiler lacks it) — wraps tick phases so captures line up with the
    request timeline."""
    try:
        return jax.profiler.TraceAnnotation(name)
    except Exception:
        return contextlib.nullcontext()
