"""Request-lifecycle tracing, mergeable latency histograms, and the crash
flight recorder.

Serving telemetry has to be cheap enough to leave on: every primitive here
is designed around the engine's single scheduler thread being the hot
writer and HTTP handler threads being occasional readers.

- ``Histogram``: fixed-bucket counts with a per-instance lock. One
  ``observe()`` is a ``bisect`` plus three integer adds — nanoseconds next
  to a decode step. Fixed bounds make histograms *mergeable* across
  engine restarts and (later) across fleet replicas: same bounds, add the
  counts. Percentiles interpolate inside the winning bucket, which is as
  good as latency percentiles ever honestly get.
- ``RequestTrace``: an append-only list of ``(span, monotonic_t)`` marks.
  Appends are GIL-atomic, so the scheduler thread never takes a lock to
  mark a span; readers only look after the request settled.
- ``TraceJsonlWriter``: terminal-settle export of completed traces, one
  JSON object per line.
- ``FlightRecorder``: a bounded ``deque`` of recent engine events. The
  supervisor dumps it to a JSON artifact on crash/circuit-open so a
  post-mortem is a file, not log archaeology.
"""

from __future__ import annotations

import json
import math
import os
import threading
import time
import uuid
from bisect import bisect_left
from collections import deque
from typing import Any, Dict, List, Optional, Sequence, Tuple


class Histogram:
    """Thread-safe fixed-bucket histogram with mergeable counts.

    ``bounds`` are the inclusive upper edges of the finite buckets; one
    implicit overflow bucket catches everything above ``bounds[-1]``.
    An observation lands in the first bucket whose upper edge is >= value
    (Prometheus ``le`` semantics).
    """

    __slots__ = ("bounds", "counts", "total", "sum", "_lock")

    def __init__(self, bounds: Sequence[float]):
        if not bounds or list(bounds) != sorted(bounds):
            raise ValueError("histogram bounds must be non-empty and ascending")
        self.bounds: Tuple[float, ...] = tuple(float(b) for b in bounds)
        self.counts: List[int] = [0] * (len(self.bounds) + 1)
        self.total = 0
        self.sum = 0.0
        self._lock = threading.Lock()

    # ------------------------------------------------------------- factories

    @classmethod
    def exponential(
        cls, lo: float = 1e-4, hi: float = 400.0, factor: float = 2.0
    ) -> "Histogram":
        """Log-spaced bounds from ``lo`` doubling up past ``hi`` — the
        default latency shape: 0.1 ms resolution at the bottom, ~7 min at
        the top, 22 buckets."""
        bounds = []
        b = float(lo)
        while b <= hi:
            bounds.append(b)
            b *= factor
        return cls(bounds)

    @classmethod
    def linear(cls, lo: float = 0.0, hi: float = 16.0, step: float = 1.0) -> "Histogram":
        """Evenly spaced bounds — for small-integer quantities like
        speculation accepted-run lengths."""
        n = int(round((hi - lo) / step))
        return cls([lo + i * step for i in range(n + 1)])

    # ------------------------------------------------------------ hot path

    def observe(self, value: float) -> None:
        i = bisect_left(self.bounds, value)
        with self._lock:
            self.counts[i] += 1
            self.total += 1
            self.sum += value

    # ------------------------------------------------------------- readers

    def merge(self, other: "Histogram") -> None:
        """Fold another histogram's counts into this one (same bounds)."""
        if other.bounds != self.bounds:
            raise ValueError("cannot merge histograms with different bounds")
        with other._lock:
            counts, total, s = list(other.counts), other.total, other.sum
        with self._lock:
            for i, c in enumerate(counts):
                self.counts[i] += c
            self.total += total
            self.sum += s

    def _state(self) -> Tuple[List[int], int, float]:
        with self._lock:
            return list(self.counts), self.total, self.sum

    def percentile(self, q: float) -> float:
        """Approximate q-th percentile (q in [0, 100]) by linear
        interpolation inside the winning bucket. The overflow bucket
        reports the last finite bound (a floor, honestly labeled)."""
        counts, total, _ = self._state()
        if total == 0:
            return 0.0
        rank = q / 100.0 * total
        seen = 0
        for i, c in enumerate(counts):
            if c == 0:
                continue
            if seen + c >= rank:
                if i >= len(self.bounds):
                    return self.bounds[-1]
                lo = self.bounds[i - 1] if i > 0 else 0.0
                hi = self.bounds[i]
                frac = (rank - seen) / c if c else 1.0
                return lo + (hi - lo) * min(max(frac, 0.0), 1.0)
            seen += c
        return self.bounds[-1]

    def summary(self) -> Dict[str, float]:
        counts, total, s = self._state()
        if total == 0:
            return {"count": 0, "mean": 0.0, "p50": 0.0, "p90": 0.0, "p99": 0.0}
        return {
            "count": total,
            "mean": s / total,
            "p50": self.percentile(50),
            "p90": self.percentile(90),
            "p99": self.percentile(99),
        }

    def prometheus_lines(
        self, name: str, labels: str = "", include_type: bool = True
    ) -> List[str]:
        """Prometheus text exposition: cumulative ``_bucket{le=...}`` lines
        plus ``_sum`` and ``_count``. ``labels`` (e.g. ``replica="0"``)
        joins each sample's label set; pass ``include_type=False`` for
        additional labelled series of a metric whose ``# TYPE`` line was
        already emitted (one TYPE per metric name, samples grouped under
        it — the fleet's per-replica view)."""
        counts, total, s = self._state()
        pre = f"{labels}," if labels else ""
        sfx = f"{{{labels}}}" if labels else ""
        lines = [f"# TYPE {name} histogram"] if include_type else []
        cum = 0
        for bound, c in zip(self.bounds, counts):
            cum += c
            lines.append(f'{name}_bucket{{{pre}le="{_fmt(bound)}"}} {cum}')
        lines.append(f'{name}_bucket{{{pre}le="+Inf"}} {total}')
        lines.append(f"{name}_sum{sfx} {_fmt(s)}")
        lines.append(f"{name}_count{sfx} {total}")
        return lines


def _fmt(v: float) -> str:
    if v != v or v in (math.inf, -math.inf):
        return "+Inf" if v > 0 else "-Inf"
    out = f"{v:.10g}"
    return out


class RequestTrace:
    """Per-request lifecycle timeline: ordered ``(span, monotonic_t)`` marks.

    Created at submit, marked from whichever thread owns the request at
    that moment (submit thread for received/queued, scheduler thread for
    everything else). ``list.append`` of a ready tuple is GIL-atomic, so
    the hot path takes no lock; ``to_dict`` is only called after the
    request settled (or by the owner of the request record).

    ``trace_id`` is the PROPAGATED identity: the fleet front door mints
    one trace, stamps its routing decision and failover hops into it, and
    every engine hop adopts the same object — so a request that reroutes
    or resettles shows all hops under one id in one JSONL record.
    """

    __slots__ = ("request_id", "trace_id", "t0", "events")

    def __init__(
        self,
        request_id: int = 0,
        t0: Optional[float] = None,
        trace_id: Optional[str] = None,
    ):
        self.request_id = request_id
        self.trace_id = trace_id or uuid.uuid4().hex[:16]
        self.t0 = time.monotonic() if t0 is None else t0
        self.events: List[Tuple[str, float]] = []

    def mark(self, span: str, t: Optional[float] = None) -> None:
        self.events.append((span, time.monotonic() if t is None else t))

    def to_dict(self) -> Dict[str, Any]:
        events = list(self.events)
        out = {
            "request_id": self.request_id,
            "trace_id": self.trace_id,
            "events": [
                {"span": span, "t_s": round(t - self.t0, 6)} for span, t in events
            ],
        }
        if events:
            out["total_s"] = round(events[-1][1] - self.t0, 6)
        return out


class TraceJsonlWriter:
    """Appends one JSON line per settled request to ``path``.

    Writes happen on the engine scheduler thread at terminal settle; the
    lock only matters for the window-engine case where settles can race a
    drain, and it is uncontended in steady state.

    ``max_bytes`` > 0 bounds the file: when the next line would push the
    active file past the limit it is rotated to ``path.1`` (existing
    ``path.N`` shift to ``path.N+1``) and only the newest ``keep``
    rotated files survive — a long-lived server cannot fill its disk
    with traces. Size is tracked in-process (one ``tell()`` at open), so
    the hot path never stats the file.
    """

    def __init__(self, path: str, max_bytes: int = 0, keep: int = 5):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._path = path
        self._max_bytes = max(0, int(max_bytes))
        self._keep = max(1, int(keep))
        self._f = open(path, "a")
        self._size = self._f.tell()
        self._lock = threading.Lock()

    def write(self, record: Dict[str, Any]) -> None:
        line = json.dumps(record) + "\n"
        with self._lock:
            if (
                self._max_bytes
                and self._size > 0
                and self._size + len(line) > self._max_bytes
            ):
                self._rotate()
            self._f.write(line)
            self._f.flush()
            self._size += len(line)

    def _rotate(self) -> None:
        """Shift ``path.N`` -> ``path.N+1`` (dropping past ``keep``),
        move the active file to ``path.1``, reopen fresh. Lock held by
        the caller."""
        self._f.close()
        for i in range(self._keep, 0, -1):
            src = f"{self._path}.{i}"
            if not os.path.exists(src):
                continue
            if i >= self._keep:
                os.remove(src)
            else:
                os.replace(src, f"{self._path}.{i + 1}")
        os.replace(self._path, f"{self._path}.1")
        self._f = open(self._path, "a")
        self._size = 0

    def close(self) -> None:
        with self._lock:
            self._f.close()


class FlightRecorder:
    """Bounded ring buffer of recent engine events.

    The engine records admissions, sheds, per-tick summaries, speculation
    acceptance, prefix evictions, drains, crashes, restarts, and circuit
    transitions here; ``EngineSupervisor.dump_flight`` serializes the ring
    to a JSON artifact when the worker crashes or the circuit opens. The
    ``deque(maxlen=...)`` bound means steady-state cost is O(1) per event
    and memory never grows.
    """

    def __init__(self, capacity: int = 1024):
        self._events: "deque[Dict[str, Any]]" = deque(maxlen=max(1, int(capacity)))
        self._lock = threading.Lock()
        self._t0 = time.monotonic()

    def record(self, kind: str, **fields: Any) -> None:
        event = {"t_s": round(time.monotonic() - self._t0, 6), "kind": kind}
        event.update(fields)
        with self._lock:
            self._events.append(event)

    def events(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._events)

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)
