"""Mesh-scaling evidence: abstract compiles of the sharded train step, their
collective volumes, and an ICI/DCN cost model projecting multi-chip throughput.

Real multi-chip hardware is not available in this environment, so scaling
claims ride on *compiled-program* evidence instead of wall clocks:

1. ``abstract_train_setup`` builds the EXACT state/batch/step the trainer
   builds (same freeze split, dtypes, shardings — mirroring
   ``train/trainer.py:_prepare_state``) but from ``jax.ShapeDtypeStruct``
   leaves, so the flagship-at-16-devices program can be lowered and compiled
   without materializing a single parameter;
2. ``observe/comm_accounting.py`` reads per-step collective bytes per mesh
   axis out of the optimized HLO;
3. ``project_step_time`` combines those bytes with the v5e link model and the
   MEASURED single-chip step time into a projected multi-chip step time
   (compute-communication overlap assumed only where XLA can actually overlap
   — see the function docstring).

``tests/test_comm_accounting.py`` pins (1)+(2) against analytic expectations;
``benchmarks/project_scaling.py`` renders (3) into BASELINE.md's
"projected v5e-16 scaling" section.

Hardware constants (stated assumptions, public v5e specs / scaling-book):

- ICI: each v5e chip has 4 links x 45 GB/s one-way. A 16-chip slice is a
  4x4 2D torus: a 1-D ring along one mesh axis uses 2 links (both
  directions) => ~90 GB/s per chip of ring bandwidth per torus dimension;
  two mesh axes can ride the two torus dimensions concurrently.
- HBM: 819 GB/s, 16 GiB per chip.  MXU: 197 bf16 TFLOP/s.
- DCN (multi-slice): ~25 GB/s per host egress (4 chips/host on v5e) =>
  ~6.25 GB/s per chip — two orders below ICI, which is why only the pure
  data axis may span slices (``runtime/mesh.py``).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial
from typing import Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

V5E = {
    "ici_ring_gbps": 90e9,     # bytes/s per chip per torus dim (bidi ring)
    "dcn_gbps": 6.25e9,        # bytes/s per chip across slices
    "hbm_gbps": 819e9,
    "bf16_flops": 197e12,
    "hbm_bytes": 16 * 2**30,
}


def _bytes(tree) -> int:
    return sum(
        int(np.prod(l.shape)) * l.dtype.itemsize for l in jax.tree.leaves(tree)
    )


@dataclass
class AbstractSetup:
    """Everything needed to lower/compile one sharded train step abstractly."""

    mesh: object
    step: object                    # jitted step fn (donates state)
    state: object                   # TrainState of ShapeDtypeStructs
    batch: Dict[str, object]        # abstract batch [accum, B, seq]
    model_config: object
    train_config: object
    trainable_bytes: int = 0
    frozen_bytes: int = 0

    def lower(self):
        return self.step.lower(self.state, self.batch)

    def compile(self):
        return self.lower().compile()

    def comm_report(self):
        from llm_fine_tune_distributed_tpu.observe.comm_accounting import (
            account_compiled,
        )

        return account_compiled(self.compile(), self.mesh)


def abstract_train_setup(
    mesh_shape: Dict[str, int],
    preset: str = "tiny",
    *,
    devices: Optional[Sequence] = None,
    accum: int = 2,
    seq: int = 64,
    per_dp_batch: int = 1,
    param_dtype: str = "float32",
    train_kwargs: Optional[dict] = None,
) -> AbstractSetup:
    """Build the trainer's sharded train step over ``mesh_shape`` with
    abstract (ShapeDtypeStruct) state — no parameter materialization, so the
    3B flagship compiles on CPU in seconds.

    Mirrors ``train/trainer.py:_prepare_state`` leaf-for-leaf: same freeze
    split, same master dtypes (trainable = ``param_dtype``, frozen =
    compute dtype), same path-rule shardings, same optimizer-state sharding
    propagation (via AOT ``output_shardings`` of ``optimizer.init``), and the
    pipe-mode stacked-layer representation when ``pipe > 1``.
    """
    from llm_fine_tune_distributed_tpu.config import (
        MeshConfig,
        TrainConfig,
        str_to_dtype,
    )
    from llm_fine_tune_distributed_tpu.models.configs import get_preset
    from llm_fine_tune_distributed_tpu.models.transformer import init_params
    from llm_fine_tune_distributed_tpu.parallel.freeze import trainable_mask
    from llm_fine_tune_distributed_tpu.parallel.optimizer import build_optimizer
    from llm_fine_tune_distributed_tpu.parallel.sharding import (
        _validate_spec,
        param_spec,
    )
    from llm_fine_tune_distributed_tpu.runtime.mesh import (
        data_parallel_size,
        make_mesh,
    )
    from llm_fine_tune_distributed_tpu.train.state import TrainState
    from llm_fine_tune_distributed_tpu.train.step import (
        build_train_step,
        jit_train_step,
    )
    from llm_fine_tune_distributed_tpu.utils.tree import split_by_mask

    mc = get_preset(preset)
    kwargs = dict(
        model_preset=preset,
        per_device_batch_size=per_dp_batch,
        gradient_accumulation_steps=accum,
        max_seq_length=seq,
        gradient_checkpointing=True,
        param_dtype=param_dtype,
    )
    kwargs.update(train_kwargs or {})
    tc = TrainConfig(**kwargs)

    mesh = make_mesh(MeshConfig(**mesh_shape), devices)
    dp = data_parallel_size(mesh)
    pipe = mesh.shape.get("pipe", 1)

    p_dtype = str_to_dtype(tc.param_dtype)
    c_dtype = str_to_dtype(tc.compute_dtype)

    shapes = jax.eval_shape(
        partial(init_params, config=mc, dtype=jnp.float32), jax.random.PRNGKey(0)
    )
    mask = trainable_mask(shapes, mc, tc)
    trainable, frozen = split_by_mask(shapes, mask)

    layer_vec = None
    if pipe > 1:
        from llm_fine_tune_distributed_tpu.parallel.pipeline import (
            build_pipeline_state_leaves,
            layer_trainable_vector,
        )
        from llm_fine_tune_distributed_tpu.utils.tree import flatten_dict

        flat_mask = flatten_dict(mask)
        # stacking is a jnp op: run it under eval_shape to stay abstract;
        # the (tiny, concrete) layer mask is rebuilt directly from the policy
        trainable, frozen, _ = jax.eval_shape(
            partial(
                build_pipeline_state_leaves,
                flat_mask=flat_mask,
                num_layers=mc.num_layers,
            ),
            trainable,
            frozen,
        )
        layer_vec = layer_trainable_vector(flat_mask, mc.num_layers)

    def spec_for(k: str, v) -> P:
        if pipe > 1:
            from llm_fine_tune_distributed_tpu.parallel.pipeline import (
                pipeline_param_spec,
            )

            return _validate_spec(pipeline_param_spec(k, v, mesh), v.shape, mesh)
        return _validate_spec(param_spec(k, v.ndim), v.shape, mesh)

    def abstract(flat, dtype_fn):
        return {
            k: jax.ShapeDtypeStruct(
                v.shape, dtype_fn(k, v), sharding=NamedSharding(mesh, spec_for(k, v))
            )
            for k, v in flat.items()
        }

    trainable = abstract(trainable, lambda k, v: p_dtype)
    frozen = abstract(
        frozen,
        lambda k, v: c_dtype
        if jnp.issubdtype(v.dtype, jnp.floating) and "absmax" not in k
        else v.dtype,
    )

    optimizer = build_optimizer(tc, None, total_steps=4, data_parallel_size=dp)
    init_compiled = jax.jit(optimizer.init).lower(trainable).compile()
    opt_shardings = init_compiled.output_shardings
    opt_shapes = jax.eval_shape(optimizer.init, trainable)
    full_set = set(np.asarray(mesh.devices).flat)

    def opt_leaf(struct, sh):
        if getattr(sh, "device_set", None) and set(sh.device_set) == full_set:
            return jax.ShapeDtypeStruct(struct.shape, struct.dtype, sharding=sh)
        return jax.ShapeDtypeStruct(
            struct.shape, struct.dtype, sharding=NamedSharding(mesh, P())
        )

    opt_state = jax.tree.map(opt_leaf, opt_shapes, opt_shardings)

    state = TrainState(
        step=jax.ShapeDtypeStruct((), jnp.int32, sharding=NamedSharding(mesh, P())),
        trainable=trainable,
        frozen=frozen,
        opt_state=opt_state,
    )

    seq_sharded = tc.attention_impl in ("ring", "ulysses") and mesh.shape["seq"] > 1
    seq_ax = "seq" if seq_sharded else None
    batch_sh = NamedSharding(mesh, P(None, ("data", "fsdp"), seq_ax))
    B = per_dp_batch * dp
    batch = {
        "input_ids": jax.ShapeDtypeStruct((accum, B, seq), jnp.int32, sharding=batch_sh),
        "loss_mask": jax.ShapeDtypeStruct((accum, B, seq), jnp.float32, sharding=batch_sh),
        "attention_mask": jax.ShapeDtypeStruct((accum, B, seq), jnp.int32, sharding=batch_sh),
    }

    if pipe > 1:
        from llm_fine_tune_distributed_tpu.parallel.pipeline import (
            build_pipeline_train_step,
        )

        step = jit_train_step(
            build_pipeline_train_step(mc, tc, optimizer, mesh, layer_vec)
        )
    else:
        act = NamedSharding(mesh, P(("data", "fsdp"), seq_ax, None))
        step = jit_train_step(
            build_train_step(mc, tc, optimizer, activation_sharding=act)
        )

    return AbstractSetup(
        mesh=mesh,
        step=step,
        state=state,
        batch=batch,
        model_config=mc,
        train_config=tc,
        trainable_bytes=_bytes(trainable),
        frozen_bytes=_bytes(frozen),
    )


# ------------------------------------------------------------------ projection


@dataclass
class Projection:
    mesh_shape: Dict[str, int]
    compute_s: float            # per-step compute time (from measured 1-chip rate)
    comm_s_by_axis: Dict[Tuple[str, ...], float]
    exposed_comm_s: float       # serialized (non-overlapped) communication
    step_s: float
    samples_per_step: int

    @property
    def samples_per_sec(self) -> float:
        return self.samples_per_step / self.step_s

    @property
    def scaling_efficiency(self) -> float:
        """Achieved fraction of perfect linear scaling vs 1 chip."""
        n = int(np.prod(list(self.mesh_shape.values())))
        perfect = self.samples_per_step / self.compute_s
        return self.samples_per_sec / perfect if perfect else 0.0


def project_step_time(
    report,
    mesh_shape: Dict[str, int],
    *,
    single_chip_samples_per_sec: float,
    samples_per_step: int,
    dcn_axes: Tuple[str, ...] = (),
    overlap_fraction: float = 0.0,
    hw: Dict[str, float] = V5E,
) -> Projection:
    """Project per-step time on real hardware from accounted wire bytes.

    - compute time = samples_per_step / (single_chip_rate x n_chips): the
      per-chip compute is identical to the measured single-chip program (same
      per-device batch), so the measured rate IS the compute model;
    - each mesh-axis' wire bytes ride one torus dimension at
      ``ici_ring_gbps``; axes in ``dcn_axes`` ride DCN instead;
    - ``overlap_fraction`` of communication hides under compute
      (conservative default 0: all collective time exposed. XLA's async
      collectives + latency-hiding scheduler typically hide the FSDP
      all-gathers behind the matmuls they feed, so real steps land between
      the 0%-overlap and 100%-overlap projections).
    """
    n = int(np.prod(list(mesh_shape.values())))
    compute_s = samples_per_step / (single_chip_samples_per_sec * n)
    comm_by_axis = {}
    for axes, byts in report.wire_bytes_by_axis().items():
        bw = hw["dcn_gbps"] if any(a in dcn_axes for a in axes) else hw["ici_ring_gbps"]
        comm_by_axis[axes] = byts / bw
    # distinct mesh axes can ride distinct torus dims concurrently, but a
    # serialized sum is the honest upper bound for a compiled program whose
    # collectives are data-dependent (gather -> matmul -> reduce chains)
    exposed = sum(comm_by_axis.values()) * (1.0 - overlap_fraction)
    return Projection(
        mesh_shape=mesh_shape,
        compute_s=compute_s,
        comm_s_by_axis=comm_by_axis,
        exposed_comm_s=exposed,
        step_s=compute_s + exposed,
        samples_per_step=samples_per_step,
    )
