"""Profiling hooks: jax.profiler traces + device memory reports.

The reference's tracing story is ad-hoc VRAM prints
(``torch.cuda.memory_allocated``, reference ``training.py:107-111``) plus
cluster dashboards (SURVEY.md §5.1) — no profiler. Here profiling is
first-class: set ``TrainConfig.profile_dir`` and the trainer captures an
XProf/TensorBoard-compatible trace of a few hot-loop steps (compile excluded)
that shows MXU utilization, HBM traffic, and collective overlap per op —
the data the ≥4x perf target is tuned against.

View: ``tensorboard --logdir <profile_dir>`` (Profile tab), or
xprof. Host 0 only; tracing other hosts adds nothing for SPMD programs.
"""

from __future__ import annotations

import logging
from typing import Optional

import jax

from llm_fine_tune_distributed_tpu.runtime.distributed import is_primary_host

logger = logging.getLogger("llm_fine_tune_distributed_tpu.observe.profiler")


class StepProfiler:
    """Trace steps [start, start+count) of the training loop.

    Skips the first steps by default so compilation and warmup don't pollute
    the trace (first-step compile dominates otherwise). ``recorder`` (an
    observe/tracing.FlightRecorder) gets a ``profile_start`` /
    ``profile_stop`` event per transition, so captures appear on the same
    timeline as crashes and restarts.
    """

    def __init__(
        self,
        profile_dir: Optional[str],
        start_step: int = 3,
        num_steps: int = 3,
        recorder=None,
    ):
        self.dir = profile_dir if (profile_dir and is_primary_host()) else None
        self.start = start_step
        self.stop_at = start_step + num_steps
        self._active = False
        self._recorder = recorder

    def _record(self, kind: str, **fields) -> None:
        if self._recorder is not None:
            try:
                self._recorder.record(kind, **fields)
            except Exception:
                pass  # telemetry must never take down the train loop

    def step(self, step: int) -> None:
        """Call once per optimizer step (after the step completes)."""
        if self.dir is None:
            return
        if not self._active and step == self.start:
            jax.profiler.start_trace(self.dir)
            self._active = True
            self._record("profile_start", dir=self.dir, step=step)
        elif self._active and step >= self.stop_at:
            jax.profiler.stop_trace()
            self._active = False
            self._record("profile_stop", dir=self.dir, step=step)
            logger.info(
                "trace for steps [%d,%d) written to %s",
                self.start, self.stop_at, self.dir,
            )

    def close(self) -> None:
        if self._active:
            jax.profiler.stop_trace()
            self._active = False
            self._record("profile_stop", dir=self.dir, step=-1)


def device_memory_report() -> dict:
    """Live HBM usage of local devices — the analog of the reference's VRAM
    print (``training.py:107-111``), per chip."""
    report = {}
    for d in jax.local_devices():
        stats = getattr(d, "memory_stats", lambda: None)()
        if stats:
            report[str(d.id)] = {
                "bytes_in_use": stats.get("bytes_in_use"),
                "peak_bytes_in_use": stats.get("peak_bytes_in_use"),
                "bytes_limit": stats.get("bytes_limit"),
            }
    return report
