"""Analytic matmul-FLOP model of the train step, split by phase.

``cost_analysis()`` (observe/xla.CompileLedger) gives the compiled
program's TOTAL FLOPs — useful for MFU, useless for attribution: it
cannot say which FLOPs belong to the frozen trunk (forward-only under
``frozen_compute``), the trainable tail (forward + backward + remat
recompute), or the loss head. This module is the attribution side:
closed-form per-token matmul FLOPs per phase from the model config, the
trunk boundary, and the remat setting. bench.py and
benchmarks/perf_ledger.py report the phase shares next to the measured
numbers so a throughput regression can be localized before profiling.

Conventions (the standard 2*params accounting, same as bench.py's
baseline derivation):

- a ``[in, out]`` matmul costs ``2*in*out`` FLOPs per token, forward;
- backward costs 2x forward (the dx and dW products each match the
  forward GEMM);
- remat adds one extra forward per backward for the rematerialized
  region (policy ``dots_no_batch`` saves matmul outputs, so the re-run
  is mostly non-matmul — counting a full extra forward is the
  conservative upper bound BASELINE.md also uses);
- attention scores/values cost ``4*seq*heads*head_dim`` per token
  (QK^T + AV, un-causal — the flash kernel's causal skip would halve
  it; kept whole so the model stays an upper bound);
- norms / RoPE / softmax / elementwise are excluded: this is a MATMUL
  FLOP model (they are the "non-matmul tax" the measured ledger covers).

The phase split assumes the ``last_n_and_head`` freeze layout that the
fast path targets: layers below the boundary do forward only (backward
is DCE'd past the ``stop_gradient``); layers at/above it do forward +
full backward. ``frozen_layers=0`` degenerates to every layer paying
full backward — correct for full fine-tuning, an upper bound for
lora/qlora (adapter dW is rank-r, counted at full rank here).
"""

from __future__ import annotations

from typing import Dict

from llm_fine_tune_distributed_tpu.config import ModelConfig

__all__ = ["layer_matmul_flops_per_token", "train_step_flop_split"]


def layer_matmul_flops_per_token(mc: ModelConfig, seq_len: int) -> float:
    """Forward matmul FLOPs per token for ONE transformer layer: the seven
    projections (q/k/v/o, gate/up/down — MoE counts the router plus the
    per-token active experts) plus the attention score/value products at
    ``seq_len``."""
    h = mc.hidden_size
    d = mc.head_dim or h // mc.num_heads
    q_dim = mc.num_heads * d
    kv_dim = mc.num_kv_heads * d
    attn_proj = h * q_dim + 2 * h * kv_dim + q_dim * h  # q, k, v, o
    if mc.num_experts:
        mlp = h * mc.num_experts  # router gate
        mlp += mc.num_experts_per_tok * 3 * h * mc.intermediate_size
    else:
        mlp = 3 * h * mc.intermediate_size  # gate, up, down
    scores = 2 * seq_len * mc.num_heads * d  # QK^T + AV, per token
    return 2.0 * (attn_proj + mlp) + 2.0 * scores


def train_step_flop_split(
    mc: ModelConfig,
    seq_len: int,
    frozen_layers: int = 0,
    remat: bool = True,
) -> Dict[str, object]:
    """Per-token matmul FLOPs of one train step, split into phases:

    - ``trunk``: layers ``[0, frozen_layers)`` — forward only (the
      boundary ``stop_gradient`` kills their backward, and remat never
      wraps them);
    - ``trainable``: the remaining layers — forward + 2x backward
      (+1 forward remat recompute when ``remat``);
    - ``loss``: the unembed projection ``[h, vocab]`` — forward + 2x
      backward (lm_head trains under every strategy this model targets).

    Returns ``{"per_token": {phase: flops}, "fractions": {phase: share},
    "total_per_token": flops}``. Multiply ``total_per_token`` by
    tokens/sec for an analytic FLOP/s to sanity-check measured MFU.
    """
    frozen_layers = max(0, min(int(frozen_layers), mc.num_layers))
    layer_fwd = layer_matmul_flops_per_token(mc, seq_len)
    bwd_mult = 3.0 + (1.0 if remat else 0.0)  # fwd + dx + dW (+ refwd)
    trunk = frozen_layers * layer_fwd
    trainable = (mc.num_layers - frozen_layers) * layer_fwd * bwd_mult
    loss = 3.0 * 2.0 * mc.hidden_size * mc.vocab_size
    total = trunk + trainable + loss
    return {
        "per_token": {"trunk": trunk, "trainable": trainable, "loss": loss},
        "fractions": {
            "trunk": trunk / total,
            "trainable": trainable / total,
            "loss": loss / total,
        },
        "total_per_token": total,
    }
