"""SLO engine: in-process metric history, per-generation latency slicing,
multi-window burn-rate objectives, and the canary verdict for deploys.

Everything here rides the serving observability the engine already pays
for — no new clocks on the token hot path:

- ``MetricRing``: a fixed-capacity time-series ring. The engine worker
  offers it the per-tick clock stamp (``_tick_done``'s ``self._now``) and
  the ring samples at its own cadence: cumulative counters/gauges from
  ``ServingStats`` plus the DELTA of each mergeable latency histogram
  since the previous sample (``observe/tracing.Histogram`` fixed buckets
  make deltas exact — subtract the cumulative counts). Windowed queries
  (``window_counters``, ``window_histogram``, ``series``) are what the
  SLO evaluation, ``GET /v1/history`` and future autoscaler signals read.
- ``GenerationSlices``: settled-request TTFT/inter-token histograms and
  completion/failure counts keyed by the ``weight_generation`` stamp
  every request already carries (infer/deploy.py) — the substrate that
  lets a deploy's tail latency be compared against the generation it
  replaced, on the same engine, under the same traffic.
- ``SloPolicy``: availability/error-rate/latency-percentile objectives
  evaluated as multi-window burn rates (SRE convention: burn =
  bad-fraction / error-budget-fraction; a breach requires EVERY window
  hot, so a blip can't page and a slow bleed can't hide).
- ``CanaryJudge``: consulted by ``HotSwapManager`` after swapping the
  FIRST replica of a fleet. It snapshots the canary's new-generation
  slice and the unswapped siblings' resident-generation slices, waits a
  confirmation window under live traffic, and verdicts the deploy on the
  per-generation deltas — blocking the roll (and rolling the canary
  back) on a regression.

Import-light by design: this module depends only on ``observe.tracing``
so ``infer/`` and ``observe/`` can both use it without cycles.
"""

from __future__ import annotations

import threading
import time
from bisect import bisect_left
from collections import deque
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

from llm_fine_tune_distributed_tpu.observe.tracing import Histogram

# Counters the ring samples from ServingStats (cumulative; windowed deltas
# are computed at query time). Kept literal so the ring works over any
# object with a ``values(names)`` -> dict method.
RING_COUNTERS = (
    "tokens_served", "requests_admitted", "requests_completed",
    "requests_failed", "requests_abandoned", "decode_steps",
    "preemptions",
    "requests_shed_overflow", "requests_shed_deadline",
    "requests_shed_deadline_decode", "requests_shed_tenant_quota",
)
# Instantaneous gauges sampled as-is (the engine passes fresh reads).
RING_GAUGES = (
    "queue_depth", "live_slots", "brownout_stage", "weight_generation",
)
# Histograms delta-decoded between samples.
RING_HISTOGRAMS = ("ttft_s", "inter_token_s")

# Shed counters that burn the availability budget: requests the service
# turned away or cancelled rather than served.
_AVAILABILITY_BAD = (
    "requests_shed_overflow", "requests_shed_deadline",
    "requests_shed_deadline_decode", "requests_shed_tenant_quota",
)


def _frac_above(
    bounds: Tuple[float, ...], counts: Sequence[int], total: int,
    threshold: float,
) -> float:
    """Fraction of observations above ``threshold`` in a fixed-bucket
    histogram state, interpolating inside the bucket the threshold lands
    in (same honesty contract as ``Histogram.percentile``)."""
    if total <= 0:
        return 0.0
    i = bisect_left(bounds, threshold)
    if i >= len(bounds):
        # threshold beyond the last finite bound: only overflow is above
        return counts[-1] / total
    below = sum(counts[:i])
    lo = bounds[i - 1] if i > 0 else 0.0
    hi = bounds[i]
    frac_in = (threshold - lo) / (hi - lo) if hi > lo else 1.0
    below += counts[i] * min(max(frac_in, 0.0), 1.0)
    return max(0.0, (total - below)) / total


class MetricRing:
    """Fixed-capacity in-process time-series of serving stats samples.

    The engine worker calls ``due(now)`` with the tick stamp it already
    took (zero extra clock reads) and, when a sample interval has
    elapsed, ``sample(now, stats, gauges)``. Each sample stores the
    cumulative counters plus the DELTA of each tracked histogram since
    the previous sample, so any trailing window's histogram is the exact
    sum of its samples' deltas — mergeable math, no decay approximations.

    Writers: the engine worker thread only. Readers: HTTP handler
    threads (``/v1/history``, ``/v1/slo``, ``/v1/stats``) and the deploy
    manager. One lock around the deque; samples are immutable once
    appended.
    """

    def __init__(self, capacity: int = 512, interval_s: float = 1.0):
        self.capacity = max(2, int(capacity))
        self.interval_s = max(1e-3, float(interval_s))
        self._samples: "deque[Dict[str, Any]]" = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self._last_t: Optional[float] = None
        # previous cumulative histogram counts, for delta decoding
        self._prev_hist: Dict[str, Tuple[List[int], int, float]] = {}

    # ------------------------------------------------------------- writer

    def due(self, now: float) -> bool:
        """Cheap per-tick check: has a sample interval elapsed? Reuses the
        caller's tick stamp — the ring never reads the clock itself."""
        return self._last_t is None or now - self._last_t >= self.interval_s

    def sample(
        self,
        now: float,
        stats,
        gauges: Optional[Dict[str, float]] = None,
    ) -> None:
        """Take one sample: cumulative counters from ``stats`` (a
        ``ServingStats``), fresh gauge reads from ``gauges``, and the
        per-histogram delta since the previous sample."""
        counters = stats.values(RING_COUNTERS)
        hist_deltas: Dict[str, Tuple[List[int], int, float]] = {}
        for name in RING_HISTOGRAMS:
            h = stats.hist.get(name)
            if h is None:
                continue
            counts, total, s = h._state()
            prev = self._prev_hist.get(name)
            if prev is None:
                delta = (list(counts), total, s)
            else:
                pcounts, ptotal, psum = prev
                delta = (
                    [c - p for c, p in zip(counts, pcounts)],
                    total - ptotal,
                    s - psum,
                )
            self._prev_hist[name] = (counts, total, s)
            hist_deltas[name] = delta
        rec: Dict[str, Any] = {
            "t": float(now),
            "counters": counters,
            "gauges": dict(gauges or {}),
            "hist": hist_deltas,
        }
        with self._lock:
            self._samples.append(rec)
        self._last_t = now

    # ------------------------------------------------------------- readers

    def __len__(self) -> int:
        with self._lock:
            return len(self._samples)

    def samples(
        self, window_s: Optional[float] = None, now: Optional[float] = None
    ) -> List[Dict[str, Any]]:
        with self._lock:
            recs = list(self._samples)
        if window_s is None or not recs:
            return recs
        t = now if now is not None else recs[-1]["t"]
        cutoff = t - float(window_s)
        return [r for r in recs if r["t"] > cutoff]

    def metrics(self) -> List[str]:
        """Metric names ``series`` can answer for."""
        return list(RING_COUNTERS) + list(RING_GAUGES)

    def window_counters(
        self, window_s: float, now: Optional[float] = None
    ) -> Dict[str, int]:
        """Counter deltas over the trailing window: newest sample minus
        the last sample at or before the window start. With no sample
        that old (engine younger than the window, or ring wrapped) the
        oldest retained sample is the baseline — the window honestly
        truncates to the history we have. Counters start at zero with
        the engine, so a missing baseline before the first sample means
        the first sample's own deltas count too."""
        with self._lock:
            recs = list(self._samples)
        if not recs:
            return {k: 0 for k in RING_COUNTERS}
        t = now if now is not None else recs[-1]["t"]
        cutoff = t - float(window_s)
        newest = recs[-1]["counters"]
        baseline: Optional[Dict[str, int]] = None
        for r in recs:
            if r["t"] <= cutoff:
                baseline = r["counters"]
            else:
                break
        if baseline is None:
            # whole retained history is inside the window; the counters
            # before the first sample are the first sample's cumulative
            # values minus its own in-window activity — unknowable here,
            # so treat engine start (zero) as the baseline when the ring
            # hasn't wrapped, else the oldest sample.
            if len(recs) == self.capacity:
                baseline = recs[0]["counters"]
            else:
                baseline = {k: 0 for k in RING_COUNTERS}
        return {
            k: max(0, int(newest.get(k, 0)) - int(baseline.get(k, 0)))
            for k in RING_COUNTERS
        }

    def window_histogram(
        self, name: str, window_s: float, now: Optional[float] = None
    ) -> Tuple[List[int], int, float]:
        """Summed histogram deltas over the trailing window:
        ``(counts, total, sum)`` with the same bucket layout as the live
        histogram. Exact — each sample's delta covers the span since the
        previous sample."""
        recs = self.samples(window_s, now)
        counts: Optional[List[int]] = None
        total = 0
        s = 0.0
        for r in recs:
            d = r["hist"].get(name)
            if d is None:
                continue
            dcounts, dtotal, dsum = d
            if counts is None:
                counts = list(dcounts)
            else:
                for i, c in enumerate(dcounts):
                    counts[i] += c
            total += dtotal
            s += dsum
        return (counts or [], total, s)

    def series(
        self,
        metric: str,
        window_s: Optional[float] = None,
        now: Optional[float] = None,
    ) -> Dict[str, Any]:
        """Time series of one counter or gauge over the trailing window
        (``GET /v1/history``). Counters come with per-sample deltas so a
        rate plot needs no client-side state. Raises ``ValueError`` for
        an unknown metric (the server's 400)."""
        is_counter = metric in RING_COUNTERS
        if not is_counter and metric not in RING_GAUGES:
            raise ValueError(
                f"unknown history metric {metric!r} "
                f"(expected one of {self.metrics()})"
            )
        recs = self.samples(window_s, now)
        t_ref = (
            now
            if now is not None
            else (recs[-1]["t"] if recs else time.monotonic())
        )
        out: List[Dict[str, float]] = []
        prev: Optional[int] = None
        for r in recs:
            src = r["counters"] if is_counter else r["gauges"]
            v = src.get(metric, 0)
            point = {"age_s": round(t_ref - r["t"], 3), "value": v}
            if is_counter:
                point["delta"] = int(v) - int(prev) if prev is not None else 0
                prev = int(v)
            out.append(point)
        return {
            "metric": metric,
            "kind": "counter" if is_counter else "gauge",
            "window_s": float(window_s) if window_s is not None else None,
            "interval_s": self.interval_s,
            "samples": out,
        }


class _Slice:
    """One weight generation's settled-request accounting. Histograms are
    internally locked; the count bumps go through the owning
    ``GenerationSlices`` lock (settles can come from submit threads)."""

    __slots__ = ("ttft", "inter_token", "completed", "failed")

    def __init__(self):
        self.ttft = Histogram.exponential()
        self.inter_token = Histogram.exponential()
        self.completed = 0
        self.failed = 0


class GenerationSlices:
    """Per-``weight_generation`` latency/error slices.

    The engine keeps a cached reference to the current generation's slice
    and observes TTFT/inter-token into it on the token hot path (reusing
    the values it already computed against the tick clock — no extra
    reads, no dict lookups per token). Settle counts key off the
    generation stamped on the request. Old generations are pruned to the
    last ``keep`` so a long-lived engine's memory stays bounded.
    """

    def __init__(self, keep: int = 8):
        self._keep = max(1, int(keep))
        self._lock = threading.Lock()
        self._slices: Dict[int, _Slice] = {}

    def slice_for(self, generation: int) -> _Slice:
        """Get-or-create the slice for one generation, pruning the oldest
        beyond ``keep`` (callers cache the return for hot-path observes)."""
        gen = int(generation)
        with self._lock:
            s = self._slices.get(gen)
            if s is None:
                s = self._slices[gen] = _Slice()
                while len(self._slices) > self._keep:
                    del self._slices[min(self._slices)]
            return s

    def note_settled(self, generation: int, failed: bool) -> None:
        gen = int(generation)
        with self._lock:
            s = self._slices.get(gen)
            if s is None:
                s = self._slices[gen] = _Slice()
                while len(self._slices) > self._keep:
                    del self._slices[min(self._slices)]
            if failed:
                s.failed += 1
            else:
                s.completed += 1

    def generations(self) -> List[int]:
        with self._lock:
            return sorted(self._slices)

    def state(
        self, generation: int
    ) -> Dict[str, Any]:
        """Cumulative slice state for baseline/delta math:
        ``{ttft: (counts,total,sum), inter_token: ..., completed, failed}``.
        Zeros for a generation with no slice yet (a fresh canary)."""
        with self._lock:
            s = self._slices.get(int(generation))
        if s is None:
            empty = Histogram.exponential()
            z = empty._state()
            return {"ttft": z, "inter_token": z, "completed": 0, "failed": 0}
        return {
            "ttft": s.ttft._state(),
            "inter_token": s.inter_token._state(),
            "completed": s.completed,
            "failed": s.failed,
        }

    @staticmethod
    def delta(
        now_state: Dict[str, Any], then_state: Dict[str, Any]
    ) -> Dict[str, Any]:
        """Per-generation activity between two ``state()`` snapshots:
        p99s over the delta histograms plus completed/failed deltas —
        the canary's confirmation-window view."""
        out: Dict[str, Any] = {}
        for name in ("ttft", "inter_token"):
            ncounts, ntotal, nsum = now_state[name]
            tcounts, ttotal, tsum = then_state[name]
            h = Histogram.exponential()
            if ncounts:
                h.counts = [
                    c - (tcounts[i] if i < len(tcounts) else 0)
                    for i, c in enumerate(ncounts)
                ]
            h.total = ntotal - ttotal
            h.sum = nsum - tsum
            out[name] = h.summary()
        out["completed"] = now_state["completed"] - then_state["completed"]
        out["failed"] = now_state["failed"] - then_state["failed"]
        done = out["completed"] + out["failed"]
        out["error_rate"] = out["failed"] / done if done else 0.0
        return out

    @staticmethod
    def merge_states(states: Iterable[Dict[str, Any]]) -> Dict[str, Any]:
        """Fold several ``state()`` snapshots (same generation, sibling
        replicas) into one — fixed bounds make the sum exact."""
        acc: Optional[Dict[str, Any]] = None
        for st in states:
            if acc is None:
                acc = {
                    "ttft": (list(st["ttft"][0]), st["ttft"][1], st["ttft"][2]),
                    "inter_token": (
                        list(st["inter_token"][0]),
                        st["inter_token"][1],
                        st["inter_token"][2],
                    ),
                    "completed": st["completed"],
                    "failed": st["failed"],
                }
                continue
            for name in ("ttft", "inter_token"):
                counts, total, s = acc[name]
                ocounts, ototal, osum = st[name]
                if not counts:
                    counts = list(ocounts)
                else:
                    for i, c in enumerate(ocounts):
                        counts[i] += c
                acc[name] = (counts, total + ototal, s + osum)
            acc["completed"] += st["completed"]
            acc["failed"] += st["failed"]
        if acc is None:
            empty = Histogram.exponential()._state()
            acc = {
                "ttft": empty, "inter_token": empty,
                "completed": 0, "failed": 0,
            }
        return acc

    def summaries(self) -> Dict[str, Dict[str, Any]]:
        """JSON-ready per-generation summaries (``/v1/stats``,
        ``/metrics`` generation-labelled series)."""
        with self._lock:
            items = sorted(self._slices.items())
        out: Dict[str, Dict[str, Any]] = {}
        for gen, s in items:
            done = s.completed + s.failed
            out[str(gen)] = {
                "completed": s.completed,
                "failed": s.failed,
                "error_rate": s.failed / done if done else 0.0,
                "ttft": s.ttft.summary(),
                "inter_token": s.inter_token.summary(),
            }
        return out

    @staticmethod
    def merged_summaries(
        many: Iterable["GenerationSlices"],
    ) -> Dict[str, Dict[str, Any]]:
        """Fleet view: per-generation summaries with replica slices
        merged (histogram counts sum, counts sum)."""
        by_gen: Dict[int, Dict[str, Any]] = {}
        for slices in many:
            for gen in slices.generations():
                st = slices.state(gen)
                if gen in by_gen:
                    by_gen[gen] = GenerationSlices.merge_states(
                        [by_gen[gen], st]
                    )
                else:
                    by_gen[gen] = st
        out: Dict[str, Dict[str, Any]] = {}
        zero = Histogram.exponential()._state()
        for gen in sorted(by_gen):
            d = GenerationSlices.delta(
                by_gen[gen],
                {"ttft": zero, "inter_token": zero, "completed": 0, "failed": 0},
            )
            out[str(gen)] = d
        return out


class SloPolicy:
    """Serving objectives evaluated as multi-window burn rates.

    Objectives (targets are the service promise; the budget is how much
    of the traffic may break it):

    - ``ttft_p99``: at most ``budget`` (default 1%) of first tokens may
      take longer than ``ttft_p99_s``.
    - ``inter_token_p99``: same over inter-token gaps.
    - ``error_rate``: failed / settled must stay under the target; the
      budget IS the target.
    - ``availability``: turned-away requests (overflow, deadline, quota
      sheds) vs. admissions must stay under ``1 - availability``.

    ``burn_rate = bad_fraction / budget`` — 1.0 means exactly eating the
    budget, sustained. A breach requires burn > ``burn_threshold`` on
    EVERY window with at least ``min_events`` in each (fast window
    catches cliffs, slow window catches bleeds, their conjunction
    suppresses blips). ``evaluate`` is pure (any thread);
    ``observe_transitions`` keeps breach state and is worker-only.
    """

    def __init__(
        self,
        ttft_p99_s: float = 2.0,
        inter_token_p99_s: float = 0.5,
        error_rate: float = 0.01,
        availability: float = 0.999,
        fast_window_s: float = 60.0,
        slow_window_s: float = 600.0,
        burn_threshold: float = 1.0,
        min_events: int = 8,
        percentile_budget: float = 0.01,
    ):
        self.ttft_p99_s = float(ttft_p99_s)
        self.inter_token_p99_s = float(inter_token_p99_s)
        self.error_rate = float(error_rate)
        self.availability = float(availability)
        self.windows = (
            ("fast", max(1e-3, float(fast_window_s))),
            ("slow", max(1e-3, float(slow_window_s))),
        )
        self.burn_threshold = float(burn_threshold)
        self.min_events = max(1, int(min_events))
        self.percentile_budget = max(1e-9, float(percentile_budget))
        self._breached: set = set()  # worker-only (observe_transitions)

    # ----------------------------------------------------------- evaluation

    def _objective_specs(self) -> List[Tuple[str, float, float]]:
        """(name, target, budget_fraction) triples."""
        return [
            ("ttft_p99", self.ttft_p99_s, self.percentile_budget),
            ("inter_token_p99", self.inter_token_p99_s, self.percentile_budget),
            ("error_rate", self.error_rate, max(self.error_rate, 1e-9)),
            (
                "availability",
                self.availability,
                max(1.0 - self.availability, 1e-9),
            ),
        ]

    def _window_view(
        self, name: str, ring: MetricRing, window_s: float,
        now: Optional[float],
    ) -> Tuple[float, int]:
        """(bad_fraction, events) of one objective over one window."""
        if name in ("ttft_p99", "inter_token_p99"):
            hname = "ttft_s" if name == "ttft_p99" else "inter_token_s"
            counts, total, _ = ring.window_histogram(hname, window_s, now)
            target = (
                self.ttft_p99_s if name == "ttft_p99"
                else self.inter_token_p99_s
            )
            if total <= 0:
                return 0.0, 0
            bounds = Histogram.exponential().bounds
            return _frac_above(bounds, counts, total, target), total
        deltas = ring.window_counters(window_s, now)
        if name == "error_rate":
            done = deltas["requests_completed"] + deltas["requests_failed"]
            return (
                deltas["requests_failed"] / done if done else 0.0,
                done,
            )
        # availability
        bad = sum(deltas[k] for k in _AVAILABILITY_BAD)
        offered = deltas["requests_admitted"] + bad
        return (bad / offered if offered else 0.0, offered)

    def evaluate(
        self, ring: MetricRing, now: Optional[float] = None
    ) -> Dict[str, Any]:
        """Burn-rate report over the ring (pure; safe from any thread)."""
        objectives: Dict[str, Any] = {}
        compliant = True
        for name, target, budget in self._objective_specs():
            windows: Dict[str, Any] = {}
            breach = True
            for label, window_s in self.windows:
                bad_frac, events = self._window_view(name, ring, window_s, now)
                burn = bad_frac / budget
                hot = events >= self.min_events and burn > self.burn_threshold
                breach = breach and hot
                windows[label] = {
                    "window_s": window_s,
                    "bad_fraction": round(bad_frac, 6),
                    "burn_rate": round(burn, 4),
                    "events": events,
                }
            objectives[name] = {
                "target": target,
                "budget": budget,
                "compliant": not breach,
                "windows": windows,
            }
            compliant = compliant and not breach
        return {
            "compliant": compliant,
            "burn_threshold": self.burn_threshold,
            "objectives": objectives,
        }

    def observe_transitions(
        self, report: Dict[str, Any]
    ) -> List[Tuple[str, Dict[str, Any]]]:
        """Edge-detect breaches against the previous report (worker
        thread only): returns ``(kind, fields)`` flight-recorder events —
        ``slo_breach`` on entering breach, ``slo_recovered`` on leaving."""
        events: List[Tuple[str, Dict[str, Any]]] = []
        for name, obj in report["objectives"].items():
            breached = not obj["compliant"]
            was = name in self._breached
            if breached and not was:
                self._breached.add(name)
                burns = {
                    label: w["burn_rate"] for label, w in obj["windows"].items()
                }
                events.append(
                    ("slo_breach", {"objective": name, "target": obj["target"],
                                    "burn_rates": burns})
                )
            elif was and not breached:
                self._breached.discard(name)
                events.append(("slo_recovered", {"objective": name}))
        return events

    @staticmethod
    def merge_reports(reports: Sequence[Dict[str, Any]]) -> Dict[str, Any]:
        """Fleet aggregation: compliant iff every replica is; per
        objective/window the max burn and summed events (the hottest
        replica is the one paging matters for)."""
        reports = [r for r in reports if r]
        if not reports:
            return {"compliant": True, "objectives": {}}
        out: Dict[str, Any] = {
            "compliant": all(r.get("compliant", True) for r in reports),
            "burn_threshold": reports[0].get("burn_threshold", 1.0),
            "objectives": {},
        }
        for name, first in reports[0]["objectives"].items():
            windows: Dict[str, Any] = {}
            for label, w in first["windows"].items():
                burns, bads, events = [], [], 0
                for r in reports:
                    rw = r["objectives"].get(name, {}).get("windows", {}).get(label)
                    if not rw:
                        continue
                    burns.append(rw["burn_rate"])
                    bads.append(rw["bad_fraction"])
                    events += rw["events"]
                windows[label] = {
                    "window_s": w["window_s"],
                    "bad_fraction": max(bads) if bads else 0.0,
                    "burn_rate": max(burns) if burns else 0.0,
                    "events": events,
                }
            out["objectives"][name] = {
                "target": first["target"],
                "budget": first["budget"],
                "compliant": all(
                    r["objectives"].get(name, {}).get("compliant", True)
                    for r in reports
                ),
                "windows": windows,
            }
        return out


class CanaryJudge:
    """Scores the first swapped replica of a fleet roll against its
    unswapped siblings before the roll continues.

    ``HotSwapManager`` calls ``judge`` right after engine 0 applies the
    new weights. The judge snapshots the canary's (empty) new-generation
    slice and each sibling's resident-generation slice, waits
    ``window_s`` while live traffic lands on both sides, then compares
    the confirmation-window DELTAS: canary p99 TTFT / inter-token vs.
    the merged sibling baseline, and the canary's error rate. Verdicts:

    - ``pass`` — canary within ratio bounds; the roll continues.
    - ``regression`` — canary p99 exceeds ``ratio * baseline_p99`` (with
      the baseline floored at ``min_baseline_s`` so microsecond noise
      can't fabricate ratios) or its error rate exceeds
      ``max_error_rate``; the manager rolls the canary back and blocks.
    - ``insufficient_traffic`` / ``insufficient_baseline`` — not enough
      settled requests on one side to judge; treated as pass-through
      (the error-rate backstop in ``HotSwapManager`` still guards).
    - ``no_siblings`` — single-replica target; nothing to compare.
    """

    def __init__(
        self,
        window_s: float = 30.0,
        min_requests: int = 8,
        poll_s: Optional[float] = None,
        ttft_ratio: float = 2.0,
        inter_token_ratio: float = 2.0,
        max_error_rate: float = 0.25,
        min_baseline_s: float = 0.005,
    ):
        self.window_s = max(0.05, float(window_s))
        self.min_requests = max(1, int(min_requests))
        self.poll_s = (
            float(poll_s) if poll_s else min(0.25, self.window_s / 4.0)
        )
        self.ttft_ratio = float(ttft_ratio)
        self.inter_token_ratio = float(inter_token_ratio)
        self.max_error_rate = float(max_error_rate)
        self.min_baseline_s = float(min_baseline_s)

    def judge(
        self, canary, siblings: Sequence[Any], generation: int
    ) -> Dict[str, Any]:
        """Blocking confirmation window (runs on the deploy manager's
        thread, never the engine worker). ``canary``/``siblings`` are
        engines exposing ``slo_slices``, ``weight_generation`` and
        ``recorder``."""
        recorder = getattr(canary, "recorder", None)
        if recorder is not None:
            recorder.record(
                "canary_begin", generation=int(generation),
                window_s=self.window_s, siblings=len(siblings),
            )
        verdict = self._judge_inner(canary, siblings, generation)
        if recorder is not None:
            fields = {
                k: v for k, v in verdict.items()
                if isinstance(v, (str, int, float, bool)) or v is None
            }
            fields.setdefault("generation", int(generation))
            recorder.record("canary_verdict", **fields)
        return verdict

    def _judge_inner(
        self, canary, siblings: Sequence[Any], generation: int
    ) -> Dict[str, Any]:
        siblings = [s for s in siblings if s is not canary]
        if not siblings:
            return {"verdict": "no_siblings", "reason": "single replica"}
        canary_then = canary.slo_slices.state(generation)
        sibling_then = [
            (sib, int(sib.weight_generation),
             sib.slo_slices.state(int(sib.weight_generation)))
            for sib in siblings
        ]
        deadline = time.monotonic() + self.window_s
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            time.sleep(min(self.poll_s, remaining))
        canary_delta = GenerationSlices.delta(
            canary.slo_slices.state(generation), canary_then
        )
        sib_now = GenerationSlices.merge_states(
            sib.slo_slices.state(gen) for sib, gen, _ in sibling_then
        )
        sib_then = GenerationSlices.merge_states(
            then for _, _, then in sibling_then
        )
        baseline = GenerationSlices.delta(sib_now, sib_then)
        result: Dict[str, Any] = {
            "generation": int(generation),
            "window_s": self.window_s,
            "canary_requests": canary_delta["completed"] + canary_delta["failed"],
            "baseline_requests": baseline["completed"] + baseline["failed"],
            "canary": canary_delta,
            "baseline": baseline,
        }
        if result["canary_requests"] < self.min_requests:
            result.update(
                verdict="insufficient_traffic",
                reason=(
                    f"canary settled {result['canary_requests']} < "
                    f"{self.min_requests} requests in {self.window_s}s"
                ),
            )
            return result
        if canary_delta["error_rate"] > self.max_error_rate:
            result.update(
                verdict="regression",
                reason=(
                    f"canary error rate {canary_delta['error_rate']:.3f} > "
                    f"{self.max_error_rate}"
                ),
            )
            return result
        if result["baseline_requests"] < self.min_requests:
            result.update(
                verdict="insufficient_baseline",
                reason=(
                    f"siblings settled {result['baseline_requests']} < "
                    f"{self.min_requests} requests in {self.window_s}s"
                ),
            )
            return result
        for name, ratio in (
            ("ttft", self.ttft_ratio), ("inter_token", self.inter_token_ratio)
        ):
            base_p99 = max(baseline[name]["p99"], self.min_baseline_s)
            if canary_delta[name]["count"] and (
                canary_delta[name]["p99"] > ratio * base_p99
            ):
                result.update(
                    verdict="regression",
                    reason=(
                        f"canary {name} p99 "
                        f"{canary_delta[name]['p99'] * 1000:.1f}ms > "
                        f"{ratio}x sibling baseline "
                        f"{base_p99 * 1000:.1f}ms"
                    ),
                )
                return result
        result.update(verdict="pass", reason="within ratio bounds")
        return result
