"""Package console entry points (``smollm3-train`` / ``smollm3-ask`` /
``smollm3-serve``, pyproject.toml [project.scripts]).

``train_main`` is the distributed SFT/DPO entry — the TPU-native equivalent
of the reference's ``training.py`` (same env-var contract: EPOCHS,
BATCH_SIZE, LEARNING_RATE, DATA_DIR, OUTPUT_DIR, AIM_REPO,
WORLD_SIZE/RANK/MASTER_ADDR/MASTER_PORT; reference ``training.py:19-23,54-60``),
with mesh shape via MESH_DATA/MESH_FSDP/MESH_TENSOR/MESH_SEQ/MESH_EXPERT.
The repo-root ``training.py`` / ``ask_tuned_model.py`` scripts are thin shims
over these functions, so ``python training.py`` and ``smollm3-train`` are the
same program.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import Optional


def train_main(argv: Optional[list] = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--config", help="JSON/YAML TrainConfig file")
    parser.add_argument("--model-preset", help="model preset override")
    parser.add_argument(
        "--resume", nargs="?", const="latest", default=None,
        help="resume from checkpoint ('latest' or a step number)",
    )
    parser.add_argument(
        "--platform", default=None,
        help="force a jax platform (e.g. 'cpu' for simulation runs; overrides "
             "any sitecustomize/env pinning)",
    )
    parser.add_argument(
        "--virtual-devices", type=int, default=None,
        help="with --platform cpu: number of virtual host devices "
             "(XLA_FLAGS --xla_force_host_platform_device_count)",
    )
    parser.add_argument(
        "--train-port", type=int, default=None,
        help="serve the training control plane (/metrics, /v1/train/status, "
             "/v1/train/flight, POST /v1/train/profile) on this port from "
             "the primary host (0 = ephemeral)",
    )
    parser.add_argument(
        "--publish-require-clean", action="store_true", default=None,
        help="skip publishing checkpoints whose trailing anomaly window is "
             "dirty instead of stamping anomaly_clean=false",
    )
    args = parser.parse_args(argv)

    if args.virtual_devices:
        import re

        flags = re.sub(
            r"--xla_force_host_platform_device_count=\d+", "",
            os.environ.get("XLA_FLAGS", ""),
        ).strip()
        # CPU-backend workaround (see tests/conftest.py): AllReducePromotion
        # check-fails on bf16 expert-axis all-reduces from pipe x EP backward
        if "xla_disable_hlo_passes" not in flags:
            flags = f"{flags} --xla_disable_hlo_passes=all-reduce-promotion".strip()
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count={args.virtual_devices}"
        ).strip()
    if args.platform:
        import jax

        # config.update (not the env var) wins even when a sitecustomize
        # registered a hardware plugin at interpreter startup
        jax.config.update("jax_platforms", args.platform)

    # Multi-host bootstrap MUST run before any jax backend use
    # (reference analog: setup_distributed, training.py:16-42).
    from llm_fine_tune_distributed_tpu.runtime.distributed import (
        initialize_distributed,
        is_primary_host,
    )

    info = initialize_distributed()

    from llm_fine_tune_distributed_tpu.config import MeshConfig, TrainConfig

    config = TrainConfig.load(args.config) if args.config else TrainConfig()
    config.apply_env_overrides()
    if args.model_preset:
        config.model_preset = args.model_preset
    if args.resume is not None:
        config.resume_from_checkpoint = args.resume
    if args.train_port is not None:
        config.train_port = args.train_port
    if args.publish_require_clean:
        config.publish_require_clean = True
    mesh_env = {
        k: os.environ.get(f"MESH_{k.upper()}")
        for k in ("data", "fsdp", "tensor", "seq", "expert", "pipe")
    }
    if any(v is not None for v in mesh_env.values()):
        config.mesh = MeshConfig(
            **{k: int(v) for k, v in mesh_env.items() if v is not None}
        )

    if is_primary_host():
        print("=" * 60)
        print("TPU-native distributed SFT")
        print(f"  process {info.process_index}/{info.process_count}, "
              f"{info.global_device_count} devices ({info.platform})")
        print(f"  epochs={config.epochs} batch={config.per_device_batch_size} "
              f"lr={config.learning_rate} accum={config.gradient_accumulation_steps}")
        print(f"  data={config.data_dir} output={config.output_dir}")
        print("=" * 60)

    if config.objective not in ("sft", "dpo"):
        raise SystemExit(
            f"unknown OBJECTIVE {config.objective!r}; expected 'sft' or 'dpo'"
        )
    if config.objective == "dpo":
        # preference-pair path (OBJECTIVE=dpo): BASELINE.json config #4
        from llm_fine_tune_distributed_tpu.train.dpo import DPOTrainer as Trainer
    else:
        from llm_fine_tune_distributed_tpu.train.trainer import SFTTrainer as Trainer

    trainer = Trainer(config)
    summary = trainer.train()

    if is_primary_host():
        print("\nDistributed Q&A fine-tuning completed successfully!")
        print(f"Training artifacts saved to {config.output_dir}/")
        steady = summary.get("samples_per_second_per_chip_steady")
        print(f"samples/sec/chip: {summary.get('samples_per_second_per_chip')}"
              + (f" (steady-state: {steady})" if steady else ""))
    return 0


def ask_main(argv: Optional[list] = None) -> int:
    """Ask the fine-tuned model a question (reference ``ask_tuned_model.py``)."""
    from llm_fine_tune_distributed_tpu.infer.cli import run_ask_cli

    return run_ask_cli(
        argv,
        description=ask_main.__doc__,
        default_model_dir="outputs/best_model",
        model_dir_env="MODEL_DIR",
        missing_dir_help="Run training first (smollm3-train) or pass --model-dir.",
    )


def serve_main(argv: Optional[list] = None) -> int:
    """Serve the tuned model over HTTP (infer/server.py)."""
    from llm_fine_tune_distributed_tpu.infer.server import main

    return main(argv)


if __name__ == "__main__":
    sys.exit(train_main())
