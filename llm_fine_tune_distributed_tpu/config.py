"""Configuration system.

The reference configures training purely through environment variables
(``EPOCHS, BATCH_SIZE, LEARNING_RATE, DATA_DIR, OUTPUT_DIR`` — reference
``training.py:54-60``) with the model name, dataset path, grad-accum, seq-len,
eval cadence and freezing policy hard-coded. Here every knob is a dataclass
field, loadable from JSON/YAML, and every reference env var still works as an
override so the deployment-manifest contract (``deploy/pytorchjob.yaml:30-66``)
is preserved.
"""

from __future__ import annotations

import dataclasses
import json
import os
from dataclasses import dataclass, field
from typing import Any, Optional, Sequence


@dataclass(frozen=True)
class ModelConfig:
    """Architecture of a dense decoder-only transformer.

    One config class covers the Llama family: Llama-3, Mistral (sliding
    window), Qwen-style (qkv bias), and SmolLM3 (NoPE-interleaved RoPE:
    ``no_rope_layers[i] == 0`` means layer *i* applies no rotary embedding —
    mirrors HF ``SmolLM3Config.no_rope_layers``).
    """

    name: str = "unnamed"
    vocab_size: int = 128256
    hidden_size: int = 2048
    intermediate_size: int = 11008
    num_layers: int = 36
    num_heads: int = 16
    num_kv_heads: int = 4
    head_dim: Optional[int] = None  # defaults to hidden_size // num_heads
    rope_theta: float = 2_000_000.0
    max_position_embeddings: int = 32768
    rms_norm_eps: float = 1e-6
    tie_word_embeddings: bool = True
    attention_bias: bool = False
    # Qwen2-style: bias on q/k/v but NOT o_proj (HF Qwen2Attention). Only
    # consulted when attention_bias is True; Llama-style configs keep True.
    attention_out_bias: bool = True
    # Qwen3-style per-head RMSNorm on q and k (over head_dim, applied after
    # the projections, before RoPE — HF Qwen3Attention q_norm/k_norm).
    qk_norm: bool = False
    # --- Gemma2-style architecture knobs (HF Gemma2Config) ---
    # Gate activation: "silu" (Llama SwiGLU), "gelu_tanh" (Gemma GeGLU /
    # gelu_pytorch_tanh), or "gelu" (exact erf). MoE models support "silu"
    # only (enforced in __post_init__; ops/moe.py hardcodes the expert MLP).
    hidden_act: str = "silu"
    # Four norms per layer: post-attention and post-feedforward OUTPUT norms
    # in addition to the two pre-norms (HF Gemma2DecoderLayer ordering)
    sandwich_norms: bool = False
    # RMSNorm weight stored zero-centered: out = normed * (1 + w), w init 0
    zero_centered_norm: bool = False
    # Multiply embedding output by sqrt(hidden_size) (Gemma normalizer)
    embed_scale: bool = False
    # Soft caps: score -> cap * tanh(score / cap)
    attn_logit_softcap: Optional[float] = None
    final_logit_softcap: Optional[float] = None
    # Attention scale = query_pre_attn_scalar**-0.5 instead of head_dim**-0.5
    query_pre_attn_scalar: Optional[float] = None
    # Sliding window only on even layers (Gemma2's local/global alternation);
    # False = the window (if any) applies to every layer (Mistral)
    alternating_sliding_window: bool = False
    # RoPE context extension (HF config.rope_scaling). None = plain RoPE;
    # "llama3" = Llama-3.1 smoothed NTK; "linear" = position interpolation.
    rope_scaling_type: Optional[str] = None
    rope_scaling_factor: float = 1.0
    rope_low_freq_factor: float = 1.0
    rope_high_freq_factor: float = 4.0
    rope_original_max_position: int = 8192
    mlp_bias: bool = False
    # SmolLM3 NoPE: 1 = RoPE on this layer, 0 = no positional embedding.
    # Empty tuple = RoPE everywhere (Llama/Mistral).
    no_rope_layers: tuple = ()
    sliding_window: Optional[int] = None  # Mistral-style local attention
    dtype: str = "bfloat16"
    # Mixture-of-experts (Mixtral-style). 0 = dense MLP. When > 0 every
    # layer's MLP becomes num_experts SwiGLU experts with top-k routing
    # (ops/moe.py); expert weights shard over the mesh "expert" axis.
    num_experts: int = 0
    num_experts_per_tok: int = 2
    # per-(batch-row, expert) token capacity = ceil(k * seq / E) * this factor;
    # overflow tokens fall through on the residual path (GShard semantics)
    capacity_factor: float = 1.25
    router_aux_coef: float = 0.01  # load-balancing loss weight (Switch/Mixtral)
    # sequences longer than this are routed in independent chunks (GShard
    # "groups"), keeping the one-hot dispatch tensors linear in seq length:
    # [b * s/chunk, chunk, E, C_chunk] instead of [b, s, E, C]. Tokens
    # compete for capacity within their chunk only.
    moe_dispatch_chunk: int = 1024

    def __post_init__(self):
        if self.num_experts and self.hidden_act != "silu":
            # ops/moe.py's expert MLP hardcodes silu — reject at config
            # construction rather than silently training with the wrong
            # activation (same fail-fast contract as rope_scaling parsing)
            raise ValueError(
                f"MoE models support hidden_act='silu' only "
                f"(got {self.hidden_act!r})"
            )

    @property
    def resolved_head_dim(self) -> int:
        return self.head_dim if self.head_dim is not None else self.hidden_size // self.num_heads

    @property
    def num_params(self) -> int:
        """Exact parameter count (matches HF model.num_parameters())."""
        h, v, f, L = self.hidden_size, self.vocab_size, self.intermediate_size, self.num_layers
        d = self.resolved_head_dim
        embed = v * h
        if self.num_experts:
            # router gate [h, E] + E SwiGLU experts (w1/w3 [h, f], w2 [f, h])
            mlp = h * self.num_experts + self.num_experts * 3 * h * f
        else:
            mlp = 3 * h * f                    # gate, up, down
        per_layer = (
            h * (self.num_heads * d)          # q_proj
            + h * (self.num_kv_heads * d) * 2  # k_proj, v_proj
            + (self.num_heads * d) * h         # o_proj
            + mlp
            + 2 * h                            # two RMSNorms
        )
        if self.attention_bias:
            per_layer += (self.num_heads + 2 * self.num_kv_heads) * d
            if self.attention_out_bias:
                per_layer += h
        if self.qk_norm:
            per_layer += 2 * d                 # q_norm, k_norm (per head_dim)
        if self.sandwich_norms:
            per_layer += 2 * h                 # post-attn + post-ffn norms
        if self.mlp_bias:
            per_layer += 2 * f + h
        total = embed + L * per_layer + h  # + final norm
        if not self.tie_word_embeddings:
            total += v * h
        return total

    def uses_rope(self, layer_idx: int) -> bool:
        if not self.no_rope_layers:
            return True
        return bool(self.no_rope_layers[layer_idx])

    def layer_sliding_window(self, layer_idx: int) -> Optional[int]:
        """Per-layer sliding window: Gemma2 alternates local (even layers) /
        global (odd); Mistral applies the window everywhere."""
        if self.sliding_window is None:
            return None
        if self.alternating_sliding_window and layer_idx % 2 != 0:
            return None
        return self.sliding_window

    def replace(self, **kw) -> "ModelConfig":
        return dataclasses.replace(self, **kw)


@dataclass(frozen=True)
class MeshConfig:
    """Logical device mesh.

    Axis meaning (scaling-book style):
      - ``data``:  pure data parallelism (gradients psum'd; params replicated)
      - ``fsdp``:  data parallelism with parameters sharded (ZeRO-3); batch is
                   sharded over data*fsdp jointly
      - ``tensor``: tensor parallelism (Megatron-style within attention/MLP)
      - ``seq``  : sequence/context parallelism — ring attention or Ulysses
                   all-to-all, selected by ``attention_impl`` (optional)
      - ``expert``: expert parallelism for MoE models — expert weights and the
                   dispatched token blocks shard over this axis (ops/moe.py)
      - ``pipe`` : pipeline parallelism — transformer blocks stacked
                   [num_layers, ...] and sharded by depth; microbatch
                   activations flow stage-to-stage with ppermute
                   (parallel/pipeline.py)

    Sizes of -1 mean "absorb remaining devices" (at most one axis may be -1).
    This replaces the reference's implicit 1-D DDP world
    (``WORLD_SIZE``/``RANK``, reference ``training.py:19-23``).
    """

    data: int = 1
    fsdp: int = -1
    tensor: int = 1
    seq: int = 1
    expert: int = 1
    pipe: int = 1

    def axis_sizes(self, n_devices: int) -> dict:
        sizes = {"data": self.data, "fsdp": self.fsdp, "tensor": self.tensor,
                 "seq": self.seq, "expert": self.expert, "pipe": self.pipe}
        unknown = [k for k, v in sizes.items() if v == -1]
        if len(unknown) > 1:
            raise ValueError(f"at most one mesh axis may be -1, got {unknown}")
        fixed = 1
        for k, v in sizes.items():
            if v != -1:
                fixed *= v
        if unknown:
            if n_devices % fixed:
                raise ValueError(f"{n_devices} devices not divisible by fixed axes {sizes}")
            sizes[unknown[0]] = n_devices // fixed
        else:
            if fixed != n_devices:
                raise ValueError(f"mesh {sizes} does not cover {n_devices} devices")
        return sizes


@dataclass
class TrainConfig:
    """Full SFT training configuration.

    Defaults reproduce the reference recipe exactly:
    epochs=4, per-device batch=8, lr=5e-5 (scaled x data-parallel size,
    reference ``training.py:263``), grad-accum 4 (``:262``), clip 1.0 (``:264``),
    log every 2 steps + first (``:266-267``), eval every 10 (``:270-271``),
    save every 500 keep 3 (``:268,276``), bf16 (``:269``), seq len 1024 with
    packing off (``:282-283``), 90/10 split seed 42 (``:164``), freeze all but
    last 2 layers + lm_head (``:113-149``).
    """

    # model / data
    model_name: str = "HuggingFaceTB/SmolLM3-3B"
    model_preset: Optional[str] = "smollm3_3b"
    data_dir: str = "data"
    dataset_file: str = "qa_dataset.parquet"
    output_dir: str = "outputs"
    tokenizer_path: Optional[str] = None  # defaults to model_name
    # None = the wilderness-survival persona (reference C7, training.py:176-186)
    system_prompt: Optional[str] = None

    # optimization
    epochs: int = 4
    per_device_batch_size: int = 8
    gradient_accumulation_steps: int = 4
    learning_rate: float = 5e-5
    scale_lr_by_data_parallel: bool = True  # lr x world_size rule, training.py:263
    # "adamw" (HF Trainer default, reference parity) | "adafactor" (factored
    # second moment — near-zero optimizer-state HBM, the classic TPU choice
    # for big models) | "lion" (sign-momentum, one state slot)
    optimizer: str = "adamw"
    weight_decay: float = 0.0
    adam_b1: float = 0.9
    adam_b2: float = 0.999
    adam_eps: float = 1e-8
    max_grad_norm: float = 1.0
    warmup_ratio: float = 0.0
    lr_schedule: str = "linear"  # HF Trainer default: linear decay to 0
    seed: int = 42

    # sequence / precision
    max_seq_length: int = 1024
    # packing=True packs multiple examples per row with an exact
    # block-diagonal segment mask (data/packing.py). Attention runs through
    # the explicit-mask XLA path (flash/ring impls apply to unpacked runs).
    packing: bool = False
    param_dtype: str = "float32"     # master weights
    compute_dtype: str = "bfloat16"  # activations / matmuls
    gradient_checkpointing: bool = True
    # remat granularity: "full" (recompute whole block — min memory),
    # "dots" / "dots_no_batch" (save matmul outputs — least recompute, most
    # HBM), "mlp" (save only the [s,f] SwiGLU product — the middle ground).
    # None = auto (resolved_remat_policy): picked by model size and PER-CHIP
    # sequence length from the measured ledger in BASELINE.md
    # ("Long-context single-chip series").
    remat_policy: Optional[str] = None
    # loss on completion tokens only? TRL SFTTrainer default (packing=False,
    # no completion_only flag in the reference) trains on the full sequence.
    completion_only_loss: bool = False
    # Compute the cross-entropy in sequence chunks of this size so the
    # [batch, seq, vocab] float32 logits tensor never materializes (HBM saver
    # for large-vocab models; None = single full-sequence unembed).
    loss_chunk_size: Optional[int] = None
    # Stream the cross-entropy over VOCAB chunks with an online logsumexp
    # (train/step.vocab_chunked_ce_sum): the f32 logits never materialize in
    # fwd OR bwd. Mutually exclusive with loss_chunk_size. vocab_size must
    # divide by it (SmolLM3's 128256 = 8 x 16032 = 16 x 8016).
    loss_vocab_chunk: Optional[int] = None

    # objective: "sft" (the reference recipe) or "dpo" (preference pairs,
    # BASELINE.json config #4 — the TRL DPOTrainer capability, first-party)
    objective: str = "sft"
    dpo_beta: float = 0.1              # TRL DPOConfig default
    dpo_label_smoothing: float = 0.0   # conservative-DPO eps

    # freezing policy (reference training.py:113-149)
    freeze_strategy: str = "last_n_and_head"  # or "none" / "lora" / "qlora"
    unfreeze_last_n_layers: int = 2

    # frozen-trunk compute (ISSUE 20): "bf16" runs frozen layers exactly as
    # today; "int8" runs the projection matmuls of entirely-frozen leading
    # layers as w8a8 (per-channel int8 weights x per-row dynamic int8
    # activations on the MXU int8 path) with a stop_gradient at the
    # trunk/trainable boundary and no trunk remat. No-op when the freeze
    # policy leaves trainable leaves in every layer (lora/qlora/none).
    frozen_compute: str = "bf16"       # or "int8"

    # QLoRA quantization (freeze_strategy="qlora": NF4 frozen base)
    quant_block_size: int = 64        # NF4 scale block (QLoRA paper default)
    quant_double_quant: bool = True   # int8-compress the absmax scales
    quant_matmul_impl: str = "auto"   # "auto" | "xla" (fused pallas retired: ops/nf4.py)

    # LoRA (external-doc config: r=16, alpha=8, dropout=0.05, 7 proj targets)
    lora_rank: int = 16
    lora_alpha: float = 8.0
    lora_dropout: float = 0.05
    lora_target_modules: Sequence[str] = (
        "q_proj", "k_proj", "v_proj", "o_proj", "gate_proj", "up_proj", "down_proj",
    )

    # cadence
    logging_steps: int = 2
    logging_first_step: bool = True
    eval_steps: int = 10
    # per-device EVAL batch size. None = per_device_batch_size (reference HF
    # semantics). Forward-only eval holds no grads/optimizer traffic, so much
    # larger batches fit — fewer scan iterations per sweep, directly cutting
    # the eval pause the r4 hardware run measured at 60-100s (VERDICT r4 #7).
    eval_batch_size: Optional[int] = None
    save_steps: int = 500
    save_total_limit: int = 3
    metric_for_best_model: str = "eval_loss"
    greater_is_better: bool = False
    load_best_model_at_end: bool = True
    # how load_best_model_at_end tracks the best weights:
    # - "per_eval": on-device snapshot at every eval improvement (finest
    #   granularity; costs one trainable-set copy of HBM)
    # - "checkpoint": restore the best SAVED checkpoint at end of run (HF's
    #   actual save-aligned semantics; zero steady-state cost — the right
    #   mode when HBM is tight, e.g. the 3B flagship on one 16 GB chip)
    # - "auto": per_eval while the trainable set is <512 MB, else checkpoint
    best_model_tracking: str = "auto"

    # data split
    validation_fraction: float = 0.1
    split_seed: int = 42
    drop_last: bool = True

    # mesh / distributed
    mesh: MeshConfig = field(default_factory=MeshConfig)
    # attention implementation: "xla" | "flash" (Pallas) | "ring" | "ulysses"
    attention_impl: str = "flash"

    # observability
    aim_repo: Optional[str] = None
    experiment_name: str = "smollm3-wilderness-finetuning-distributed"
    profile_dir: Optional[str] = None
    # training control plane (observe/trainplane.py): primary-host HTTP
    # server exposing /metrics, /v1/train/status, /v1/train/flight and
    # POST /v1/train/profile while the run steps. None = off; 0 = bind an
    # ephemeral port (tests/benches read it back from the plane object).
    train_port: Optional[int] = None
    # anomaly sentinels: trailing window (steps) a publish must keep clean
    # to get anomaly_clean=true, and the EWMA band width (sigmas) for the
    # loss-spike / grad-explosion detectors.
    anomaly_window_steps: int = 100
    anomaly_band_sigma: float = 6.0

    # native runtime (C++ layer, native/*.cc)
    use_native_loader: bool = True   # prefetching C++ batch pipeline, auto-fallback
    heartbeat: bool = False          # TCP failure detector (auto-on multi-host)
    heartbeat_port: int = 23457      # analog of reference master port 23456
    heartbeat_timeout_ms: int = 30000
    # cross-host param-consistency check every N steps (0 = off) — the
    # systematic form of the reference runbook's gradient-desync diagnosis
    # (docs/single-vs-distributed-comparison.md:571-580)
    desync_check_steps: int = 0
    # step watchdog (runtime/watchdog.py): seconds of training-loop silence
    # before reporting a wedged device link (0 = off). The single-process
    # analog of the multi-host heartbeat — a dead tunneled link otherwise
    # hangs the run forever with a healthy-looking process.
    watchdog_timeout_s: float = 0.0
    watchdog_action: str = "warn"  # or "abort": os._exit for restart+resume

    # checkpoint payload / overlap (VERDICT r4 #1)
    # trainable-only: persist (step, trainable masters, optimizer state) +
    # a fingerprint of the frozen params, re-deriving the frozen 86.4% from
    # the base checkpoint/seed at restore — cuts the flagship checkpoint
    # 7.4 GB -> ~2.1 GB. Incompatible with cross-mesh-layout (pipe<->flat)
    # resume; use full checkpoints when planning an elastic layout change.
    checkpoint_trainable_only: bool = False
    # single-process runs: hand the device->host stream + Orbax write to a
    # background thread after an on-device snapshot, so the next train step
    # never blocks on checkpoint IO (transient HBM: one payload copy).
    # Multi-process saves always use Orbax's own async path.
    checkpoint_async_snapshot: bool = True

    # live deployment (train/publish.py -> infer/deploy.py): after each
    # checkpoint save, also publish the trainable weights + manifest
    # (frozen-param fingerprint, step, eval metrics) atomically to this
    # directory so a serving fleet started with --publish-watch-dir
    # hot-swaps them without a restart. keep_last bounds disk: only the
    # newest K publishes survive retention.
    publish_dir: Optional[str] = None
    publish_keep_last: int = 3
    # refuse to publish a checkpoint whose trailing anomaly window is
    # dirty (non-finite loss, loss spike, grad explosion) instead of
    # stamping it anomaly_clean=false — keeps diverging weights from ever
    # reaching the deployment watch dir.
    publish_require_clean: bool = False

    # resume
    resume_from_checkpoint: Optional[str] = None  # "latest" or a path

    def effective_batch_size(self, data_parallel_size: int) -> int:
        return self.per_device_batch_size * self.gradient_accumulation_steps * data_parallel_size

    def resolved_remat_policy(
        self, model_config: "ModelConfig", seq_parallel_size: int = 1
    ) -> str:
        """Resolve remat_policy=None ("auto") by model size AND per-chip
        sequence length. An explicit setting always wins.

        Measured on the single v5e chip (SmolLM3-3B, bf16, BASELINE.md
        "Long-context single-chip series"): at seq 1024/2048 the
        matmul-saving "dots_no_batch" is fastest; at seq 4096 its saved dot
        products (~256MB/layer) blow HBM (19.4G > 15.75G) while "mlp" (save
        only the [s,f] SwiGLU product) fits and runs 2.4x faster than
        full-block remat; at 8k even the mlp saves OOM (17.1G). Big models
        always take minimum-HBM "full".

        ``seq_parallel_size``: the mesh's seq-axis size. A ring/ulysses run
        at global seq 8192 over 4 chips holds 2048 tokens per chip — the
        HBM pressure the ledger keys on is per-chip, so auto resolves on
        ``max_seq_length / seq_parallel_size``."""
        if self.remat_policy is not None:
            return self.remat_policy
        if model_config.num_params >= 6e9:
            return "full"
        per_chip_seq = self.max_seq_length // max(seq_parallel_size, 1)
        if per_chip_seq >= 8192:
            return "full"
        return "mlp" if per_chip_seq >= 4096 else "dots_no_batch"

    def scaled_learning_rate(self, data_parallel_size: int) -> float:
        if self.scale_lr_by_data_parallel:
            return self.learning_rate * data_parallel_size
        return self.learning_rate

    # ---- env-var override surface (reference training.py:54-60 + pytorchjob.yaml:30-66)

    _ENV_MAP = {
        "EPOCHS": ("epochs", int),
        "BATCH_SIZE": ("per_device_batch_size", int),
        "LEARNING_RATE": ("learning_rate", float),
        "DATA_DIR": ("data_dir", str),
        "OUTPUT_DIR": ("output_dir", str),
        "AIM_REPO": ("aim_repo", str),
        "MODEL_NAME": ("model_name", str),
        # MODEL_PRESET=none: resolve the architecture from MODEL_NAME's
        # config.json (the pre-staged local HF checkpoint contract)
        "MODEL_PRESET": ("model_preset", lambda s: None if s.lower() == "none" else s),
        "TOKENIZER_PATH": ("tokenizer_path", str),
        "MAX_SEQ_LENGTH": ("max_seq_length", int),
        "GRAD_ACCUM_STEPS": ("gradient_accumulation_steps", int),
        "SEED": ("seed", int),
        "ATTENTION_IMPL": ("attention_impl", str),
        "OPTIMIZER": ("optimizer", str),
        "PARAM_DTYPE": ("param_dtype", str),
        "FREEZE_STRATEGY": ("freeze_strategy", str),
        "FROZEN_COMPUTE": ("frozen_compute", str),
        "REMAT_POLICY": ("remat_policy", str),
        "LOSS_CHUNK_SIZE": ("loss_chunk_size", int),
        "LOSS_VOCAB_CHUNK": ("loss_vocab_chunk", int),
        "RESUME_FROM_CHECKPOINT": ("resume_from_checkpoint", str),
        "CHECKPOINT_TRAINABLE_ONLY": ("checkpoint_trainable_only", "_env_bool"),
        "CHECKPOINT_ASYNC_SNAPSHOT": ("checkpoint_async_snapshot", "_env_bool"),
        "PUBLISH_DIR": ("publish_dir", str),
        "PUBLISH_KEEP_LAST": ("publish_keep_last", int),
        "PUBLISH_REQUIRE_CLEAN": ("publish_require_clean", "_env_bool"),
        "TRAIN_PORT": ("train_port", int),
        "ANOMALY_WINDOW_STEPS": ("anomaly_window_steps", int),
        "ANOMALY_BAND_SIGMA": ("anomaly_band_sigma", float),
        "WATCHDOG_TIMEOUT_S": ("watchdog_timeout_s", float),
        "WATCHDOG_ACTION": ("watchdog_action", str),
        "OBJECTIVE": ("objective", str),
        "DPO_BETA": ("dpo_beta", float),
        "LOGGING_STEPS": ("logging_steps", int),
        "EVAL_STEPS": ("eval_steps", int),
        "EVAL_BATCH_SIZE": ("eval_batch_size", int),
        "SAVE_STEPS": ("save_steps", int),
        "SAVE_TOTAL_LIMIT": ("save_total_limit", int),
        "EXPERIMENT_NAME": ("experiment_name", str),
    }

    @staticmethod
    def _env_bool(s: str) -> bool:
        v = s.strip().lower()
        if v in ("1", "true", "yes", "on"):
            return True
        if v in ("0", "false", "no", "off"):
            return False
        raise ValueError(f"boolean env var must be 1/0/true/false/yes/no/on/off, got {s!r}")

    def apply_env_overrides(self, environ=None) -> "TrainConfig":
        env = os.environ if environ is None else environ
        for var, (attr, cast) in self._ENV_MAP.items():
            if var in env and env[var] != "":
                if cast == "_env_bool":
                    cast = self._env_bool
                setattr(self, attr, cast(env[var]))
        return self

    # ---- (de)serialization

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["lora_target_modules"] = list(self.lora_target_modules)
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "TrainConfig":
        d = dict(d)
        if "mesh" in d and isinstance(d["mesh"], dict):
            d["mesh"] = MeshConfig(**d["mesh"])
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(d) - known
        if unknown:
            raise ValueError(f"unknown config keys: {sorted(unknown)}")
        return cls(**d)

    @classmethod
    def load(cls, path: str) -> "TrainConfig":
        """Load from a JSON or YAML file."""
        with open(path) as f:
            text = f.read()
        if path.endswith((".yaml", ".yml")):
            try:
                import yaml  # type: ignore
            except ImportError as e:
                raise ImportError("pyyaml not available; use JSON config") from e
            data = yaml.safe_load(text)
        else:
            data = json.loads(text)
        return cls.from_dict(data)

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.to_dict(), f, indent=2)


def str_to_dtype(name: str):
    import jax.numpy as jnp

    return {
        "float32": jnp.float32,
        "f32": jnp.float32,
        "bfloat16": jnp.bfloat16,
        "bf16": jnp.bfloat16,
        "float16": jnp.float16,
    }[name]
