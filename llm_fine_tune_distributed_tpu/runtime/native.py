"""Build + ctypes bindings for the native C++ runtime (package ``native/*.cc``).

The reference's host-side native layer arrives via dependencies (Arrow C++,
torch DataLoader workers, NCCL bootstrap — SURVEY.md §2.3); ours is
first-party: a threaded prefetching batch pipeline (native/loader.cc) and a
TCP heartbeat failure detector (native/heartbeat.cc), shipped as package
data so a pip install carries them. Compiled on first use with g++ into
``_tpu_runtime.so`` (beside the sources, or in a per-user cache dir when the
install is read-only) and rebuilt whenever a source file is newer than the
binary. Everything degrades gracefully: callers check ``available()`` and
fall back to the pure-Python paths.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_PKG_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_NATIVE_DIR = os.path.join(_PKG_ROOT, "native")
_SOURCES = ("loader.cc", "heartbeat.cc")
_LIB_NAME = "_tpu_runtime.so"


def _lib_dir() -> str:
    """Directory for the built .so: beside the sources when writable
    (dev checkout, container image), else a per-user cache dir (read-only
    site-packages installs)."""
    if os.access(_NATIVE_DIR, os.W_OK):
        return _NATIVE_DIR
    cache = os.path.join(
        os.environ.get("XDG_CACHE_HOME", os.path.expanduser("~/.cache")),
        "llm_fine_tune_distributed_tpu",
    )
    os.makedirs(cache, exist_ok=True)
    return cache

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_error: Optional[str] = None


def _needs_build(lib_path: str) -> bool:
    if not os.path.exists(lib_path):
        return True
    lib_mtime = os.path.getmtime(lib_path)
    return any(
        os.path.getmtime(os.path.join(_NATIVE_DIR, s)) > lib_mtime for s in _SOURCES
    )


def _build(lib_path: str) -> None:
    # Compile to a per-pid temp file, then atomically rename into place:
    # concurrent first-use builds (multi-host shared checkout, parallel test
    # workers) must never dlopen a half-written .so.
    srcs = [os.path.join(_NATIVE_DIR, s) for s in _SOURCES]
    tmp_path = f"{lib_path}.tmp.{os.getpid()}"
    cmd = [
        "g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
        "-Wall", "-Werror", "-o", tmp_path, *srcs,
    ]
    proc = subprocess.run(cmd, capture_output=True, text=True, timeout=300)
    if proc.returncode != 0:
        raise RuntimeError(f"native build failed:\n{proc.stderr}")
    os.replace(tmp_path, lib_path)


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    c = ctypes
    i32p, i64p = c.POINTER(c.c_int32), c.POINTER(c.c_int64)
    lib.sft_loader_create.restype = c.c_void_p
    lib.sft_loader_create.argtypes = [
        i32p, i32p, i32p, c.c_int64, c.c_int64, c.c_int64, c.c_int64,
        c.c_int64, c.c_int64, c.c_uint64, c.c_int, c.c_int, c.c_int,
    ]
    pp32 = c.POINTER(i32p)
    lib.sft_loader_create_multi.restype = c.c_void_p
    lib.sft_loader_create_multi.argtypes = [
        pp32, c.c_int32, c.c_int64, c.c_int64, c.c_int64, c.c_int64,
        c.c_int64, c.c_int64, c.c_uint64, c.c_int, c.c_int, c.c_int,
    ]
    lib.sft_loader_next_multi.restype = c.c_int
    lib.sft_loader_next_multi.argtypes = [c.c_void_p, pp32]
    lib.sft_loader_steps_per_epoch.restype = c.c_int64
    lib.sft_loader_steps_per_epoch.argtypes = [c.c_void_p]
    lib.sft_loader_start_epoch.restype = None
    lib.sft_loader_start_epoch.argtypes = [c.c_void_p, c.c_int64]
    lib.sft_loader_next.restype = c.c_int
    lib.sft_loader_next.argtypes = [c.c_void_p, i32p, i32p, i32p]
    lib.sft_loader_destroy.restype = None
    lib.sft_loader_destroy.argtypes = [c.c_void_p]
    lib.sft_loader_epoch_order.restype = None
    lib.sft_loader_epoch_order.argtypes = [c.c_void_p, c.c_int64, i64p]

    lib.hb_start_coordinator.restype = c.c_void_p
    lib.hb_start_coordinator.argtypes = [c.c_int, c.c_int]
    lib.hb_coordinator_port.restype = c.c_int
    lib.hb_coordinator_port.argtypes = [c.c_void_p]
    lib.hb_dead_mask.restype = c.c_uint64
    lib.hb_dead_mask.argtypes = [c.c_void_p, c.c_int]
    lib.hb_rank_age_ms.restype = c.c_int64
    lib.hb_rank_age_ms.argtypes = [c.c_void_p, c.c_int]
    lib.hb_stop_coordinator.restype = None
    lib.hb_stop_coordinator.argtypes = [c.c_void_p]
    lib.hb_start_worker.restype = c.c_void_p
    lib.hb_start_worker.argtypes = [c.c_char_p, c.c_int, c.c_int, c.c_int]
    lib.hb_stop_worker.restype = None
    lib.hb_stop_worker.argtypes = [c.c_void_p]
    return lib


def load() -> Optional[ctypes.CDLL]:
    """Compile (if stale) and load the native library; None if unavailable."""
    global _lib, _build_error
    with _lock:
        if _lib is not None:
            return _lib
        if _build_error is not None:
            return None
        lib_path = os.path.join(_lib_dir(), _LIB_NAME)
        try:
            prebuilt = not _needs_build(lib_path)
            if not prebuilt:
                _build(lib_path)
            try:
                _lib = _bind(ctypes.CDLL(lib_path))
            except (OSError, AttributeError):
                # A pre-existing binary may be stale or built for another
                # platform (equal mtimes defeat _needs_build on a fresh
                # checkout): dlopen fails with OSError, a missing symbol
                # (older ABI than _bind expects) with AttributeError.
                # Rebuild from the shipped sources and retry once.
                if not prebuilt:
                    raise
                _build(lib_path)
                _lib = _bind(ctypes.CDLL(lib_path))
        except (OSError, AttributeError, RuntimeError, subprocess.SubprocessError) as e:
            _build_error = str(e)
            return None
        return _lib


def available() -> bool:
    return load() is not None


def build_error() -> Optional[str]:
    return _build_error
