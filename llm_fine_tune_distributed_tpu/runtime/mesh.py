"""Device mesh construction.

The reference's distributed world is a flat NCCL rank list
(``WORLD_SIZE``/``RANK``, reference ``training.py:19-23``). The TPU-native
analog is an N-D logical mesh over the physical ICI/DCN topology; XLA emits the
collectives (psum / all-gather / reduce-scatter) from sharding annotations —
there is no NCCL env-var zoo (reference ``deploy/pytorchjob.yaml:51-64``).

Axis order puts ``data`` outermost so that, on multi-slice systems, the pure
data-parallel axis (which only communicates once per step for the gradient
reduction) maps onto DCN while fsdp/tensor/seq traffic stays on ICI —
the standard scaling-book layout. ``make_mesh`` enforces this for real: when
the device pool spans multiple slices (``device.slice_index`` differs) it
builds the mesh with ``mesh_utils.create_hybrid_device_mesh``, spreading
ONLY the data axis across slices and refusing shapes that would put any
other axis on DCN (~6 GB/s/chip vs ~90 GB/s ICI — see
observe/scaling.py:V5E).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from llm_fine_tune_distributed_tpu.config import MeshConfig
from llm_fine_tune_distributed_tpu.utils.compat import (
    mesh_auto_axis_types,
    mesh_kwargs,
)

MESH_AXES = ("data", "pipe", "fsdp", "tensor", "seq", "expert")


def make_mesh(
    config: Optional[MeshConfig] = None,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Build a Mesh with axes (data, fsdp, tensor, seq) from a MeshConfig.

    Uses ``jax.make_mesh`` when laying out over real TPU devices so the mesh
    follows the physical ICI topology; falls back to a reshape for explicit
    device lists (tests).
    """
    config = config or MeshConfig()
    if devices is None:
        devices = jax.devices()
    try:
        sizes = config.axis_sizes(len(devices))
    except ValueError:
        # Fully-specified mesh smaller than the device pool: use a prefix of
        # the devices (tests / deliberate under-subscription).
        explicit = {"data": config.data, "fsdp": config.fsdp,
                    "tensor": config.tensor, "seq": config.seq,
                    "expert": config.expert, "pipe": config.pipe}
        if -1 in explicit.values():
            raise
        product = 1
        for v in explicit.values():
            product *= v
        if product > len(devices):
            raise
        devices = list(devices)[:product]
        sizes = config.axis_sizes(product)
    shape = tuple(sizes[a] for a in MESH_AXES)
    # Auto axis types: sharding propagates GSPMD/Shardy-style from the
    # annotations on params/batch plus with_sharding_constraint points.
    # (jax.make_mesh defaults to Explicit axis types as of jax 0.9, which
    # instead type-checks every intermediate — not what we want here. On
    # jax 0.4.x AxisType does not exist and auto is the only semantics:
    # mesh_auto_axis_types returns None and the kwarg is omitted.)
    auto = mesh_auto_axis_types(len(MESH_AXES))
    n_slices = len({getattr(d, "slice_index", 0) or 0 for d in devices})
    if n_slices > 1:
        return _make_hybrid_mesh(sizes, devices, n_slices, auto)
    if devices is jax.devices() or list(devices) == list(jax.devices()):
        try:
            return jax.make_mesh(shape, MESH_AXES, **mesh_kwargs(auto))
        except Exception:
            pass
    dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, MESH_AXES, **mesh_kwargs(auto))


def _make_hybrid_mesh(sizes: dict, devices, n_slices: int, axis_types) -> Mesh:
    """Multi-slice mesh: the data axis (and only it) spreads across slices.

    Per-slice traffic (fsdp all-gathers, tensor psums, seq permutes, pipe
    boundaries, expert dispatch) must ride ICI; the pure data axis carries
    one gradient reduction per accumulation step — the only volume DCN can
    afford (BASELINE.md "Multi-slice note"). Shapes that cannot place every
    non-data axis within a slice are rejected rather than silently built
    slow."""
    from jax.experimental import mesh_utils

    if sizes["data"] % n_slices:
        raise ValueError(
            f"multi-slice mesh: data={sizes['data']} must be divisible by "
            f"the slice count ({n_slices}) — only the pure data axis may "
            "span slices (DCN); fsdp/tensor/seq/pipe/expert traffic needs ICI"
        )
    per_slice = len(devices) // n_slices
    ici = dict(sizes, data=sizes["data"] // n_slices)
    ici_product = 1
    for a in MESH_AXES:
        ici_product *= ici[a]
    if ici_product != per_slice:
        raise ValueError(
            f"multi-slice mesh: non-data axes need {ici_product} devices per "
            f"slice but each slice has {per_slice}"
        )
    dcn = {a: (n_slices if a == "data" else 1) for a in MESH_AXES}
    dev_array = mesh_utils.create_hybrid_device_mesh(
        tuple(ici[a] for a in MESH_AXES),
        tuple(dcn[a] for a in MESH_AXES),
        devices=list(devices),
    )
    return Mesh(dev_array, MESH_AXES, **mesh_kwargs(axis_types))


def data_parallel_size(mesh: Mesh) -> int:
    """Number of data-parallel replicas = data * fsdp (batch is sharded over
    both; fsdp additionally shards params). Drives the lr x world_size rule
    (reference ``training.py:263``)."""
    return mesh.shape["data"] * mesh.shape["fsdp"]


def describe_mesh(mesh: Mesh) -> str:
    parts = [f"{a}={mesh.shape[a]}" for a in mesh.axis_names]
    return f"Mesh({', '.join(parts)}) over {mesh.size} devices"
