"""Single-process step watchdog: detect a wedged device link.

The multi-host failure story (native/heartbeat.cc + runtime/failure.py)
detects a DEAD PEER; nothing detected a dead DEVICE LINK under a
single-process run. Observed on the round-5 flagship (tunneled v5e): one
run sat 452 s in a silent link stall mid-step and a later run wedged
PERMANENTLY between two train steps — steady 3.3 s/step, then infinite
block inside a device sync, log silent, process sleeping. Kubernetes sees
a healthy process and never restarts it; resume-from-checkpoint never
gets its chance.

The watchdog is a daemon thread the trainer pokes once per loop iteration.
If no poke arrives within ``timeout_s`` it reports loudly to stderr (with
the stall duration and last step), and in ``action="abort"`` mode hard-exits
the process (``os._exit``) so the job manager restarts it and training
resumes from the latest checkpoint — turning an invisible infinite hang
into the same restart->resume path a dead host takes. ``os._exit`` is
deliberate: a wedged XLA sync cannot be interrupted from Python, so a
cooperative shutdown would itself hang.

Cost: one event-wait thread; the poke is a timestamp store.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from typing import Optional


class StepWatchdog:
    def __init__(
        self,
        timeout_s: float,
        action: str = "warn",
        on_trip=None,
        poll_s: Optional[float] = None,
        start_paused: bool = False,
        recorder=None,
    ):
        """``start_paused=True``: stay disarmed until the FIRST poke — the
        trainer uses this so the startup window (mid-epoch resume
        fast-forward + multi-minute first-step compile) can never
        false-trip into an unrecoverable abort/restart loop.

        ``recorder``: optional FlightRecorder; trips are recorded from the
        poller thread and re-arms from ``poke`` only on the paused->armed
        transition, so the per-step poke stays a bare timestamp store."""
        if action not in ("warn", "abort"):
            raise ValueError(f"watchdog action must be warn|abort, got {action!r}")
        self.timeout_s = float(timeout_s)
        self.action = action
        self._on_trip = on_trip  # test hook; called instead of os._exit
        self._recorder = recorder
        self._poll_s = poll_s if poll_s is not None else min(self.timeout_s / 4, 10.0)
        self._last_poke = time.monotonic()
        self._last_step = 0
        self._tripped = 0  # count of warnings fired (monotonic)
        self._paused = start_paused
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="step-watchdog", daemon=True
        )
        self._thread.start()

    # ------------------------------------------------------------------ API

    def poke(self, step: int) -> None:
        """Call once per training-loop iteration (host side, cheap).
        A poke is definite progress, so it also re-arms a paused watchdog."""
        self._last_poke = time.monotonic()
        self._last_step = step
        if self._paused:
            # paused->armed happens only at eval/checkpoint boundaries, so
            # the flight event (a clock read) never rides the hot path
            self._paused = False
            if self._recorder is not None:
                self._recorder.record("watchdog_rearm", step=step)

    def pause(self) -> None:
        """Disarm during legitimately long host-side phases (checkpoint
        restore, artifact export) so slow-but-progressing IO never trips."""
        self._paused = True

    def resume(self) -> None:
        self._last_poke = time.monotonic()
        self._paused = False

    @property
    def trips(self) -> int:
        return self._tripped

    def stop(self) -> None:
        self._stop.set()

    # -------------------------------------------------------------- internal

    def _run(self) -> None:
        while not self._stop.wait(self._poll_s):
            if self._paused:
                continue
            silent = time.monotonic() - self._last_poke
            if silent < self.timeout_s:
                continue
            self._tripped += 1
            if self._recorder is not None:
                self._recorder.record(
                    "watchdog_trip",
                    silent_s=round(silent, 1),
                    last_step=self._last_step,
                    action=self.action,
                )
            print(
                f"[watchdog] no training-loop progress for {silent:.0f}s "
                f"(last step {self._last_step}, timeout {self.timeout_s:.0f}s) "
                "— the device link may be wedged"
                + (
                    "; aborting for restart+resume"
                    if self.action == "abort"
                    else ""
                ),
                file=sys.stderr,
                flush=True,
            )
            if self.action == "abort":
                if self._on_trip is not None:
                    self._on_trip()
                    return
                os._exit(42)
            # warn mode: re-arm so a persisting stall warns once per timeout
            self._last_poke = time.monotonic()
