"""Ring attention: sequence/context parallelism over a mesh axis.

The reference has NO long-context path — context is fixed at 1024 tokens and
its only attention optimization is flash-attn-2 for memory (SURVEY.md §5.7;
reference ``training.py:282``, ``requirements.txt:10``). This module is the
TPU-native long-context design the survey calls for: each device in the
``seq`` mesh axis holds one contiguous chunk of the sequence, K/V chunks
rotate around the ICI ring with ``jax.lax.ppermute``, and every device
accumulates its queries' attention with the blockwise online-softmax
recurrence (the same math as the Pallas flash kernel in
ops/flash_attention.py, lifted from VMEM blocks to mesh shards).

Peak memory per device is O(seq/N * seq/N) score tiles instead of O(seq^2),
and the N-1 ppermute hops overlap with the blockwise compute — XLA pipelines
the collective-permute against the einsums, which is what makes this the
idiomatic TPU expression of context parallelism (vs. all-gathering K/V).

Called inside ``jax.shard_map`` (fully manual over all mesh axes): batch is
sharded over (data, fsdp), heads over tensor, sequence over seq. Gradients
flow through ``ppermute`` (reverse permutation on backward), so the same code
path trains — no separate backward kernel needed.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

_NEG_INF = -2.0e38  # finite: (-inf) arithmetic breeds NaNs in the recurrence


def _local_ring_attention(q, k, v, padding_mask, segment_ids=None, *, axis_name: str,
                          axis_size: int, causal: bool):
    """Blockwise attention over ring-rotated K/V chunks.

    Runs on ONE device's shards inside shard_map:
      q: [b, lq, h, d]   — this device's query chunk (lq = seq / axis_size)
      k, v: [b, lk, hk, d] — this device's K/V chunk, rotated each step
      padding_mask: [b, lk] (1 = real token) rotated alongside, or None.
      segment_ids: [b, lq] packing segments (data/packing.py) or None. The
        query-side chunk stays resident; a key-side copy rotates with K/V and
        attention is restricted to equal ids — packed rows keep segments
        contiguous, so row-position causality + id equality reproduces the
        block-diagonal causal mask exactly (parity pinned in
        tests/test_ring_attention.py).
    """
    my_idx = jax.lax.axis_index(axis_name)
    b, lq, num_heads, d = q.shape
    lk, num_kv = k.shape[1], k.shape[2]
    groups = num_heads // num_kv

    scale = 1.0 / jnp.sqrt(d).astype(jnp.float32)
    # [b, lq, hk, g, d] — GQA grouping computed once.
    qg = (q.astype(jnp.float32) * scale).reshape(b, lq, num_kv, groups, d)
    q_pos = my_idx * lq + jnp.arange(lq)

    # Online-softmax carry: running max m, denominator l, weighted output o.
    o = jnp.zeros((b, num_kv, groups, lq, d), jnp.float32)
    m = jnp.full((b, num_kv, groups, lq), _NEG_INF, jnp.float32)
    l = jnp.zeros((b, num_kv, groups, lq), jnp.float32)

    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
    cur_k, cur_v, cur_pad, cur_seg = k, v, padding_mask, segment_ids

    for t in range(axis_size):
        # After t forward rotations this device holds chunk (my_idx - t).
        kv_idx = (my_idx - t) % axis_size
        k_pos = kv_idx * lk + jnp.arange(lk)

        scores = jnp.einsum(
            "bqhgd,bkhd->bhgqk", qg, cur_k.astype(jnp.float32)
        )  # [b, hk, g, lq, lk]
        if causal:
            cmask = k_pos[None, :] <= q_pos[:, None]  # [lq, lk]
            scores = jnp.where(cmask[None, None, None], scores, _NEG_INF)
        if cur_pad is not None:
            pm = cur_pad.astype(bool)[:, None, None, None, :]
            scores = jnp.where(pm, scores, _NEG_INF)
        if segment_ids is not None:
            sm = segment_ids[:, :, None] == cur_seg[:, None, :]  # [b, lq, lk]
            scores = jnp.where(sm[:, None, None], scores, _NEG_INF)

        m_new = jnp.maximum(m, scores.max(axis=-1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(scores - m_new[..., None])
        l = l * alpha + p.sum(axis=-1)
        o = o * alpha[..., None] + jnp.einsum("bhgqk,bkhd->bhgqd", p, cur_v.astype(jnp.float32))
        m = m_new

        if t < axis_size - 1:
            cur_k = jax.lax.ppermute(cur_k, axis_name, perm)
            cur_v = jax.lax.ppermute(cur_v, axis_name, perm)
            if cur_pad is not None:
                cur_pad = jax.lax.ppermute(cur_pad, axis_name, perm)
            if cur_seg is not None:
                cur_seg = jax.lax.ppermute(cur_seg, axis_name, perm)

    # Fully-masked rows (pad queries) have l == 0; their output is dropped by
    # the loss mask, so any finite value works.
    out = o / jnp.maximum(l, 1e-30)[..., None]
    # [b, hk, g, lq, d] -> [b, lq, h, d]
    out = out.transpose(0, 3, 1, 2, 4).reshape(b, lq, num_heads, d)
    return out.astype(q.dtype)


def shard_map_seq_attention(local, mesh: Mesh, axis_name: str, q, k, v,
                            padding_mask=None, segment_ids=None):
    """Shared global-view plumbing for BOTH sequence-parallel strategies:
    shard q/k/v (+ optional per-row operands) over the mesh and shard_map the
    local kernel. ``local(q, k, v, padding_mask, segment_ids)`` runs on one
    device's chunks. One source of truth so the optional-operand binding
    cannot drift between ring and Ulysses entries."""
    qkv_spec = P(("data", "fsdp"), axis_name, "tensor", None)
    row_spec = P(("data", "fsdp"), axis_name)

    has_pad = padding_mask is not None
    has_seg = segment_ids is not None

    def run(q_, k_, v_, *rest):
        rest = list(rest)
        p_ = rest.pop(0) if has_pad else None
        s_ = rest.pop(0) if has_seg else None
        return local(q_, k_, v_, p_, s_)

    from llm_fine_tune_distributed_tpu.utils.compat import shard_map

    fn = shard_map(
        run,
        mesh=mesh,
        in_specs=(qkv_spec,) * 3
        + ((row_spec,) if has_pad else ())
        + ((row_spec,) if has_seg else ()),
        out_specs=qkv_spec,
        check_vma=False,
    )
    args = (q, k, v) + ((padding_mask,) if has_pad else ()) + (
        (segment_ids,) if has_seg else ()
    )
    return fn(*args)


def seq_parallel_static_preconditions(
    seq_len: int, num_heads: int, num_kv: int, mesh: Optional[Mesh], *,
    axis_name: str = "seq", sliding_window: Optional[int] = None,
    causal: bool = True,
) -> bool:
    """The MODEL/CONFIG-decidable half of the seq-parallel preconditions:
    live seq axis, causal non-windowed attention, seq length and (kv) heads
    divisible by the mesh. Shared by the runtime predicates below AND the
    trainer's static remat resolution (train/step.static_seq_parallel_size) —
    one source of truth so a precondition added here can never make runtime
    fall back while the remat policy still divides per-chip seq (ADVICE r4)."""
    if mesh is None or axis_name not in mesh.shape or mesh.shape[axis_name] <= 1:
        return False
    if sliding_window is not None or not causal:
        return False  # cross-chunk window bookkeeping not implemented
    n_seq = mesh.shape[axis_name]
    tensor = mesh.shape.get("tensor", 1)
    return (
        seq_len % n_seq == 0
        and num_heads % tensor == 0
        and num_kv % tensor == 0
        and (num_heads // tensor) % max(num_kv // tensor, 1) == 0
    )


def seq_parallel_preconditions(q, k, mesh: Optional[Mesh], *, axis_name: str = "seq",
                               sliding_window: Optional[int] = None,
                               causal: bool = True) -> bool:
    """Checks shared by BOTH sequence-parallel strategies (ring here, Ulysses
    in parallel/ulysses.py): the static preconditions above plus the
    batch/shape facts only known at dispatch time. Keeping one source of
    truth stops the two ``*_supported`` predicates from drifting apart."""
    if q.shape[1] != k.shape[1]:
        return False  # decode/KV-cache path (q_len != kv_len): positions would lie
    if not seq_parallel_static_preconditions(
        q.shape[1], q.shape[2], k.shape[2], mesh,
        axis_name=axis_name, sliding_window=sliding_window, causal=causal,
    ):
        return False
    batch_ways = mesh.shape.get("data", 1) * mesh.shape.get("fsdp", 1)
    return q.shape[0] % batch_ways == 0


def ring_attention_supported(q, k, mesh: Optional[Mesh], *, axis_name: str = "seq",
                             sliding_window: Optional[int] = None, causal: bool = True) -> bool:
    return seq_parallel_preconditions(
        q, k, mesh, axis_name=axis_name, sliding_window=sliding_window, causal=causal
    )


def ring_attention(q, k, v, *, mesh: Mesh, axis_name: str = "seq", padding_mask=None,
                   segment_ids=None, causal: bool = True):
    """Global-view entry: shard q/k/v over the mesh and run the ring.

    q: [batch, seq, heads, dim]; k, v: [batch, seq, kv_heads, dim];
    padding_mask: optional [batch, seq], 1 = real token;
    segment_ids: optional [batch, seq] packing segments (packed long-context
    runs keep their seq axis — VERDICT r3 #5).
    Layout contract matches ops/attention.py; call sites go through
    ``ops.attention.attention(impl="ring", mesh=...)``.
    """
    local = partial(
        _local_ring_attention, axis_name=axis_name,
        axis_size=mesh.shape[axis_name], causal=causal,
    )
    return shard_map_seq_attention(
        local, mesh, axis_name, q, k, v,
        padding_mask=padding_mask, segment_ids=segment_ids,
    )
