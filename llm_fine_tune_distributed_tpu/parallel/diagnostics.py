"""Parallelism-dispatch diagnostics.

The sequence-parallel attention impls ("ring", "ulysses") fall back to
flash/XLA attention when their shape preconditions fail
(ops/attention._seq_parallel_fallback). The fallback warns when a provisioned
seq axis goes unused, but a warning is easy to miss — VERDICT r4 found a
"ulysses parity test" whose mesh violated the batch-divisibility precondition,
so it silently tested the fallback and passed anyway. ``assert_seq_parallel``
is the un-missable form: it turns the fallback warning into an error AND
positively asserts (via the trace-time dispatch ledger in ops/attention.py)
that the claimed implementation actually ran. Every ring/ulysses parity test
wraps its forward in this guard; users can wrap their own first training step
to prove a long-context mesh is live (docs/operating-manual.md
troubleshooting table).
"""

from __future__ import annotations

import warnings
from contextlib import contextmanager

_FALLBACK_MSG = ".*seq axis is NOT being used.*"


@contextmanager
def assert_seq_parallel(expected: str):
    """Fail unless an ``attention(impl=expected)`` call inside the block
    dispatched to the REAL sequence-parallel path (no silent fallback).

    ``expected``: "ring" | "ulysses" | "ring_manual" | "ulysses_manual".
    The check is trace-time: wrap the first (compiling) call of a jitted
    function, not a cache-hit re-execution.
    """
    import importlib

    # ops/__init__.py re-exports the attention FUNCTION under the same name,
    # so attribute-style imports would resolve to it — fetch the module.
    att = importlib.import_module("llm_fine_tune_distributed_tpu.ops.attention")

    valid = ("ring", "ulysses", "ring_manual", "ulysses_manual")
    if expected not in valid:
        raise ValueError(f"expected must be one of {valid}, got {expected!r}")
    before = att.dispatch_count(expected)
    with warnings.catch_warnings():
        warnings.filterwarnings("error", message=_FALLBACK_MSG)
        yield
    after = att.dispatch_count(expected)
    if after <= before:
        raise AssertionError(
            f"attention impl {expected!r} never dispatched inside the guarded "
            f"block — the code under test ran a different attention path "
            f"(check seq-axis size, batch % (data*fsdp), seq-length and "
            f"head/kv-head divisibility: parallel/ring_attention."
            f"seq_parallel_preconditions)"
        )
