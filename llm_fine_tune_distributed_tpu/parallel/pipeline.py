"""Pipeline parallelism (GPipe schedule) over a ``pipe`` mesh axis.

The reference has no pipeline parallelism (SURVEY.md §2.4 marks it absent;
the MPMD-pipeline paper in PAPERS.md is its design pointer). This is the
TPU-native expression: not MPMD processes with send/recv, but ONE SPMD
program over a ``pipe`` mesh axis where

- each stage device holds a contiguous slice of the transformer blocks
  (stacked layer-major, so the per-stage compute is a ``lax.scan`` over its
  own layers — one compiled block body regardless of depth);
- activations move stage-to-stage with ``jax.lax.ppermute`` (ICI
  neighbor-exchange, the cheapest collective on a TPU torus);
- the GPipe timetable is a ``lax.scan`` over ``M + S - 1`` ticks: stage ``s``
  processes microbatch ``t - s`` at tick ``t`` (bubble ticks compute on
  zeros and are masked out);
- the BACKWARD pipeline is not hand-written at all: ``jax.grad`` through the
  scan + ppermute yields the reversed schedule automatically — the
  correctness-by-construction benefit of a functional pipeline.

Embedding/unembedding and the final norm live outside the pipelined blocks:
embedding is applied to all microbatches up front (host of stage 0 data),
the last stage's outputs are collected, and the loss closes over them. The
embedding table is replicated across stages (it is ~3% of SmolLM3's params).

Wired into SFTTrainer via the ``pipe`` mesh axis (``MESH_PIPE=2 python
training.py``): ``build_pipeline_train_step`` / ``build_pipeline_eval_step``
below are the drop-in step builders, with the stacked-layer state
representation handled by ``stack_flat_layer_leaves`` and partial-layer
freezing by a per-layer gradient mask. The pipe axis composes with
data/fsdp data parallelism (the microbatch dim shards over them inside the
schedule's shard_map).

Schedule note (why GPipe, not 1F1B): differentiating the tick scan yields
the exact time-reversed pipeline, so one optimizer step costs
``2*(M + S - 1)`` stage-ticks against an ideal ``2*M`` — the same bubble
fraction ``(S-1)/(M+S-1)`` 1F1B has (1F1B reorders the SAME work; its
advantage is peak activation memory, capped at S in-flight microbatches
instead of M). Here that memory pressure is addressed where XLA can see it:
``remat_blocks`` saves only stage-boundary activations ([mb, seq, h] per
tick) and recomputes block internals, so in-flight cost is one boundary
tensor per microbatch — smaller than 1F1B's S full stage residuals whenever
h is small relative to per-block state. Cutting the bubble itself requires
interleaved virtual stages (Megatron-style), which trades v× more ppermute
volume for a v× smaller bubble — worth it only at large S; the mesh sizes
this framework targets (pipe ≤ 8) prefer raising M (grad-accum) instead.

Composes with LoRA/QLoRA (adapter leaves stack like any per-layer leaf; the
all-frozen base groups stay out of the optimizer — build_pipeline_state_leaves),
with DPO (train/dpo.build_pipeline_dpo_train_step runs both DPO forwards as
schedules), with expert parallelism (manual-subset shard_map; stacked experts
shard over pipe AND expert), and and with sequence parallelism — BOTH impls (``attention_impl="ring"`` or
``"ulysses"`` + a live seq axis: the schedule goes manual over seq and
stages call the local kernels — long-context pipe runs). Scope bounds
(raised loudly by the trainer): packing (no segment support in the
schedule) and seq-parallel x MoE (per-chunk routing would change capacity
semantics).
"""

from __future__ import annotations

import re
from typing import Dict

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from llm_fine_tune_distributed_tpu.utils.compat import shard_map

import optax

from llm_fine_tune_distributed_tpu.config import ModelConfig
from llm_fine_tune_distributed_tpu.models.transformer import _block, unembed
from llm_fine_tune_distributed_tpu.ops.norms import rms_norm
from llm_fine_tune_distributed_tpu.ops.rope import rope_cos_sin


def stack_stage_params(params: Dict, config: ModelConfig, num_stages: int) -> Dict:
    """Layer dicts -> leaves stacked [num_layers, ...] (layer-major).

    Sharding the leading dim over ``pipe`` gives each stage its contiguous
    block of layers; within a stage the compute scans over the local slice.
    """
    if config.num_layers % num_stages:
        raise ValueError(
            f"{config.num_layers} layers not divisible by {num_stages} stages"
        )
    layers = params["model"]["layers"]
    return jax.tree.map(
        lambda *leaves: jnp.stack(leaves),
        *[layers[str(i)] for i in range(config.num_layers)],
    )


def stage_sharding(mesh: Mesh):
    """Stacked layer leaves: leading (layer) dim sharded over ``pipe``."""
    return NamedSharding(mesh, P("pipe"))


def pipeline_forward(
    params: Dict,
    stacked_layers: Dict,
    input_ids,
    config: ModelConfig,
    mesh: Mesh,
    num_microbatches: int,
    *,
    padding_mask=None,
    compute_dtype=jnp.bfloat16,
    remat_blocks: bool = True,
    output_hidden: bool = False,
    return_aux: bool = False,
    attention_impl: str = "xla",
):
    """Pipelined forward: logits for ``input_ids [M * mb, seq]``.

    ``params`` holds the non-pipelined leaves (embedding, final norm, lm_head
    if untied), replicated; ``stacked_layers`` are the transformer blocks
    stacked [L, ...] and sharded over ``pipe``. ``padding_mask [M*mb, seq]``
    (1 = real token) travels the schedule alongside each microbatch.

    MoE models work too: each stage accumulates its layers' router aux loss
    in the scan carry, bubble ticks are masked out, and the psum over the
    pipe axis yields the total. With ``return_aux=True`` the result is
    ``(out, aux)`` where aux is the layer-SUM averaged over microbatches —
    the same scale ``models/transformer.forward`` returns per microbatch.
    Expert parallelism composes: the schedule's shard_map is manual only
    over pipe + dp axes, so expert-sharded stacked leaves
    ([L, E, in, out] -> P("pipe", "expert", ...)) keep EP inside each stage
    (GSPMD partitions the dispatch/combine einsums over ``expert``).
    """
    S = mesh.shape["pipe"]
    M = num_microbatches
    # 3D input [M, mb, seq] keeps the microbatch dims through the whole
    # computation (loss included) — the sharded-trainer path, where flattening
    # would mix the pipe-sharded M dim into the dp-sharded row dim and force
    # GSPMD resharding of the batch. 2D input [M * mb, seq] is the
    # building-block API (parity tests vs the flat forward).
    micro_dims = input_ids.ndim == 3
    if micro_dims:
        if input_ids.shape[0] != M:
            raise ValueError(
                f"leading dim {input_ids.shape[0]} != num_microbatches {M}"
            )
        _, mb, seq = input_ids.shape
        ids = input_ids
    else:
        B, seq = input_ids.shape
        if B % M:
            raise ValueError(f"batch {B} not divisible by {M} microbatches")
        mb = B // M
        ids = input_ids.reshape(M, mb, seq)  # token ids, NOT embeddings: 4
        # bytes per position instead of 2*h — the schedule's input stays tiny
    L_local = config.num_layers // S

    embed = params["model"]["embed_tokens"]["weight"].astype(compute_dtype)
    if padding_mask is None:
        pm = jnp.ones((M, mb, seq), jnp.float32)
    else:
        pm = padding_mask if micro_dims else padding_mask.reshape(M, mb, seq)
    # [1, seq]: broadcasts over however many microbatch rows a device holds
    # (the mb dim shards over data/fsdp inside the shard_map)
    positions = jnp.arange(seq, dtype=jnp.int32)[None]
    cos, sin = rope_cos_sin(
        positions, config.resolved_head_dim, config.rope_theta, config=config
    )
    # Per-layer RoPE flags as DATA: the layer scan compiles one block body,
    # and NoPE-interleaved models (SmolLM3) select rope/no-rope per layer.
    # Uniform patterns (every preset except NoPE ones) skip the
    # rotate-then-select and keep the static branch.
    flags_list = [config.uses_rope(i) for i in range(config.num_layers)]
    uniform_rope = all(flags_list) or not any(flags_list)
    rope_flags = jnp.asarray(flags_list, jnp.bool_)

    # pipe x ring composition: a live seq axis + attention_impl="ring" makes
    # the schedule manual over "seq" too; each device holds a sequence CHUNK
    # and the stage compute calls the LOCAL ring kernel ("ring_manual" in
    # ops/attention.py), rotating K/V over the seq axis per layer.
    seq_parallel = (
        attention_impl in ("ring", "ulysses") and mesh.shape.get("seq", 1) > 1
    )
    if seq_parallel and config.num_experts > 0:
        raise ValueError(
            f"pipe x {attention_impl} does not compose with MoE: inside the "
            "manual-seq schedule the router would see per-chunk token "
            "populations, changing capacity semantics"
        )
    if seq_parallel and seq % mesh.shape["seq"]:
        raise ValueError(
            f"seq {seq} not divisible by the seq axis ({mesh.shape['seq']})"
        )
    if (
        attention_impl == "ulysses"
        and seq_parallel
        and config.num_kv_heads % mesh.shape["seq"]
    ):
        raise ValueError(
            f"ulysses needs kv heads ({config.num_kv_heads}) divisible by "
            f"the seq axis ({mesh.shape['seq']})"
        )
    stage_impl = f"{attention_impl}_manual" if seq_parallel else "xla"

    def run_stage(stage_layers, x, mask, stage_flags, cos_l, sin_l):
        """Scan my L_local blocks over x [mb, seq_local, h]."""

        def one_block(carry, args):
            h, aux = carry
            layer_params, flag = args
            h, _, layer_aux = _block(
                layer_params, h, cos_l, sin_l, mask, None, None, None, 0,
                config=config, layer_idx=0, attention_impl=stage_impl,
                compute_dtype=compute_dtype,
                mesh=mesh if seq_parallel else None,
                rope_flag=None if uniform_rope else flag,
            )
            return (h, aux + layer_aux), None

        body = jax.checkpoint(one_block) if remat_blocks else one_block
        (x, aux), _ = jax.lax.scan(body, (x, jnp.float32(0.0)), (stage_layers, stage_flags))
        return x, aux

    def spmd(stacked_local, embed_local, ids_local, pm_local, flags_local):
        # stacked_local: this stage's layers [L_local, ...]; ids_local/
        # pm_local: this device's microbatch COLUMN of token ids + padding
        # masks ([M, mb_local, seq] — the mb dim shards over data/fsdp, so
        # the pipe axis composes with data parallelism); embed_local: the
        # embedding table (replicated, it is a param).
        s = jax.lax.axis_index("pipe")
        T = M + S - 1
        h_dim = embed_local.shape[-1]
        mb_local = ids_local.shape[1]
        seq_local = ids_local.shape[2]
        if seq_parallel:
            # my sequence chunk's RoPE tables (cos/sin enter the manual
            # context replicated at full length; positions are global)
            s_off = jax.lax.axis_index("seq") * seq_local
            cos_l = jax.lax.dynamic_slice_in_dim(cos, s_off, seq_local, axis=1)
            sin_l = jax.lax.dynamic_slice_in_dim(sin, s_off, seq_local, axis=1)
        else:
            cos_l, sin_l = cos, sin

        def tick(carry, t):
            buf, aux_sum = carry  # [mb_local, seq, h] activation at my stage
            m = t - s    # microbatch index my stage works on this tick
            m_safe = jnp.clip(m, 0, M - 1)
            # stage 0 embeds its own microbatch; others use the received
            # buffer. lax.cond (not where) so stages > 0 skip the [mb, seq, h]
            # embedding gather at runtime — legal here because neither branch
            # holds a collective.
            my_ids = jax.lax.dynamic_index_in_dim(
                ids_local, jnp.clip(t, 0, M - 1), axis=0, keepdims=False
            )
            x_in = jax.lax.cond(
                s == 0,
                lambda: embed_local[my_ids].astype(buf.dtype),
                lambda: buf,
            )
            # my microbatch's padding mask rides the same timetable
            mask = jax.lax.dynamic_index_in_dim(pm_local, m_safe, axis=0, keepdims=False)
            y, aux_tick = run_stage(stacked_local, x_in, mask, flags_local, cos_l, sin_l)
            # mask bubble ticks so garbage never enters the ring (or the aux)
            valid = (m >= 0) & (m < M)
            y = jnp.where(valid, y, jnp.zeros_like(y))
            aux_sum = aux_sum + jnp.where(valid, aux_tick, 0.0)
            # pass to the next stage (last stage's output falls off the end)
            y_next = jax.lax.ppermute(
                y, "pipe", [(i, (i + 1) % S) for i in range(S)]
            )
            # last stage emits microbatch m_out = t - (S - 1)
            out = jnp.where(s == S - 1, y, jnp.zeros_like(y))
            return (y_next, aux_sum), out

        (_, aux_local), outs = jax.lax.scan(
            tick,
            (jnp.zeros((mb_local, seq_local, h_dim), compute_dtype), jnp.float32(0.0)),
            jnp.arange(T),
        )
        # total router aux over every (stage, microbatch), averaged over
        # microbatches -> the per-microbatch layer-sum scale forward() uses.
        # With the mb dim sharded, each dp column saw different rows: pmean
        # over the dp axes makes the scalar truly replicated.
        aux = jax.lax.psum(aux_local, "pipe") / M
        if dp_axes:
            aux = jax.lax.pmean(aux, dp_axes)
        # outs [T, mb, seq, h]: last stage's real outputs live at ticks
        # t = m + S - 1; drop the S-1 bubble rows first so the collective
        # moves only real data. When M divides S-ways, reduce-scatter leaves
        # each stage 1/S of the output (sharded over pipe) instead of a full
        # all-reduce copy per stage.
        outs = outs[S - 1 :]
        if M % S == 0:
            return (
                jax.lax.psum_scatter(outs, "pipe", scatter_dimension=0, tiled=True),
                aux,
            )
        return jax.lax.psum(outs, "pipe"), aux

    # the microbatch dim shards over any live data-parallel axes (pipe + dp
    # composition); meshes without those axes (unit tests) stay replicated
    dp_axes = tuple(
        a for a in ("data", "fsdp") if a in mesh.shape and mesh.shape[a] > 1
    )
    mb_spec = dp_axes if dp_axes else None
    seq_spec = "seq" if seq_parallel else None
    out_spec = (
        P("pipe", mb_spec, seq_spec) if M % S == 0 else P(None, mb_spec, seq_spec)
    )
    # Manual only over the axes the schedule itself communicates on (pipe
    # ppermute/psum + the dp pmean); every other axis — EXPERT above all —
    # stays automatic, so stacked MoE leaves sharded [L->pipe, E->expert,...]
    # keep their expert-dim sharding inside the stage compute and GSPMD
    # partitions the dispatch/combine einsums over the expert axis exactly as
    # on a flat mesh (pipe x EP composition).
    manual_axes = {"pipe", *dp_axes} | ({"seq"} if seq_parallel else set())
    outs, aux = shard_map(
        spmd,
        mesh=mesh,
        in_specs=(
            P("pipe"), P(),
            P(None, mb_spec, seq_spec), P(None, mb_spec, seq_spec), P("pipe"),
        ),
        out_specs=(out_spec, P()),
        axis_names=manual_axes,
        check_vma=False,
    )(stacked_layers, embed, ids, pm, rope_flags)

    # [M, mb, seq, h] -> final norm (+ unembed unless the caller chunks the
    # loss; same code path as the plain forward for exact parity). With
    # micro_dims the [M, mb, ...] layout survives to the caller so the M dim
    # stays cleanly pipe-sharded all the way into the loss.
    h = outs if micro_dims else outs.reshape(M * mb, seq, -1)
    h = rms_norm(
        h,
        params["model"]["norm"]["weight"],
        config.rms_norm_eps,
        zero_centered=config.zero_centered_norm,
    )
    if output_hidden:
        out = h.astype(compute_dtype)
    else:
        out = unembed(params, h, config, compute_dtype=compute_dtype, logits_dtype=jnp.float32)
    return (out, aux) if return_aux else out


def pipeline_loss_fn(
    params: Dict,
    stacked_layers: Dict,
    batch: Dict,
    config: ModelConfig,
    mesh: Mesh,
    num_microbatches: int,
    compute_dtype=jnp.bfloat16,
    loss_chunk_size=None,
    include_router_aux: bool = True,
    attention_impl: str = "xla",
):
    """Masked next-token CE through the pipeline (same objective as
    train/step.py's make_loss_fn, including the chunked large-vocab path and
    the MoE router aux term at the same layer-mean scale).
    Differentiable: jax.grad through this yields the reverse-schedule
    backward pipeline automatically.

    Batch arrays may be [B, seq] (building-block API) or [M, mb, seq]
    (trainer path — keeps the pipe-sharded M dim separate from the
    dp-sharded mb dim so no array ever needs a cross-axis reshard)."""
    ids = batch["input_ids"]
    micro_dims = ids.ndim == 3
    targets = ids[..., 1:]
    mask = batch["loss_mask"][..., 1:].astype(jnp.float32)
    tokens = jnp.maximum(mask.sum(), 1.0)
    want_aux = include_router_aux and config.num_experts > 0

    def add_aux(loss, aux):
        if not want_aux:
            return loss
        return loss + config.router_aux_coef * aux / config.num_layers

    if loss_chunk_size is not None:
        # never materialize [B, seq, vocab] logits (128k-vocab models):
        # unembed chunk-by-chunk exactly like train/step.py
        from llm_fine_tune_distributed_tpu.train.step import chunked_ce_sum

        hidden, aux = pipeline_forward(
            params, stacked_layers, ids, config, mesh,
            num_microbatches, padding_mask=batch.get("attention_mask"),
            compute_dtype=compute_dtype, output_hidden=True, return_aux=True,
            attention_impl=attention_impl,
        )
        if micro_dims:
            # one chunked-CE pass per microbatch (lax.map keeps a single
            # compiled body and one [mb, chunk, vocab] tile live at a time)
            ce_sum = jax.lax.map(
                lambda args: chunked_ce_sum(
                    params, args[0][:, :-1], args[1], args[2], config,
                    loss_chunk_size, compute_dtype,
                ),
                (hidden, targets, mask),
            ).sum()
        else:
            ce_sum = chunked_ce_sum(
                params, hidden[:, :-1], targets, mask, config, loss_chunk_size,
                compute_dtype,
            )
        return add_aux(ce_sum / tokens, aux)
    logits, aux = pipeline_forward(
        params, stacked_layers, ids, config, mesh,
        num_microbatches, padding_mask=batch.get("attention_mask"),
        compute_dtype=compute_dtype, return_aux=True,
        attention_impl=attention_impl,
    )
    ce = optax.softmax_cross_entropy_with_integer_labels(logits[..., :-1, :], targets)
    return add_aux((ce * mask).sum() / tokens, aux)


# ---------------------------------------------------------------------------
# trainer wiring: stacked flat-state representation + step builders
# ---------------------------------------------------------------------------

# Flat state keys for the stacked transformer blocks live under this marker
# ("model/layers/@stacked/self_attn/q_proj/kernel" -> one [L, h, qd] leaf).
STACKED_PREFIX = "model/layers/@stacked/"
_LAYER_KEY = re.compile(r"^model/layers/(\d+)/(.+)$")


def stack_flat_layer_leaves(flat: Dict, num_layers: int) -> Dict:
    """Per-layer flat leaves -> one stacked [num_layers, ...] leaf each.

    The trainer's flat state dicts keep their non-layer leaves (embedding,
    final norm, lm_head) untouched; every ``model/layers/<i>/<rest>`` group
    must be present for all ``num_layers`` (uniform architectures only —
    which every preset is)."""
    groups: Dict[str, Dict[int, jnp.ndarray]] = {}
    out = {}
    for k, v in flat.items():
        m = _LAYER_KEY.match(k)
        if m is None:
            out[k] = v
        else:
            groups.setdefault(m.group(2), {})[int(m.group(1))] = v
    for rest, by_layer in groups.items():
        if len(by_layer) != num_layers:
            raise ValueError(
                f"layer leaf {rest!r} present for {sorted(by_layer)} but the "
                f"model has {num_layers} layers"
            )
        out[STACKED_PREFIX + rest] = jnp.stack(
            [by_layer[i] for i in range(num_layers)]
        )
    return out


def unstack_flat_layer_leaves(flat: Dict) -> Dict:
    """Inverse of stack_flat_layer_leaves (host-side: used for artifact
    export and checkpoint interop with non-pipelined meshes)."""
    out = {}
    for k, v in flat.items():
        if not k.startswith(STACKED_PREFIX):
            out[k] = v
            continue
        rest = k[len(STACKED_PREFIX):]
        for i in range(v.shape[0]):
            out[f"model/layers/{i}/{rest}"] = v[i]
    return out


def split_stacked_flat(flat: Dict):
    """Merged flat params -> (rest_nested, stacked_layers_nested) for
    pipeline_forward."""
    from llm_fine_tune_distributed_tpu.utils.tree import unflatten_dict

    stacked = {
        k[len(STACKED_PREFIX):]: v
        for k, v in flat.items()
        if k.startswith(STACKED_PREFIX)
    }
    rest = {k: v for k, v in flat.items() if not k.startswith(STACKED_PREFIX)}
    return unflatten_dict(rest), unflatten_dict(stacked)


def build_pipeline_state_leaves(trainable: Dict, frozen: Dict, flat_mask: Dict, num_layers: int):
    """Stack the per-layer block leaves of a flat (trainable, frozen) state
    split and re-partition for pipe mode.

    A stacked leaf may span frozen AND trainable layers (last-N freezing), so
    any stacked group with at least one trainable layer lives in
    ``trainable`` and the per-layer freeze mask becomes the gradient/update
    mask the pipeline train step applies. Groups trainable in NO layer (LoRA
    base kernels, ``lora_scale``) stay ``frozen`` — which is what keeps the
    optimizer state at adapter size under pipe x LoRA/QLoRA, exactly like
    the flat path. Returns ``(trainable, frozen, layer_vec)``. Single source
    for the trainer and the dryrun harness."""
    merged = stack_flat_layer_leaves({**trainable, **frozen}, num_layers)

    def group_trains(stacked_key: str) -> bool:
        rest = stacked_key[len(STACKED_PREFIX):]
        return any(
            flat_mask.get(f"model/layers/{i}/{rest}", False)
            for i in range(num_layers)
        )

    new_trainable = {
        k: v
        for k, v in merged.items()
        if (group_trains(k) if k.startswith(STACKED_PREFIX) else flat_mask.get(k, False))
    }
    new_frozen = {k: v for k, v in merged.items() if k not in new_trainable}
    return new_trainable, new_frozen, layer_trainable_vector(flat_mask, num_layers)


# NF4-quantized expert leaves ([L, E, in/8, out] packed + [L, E, in/b, out]
# absmax) keep the base orientation; _validate_spec (the trainer applies it
# to every pipeline spec) drops any dim the packed shapes no longer divide.
# absmax_scale [L, G] / absmax_offset [L] fall through to plain P("pipe").
_STACKED_EXPERT = re.compile(
    r"block_sparse_moe/experts/(w1|w3|w2)(_nf4|_absmax_q|_absmax)?$"
)


def pipeline_param_spec(path: str, leaf, mesh: Mesh) -> P:
    """Sharding for the pipe-mode state: stacked block leaves shard their
    leading (layer) dim over ``pipe``; stacked MoE expert weights
    ([L, E, in, out]) additionally shard the expert dim over ``expert`` and
    their in/out dims like the flat rules (pipe x EP — the memory win both
    axes exist for on mixtral-class models). Everything else (embedding,
    norms, lm_head) is replicated — those leaves enter the schedule's
    shard_map with replicated in_specs. (FSDP-within-stage is a possible
    refinement; the at-rest cost of replicating non-block leaves is the
    embedding only.)"""
    if path.startswith(STACKED_PREFIX):
        m = _STACKED_EXPERT.search(path)
        if m is not None and "expert" in mesh.shape:
            # same orientation as parallel/sharding._MATRIX_RULES, shifted
            # one dim right for the leading layer axis — but only AUTO axes
            # (expert, tensor) may shard here: fsdp/data are MANUAL inside
            # the schedule's shard_map, and a manual-axis sharding not
            # described by the P("pipe") in_spec would just be gathered away
            # at shard_map entry
            if m.group(1) == "w2":
                return P("pipe", "expert", "tensor", None)
            return P("pipe", "expert", None, "tensor")
        return P("pipe")
    return P()


def layer_trainable_vector(flat_mask: Dict, num_layers: int):
    """[num_layers] 0/1 vector: layer i is trainable iff any of its leaves
    is trainable under the freezing policy (parallel/freeze.py). Applied as
    a gradient/update mask on the stacked leaves, which keeps optax's
    whole-leaf masking semantics while freezing layer slices."""
    import numpy as np

    vec = np.zeros((num_layers,), np.float32)
    for k, v in flat_mask.items():
        m = _LAYER_KEY.match(k)
        if m is not None and v:
            vec[int(m.group(1))] = 1.0
    return jnp.asarray(vec)


def _mask_stacked(tree: Dict, layer_vec):
    """Multiply stacked-leaf entries by the per-layer mask (broadcast over
    the trailing dims); non-stacked leaves pass through."""
    out = {}
    for k, g in tree.items():
        if k.startswith(STACKED_PREFIX):
            vec = layer_vec.reshape((-1,) + (1,) * (g.ndim - 1))
            g = g * vec.astype(g.dtype)
        out[k] = g
    return out


def build_pipeline_train_step(model_config, train_config, optimizer, mesh, layer_vec):
    """train_step(state, batch) -> (state, metrics) over the pipe mesh axis.

    ``batch`` arrays are [grad_accum, global_batch, seq] (the standard loader
    layout); the accumulation dim becomes the pipeline's microbatch stream
    (M = grad_accum), so one optimizer step is ONE schedule of
    M + S - 1 ticks — accumulation and pipelining are the same loop.

    Loss semantics: global token-mean over the whole per-step batch (the flat
    path computes the mean of per-microbatch means; the two agree exactly
    when microbatches carry equal token counts, and to < 1e-3 relative on
    this dataset's padding distribution).

    Freezing: grads AND updates on stacked leaves are masked by
    ``layer_vec`` — masking updates too keeps AdamW's decoupled weight decay
    off frozen layers."""
    from llm_fine_tune_distributed_tpu.config import str_to_dtype

    compute_dtype = str_to_dtype(train_config.compute_dtype)
    M = train_config.gradient_accumulation_steps
    chunk = train_config.loss_chunk_size

    def loss_fn(trainable, frozen, flat_batch):
        params, stacked_layers = split_stacked_flat({**trainable, **frozen})
        return pipeline_loss_fn(
            params, stacked_layers, flat_batch, model_config, mesh, M,
            compute_dtype=compute_dtype, loss_chunk_size=chunk,
            attention_impl=train_config.attention_impl,
        )

    def train_step(state, batch):
        # batch arrays stay [accum, B, seq]: microbatch m of the schedule is
        # exactly accumulation slice m, and the (pipe-sharded) accum dim is
        # never reshaped into the (dp-sharded) batch dim
        loss, grads = jax.value_and_grad(loss_fn)(
            state.trainable, state.frozen, batch
        )
        grads = _mask_stacked(grads, layer_vec)
        grad_norm = optax.global_norm(grads)
        updates, new_opt_state = optimizer.update(
            grads, state.opt_state, state.trainable
        )
        updates = _mask_stacked(updates, layer_vec)
        new_trainable = optax.apply_updates(state.trainable, updates)
        new_state = state.replace(
            step=state.step + 1,
            trainable=new_trainable,
            opt_state=new_opt_state,
        )
        return new_state, {"loss": loss, "grad_norm": grad_norm}

    return train_step


def eval_microbatches(mesh: Mesh, batch_rows: int) -> int:
    """Microbatch count for an eval schedule over ``batch_rows`` rows.

    M=S fills the schedule when legal; the schedule's shard_map shards the
    microbatch dim over live dp axes, so rows/M must stay divisible by them.
    Degenerate M=1 keeps any batch size valid (full bubble, correct result).
    Shared by the SFT and DPO pipe eval builders so the rule cannot drift."""
    S = mesh.shape["pipe"]
    dp = 1
    for ax in ("data", "fsdp"):
        if ax in mesh.shape:
            dp *= mesh.shape[ax]
    return S if batch_rows % S == 0 and (batch_rows // S) % dp == 0 else 1


def build_pipeline_eval_step(model_config, train_config, mesh):
    """eval_step(state, batch[b, s]) -> (ce_sum, token_count), matching
    train/step.build_eval_step's contract (pure CE, no router aux)."""
    from llm_fine_tune_distributed_tpu.config import str_to_dtype

    compute_dtype = str_to_dtype(train_config.compute_dtype)
    chunk = train_config.loss_chunk_size

    def eval_step(state, batch):
        params, stacked_layers = split_stacked_flat(
            {**state.trainable, **state.frozen}
        )
        b = batch["input_ids"].shape[0]
        m = eval_microbatches(mesh, b)
        micro_batch = {
            k: v.reshape((m, b // m) + v.shape[1:]) for k, v in batch.items()
        }
        loss = pipeline_loss_fn(
            params, stacked_layers, micro_batch, model_config, mesh, m,
            compute_dtype=compute_dtype, loss_chunk_size=chunk,
            include_router_aux=False,
            attention_impl=train_config.attention_impl,
        )
        tokens = jnp.maximum(batch["loss_mask"][:, 1:].astype(jnp.float32).sum(), 1.0)
        return loss * tokens, tokens

    return eval_step


def bubble_fraction(num_microbatches: int, num_stages: int) -> float:
    """Idle fraction of the GPipe timetable: (S-1)/(M+S-1) per pass (the
    backward pass, being the scan's exact transpose, has the same fraction).
    The trainer warns when grad_accum makes this large."""
    return (num_stages - 1) / (num_microbatches + num_stages - 1)
