"""Pipeline parallelism (GPipe schedule) over a ``pipe`` mesh axis.

The reference has no pipeline parallelism (SURVEY.md §2.4 marks it absent;
the MPMD-pipeline paper in PAPERS.md is its design pointer). This is the
TPU-native expression: not MPMD processes with send/recv, but ONE SPMD
program over a ``pipe`` mesh axis where

- each stage device holds a contiguous slice of the transformer blocks
  (stacked layer-major, so the per-stage compute is a ``lax.scan`` over its
  own layers — one compiled block body regardless of depth);
- activations move stage-to-stage with ``jax.lax.ppermute`` (ICI
  neighbor-exchange, the cheapest collective on a TPU torus);
- the GPipe timetable is a ``lax.scan`` over ``M + S - 1`` ticks: stage ``s``
  processes microbatch ``t - s`` at tick ``t`` (bubble ticks compute on
  zeros and are masked out);
- the BACKWARD pipeline is not hand-written at all: ``jax.grad`` through the
  scan + ppermute yields the reversed schedule automatically — the
  correctness-by-construction benefit of a functional pipeline.

Embedding/unembedding and the final norm live outside the pipelined blocks:
embedding is applied to all microbatches up front (host of stage 0 data),
the last stage's outputs are collected, and the loss closes over them. The
embedding table is replicated across stages (it is ~3% of SmolLM3's params).

Scope: first-class building block with exact-parity tests against the plain
``forward`` path (tests/test_pipeline.py). Not yet wired into SFTTrainer's
mesh config — TP/FSDP/SP cover the BASELINE.json configs; the pipeline axis
targets models whose layer count, not width, is the scaling constraint.
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax import shard_map

import optax

from llm_fine_tune_distributed_tpu.config import ModelConfig
from llm_fine_tune_distributed_tpu.models.transformer import _block, unembed
from llm_fine_tune_distributed_tpu.ops.norms import rms_norm
from llm_fine_tune_distributed_tpu.ops.rope import rope_cos_sin


def stack_stage_params(params: Dict, config: ModelConfig, num_stages: int) -> Dict:
    """Layer dicts -> leaves stacked [num_layers, ...] (layer-major).

    Sharding the leading dim over ``pipe`` gives each stage its contiguous
    block of layers; within a stage the compute scans over the local slice.
    """
    if config.num_layers % num_stages:
        raise ValueError(
            f"{config.num_layers} layers not divisible by {num_stages} stages"
        )
    layers = params["model"]["layers"]
    return jax.tree.map(
        lambda *leaves: jnp.stack(leaves),
        *[layers[str(i)] for i in range(config.num_layers)],
    )


def stage_sharding(mesh: Mesh):
    """Stacked layer leaves: leading (layer) dim sharded over ``pipe``."""
    return NamedSharding(mesh, P("pipe"))


def pipeline_forward(
    params: Dict,
    stacked_layers: Dict,
    input_ids,
    config: ModelConfig,
    mesh: Mesh,
    num_microbatches: int,
    *,
    padding_mask=None,
    compute_dtype=jnp.bfloat16,
    remat_blocks: bool = True,
):
    """Pipelined forward: logits for ``input_ids [M * mb, seq]``.

    ``params`` holds the non-pipelined leaves (embedding, final norm, lm_head
    if untied), replicated; ``stacked_layers`` are the transformer blocks
    stacked [L, ...] and sharded over ``pipe``. ``padding_mask [M*mb, seq]``
    (1 = real token) travels the schedule alongside each microbatch.
    """
    if config.no_rope_layers and not all(config.no_rope_layers):
        raise NotImplementedError(
            "pipeline v1 requires a uniform RoPE pattern (the per-stage layer "
            "scan compiles ONE block body; NoPE-interleaved models need "
            "per-layer branching)"
        )
    S = mesh.shape["pipe"]
    M = num_microbatches
    B, seq = input_ids.shape
    if B % M:
        raise ValueError(f"batch {B} not divisible by {M} microbatches")
    mb = B // M
    L_local = config.num_layers // S

    embed = params["model"]["embed_tokens"]["weight"].astype(compute_dtype)
    x0 = embed[input_ids].reshape(M, mb, seq, -1)  # all microbatches, embedded
    if padding_mask is None:
        padding_mask = jnp.ones((B, seq), jnp.float32)
    pm = padding_mask.reshape(M, mb, seq)
    positions = jnp.broadcast_to(jnp.arange(seq, dtype=jnp.int32)[None], (mb, seq))
    cos, sin = rope_cos_sin(positions, config.resolved_head_dim, config.rope_theta)

    def run_stage(stage_layers, x, mask):
        """Scan my L_local blocks over x [mb, seq, h]."""

        def one_block(h, layer_params):
            h, _ = _block(
                layer_params, h, cos, sin, mask, None, None, None, 0,
                config=config, layer_idx=0, attention_impl="xla",
                compute_dtype=compute_dtype,
            )
            return h, None

        body = jax.checkpoint(one_block) if remat_blocks else one_block
        x, _ = jax.lax.scan(body, x, stage_layers)
        return x

    def spmd(stacked_local, x0_local, pm_local):
        # stacked_local: this stage's layers [L_local, ...]; x0_local/pm_local:
        # the full embedded microbatch stack + padding masks (replicated).
        s = jax.lax.axis_index("pipe")
        T = M + S - 1

        def tick(carry, t):
            buf = carry  # [mb, seq, h] activation arriving at my stage
            m = t - s    # microbatch index my stage works on this tick
            m_safe = jnp.clip(m, 0, M - 1)
            # stage 0 reads its own input; others use the received buffer
            x_in = jnp.where(
                s == 0,
                jax.lax.dynamic_index_in_dim(
                    x0_local, jnp.clip(t, 0, M - 1), axis=0, keepdims=False
                ),
                buf,
            )
            # my microbatch's padding mask rides the same timetable
            mask = jax.lax.dynamic_index_in_dim(pm_local, m_safe, axis=0, keepdims=False)
            y = run_stage(stacked_local, x_in, mask)
            # mask bubble ticks so garbage never enters the ring
            valid = (m >= 0) & (m < M)
            y = jnp.where(valid, y, jnp.zeros_like(y))
            # pass to the next stage (last stage's output falls off the end)
            y_next = jax.lax.ppermute(
                y, "pipe", [(i, (i + 1) % S) for i in range(S)]
            )
            # last stage emits microbatch m_out = t - (S - 1)
            out = jnp.where(s == S - 1, y, jnp.zeros_like(y))
            return y_next, out

        _, outs = jax.lax.scan(tick, jnp.zeros((mb, seq, x0_local.shape[-1]),
                                               x0_local.dtype), jnp.arange(T))
        # outs [T, mb, seq, h]: last stage's real outputs live at ticks
        # t = m + S - 1; drop the S-1 bubble rows BEFORE the psum so the
        # all-reduce (and its transpose on backward) moves only real data.
        outs = jax.lax.psum(outs[S - 1 :], "pipe")
        return outs

    outs = shard_map(
        spmd,
        mesh=mesh,
        in_specs=(P("pipe"), P(), P()),
        out_specs=P(),
        check_vma=False,
    )(stacked_layers, x0, pm)

    # [M, mb, seq, h] -> final norm + unembed (replicated, off-pipeline;
    # same code path as the plain forward for exact parity)
    h = outs.reshape(B, seq, -1)
    h = rms_norm(h, params["model"]["norm"]["weight"], config.rms_norm_eps)
    return unembed(params, h, config, compute_dtype=compute_dtype, logits_dtype=jnp.float32)


def pipeline_loss_fn(
    params: Dict,
    stacked_layers: Dict,
    batch: Dict,
    config: ModelConfig,
    mesh: Mesh,
    num_microbatches: int,
    compute_dtype=jnp.bfloat16,
):
    """Masked next-token CE through the pipeline (same objective as
    train/step.py's make_loss_fn). Differentiable: jax.grad through this
    yields the reverse-schedule backward pipeline automatically."""
    logits = pipeline_forward(
        params, stacked_layers, batch["input_ids"], config, mesh,
        num_microbatches, padding_mask=batch.get("attention_mask"),
        compute_dtype=compute_dtype,
    )
    targets = batch["input_ids"][:, 1:]
    mask = batch["loss_mask"][:, 1:].astype(jnp.float32)
    ce = optax.softmax_cross_entropy_with_integer_labels(logits[:, :-1], targets)
    return (ce * mask).sum() / jnp.maximum(mask.sum(), 1.0)
