"""Partial-layer freezing policy.

Reference behavior (C5, ``training.py:113-149``): freeze every param, then
unfreeze the LAST 2 transformer layers + lm_head, yielding 418.9M/3.075B =
13.62% trainable on SmolLM3-3B (``claude.md:241-245``). On error the reference
falls back to full fine-tuning (``training.py:143-145``).

TPU-native expression: a boolean mask pytree consumed by
``optax.masked`` / ``multi_transform`` so frozen params get no optimizer state
(the memory win) and their gradients are never materialized into updates.
With tied embeddings, "lm_head" trainable means the embedding matrix is
trainable (same tensor — matching what torch does for tied weights).
"""

from __future__ import annotations

import re
from typing import Callable

from llm_fine_tune_distributed_tpu.config import ModelConfig, TrainConfig
from llm_fine_tune_distributed_tpu.utils.tree import (
    count_params,
    count_params_where,
    map_with_path,
    tree_paths,
)

_LAYER_RE = re.compile(r"model/layers/(\d+)/")


def trainable_predicate(config: ModelConfig, train: TrainConfig) -> Callable[[str], bool]:
    strategy = train.freeze_strategy
    if strategy == "none":
        return lambda path: True
    if strategy in ("lora", "qlora"):
        # Only adapter matrices train; base weights AND the (constant)
        # alpha/r scale stay frozen. For qlora the frozen base is additionally
        # NF4-quantized after the split (parallel/qlora.py).
        return lambda path: path.endswith(("lora_a", "lora_b"))
    if strategy == "last_n_and_head":
        cutoff = config.num_layers - train.unfreeze_last_n_layers

        def pred(path: str) -> bool:
            m = _LAYER_RE.search(path)
            if m:
                return int(m.group(1)) >= cutoff
            if "lm_head" in path:
                return True
            if config.tie_word_embeddings and "embed_tokens" in path:
                return True  # tied: the lm_head IS the embedding matrix
            return False  # final norm + embeddings(untied) stay frozen

        return pred
    raise ValueError(f"unknown freeze_strategy {strategy!r}")


def trainable_mask(params, config: ModelConfig, train: TrainConfig):
    """Boolean pytree: True = trainable."""
    pred = trainable_predicate(config, train)
    return map_with_path(lambda path, leaf: pred(path), params)


def frozen_trunk_boundary(flat_mask: dict, num_layers: int) -> int:
    """Number of leading *entirely frozen* transformer layers — the trunk.

    ``flat_mask`` is the flattened trainable mask (path -> bool). Returns the
    earliest layer index with ANY trainable leaf; layers ``[0, boundary)``
    form the frozen trunk eligible for the int8 fast path
    (``TrainConfig.frozen_compute``). 0 means "no trunk":

    - ``last_n_and_head`` (unfreeze_last_n_layers=n) -> ``num_layers - n``;
    - lora/qlora (trainable lora_a/lora_b in every layer) -> 0;
    - ``none`` (full fine-tune) -> 0.

    Note the boundary is *layer*-based: a trainable non-layer leaf (tied
    ``embed_tokens``/``lm_head``) does not shrink the trunk. Under int8
    frozen-compute the tied embedding's gradient contribution *through the
    trunk's input lookup* is dropped by the boundary ``stop_gradient`` — a
    documented approximation (docs/architecture.md "Training fast path");
    the lm_head-side gradient of the tied matrix is unaffected.
    """
    boundary = num_layers
    for path, trainable in flat_mask.items():
        if not trainable:
            continue
        m = _LAYER_RE.search(path)
        if m:
            boundary = min(boundary, int(m.group(1)))
            if boundary == 0:
                break
    return boundary


def quantize_trunk_int8(frozen: dict, boundary: int):
    """Quantize the projection kernels of the frozen trunk (layers
    ``[0, boundary)``) to the serving int8 sibling layout: each 2-D
    ``.../kernel`` leaf is replaced by ``kernel_int8`` codes +
    ``kernel_int8_scale`` per-output-channel f32 scales (ops/int8.py).
    Norms, embeddings, and the MoE router gate pass through unchanged —
    they run bf16 in the trunk too. Quantize from full precision (before
    any bf16 cast) so the 8-bit rounding is the only rounding.

    Returns ``(new_flat, n_quantized)``. Shared by the trainer
    (_prepare_state) and bench.py so the two can never disagree on which
    leaves the w8a8 fast path covers.
    """
    from llm_fine_tune_distributed_tpu.ops.int8 import INT8_SUFFIXES, quantize_int8

    quantized = {}
    n_quant = 0
    for k, v in frozen.items():
        m = _LAYER_RE.search(k)
        if (
            m is not None
            and int(m.group(1)) < boundary
            and k.endswith("/kernel")
            and not k.endswith("block_sparse_moe/gate/kernel")
            and getattr(v, "ndim", 0) == 2
        ):
            q = quantize_int8(v)
            for suffix in INT8_SUFFIXES:
                quantized[f"{k}_{suffix}"] = q[suffix]
            n_quant += 1
        else:
            quantized[k] = v
    return quantized, n_quant


def describe_trainable(params, mask) -> dict:
    """Trainable-parameter report (the reference prints this at
    ``training.py:147-149``; values recorded into training_summary.json at
    ``training.py:323-326``)."""
    total = count_params(params)
    flat_mask = {p: m for p, m in tree_paths(mask)}
    trainable = count_params_where(params, lambda p: flat_mask[p])
    return {
        "total_parameters": total,
        "trainable_parameters": trainable,
        "trainable_percent": round(100.0 * trainable / total, 2),
    }
