"""Ulysses-style all-to-all sequence/context parallelism.

The second context-parallel strategy beside ring attention
(parallel/ring_attention.py). The reference has no long-context path at all
(SURVEY.md §5.7 — fixed 1024-token context, reference ``training.py:282``);
this module and the ring are the TPU-native designs that subsume it.

Where the ring keeps queries resident and rotates K/V chunks around the ICI
ring, Ulysses (DeepSpeed-Ulysses, arXiv:2309.14509 — pattern reference only)
re-partitions with two ``all_to_all`` collectives:

  [batch, seq/N, heads, dim]  --all_to_all-->  [batch, seq, heads/N, dim]
      (sequence-sharded)                         (head-sharded)

so every device runs an ordinary *full-sequence* attention over its subset of
heads — which means the Pallas flash kernel (ops/flash_attention.py) runs
unmodified on the head-sharded view, something the ring's online-softmax
recurrence cannot reuse. After attention, the inverse all_to_all restores the
sequence sharding for the (sequence-sharded) o_proj matmul.

Trade-offs vs the ring, honestly:
- Ulysses moves O(seq * heads * dim / N) bytes twice per layer regardless of
  masking; the ring moves K/V (kv_heads, typically ≤ heads/4 under GQA) N-1
  times but cannot use the flash kernel. On ICI both are cheap; Ulysses wins
  when the flash kernel's VMEM blocking beats XLA attention (long seq), the
  ring wins when kv_heads << heads and seq is extreme.
- Ulysses parallelism degree is capped by ``num_kv_heads`` (each device needs
  ≥1 KV head); the ring is capped only by sequence length.

Gradients flow through ``all_to_all`` natively (its transpose is the inverse
all_to_all), so the same code path trains.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P


def _a2a_seq_to_heads(x, axis_name: str):
    """[b, seq/N, h, d] (seq-sharded) -> [b, seq, h/N, d] (head-sharded)."""
    return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1, tiled=True)


def _a2a_heads_to_seq(x, axis_name: str):
    """[b, seq, h/N, d] (head-sharded) -> [b, seq/N, h, d] (seq-sharded)."""
    return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2, tiled=True)


def _local_ulysses_attention(
    q, k, v, padding_mask, segment_ids=None, *, axis_name: str, causal: bool,
    attention_impl: str
):
    """Runs on ONE device's shards inside shard_map.

    q: [b, lq, h, d], k/v: [b, lq, hk, d] — this device's sequence chunk
    (lq = seq / N). padding_mask: [b, lq] (1 = real token) or None.
    segment_ids: [b, lq] packing segments or None — all-gathered like the
    padding mask (each device's full-sequence attention needs every id) and
    masked natively by the inner flash/XLA kernel.
    """
    # Re-partition: full sequence, 1/N of the heads.
    q = _a2a_seq_to_heads(q, axis_name)  # [b, s, h/N, d]
    k = _a2a_seq_to_heads(k, axis_name)  # [b, s, hk/N, d]
    v = _a2a_seq_to_heads(v, axis_name)
    if padding_mask is not None:
        # Every device needs the whole mask for its full-sequence attention.
        padding_mask = jax.lax.all_gather(
            padding_mask, axis_name, axis=1, tiled=True
        )  # [b, s]
    if segment_ids is not None:
        segment_ids = jax.lax.all_gather(
            segment_ids, axis_name, axis=1, tiled=True
        )  # [b, s]

    # Ordinary attention on the head-sharded view. The flash kernel applies
    # when shapes allow; otherwise the dispatch falls back to XLA attention.
    from llm_fine_tune_distributed_tpu.ops.attention import attention

    out = attention(
        q, k, v, impl=attention_impl, padding_mask=padding_mask,
        segment_ids=segment_ids, causal=causal
    )  # [b, s, h/N, d]

    # Restore sequence sharding for the residual stream.
    return _a2a_heads_to_seq(out, axis_name)  # [b, lq, h, d]


def ulysses_static_preconditions(
    num_heads: int, num_kv: int, mesh: Optional[Mesh], *, axis_name: str = "seq"
) -> bool:
    """The ulysses-specific static half: the all_to_all re-partition needs
    each seq-axis device to receive whole (query and KV) heads. Shared by the
    runtime predicate below and train/step.static_seq_parallel_size.
    (Post-a2a GQA grouping needs no extra check: the shared preconditions
    give heads_local % kv_local == 0, so whole groups divide alongside kv
    heads.)"""
    if mesh is None or axis_name not in mesh.shape:
        return False
    n_seq = mesh.shape[axis_name]
    tensor = mesh.shape.get("tensor", 1)
    heads_local = num_heads // max(tensor, 1)
    kv_local = num_kv // max(tensor, 1)
    return heads_local % n_seq == 0 and kv_local % n_seq == 0


def ulysses_attention_supported(
    q,
    k,
    mesh: Optional[Mesh],
    *,
    axis_name: str = "seq",
    sliding_window: Optional[int] = None,
    causal: bool = True,
) -> bool:
    """Same contract as ``ring_attention_supported``: the dispatch calls this
    with global-view shapes and falls back to XLA attention when False."""
    from llm_fine_tune_distributed_tpu.parallel.ring_attention import (
        seq_parallel_preconditions,
    )

    if not seq_parallel_preconditions(
        q, k, mesh, axis_name=axis_name, sliding_window=sliding_window, causal=causal
    ):
        return False
    return ulysses_static_preconditions(
        q.shape[2], k.shape[2], mesh, axis_name=axis_name
    )


def ulysses_attention(
    q,
    k,
    v,
    *,
    mesh: Mesh,
    axis_name: str = "seq",
    padding_mask=None,
    segment_ids=None,
    causal: bool = True,
    attention_impl: str = "flash",
):
    """Global-view entry: shard q/k/v over the mesh and run Ulysses.

    q: [batch, seq, heads, dim]; k, v: [batch, seq, kv_heads, dim];
    padding_mask: optional [batch, seq], 1 = real token; segment_ids:
    optional [batch, seq] packing segments. Layout contract matches
    ops/attention.py; call sites go through
    ``ops.attention.attention(impl="ulysses", mesh=...)``.
    """
    from llm_fine_tune_distributed_tpu.parallel.ring_attention import (
        shard_map_seq_attention,
    )

    local = partial(
        _local_ulysses_attention,
        axis_name=axis_name,
        causal=causal,
        attention_impl=attention_impl,
    )
    return shard_map_seq_attention(
        local, mesh, axis_name, q, k, v,
        padding_mask=padding_mask, segment_ids=segment_ids,
    )
