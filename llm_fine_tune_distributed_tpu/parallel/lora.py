"""LoRA adapters: init, merge, and PEFT-compatible export.

The reference ships no LoRA in code, but its external-doc article documents
the exact intended configuration — r=16, alpha=8, dropout=0.05, seven
projection targets (SURVEY.md C23; Kubeflow-Trainer article p.11) — and
BASELINE.json's 70B config requires QLoRA-style adapter training. The model
side is already wired: ``models/transformer.py:_linear`` adds
``(alpha/r) * x @ A @ B`` whenever ``lora_a``/``lora_b``/``lora_scale`` sit
beside a kernel, ``parallel/freeze.py`` trains only ``lora_*`` paths under
``freeze_strategy="lora"``, and ``parallel/sharding.py`` has adapter
sharding rules. This module is the lifecycle: create the adapter leaves,
merge them into the base weights for serving, and round-trip them as a
standalone PEFT-layout safetensors file.

TPU note: rank-16 matmuls are far below the MXU's 128x128 tile, so LoRA's
win here is optimizer-state memory (adam moments on ~0.5%% of params), not
FLOPs — same as on GPU, but the merge-for-serving path matters more because
tiny matmuls waste MXU occupancy at inference.

``lora_dropout`` is recorded in adapter_config.json for PEFT interop but not
applied during training: the jitted train step is deterministic (no dropout
RNG is threaded through the model) and at r=16 the regularization effect is
marginal for SFT-scale runs.
"""

from __future__ import annotations

import math
from typing import Dict, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from llm_fine_tune_distributed_tpu.config import TrainConfig

Params = Dict[str, object]


def _target(path: str, modules: Sequence[str]) -> bool:
    return path.endswith("/kernel") and any(f"/{m}/kernel" in f"/{path}" for m in modules)


def add_lora_params(
    params: Params,
    rng,
    *,
    rank: int = 16,
    alpha: float = 8.0,
    target_modules: Sequence[str] = (
        "q_proj", "k_proj", "v_proj", "o_proj", "gate_proj", "up_proj", "down_proj",
    ),
    dtype=jnp.float32,
) -> Params:
    """Return a copy of ``params`` with adapter leaves beside each target
    kernel. A ~ Kaiming-uniform (HF PEFT init), B = 0 so the adapted model
    starts exactly equal to the base model. Each adapter's key is
    ``fold_in(rng, crc32(path))`` — deterministic and order-independent."""
    import zlib

    def walk(node, prefix):
        if not isinstance(node, dict):
            return node
        out = {}
        for name, child in node.items():
            path = f"{prefix}/{name}" if prefix else name
            if (
                isinstance(child, dict)
                and "kernel" in child
                and _target(f"{path}/kernel", target_modules)
            ):
                kernel = child["kernel"]
                d_in, d_out = kernel.shape
                sub = jax.random.fold_in(rng, zlib.crc32(path.encode()))
                bound = math.sqrt(3.0) * math.sqrt(1.0 / d_in)  # kaiming a=sqrt(5)
                entry = dict(child)
                entry["lora_a"] = jax.random.uniform(
                    sub, (d_in, rank), dtype, minval=-bound, maxval=bound
                )
                entry["lora_b"] = jnp.zeros((rank, d_out), dtype)
                entry["lora_scale"] = jnp.asarray(alpha / rank, dtype)
                out[name] = entry
            else:
                out[name] = walk(child, path)
        return out

    return walk(params, "")


def add_lora_from_config(params: Params, rng, train: TrainConfig) -> Params:
    return add_lora_params(
        params,
        rng,
        rank=train.lora_rank,
        alpha=train.lora_alpha,
        target_modules=tuple(train.lora_target_modules),
    )


def merge_lora(params: Params) -> Params:
    """Fold adapters into the base kernels (W' = W + scale * A @ B) and drop
    the adapter leaves — the serving-time form."""

    def walk(node):
        if not isinstance(node, dict):
            return node
        if "kernel" in node and "lora_a" in node:
            out = {k: v for k, v in node.items() if not k.startswith("lora_")}
            delta = (node["lora_a"] @ node["lora_b"]) * node["lora_scale"]
            out["kernel"] = (
                node["kernel"].astype(jnp.float32) + delta.astype(jnp.float32)
            ).astype(node["kernel"].dtype)
            return out
        return {k: walk(v) for k, v in node.items()}

    return walk(params)


def strip_lora(params: Params) -> Params:
    """Remove adapter leaves without merging (back to the pristine base)."""

    def walk(node):
        if not isinstance(node, dict):
            return node
        return {k: walk(v) for k, v in node.items() if not k.startswith("lora_")}

    return walk(params)


# ---------------------------------------------------------------------------
# PEFT-layout adapter export/import (adapter_model.safetensors)
# ---------------------------------------------------------------------------


def lora_state_dict(params: Params) -> Dict[str, np.ndarray]:
    """Adapters as a PEFT-style state dict:
    ``base_model.model.<path>.lora_A.weight [r, in]`` (torch layout) etc."""
    flat: Dict[str, np.ndarray] = {}

    def walk(node, prefix):
        if not isinstance(node, dict):
            return
        if "lora_a" in node:
            base = f"base_model.model.{prefix}"
            flat[f"{base}.lora_A.weight"] = np.ascontiguousarray(np.asarray(node["lora_a"]).T)
            flat[f"{base}.lora_B.weight"] = np.ascontiguousarray(np.asarray(node["lora_b"]).T)
            return
        for k, v in node.items():
            walk(v, f"{prefix}.{k}" if prefix else k)

    walk(params, "")
    return flat


def save_lora_adapter(params: Params, path: str, train: TrainConfig) -> None:
    """Write ``adapter_model.safetensors`` + ``adapter_config.json`` (the HF
    PEFT directory layout, loadable by ``peft.PeftModel``)."""
    import json
    import os

    from safetensors.numpy import save_file

    os.makedirs(path, exist_ok=True)
    state = lora_state_dict(params)
    if not state:
        raise ValueError("params carry no LoRA adapters")
    save_file(state, os.path.join(path, "adapter_model.safetensors"), metadata={"format": "pt"})
    with open(os.path.join(path, "adapter_config.json"), "w") as f:
        json.dump(
            {
                "peft_type": "LORA",
                "r": train.lora_rank,
                "lora_alpha": train.lora_alpha,
                "lora_dropout": train.lora_dropout,
                "target_modules": list(train.lora_target_modules),
                "task_type": "CAUSAL_LM",
            },
            f,
            indent=2,
        )


def _kernel_module_names(params: Params) -> set:
    """Names of every linear module in the model (dicts holding a kernel)."""
    names = set()

    def walk(node, name):
        if not isinstance(node, dict):
            return
        if "kernel" in node:
            names.add(name)
            return
        for k, v in node.items():
            walk(v, k)

    walk(params, "")
    names.discard("")
    return names


def validate_adapter_config(acfg: dict, params: Params, path: str = "") -> None:
    """Validate an ``adapter_config.json`` against the model BEFORE any
    tensor is attached, so a mismatched adapter fails with a ValueError that
    names the offending field instead of a shape error deep inside the tree
    merge. Checks: ``r`` (positive int), ``lora_alpha`` (positive number),
    ``target_modules`` (non-empty, every name a linear module the model
    actually has)."""
    where = f" ({path})" if path else ""
    r = acfg.get("r")
    if not isinstance(r, int) or isinstance(r, bool) or r < 1:
        raise ValueError(
            f"adapter_config.json{where}: field 'r' must be a positive "
            f"integer, got {r!r}"
        )
    alpha = acfg.get("lora_alpha")
    if not isinstance(alpha, (int, float)) or isinstance(alpha, bool) or alpha <= 0:
        raise ValueError(
            f"adapter_config.json{where}: field 'lora_alpha' must be a "
            f"positive number, got {alpha!r}"
        )
    targets = acfg.get("target_modules")
    if not targets or not isinstance(targets, (list, tuple)):
        raise ValueError(
            f"adapter_config.json{where}: field 'target_modules' must be a "
            f"non-empty list of module names, got {targets!r}"
        )
    known = _kernel_module_names(params)
    unknown = sorted(t for t in targets if t not in known)
    if unknown:
        raise ValueError(
            f"adapter_config.json{where}: field 'target_modules' names "
            f"modules the model does not have: {unknown} (model linear "
            f"modules: {sorted(known)})"
        )


def peft_adapter_state(params: Params, path: str, train: TrainConfig = None):
    """Load AND validate a PEFT adapter directory against ``params``.

    Returns ``(entries, scale, acfg)``: ``entries`` maps each adapted
    module's tree path (tuple of keys ending at the dict holding
    ``kernel``) to ``(A [in, r], B [r, out])`` float32 numpy arrays already
    transposed to JAX kernel layout; ``scale`` is ``alpha / r``. Every
    tensor's rank and in/out dims are checked against ``acfg`` and the
    model's kernels here, with errors that name the mismatched field or
    module — the import path never dies inside the tree merge."""
    import json
    import os

    from safetensors.numpy import load_file

    state = load_file(os.path.join(path, "adapter_model.safetensors"))
    cfg_path = os.path.join(path, "adapter_config.json")
    acfg = None
    if os.path.exists(cfg_path):
        with open(cfg_path) as f:
            acfg = json.load(f)
        validate_adapter_config(acfg, params, path)
        rank = int(acfg["r"])
        scale = float(acfg["lora_alpha"]) / rank
    elif train is not None:
        rank = int(train.lora_rank)
        scale = float(train.lora_alpha) / rank
    else:
        raise ValueError(f"{path} has no adapter_config.json and no TrainConfig given")

    entries: Dict[tuple, tuple] = {}

    def walk(node, prefix):
        if not isinstance(node, dict):
            return
        base = "base_model.model." + ".".join(prefix) if prefix else "base_model.model"
        a_name = f"{base}.lora_A.weight"
        if "kernel" in node:
            if a_name not in state:
                return
            a_t, b_t = state[a_name], state[f"{base}.lora_B.weight"]
            module = ".".join(prefix)
            # torch layout: lora_A.weight [r, in], lora_B.weight [out, r]
            if a_t.shape[0] != rank or b_t.shape[-1] != rank:
                raise ValueError(
                    f"adapter_config.json ({path}): field 'r' = {rank} does "
                    f"not match the saved tensors for {module} "
                    f"(lora_A {tuple(a_t.shape)}, lora_B {tuple(b_t.shape)})"
                )
            d_in, d_out = node["kernel"].shape
            if a_t.shape[1] != d_in or b_t.shape[0] != d_out:
                raise ValueError(
                    f"adapter ({path}) does not fit the model: {module} has "
                    f"kernel [in={d_in}, out={d_out}] but the adapter was "
                    f"trained for [in={a_t.shape[1]}, out={b_t.shape[0]}]"
                )
            entries[tuple(prefix)] = (
                np.ascontiguousarray(a_t.T.astype(np.float32)),
                np.ascontiguousarray(b_t.T.astype(np.float32)),
            )
            return
        for k, v in node.items():
            walk(v, prefix + (k,))

    walk(params, ())
    if not entries:
        raise ValueError(
            f"adapter ({path}) matched no module of the model: its tensor "
            "names do not line up with any kernel path"
        )
    return entries, np.float32(scale), acfg


def load_lora_adapter(params: Params, path: str, train: TrainConfig = None) -> Params:
    """Attach adapters from a PEFT directory onto a base params pytree.

    The scale comes from the directory's own ``adapter_config.json`` (the
    adapter is self-describing); ``train`` is only a fallback for bare
    directories without a config file. The config and every tensor are
    validated against the model first (``peft_adapter_state``)."""
    entries, scale, _ = peft_adapter_state(params, path, train)

    def walk(node, prefix):
        if not isinstance(node, dict):
            return node
        if tuple(prefix) in entries:
            a, b = entries[tuple(prefix)]
            out = dict(node)
            out["lora_a"] = jnp.asarray(a)
            out["lora_b"] = jnp.asarray(b)
            out["lora_scale"] = jnp.asarray(scale)
            return out
        return {k: walk(v, prefix + [k]) for k, v in node.items()}

    return walk(params, [])
