"""Sharding rules: param-path -> PartitionSpec over the (data, fsdp, tensor, seq) mesh.

This module is the TPU-native replacement for the reference's entire
parallelism story (DDP-only, ``ddp_backend="nccl"`` reference
``training.py:285``) and its aspired FSDP next step (external-doc article):

- DP    : params replicated; batch split over (data, fsdp); gradients psum'd
          by XLA (the analog of NCCL bucketed all-reduce,
          ``docs/architecture-diagram.md:119-135``).
- FSDP  : each param's largest dim additionally sharded over ``fsdp``
          (ZeRO-3); XLA turns the gradient psum into reduce-scatter +
          all-gather automatically.
- TP    : Megatron-style — attention q/k/v and MLP gate/up shard their output
          dim over ``tensor``; o_proj and down shard their input dim, so each
          block needs exactly two psums (inserted by XLA from the annotations).
- seq   : reserved for ring attention (parallel/ring_attention.py).

Rules are by HF param path, so they apply to every model in models/configs.py.
"""

from __future__ import annotations

import re
from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from llm_fine_tune_distributed_tpu.utils.tree import map_with_path

# (path regex, spec builder) — first match wins. Specs are (dim0, dim1) for
# matrices, (dim0,) for vectors. None = replicated on that dim.
# "tensor-column": output dim over tensor; "tensor-row": input dim over tensor.
# NF4-quantized kernels (ops/nf4.py) keep the base kernel's orientation:
# packed [in/8, out] and absmax [in/block, out] shard like kernel [in, out]
# (_validate_spec drops any axis the smaller dims no longer divide).
# int8 weight-only inference kernels (ops/int8.py) keep the base [in, out]
# orientation; their 1-D scales fall through to the replicated default.
_QK = r"kernel(_nf4|_absmax|_absmax_q|_int8)?$"
_MATRIX_RULES = [
    # attention projections
    (re.compile(r".*self_attn/(q_proj|k_proj|v_proj)/" + _QK), ("fsdp", "tensor")),
    (re.compile(r".*self_attn/o_proj/" + _QK), ("tensor", "fsdp")),
    # MLP
    (re.compile(r".*mlp/(gate_proj|up_proj)/" + _QK), ("fsdp", "tensor")),
    (re.compile(r".*mlp/down_proj/" + _QK), ("tensor", "fsdp")),
    # embeddings: [vocab, hidden] — shard vocab over tensor, hidden over fsdp
    (re.compile(r".*embed_tokens/weight$"), ("tensor", "fsdp")),
    (re.compile(r".*lm_head/kernel$"), ("fsdp", "tensor")),
    # LoRA adapters: A [in, r] shard in-dim like the base kernel's in-dim;
    # B [r, out] shard out-dim. Conservative: fsdp only (r is tiny).
    (re.compile(r".*/lora_a$"), ("fsdp", None)),
    (re.compile(r".*/lora_b$"), (None, "fsdp")),
    # Stacked adapter pools (infer/adapters.py): same orientation with a
    # leading [max_adapters] pool dim. lora_scale_pool is 1-D -> replicated.
    (re.compile(r".*/lora_a_pool$"), (None, "fsdp", None)),
    (re.compile(r".*/lora_b_pool$"), (None, None, "fsdp")),
    # MoE (ops/moe.py): stacked expert weights shard the expert dim over the
    # "expert" axis (expert parallelism) plus the usual fsdp/tensor dims;
    # the router gate [h, E] is tiny — fsdp on the input dim only.
    # NF4-quantized experts ([E, in/8, out] packed + [E, in/block, out]
    # absmax) keep the same orientation; _validate_spec drops any dim the
    # packed shapes no longer divide.
    (re.compile(r".*block_sparse_moe/experts/(w1|w3)(_nf4|_absmax|_absmax_q)?$"),
     ("expert", "fsdp", "tensor")),
    (re.compile(r".*block_sparse_moe/experts/w2(_nf4|_absmax|_absmax_q)?$"),
     ("expert", "tensor", "fsdp")),
    (re.compile(r".*block_sparse_moe/gate/kernel$"), ("fsdp", None)),
]


def param_spec(path: str, ndim: int) -> P:
    """PartitionSpec for one param."""
    if ndim <= 1:
        # norms / biases / scalars: replicated (tiny).
        return P()
    for pat, dims in _MATRIX_RULES:
        if pat.match(path):
            return P(*dims)
    return P()


def param_sharding_rules(params, mesh: Mesh):
    """Pytree of NamedSharding matching ``params``' structure.

    Falls back to replication for any dim whose size does not divide the mesh
    axis (e.g. tiny test models on an 8-way fsdp axis).
    """

    def rule(path: str, leaf) -> NamedSharding:
        spec = param_spec(path, getattr(leaf, "ndim", 0))
        spec = _validate_spec(spec, getattr(leaf, "shape", ()), mesh)
        return NamedSharding(mesh, spec)

    return map_with_path(rule, params)


def _validate_spec(spec: P, shape, mesh: Mesh) -> P:
    fixed = []
    for i, axis in enumerate(spec):
        if axis is None:
            fixed.append(None)
            continue
        if axis == "expert" and axis not in mesh.shape:
            # the one axis that is legitimately optional (meshes built before
            # MoE support have 4 axes): replicate the expert dim. Any OTHER
            # unknown axis is a bug in the rules and raises below.
            fixed.append(None)
            continue
        size = mesh.shape[axis]
        if i < len(shape) and shape[i] % size == 0:
            fixed.append(axis)
        else:
            fixed.append(None)
    while fixed and fixed[-1] is None:
        fixed.pop()
    return P(*fixed)


def shard_params(params, mesh: Mesh):
    """Place a (host-local) params pytree onto the mesh per the rules.

    Works on process-spanning meshes too: ``jax.device_put`` cannot target
    another process's devices, so when the mesh is not fully addressable
    each leaf is assembled with ``make_array_from_callback`` — every process
    holds the full host copy (same checkpoint on every host) and contributes
    its local shards. This is the multi-host inference load path
    (``Generator(mesh=...)`` with tensor spanning hosts)."""
    shardings = param_sharding_rules(params, mesh)
    if len(mesh.devices.flat) == len([d for d in mesh.devices.flat if d.process_index == jax.process_index()]):
        return jax.device_put(params, shardings)
    return jax.tree.map(
        lambda x, sh: global_array_from_host(np.asarray(x), sh), params, shardings
    )


def global_array_from_host(host_array: np.ndarray, sharding: NamedSharding):
    """Global jax.Array over a (possibly multi-process) mesh from a host
    array every process holds in full: each process contributes the shards
    its devices own."""
    return jax.make_array_from_callback(
        host_array.shape, sharding, lambda idx: host_array[idx]
    )


def mesh_fully_addressable(mesh: Mesh) -> bool:
    """True when every mesh device belongs to this process (single-controller
    placement via ``jax.device_put`` is legal); False on a process-spanning
    mesh, where leaves must be assembled as global arrays."""
    pid = jax.process_index()
    return all(d.process_index == pid for d in mesh.devices.flat)


def place_tree(tree, shardings):
    """Place a host-local pytree under a matching pytree of NamedShardings,
    choosing ``device_put`` or global-array assembly per the mesh's
    addressability (the same split ``shard_params`` makes for weights)."""
    meshes = {sh.mesh for sh in jax.tree.leaves(shardings)}
    if all(mesh_fully_addressable(m) for m in meshes):
        return jax.device_put(tree, shardings)
    return jax.tree.map(
        lambda x, sh: global_array_from_host(np.asarray(x), sh), tree, shardings
    )


# KV cache / paged block pool leaves, by leaf name. Dense rows and paged
# blocks share the layout [rows|blocks, len, num_kv_heads, head_dim]: the
# kv-head dim shards over ``tensor`` so each chip holds the heads its
# (column-sharded) k/v projections produce — decode attention then needs no
# resharding between projection, cache write, and the gather/softmax.
# int8 pools carry sibling per-block scales [blocks, num_kv_heads] that
# shard the same head dim. _validate_spec drops the tensor axis when it
# does not divide num_kv_heads (head replication — see make_tp_mesh).
_KV_LEAF_DIMS = {
    "k": (None, None, "tensor", None),
    "v": (None, None, "tensor", None),
    "k_scale": (None, "tensor"),
    "v_scale": (None, "tensor"),
}


def kv_cache_spec(path: str, shape, mesh: Mesh) -> P:
    name = path.rsplit("/", 1)[-1]
    dims = _KV_LEAF_DIMS.get(name)
    if dims is None or len(dims) != len(shape):
        return P()
    return _validate_spec(P(*dims), shape, mesh)


def kv_cache_shardings(cache, mesh: Mesh):
    """Pytree of NamedSharding for a dense KV cache or paged block pool
    (``models/transformer.init_cache`` / ``init_paged_cache`` layout)."""
    return map_with_path(
        lambda path, leaf: NamedSharding(
            mesh, kv_cache_spec(path, getattr(leaf, "shape", ()), mesh)
        ),
        cache,
    )


def batch_spec(mesh: Mesh, seq_axis: bool = False) -> P:
    """Batch arrays [batch, seq, ...]: batch over (data, fsdp), optionally
    sequence over seq (ring attention)."""
    if seq_axis and mesh.shape["seq"] > 1:
        return P(("data", "fsdp"), "seq")
    return P(("data", "fsdp"))


def logical_batch_sharding(mesh: Mesh, seq_axis: bool = False) -> NamedSharding:
    return NamedSharding(mesh, batch_spec(mesh, seq_axis))
