"""QLoRA: NF4-quantized frozen base + LoRA adapters (BASELINE.json config #5,
"Llama-3-70B QLoRA multi-host SFT (nf4 quant + Pallas matmul)").

The reference repo has no quantization code — QLoRA appears only in its
external-doc Kubeflow article (r=16, alpha=8, dropout=0.05, 7 proj targets,
p.11) as the aspired next step. Here it is first-party: after the LoRA
adapters are attached (parallel/lora.py) and the params split into
trainable/frozen (parallel/freeze.py), every frozen transformer-block linear
kernel is replaced by its NF4 packed form (ops/nf4.py). The model's
``_linear`` dispatches on the ``kernel_nf4`` leaf automatically, so forward,
eval, and generate all run off the quantized base with no further wiring.

Memory math for the 70B config: 70e9 params * 4.5 bits ≈ 39 GB frozen base
(vs 140 GB bf16) + adapter params + optimizer state only for adapters —
what makes a v5p-128 host fleet hold the model comfortably with long remat.
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

from llm_fine_tune_distributed_tpu.ops.nf4 import (
    DEFAULT_BLOCK_SIZE,
    DEQUANT_MARKERS,
    dequantize_nf4,
    dequantize_nf4_layered,
    dequantize_nf4_layered_stacked,
    dequantize_nf4_stacked,
    quantize_nf4,
    quantize_nf4_layered,
    quantize_nf4_layered_stacked,
    quantize_nf4_stacked,
    quantized_layout,
    quantized_layout_layered,
    quantized_layout_layered_stacked,
    quantized_layout_stacked,
)

# leaf names that quantize: dense block linears + stacked MoE expert weights
_EXPERT_LEAVES = ("w1", "w2", "w3")


def _is_quantizable(path: str, leaf) -> bool:
    if "/layers/" not in path:
        return False
    if path.endswith("block_sparse_moe/gate/kernel"):
        # the MoE router gate is tiny ([h, E] — ~0.01% of expert bytes) and
        # NF4 rounding would perturb every routing decision: keep it exact
        return False
    if path.endswith("/kernel"):
        if getattr(leaf, "ndim", 0) == 2:
            return leaf.shape[0] % 8 == 0
        # pipe-mode stacked block kernels [L, in, out]: same layout as the
        # stacked expert case below — packs along the per-layer in dim
        return getattr(leaf, "ndim", 0) == 3 and leaf.shape[1] % 8 == 0
    if path.endswith(tuple(f"/experts/{w}" for w in _EXPERT_LEAVES)):
        # stacked [E, in, out]: packs along the per-expert in dim;
        # pipe-stacked [L, E, in, out] packs along the same per-expert dim
        if getattr(leaf, "ndim", 0) == 3:
            return leaf.shape[1] % 8 == 0
        return getattr(leaf, "ndim", 0) == 4 and leaf.shape[2] % 8 == 0
    return False


def _quant_in_dim(leaf) -> int:
    """The dim the block grid runs along (per-expert in dim for 3-D/4-D)."""
    ndim = getattr(leaf, "ndim", 0)
    if ndim == 4:
        return leaf.shape[2]
    return leaf.shape[1] if ndim == 3 else leaf.shape[0]


def quantize_frozen(
    frozen: Dict[str, np.ndarray],
    block_size: int = DEFAULT_BLOCK_SIZE,
    double_quant: bool = True,
) -> Dict[str, np.ndarray]:
    """Replace each frozen block-linear ``.../kernel`` leaf with NF4 leaves.

    Non-matching leaves (embeddings, norms, lm_head, biases, odd shapes) pass
    through unchanged — QLoRA quantizes only the transformer-block linears.
    """
    out: Dict[str, np.ndarray] = {}
    for path, leaf in frozen.items():
        if not _is_quantizable(path, leaf) or _quant_in_dim(leaf) % block_size:
            out[path] = leaf
            continue
        # pass the leaf as-is: on-device arrays quantize on the accelerator
        # (ops/nf4._quantize_codes_jax) with no host round-trip
        ndim = getattr(leaf, "ndim", 0)
        if ndim == 4:
            # pipe-stacked MoE experts [L, E, in, out]: per-layer stacked
            # layouts under a leading layer dim (qlora x pipe x MoE)
            q = quantize_nf4_layered_stacked(leaf, block_size, double_quant)
        elif ndim == 3:
            # pipe-stacked block kernels [L, in, out] quantize per layer so
            # every leaf keeps the layer dim the schedule's scan slices;
            # MoE expert stacks [E, in, out] keep the flattened layout
            if "@stacked/" in path:
                q = quantize_nf4_layered(leaf, block_size, double_quant)
            else:
                q = quantize_nf4_stacked(leaf, block_size, double_quant)
        else:
            q = quantize_nf4(leaf, block_size, double_quant)
        for suffix, arr in q.items():
            out[f"{path}_{suffix}"] = jnp.asarray(arr)
    return out


def dequantize_frozen(frozen: Dict, dtype=jnp.bfloat16) -> Dict:
    """Inverse transform for export: NF4 leaf groups -> ``.../kernel``.

    Used when emitting ``best_model/`` safetensors (the inference contract,
    reference ``training.py:310-311``) and when merging LoRA into the base.
    """
    out: Dict = {}
    groups: Dict[str, Dict] = {}
    quant_bases = ("kernel",) + _EXPERT_LEAVES
    for path, leaf in frozen.items():
        for marker in DEQUANT_MARKERS:
            if path.endswith(tuple(f"{b}{marker}" for b in quant_bases)):
                base = path[: -len(marker)]
                groups.setdefault(base, {})[marker[1:]] = leaf
                break
        else:
            out[path] = leaf
    for base, q in groups.items():
        nf4_ndim = getattr(q["nf4"], "ndim", 2)
        if nf4_ndim == 4:  # pipe-stacked experts: per-layer stacked layouts
            out[base] = dequantize_nf4_layered_stacked(q, dtype=dtype)
        elif nf4_ndim == 3:
            if "@stacked/" in base:  # pipe-stacked kernel: per-layer layout
                out[base] = dequantize_nf4_layered(q, dtype=dtype)
            else:  # stacked expert weight: flattened layout
                out[base] = dequantize_nf4_stacked(q, dtype=dtype)
        else:
            out[base] = dequantize_nf4(q, dtype=dtype)
    return out


def quantize_frozen_abstract(
    frozen: Dict,
    block_size: int = DEFAULT_BLOCK_SIZE,
    double_quant: bool = True,
) -> Dict:
    """Shape-level ``quantize_frozen``: ShapeDtypeStructs in, structs out.

    Lets planners (and the big-config trace tests) compute the exact
    post-quantization memory layout of a 70B model without touching weights.
    The layout comes from ops/nf4.quantized_layout — the same source the
    real quantizer encodes — so the two cannot drift.
    """
    out: Dict = {}
    for path, leaf in frozen.items():
        if not _is_quantizable(path, leaf) or _quant_in_dim(leaf) % block_size:
            out[path] = leaf
            continue
        ndim = getattr(leaf, "ndim", 0)
        if ndim == 4:
            layout_fn = quantized_layout_layered_stacked
        elif ndim == 3:
            layout_fn = (
                quantized_layout_layered if "@stacked/" in path else quantized_layout_stacked
            )
        else:
            layout_fn = quantized_layout
        for suffix, (shape, dtype) in layout_fn(
            leaf.shape, block_size, double_quant
        ).items():
            out[f"{path}_{suffix}"] = jax.ShapeDtypeStruct(shape, dtype)
    return out


def quantized_fraction(frozen: Dict) -> float:
    """Fraction of frozen bytes stored in NF4 form (for run summaries)."""
    q_bytes = total = 0
    for path, leaf in frozen.items():
        nbytes = getattr(leaf, "nbytes", 0)
        total += nbytes
        tail = path.rsplit("/", 1)[-1]
        if any(
            tail.startswith(f"{b}_nf4") or tail.startswith(f"{b}_absmax")
            for b in ("kernel",) + _EXPERT_LEAVES
        ):
            q_bytes += nbytes
    return q_bytes / total if total else 0.0
