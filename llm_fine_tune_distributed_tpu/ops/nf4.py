"""NF4 (NormalFloat4) blockwise quantization — the QLoRA storage format
(Dettmers et al. 2023), built TPU-first.

BASELINE.json config #5 names "Llama-3-70B QLoRA multi-host SFT (nf4 quant +
Pallas matmul)". The reference repo itself has no quantization code (SURVEY.md
§2.1 "not present" list; QLoRA appears only in its external-doc article), so
this subsystem is first-party.

Storage layout (chosen for the TPU memory system, not a CUDA translation):
- A weight ``W [in, out]`` is quantized along the **contraction (in) axis** in
  blocks of ``block_size`` rows per column: ``absmax [in/block, out]``.
  Per-column blocks keep the scale grid aligned with how a matmul tile
  consumes rows, so a fused kernel rescales with a plain broadcast.
- 4-bit codes are packed 8-per-int32 into ``packed [in/8, out]``; nibble ``s``
  of word ``r`` holds logical row ``8 r + s``. int32 is the native TPU
  vector-memory word — int4/uint8 tiles have harsh (32, 128) sublane minimums
  and poor op coverage on the VPU, while int32 shift/mask decode vectorizes
  cleanly.
- Optional **double quantization** compresses the f32 absmax tensor to int8
  with one f32 scale per group of 256 scales plus a global mean offset
  (the QLoRA paper's second-level scheme), cutting scale overhead from
  0.5 bit/param to ~0.13 bit/param at block 64.

Effective bits/param at block 64: 4 + 32/64 = 4.5 (single quant) or
4 + 8/64 + ~32/(64*256) = ~4.13 (double quant).
"""

from __future__ import annotations

import functools
import math
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

# The 16 NF4 code points: quantiles of N(0,1) normalized to [-1, 1]
# (exact constants from the QLoRA reference implementation).
NF4_CODEBOOK = np.array(
    [
        -1.0,
        -0.6961928009986877,
        -0.5250730514526367,
        -0.39491748809814453,
        -0.28444138169288635,
        -0.18477343022823334,
        -0.09105003625154495,
        0.0,
        0.07958029955625534,
        0.16093020141124725,
        0.24611230194568634,
        0.33791524171829224,
        0.44070982933044434,
        0.5626170039176941,
        0.7229568362236023,
        1.0,
    ],
    dtype=np.float32,
)

DEFAULT_BLOCK_SIZE = 64
ABSMAX_GROUP = 256  # double-quant group size (QLoRA paper)


def _nearest_code(x: np.ndarray) -> np.ndarray:
    """Index of the nearest NF4 code point for each normalized value."""
    # midpoints between consecutive code points -> searchsorted buckets
    mids = (NF4_CODEBOOK[1:] + NF4_CODEBOOK[:-1]) / 2.0
    return np.searchsorted(mids, x).astype(np.int32)


def quantize_nf4(
    w,
    block_size: int = DEFAULT_BLOCK_SIZE,
    double_quant: bool = True,
) -> Dict[str, Any]:  # values: np.ndarray, or jax.Array ("nf4" on the device path)
    """Quantize ``w [in, out]`` to NF4 (one-shot at load/startup).

    Large leaves on an accelerator backend quantize on-device and return the
    packed codes as device arrays; small leaves / CPU take a numpy path.

    Returns a flat dict of arrays (ready to live as sibling param-tree leaves):
      ``nf4``            int32 [in/8, out]   — packed 4-bit codes
      ``absmax``         f32   [in/block, out]        (single quant), or
      ``absmax_q``       int8  [in/block, out]        (double quant)
      ``absmax_scale``   f32   [n_groups]
      ``absmax_offset``  f32   []
    """
    if getattr(w, "ndim", None) != 2:
        raise ValueError(f"quantize_nf4 expects a 2-D weight, got {np.shape(w)}")
    k, n = w.shape
    if k % 8:
        raise ValueError(f"in-dim {k} not divisible by the int32 pack factor 8")
    if k % block_size:
        raise ValueError(f"in-dim {k} not divisible by block_size {block_size}")

    if w.size >= 1 << 22 and jax.default_backend() != "cpu":
        # Device-accelerated quantization: the numpy path takes ~10+ minutes
        # for a 3B model's block linears; one jitted pass per leaf on the
        # accelerator does the same in milliseconds. The packed codes STAY on
        # device (they are about to live there as frozen params anyway); only
        # the small absmax comes to host for the double-quant step.
        packed, absmax = _quantize_codes_jax(jnp.asarray(w, jnp.float32), block_size)
        absmax = np.asarray(absmax)
    else:
        w = np.asarray(w, dtype=np.float32)
        # per-(block, column) absmax
        blocks = w.reshape(k // block_size, block_size, n)
        absmax = np.abs(blocks).max(axis=1)  # [k/block, n]
        safe = np.where(absmax == 0.0, 1.0, absmax)
        normalized = blocks / safe[:, None, :]
        codes = _nearest_code(normalized.reshape(k, n))

        # pack 8 consecutive rows per int32 word (nibble s = row 8r+s)
        codes = codes.reshape(k // 8, 8, n).astype(np.uint32)
        packed = np.zeros((k // 8, n), dtype=np.uint32)
        for s in range(8):
            packed |= codes[:, s, :] << np.uint32(4 * s)
        packed = packed.astype(np.int32)
    out = {"nf4": packed}  # np (small path) or on-device jnp (jax path)

    if not double_quant:
        out["absmax"] = absmax.astype(np.float32)
        return out

    flat = absmax.reshape(-1)
    offset = np.float32(flat.mean())
    centered = flat - offset
    pad = (-centered.size) % ABSMAX_GROUP
    grouped = np.pad(centered, (0, pad)).reshape(-1, ABSMAX_GROUP)
    gmax = np.abs(grouped).max(axis=1)
    gscale = np.where(gmax == 0.0, 1.0, gmax) / 127.0
    q = np.clip(np.round(grouped / gscale[:, None]), -127, 127).astype(np.int8)
    out["absmax_q"] = q.reshape(-1)[: centered.size].reshape(absmax.shape)
    out["absmax_scale"] = gscale.astype(np.float32)
    out["absmax_offset"] = np.asarray(offset, np.float32)
    return out


@functools.partial(jax.jit, static_argnames=("block_size",))
def _quantize_codes_jax(w, block_size: int):
    """Device-side NF4 quantize: returns (packed int32 [k/8, n], absmax f32).

    Bit-identical to the numpy path: same absmax grid, same midpoint
    bucketing (searchsorted over the 15 code midpoints), same nibble layout.
    """
    k, n = w.shape
    blocks = w.reshape(k // block_size, block_size, n)
    absmax = jnp.abs(blocks).max(axis=1)
    safe = jnp.where(absmax == 0.0, 1.0, absmax)
    normalized = (blocks / safe[:, None, :]).reshape(k, n)
    mids = jnp.asarray((NF4_CODEBOOK[1:] + NF4_CODEBOOK[:-1]) / 2.0)
    codes = jnp.searchsorted(mids, normalized.reshape(-1)).reshape(k, n)
    codes = codes.reshape(k // 8, 8, n).astype(jnp.uint32)
    packed = jnp.zeros((k // 8, n), jnp.uint32)
    for s in range(8):
        packed = packed | (codes[:, s, :] << jnp.uint32(4 * s))
    return packed.astype(jnp.int32), absmax


def _dequant_absmax(q: Dict, dtype=jnp.float32):
    """Recover the f32 absmax [in/block, out] from either storage form."""
    if "absmax" in q:
        return q["absmax"].astype(dtype)
    shape = q["absmax_q"].shape
    flat = q["absmax_q"].astype(dtype).reshape(-1)
    pad = (-flat.size) % ABSMAX_GROUP
    grouped = jnp.pad(flat, (0, pad)).reshape(-1, ABSMAX_GROUP)
    deq = grouped * q["absmax_scale"][:, None].astype(dtype)
    return (deq.reshape(-1)[: flat.size] + q["absmax_offset"].astype(dtype)).reshape(shape)


def unpack_codes(packed):
    """int32 [k/8, n] -> int32 codes [k, n] (nibble s of word r = row 8r+s)."""
    k8, n = packed.shape
    u = packed.astype(jnp.uint32)
    nibbles = [(u >> jnp.uint32(4 * s)) & jnp.uint32(0xF) for s in range(8)]
    return jnp.stack(nibbles, axis=1).reshape(k8 * 8, n).astype(jnp.int32)


def dequantize_nf4(q: Dict, dtype=jnp.bfloat16):
    """Reconstruct the bf16/f32 weight [in, out] (pure XLA).

    Under ``jax.checkpoint``-wrapped blocks only one layer's dequantized
    weight is live at a time, so peak HBM stays ~4.5 bits/param for the
    frozen base — the QLoRA memory profile without a custom allocator.
    """
    packed = q["nf4"]
    k = packed.shape[0] * 8
    codes = unpack_codes(packed)
    codebook = jnp.asarray(NF4_CODEBOOK, dtype=jnp.float32)
    w = codebook[codes]  # [k, n] f32
    absmax = _dequant_absmax(q, jnp.float32)
    block = k // absmax.shape[0]
    w = w.reshape(absmax.shape[0], block, -1) * absmax[:, None, :]
    return w.reshape(k, -1).astype(dtype)


def nf4_matmul(x, q: Dict, impl: str = "auto", compute_dtype=jnp.bfloat16):
    """``x [. , in] @ dequant(q) [in, out]``.

    impl: "xla" (dequantize then jnp.dot; XLA fuses decode into the operand
    read where it can) or "auto" (resolves to "xla").

    A fused Pallas decode kernel was built and RETIRED after head-to-head
    measurement on a v5e chip (round-2 shootout; BASELINE.md "NF4 matmul
    implementations"): at the 3B train-microbatch shape (M=2048, K=2048,
    N=11008) fused-pallas ran 7.8ms vs 6.7ms XLA vs 5.6ms bf16, and at
    batch-1 decode both NF4 paths sat ~6.5ms vs 20us bf16. The bottleneck
    is not HBM (a fused kernel's win) but the exact nibble decode itself:
    any exact NF4 expansion — select chain, binary select tree, one-hot
    compare + MXU dot, Lagrange polynomial — costs ~16 VPU ops per weight,
    and the VPU is ~100x slower than the MXU on this chip. NF4's value here
    is MEMORY (4.5 bits/param at rest, one layer decoded at a time under
    remat/liveness), not speed; for decode SPEED use int8 weight-only
    (ops/int8.py: 1 multiply per weight, measured 1.5x bf16).
    """
    if impl == "auto":
        impl = "xla"
    if impl != "xla":
        raise ValueError(
            f"unknown nf4 matmul impl {impl!r} (the fused Pallas kernel was "
            "retired after losing to the XLA path on v5e — see nf4_matmul "
            "docstring; use impl='xla' or int8 weight-only for speed)"
        )
    w = dequantize_nf4(q, dtype=compute_dtype)
    return x.astype(compute_dtype) @ w


# Canonical sibling-leaf naming scheme for a quantized ``kernel``. Every
# consumer (models/transformer._linear, parallel/qlora) derives its key lists
# from these two tuples — do not re-encode the scheme elsewhere.
QUANT_SUFFIXES = ("nf4", "absmax", "absmax_q", "absmax_scale", "absmax_offset")
# longest-first so suffix matching is unambiguous ("_absmax_q" before "_absmax")
DEQUANT_MARKERS = ("_absmax_offset", "_absmax_scale", "_absmax_q", "_absmax", "_nf4")


def quantized_keys(prefix: str) -> tuple:
    """The sibling leaf names a quantized ``{prefix}`` may occupy."""
    return tuple(f"{prefix}_{s}" for s in QUANT_SUFFIXES)


def quantized_layout(shape, block_size: int = DEFAULT_BLOCK_SIZE, double_quant: bool = True):
    """suffix -> (shape, dtype) for quantize_nf4's output arrays.

    The single source of truth for the storage layout — used by shape-level
    planners (parallel/qlora.quantize_frozen_abstract) so the abstract and
    real quantizers cannot drift. Rejects exactly the shapes quantize_nf4
    rejects, so a planner cannot produce a layout the quantizer won't.
    """
    k, n = shape
    if k % 8:
        raise ValueError(f"in-dim {k} not divisible by the int32 pack factor 8")
    if k % block_size:
        raise ValueError(f"in-dim {k} not divisible by block_size {block_size}")
    out = {"nf4": ((k // 8, n), jnp.int32)}
    if double_quant:
        n_scales = (k // block_size) * n
        out["absmax_q"] = ((k // block_size, n), jnp.int8)
        out["absmax_scale"] = ((math.ceil(n_scales / ABSMAX_GROUP),), jnp.float32)
        out["absmax_offset"] = ((), jnp.float32)
    else:
        out["absmax"] = ((k // block_size, n), jnp.float32)
    return out


# ---------------------------------------------------------------------------
# stacked (MoE expert) weights [E, in, out]
# ---------------------------------------------------------------------------


def _validate_stacked_in_dim(k: int, block_size: int) -> None:
    """Shared by quantize_nf4_stacked and quantized_layout_stacked so the
    abstract layout rejects exactly the shapes the real quantizer rejects."""
    if k % 8:
        raise ValueError(f"per-expert in-dim {k} not divisible by the pack factor 8")
    if k % block_size:
        raise ValueError(f"per-expert in-dim {k} not divisible by block_size {block_size}")


def quantize_nf4_stacked(w, block_size: int = DEFAULT_BLOCK_SIZE, double_quant: bool = True):
    """NF4-quantize a stacked expert weight ``[E, in, out]`` (ops/moe.py
    layout). Internally reshapes to ``[E*in, out]`` — with ``in`` a multiple
    of ``block_size`` no absmax block crosses an expert boundary, so each
    expert quantizes exactly as it would standalone. The packed codes and
    absmax keep the leading expert dim (``nf4 [E, in/8, out]``) so the
    expert-parallel sharding rules apply unchanged.
    """
    e, k, n = w.shape
    _validate_stacked_in_dim(k, block_size)
    q = quantize_nf4(w.reshape(e * k, n), block_size, double_quant)
    q["nf4"] = jnp.asarray(q["nf4"]).reshape(e, k // 8, n)
    for key in ("absmax", "absmax_q"):
        if key in q:
            q[key] = jnp.asarray(q[key]).reshape(e, k // block_size, n)
    return q


def dequantize_nf4_stacked(q: Dict, dtype=jnp.bfloat16):
    """Inverse of ``quantize_nf4_stacked``: NF4 leaves -> ``[E, in, out]``."""
    e, k8, n = q["nf4"].shape
    flat = {"nf4": q["nf4"].reshape(e * k8, n)}
    for key in ("absmax", "absmax_q"):
        if key in q:
            arr = q[key]
            flat[key] = arr.reshape(e * arr.shape[1], n)
    for key in ("absmax_scale", "absmax_offset"):
        if key in q:
            flat[key] = q[key]
    return dequantize_nf4(flat, dtype=dtype).reshape(e, k8 * 8, n)


def _quantize_per_layer(w, quantize_fn, block_size, double_quant):
    """Quantize leading-dim slices independently and stack every produced
    leaf under that layer dim. Shared by the two layered quantizers so their
    layer-slicing semantics cannot diverge."""
    outs = [quantize_fn(w[i], block_size, double_quant) for i in range(w.shape[0])]
    return {
        k: jnp.stack([jnp.asarray(o[k]) for o in outs]) for k in outs[0]
    }


def _dequantize_per_layer(q: Dict, dequantize_fn, dtype):
    """Inverse of ``_quantize_per_layer`` for either per-layer layout."""
    L = q["nf4"].shape[0]
    return jnp.stack([
        dequantize_fn({k: v[i] for k, v in q.items()}, dtype=dtype)
        for i in range(L)
    ])


def quantize_nf4_layered(w, block_size: int = DEFAULT_BLOCK_SIZE, double_quant: bool = True):
    """NF4-quantize a pipe-stacked kernel ``[L, in, out]`` LAYER BY LAYER.

    Unlike ``quantize_nf4_stacked`` (which flattens to ``[E*in, out]`` and
    keeps one global double-quant scale vector), every produced leaf here
    carries the leading layer dim — ``absmax_scale [L, G]``,
    ``absmax_offset [L]`` — because the pipeline schedule's ``lax.scan``
    slices the whole leaf tree per layer (parallel/pipeline.py:run_stage)
    and each slice must be a complete standalone ``quantize_nf4`` layout.
    Double-quant groups therefore never cross layer boundaries.
    """
    _validate_stacked_in_dim(w.shape[1], block_size)
    return _quantize_per_layer(w, quantize_nf4, block_size, double_quant)


def dequantize_nf4_layered(q: Dict, dtype=jnp.bfloat16):
    """Inverse of ``quantize_nf4_layered``: per-layer leaves -> [L, in, out]."""
    return _dequantize_per_layer(q, dequantize_nf4, dtype)


def quantized_layout_layered(shape, block_size: int = DEFAULT_BLOCK_SIZE, double_quant: bool = True):
    """``quantized_layout`` for a pipe-stacked ``[L, in, out]`` kernel: every
    leaf gains the leading layer dim (see quantize_nf4_layered)."""
    l, k, n = shape
    per_layer = quantized_layout((k, n), block_size, double_quant)
    return {key: ((l, *s), dt) for key, (s, dt) in per_layer.items()}


def quantize_nf4_layered_stacked(w, block_size: int = DEFAULT_BLOCK_SIZE, double_quant: bool = True):
    """NF4-quantize a pipe-stacked MoE expert weight ``[L, E, in, out]``
    LAYER BY LAYER (qlora x pipe x MoE — the dominant bytes of an MoE model).

    Each layer quantizes via ``quantize_nf4_stacked`` and the leaves stack a
    leading layer dim — ``nf4 [L, E, in/8, out]``, ``absmax_q
    [L, E, in/block, out]``, ``absmax_scale [L, G]``, ``absmax_offset [L]`` —
    so the pipeline schedule's per-layer ``lax.scan`` slice is a complete
    standalone ``quantize_nf4_stacked`` layout that ops/moe.py's
    ``dequantize_nf4_stacked`` consumes unchanged. Double-quant groups never
    cross layer boundaries (same invariant as ``quantize_nf4_layered``)."""
    _validate_stacked_in_dim(w.shape[2], block_size)
    return _quantize_per_layer(w, quantize_nf4_stacked, block_size, double_quant)


def dequantize_nf4_layered_stacked(q: Dict, dtype=jnp.bfloat16):
    """Inverse of ``quantize_nf4_layered_stacked``: leaves -> [L, E, in, out]."""
    return _dequantize_per_layer(q, dequantize_nf4_stacked, dtype)


def quantized_layout_layered_stacked(shape, block_size: int = DEFAULT_BLOCK_SIZE, double_quant: bool = True):
    """``quantized_layout`` for a pipe-stacked ``[L, E, in, out]`` expert
    weight: every per-layer stacked leaf gains the leading layer dim."""
    l, e, k, n = shape
    per_layer = quantized_layout_stacked((e, k, n), block_size, double_quant)
    return {key: ((l, *s), dt) for key, (s, dt) in per_layer.items()}


def quantized_layout_stacked(shape, block_size: int = DEFAULT_BLOCK_SIZE, double_quant: bool = True):
    """``quantized_layout`` for a stacked ``[E, in, out]`` expert weight.

    Rejects exactly the shapes ``quantize_nf4_stacked`` rejects (the
    PER-EXPERT in-dim must divide the pack factor and block size — the
    flattened e*in passing those checks is not sufficient)."""
    e, k, n = shape
    _validate_stacked_in_dim(k, block_size)
    flat = quantized_layout((e * k, n), block_size, double_quant)
    out = {"nf4": ((e, k // 8, n), jnp.int32)}
    for key in ("absmax", "absmax_q"):
        if key in flat:
            (shape2, dtype) = flat[key]
            out[key] = ((e, k // block_size, n), dtype)
    for key in ("absmax_scale", "absmax_offset"):
        if key in flat:
            out[key] = flat[key]
    return out


def quantize_params_nf4(params, predicate=None, block_size: int = DEFAULT_BLOCK_SIZE):
    """Replace every matching transformer-block linear with its NF4 sibling
    leaves — the NF4 counterpart of ``ops/int8.quantize_params_int8``
    (``--quantize-weights nf4`` on the inference entry points).

    Same predicate and same exclusions as the int8 path: embeddings, the
    lm_head and the MoE router gate stay full precision. A leaf whose in-dim
    does not divide ``block_size`` (small presets) falls back to the largest
    valid block — the pack-factor minimum of 8 — instead of failing; an
    in-dim not divisible by 8 has no NF4 form at all and raises, exactly as
    the int8 quantizer is loud about predicate hits it cannot serve.
    """
    from llm_fine_tune_distributed_tpu.ops.int8 import quantize_params_int8  # noqa: F401 (predicate parity documented there)
    from llm_fine_tune_distributed_tpu.utils.tree import flatten_dict, unflatten_dict

    def is_stacked_expert(path: str) -> bool:
        return path.endswith(("/experts/w1", "/experts/w2", "/experts/w3"))

    if predicate is None:
        predicate = lambda path: "/layers/" in path and (
            (path.endswith("/kernel") and not path.endswith("block_sparse_moe/gate/kernel"))
            or is_stacked_expert(path)
        )

    flat = flatten_dict(params)
    out = {}
    for path, leaf in flat.items():
        if not predicate(path):
            out[path] = leaf
            continue
        if getattr(leaf, "ndim", 0) == 2 and path.endswith("/kernel"):
            k, quantize_fn = leaf.shape[0], quantize_nf4
        elif getattr(leaf, "ndim", 0) == 3 and is_stacked_expert(path):
            k, quantize_fn = leaf.shape[1], quantize_nf4_stacked
        else:
            raise ValueError(
                f"predicate matched {path!r} (ndim="
                f"{getattr(leaf, 'ndim', None)}) but only 2-D .../kernel "
                "leaves and stacked 3-D expert weights have an NF4 form"
            )
        bs = block_size if k % block_size == 0 else 8
        q = quantize_fn(leaf, block_size=bs)
        for suffix in QUANT_SUFFIXES:
            if suffix in q:
                out[f"{path}_{suffix}"] = jnp.asarray(q[suffix])
    return unflatten_dict(out)
