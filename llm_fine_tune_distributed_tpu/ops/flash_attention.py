"""Pallas (Mosaic) flash attention for TPU.

TPU-native replacement for the reference's flash-attn-2 CUDA kernels
(reference ``requirements.txt:10``, ``training.py:101``). Blockwise-softmax
attention computed in VMEM tiles so the [seq, seq] score matrix never
materializes in HBM.

Implemented in a later milestone; until then ``flash_attention_supported``
returns False and the dispatcher (ops/attention.py) falls back to XLA
attention, which is numerically identical.
"""

from __future__ import annotations

import jax


def flash_attention_supported(q, k, v, *, sliding_window=None, causal=True) -> bool:
    return False


def pallas_flash_attention(q, k, v, *, padding_mask=None):
    raise NotImplementedError("pallas flash attention lands in a later milestone")
