"""Pallas (Mosaic) flash attention for TPU — forward AND backward kernels.

TPU-native replacement for the reference's flash-attn-2 CUDA dependency
(reference ``requirements.txt:10``, ``training.py:101``). Blockwise online-
softmax attention computed in VMEM tiles: the [seq, seq] score matrix never
materializes in HBM, in either direction.

Formulation (FlashAttention-2 style):
  fwd   per (batch, q_head, q_block): stream K/V blocks up to the causal
        limit, carrying running max ``m``, normalizer ``l`` and the
        unnormalized accumulator; emit O and LSE = m + log(l).
  bwd   delta = rowsum(dO * O); then
        dq  per (batch, q_head, q_block):   ds = p * (dO V^T - delta); dq = ds K
        dk/dv per (batch, KV head, k_block): dv += p^T dO; dk += ds^T q,
        accumulated over the KV head's query group inside the kernel.

GQA is handled by BlockSpec index maps (K/V indexed with ``head // groups``
in fwd/dq; q/dO indexed per-group in dk/dv) — K/V are never repeated in HBM
and dk/dv stay at KV-head width. Dense-cache decode uses the XLA cache path,
not this kernel; quantized PAGED decode has its own fused kernel below
(``paged_decode_attention`` — block-table gather + per-block dequant +
online softmax in one VMEM pass).

Layout contract (matches ops/attention.py): q [b, sq, hq, d], k/v
[b, sk, hkv, d], output [b, sq, hq, d] in q.dtype. Masking is expressed as
per-position ``segments`` [b, s] int32 — attention flows within equal ids
only (0 = padding tail; sequence packing passes its real segment ids, plain
right-padded batches pass the 1/0 padding mask); softmax runs in float32.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1.0e30
_MAX_KERNEL_SEQ = 4096  # whole K/V/Q reside in VMEM per program; ring
                        # attention (parallel/ring_attention.py) covers longer


# ---------------------------------------------------------------------------
# forward kernel
# ---------------------------------------------------------------------------


def _fwd_kernel(seg_ref, q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale, block_k, groups):
    iq = pl.program_id(2)
    q = q_ref[0, 0].astype(jnp.float32)  # [BQ, d]
    bq, d = q.shape
    q_start = iq * bq
    # 0 = padding, >0 = packed segment id; ref-indexed with pl.ds (Mosaic
    # has no dynamic_slice on loaded arrays)
    q_seg = seg_ref[0, pl.ds(q_start, bq), 0]

    m0 = jnp.full((bq,), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq,), jnp.float32)
    acc0 = jnp.zeros((bq, d), jnp.float32)
    # causal upper bound: K blocks whose start exceeds the last q position of
    # this block contribute nothing
    n_blocks = (q_start + bq + block_k - 1) // block_k

    def body(j, carry):
        m, l, acc = carry
        k_blk = k_ref[0, 0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, 0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [BQ, BK]
        q_pos = q_start + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)
        k_pos = j * block_k + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 1)
        k_seg = seg_ref[0, pl.ds(j * block_k, block_k), 0]
        # same-segment test subsumes padding: pad queries (seg 0) attend only
        # the pad tail (incl. themselves at k==q, keeping softmax finite),
        # real queries never see pad keys or other segments
        mask = (k_pos <= q_pos) & (q_seg[:, None] == k_seg[None, :])
        s = jnp.where(mask, s, _NEG_INF)

        m_new = jnp.maximum(m, jnp.max(s, axis=1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[:, None])
        p = jnp.where(mask, p, 0.0)  # exp(-1e30 - m) underflows anyway; be exact
        l = l * alpha + jnp.sum(p, axis=1)
        acc = acc * alpha[:, None] + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return m_new, l, acc

    m, l, acc = jax.lax.fori_loop(0, n_blocks, body, (m0, l0, acc0))
    l_safe = jnp.maximum(l, 1e-30)
    o_ref[0, 0] = (acc / l_safe[:, None]).astype(o_ref.dtype)
    lse_ref[0, 0, :, 0] = m + jnp.log(l_safe)


def _fwd(q, k, v, segments, *, scale, block_q, block_k, groups, interpret):
    b, hq, sq, d = q.shape
    sk = k.shape[2]
    grid = (b, hq, sq // block_q)
    out_shape = (
        jax.ShapeDtypeStruct((b, hq, sq, d), q.dtype),
        # trailing unit dim: TPU tiling wants the block's last dim equal to
        # the array's (1) and the second-to-last divisible by 8 (block_q)
        jax.ShapeDtypeStruct((b, hq, sq, 1), jnp.float32),
    )
    kernel = functools.partial(_fwd_kernel, scale=scale, block_k=block_k, groups=groups)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, sk, 1), lambda b_, h, i: (b_, 0, 0)),
            pl.BlockSpec((1, 1, block_q, d), lambda b_, h, i: (b_, h, i, 0)),
            pl.BlockSpec((1, 1, sk, d), lambda b_, h, i: (b_, h // groups, 0, 0)),
            pl.BlockSpec((1, 1, sk, d), lambda b_, h, i: (b_, h // groups, 0, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, 1, block_q, d), lambda b_, h, i: (b_, h, i, 0)),
            pl.BlockSpec((1, 1, block_q, 1), lambda b_, h, i: (b_, h, i, 0)),
        ),
        out_shape=out_shape,
        interpret=interpret,
    )(segments[:, :, None], q, k, v)


# ---------------------------------------------------------------------------
# backward kernels
# ---------------------------------------------------------------------------


def _dq_kernel(seg_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, *, scale, block_k):
    iq = pl.program_id(2)
    q = q_ref[0, 0].astype(jnp.float32)
    do = do_ref[0, 0].astype(jnp.float32)
    lse = lse_ref[0, 0, :, 0]
    delta = delta_ref[0, 0, :, 0]
    bq, d = q.shape
    q_start = iq * bq
    q_seg = seg_ref[0, pl.ds(q_start, bq), 0]
    n_blocks = (q_start + bq + block_k - 1) // block_k

    def body(j, dq_acc):
        k_blk = k_ref[0, 0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, 0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        q_pos = q_start + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)
        k_pos = j * block_k + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 1)
        k_seg = seg_ref[0, pl.ds(j * block_k, block_k), 0]
        mask = (k_pos <= q_pos) & (q_seg[:, None] == k_seg[None, :])
        p = jnp.where(mask, jnp.exp(s - lse[:, None]), 0.0)
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta[:, None])
        return dq_acc + jax.lax.dot_general(
            ds, k_blk, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    dq = jax.lax.fori_loop(0, n_blocks, body, jnp.zeros((bq, d), jnp.float32))
    dq_ref[0, 0] = (dq * scale).astype(dq_ref.dtype)


def _dkv_kernel(seg_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref, *, scale, block_q, groups):
    """Per (batch, KV head, k_block): accumulate dk/dv over this KV head's
    ``groups`` query heads and all causal q blocks — dk/dv stay at KV-head
    width (no group-factor HBM inflation)."""
    jk = pl.program_id(2)
    k_blk = k_ref[0, 0].astype(jnp.float32)  # [BK, d]
    v_blk = v_ref[0, 0].astype(jnp.float32)
    bk, d = k_blk.shape
    sq = q_ref.shape[2]
    k_start = jk * bk
    k_seg = seg_ref[0, pl.ds(k_start, bk), 0]
    # causal: only q blocks at/after this k block contribute
    start_block = k_start // block_q
    n_blocks = sq // block_q

    def make_body(g):
        def body(i, carry):
            dk_acc, dv_acc = carry
            q_blk = q_ref[0, g, pl.ds(i * block_q, block_q), :].astype(jnp.float32)
            do_blk = do_ref[0, g, pl.ds(i * block_q, block_q), :].astype(jnp.float32)
            lse_blk = lse_ref[0, g, pl.ds(i * block_q, block_q), 0]
            delta_blk = delta_ref[0, g, pl.ds(i * block_q, block_q), 0]
            s = jax.lax.dot_general(
                q_blk, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
            ) * scale  # [BQ, BK]
            q_pos = i * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, bk), 0)
            k_pos = k_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, bk), 1)
            q_seg = seg_ref[0, pl.ds(i * block_q, block_q), 0]
            mask = (k_pos <= q_pos) & (q_seg[:, None] == k_seg[None, :])
            p = jnp.where(mask, jnp.exp(s - lse_blk[:, None]), 0.0)
            dv_acc = dv_acc + jax.lax.dot_general(
                p, do_blk, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
            )
            dp = jax.lax.dot_general(
                do_blk, v_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
            )
            ds = p * (dp - delta_blk[:, None])
            dk_acc = dk_acc + jax.lax.dot_general(
                ds, q_blk, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
            )
            return dk_acc, dv_acc

        return body

    dk = jnp.zeros((bk, d), jnp.float32)
    dv = jnp.zeros((bk, d), jnp.float32)
    for g in range(groups):  # static unroll over the KV head's query group
        dk, dv = jax.lax.fori_loop(start_block, n_blocks, make_body(g), (dk, dv))
    dk_ref[0, 0] = (dk * scale).astype(dk_ref.dtype)
    dv_ref[0, 0] = dv.astype(dv_ref.dtype)


def _bwd(q, k, v, segments, o, lse, do, *, scale, block_q, block_k, groups, interpret):
    """Head-major inputs: q/o/do/lse [b, hq, ...], k/v [b, hkv, s, d]."""
    b, hq, sq, d = q.shape
    hkv = k.shape[1]
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)[..., None]  # [b,hq,sq,1]

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, block_k=block_k),
        grid=(b, hq, sq // block_q),
        in_specs=[
            pl.BlockSpec((1, sq, 1), lambda b_, h, i: (b_, 0, 0)),
            pl.BlockSpec((1, 1, block_q, d), lambda b_, h, i: (b_, h, i, 0)),
            pl.BlockSpec((1, 1, sq, d), lambda b_, h, i: (b_, h // groups, 0, 0)),
            pl.BlockSpec((1, 1, sq, d), lambda b_, h, i: (b_, h // groups, 0, 0)),
            pl.BlockSpec((1, 1, block_q, d), lambda b_, h, i: (b_, h, i, 0)),
            pl.BlockSpec((1, 1, block_q, 1), lambda b_, h, i: (b_, h, i, 0)),
            pl.BlockSpec((1, 1, block_q, 1), lambda b_, h, i: (b_, h, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, d), lambda b_, h, i: (b_, h, i, 0)),
        out_shape=jax.ShapeDtypeStruct((b, hq, sq, d), q.dtype),
        interpret=interpret,
    )(segments[:, :, None], q, k, v, do, lse, delta)

    # grid over KV heads; q/do/lse/delta blocks span the head's query group
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, block_q=block_q, groups=groups),
        grid=(b, hkv, sq // block_k),
        in_specs=[
            pl.BlockSpec((1, sq, 1), lambda b_, h, j: (b_, 0, 0)),
            pl.BlockSpec((1, groups, sq, d), lambda b_, h, j: (b_, h, 0, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda b_, h, j: (b_, h, j, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda b_, h, j: (b_, h, j, 0)),
            pl.BlockSpec((1, groups, sq, d), lambda b_, h, j: (b_, h, 0, 0)),
            pl.BlockSpec((1, groups, sq, 1), lambda b_, h, j: (b_, h, 0, 0)),
            pl.BlockSpec((1, groups, sq, 1), lambda b_, h, j: (b_, h, 0, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, 1, block_k, d), lambda b_, h, j: (b_, h, j, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda b_, h, j: (b_, h, j, 0)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((b, hkv, sq, d), k.dtype),
            jax.ShapeDtypeStruct((b, hkv, sq, d), v.dtype),
        ),
        interpret=interpret,
    )(segments[:, :, None], q, k, v, do, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# custom-vjp wrapper (public entry)
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _make_flash_fn(scale: float, block_q: int, block_k: int, groups: int, interpret: bool):
    """One custom_vjp closure per static configuration."""

    @jax.custom_vjp
    def fn(q, k, v, segments):
        o, _ = _fwd(
            q, k, v, segments,
            scale=scale, block_q=block_q, block_k=block_k, groups=groups,
            interpret=interpret,
        )
        return o

    def fn_fwd(q, k, v, segments):
        o, lse = _fwd(
            q, k, v, segments,
            scale=scale, block_q=block_q, block_k=block_k, groups=groups,
            interpret=interpret,
        )
        return o, (q, k, v, segments, o, lse)

    def fn_bwd(res, do):
        q, k, v, segments, o, lse = res
        dq, dk, dv = _bwd(
            q, k, v, segments, o, lse, do,
            scale=scale, block_q=block_q, block_k=block_k, groups=groups,
            interpret=interpret,
        )
        dsegments = np.zeros(segments.shape, jax.dtypes.float0)
        return dq, dk, dv, dsegments

    fn.defvjp(fn_fwd, fn_bwd)
    return fn


def _pick_block(s: int) -> int:
    import os

    override = os.environ.get("FLASH_BLOCK", "")
    if override:
        blk = int(override)  # perf-sweep knob (BASELINE.md perf ledger)
        if blk % 128:
            raise ValueError(
                f"FLASH_BLOCK={blk} violates the kernel's 128-lane alignment"
            )
        if s % blk:
            raise ValueError(
                f"FLASH_BLOCK={blk} does not divide seq length {s}"
            )
        return blk
    for blk in (512, 256, 128):
        if s % blk == 0:
            return blk
    return 0


def flash_attention_supported(
    q, k, v, *, sliding_window=None, causal: bool = True
) -> bool:
    """Static eligibility check run at trace time by ops/attention.py."""
    b, sq, hq, d = q.shape
    sk = k.shape[1]
    if jax.default_backend() != "tpu":
        return False
    if not causal or sliding_window is not None:
        return False
    if sq != sk or sq > _MAX_KERNEL_SEQ:
        return False  # decode/cache path and very long sequences use xla/ring
    if _pick_block(sq) == 0:
        return False
    if d % 128 != 0:
        return False  # MXU lane alignment (all supported models have d=128)
    return hq % k.shape[2] == 0


# ---------------------------------------------------------------------------
# fused paged decode attention (int8 KV pool)
# ---------------------------------------------------------------------------


def _paged_decode_kernel(
    tables_ref, lengths_ref,  # scalar-prefetch (SMEM)
    q_ref, k_ref, v_ref, ks_ref, vs_ref,  # VMEM inputs
    o_ref,  # VMEM output
    m_ref, l_ref, acc_ref,  # VMEM scratch, persistent across the block dim
    *, scale,
):
    """One (batch row, kv head, table slot) step of online-softmax decode.

    The grid's innermost dim walks the row's block table; the BlockSpec
    index maps have already gathered THIS slot's pool block (and its absmax
    scales) into VMEM via the prefetched table, so the kernel never sees the
    pool — no [b, nb*L] gather materializes anywhere. Dequantization folds
    into the math: k codes scale the logits (``scale * k_absmax/127``), v
    codes scale the accumulator update — two scalar multiplies per block
    instead of casting L*d elements. The (m, l, acc) carry lives in scratch
    that persists across the innermost grid dim; the output block flushes
    once, on the last table slot.
    """
    b_i = pl.program_id(0)
    i = pl.program_id(2)
    nb = pl.num_programs(2)

    @pl.when(i == 0)
    def _init():
        m_ref[...] = jnp.full(m_ref.shape, _NEG_INF, jnp.float32)
        l_ref[...] = jnp.zeros(l_ref.shape, jnp.float32)
        acc_ref[...] = jnp.zeros(acc_ref.shape, jnp.float32)

    q = q_ref[0, 0].astype(jnp.float32)  # [G, d]
    k_blk = k_ref[0, :, 0, :].astype(jnp.float32)  # [L, d] int8 codes
    v_blk = v_ref[0, :, 0, :].astype(jnp.float32)
    g, _ = q.shape
    block_len = k_blk.shape[0]
    k_scale = ks_ref[0, 0] / 127.0
    v_scale = vs_ref[0, 0] / 127.0

    s = jax.lax.dot_general(
        q, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * (scale * k_scale)  # [G, L]
    # gathered index IS logical position (models/transformer._block): slot i
    # of the table covers positions [i*L, (i+1)*L); visible iff < length.
    # Null-table slots gather block 0 (zero codes, zero scale) at positions
    # at/above length, so they are masked here exactly like the XLA path.
    k_pos = i * block_len + jax.lax.broadcasted_iota(
        jnp.int32, (g, block_len), 1
    )
    mask = k_pos < lengths_ref[b_i]
    s = jnp.where(mask, s, _NEG_INF)

    m_prev = m_ref[...]  # [G, 1]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
    alpha = jnp.exp(m_prev - m_new)
    p = jnp.where(mask, jnp.exp(s - m_new), 0.0)  # [G, L]
    l_ref[...] = l_ref[...] * alpha + jnp.sum(p, axis=1, keepdims=True)
    acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
        p, v_blk, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    ) * v_scale
    m_ref[...] = m_new

    @pl.when(i == nb - 1)
    def _flush():
        l_safe = jnp.maximum(l_ref[...], 1e-30)
        o_ref[0, 0] = (acc_ref[...] / l_safe).astype(o_ref.dtype)


def paged_decode_mode() -> str:
    """How ``models/transformer._block`` should read the int8 paged pool at
    decode: ``"fused"`` (Pallas kernel), ``"interpret"`` (kernel under the
    Pallas interpreter — CPU-runnable, tier-1 coverage of the kernel math)
    or ``"xla"`` (dequantizing gather + masked attention — the default
    everywhere off-TPU, so CPU CI never depends on Mosaic). The
    ``PAGED_DECODE`` env var overrides the backend-based choice — the
    serve_bench head-to-head sets it to pin each arm's path."""
    import os

    override = os.environ.get("PAGED_DECODE", "").lower()
    if override in ("fused", "xla", "interpret"):
        return override
    return "fused" if jax.default_backend() == "tpu" else "xla"


def paged_decode_attention(
    q, k_pool, v_pool, k_scale, v_scale, block_tables, *,
    lengths, scale=None, interpret: bool = False,
):
    """Fused decode attention over an int8 block-paged KV pool.

    ``q [b, 1, hq, d]`` (one decode token per row), ``k_pool``/``v_pool``
    int8 ``[num_blocks, L, hkv, d]`` with absmax scales ``[num_blocks,
    hkv]`` f32 (models/transformer.init_paged_cache int8 layout),
    ``block_tables [b, nb]`` int32, ``lengths [b]`` int32 (visible positions
    per row, i.e. query position + 1). Returns ``[b, 1, hq, d]`` in q.dtype.

    Replaces the XLA sequence gather-pool -> dequantize -> mask -> softmax,
    whose gathered ``[b, nb*L, hkv, d]`` view round-trips through HBM every
    decode tick — at batch 32 x 4k context that view is ~8x the bytes of
    the int8 blocks it was gathered from. Here the block table is a scalar-
    prefetch operand, so the BlockSpec index maps DMA exactly the table's
    blocks into VMEM (the paged analog of the fwd kernel's GQA index maps)
    and each is read once, in its 1-byte form.

    Decode is HBM-bandwidth-bound — the opposite regime from the retired
    NF4 matmul kernel (ops/nf4.py nf4_matmul), whose VPU nibble-decode lost
    to the MXU it was feeding. The dequant here is two scalar multiplies
    per block, so the kernel's byte traffic is the int8 pool itself;
    serve_bench's SERVE_QUANT arm measures it head-to-head against the XLA
    gather on the same pool and the bf16 baseline before it ships anywhere
    (fallback policy: ``paged_decode_mode``).

    Measured (serve_bench SERVE_QUANT, tiny preset, CPU via the XLA
    fallback — the regime tier-1 actually runs; TPU numbers go here after
    a device shootout, the nf4_matmul discipline): at an equal
    bf16-equivalent pool budget of 208 KiB the int8 pool sustains 8 decode
    slots vs bf16's 4 (slot ratio 2.0, gate >= 1.8) at 1394 vs 1466
    tokens/sec — the ~5% CPU dequant overhead buys 2x the resident
    batch, and every quantized request's greedy tokens matched the bf16
    arm's. Interpret-mode kernel vs XLA reference: max |diff| 2.4e-7
    (tests/test_quantized_serving.py pins it at 1e-5).
    """
    b, s, hq, d = q.shape
    if s != 1:
        raise ValueError(f"paged decode takes one query token per row, got s={s}")
    num_blocks, block_len, hkv, _ = k_pool.shape
    if hq % hkv:
        raise ValueError(f"q heads {hq} not a multiple of kv heads {hkv}")
    groups = hq // hkv
    nb = block_tables.shape[1]
    if scale is None:
        scale = float(1.0 / np.sqrt(d))
    # head-major grouping: q head h serves kv head h // groups, so the
    # [hkv, G] split is a plain reshape
    qg = q[:, 0].reshape(b, hkv, groups, d)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, hkv, nb),
        in_specs=[
            pl.BlockSpec((1, 1, groups, d), lambda bi, hi, i, t, ln: (bi, hi, 0, 0)),
            pl.BlockSpec((1, block_len, 1, d), lambda bi, hi, i, t, ln: (t[bi, i], 0, hi, 0)),
            pl.BlockSpec((1, block_len, 1, d), lambda bi, hi, i, t, ln: (t[bi, i], 0, hi, 0)),
            pl.BlockSpec((1, 1), lambda bi, hi, i, t, ln: (t[bi, i], hi)),
            pl.BlockSpec((1, 1), lambda bi, hi, i, t, ln: (t[bi, i], hi)),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, groups, d), lambda bi, hi, i, t, ln: (bi, hi, 0, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((groups, 1), jnp.float32),  # m
            pltpu.VMEM((groups, 1), jnp.float32),  # l
            pltpu.VMEM((groups, d), jnp.float32),  # acc
        ],
    )
    out = pl.pallas_call(
        functools.partial(_paged_decode_kernel, scale=float(scale)),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, hkv, groups, d), q.dtype),
        interpret=interpret,
    )(
        block_tables.astype(jnp.int32), lengths.astype(jnp.int32),
        qg, k_pool, v_pool,
        k_scale.astype(jnp.float32), v_scale.astype(jnp.float32),
    )
    return out.reshape(b, 1, hq, d)


def pallas_flash_attention(
    q, k, v, *, padding_mask=None, segment_ids=None, interpret: bool = False
):
    """q [b, sq, hq, d], k/v [b, sk, hkv, d] -> [b, sq, hq, d] (q.dtype).

    Masking is expressed as per-position segments [b, sk] int32: attention
    flows only within equal segment ids (plus causal). ``segment_ids`` comes
    from the packing pipeline (data/packing.py, 0 = pad tail); without it,
    ``padding_mask`` (1 = real) degenerates to the two-segment real/pad case.
    Softmax in f32; causal.
    """
    b, sq, hq, d = q.shape
    hkv = k.shape[2]
    groups = hq // hkv
    if segment_ids is not None:
        segments = segment_ids.astype(jnp.int32)
    elif padding_mask is not None:
        segments = padding_mask.astype(jnp.int32)
    else:
        segments = jnp.ones((b, sq), jnp.int32)

    block = _pick_block(sq)
    if block == 0:
        raise ValueError(
            f"flash attention requires seq length divisible by 128, got {sq} "
            f"(use ops.attention.attention() for automatic XLA fallback)"
        )
    fn = _make_flash_fn(float(1.0 / np.sqrt(d)), block, block, groups, interpret)
    # head-major layout for clean blocking
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    out = fn(qt, kt, vt, segments)
    return out.transpose(0, 2, 1, 3)
