"""RMSNorm, computed in float32 regardless of input dtype (HF Llama semantics)."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def rms_norm(x, weight, eps: float = 1e-6, zero_centered: bool = False):
    """x: [..., hidden]; weight: [hidden]. Returns same dtype as x.

    ``zero_centered``: Gemma convention — the stored weight is an offset
    from 1 (init zeros), out = normed * (1 + w) (HF Gemma2RMSNorm)."""
    dtype = x.dtype
    x32 = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
    normed = x32 * jax.lax.rsqrt(var + eps)
    w32 = weight.astype(jnp.float32)
    if zero_centered:
        w32 = 1.0 + w32
    # HF casts back to input dtype before multiplying by the weight; doing the
    # multiply in f32 and casting once at the end is equivalent within bf16 ulp.
    return (normed * w32).astype(dtype)
