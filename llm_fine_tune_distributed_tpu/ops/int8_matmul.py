"""w8a8 int8 matmul for the frozen-trunk training fast path.

The serving stack's int8 path (ops/int8.py) is *weight-only*: codes dequantize
to bf16 and the matmul runs on the bf16 MXU path — right for bandwidth-bound
batch-1 decode, wrong for the compute-bound training forward. Here both
operands are int8 so the MXU runs its int8 mode (~2x bf16 throughput on
v4/v5e):

- weights: the serving format unchanged — ``kernel_int8 [in, out]`` codes with
  per-output-channel ``kernel_int8_scale [out]`` f32 (absmax/127, symmetric);
- activations: quantized dynamically per ROW (per token) — absmax over the
  feature dim, symmetric, recomputed every step so no calibration pass;
- the product accumulates in int32 (``preferred_element_type``) and a single
  fused f32 rescale ``acc * (row_scale x col_scale)`` dequantizes.

Error model: both roundings are absmax-symmetric, so the result is exact up
to one 8-bit rounding per operand — the parity tests
(tests/test_frozen_trunk.py) pin the band against the bf16 reference.

``TRUNK_MATMUL`` env override (``xla`` | ``pallas`` | ``interpret``) picks the
implementation, PAGED_DECODE-style: ``xla`` is the default everywhere (XLA
lowers the s8xs8->s32 ``dot_general`` onto the MXU int8 path natively, so the
Pallas kernel is a fallback, not the default); ``pallas`` forces the fused
kernel; ``interpret`` runs the kernel under the Pallas interpreter —
CPU-runnable, tier-1 coverage of the kernel math.
"""

from __future__ import annotations

import functools
import os
from typing import Dict, Tuple

import jax
import jax.numpy as jnp

TRUNK_MATMUL_MODES = ("xla", "pallas", "interpret")


def trunk_matmul_mode() -> str:
    """Implementation of the w8a8 trunk matmul. ``TRUNK_MATMUL`` overrides
    the default (``xla``) — bench arms and the interpret/XLA parity tests
    set it to pin each arm's path."""
    override = os.environ.get("TRUNK_MATMUL", "").lower()
    if override in TRUNK_MATMUL_MODES:
        return override
    return "xla"


def quantize_rows_int8(x) -> Tuple[jax.Array, jax.Array]:
    """Dynamic per-row activation quantization: ``x [..., in]`` ->
    ``(codes int8 [..., in], scale f32 [...])`` with absmax/127 symmetric
    scales over the trailing (feature) dim. All-zero rows get scale 1.0 and
    all-zero codes, so they dequantize to exact zeros."""
    xf = x.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(xf), axis=-1)  # [...]
    scale = jnp.where(absmax == 0.0, 1.0, absmax) / 127.0
    codes = jnp.clip(jnp.round(xf / scale[..., None]), -127, 127).astype(jnp.int8)
    return codes, scale


def _w8a8_xla(xq, x_scale, wq, w_scale, compute_dtype):
    """s8 x s8 -> s32 ``dot_general`` + fused f32 rescale. XLA maps the int8
    contraction onto the MXU int8 path on TPU; on CPU it is a plain int32
    GEMM — bit-identical math either way."""
    acc = jax.lax.dot_general(
        xq, wq,
        dimension_numbers=(((xq.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )  # [..., out] int32
    out = acc.astype(jnp.float32) * x_scale[..., None] * w_scale
    return out.astype(compute_dtype)


# ---------------------------------------------------------------------------
# Pallas variant. One fused kernel per (row-block, col-block) grid cell: the
# int8 operand tiles stream HBM -> VMEM, ``jnp.dot`` hits the MXU with an
# int32 accumulator, and the per-row/per-col scales apply before write-back —
# the f32 [M, N] product never round-trips through HBM unscaled. K is kept
# whole per cell (trunk projections have K = hidden or intermediate; the
# largest flagship tile, 128 x 11008 int8 x 2 operands + 128 x 512 f32 out,
# sits well under the ~16MB VMEM budget).
# ---------------------------------------------------------------------------

_BM = 128   # row tile (tokens)
_BN = 512   # output-channel tile


def _w8a8_kernel(xq_ref, wq_ref, xs_ref, ws_ref, out_ref):
    acc = jnp.dot(xq_ref[:], wq_ref[:], preferred_element_type=jnp.int32)
    # scales arrive as 2-D tiles ([bm, 1] rows / [1, bn] cols) — Mosaic wants
    # >=2-D operands, and the broadcast shapes are already matmul-aligned
    out_ref[:] = (acc.astype(jnp.float32) * xs_ref[:] * ws_ref[:]).astype(
        out_ref.dtype
    )


@functools.partial(jax.jit, static_argnames=("compute_dtype", "interpret"))
def _w8a8_pallas(xq, x_scale, wq, w_scale, compute_dtype, interpret=False):
    from jax.experimental import pallas as pl

    m, k = xq.shape
    k2, n = wq.shape
    assert k == k2, (xq.shape, wq.shape)
    bm, bn = min(_BM, m), min(_BN, n)
    pad_m = (-m) % bm
    if pad_m:
        xq = jnp.pad(xq, ((0, pad_m), (0, 0)))
        x_scale = jnp.pad(x_scale, (0, pad_m))
    pad_n = (-n) % bn
    if pad_n:
        wq = jnp.pad(wq, ((0, 0), (0, pad_n)))
        w_scale = jnp.pad(w_scale, (0, pad_n))
    out = pl.pallas_call(
        _w8a8_kernel,
        grid=((m + pad_m) // bm, (n + pad_n) // bn),
        in_specs=[
            pl.BlockSpec((bm, k), lambda i, j: (i, 0)),
            pl.BlockSpec((k, bn), lambda i, j: (0, j)),
            pl.BlockSpec((bm, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((1, bn), lambda i, j: (0, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m + pad_m, n + pad_n), compute_dtype),
        interpret=interpret,
    )(xq, wq, x_scale[:, None], w_scale[None, :])
    return out[:m, :n]


def int8_w8a8_matmul(x, q: Dict, compute_dtype=jnp.bfloat16, impl=None):
    """``x [..., in]`` x serving-format int8 weight ``q`` (``{"int8" [in,
    out], "int8_scale" [out]}``) -> ``[..., out]`` in ``compute_dtype``,
    computed w8a8: dynamic per-row activation quantization, int8 x int8
    contraction with an int32 accumulator, one fused scale dequant.

    ``impl`` defaults to :func:`trunk_matmul_mode`. This op sits behind the
    trunk-boundary ``stop_gradient`` (train/step.py) so it never needs a
    VJP; the rounding is non-differentiable by construction.
    """
    impl = impl or trunk_matmul_mode()
    if impl not in TRUNK_MATMUL_MODES:
        raise ValueError(
            f"unknown trunk matmul impl {impl!r} (expected one of {TRUNK_MATMUL_MODES})"
        )
    xq, x_scale = quantize_rows_int8(x)
    wq, w_scale = q["int8"], q["int8_scale"].astype(jnp.float32)
    if impl == "xla":
        return _w8a8_xla(xq, x_scale, wq, w_scale, compute_dtype)
    lead = xq.shape[:-1]
    out = _w8a8_pallas(
        xq.reshape(-1, xq.shape[-1]),
        x_scale.reshape(-1),
        wq,
        w_scale,
        compute_dtype,
        interpret=impl == "interpret",
    )
    return out.reshape(*lead, out.shape[-1])
