"""Rotary position embeddings (HF Llama "rotate_half" convention).

Must match HF numerics exactly so imported safetensors weights reproduce the
reference model's logits (the reference loads HF SmolLM3-3B,
reference ``training.py:97-102``). HF applies RoPE by splitting the head dim
in half (NOT even/odd interleaving):

    rotate_half(x) = concat(-x[..., d/2:], x[..., :d/2])
    x_rot = x * cos + rotate_half(x) * sin

with ``cos/sin = f(outer(positions, inv_freq))`` tiled twice along the last dim.
"""

from __future__ import annotations

import jax.numpy as jnp


def rope_inv_freq(
    head_dim: int,
    theta: float,
    *,
    scaling_type=None,
    factor: float = 1.0,
    low_freq_factor: float = 1.0,
    high_freq_factor: float = 4.0,
    original_max_position: int = 8192,
):
    """Per-frequency inverse wavelengths, with optional context extension.

    ``scaling_type``:
      - None: plain RoPE.
      - "linear": positions effectively divided by ``factor`` (HF "linear").
      - "llama3": HF's Llama-3.1 smoothed NTK scheme
        (modeling_rope_utils._compute_llama3_parameters) — long wavelengths
        (> original_max/low_freq_factor) are slowed by ``factor``, short ones
        (< original_max/high_freq_factor) untouched, with linear interpolation
        in between. Matching HF exactly is required for imported Llama-3.1+
        checkpoints to reproduce reference logits.
    """
    half = head_dim // 2
    inv_freq = 1.0 / (theta ** (jnp.arange(0, half, dtype=jnp.float32) / half))
    if scaling_type in (None, "default"):
        return inv_freq
    if scaling_type == "linear":
        return inv_freq / factor
    if scaling_type == "llama3":
        low_freq_wavelen = original_max_position / low_freq_factor
        high_freq_wavelen = original_max_position / high_freq_factor
        wavelen = 2.0 * jnp.pi / inv_freq
        scaled = inv_freq / factor
        smooth = (original_max_position / wavelen - low_freq_factor) / (
            high_freq_factor - low_freq_factor
        )
        smoothed = (1.0 - smooth) * scaled + smooth * inv_freq
        out = jnp.where(wavelen > low_freq_wavelen, scaled, inv_freq)
        is_medium = (wavelen <= low_freq_wavelen) & (wavelen >= high_freq_wavelen)
        return jnp.where(is_medium, smoothed, out)
    raise ValueError(f"unsupported rope scaling type: {scaling_type!r}")


def rope_cos_sin(positions, head_dim: int, theta: float, dtype=jnp.float32, *, config=None):
    """Compute cos/sin tables for given positions.

    Args:
      positions: int array [...,] token positions (any leading shape).
      head_dim: per-head dimension (must be even).
      theta: RoPE base frequency.
      config: optional ModelConfig; when given, its rope_scaling_* fields
        select the context-extension scheme (Llama-3.1 "llama3", "linear").

    Returns:
      (cos, sin) arrays of shape positions.shape + (head_dim,).
    """
    # f32 throughout: bf16 position phases destroy long-context accuracy.
    if config is not None and config.rope_scaling_type:
        inv_freq = rope_inv_freq(
            head_dim,
            theta,
            scaling_type=config.rope_scaling_type,
            factor=config.rope_scaling_factor,
            low_freq_factor=config.rope_low_freq_factor,
            high_freq_factor=config.rope_high_freq_factor,
            original_max_position=config.rope_original_max_position,
        )
    else:
        inv_freq = rope_inv_freq(head_dim, theta)
    freqs = positions.astype(jnp.float32)[..., None] * inv_freq  # [..., half]
    emb = jnp.concatenate([freqs, freqs], axis=-1)  # [..., head_dim]
    return jnp.cos(emb).astype(dtype), jnp.sin(emb).astype(dtype)


def _rotate_half(x):
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate([-x2, x1], axis=-1)


def apply_rope(q, k, cos, sin):
    """Apply rotary embedding to q and k.

    Args:
      q: [batch, seq, num_heads, head_dim]
      k: [batch, seq, num_kv_heads, head_dim]
      cos/sin: [batch, seq, head_dim] (or broadcastable)

    Returns rotated (q, k), same dtypes as inputs.
    """
    # Broadcast over the heads axis.
    c = cos[..., None, :]
    s = sin[..., None, :]
    q_dtype, k_dtype = q.dtype, k.dtype
    q32, k32 = q.astype(jnp.float32), k.astype(jnp.float32)
    c32, s32 = c.astype(jnp.float32), s.astype(jnp.float32)
    q_rot = q32 * c32 + _rotate_half(q32) * s32
    k_rot = k32 * c32 + _rotate_half(k32) * s32
    return q_rot.astype(q_dtype), k_rot.astype(k_dtype)
