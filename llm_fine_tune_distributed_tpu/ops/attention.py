"""Attention implementations and dispatch.

Replaces the reference's flash-attn-2 CUDA kernels
(reference ``requirements.txt:10``, ``training.py:101``) with TPU paths:

- ``"xla"``:   plain masked attention — XLA fuses this well at seq<=1024 and it
               is the numerically-transparent fallback.
- ``"flash"``: Pallas (Mosaic) blockwise flash attention kernel (ops/flash_attention.py).
- ``"ring"``:  ring attention over a sequence-parallel mesh axis (parallel/ring_attention.py),
               selected by the trainer when mesh.seq > 1.
- ``"ulysses"``: all-to-all sequence parallelism (parallel/ulysses.py) — heads
               re-partitioned over the seq axis so each device runs full-sequence
               flash attention on its head subset.

All implementations take/return the same layout:
  q: [batch, q_len, num_heads, head_dim]
  k,v: [batch, kv_len, num_kv_heads, head_dim]   (GQA: num_heads % num_kv_heads == 0)
and compute softmax in float32.
"""

from __future__ import annotations

import collections
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

_NEG_INF = -2.0e38  # large finite negative; avoids NaN from (-inf) - (-inf)

# Trace-time dispatch ledger: which implementation each attention() call
# actually resolved to (post-fallback). A sequence-parallel impl silently
# degrading to flash/XLA is the difference between a live seq axis and dead
# parallelism (VERDICT r4 weak #1: a "ulysses parity test" that really
# exercised the fallback), so the resolution is recorded where it happens and
# parallel/diagnostics.assert_seq_parallel() lets tests/users pin the path.
_DISPATCH_COUNTS: collections.Counter = collections.Counter()


def dispatch_count(impl: str) -> int:
    """How many attention() calls resolved to ``impl`` (trace-time count)."""
    return _DISPATCH_COUNTS[impl]


def _causal_mask(q_len: int, kv_len: int, sliding_window: Optional[int] = None):
    """[q_len, kv_len] bool mask; True = attend. Supports decode offset where
    q positions are the last q_len of kv_len."""
    q_pos = jnp.arange(q_len)[:, None] + (kv_len - q_len)
    k_pos = jnp.arange(kv_len)[None, :]
    mask = k_pos <= q_pos
    if sliding_window is not None:
        mask &= k_pos > q_pos - sliding_window
    return mask


def softcap(x, cap):
    """Gemma2 logit soft-capping: cap * tanh(x / cap).

    Single definition shared by attention scores, unembed, and the
    vocab-streamed CE — the streamed loss must stay bit-identical to the
    materialized-logits path, so the formula must not fork."""
    return cap * jnp.tanh(x / cap)


def xla_attention(
    q,
    k,
    v,
    *,
    padding_mask=None,
    segment_ids=None,
    causal: bool = True,
    sliding_window: Optional[int] = None,
    mask=None,
    scale: Optional[float] = None,
    logit_softcap: Optional[float] = None,
):
    """Reference masked attention with GQA, f32 softmax.

    padding_mask: optional [batch, kv_len] bool/int, 1 = real token.
    segment_ids: optional [batch, kv_len] int32 packing segments — attention
      is restricted to equal ids (block-diagonal; 0 = pad tail).
    mask: optional explicit [batch, q_len, kv_len] bool mask (True = attend);
      when given it replaces the causal mask (used by the KV-cache decode path).
    """
    b, q_len, num_heads, head_dim = q.shape
    kv_len, num_kv = k.shape[1], k.shape[2]
    groups = num_heads // num_kv

    if scale is None:
        scale = 1.0 / jnp.sqrt(head_dim).astype(jnp.float32)
    # [b, q, kv_heads, groups, d]
    qg = q.reshape(b, q_len, num_kv, groups, head_dim)
    # scores: [b, kv_heads, groups, q, kv]
    scores = jnp.einsum("bqhgd,bkhd->bhgqk", qg.astype(jnp.float32), k.astype(jnp.float32))
    scores = scores * scale
    if logit_softcap is not None:
        # Gemma2: cap BEFORE masking (HF Gemma2Attention eager path)
        scores = softcap(scores, logit_softcap)

    if mask is not None:
        scores = jnp.where(mask[:, None, None], scores, _NEG_INF)
    elif causal:
        cmask = _causal_mask(q_len, kv_len, sliding_window)
        scores = jnp.where(cmask[None, None, None], scores, _NEG_INF)
    if padding_mask is not None:
        pm = padding_mask.astype(bool)[:, None, None, None, :]
        scores = jnp.where(pm, scores, _NEG_INF)
    if segment_ids is not None:
        # note: a fully-masked row is safe — _NEG_INF is finite, so softmax
        # degrades to uniform garbage on pad rows, which the loss mask drops
        same = segment_ids[:, None, :] == segment_ids[:, :, None]  # [b, q, kv]
        scores = jnp.where(same[:, None, None], scores, _NEG_INF)

    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhgqk,bkhd->bqhgd", probs, v.astype(jnp.float32))
    return out.reshape(b, q_len, num_heads, head_dim).astype(q.dtype)


def _seq_parallel_fallback(impl: str, q, mesh) -> str:
    """Fallback target when a sequence-parallel impl cannot apply.

    A missing/size-1 seq axis is the ordinary single-device case — fall back
    quietly. A PROVISIONED seq axis with an unsupported shape (e.g. ulysses
    capped by kv heads, or an indivisible seq length) means the user's
    parallelism is silently dead — be loud, because at long-context shapes
    the difference between the flash kernel and quadratic XLA attention is
    an OOM. Either way prefer "flash" (linear memory), which itself degrades
    to XLA attention only when truly unsupported."""
    if mesh is not None and mesh.shape.get("seq", 1) > 1:
        import warnings

        warnings.warn(
            f"attention_impl={impl!r} requested but unsupported for shape "
            f"q={tuple(q.shape)} on mesh {dict(mesh.shape)} — the seq axis is "
            "NOT being used; falling back to flash/XLA attention (check head/"
            "kv-head divisibility by the seq axis and seq-length alignment)",
            stacklevel=3,
        )
    return "flash"


def attention(
    q,
    k,
    v,
    *,
    impl: str = "xla",
    padding_mask=None,
    segment_ids=None,
    causal: bool = True,
    sliding_window: Optional[int] = None,
    mesh=None,
    scale: Optional[float] = None,
    logit_softcap: Optional[float] = None,
):
    """Dispatch to the selected attention implementation.

    ``mesh`` is consulted by the sequence-parallel paths (ring and ulysses);
    the trainer passes the active mesh whenever ``attention_impl`` is one of
    those. Without a mesh (or with an unsupported shape) they fall back to
    the flash kernel, which itself degrades to XLA attention when it cannot
    apply.

    ``scale`` / ``logit_softcap`` (Gemma2 query_pre_attn_scalar and
    attn_logit_softcapping): only the XLA path implements them, so a
    non-default value routes there directly — the tanh softcap breaks the
    flash kernel's running-max algebra, and correctness beats kernel speed
    for the families that need it.
    """
    if scale is not None or logit_softcap is not None:
        if impl in ("ring_manual", "ulysses_manual"):
            # inside a shard_map manual over seq, a block-local xla fallback
            # would silently drop cross-shard attention — refuse instead
            raise ValueError(
                f"{impl} does not support custom scale / logit softcap"
            )
        if impl in ("ring", "ulysses"):
            # loud when a provisioned seq axis goes unused (same contract as
            # the shape-based fallback)
            impl = _seq_parallel_fallback(impl, q, mesh)
        return xla_attention(
            q, k, v, padding_mask=padding_mask, segment_ids=segment_ids,
            causal=causal, sliding_window=sliding_window,
            scale=scale, logit_softcap=logit_softcap,
        )
    if impl == "ulysses":
        from llm_fine_tune_distributed_tpu.parallel.ulysses import (
            ulysses_attention,
            ulysses_attention_supported,
        )

        if ulysses_attention_supported(
            q, k, mesh, sliding_window=sliding_window, causal=causal
        ):
            _DISPATCH_COUNTS["ulysses"] += 1
            return ulysses_attention(
                q, k, v, mesh=mesh, padding_mask=padding_mask,
                segment_ids=segment_ids, causal=causal
            )
        impl = _seq_parallel_fallback("ulysses", q, mesh)
    if impl == "ring":
        from llm_fine_tune_distributed_tpu.parallel.ring_attention import (
            ring_attention,
            ring_attention_supported,
        )

        if ring_attention_supported(
            q, k, mesh, sliding_window=sliding_window, causal=causal
        ):
            _DISPATCH_COUNTS["ring"] += 1
            return ring_attention(
                q, k, v, mesh=mesh, padding_mask=padding_mask,
                segment_ids=segment_ids, causal=causal
            )
        impl = _seq_parallel_fallback("ring", q, mesh)
    if impl == "ulysses_manual":
        # Same manual-context contract as ring_manual below: the caller is
        # inside a shard_map manual over "seq", q/k/v are sequence chunks,
        # and the local kernel's all_to_all/all_gather ride that axis.
        from llm_fine_tune_distributed_tpu.parallel.ulysses import (
            _local_ulysses_attention,
        )

        if sliding_window is not None:
            raise ValueError("ulysses attention has no sliding-window support")
        if segment_ids is not None:
            # the pipeline schedule (the only manual-context caller) rejects
            # packing up front; reaching here would silently drop the mask
            raise ValueError("ulysses_manual has no segment support")
        _DISPATCH_COUNTS["ulysses_manual"] += 1
        return _local_ulysses_attention(
            q, k, v, padding_mask,
            axis_name="seq", causal=causal, attention_impl="flash",
        )
    if impl == "ring_manual":
        # The caller is ALREADY inside a shard_map that is manual over the
        # "seq" axis (the pipeline schedule, pipe x ring composition):
        # q/k/v here are one device's sequence CHUNKS, so dispatch straight
        # to the local ring kernel — wrapping the global-view entry would
        # illegally nest a manual "seq" shard_map.
        from llm_fine_tune_distributed_tpu.parallel.ring_attention import (
            _local_ring_attention,
        )

        if sliding_window is not None:
            raise ValueError("ring attention has no sliding-window support")
        if segment_ids is not None:
            raise ValueError("ring_manual has no segment support")
        _DISPATCH_COUNTS["ring_manual"] += 1
        return _local_ring_attention(
            q, k, v, padding_mask,
            axis_name="seq", axis_size=mesh.shape["seq"], causal=causal,
        )
    if impl == "flash":
        # Pallas kernel requires TPU, no sliding window (falls back otherwise).
        from llm_fine_tune_distributed_tpu.ops.flash_attention import (
            flash_attention_supported,
            pallas_flash_attention,
        )

        if flash_attention_supported(q, k, v, sliding_window=sliding_window, causal=causal):
            _DISPATCH_COUNTS["flash"] += 1
            return pallas_flash_attention(
                q, k, v, padding_mask=padding_mask, segment_ids=segment_ids
            )
        impl = "xla"
    if impl == "xla":
        _DISPATCH_COUNTS["xla"] += 1
        return xla_attention(
            q, k, v, padding_mask=padding_mask, segment_ids=segment_ids,
            causal=causal, sliding_window=sliding_window,
        )
    raise ValueError(f"unknown attention impl {impl!r}")
