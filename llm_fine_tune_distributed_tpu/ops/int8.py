"""Int8 weight-only quantization for inference.

Batch-1 autoregressive decode is weight-bandwidth-bound: every generated
token reads every matmul weight once, so tokens/sec is HBM GB/s divided by
the weight-stream size. NF4 (ops/nf4.py) halves that stream twice over but
its nibble unpack is VPU-bound on v5e (measured 20 tok/s vs 73 bf16 for the
3B flagship, benchmarks/decode_bench.py). Int8 sits in the sweet spot:

- the weight stream halves (int8 at rest vs bf16);
- dequantization is ONE convert + ONE broadcast multiply, which XLA fuses
  into the matmul operand read — no unpack, no codebook lookup;
- symmetric per-output-channel scales keep matmul semantics exact up to the
  8-bit rounding (no zero points to fold).

Storage: sibling leaves ``kernel_int8 [in, out] int8`` +
``kernel_int8_scale [out] f32`` (per-output-channel absmax / 127), consumed
by ``models/transformer._linear`` exactly like the NF4 leaves. This is an
INFERENCE format — the trainer never produces it; ``quantize_params_int8``
converts a loaded checkpoint in one pass (CLI flag ``--quantize int8`` on
the inference entry points).
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp

INT8_SUFFIXES = ("int8", "int8_scale")


def quantize_int8(w) -> Dict[str, jax.Array]:
    """``w [in, out]`` -> {"int8": int8 [in, out], "int8_scale": f32 [out]}."""
    w = jnp.asarray(w)
    if w.ndim != 2:
        raise ValueError(f"quantize_int8 expects a 2-D weight, got {w.shape}")
    absmax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=0)  # [out]
    scale = jnp.where(absmax == 0.0, 1.0, absmax) / 127.0
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / scale[None, :]), -127, 127)
    return {"int8": q.astype(jnp.int8), "int8_scale": scale.astype(jnp.float32)}


def dequantize_int8(q: Dict, dtype=jnp.bfloat16):
    """Inverse: int8 codes * per-channel scale -> [in, out] in ``dtype``."""
    return (
        q["int8"].astype(jnp.float32) * q["int8_scale"][None, :].astype(jnp.float32)
    ).astype(dtype)


def int8_matmul(x, q: Dict, compute_dtype=jnp.bfloat16):
    """``x [..., in] @ dequant(q)``. The convert+scale fuses into the matmul
    operand read under XLA; the HBM stream is the int8 codes. The scale is
    applied in f32 and the product cast once, so this path and
    ``dequantize_int8`` agree exactly (up to the single cast) instead of
    compounding a bf16-rounded scale on top of the 8-bit rounding."""
    w = (q["int8"].astype(jnp.float32) * q["int8_scale"][None, :]).astype(compute_dtype)
    return x.astype(compute_dtype) @ w


def quantize_int8_stacked(w) -> Dict[str, jax.Array]:
    """Stacked expert weight ``[E, in, out]`` -> int8 codes + per-(expert,
    channel) scales ``[E, out]`` (each expert quantized independently)."""
    w = jnp.asarray(w)
    if w.ndim != 3:
        raise ValueError(f"quantize_int8_stacked expects [E, in, out], got {w.shape}")
    absmax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=1)  # [E, out]
    scale = jnp.where(absmax == 0.0, 1.0, absmax) / 127.0
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / scale[:, None, :]), -127, 127)
    return {"int8": q.astype(jnp.int8), "int8_scale": scale.astype(jnp.float32)}


def dequantize_int8_stacked(q: Dict, dtype=jnp.bfloat16):
    """Inverse: [E, in, out] in ``dtype``."""
    return (
        q["int8"].astype(jnp.float32) * q["int8_scale"][:, None, :].astype(jnp.float32)
    ).astype(dtype)


# the single source of truth for inference quantization modes (CLI choices,
# server fail-fast check, and maybe_quantize all reference this)
QUANTIZE_MODES = ("none", "int8")


def maybe_quantize(params, mode: str):
    """Shared inference-entry helper (CLI + server): apply the selected
    weight-only quantization mode to a loaded params pytree."""
    if mode not in QUANTIZE_MODES:
        raise ValueError(
            f"unknown quantize mode {mode!r} (expected one of {QUANTIZE_MODES})"
        )
    if mode == "none":
        return params
    print("Quantizing block linears to int8 (weight-only) ...")
    return quantize_params_int8(params)


def quantize_params_int8(params, predicate=None):
    """Replace every matching 2-D ``.../kernel`` leaf (transformer-block
    linears by default) with its int8 sibling leaves. Works on the nested
    params pytree; non-matching leaves pass through untouched.

    Embeddings and the lm_head stay full precision: the embedding gather
    reads one row per token (not bandwidth-bound) and the unembed feeds the
    sampling distribution where 8-bit rounding is most visible. The MoE
    router gate also stays exact — same reasoning as the NF4 path
    (parallel/qlora._is_quantizable): it is ~0.01% of the bytes and 8-bit
    rounding there would perturb every routing decision.
    """
    def is_stacked_expert(path: str) -> bool:
        return path.endswith(("/experts/w1", "/experts/w2", "/experts/w3"))

    if predicate is None:
        predicate = lambda path: "/layers/" in path and (
            (path.endswith("/kernel") and not path.endswith("block_sparse_moe/gate/kernel"))
            or is_stacked_expert(path)
        )

    from llm_fine_tune_distributed_tpu.utils.tree import flatten_dict, unflatten_dict

    flat = flatten_dict(params)
    out = {}
    for path, leaf in flat.items():
        if not predicate(path):
            out[path] = leaf
        elif getattr(leaf, "ndim", 0) == 2 and path.endswith("/kernel"):
            q = quantize_int8(leaf)
            for suffix in INT8_SUFFIXES:
                out[f"{path}_{suffix}"] = q[suffix]
        elif getattr(leaf, "ndim", 0) == 3 and is_stacked_expert(path):
            q = quantize_int8_stacked(leaf)
            for suffix in INT8_SUFFIXES:
                out[f"{path}_{suffix}"] = q[suffix]
        else:
            # a predicate hit with no int8 form (embedding, norm, odd shape)
            # would produce orphaned leaves no consumer reads — be loud
            raise ValueError(
                f"predicate matched {path!r} (ndim="
                f"{getattr(leaf, 'ndim', None)}) but only 2-D .../kernel "
                "leaves and stacked 3-D expert weights have an int8 form"
            )
    return unflatten_dict(out)
