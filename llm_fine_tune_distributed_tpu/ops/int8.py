"""Int8 weight-only quantization for inference.

Batch-1 autoregressive decode is weight-bandwidth-bound: every generated
token reads every matmul weight once, so tokens/sec is HBM GB/s divided by
the weight-stream size. NF4 (ops/nf4.py) halves that stream twice over but
its nibble unpack is VPU-bound on v5e (measured 20 tok/s vs 73 bf16 for the
3B flagship, benchmarks/decode_bench.py). Int8 sits in the sweet spot:

- the weight stream halves (int8 at rest vs bf16);
- dequantization is ONE convert + ONE broadcast multiply, which XLA fuses
  into the matmul operand read — no unpack, no codebook lookup;
- symmetric per-output-channel scales keep matmul semantics exact up to the
  8-bit rounding (no zero points to fold).

Storage: sibling leaves ``kernel_int8 [in, out] int8`` +
``kernel_int8_scale [out] f32`` (per-output-channel absmax / 127), consumed
by ``models/transformer._linear`` exactly like the NF4 leaves. This is an
INFERENCE format — the trainer never produces it; ``quantize_params_int8``
converts a loaded checkpoint in one pass (CLI flag ``--quantize int8`` on
the inference entry points).
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp

INT8_SUFFIXES = ("int8", "int8_scale")


def quantize_int8(w) -> Dict[str, jax.Array]:
    """``w [in, out]`` -> {"int8": int8 [in, out], "int8_scale": f32 [out]}."""
    w = jnp.asarray(w)
    if w.ndim != 2:
        raise ValueError(f"quantize_int8 expects a 2-D weight, got {w.shape}")
    absmax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=0)  # [out]
    scale = jnp.where(absmax == 0.0, 1.0, absmax) / 127.0
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / scale[None, :]), -127, 127)
    return {"int8": q.astype(jnp.int8), "int8_scale": scale.astype(jnp.float32)}


def dequantize_int8(q: Dict, dtype=jnp.bfloat16):
    """Inverse: int8 codes * per-channel scale -> [in, out] in ``dtype``."""
    return (
        q["int8"].astype(jnp.float32) * q["int8_scale"][None, :].astype(jnp.float32)
    ).astype(dtype)


def int8_matmul(x, q: Dict, compute_dtype=jnp.bfloat16):
    """``x [..., in] @ dequant(q)``. The convert+scale fuses into the matmul
    operand read under XLA; the HBM stream is the int8 codes. The scale is
    applied in f32 and the product cast once, so this path and
    ``dequantize_int8`` agree exactly (up to the single cast) instead of
    compounding a bf16-rounded scale on top of the 8-bit rounding."""
    w = (q["int8"].astype(jnp.float32) * q["int8_scale"][None, :]).astype(compute_dtype)
    return x.astype(compute_dtype) @ w


def quantize_int8_stacked(w) -> Dict[str, jax.Array]:
    """Stacked expert weight ``[E, in, out]`` -> int8 codes + per-(expert,
    channel) scales ``[E, out]`` (each expert quantized independently)."""
    w = jnp.asarray(w)
    if w.ndim != 3:
        raise ValueError(f"quantize_int8_stacked expects [E, in, out], got {w.shape}")
    absmax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=1)  # [E, out]
    scale = jnp.where(absmax == 0.0, 1.0, absmax) / 127.0
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / scale[:, None, :]), -127, 127)
    return {"int8": q.astype(jnp.int8), "int8_scale": scale.astype(jnp.float32)}


def dequantize_int8_stacked(q: Dict, dtype=jnp.bfloat16):
    """Inverse: [E, in, out] in ``dtype``."""
    return (
        q["int8"].astype(jnp.float32) * q["int8_scale"][:, None, :].astype(jnp.float32)
    ).astype(dtype)


# the single source of truth for inference quantization modes (CLI choices,
# server fail-fast check, and maybe_quantize all reference this)
QUANTIZE_MODES = ("none", "int8", "nf4")

# paged-KV-pool quantization modes (--quantize-kv): per-block int8 with a
# sibling absmax-scale pool (models/transformer.init_paged_cache)
KV_QUANT_MODES = ("none", "int8")


def maybe_quantize(params, mode: str):
    """Shared inference-entry helper (CLI + server): apply the selected
    weight-only quantization mode to a loaded params pytree."""
    if mode not in QUANTIZE_MODES:
        raise ValueError(
            f"unknown quantize mode {mode!r} (expected one of {QUANTIZE_MODES})"
        )
    if mode == "none":
        return params
    if mode == "nf4":
        from llm_fine_tune_distributed_tpu.ops.nf4 import quantize_params_nf4

        print("Quantizing block linears to NF4 (weight-only) ...")
        return quantize_params_nf4(params)
    print("Quantizing block linears to int8 (weight-only) ...")
    return quantize_params_int8(params)


def quantize_params_int8(params, predicate=None):
    """Replace every matching 2-D ``.../kernel`` leaf (transformer-block
    linears by default) with its int8 sibling leaves. Works on the nested
    params pytree; non-matching leaves pass through untouched.

    Embeddings and the lm_head stay full precision: the embedding gather
    reads one row per token (not bandwidth-bound) and the unembed feeds the
    sampling distribution where 8-bit rounding is most visible. The MoE
    router gate also stays exact — same reasoning as the NF4 path
    (parallel/qlora._is_quantizable): it is ~0.01% of the bytes and 8-bit
    rounding there would perturb every routing decision.
    """
    def is_stacked_expert(path: str) -> bool:
        return path.endswith(("/experts/w1", "/experts/w2", "/experts/w3"))

    if predicate is None:
        predicate = lambda path: "/layers/" in path and (
            (path.endswith("/kernel") and not path.endswith("block_sparse_moe/gate/kernel"))
            or is_stacked_expert(path)
        )

    from llm_fine_tune_distributed_tpu.utils.tree import flatten_dict, unflatten_dict

    flat = flatten_dict(params)
    out = {}
    for path, leaf in flat.items():
        if not predicate(path):
            out[path] = leaf
        elif getattr(leaf, "ndim", 0) == 2 and path.endswith("/kernel"):
            q = quantize_int8(leaf)
            for suffix in INT8_SUFFIXES:
                out[f"{path}_{suffix}"] = q[suffix]
        elif getattr(leaf, "ndim", 0) == 3 and is_stacked_expert(path):
            q = quantize_int8_stacked(leaf)
            for suffix in INT8_SUFFIXES:
                out[f"{path}_{suffix}"] = q[suffix]
        else:
            # a predicate hit with no int8 form (embedding, norm, odd shape)
            # would produce orphaned leaves no consumer reads — be loud
            raise ValueError(
                f"predicate matched {path!r} (ndim="
                f"{getattr(leaf, 'ndim', None)}) but only 2-D .../kernel "
                "leaves and stacked 3-D expert weights have an int8 form"
            )
    return unflatten_dict(out)


# ---------------------------------------------------------------------------
# Paged KV pool quantization (--quantize-kv int8)
#
# The pool keeps the bf16 layout's [num_blocks, block_len, kv_heads, head_dim]
# shape in int8 plus a sibling absmax pool [num_blocks, kv_heads] f32 indexed
# by the SAME block ids the block tables carry (infer/paged.py allocates ids,
# never bytes, so it is untouched). Per-(block, kv-head) scales rather than
# per-block: the k/v magnitude spread across heads is the dominant error term
# at 8 bits, and the extra scale column costs 4 bytes per head per block
# against block_len * head_dim codes. Codes are symmetric absmax/127, like
# the weight path; scale 0 means "never written" and dequantizes to exactly
# 0.0, which keeps the null block (id 0) all-zero by construction.
# ---------------------------------------------------------------------------


def quantize_kv_write(codes, scales, blk, off, x):
    """Scatter new K or V tokens into an int8 paged pool, growing per-block
    scales as needed.

    ``codes`` int8 [num_blocks, block_len, kv_heads, d], ``scales`` f32
    [num_blocks, kv_heads] (per-block-per-head absmax), ``blk``/``off``
    int32 [b, s] (pool block id / slot within block per token), ``x``
    [b, s, kv_heads, d]. Returns ``(new_codes, new_scales)``.

    A write may raise a block's absmax, so the block's EXISTING codes are
    re-expressed under the grown scale (gather touched blocks, multiply by
    old/new, round, scatter back). Blocks whose scale did not grow rescale
    by exactly 1.0 — an int8 -> f32 -> round -> int8 identity — so blocks
    not written this call (in particular COW-shared prefix blocks, which are
    never written again after their last prefill token) stay bit-stable.
    Duplicate block ids within one call (a prefill chunk spanning a block)
    compute identical rescaled content from the already-maxed new scales, so
    overlapping scatters agree regardless of order. Writes routed to the
    null block (id 0 — dead rows, clamped redirects) are forced to zero
    codes and a zero scale, so block 0 dequantizes to 0.0 forever.
    """
    xf = x.astype(jnp.float32)
    null = blk == 0  # [b, s]
    tok_amax = jnp.where(
        null[..., None], 0.0, jnp.max(jnp.abs(xf), axis=-1)
    )  # [b, s, h]
    new_scales = scales.at[blk].max(tok_amax)
    old_blk = scales[blk]  # [b, s, h]
    new_blk = new_scales[blk]
    safe_new = jnp.where(new_blk == 0.0, 1.0, new_blk)
    ratio = jnp.where(new_blk == 0.0, 0.0, old_blk / safe_new)
    touched = codes[blk].astype(jnp.float32)  # [b, s, L, h, d]
    rescaled = jnp.clip(
        jnp.round(touched * ratio[:, :, None, :, None]), -127, 127
    ).astype(jnp.int8)
    new_codes = codes.at[blk].set(rescaled)
    q = jnp.clip(jnp.round(xf * (127.0 / safe_new[..., None])), -127, 127)
    q = jnp.where(null[..., None, None], 0, q.astype(jnp.int8))
    new_codes = new_codes.at[blk, off].set(q)
    return new_codes, new_scales


def dequantize_kv_gather(codes, scales, block_tables, dtype=jnp.bfloat16):
    """Gather a row's table blocks out of an int8 paged pool into the dense
    [b, nb * block_len, kv_heads, d] view ``models/transformer._block``
    attends over (the XLA fallback for the fused Pallas decode kernel —
    ops/flash_attention.paged_decode_attention). The gathered index IS the
    logical position, exactly like the bf16 layout, so the caller's position
    mask applies unchanged; null-table entries gather block 0, whose scale
    is pinned at 0 so they dequantize to exact zeros."""
    b, nb = block_tables.shape
    _, L, h, d = codes.shape
    flat = block_tables.reshape(-1)
    blocks = codes[flat].astype(jnp.float32).reshape(b, nb, L, h, d)
    sc = (scales[flat] / 127.0).reshape(b, nb, 1, h, 1)
    return (blocks * sc).astype(dtype).reshape(b, nb * L, h, d)
