"""Pytree helpers keyed by parameter path (used by freezing, sharding, LoRA)."""

from __future__ import annotations

from typing import Callable, Iterable, Tuple

import jax
import numpy as np


def _path_str(key_path) -> str:
    parts = []
    for k in key_path:
        if hasattr(k, "key"):
            parts.append(str(k.key))
        elif hasattr(k, "idx"):
            parts.append(str(k.idx))
        else:
            parts.append(str(k))
    return "/".join(parts)


def tree_paths(tree) -> list:
    """List of (path_str, leaf) for every leaf."""
    leaves = jax.tree_util.tree_leaves_with_path(tree)
    return [(_path_str(kp), leaf) for kp, leaf in leaves]


def map_with_path(fn: Callable[[str, object], object], tree):
    """tree_map where fn receives ('model/layers/0/self_attn/q_proj/kernel', leaf)."""
    return jax.tree_util.tree_map_with_path(lambda kp, leaf: fn(_path_str(kp), leaf), tree)


def count_params(tree) -> int:
    return sum(int(np.prod(leaf.shape)) for leaf in jax.tree_util.tree_leaves(tree))


def count_params_where(tree, predicate: Callable[[str], bool]) -> int:
    total = 0
    for path, leaf in tree_paths(tree):
        if predicate(path):
            total += int(np.prod(leaf.shape))
    return total
