"""Version compatibility shims for the pinned jax.

``jax.shard_map`` became a top-level export (with ``check_vma`` /
``axis_names`` keywords) after the 0.4 series; the pinned jax 0.4.37 only
ships ``jax.experimental.shard_map.shard_map`` (``check_rep`` /
``auto``). Every call site in this repo routes through this module so the
codebase is written against the MODERN surface and the translation to the
experimental one lives in exactly one place:

- ``check_vma`` (new name) -> ``check_rep`` (old name): both toggle the
  replication/varying-manual-axes check.
- ``axis_names`` (the axes the body is MANUAL over) -> ``auto`` (the
  complement: mesh axes left automatic/GSPMD-partitioned).

``jax.sharding.AxisType`` (Auto/Explicit mesh axis typing) is likewise
newer than 0.4.37. On 0.4.x GSPMD auto-propagation is the ONLY mesh
semantics, so "Auto axis types" degrades to constructing the mesh without
the kwarg — ``mesh_auto_axis_types`` / ``make_mesh`` encode that.
"""

from __future__ import annotations

from typing import Optional, Set

import jax


def shard_map(f, mesh, in_specs, out_specs, *,
              check_vma: bool = True, axis_names: Optional[Set] = None):
    """``jax.shard_map`` when available, else the experimental fallback."""
    if hasattr(jax, "shard_map"):
        kwargs = {"check_vma": check_vma}
        if axis_names is not None:
            kwargs["axis_names"] = set(axis_names)
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    auto = frozenset()
    if axis_names is not None:
        auto = frozenset(mesh.axis_names) - frozenset(axis_names)
    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=check_vma, auto=auto,
    )


def mesh_auto_axis_types(n: int):
    """``(AxisType.Auto,) * n`` where AxisType exists, else None (0.4.x,
    where every mesh axis is implicitly auto and Mesh/make_mesh take no
    ``axis_types``)."""
    axis_type = getattr(jax.sharding, "AxisType", None)
    if axis_type is None:
        return None
    return (axis_type.Auto,) * n


def mesh_kwargs(axis_types) -> dict:
    """kwargs for Mesh()/jax.make_mesh(): {} when axis_types is None."""
    return {} if axis_types is None else {"axis_types": axis_types}


def make_mesh(axis_shapes, axis_names, *, auto_axis_types: bool = True):
    """``jax.make_mesh`` with Auto axis types where the pinned jax supports
    typed mesh axes, plain ``jax.make_mesh`` otherwise."""
    kwargs = {}
    if auto_axis_types:
        kwargs = mesh_kwargs(mesh_auto_axis_types(len(axis_names)))
    return jax.make_mesh(axis_shapes, axis_names, **kwargs)
