"""Parallel device->host transfer.

On tunneled/remote-TPU links (docs/operating-manual.md "Tunneled /
remote-TPU environments") a single device->host stream sustains ~16 MB/s,
but the link multiplexes: four concurrent fetches aggregate ~42 MB/s
(measured on the v5e tunnel, r5). The artifact-export and checkpoint paths
move 2-6 GB at end of training, so fetching leaves through a small thread
pool — splitting any huge leaf into row blocks so one 0.5 GB embedding
table cannot serialize the pool — cuts the terminal wall-clock stall ~2.6x.
On local-PCIe hosts the pool is harmless (transfers are already
microseconds per MB and the GIL releases during each copy).
"""

from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict

import numpy as np

_DEFAULT_WORKERS = 4
_SPLIT_BYTES = 128 * 1024 * 1024


def _leaf_spans(leaf, split_bytes: int):
    """Row spans to fetch a leaf in. The SLICING happens inside the worker
    (just before its np.asarray), not here: a JAX slice is a device COPY,
    and pre-materializing every block of every big leaf would spike
    transient HBM by the total large-leaf size — on a chip already near the
    ceiling that is an OOM (r5 review finding). Lazy slicing bounds the
    transient to workers x split_bytes."""
    nbytes = getattr(leaf, "nbytes", 0)
    shape = getattr(leaf, "shape", ())
    if nbytes <= split_bytes or not shape or shape[0] < 2:
        return [None]  # fetch whole
    rows = shape[0]
    n_blocks = min(rows, max(2, -(-nbytes // split_bytes)))
    step = -(-rows // n_blocks)
    return [(i, min(i + step, rows)) for i in range(0, rows, step)]


def parallel_device_get(
    flat: Dict[str, Any], workers: int = _DEFAULT_WORKERS, split_bytes: int = _SPLIT_BYTES
) -> Dict[str, np.ndarray]:
    """{name: device_array} -> {name: np.ndarray}, fetched concurrently.

    Only valid for process-local (fully addressable) arrays — multi-process
    resharding must happen before this (trainer._host_fetch does). Large
    leaves are sliced into row blocks on device (cheap view-copies) so their
    transfer parallelizes too.
    """
    jobs = []  # (key, span) — leaves looked up at fetch time, sliced lazily
    for k, v in flat.items():
        for span in _leaf_spans(v, split_bytes):
            jobs.append((k, span))

    def fetch(job):
        k, span = job
        leaf = flat[k]
        piece = leaf if span is None else leaf[span[0] : span[1]]
        arr = np.asarray(piece)
        del piece  # free the device block before the next one is sliced
        return k, span, arr

    out: Dict[str, Any] = {}
    with ThreadPoolExecutor(max_workers=workers) as pool:
        for k, span, arr in pool.map(fetch, jobs):
            if span is None:
                out[k] = arr
            else:
                out.setdefault(k, []).append((span, arr))
    for k, v in list(out.items()):
        if isinstance(v, list):
            v.sort(key=lambda p: p[0][0])
            out[k] = np.concatenate([arr for _, arr in v], axis=0)
    return out


def parallel_device_get_tree(tree, workers: int = _DEFAULT_WORKERS,
                             split_bytes: int = _SPLIT_BYTES):
    """Pytree version of :func:`parallel_device_get`. Holds no reference to
    the input leaves after returning, so a caller that drops its own
    reference (e.g. the background checkpoint saver's on-device snapshot)
    frees the device buffers immediately — before any slow downstream write."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(tree)
    fetched = parallel_device_get(
        {str(i): leaf for i, leaf in enumerate(leaves)},
        workers=workers, split_bytes=split_bytes,
    )
    del leaves, tree
    return jax.tree_util.tree_unflatten(
        treedef, [fetched[str(i)] for i in range(len(fetched))]
    )
