"""Model presets for the supported decoder families.

The flagship is SmolLM3-3B (the reference's hard-coded model,
reference ``training.py:54``); the other presets cover the configs named in
BASELINE.json (Llama-3-8B FSDP, Mistral-7B DPO, Llama-3-70B QLoRA) plus the
Mixtral MoE family (expert parallelism, ops/moe.py). Values verified against
the HF ``transformers`` config classes
(``SmolLM3Config``/``LlamaConfig``/``MistralConfig``/``MixtralConfig``).
"""

from __future__ import annotations

from llm_fine_tune_distributed_tpu.config import ModelConfig


def _smollm3_no_rope(num_layers: int, interval: int = 4) -> tuple:
    """SmolLM3 NoPE pattern: every `interval`-th layer (1-indexed) has no RoPE.

    Matches HF ``SmolLM3Config``: ``no_rope_layers[i] = 0 if (i+1) % 4 == 0``.
    """
    return tuple(0 if (i + 1) % interval == 0 else 1 for i in range(num_layers))


PRESETS = {
    # Tiny config for unit tests — same structure as SmolLM3 (GQA + NoPE).
    "tiny": ModelConfig(
        name="tiny",
        vocab_size=512,
        hidden_size=64,
        intermediate_size=128,
        num_layers=4,
        num_heads=4,
        num_kv_heads=2,
        rope_theta=10_000.0,
        max_position_embeddings=512,
        tie_word_embeddings=True,
        no_rope_layers=_smollm3_no_rope(4),
    ),
    # Tiny config with untied embeddings + sliding window (Mistral-style paths).
    "tiny_mistral": ModelConfig(
        name="tiny_mistral",
        vocab_size=512,
        hidden_size=64,
        intermediate_size=128,
        num_layers=2,
        num_heads=4,
        num_kv_heads=2,
        rope_theta=10_000.0,
        max_position_embeddings=512,
        tie_word_embeddings=False,
        sliding_window=64,
    ),
    "smollm3_3b": ModelConfig(
        name="smollm3_3b",
        vocab_size=128256,
        hidden_size=2048,
        intermediate_size=11008,
        num_layers=36,
        num_heads=16,
        num_kv_heads=4,
        rope_theta=5_000_000.0,  # HuggingFaceTB/SmolLM3-3B release value
        max_position_embeddings=65536,
        rms_norm_eps=1e-6,
        tie_word_embeddings=True,
        no_rope_layers=_smollm3_no_rope(36),
    ),
    "llama3_8b": ModelConfig(
        name="llama3_8b",
        vocab_size=128256,
        hidden_size=4096,
        intermediate_size=14336,
        num_layers=32,
        num_heads=32,
        num_kv_heads=8,
        rope_theta=500_000.0,
        max_position_embeddings=8192,
        rms_norm_eps=1e-5,
        tie_word_embeddings=False,
    ),
    "llama3_1_8b": ModelConfig(
        # HF meta-llama/Llama-3.1-8B: same arch as llama3_8b, 128k context
        # via the "llama3" smoothed-NTK rope scaling
        name="llama3_1_8b",
        vocab_size=128256,
        hidden_size=4096,
        intermediate_size=14336,
        num_layers=32,
        num_heads=32,
        num_kv_heads=8,
        rope_theta=500_000.0,
        max_position_embeddings=131072,
        rms_norm_eps=1e-5,
        tie_word_embeddings=False,
        rope_scaling_type="llama3",
        rope_scaling_factor=8.0,
        rope_low_freq_factor=1.0,
        rope_high_freq_factor=4.0,
        rope_original_max_position=8192,
    ),
    "llama3_2_1b": ModelConfig(
        # HF meta-llama/Llama-3.2-1B: tied embeddings, llama3 rope factor 32
        name="llama3_2_1b",
        vocab_size=128256,
        hidden_size=2048,
        intermediate_size=8192,
        num_layers=16,
        num_heads=32,
        num_kv_heads=8,
        head_dim=64,
        rope_theta=500_000.0,
        max_position_embeddings=131072,
        rms_norm_eps=1e-5,
        tie_word_embeddings=True,
        rope_scaling_type="llama3",
        rope_scaling_factor=32.0,
        rope_low_freq_factor=1.0,
        rope_high_freq_factor=4.0,
        rope_original_max_position=8192,
    ),
    "llama3_2_3b": ModelConfig(
        # HF meta-llama/Llama-3.2-3B
        name="llama3_2_3b",
        vocab_size=128256,
        hidden_size=3072,
        intermediate_size=8192,
        num_layers=28,
        num_heads=24,
        num_kv_heads=8,
        head_dim=128,
        rope_theta=500_000.0,
        max_position_embeddings=131072,
        rms_norm_eps=1e-5,
        tie_word_embeddings=True,
        rope_scaling_type="llama3",
        rope_scaling_factor=32.0,
        rope_low_freq_factor=1.0,
        rope_high_freq_factor=4.0,
        rope_original_max_position=8192,
    ),
    "llama3_70b": ModelConfig(
        name="llama3_70b",
        vocab_size=128256,
        hidden_size=8192,
        intermediate_size=28672,
        num_layers=80,
        num_heads=64,
        num_kv_heads=8,
        rope_theta=500_000.0,
        max_position_embeddings=8192,
        rms_norm_eps=1e-5,
        tie_word_embeddings=False,
    ),
    # Tiny MoE config (Mixtral structure) for unit tests / EP mesh tests.
    "tiny_moe": ModelConfig(
        name="tiny_moe",
        vocab_size=512,
        hidden_size=64,
        intermediate_size=128,
        num_layers=2,
        num_heads=4,
        num_kv_heads=2,
        rope_theta=10_000.0,
        max_position_embeddings=512,
        tie_word_embeddings=False,
        num_experts=4,
        num_experts_per_tok=2,
    ),
    "mixtral_8x7b": ModelConfig(
        name="mixtral_8x7b",
        vocab_size=32000,
        hidden_size=4096,
        intermediate_size=14336,
        num_layers=32,
        num_heads=32,
        num_kv_heads=8,
        rope_theta=1_000_000.0,
        max_position_embeddings=32768,
        rms_norm_eps=1e-5,
        tie_word_embeddings=False,
        num_experts=8,
        num_experts_per_tok=2,
    ),
    "qwen2_7b": ModelConfig(
        # HF Qwen/Qwen2-7B: qkv bias without o_proj bias, untied embeddings
        name="qwen2_7b",
        vocab_size=152064,
        hidden_size=3584,
        intermediate_size=18944,
        num_layers=28,
        num_heads=28,
        num_kv_heads=4,
        rope_theta=1_000_000.0,
        max_position_embeddings=32768,
        rms_norm_eps=1e-6,
        tie_word_embeddings=False,
        attention_bias=True,
        attention_out_bias=False,
    ),
    "qwen3_8b": ModelConfig(
        # HF Qwen/Qwen3-8B: per-head q/k RMSNorm, no attention bias, untied
        name="qwen3_8b",
        vocab_size=151936,
        hidden_size=4096,
        intermediate_size=12288,
        num_layers=36,
        num_heads=32,
        num_kv_heads=8,
        head_dim=128,
        rope_theta=1_000_000.0,
        max_position_embeddings=40960,
        rms_norm_eps=1e-6,
        tie_word_embeddings=False,
        qk_norm=True,
    ),
    "tiny_gemma2": ModelConfig(
        # unit-test scale Gemma2: every family knob live. vocab 512 = the
        # byte-chatml test tokenizer's vocab (256 bytes + specials + pad)
        name="tiny_gemma2",
        vocab_size=512,
        hidden_size=64,
        intermediate_size=128,
        num_layers=4,
        num_heads=4,
        num_kv_heads=2,
        head_dim=16,
        rope_theta=10_000.0,
        max_position_embeddings=512,
        tie_word_embeddings=True,
        sliding_window=8,
        alternating_sliding_window=True,
        hidden_act="gelu_tanh",
        sandwich_norms=True,
        zero_centered_norm=True,
        embed_scale=True,
        attn_logit_softcap=50.0,
        final_logit_softcap=30.0,
        query_pre_attn_scalar=16.0,
    ),
    "gemma2_9b": ModelConfig(
        # HF google/gemma-2-9b: GeGLU, sandwich norms, zero-centered RMSNorm,
        # scaled embeddings, attn/final logit softcaps, local/global
        # alternating sliding window, tied embeddings
        name="gemma2_9b",
        vocab_size=256000,
        hidden_size=3584,
        intermediate_size=14336,
        num_layers=42,
        num_heads=16,
        num_kv_heads=8,
        head_dim=256,
        rope_theta=10_000.0,
        max_position_embeddings=8192,
        rms_norm_eps=1e-6,
        tie_word_embeddings=True,
        sliding_window=4096,
        alternating_sliding_window=True,
        hidden_act="gelu_tanh",
        sandwich_norms=True,
        zero_centered_norm=True,
        embed_scale=True,
        attn_logit_softcap=50.0,
        final_logit_softcap=30.0,
        query_pre_attn_scalar=256.0,
    ),
    "mistral_7b": ModelConfig(
        name="mistral_7b",
        vocab_size=32000,
        hidden_size=4096,
        intermediate_size=14336,
        num_layers=32,
        num_heads=32,
        num_kv_heads=8,
        rope_theta=1_000_000.0,  # v0.2+ (v0.1 used 10k + sliding_window=4096)
        max_position_embeddings=32768,
        rms_norm_eps=1e-5,
        tie_word_embeddings=False,
    ),
}


def get_preset(name: str) -> ModelConfig:
    try:
        return PRESETS[name]
    except KeyError:
        raise KeyError(f"unknown model preset {name!r}; available: {sorted(PRESETS)}")


def to_hf_dict(mc: ModelConfig) -> dict:
    """ModelConfig -> HF-style config.json dict (the trainer's saved-artifact
    contract; reference ``training.py:310-311`` writes HF config.json via
    save_model). Every architecture knob is explicit so ``from_hf_config``
    round-trips EXACTLY regardless of the model_type string — the
    model_type-prefix heuristics below never apply to this framework's own
    saves. Round-trip pinned by tests/test_hf_parity.py."""
    return {
        "model_type": mc.name,
        "vocab_size": mc.vocab_size,
        "hidden_size": mc.hidden_size,
        "intermediate_size": mc.intermediate_size,
        "num_hidden_layers": mc.num_layers,
        "num_attention_heads": mc.num_heads,
        "num_key_value_heads": mc.num_kv_heads,
        "head_dim": mc.head_dim,
        "rope_theta": mc.rope_theta,
        "max_position_embeddings": mc.max_position_embeddings,
        "rms_norm_eps": mc.rms_norm_eps,
        "tie_word_embeddings": mc.tie_word_embeddings,
        "attention_bias": mc.attention_bias,
        "attention_out_bias": mc.attention_out_bias,
        "qk_norm": mc.qk_norm,
        # Gemma2-family knobs (explicit keys beat the from_hf_config
        # model_type heuristics on reload)
        "hidden_act": mc.hidden_act,
        # gemma-family model_types resolve their activation from
        # hidden_activation (with a gelu_pytorch_tanh default that would
        # override an exact-GeLU hidden_act on round-trip — ADVICE r4);
        # write both keys so reload is exact for every family
        "hidden_activation": mc.hidden_act,
        "sandwich_norms": mc.sandwich_norms,
        "zero_centered_norm": mc.zero_centered_norm,
        "embed_scale": mc.embed_scale,
        "attn_logit_softcap": mc.attn_logit_softcap,
        "final_logit_softcap": mc.final_logit_softcap,
        "query_pre_attn_scalar": mc.query_pre_attn_scalar,
        "alternating_sliding_window": mc.alternating_sliding_window,
        # HF rope_scaling dict shape so any HF-compatible loader (and our
        # from_hf_config) reads the context extension
        "rope_scaling": (
            {
                "rope_type": mc.rope_scaling_type,
                "factor": mc.rope_scaling_factor,
                "low_freq_factor": mc.rope_low_freq_factor,
                "high_freq_factor": mc.rope_high_freq_factor,
                "original_max_position_embeddings": mc.rope_original_max_position,
            }
            if mc.rope_scaling_type
            else None
        ),
        "mlp_bias": mc.mlp_bias,
        "no_rope_layers": list(mc.no_rope_layers),
        "sliding_window": mc.sliding_window,
        # MoE round trip (HF MixtralConfig naming — consumed by
        # models/configs.from_hf_config at inference load time)
        "num_local_experts": mc.num_experts,
        "num_experts_per_tok": mc.num_experts_per_tok,
        "router_aux_loss_coef": mc.router_aux_coef,
    }


def load_model_config(path: str) -> ModelConfig:
    """Read ``path/config.json`` (HF layout) into a ModelConfig — the ONE
    place train-time (trainer._resolve_model_config) and inference-time
    (infer.load_model_dir) architecture resolution share, so the two can
    never diverge."""
    import json
    import os
    from types import SimpleNamespace

    cfg_path = os.path.join(path, "config.json")
    if not os.path.isfile(cfg_path):
        raise FileNotFoundError(f"no config.json under {path}")
    with open(cfg_path) as f:
        raw = json.load(f)
    return from_hf_config(SimpleNamespace(**raw))


def _parse_hidden_act(act) -> str:
    """Map HF activation names to the two implemented gate activations —
    reject anything else at load time (same contract as the rope_scaling
    check below: fail before multi-GB weights load, not inside jit)."""
    act = str(act)
    if act in ("silu", "swish"):
        return "silu"
    if act in ("gelu_tanh", "gelu_pytorch_tanh", "gelu_new"):
        return "gelu_tanh"
    if act == "gelu":
        return "gelu"  # exact (erf) GeLU — early Gemma configs
    raise ValueError(
        f"unsupported hidden_act {act!r}; supported: silu/swish, "
        "gelu (exact), gelu_pytorch_tanh (tanh-approx GeGLU)"
    )


def from_hf_config(hf_config) -> ModelConfig:
    """Build a ModelConfig from a HF transformers PretrainedConfig.

    Lets users point at any local HF checkpoint directory (``config.json``)
    for Llama-family models, mirroring the reference's
    ``AutoModelForCausalLM.from_pretrained`` flexibility
    (reference ``training.py:97-102``).
    """
    g = lambda k, default=None: getattr(hf_config, k, default)
    # The qwen*/gemma* model_type-prefix heuristics below were validated
    # against these exact HF model_types (logit-parity tests,
    # tests/test_hf_parity.py). An ADJACENT family member — e.g. gemma3_text
    # (5:1 local/global window pattern, qk-norm, per-layer rope base) or
    # qwen2_moe (different expert-config keys) — would match the prefix,
    # load without error, and produce wrong logits. Fail before the
    # multi-GB weights load instead (same contract as the rope_scaling and
    # hidden_act checks — ADVICE r4). Checkpoints written by this
    # framework's trainer carry every knob explicitly (_save_model_config
    # always writes sandwich_norms AND qk_norm), so they bypass the
    # heuristics and are accepted under any model_type name.
    mt = str(g("model_type") or "")
    _VALIDATED_HEURISTIC_TYPES = {"qwen2", "qwen3", "gemma", "gemma2"}
    framework_save = g("sandwich_norms") is not None and g("qk_norm") is not None
    if (
        mt.startswith(("qwen", "gemma"))
        and mt not in _VALIDATED_HEURISTIC_TYPES
        and not framework_save
    ):
        raise ValueError(
            f"unrecognized {mt!r} model_type: the qwen*/gemma* architecture "
            f"heuristics are validated only for {sorted(_VALIDATED_HEURISTIC_TYPES)} "
            "(adjacent variants like gemma3/qwen2_moe differ architecturally "
            "and would silently produce wrong logits). Convert the config to "
            "explicit keys or add a validated preset."
        )
    no_rope = g("no_rope_layers") or ()
    # HF rope_scaling dict: {"rope_type"|"type": "llama3"|"linear"|"default",
    # "factor", "low_freq_factor", "high_freq_factor",
    # "original_max_position_embeddings"} (Llama-3.1+ checkpoints).
    rs = g("rope_scaling") or {}
    if not isinstance(rs, dict):
        rs = dict(rs)
    rs_type = rs.get("rope_type", rs.get("type"))
    if rs_type in ("default", None):
        rs_type = None
    elif rs_type not in ("linear", "llama3"):
        # reject at config-load time, not minutes later inside the first
        # forward's jit trace (after multi-GB weight loading)
        raise ValueError(
            f"unsupported rope_scaling type {rs_type!r}; supported: "
            "'llama3' (Llama-3.1 smoothed NTK), 'linear', 'default'"
        )
    return ModelConfig(
        name=g("model_type", "hf_model"),
        vocab_size=g("vocab_size"),
        hidden_size=g("hidden_size"),
        intermediate_size=g("intermediate_size"),
        num_layers=g("num_hidden_layers"),
        num_heads=g("num_attention_heads"),
        num_kv_heads=g("num_key_value_heads") or g("num_attention_heads"),
        head_dim=g("head_dim"),
        rope_theta=g("rope_theta", 10_000.0),
        max_position_embeddings=g("max_position_embeddings", 4096),
        rms_norm_eps=g("rms_norm_eps", 1e-6),
        tie_word_embeddings=bool(g("tie_word_embeddings", False)),
        # HF Qwen2-family configs (qwen2, qwen2_moe, qwen2_vl, ...) carry no
        # attention_bias field — their attention has qkv bias (no o bias)
        # implicitly. An explicit attention_out_bias key (written by
        # trainer._save_model_config) wins over the model_type heuristic so
        # saved checkpoints round-trip regardless of their model_type string.
        attention_bias=bool(
            g("attention_bias", False)
            or str(g("model_type") or "").startswith("qwen2")
        ),
        attention_out_bias=bool(
            g(
                "attention_out_bias",
                not str(g("model_type") or "").startswith("qwen2"),
            )
        ),
        # Qwen3-family: per-head q/k RMSNorm is architectural (HF carries no
        # flag); an explicit qk_norm key (trainer._save_model_config) wins.
        qk_norm=bool(
            g("qk_norm", str(g("model_type") or "").startswith("qwen3"))
        ),
        # Gemma2 family: GeGLU/sandwich-norm/zero-centered/softcap knobs.
        # Explicit keys (written by trainer._save_model_config) win; the
        # model_type heuristic covers pristine HF gemma2 checkpoints.
        hidden_act=_parse_hidden_act(
            # Gemma family: HF's GemmaConfig/Gemma2Config resolve the
            # activation from hidden_activation, DEFAULTING to
            # gelu_pytorch_tanh and overriding a stale hidden_act="gelu"
            # (early gemma configs) with a warning — mirror that precedence.
            (g("hidden_activation") or "gelu_pytorch_tanh")
            if str(g("model_type") or "").startswith("gemma")
            else (g("hidden_act") or g("hidden_activation") or "silu")
        ),
        sandwich_norms=bool(
            g("sandwich_norms", str(g("model_type") or "").startswith("gemma2"))
        ),
        zero_centered_norm=bool(
            g(
                "zero_centered_norm",
                str(g("model_type") or "").startswith("gemma"),
            )
        ),
        embed_scale=bool(
            g("embed_scale", str(g("model_type") or "").startswith("gemma"))
        ),
        attn_logit_softcap=(
            g("attn_logit_softcap", None) or g("attn_logit_softcapping", None)
        ),
        final_logit_softcap=(
            g("final_logit_softcap", None) or g("final_logit_softcapping", None)
        ),
        query_pre_attn_scalar=g("query_pre_attn_scalar"),
        alternating_sliding_window=bool(
            g(
                "alternating_sliding_window",
                str(g("model_type") or "").startswith("gemma2"),
            )
        ),
        rope_scaling_type=rs_type,
        rope_scaling_factor=float(rs.get("factor", 1.0)),
        rope_low_freq_factor=float(rs.get("low_freq_factor", 1.0)),
        rope_high_freq_factor=float(rs.get("high_freq_factor", 4.0)),
        rope_original_max_position=int(
            rs.get("original_max_position_embeddings", 8192)
        ),
        mlp_bias=bool(g("mlp_bias", False)),
        no_rope_layers=tuple(no_rope),
        sliding_window=g("sliding_window") if g("use_sliding_window", True) else None,
        # MoE (HF MixtralConfig naming). router_aux_loss_coef=0.0 is a
        # legitimate explicit choice (aux disabled) — only None falls back.
        num_experts=g("num_local_experts", 0) or 0,
        num_experts_per_tok=g("num_experts_per_tok", 2) or 2,
        router_aux_coef=(
            0.01 if g("router_aux_loss_coef") is None else g("router_aux_loss_coef")
        ),
    )
