"""Llama-family decoder-only transformer as pure JAX functions over a pytree.

Design notes (TPU-first, not a torch translation):

- Params are a plain nested dict whose paths mirror HF checkpoint names
  (``model.layers.0.self_attn.q_proj`` ...), so safetensors import/export is a
  rename-free transpose (models/hf_io.py) and sharding rules match on path.
- No module framework: ``forward`` is a pure function — trivially jittable,
  shardable with NamedSharding on the params pytree, and rematerializable per
  block with ``jax.checkpoint`` (the analog of the reference's
  ``gradient_checkpointing=True``, reference ``training.py:280``).
- Master params stay float32; compute casts to bfloat16 at use (the MXU path).
  Softmax/RMSNorm/RoPE run in float32.
- Covers SmolLM3 (GQA + NoPE-interleaved RoPE + tied embeddings), Llama-3,
  Mistral (sliding window) via ModelConfig — the model surface of the
  reference's ``AutoModelForCausalLM`` usage (reference ``training.py:97-102``).

Linear weights are stored in JAX kernel layout ``[in, out]`` under the leaf
name ``kernel`` (transpose of torch ``weight``); norm/embedding leaves are
``weight`` in torch layout.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name

from llm_fine_tune_distributed_tpu.config import ModelConfig
from llm_fine_tune_distributed_tpu.ops.attention import attention, softcap, xla_attention
from llm_fine_tune_distributed_tpu.ops.int8 import (
    KV_QUANT_MODES,
    dequantize_kv_gather,
    quantize_kv_write,
)
from llm_fine_tune_distributed_tpu.ops.norms import rms_norm
from llm_fine_tune_distributed_tpu.ops.rope import apply_rope, rope_cos_sin

Params = Dict[str, Any]


# ---------------------------------------------------------------------------
# init
# ---------------------------------------------------------------------------


def init_params(rng, config: ModelConfig, dtype=jnp.float32) -> Params:
    """Random init (normal 0.02, HF convention). Returns the params pytree."""
    h = config.hidden_size
    d = config.resolved_head_dim
    qd, kvd = config.num_heads * d, config.num_kv_heads * d
    f, v = config.intermediate_size, config.vocab_size

    keys = iter(jax.random.split(rng, 2 + config.num_layers * 7))

    def dense(key, shape):
        return (jax.random.normal(key, shape, jnp.float32) * 0.02).astype(dtype)

    # Gemma zero-centered RMSNorm stores the weight as an offset from 1
    # (init 0); Llama-style stores the multiplier itself (init 1).
    def norm_init():
        if config.zero_centered_norm:
            return {"weight": jnp.zeros((h,), dtype)}
        return {"weight": jnp.ones((h,), dtype)}

    layers = {}
    for i in range(config.num_layers):
        attn = {
            "q_proj": {"kernel": dense(next(keys), (h, qd))},
            "k_proj": {"kernel": dense(next(keys), (h, kvd))},
            "v_proj": {"kernel": dense(next(keys), (h, kvd))},
            "o_proj": {"kernel": dense(next(keys), (qd, h))},
        }
        if config.attention_bias:
            # HF Llama applies attention_bias to q/k/v/o alike; Qwen2 skips
            # the o_proj bias (attention_out_bias=False).
            attn["q_proj"]["bias"] = jnp.zeros((qd,), dtype)
            attn["k_proj"]["bias"] = jnp.zeros((kvd,), dtype)
            attn["v_proj"]["bias"] = jnp.zeros((kvd,), dtype)
            if config.attention_out_bias:
                attn["o_proj"]["bias"] = jnp.zeros((h,), dtype)
        if config.qk_norm:
            attn["q_norm"] = {"weight": jnp.ones((d,), dtype)}
            attn["k_norm"] = {"weight": jnp.ones((d,), dtype)}
        layer = {
            "input_layernorm": norm_init(),
            "self_attn": attn,
            "post_attention_layernorm": norm_init(),
        }
        if config.sandwich_norms:
            # Gemma2: post_attention_layernorm norms the attention OUTPUT;
            # pre_feedforward replaces Llama's post_attention pre-MLP role
            layer["pre_feedforward_layernorm"] = norm_init()
            layer["post_feedforward_layernorm"] = norm_init()
        if config.num_experts > 0:
            from llm_fine_tune_distributed_tpu.ops.moe import init_moe_params

            # consumes one key (split internally); a model is uniformly MoE
            # or dense so per-layer key alignment needs no padding
            layer["block_sparse_moe"] = init_moe_params(next(keys), config, dtype)
        else:
            mlp = {
                "gate_proj": {"kernel": dense(next(keys), (h, f))},
                "up_proj": {"kernel": dense(next(keys), (h, f))},
                "down_proj": {"kernel": dense(next(keys), (f, h))},
            }
            if config.mlp_bias:
                mlp["gate_proj"]["bias"] = jnp.zeros((f,), dtype)
                mlp["up_proj"]["bias"] = jnp.zeros((f,), dtype)
                mlp["down_proj"]["bias"] = jnp.zeros((h,), dtype)
            layer["mlp"] = mlp
        layers[str(i)] = layer

    params: Params = {
        "model": {
            "embed_tokens": {"weight": dense(next(keys), (v, h))},
            "layers": layers,
            "norm": norm_init(),
        }
    }
    if not config.tie_word_embeddings:
        params["lm_head"] = {"kernel": dense(next(keys), (h, v))}
    return params


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _linear(x, p, compute_dtype, quant_impl: str = "auto", adapter_idx=None,
            w8a8: bool = False):
    """x @ kernel (+ bias), with optional additive LoRA branch.

    LoRA params, when present (parallel/lora.py), live beside the kernel as
    ``lora_a [in, r]`` / ``lora_b [r, out]`` and contribute
    ``(alpha/r) * x @ A @ B`` (external-doc LoRA config: r=16, alpha=8).

    Multi-tenant POOLED adapters (infer/adapters.py) instead store stacked
    leaves ``lora_a_pool [max_adapters, in, r]`` / ``lora_b_pool
    [max_adapters, r, out]`` / ``lora_scale_pool [max_adapters]`` beside the
    kernel, and ``adapter_idx`` ([batch] int32) selects each row's adapter
    with a batched gather — different tenants co-batch in ONE dispatch.
    Pool row 0 is the identity adapter (all-zero A and B), so rows with
    idx 0 contribute an exactly-zero delta and stay bit-identical to the
    base model. The pool arrays are shape-stable: hot-loading or evicting
    an adapter is a value update, never a recompile.

    NF4-quantized kernels (QLoRA frozen base, ops/nf4.py) replace ``kernel``
    with sibling leaves ``kernel_nf4`` (+ absmax scales); the matmul then
    runs through the fused Pallas decode kernel or the XLA dequant path.
    Int8 weight-only kernels (inference, ops/int8.py) replace it with
    ``kernel_int8`` + ``kernel_int8_scale``. ``w8a8=True`` (the frozen-trunk
    training fast path, TrainConfig.frozen_compute="int8") runs those same
    leaves as a true int8 x int8 MXU matmul with dynamic per-row activation
    quantization instead of the weight-only dequant; adapters, biases, and
    every non-projection op stay in ``compute_dtype``.
    """
    if "kernel_int8" in p:
        q = {"int8": p["kernel_int8"], "int8_scale": p["kernel_int8_scale"]}
        if w8a8:
            from llm_fine_tune_distributed_tpu.ops.int8_matmul import (
                int8_w8a8_matmul,
            )

            y = int8_w8a8_matmul(x, q, compute_dtype=compute_dtype)
        else:
            from llm_fine_tune_distributed_tpu.ops.int8 import int8_matmul

            y = int8_matmul(x, q, compute_dtype=compute_dtype)
    elif "kernel_nf4" in p:
        from llm_fine_tune_distributed_tpu.ops.nf4 import QUANT_SUFFIXES, nf4_matmul

        q = {s: p[f"kernel_{s}"] for s in QUANT_SUFFIXES if f"kernel_{s}" in p}
        y = nf4_matmul(
            x.astype(compute_dtype), q, impl=quant_impl, compute_dtype=compute_dtype
        )
    else:
        y = x @ p["kernel"].astype(compute_dtype)
    if "lora_a" in p:
        a = p["lora_a"].astype(compute_dtype)
        b = p["lora_b"].astype(compute_dtype)
        y = y + (x @ a) @ b * p["lora_scale"].astype(compute_dtype)
    if adapter_idx is not None and "lora_a_pool" in p:
        # Batched gather: row i computes with adapter adapter_idx[i]'s A/B.
        # Mirrors the single-adapter branch's arithmetic ((x @ A) @ B * s)
        # so a pooled row matches the same adapter served via lora leaves.
        a = jnp.take(p["lora_a_pool"], adapter_idx, axis=0).astype(compute_dtype)
        bp = jnp.take(p["lora_b_pool"], adapter_idx, axis=0).astype(compute_dtype)
        sc = jnp.take(p["lora_scale_pool"], adapter_idx, axis=0).astype(compute_dtype)
        delta = jnp.einsum("bsr,bro->bso", jnp.einsum("bsi,bir->bsr", x, a), bp)
        y = y + delta * sc[:, None, None]
    if "bias" in p:
        y = y + p["bias"].astype(compute_dtype)
    return y


def _block(
    lp: Params,
    x,
    cos,
    sin,
    padding_mask,
    segment_ids,
    explicit_mask,
    cache_entry,
    cache_pos,
    *,
    config: ModelConfig,
    layer_idx: int,
    attention_impl: str,
    compute_dtype,
    mesh=None,
    quant_impl: str = "auto",
    rope_flag=None,
    windowed_mask=None,
    block_tables=None,
    adapter_idx=None,
    w8a8: bool = False,
):
    """One transformer block. Returns (x, new_cache_entry, moe_aux).

    ``rope_flag`` (traced bool scalar) overrides the static
    ``config.uses_rope(layer_idx)`` decision — used by the pipeline's
    layer-scan, where the absolute layer index is data, not Python.
    ``moe_aux`` is the layer's load-balancing loss (f32 scalar; 0.0 for
    dense models — ``config.num_experts == 0``).
    ``block_tables`` ([batch, nb] int32) switches the cache entry to the
    PAGED layout: a global block pool instead of per-row buffers (see the
    cache-write branch below and ``init_paged_cache``).
    """
    b, s, h = x.shape
    d = config.resolved_head_dim
    eps = config.rms_norm_eps
    zc = config.zero_centered_norm
    attn_p = lp["self_attn"]

    hid = rms_norm(x, lp["input_layernorm"]["weight"], eps, zero_centered=zc)
    q = _linear(hid, attn_p["q_proj"], compute_dtype, quant_impl, adapter_idx, w8a8).reshape(b, s, config.num_heads, d)
    k = _linear(hid, attn_p["k_proj"], compute_dtype, quant_impl, adapter_idx, w8a8).reshape(b, s, config.num_kv_heads, d)
    v = _linear(hid, attn_p["v_proj"], compute_dtype, quant_impl, adapter_idx, w8a8).reshape(b, s, config.num_kv_heads, d)

    if config.qk_norm:
        # Qwen3: per-head RMSNorm over head_dim, before RoPE (HF Qwen3Attention)
        q = rms_norm(q, attn_p["q_norm"]["weight"], eps)
        k = rms_norm(k, attn_p["k_norm"]["weight"], eps)

    if rope_flag is not None:
        qr, kr = apply_rope(q, k, cos, sin)
        q = jnp.where(rope_flag, qr, q)
        k = jnp.where(rope_flag, kr, k)
    elif config.uses_rope(layer_idx):
        q, k = apply_rope(q, k, cos, sin)

    new_entry = None
    paged_quant = None  # int8 paged pool: (ck, cv, k_scale, v_scale, pos)
    if cache_entry is not None and block_tables is not None:
        # Paged cache: the entry is the GLOBAL pool [num_blocks, L, kv_heads,
        # d] and the row's block table maps logical position p to pool cell
        # (table[p // L], p % L). Writes scatter each chunk token at its
        # logical position through the table; reads gather the table's blocks
        # back into one [b, nb*L] view whose index IS the logical position —
        # so the caller's position mask applies to the view unchanged, and a
        # row's decode cost tracks the blocks its table exposes (nb), not a
        # global buffer ceiling. Unused table entries hold the null block
        # (id 0): their view positions sit above every live query, hence
        # always masked; dead rows get an all-null table from the engine so
        # their (frozen-position) writes land in null-block garbage instead
        # of a block since reassigned to a live row.
        L = cache_entry["k"].shape[1]
        nb = block_tables.shape[1]
        offset = (
            cache_pos[:, None] if getattr(cache_pos, "ndim", 0) == 1 else cache_pos
        )
        pos = jnp.broadcast_to(offset + jnp.arange(s)[None, :], (b, s))
        # NOTE the clip: a position past the table view REDIRECTS its write
        # into the view's LAST entry instead of dropping it (the dense branch
        # below drops out-of-bounds scatters). Callers whose writes can run
        # past a row's logical end — the speculative verify step writes K
        # positions past the last accepted token — must size the table view
        # to cover pos + K (engine-side block headroom), or live KV gets
        # overwritten.
        blk = jnp.take_along_axis(block_tables, jnp.clip(pos // L, 0, nb - 1), axis=1)
        off = pos % L
        if "k_scale" in cache_entry:
            # Int8 pool (--quantize-kv int8): codes keep the bf16 layout's
            # [nb, L, h, d] shape, per-(block, kv-head) absmax scales live in
            # sibling pools indexed by the same block ids. Writes quantize at
            # insert (growing a block's scale rescales its resident codes;
            # untouched blocks are bit-stable — ops/int8.quantize_kv_write);
            # reads either fuse gather+dequant+attention into the Pallas
            # decode kernel (TPU, s == 1) or fall back to the dequantizing
            # XLA gather below.
            ck, k_sc = quantize_kv_write(
                cache_entry["k"], cache_entry["k_scale"], blk, off, k
            )
            cv, v_sc = quantize_kv_write(
                cache_entry["v"], cache_entry["v_scale"], blk, off, v
            )
            new_entry = {"k": ck, "v": cv, "k_scale": k_sc, "v_scale": v_sc}
            paged_quant = (ck, cv, k_sc, v_sc, pos)
        else:
            ck = cache_entry["k"].at[blk, off].set(k.astype(cache_entry["k"].dtype))
            cv = cache_entry["v"].at[blk, off].set(v.astype(cache_entry["v"].dtype))
            new_entry = {"k": ck, "v": cv}
            flat = block_tables.reshape(-1)
            k = ck[flat].reshape(b, nb * L, ck.shape[2], ck.shape[3])
            v = cv[flat].reshape(b, nb * L, cv.shape[2], cv.shape[3])
    elif cache_entry is not None:
        # Decode/prefill with a fixed-size KV buffer: write k,v at cache_pos.
        # A scalar cache_pos writes the same slots for every row (single
        # prompt / aligned batch); a [batch] vector writes per-row slots —
        # ragged batched decode, where row i's token t lives at slot
        # len_i + t so the slot == position invariant holds per row.
        # Out-of-bounds slots DROP (jax scatter default): a speculative
        # verify chunk overrunning the buffer on a slot's final tick
        # cannot clobber other rows' live KV.
        if getattr(cache_pos, "ndim", 0) == 1:
            slots = cache_pos[:, None] + jnp.arange(s)[None, :]  # [b, s]
            ck = cache_entry["k"].at[jnp.arange(b)[:, None], slots].set(
                k.astype(cache_entry["k"].dtype)
            )
            cv = cache_entry["v"].at[jnp.arange(b)[:, None], slots].set(
                v.astype(cache_entry["v"].dtype)
            )
        else:
            ck = jax.lax.dynamic_update_slice(cache_entry["k"], k.astype(cache_entry["k"].dtype), (0, cache_pos, 0, 0))
            cv = jax.lax.dynamic_update_slice(cache_entry["v"], v.astype(cache_entry["v"].dtype), (0, cache_pos, 0, 0))
        new_entry = {"k": ck, "v": cv}
        k, v = ck, cv

    # Per-layer attention knobs (Gemma2: alternating local/global windows,
    # query_pre_attn_scalar scale, logit softcap — all None for Llama-family)
    layer_window = config.layer_sliding_window(layer_idx)
    attn_scale = (
        None
        if config.query_pre_attn_scalar is None
        else float(config.query_pre_attn_scalar) ** -0.5
    )
    out = None
    if paged_quant is not None:
        ck, cv, k_sc, v_sc, pos = paged_quant
        from llm_fine_tune_distributed_tpu.ops.flash_attention import (
            paged_decode_attention,
            paged_decode_mode,
        )

        mode = paged_decode_mode()
        if (
            mode != "xla"
            and s == 1
            and padding_mask is None
            and layer_window is None
            and config.attn_logit_softcap is None
        ):
            # fused Pallas kernel: block-table gather + per-block dequant +
            # online softmax in one VMEM pass — the gathered [b, nb*L] view
            # never materializes in HBM. Decode (s == 1) only; prefill
            # chunks and speculative verify use the XLA gather below.
            out = paged_decode_attention(
                q, ck, cv, k_sc, v_sc, block_tables,
                lengths=pos[:, 0] + 1,
                scale=(
                    float(attn_scale)
                    if attn_scale is not None
                    else float(d) ** -0.5
                ),
                interpret=(mode == "interpret"),
            )
        else:
            k = dequantize_kv_gather(ck, k_sc, block_tables, compute_dtype)
            v = dequantize_kv_gather(cv, v_sc, block_tables, compute_dtype)
    if out is not None:
        pass
    elif explicit_mask is not None:
        # windowed_mask carries the window restriction; a global layer (no
        # window) uses the plain causal/padding mask
        m = windowed_mask if (layer_window is not None and windowed_mask is not None) else explicit_mask
        out = xla_attention(
            q, k, v, mask=m, causal=False,
            scale=attn_scale, logit_softcap=config.attn_logit_softcap,
        )
    else:
        out = attention(
            q,
            k,
            v,
            impl=attention_impl,
            padding_mask=padding_mask,
            segment_ids=segment_ids,
            causal=True,
            sliding_window=layer_window,
            mesh=mesh,
            scale=attn_scale,
            logit_softcap=config.attn_logit_softcap,
        )

    out = out.reshape(b, s, config.num_heads * d)
    attn_out = _linear(out, attn_p["o_proj"], compute_dtype, quant_impl, adapter_idx, w8a8)
    if config.sandwich_norms:
        # Gemma2: post_attention_layernorm norms the attention OUTPUT
        attn_out = rms_norm(
            attn_out, lp["post_attention_layernorm"]["weight"], eps, zero_centered=zc
        )
    x = x + attn_out

    pre_ffn = (
        "pre_feedforward_layernorm" if config.sandwich_norms
        else "post_attention_layernorm"
    )
    hid = rms_norm(x, lp[pre_ffn]["weight"], eps, zero_centered=zc)
    aux = jnp.float32(0.0)
    if config.num_experts > 0:
        from llm_fine_tune_distributed_tpu.ops.moe import moe_mlp

        # token-level real/pad mask for routing: packed batches encode pads
        # as segment 0; the cache path's padding_mask covers the KV buffer
        # (wrong length for the current chunk) and is skipped
        token_mask = None
        if segment_ids is not None:
            token_mask = segment_ids > 0
        elif padding_mask is not None and padding_mask.shape[-1] == s:
            token_mask = padding_mask
        moe_out, aux = moe_mlp(
            lp["block_sparse_moe"], hid, config, compute_dtype, mesh=mesh,
            token_mask=token_mask,
            # decode/prefill (KV cache live) is dropless like HF Mixtral:
            # capacity drops would make outputs depend on batch/chunk shape
            dropless=cache_entry is not None,
        )
        if config.sandwich_norms:
            moe_out = rms_norm(
                moe_out, lp["post_feedforward_layernorm"]["weight"], eps,
                zero_centered=zc,
            )
        x = x + moe_out
    else:
        gate = _linear(hid, lp["mlp"]["gate_proj"], compute_dtype, quant_impl, adapter_idx, w8a8)
        up = _linear(hid, lp["mlp"]["up_proj"], compute_dtype, quant_impl, adapter_idx, w8a8)
        # Named so remat_policy="mlp" can save JUST this [b, s, f] product: the
        # gate/up matmuls are ~58% of a block's param FLOPs, so saving their
        # fused output avoids most of full-remat's recompute at one tensor per
        # layer of extra HBM (vs. two for saving gate and up separately).
        if config.hidden_act == "gelu_tanh":
            act = jax.nn.gelu(gate.astype(jnp.float32), approximate=True).astype(gate.dtype)
        elif config.hidden_act == "gelu":
            act = jax.nn.gelu(gate.astype(jnp.float32), approximate=False).astype(gate.dtype)
        else:
            act = jax.nn.silu(gate)
        prod = checkpoint_name(act * up, "mlp_act")
        mlp_out = _linear(prod, lp["mlp"]["down_proj"], compute_dtype, quant_impl, adapter_idx, w8a8)
        if config.sandwich_norms:
            mlp_out = rms_norm(
                mlp_out, lp["post_feedforward_layernorm"]["weight"], eps, zero_centered=zc
            )
        x = x + mlp_out
    return x, new_entry, aux


def forward(
    params: Params,
    input_ids,
    config: ModelConfig,
    *,
    positions=None,
    padding_mask=None,
    segment_ids=None,
    cache: Optional[Dict[str, Any]] = None,
    cache_pos: int | jax.Array = 0,
    block_tables=None,
    attention_impl: str = "xla",
    compute_dtype=jnp.bfloat16,
    remat: bool = False,
    remat_policy: Optional[str] = None,
    logits_dtype=jnp.float32,
    activation_sharding=None,
    output_hidden: bool = False,
    quant_impl: str = "auto",
    return_aux: bool = False,
    adapter_idx=None,
    frozen_layers: int = 0,
    frozen_compute: str = "bf16",
) -> (
    Tuple[jax.Array, Optional[Dict[str, Any]]]
    | Tuple[jax.Array, Optional[Dict[str, Any]], jax.Array]
):
    """Run the model.

    Args:
      input_ids: int32 [batch, seq].
      positions: int32 [batch, seq] absolute positions (default arange, or
        cache_pos offset when a cache is passed).
      padding_mask: [batch, seq] 1=real token (training path).
      cache: optional KV cache dict (see ``init_cache``); when given,
        attention runs over the full cache buffer with a position mask.
      cache_pos: where this chunk starts in the cache — a scalar (all rows
        aligned) or a [batch] vector for per-row starts (ragged batched
        decode: row i's slots stay equal to its logical positions).
      block_tables: optional [batch, nb] int32 — switches ``cache`` to the
        PAGED layout (``init_paged_cache``): one global block pool shared by
        all rows, each row's table mapping logical position p to pool cell
        (table[p // block_len], p % block_len). The attention view per row is
        the gathered nb*block_len positions its table exposes.
      adapter_idx: optional [batch] int32 — per-row slot into the stacked
        multi-tenant LoRA pools (infer/adapters.py) attached beside target
        kernels. Row i's projections add adapter adapter_idx[i]'s low-rank
        delta; index 0 is the identity (zero) adapter. Ignored when the
        params tree carries no ``lora_*_pool`` leaves.
      remat: rematerialize each block on backward
        (analog of reference ``gradient_checkpointing=True``, training.py:280).
      frozen_layers / frozen_compute: the frozen-trunk fast path
        (TrainConfig.frozen_compute="int8"): with ``frozen_compute="int8"``
        and no cache, layers ``[0, frozen_layers)`` run their projection
        matmuls w8a8 on pre-quantized ``kernel_int8`` siblings, skip remat,
        and end in a boundary ``stop_gradient``. ``"bf16"`` (default) and
        the cache path are bit-identical to a model without these kwargs.
      output_hidden: return the final-norm hidden states [batch, seq, hidden]
        (in ``compute_dtype``) instead of logits — the chunked-loss path
        (train/step.py) unembeds chunk-by-chunk so the [batch, seq, vocab]
        float32 logits tensor never materializes in HBM.
      return_aux: also return the summed MoE load-balancing loss as a third
        element ``(out, cache, aux)`` — 0.0 for dense models. The train step
        requests it when ``config.num_experts > 0``.
      activation_sharding: optional ``NamedSharding`` for the [batch, seq,
        hidden] activations (normally batch over (data, fsdp)). Constraining
        activations explicitly keeps XLA/Shardy propagation on the intended
        layout — without it, propagation can try to shard the hidden dim with
        the same axis as the batch dim and fail (or silently pick a slow
        layout). Set by the trainer whenever a mesh is in use.

    Returns:
      (logits [batch, seq, vocab] in ``logits_dtype``, updated cache or None).
    """
    b, s = input_ids.shape
    if positions is None:
        # scalar cache_pos broadcasts; a [batch] vector gives per-row offsets
        # (ragged batched decode)
        offset = (
            cache_pos[:, None] if getattr(cache_pos, "ndim", 0) == 1 else cache_pos
        )
        positions = jnp.arange(s, dtype=jnp.int32)[None, :] + offset
        positions = jnp.broadcast_to(positions, (b, s)).astype(jnp.int32)

    def constrain(h):
        if activation_sharding is not None:
            return jax.lax.with_sharding_constraint(h, activation_sharding)
        return h

    # Sequence parallelism (ring / ulysses) shard_maps over the mesh, and the
    # MoE dispatch constrains its expert blocks to it; recover the mesh from
    # the activation sharding so call sites stay unchanged. (The attention
    # dispatch ignores it for non-sequence-parallel impls.)
    mesh = None
    if activation_sharding is not None:
        mesh = getattr(activation_sharding, "mesh", None)

    embed = params["model"]["embed_tokens"]["weight"].astype(compute_dtype)
    if mesh is not None and (
        dict(mesh.shape).get("tensor", 1) > 1 or dict(mesh.shape).get("data", 1) > 1
    ):
        # Embedding-lookup layout: shard the table by vocab (tensor, else
        # fsdp) and gather the hidden dim. FSDP shards the table's hidden dim
        # with the same mesh axis that shards the ids' batch dim; on tensor>1
        # or data>1 meshes GSPMD resolves that conflict by replicating the
        # gather output and repartitioning it ("involuntary full
        # rematerialization", spmd_partitioner.cc warnings). With the table
        # vocab-sharded, each device gathers from its vocab shard (masked +
        # psum) and the output lands directly on the activation layout.
        # (1, fsdp, 1, *) meshes reshard the (small) gather output cleanly
        # without help, so they skip this.
        embed = _lookup_table_constraint(embed, mesh)
    x = constrain(embed[input_ids])
    if config.embed_scale:
        # Gemma normalizer: HF multiplies by a sqrt(hidden) scalar cast to
        # the activation dtype first — mirror the cast for bf16 bit-parity
        x = x * jnp.asarray(config.hidden_size**0.5, dtype=x.dtype)
    cos, sin = rope_cos_sin(
        positions, config.resolved_head_dim, config.rope_theta, config=config
    )

    explicit_mask = None
    windowed_mask = None
    if segment_ids is not None:
        if cache is not None:
            raise ValueError("segment_ids (packing) and KV cache are exclusive")
        # Packed batch (data/packing.py): attention is restricted to equal
        # segment ids (block-diagonal causal). The segment ids flow into the
        # attention dispatch so the Pallas flash kernel (which masks by
        # segment natively) stays usable; only the sliding-window case needs
        # an explicit mask (window distance uses per-segment positions).
        if config.sliding_window is not None:
            idx = jnp.arange(s, dtype=jnp.int32)
            causal = idx[None, None, :] <= idx[None, :, None]
            same_seg = segment_ids[:, :, None] == segment_ids[:, None, :]
            explicit_mask = causal & same_seg
            q_pos, k_pos = positions[:, :, None], positions[:, None, :]
            # windowed variant for the layers the window applies to; global
            # layers (Gemma2 odd layers) keep the plain block-causal mask
            windowed_mask = explicit_mask & (k_pos > q_pos - config.sliding_window)
            segment_ids = None  # consumed into the explicit mask
    elif cache is not None:
        # Mask over the fixed-size buffer: key j visible to query i iff
        # j <= position(i), and within the sliding window if configured.
        # Paged caches mask the gathered [nb * block_len] view — gathered
        # index IS logical position, so the same rule applies verbatim.
        if block_tables is not None:
            kv_len = block_tables.shape[1] * cache["layers"]["0"]["k"].shape[1]
        else:
            kv_len = cache["layers"]["0"]["k"].shape[1]
        k_pos = jnp.arange(kv_len, dtype=jnp.int32)[None, None, :]
        q_pos = positions[:, :, None]
        explicit_mask = k_pos <= q_pos
        if padding_mask is not None:
            # With a cache, padding_mask must cover the WHOLE buffer
            # [batch, kv_len] (1 = real token at that cache slot), so batched
            # generate over ragged prompts can mask pad keys already written.
            if padding_mask.shape[-1] != kv_len:
                raise ValueError(
                    f"with a KV cache, padding_mask must be [batch, {kv_len}] "
                    f"(full buffer), got {padding_mask.shape}"
                )
            explicit_mask &= padding_mask.astype(bool)[:, None, :]
        if config.sliding_window is not None:
            # after padding so the windowed variant carries the pad bits too
            windowed_mask = explicit_mask & (k_pos > q_pos - config.sliding_window)

    new_layers = {}
    moe_aux = jnp.float32(0.0)
    # Frozen-trunk fast path (TrainConfig.frozen_compute="int8"): layers
    # [0, frozen_layers) carry pre-quantized kernel_int8 siblings and run
    # their projections w8a8 (ops/int8_matmul). The trunk is a pure
    # inference forward: no remat wrap (nothing will ever replay it) and a
    # stop_gradient at the boundary so no cotangent enters it — the
    # compile-cost guard (tests/test_frozen_trunk.py) pins both.
    trunk_layers = frozen_layers if (frozen_compute == "int8" and cache is None) else 0
    for i in range(config.num_layers):
        entry = cache["layers"][str(i)] if cache is not None else None
        in_trunk = i < trunk_layers
        if in_trunk and i == 0:
            # trunk ENTRY stop_gradient: with tied embeddings the trunk's
            # input lookup carries a tangent (embed_tokens is trainable);
            # killing it here — not just at the exit boundary below — means
            # autodiff never traces the trunk at all, which the Pallas
            # w8a8 kernel requires (pallas_call has no JVP rule) and which
            # drops the same embedding-through-trunk gradient the exit
            # boundary drops anyway (documented approximation).
            x = jax.lax.stop_gradient(x)
        block_fn = partial(
            _block,
            config=config,
            layer_idx=i,
            attention_impl=attention_impl,
            compute_dtype=compute_dtype,
            mesh=mesh,
            quant_impl=quant_impl,
            windowed_mask=windowed_mask,
            block_tables=block_tables,
            adapter_idx=adapter_idx,
            w8a8=in_trunk,
        )
        if remat and cache is None and not in_trunk:
            if remat_policy in (None, "full"):
                block_fn = jax.checkpoint(block_fn)
            else:
                # Selective remat: save the expensive tensors, recompute the
                # cheap elementwise ops — trades HBM for less recompute FLOPs
                # than full-block remat (v5e is compute-bound here).
                policies = {
                    "dots": jax.checkpoint_policies.checkpoint_dots,
                    "dots_no_batch": jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
                    "mlp": jax.checkpoint_policies.save_only_these_names("mlp_act"),
                }
                if remat_policy not in policies:
                    raise ValueError(
                        f"unknown remat_policy {remat_policy!r}; expected one of "
                        f"'full', {sorted(policies)}"
                    )
                block_fn = jax.checkpoint(block_fn, policy=policies[remat_policy])
        x, new_entry, layer_aux = block_fn(
            params["model"]["layers"][str(i)],
            x,
            cos,
            sin,
            padding_mask,
            segment_ids,
            explicit_mask,
            entry,
            cache_pos,
        )
        x = constrain(x)
        if in_trunk and i == trunk_layers - 1:
            # trunk/trainable boundary: the only gradient path through the
            # trunk is the (tied) embedding's contribution via the input
            # lookup — deliberately dropped here (documented approximation,
            # docs/architecture.md "Training fast path") so the trunk
            # backward is dead code the compiler eliminates.
            x = jax.lax.stop_gradient(x)
        moe_aux = moe_aux + layer_aux
        if new_entry is not None:
            new_layers[str(i)] = new_entry

    x = rms_norm(
        x,
        params["model"]["norm"]["weight"],
        config.rms_norm_eps,
        zero_centered=config.zero_centered_norm,
    )

    new_cache = {"layers": new_layers} if cache is not None else None
    if output_hidden:
        out = x.astype(compute_dtype)
    else:
        out = unembed(
            params, x, config, compute_dtype=compute_dtype, logits_dtype=logits_dtype, mesh=mesh
        )
    if return_aux:
        return out, new_cache, moe_aux
    return out, new_cache


def _lookup_table_constraint(table, mesh, vocab_dim: int = 0):
    """Constrain a [vocab, hidden]-shaped (or transposed) weight so only the
    vocab dim stays sharded and the hidden dim is gathered. Shared by the
    embedding lookup and the unembed matmul — both places where FSDP's
    hidden-dim sharding collides with the batch-sharded activations and GSPMD
    would otherwise fall back to replicate-then-repartition
    (spmd_partitioner.cc "Involuntary full rematerialization" warnings,
    VERDICT r1 #1).

    The vocab dim shards over ``tensor`` when live (Megatron layout), else
    over ``fsdp`` — the table stays distributed either way (never fully
    replicated for a large-vocab model); GSPMD lowers the lookup to a masked
    local gather + psum over the vocab shards, with only activation-sized
    collectives on the hot path."""
    axes = dict(mesh.shape)
    vocab_ax = None
    for ax in ("tensor", "fsdp"):
        if axes.get(ax, 1) > 1 and table.shape[vocab_dim] % axes[ax] == 0:
            vocab_ax = ax
            break
    if vocab_ax is None:
        # nothing to shard (single-chip mesh, or indivisible vocab): a
        # no-op constraint would still be an HLO boundary that blocks XLA
        # from fusing the weight cast into the matmul — measurably slower
        # inside the remat'd chunked-CE loop
        return table
    spec = [None, None]
    spec[vocab_dim] = vocab_ax
    return jax.lax.with_sharding_constraint(
        table, jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec(*spec))
    )


def unembed(params: Params, hidden, config: ModelConfig, *, compute_dtype=jnp.bfloat16, logits_dtype=jnp.float32, mesh=None):
    """Project hidden states [..., hidden] -> logits [..., vocab] (tied or not).

    With a ``mesh``, the projection weight is constrained like the embedding
    lookup table (vocab over ``tensor``, hidden gathered): under FSDP the
    weight moves to the data, the batch-sharded activations stay put —
    without this, GSPMD reshards the activations (and their cotangents) to
    the weight's hidden-dim sharding through a replicate-then-repartition
    fallback on data>1 meshes."""
    h = hidden.astype(compute_dtype)
    if config.tie_word_embeddings:
        embed = params["model"]["embed_tokens"]["weight"].astype(compute_dtype)
        if mesh is not None:
            embed = _lookup_table_constraint(embed, mesh, vocab_dim=0)
        logits = jnp.einsum("...h,vh->...v", h, embed)
    else:
        kernel = params["lm_head"]["kernel"].astype(compute_dtype)
        if mesh is not None:
            kernel = _lookup_table_constraint(kernel, mesh, vocab_dim=1)
        logits = h @ kernel
    logits = logits.astype(logits_dtype)
    if config.final_logit_softcap is not None:
        # Gemma2 final_logit_softcapping — elementwise, so it composes with
        # both CE chunking schemes (each slice caps its own logits)
        logits = softcap(logits, config.final_logit_softcap)
    return logits


def init_cache(
    config: ModelConfig, batch_size: int, max_len: int, dtype=jnp.bfloat16,
    mesh=None,
):
    """Fixed-size KV cache buffers for autoregressive decoding.

    With ``mesh`` the buffers are allocated directly under the KV partition
    rules (``parallel/sharding.kv_cache_spec``: kv-head dim over ``tensor``)
    — zeros compile straight into sharded device buffers, so a pool that
    only fits *sharded* never stages unsharded on one chip."""
    d = config.resolved_head_dim
    shape = (batch_size, max_len, config.num_kv_heads, d)

    def alloc():
        return {
            "layers": {
                str(i): {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}
                for i in range(config.num_layers)
            }
        }

    return _alloc_kv(alloc, mesh)


def _alloc_kv(alloc, mesh):
    """Run a zeros-allocating thunk, placing its leaves under the mesh's KV
    shardings when a mesh is given (jit out_shardings: works identically on
    single-process and process-spanning meshes)."""
    if mesh is None:
        return alloc()
    from llm_fine_tune_distributed_tpu.parallel.sharding import (
        kv_cache_shardings,
    )

    shapes = jax.eval_shape(alloc)
    shardings = kv_cache_shardings(shapes, mesh)
    return jax.jit(alloc, out_shardings=shardings)()


def init_paged_cache(
    config: ModelConfig,
    num_blocks: int,
    block_len: int,
    dtype=jnp.bfloat16,
    kv_quant: str = "none",
    mesh=None,
):
    """Global paged KV pool for the block-paged continuous engine: per layer
    one [num_blocks, block_len, kv_heads, head_dim] buffer shared by every
    decode slot, addressed through per-slot block tables (``forward``'s
    ``block_tables``). Block 0 is the NULL block (infer/paged.py): never
    allocated, mapped into unused table entries and dead rows so stray writes
    and gathers hit garbage that the position mask always hides.

    ``kv_quant="int8"`` keeps the same per-layer ``k``/``v`` shape in int8
    and adds sibling ``k_scale``/``v_scale`` pools — f32 per-(block, kv-head)
    absmax, indexed by the same block ids — halving HBM per cached token.
    ``_block`` detects the layout by the ``k_scale`` key; the allocator and
    prefix cache (infer/paged.py) deal only in block ids and are untouched.
    Scales start at 0 ("never written"), so every block — the null block
    forever — dequantizes to exact zeros until its first real write.

    ``mesh`` allocates every pool leaf — the int8 code pools AND their
    scale siblings — directly under the KV partition rules (see
    ``init_cache``): kv-head dim over ``tensor``, block dim replicated, so
    one global block id still addresses the same block on every chip.
    """
    if kv_quant not in KV_QUANT_MODES:
        raise ValueError(
            f"unknown kv_quant mode {kv_quant!r} (expected one of {KV_QUANT_MODES})"
        )
    d = config.resolved_head_dim
    shape = (num_blocks, block_len, config.num_kv_heads, d)
    if kv_quant == "int8":
        scale_shape = (num_blocks, config.num_kv_heads)
        entry = lambda: {
            "k": jnp.zeros(shape, jnp.int8),
            "v": jnp.zeros(shape, jnp.int8),
            "k_scale": jnp.zeros(scale_shape, jnp.float32),
            "v_scale": jnp.zeros(scale_shape, jnp.float32),
        }
    else:
        entry = lambda: {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}
    return _alloc_kv(
        lambda: {"layers": {str(i): entry() for i in range(config.num_layers)}},
        mesh,
    )


def insert_cache_row(cache, row_cache, slot):
    """Write a batch-1 cache's K/V into row ``slot`` of a multi-row cache
    without touching the other rows — the continuous-batching prefill-insert
    (infer/engine.py): a freed slot adopts a freshly prefilled prompt while
    its neighbors keep decoding.

    ``row_cache`` buffers may be SHORTER than ``cache``'s (prompt-bucket vs
    full decode buffer): only the leading ``row_cache`` slots of the row are
    overwritten. Stale K/V beyond them is harmless under the slot == position
    invariant — every cache slot ``j`` is rewritten (by prompt prefill or by
    decode token ``j - prompt_len``) before any query position ``>= j`` can
    attend to it, and slots above the current position are always masked.

    ``slot`` may be a traced int32 scalar (one compiled insert program serves
    every slot index).
    """
    new_layers = {}
    for i, entry in cache["layers"].items():
        row = row_cache["layers"][i]
        new_layers[i] = {
            n: jax.lax.dynamic_update_slice(
                entry[n], row[n].astype(entry[n].dtype), (slot, 0, 0, 0)
            )
            for n in ("k", "v")
        }
    return {"layers": new_layers}


class TransformerLM:
    """Thin OO facade over the functional API (convenience for scripts)."""

    def __init__(self, config: ModelConfig):
        self.config = config

    def init(self, rng, dtype=jnp.float32) -> Params:
        return init_params(rng, self.config, dtype)

    def apply(self, params, input_ids, **kw):
        return forward(params, input_ids, self.config, **kw)
