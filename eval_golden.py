#!/usr/bin/env python
"""Golden-question eval CLI: the programmatic version of the reference's
manual 5-question tuned-vs-original comparison (reference README.md:15-21).

Usage:
  python eval_golden.py --tuned-dir outputs/best_model \\
                        [--original-dir <base model dir>] \\
                        [--report golden_report.json] [--max-new-tokens 256]

With only --tuned-dir, answers the questions with the tuned model. With both
dirs, prints the side-by-side diff report and writes it as JSON.
"""

import argparse
import os
import sys


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--tuned-dir", default=os.environ.get("MODEL_DIR", "outputs/best_model"))
    parser.add_argument("--original-dir", default=None)
    parser.add_argument("--report", default="golden_report.json")
    parser.add_argument("--max-new-tokens", type=int, default=256)
    parser.add_argument(
        "--question-set",
        choices=["golden", "wilderness", "both"],
        default="golden",
        help="golden = the reference's five (README.md:15-21); wilderness = "
        "extra domain smoke set; both = concatenation",
    )
    args = parser.parse_args(argv)

    from llm_fine_tune_distributed_tpu.infer import Generator, load_model_dir, load_tokenizer_dir
    from llm_fine_tune_distributed_tpu.infer.golden import (
        GOLDEN_QUESTIONS,
        WILDERNESS_QUESTIONS,
        compare_golden,
        print_report,
        run_golden_eval,
        save_report,
    )

    questions = {
        "golden": GOLDEN_QUESTIONS,
        "wilderness": WILDERNESS_QUESTIONS,
        "both": GOLDEN_QUESTIONS + WILDERNESS_QUESTIONS,
    }[args.question_set]

    def make_generator(path):
        params, mc = load_model_dir(path)
        return Generator(params, mc, load_tokenizer_dir(path))

    if not os.path.isdir(args.tuned_dir):
        print(f"Error: model directory not found: {args.tuned_dir!r}")
        return 1

    print(f"Evaluating tuned model: {args.tuned_dir}")
    tuned = run_golden_eval(
        make_generator(args.tuned_dir),
        questions=questions,
        max_new_tokens=args.max_new_tokens,
    )
    if args.original_dir is None:
        import json

        for a in tuned:
            print("=" * 72)
            print(f"Q: {a.question}\nA: {a.answer[:400]}")
        if args.report:
            # single-model mode still leaves an artifact (the tuned answers)
            # so CI / the run report can archive the eval, not just stdout
            with open(args.report, "w") as f:
                json.dump(
                    {
                        "mode": "tuned-only",
                        "tuned_dir": args.tuned_dir,
                        "answers": [
                            {"question": a.question, "answer": a.answer}
                            for a in tuned
                        ],
                    },
                    f,
                    indent=2,
                )
            print(f"Report written to {args.report}")
        return 0

    print(f"Evaluating original model: {args.original_dir}")
    original = run_golden_eval(
        make_generator(args.original_dir),
        questions=questions,
        max_new_tokens=args.max_new_tokens,
        # reference passes enable_thinking=False only for the base model
        # (ask_original_model.py:44)
        template_kwargs={"enable_thinking": False},
    )
    report = compare_golden(tuned, original)
    print_report(report)
    save_report(report, args.report)
    print(f"Report written to {args.report}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
