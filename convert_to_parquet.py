#!/usr/bin/env python
"""JSONL -> Parquet dataset conversion CLI (reference ``convert_to_parquet.py``).

Usage: python convert_to_parquet.py [data/final_qa_data_unique.jsonl] [out.parquet]
"""

import sys

from llm_fine_tune_distributed_tpu.data.convert import convert_jsonl_to_parquet

if __name__ == "__main__":
    jsonl = sys.argv[1] if len(sys.argv) > 1 else "data/final_qa_data_unique.jsonl"
    out = sys.argv[2] if len(sys.argv) > 2 else None
    convert_jsonl_to_parquet(jsonl, out)
