#!/bin/bash
# Build -> push -> patch -> apply -> tail, the reference deploy pipeline
# (deploy/deploy-script.sh:1-141, C15) retargeted at the TPU JobSet.
set -euo pipefail

REGISTRY_URL="${REGISTRY_URL:-ghcr.io/example}"
IMAGE_NAME="${IMAGE_NAME:-smollm3-tpu-finetune}"
NAMESPACE="${NAMESPACE:-lyric-professor}"
JOB_NAME="smollm3-tpu-finetuning"

cd "$(dirname "$0")/.."

# Timestamp version stamp (reference :15-23)
VERSION="0.1.$(date +%Y%m%d%H%M%S)"
echo "$VERSION" > .version
echo "=== Deploying ${IMAGE_NAME}:${VERSION} ==="

# Build + push (reference :29-36)
docker build -f deploy/Dockerfile -t "${REGISTRY_URL}/${IMAGE_NAME}:${VERSION}" .
docker push "${REGISTRY_URL}/${IMAGE_NAME}:${VERSION}"

# Patch a temp copy of the JobSet with image/version (reference :42-49)
sed -e "s|REGISTRY_URL/smollm3-tpu-finetune:VERSION|${REGISTRY_URL}/${IMAGE_NAME}:${VERSION}|" \
    deploy/jobset.yaml > deploy/jobset-temp.yaml

# Delete any existing job, force stragglers (reference :58-77)
if kubectl get jobset "$JOB_NAME" -n "$NAMESPACE" >/dev/null 2>&1; then
    echo "Deleting existing JobSet ${JOB_NAME}..."
    kubectl delete jobset "$JOB_NAME" -n "$NAMESPACE" --timeout=60s || true
    kubectl delete pods -n "$NAMESPACE" -l "app=${JOB_NAME}" \
        --force --grace-period=0 2>/dev/null || true
fi

# Storage + Aim stack (reference :79-81)
kubectl apply -f deploy/storage.yaml
kubectl apply -f aim/aim-pvc.yaml -f aim/aim-deploy.yaml -f aim/aim-svc.yaml

# Headless service for pod-to-pod DNS: the jax.distributed coordinator and
# the heartbeat detector dial worker-0 by name (the reference creates the
# master Service on 23456 here, :83-105)
kubectl apply -f - <<EOF
apiVersion: v1
kind: Service
metadata:
  name: ${JOB_NAME}
  namespace: ${NAMESPACE}
spec:
  clusterIP: None
  selector:
    app: ${JOB_NAME}
  ports:
    - name: coordinator
      port: 23456
    - name: heartbeat
      port: 23457
EOF

# Apply the job (reference :107-109)
kubectl apply -f deploy/jobset-temp.yaml

echo "=== Status ==="
kubectl get jobset "$JOB_NAME" -n "$NAMESPACE"
kubectl get pods -n "$NAMESPACE" -l "app=${JOB_NAME}" -o wide

# Tail host-0 logs (reference :141-142)
echo "=== Following host-0 logs (Ctrl-C to stop) ==="
kubectl wait --for=condition=Ready pod \
    -l "app=${JOB_NAME},batch.kubernetes.io/job-completion-index=0" \
    -n "$NAMESPACE" --timeout=600s || true
kubectl logs -f -n "$NAMESPACE" \
    -l "app=${JOB_NAME},batch.kubernetes.io/job-completion-index=0"
