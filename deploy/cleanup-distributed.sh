#!/bin/bash
# Confirm-gated teardown (reference deploy/cleanup-distributed.sh:1-112, C17):
# job delete, force pod sweep, service delete, separately-gated PVC delete.
set -uo pipefail

NAMESPACE="${NAMESPACE:-lyric-professor}"
JOB_NAME="smollm3-tpu-finetuning"
SEL="app=${JOB_NAME}"

read -r -p "Delete JobSet ${JOB_NAME} and its pods? [y/N] " yn
if [[ "$yn" == [Yy]* ]]; then
    kubectl delete jobset "$JOB_NAME" -n "$NAMESPACE" --timeout=60s 2>/dev/null || true
    # Force-delete stragglers (reference :43-47)
    kubectl delete pods -n "$NAMESPACE" -l "$SEL" --force --grace-period=0 2>/dev/null || true
    # Service (reference :49-51)
    kubectl delete service "$JOB_NAME" -n "$NAMESPACE" 2>/dev/null || true
    echo "Job resources removed."
fi

# PVC deletion is gated separately — it destroys the trained model
# (reference :53-60)
read -r -p "ALSO delete PVCs (model output + Aim runs)? This DESTROYS trained models and metrics. [y/N] " yn
if [[ "$yn" == [Yy]* ]]; then
    kubectl delete pvc master-model-storage-pvc -n "$NAMESPACE" 2>/dev/null || true
    kubectl delete pvc aim-runs-claim -n "$NAMESPACE" 2>/dev/null || true
    echo "PVCs removed."
fi

# Orphan sweep (reference :71-88)
orphans=$(kubectl get pods -n "$NAMESPACE" -l "$SEL" -o name 2>/dev/null)
if [[ -n "$orphans" ]]; then
    echo "Sweeping orphans: $orphans"
    kubectl delete -n "$NAMESPACE" $orphans --force --grace-period=0 2>/dev/null || true
fi

# Temp manifest (reference :94-100)
rm -f "$(dirname "$0")/jobset-temp.yaml"
echo "Cleanup complete."
