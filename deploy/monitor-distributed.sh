#!/bin/bash
# Cluster-side monitoring (reference deploy/monitor-distributed.sh:1-79, C16):
# job/pod status, resource usage, TPU allocation, events, interactive log follow.
set -uo pipefail

NAMESPACE="${NAMESPACE:-lyric-professor}"
JOB_NAME="smollm3-tpu-finetuning"
SEL="app=${JOB_NAME}"

echo "=== JobSet status ==="
kubectl get jobset "$JOB_NAME" -n "$NAMESPACE" 2>/dev/null || echo "(no JobSet)"
echo
echo "=== Pods ==="
kubectl get pods -n "$NAMESPACE" -l "$SEL" -o wide

echo
echo "=== Resource usage (kubectl top) ==="
kubectl top pods -n "$NAMESPACE" -l "$SEL" 2>/dev/null || echo "(metrics-server unavailable)"

echo
echo "=== TPU allocation ==="
kubectl get pods -n "$NAMESPACE" -l "$SEL" \
    -o custom-columns='POD:.metadata.name,TPUS:.spec.containers[0].resources.requests.google\.com/tpu,NODE:.spec.nodeName'

echo
echo "=== Recent events ==="
kubectl get events -n "$NAMESPACE" --sort-by=.lastTimestamp 2>/dev/null | tail -10

echo
echo "Follow logs: [0-9] host index, (a)ll hosts, (q)uit"
read -r -n 1 choice
echo
case "$choice" in
    [0-9])
        kubectl logs -f -n "$NAMESPACE" \
            -l "$SEL,batch.kubernetes.io/job-completion-index=${choice}"
        ;;
    a)
        kubectl logs -f -n "$NAMESPACE" -l "$SEL" --prefix --max-log-requests=16
        ;;
    *) ;;
esac
