"""LoRA lifecycle (parallel/lora.py): init (B=0 identity), training under
freeze_strategy='lora', merge-for-serving, PEFT adapter round-trip. Config
parity: external-doc article r=16/alpha=8/7 targets (SURVEY.md C23)."""

import pytest

import os

import jax
import jax.numpy as jnp
import numpy as np

from llm_fine_tune_distributed_tpu.config import TrainConfig
from llm_fine_tune_distributed_tpu.models.configs import get_preset
from llm_fine_tune_distributed_tpu.models.transformer import forward, init_params
from llm_fine_tune_distributed_tpu.parallel.freeze import trainable_mask
from llm_fine_tune_distributed_tpu.parallel.lora import (
    add_lora_params,
    load_lora_adapter,
    lora_state_dict,
    merge_lora,
    save_lora_adapter,
    strip_lora,
)
from llm_fine_tune_distributed_tpu.utils.tree import split_by_mask, tree_paths

CFG = get_preset("tiny")


def _base_params():
    return init_params(jax.random.PRNGKey(0), CFG, dtype=jnp.float32)


def _ids():
    return jnp.asarray(
        np.random.RandomState(1).randint(0, CFG.vocab_size, (2, 32)), jnp.int32
    )


def test_init_is_identity():
    """B=0 at init: adapted forward must equal base forward exactly."""
    params = _base_params()
    adapted = add_lora_params(params, jax.random.PRNGKey(7), rank=4)
    ids = _ids()
    ref, _ = forward(params, ids, CFG, compute_dtype=jnp.float32)
    out, _ = forward(adapted, ids, CFG, compute_dtype=jnp.float32)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_adapter_coverage_and_shapes():
    adapted = add_lora_params(_base_params(), jax.random.PRNGKey(7), rank=4)
    paths = [p for p, _ in tree_paths(adapted)]
    # every layer x 7 targets gets A, B, scale
    n_targets = CFG.num_layers * 7
    assert sum(p.endswith("lora_a") for p in paths) == n_targets
    assert sum(p.endswith("lora_b") for p in paths) == n_targets
    q = adapted["model"]["layers"]["0"]["self_attn"]["q_proj"]
    assert q["lora_a"].shape == (CFG.hidden_size, 4)
    assert q["lora_b"].shape[0] == 4


def test_freeze_mask_trains_only_adapters():
    cfg = TrainConfig(freeze_strategy="lora", model_preset="tiny")
    adapted = add_lora_params(_base_params(), jax.random.PRNGKey(7), rank=4)
    mask = trainable_mask(adapted, CFG, cfg)
    trainable, frozen = split_by_mask(adapted, mask)
    assert trainable and all(k.endswith(("lora_a", "lora_b")) for k in trainable)
    assert all(not k.endswith(("lora_a", "lora_b")) for k in frozen)


def test_merge_matches_adapted_forward():
    params = add_lora_params(_base_params(), jax.random.PRNGKey(7), rank=4)
    # give B real values so the adapters actually contribute
    def bump(node):
        if isinstance(node, dict):
            if "lora_b" in node:
                node = dict(node)
                node["lora_b"] = jnp.ones_like(node["lora_b"]) * 0.01
                return node
            return {k: bump(v) for k, v in node.items()}
        return node

    params = bump(params)
    ids = _ids()
    adapted_out, _ = forward(params, ids, CFG, compute_dtype=jnp.float32)
    merged = merge_lora(params)
    assert not any(p.endswith(("lora_a", "lora_b")) for p, _ in tree_paths(merged))
    merged_out, _ = forward(merged, ids, CFG, compute_dtype=jnp.float32)
    np.testing.assert_allclose(
        np.asarray(merged_out), np.asarray(adapted_out), atol=1e-4
    )
    # and differs from base (adapters were non-trivial)
    base_out, _ = forward(strip_lora(params), ids, CFG, compute_dtype=jnp.float32)
    assert np.abs(np.asarray(merged_out) - np.asarray(base_out)).max() > 1e-4


def test_peft_roundtrip(tmp_path):
    cfg = TrainConfig(freeze_strategy="lora", lora_rank=4, lora_alpha=8.0)
    params = add_lora_params(
        _base_params(), jax.random.PRNGKey(7), rank=4, alpha=8.0
    )
    state = lora_state_dict(params)
    assert any(k.endswith("lora_A.weight") for k in state)
    assert all(k.startswith("base_model.model.model.layers") for k in state)

    save_lora_adapter(params, str(tmp_path / "adapter"), cfg)
    assert os.path.exists(tmp_path / "adapter" / "adapter_model.safetensors")
    assert os.path.exists(tmp_path / "adapter" / "adapter_config.json")

    # no TrainConfig passed: scale must come from adapter_config.json itself
    restored = load_lora_adapter(_base_params(), str(tmp_path / "adapter"))
    ids = _ids()
    a, _ = forward(params, ids, CFG, compute_dtype=jnp.float32)
    b, _ = forward(restored, ids, CFG, compute_dtype=jnp.float32)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)
    scale = restored["model"]["layers"]["0"]["self_attn"]["q_proj"]["lora_scale"]
    assert float(scale) == 2.0  # alpha 8 / r 4, NOT the default alpha/r = 0.5


@pytest.mark.slow
def test_lora_sft_trains_and_exports(tmp_path):
    """End-to-end: freeze_strategy='lora' trains (loss decreases) and exports
    both the merged best_model and the PEFT adapter dir."""
    from llm_fine_tune_distributed_tpu.train.trainer import SFTTrainer

    cfg = TrainConfig(
        model_name="",
        model_preset="tiny",
        tokenizer_path="byte-chatml",
        data_dir="data",
        output_dir=str(tmp_path / "out"),
        epochs=1,
        per_device_batch_size=4,
        gradient_accumulation_steps=1,
        max_seq_length=64,
        freeze_strategy="lora",
        lora_rank=4,
        attention_impl="xla",
        eval_steps=0,
        save_steps=0,
        logging_steps=10,
        use_native_loader=False,
        learning_rate=5e-3,
        scale_lr_by_data_parallel=False,
    )
    trainer = SFTTrainer(cfg)
    summary = trainer.train()
    # On the tiny preset LoRA is ~9% of params (fraction shrinks ~1/hidden
    # with model size; on SmolLM3-3B it is <1%). The point: far below the
    # 13.62% of the default last-2+head policy AND only adapter leaves.
    assert summary["trainable_params"] < 0.12 * summary["total_params"]
    hist = trainer.metrics.history
    assert hist[0]["loss"] > hist[-1]["loss"], "LoRA SFT loss did not decrease"
    assert os.path.exists(tmp_path / "out" / "adapter" / "adapter_model.safetensors")
    assert os.path.exists(tmp_path / "out" / "best_model" / "model.safetensors")
