"""End-to-end SFT integration test (SURVEY.md §4c): tiny model + synthetic QA
parquet -> loss decreases -> artifact contract holds (best_model/ safetensors,
training_history.json, training_summary.json — reference training.py:307-339).
Runs on the 8-device virtual CPU mesh with fsdp=2 to exercise sharding."""

import json
import os

import numpy as np
import pytest

import jax

from llm_fine_tune_distributed_tpu.config import MeshConfig, TrainConfig
from llm_fine_tune_distributed_tpu.data.convert import convert_jsonl_to_parquet


@pytest.fixture(scope="module")
def qa_parquet(tmp_path_factory):
    """Synthetic QA jsonl -> parquet via the real converter."""
    tmp = tmp_path_factory.mktemp("data")
    jsonl = tmp / "qa.jsonl"
    rng = np.random.RandomState(0)
    topics = ["Knots", "First Aid", "Cooking"]
    with open(jsonl, "w") as f:
        for i in range(96):
            t = topics[i % 3]
            f.write(
                json.dumps(
                    {
                        "topic": t,
                        "question": f"question number {i} about {t.lower()}?",
                        "answer": f"answer {i}: " + " ".join(["word"] * int(rng.randint(3, 10))),
                    }
                )
                + "\n"
            )
    path = convert_jsonl_to_parquet(str(jsonl), str(tmp / "qa_dataset.parquet"), verbose=False)
    return tmp, os.path.basename(path)


def make_config(tmp_out, data_dir, dataset_file, **overrides):
    base = dict(
        model_name="tiny-random",  # not a dir -> random init
        model_preset="tiny",
        tokenizer_path="byte-chatml",
        data_dir=str(data_dir),
        dataset_file=dataset_file,
        output_dir=str(tmp_out),
        epochs=2,
        per_device_batch_size=2,
        gradient_accumulation_steps=2,
        learning_rate=2e-3,
        max_seq_length=128,
        eval_steps=5,
        logging_steps=2,
        save_steps=8,
        gradient_checkpointing=True,
        mesh=MeshConfig(data=1, fsdp=2, tensor=1, seq=1),
    )
    base.update(overrides)
    return TrainConfig(**base)


@pytest.mark.slow
def test_sft_end_to_end(qa_parquet, tmp_path):
    from llm_fine_tune_distributed_tpu.train.trainer import SFTTrainer

    data_dir, dataset_file = qa_parquet
    out = tmp_path / "outputs"
    config = make_config(out, data_dir, dataset_file)
    trainer = SFTTrainer(config)
    summary = trainer.train()

    # --- loss decreased
    history = trainer.metrics.history
    losses = [h["loss"] for h in history if "loss" in h]
    assert len(losses) >= 3
    assert losses[-1] < losses[0], f"loss did not decrease: {losses[0]} -> {losses[-1]}"

    # --- artifact contract (reference training.py:307-339)
    assert (out / "best_model" / "model.safetensors").exists()
    assert (out / "best_model" / "config.json").exists()
    assert (out / "training_history.json").exists()
    assert (out / "training_summary.json").exists()
    with open(out / "training_summary.json") as f:
        s = json.load(f)
    for key in (
        "model_name", "dataset_path", "epochs", "batch_size", "learning_rate",
        "trainable_params", "total_params", "training_samples",
        "validation_samples", "final_train_loss", "world_size",
        "distributed_training",
    ):
        assert key in s, f"summary missing reference key {key}"
    assert s["trainable_params"] < s["total_params"]  # freezing active
    assert summary["samples_per_second_per_chip"] > 0

    # --- checkpoints rotated and resumable
    ckpts = os.listdir(out / "checkpoints")
    assert len([c for c in ckpts if c.isdigit()]) <= 3


@pytest.mark.slow
def test_freezing_only_updates_last_layers(qa_parquet, tmp_path):
    """Frozen layer params must be bit-identical after training; unfrozen must move."""
    from llm_fine_tune_distributed_tpu.train.trainer import SFTTrainer

    data_dir, dataset_file = qa_parquet
    config = make_config(tmp_path / "o2", data_dir, dataset_file, epochs=1, eval_steps=100, save_steps=100)
    trainer = SFTTrainer(config)
    frozen_keys = list(trainer.state.frozen)
    assert any("layers/0/" in k for k in frozen_keys)  # first layers frozen
    assert all("layers/3/" not in k for k in frozen_keys)  # last layer (idx 3) trainable
    before = {k: np.asarray(v).copy() for k, v in trainer.state.trainable.items()}
    trainer.train()
    moved = [
        k for k, v in trainer.state.trainable.items()
        if not np.allclose(np.asarray(v), before[k])
    ]
    assert moved, "no trainable parameter moved during training"


@pytest.mark.slow
def test_resume_from_checkpoint(qa_parquet, tmp_path):
    from llm_fine_tune_distributed_tpu.train.trainer import SFTTrainer

    data_dir, dataset_file = qa_parquet
    out = tmp_path / "o3"
    config = make_config(out, data_dir, dataset_file, epochs=1, save_steps=4, eval_steps=100)
    t1 = SFTTrainer(config)
    t1.train()
    step_after = int(t1.state.step)
    assert step_after > 0

    config2 = make_config(out, data_dir, dataset_file, epochs=2, save_steps=4, eval_steps=100,
                          resume_from_checkpoint="latest")
    t2 = SFTTrainer(config2)
    t2.train()
    assert int(t2.state.step) > step_after


@pytest.mark.slow
def test_gemma2_family_sft_smoke(qa_parquet, tmp_path):
    """The full Gemma2 knob set survives the real trainer loop (freeze
    policy, sharding over the 4-norm layers, save) and the saved
    config.json round-trips every family knob through from_hf_config."""
    from llm_fine_tune_distributed_tpu.models.configs import from_hf_config
    from llm_fine_tune_distributed_tpu.train.trainer import SFTTrainer

    data_dir, dataset_file = qa_parquet
    out = tmp_path / "outputs"
    config = make_config(
        out, data_dir, dataset_file, model_preset="tiny_gemma2", epochs=1
    )
    trainer = SFTTrainer(config)
    trainer.train()
    losses = [h["loss"] for h in trainer.metrics.history if "loss" in h]
    assert losses[-1] < losses[0]

    import types

    with open(out / "best_model" / "config.json") as f:
        saved = json.load(f)
    cfg = from_hf_config(types.SimpleNamespace(**saved))
    src = trainer.model_config
    for field in (
        "hidden_act", "sandwich_norms", "zero_centered_norm", "embed_scale",
        "attn_logit_softcap", "final_logit_softcap", "query_pre_attn_scalar",
        "alternating_sliding_window", "sliding_window",
    ):
        assert getattr(cfg, field) == getattr(src, field), field


def test_answer_only_eval_metric_and_eval_batch_size(qa_parquet, tmp_path):
    """(a) eval_loss_answer (completion-span CE, VERDICT r4 #4) is computed
    from the same eval forward and logged beside the full-sequence eval_loss;
    with a long constant system prompt the two must differ. (b) eval_loss is
    a token-weighted sum, so a different eval_batch_size must reproduce it
    bit-closely while cutting the number of eval dispatches (VERDICT r4 #7)."""
    from llm_fine_tune_distributed_tpu.train.trainer import SFTTrainer

    data_dir, dataset_file = qa_parquet

    def one_eval(out, **overrides):
        # short prompt: the default 1378-byte wilderness persona would
        # truncate every completion away at seq 128 (the r4 flagship's
        # silent data bug — case (d) pins that path)
        kw = dict(system_prompt="Be brief.", use_native_loader=False)
        kw.update(overrides)
        cfg = make_config(out, data_dir, dataset_file, epochs=1, **kw)
        trainer = SFTTrainer(cfg)
        loss = trainer.evaluate()
        return trainer, loss

    trainer, loss = one_eval(tmp_path / "a")
    assert "completion_mask" in trainer.val_arrays
    ans = trainer._last_eval_answer
    assert ans is not None and np.isfinite(ans)
    # the full-sequence loss averages prompt tokens too; the answer metric
    # is a different quantity (identical values would mean the mask did
    # nothing)
    assert abs(ans - loss) > 1e-6
    # answer mask is a non-empty strict subset of the full loss mask
    cm = trainer.val_arrays["completion_mask"]
    lm = trainer.val_arrays["loss_mask"]
    assert (cm <= lm).all() and 0 < cm.sum() < lm.sum()

    # (b) eval invariance to eval_batch_size
    _, loss_big = one_eval(tmp_path / "b", eval_batch_size=8)
    np.testing.assert_allclose(loss_big, loss, rtol=1e-5)

    # (c) the metric rides into the training logs
    cfg = make_config(tmp_path / "c", data_dir, dataset_file, epochs=1,
                      eval_steps=5, system_prompt="Be brief.",
                      use_native_loader=False)
    tr = SFTTrainer(cfg)
    tr.train()
    evals = [h for h in tr.metrics.history if "eval_loss" in h]
    assert evals and all("eval_loss_answer" in h for h in evals)

    # (d) fully-truncated completions (the r4 flagship data bug): metric
    # suppressed, not reported as a perfect 0.0
    tr2, _ = one_eval(tmp_path / "d", system_prompt=None)
    assert tr2.val_arrays["completion_mask"].sum() == 0
    assert tr2._last_eval_answer is None


def test_checkpoint_best_mode_warns_when_no_midrun_save_possible(
    qa_parquet, tmp_path, capsys
):
    """save_steps beyond total_steps in checkpoint-mode best tracking means
    only the end-of-train save ever exists: load_best_model_at_end silently
    degrades to final-weights-only. The trainer must say so up front."""
    from llm_fine_tune_distributed_tpu.train.trainer import SFTTrainer

    data_dir, dataset_file = qa_parquet
    cfg = make_config(
        tmp_path, data_dir, dataset_file, epochs=1, save_steps=500,
        use_native_loader=False, best_model_tracking="checkpoint",
        load_best_model_at_end=True,
    )
    trainer = SFTTrainer(cfg)
    assert cfg.save_steps > trainer.total_steps  # the degenerate shape
    capsys.readouterr()
    assert trainer._resolve_best_mode() == "checkpoint"
    out = capsys.readouterr().out
    assert "final-weights-only" in out

    # aligned cadence below total_steps: no warning
    cfg2 = make_config(
        tmp_path / "ok", data_dir, dataset_file, epochs=1, save_steps=5,
        eval_steps=5, use_native_loader=False,
        best_model_tracking="checkpoint", load_best_model_at_end=True,
    )
    trainer2 = SFTTrainer(cfg2)
    assert cfg2.save_steps <= trainer2.total_steps
    capsys.readouterr()
    trainer2._resolve_best_mode()
    assert "final-weights-only" not in capsys.readouterr().out
