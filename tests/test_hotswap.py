"""Live deployment: zero-downtime checkpoint hot-swap (ISSUE 10).

Pins the train→serve loop end to end: the trainer-side publish protocol
(train/publish.py — atomic weights-then-manifest commit, keep-last-K
retention), the serving-side watcher/manager (infer/deploy.py — frozen-
fingerprint verification, rolling swaps, instant rollback), and the
engine tick-boundary swap itself (infer/engine.py):

- an identity swap is greedy bit-identical on both slot engines, with the
  warm jit caches intact (zero recompiles after warmup);
- a request in flight across a swap completes on the OLD generation;
- the paged prefix cache flushes on a real weight change (and only then)
  and rebuilds under post-swap traffic;
- rollback restores the prior outputs bit-for-bit and the poller does not
  immediately redeploy the generation that was rolled back;
- a worker crash with a swap staged recovers into a consistent single
  application of that swap;
- 16 concurrent clients across a rolling fleet swap lose zero requests.
"""

import os
import threading

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from llm_fine_tune_distributed_tpu.data.tokenizer import ByteChatMLTokenizer
from llm_fine_tune_distributed_tpu.infer.batching import GenerationConfig
from llm_fine_tune_distributed_tpu.infer.deploy import (
    CheckpointWatcher,
    HotSwapManager,
)
from llm_fine_tune_distributed_tpu.infer.engine import (
    ContinuousBatchingEngine,
    PagedContinuousBatchingEngine,
)
from llm_fine_tune_distributed_tpu.infer.fleet import EngineFleet
from llm_fine_tune_distributed_tpu.infer.generate import Generator
from llm_fine_tune_distributed_tpu.models.configs import get_preset
from llm_fine_tune_distributed_tpu.models.transformer import init_params
from llm_fine_tune_distributed_tpu.train.checkpoints import frozen_fingerprint
from llm_fine_tune_distributed_tpu.train.publish import (
    CheckpointPublisher,
    MANIFEST_NAME,
    atomic_write_bytes,
    list_published,
    load_manifest,
    load_weights,
    parse_step,
    step_dir_name,
    weights_digest,
)
from llm_fine_tune_distributed_tpu.utils.tree import flatten_dict

GREEDY = GenerationConfig(max_new_tokens=6, do_sample=False)
LONG = GenerationConfig(max_new_tokens=32, do_sample=False)


@pytest.fixture(scope="module")
def generator():
    mc = get_preset("tiny")
    params = init_params(jax.random.PRNGKey(0), mc, dtype=jnp.float32)
    return Generator(
        params, mc, ByteChatMLTokenizer(), compute_dtype=jnp.float32,
        eos_token_ids=[],
    )


def _make(generator, kind, **kw):
    kw.setdefault("restart_backoff_s", 0.01)
    kw.setdefault("restart_backoff_max_s", 0.02)
    if kind == "paged":
        return PagedContinuousBatchingEngine(
            generator, slots=4, buf_len=96, prompt_bucket=16,
            block_len=16, prefill_chunk=32, **kw,
        )
    return ContinuousBatchingEngine(
        generator, slots=4, buf_len=96, prompt_bucket=16, **kw
    )


def _prompt(text="hello world"):
    return ByteChatMLTokenizer().encode(text)


def _split(generator, n_trainable=2):
    """(trainable, frozen_fp) pretending the first couple of kernels are
    the fine-tuned set — the same flat {path: leaf} shape the trainer's
    TrainState carries."""
    flat = flatten_dict(generator.params)
    keys = sorted(k for k in flat if k.endswith("kernel"))[:n_trainable]
    trainable = {k: np.asarray(flat[k]) for k in keys}
    frozen = {k: v for k, v in flat.items() if k not in trainable}
    return trainable, frozen_fingerprint(frozen)


# ------------------------------------------------------- publish protocol


def test_atomic_write_replaces_never_tears(tmp_path):
    p = str(tmp_path / "blob.bin")
    atomic_write_bytes(p, b"first")
    atomic_write_bytes(p, b"second")
    assert open(p, "rb").read() == b"second"
    # no temp litter after successful replaces
    assert os.listdir(tmp_path) == ["blob.bin"]


def test_manifest_is_the_commit_point(tmp_path):
    pub = CheckpointPublisher(str(tmp_path), keep_last=3)
    trainable = {"a/kernel": np.ones((2, 2), np.float32)}
    path = pub.publish(7, trainable, frozen_fp={"b": np.zeros(4, np.float32)})
    assert parse_step(os.path.basename(path)) == 7
    assert list_published(str(tmp_path)) == [(7, path)]
    manifest = load_manifest(path)
    assert manifest["step"] == 7
    assert manifest["weight_fingerprint"] == weights_digest(trainable)
    loaded = load_weights(path, manifest)
    assert set(loaded) == {"a/kernel"}
    np.testing.assert_array_equal(loaded["a/kernel"], trainable["a/kernel"])
    # a dir whose manifest is gone is invisible, weights notwithstanding
    os.unlink(os.path.join(path, MANIFEST_NAME))
    assert list_published(str(tmp_path)) == []


def test_torn_manifest_reads_as_no_publish(tmp_path):
    pub = CheckpointPublisher(str(tmp_path), keep_last=3)
    pub.publish(1, {"w": np.ones(3, np.float32)}, frozen_fp={})
    path = pub.publish(2, {"w": np.full(3, 2.0, np.float32)}, frozen_fp={})
    # tear step 2's manifest mid-write: the watcher must fall back to 1
    with open(os.path.join(path, MANIFEST_NAME), "w") as f:
        f.write('{"schema": 1, "step": 2, "weights_fi')
    watcher = CheckpointWatcher(str(tmp_path), verify_frozen=False)
    dep = watcher.check()
    assert dep is not None and dep["step"] == 1


def test_unloadable_weights_skipped(tmp_path):
    pub = CheckpointPublisher(str(tmp_path), keep_last=3)
    pub.publish(1, {"w": np.ones(3, np.float32)}, frozen_fp={})
    path = pub.publish(2, {"w": np.full(3, 2.0, np.float32)}, frozen_fp={})
    os.unlink(os.path.join(path, "trainable.npz"))
    watcher = CheckpointWatcher(str(tmp_path), verify_frozen=False)
    dep = watcher.check()
    assert dep is not None and dep["step"] == 1


def test_retention_keeps_last_k(tmp_path):
    pub = CheckpointPublisher(str(tmp_path), keep_last=3)
    for step in range(1, 6):
        pub.publish(step, {"w": np.full(2, float(step), np.float32)},
                    frozen_fp={})
    steps = [s for s, _ in list_published(str(tmp_path))]
    assert steps == [3, 4, 5]
    # the evicted dirs are gone entirely, not just de-listed
    assert not os.path.exists(str(tmp_path / step_dir_name(1)))
    # the newest publish is still fully loadable after retention
    watcher = CheckpointWatcher(str(tmp_path), verify_frozen=False)
    assert watcher.check()["step"] == 5


def test_identical_payload_same_fingerprint():
    w = {"a": np.arange(6, dtype=np.float32).reshape(2, 3)}
    assert weights_digest(w) == weights_digest({k: v.copy() for k, v in w.items()})
    changed = {"a": w["a"] + 1e-3}
    assert weights_digest(w) != weights_digest(changed)


# --------------------------------------------------- engine tick-boundary


@pytest.mark.parametrize("kind", ["continuous", "paged"])
def test_identity_swap_bit_identical_zero_recompiles(generator, kind, tmp_path):
    engine = _make(generator, kind)
    prompt = _prompt()
    before = engine.submit(prompt, GREEDY)
    # the ledger is shared on the Generator (all engines, all tests), so
    # the zero-recompile claim is a DELTA across the swap: everything this
    # traffic needs is compiled now, and the swap must add nothing
    compiles0 = engine.stats_snapshot()["compile"]["total_compiles"]

    trainable, frozen_fp = _split(generator)
    pub = CheckpointPublisher(str(tmp_path))
    pub.publish(1, trainable, frozen_fp=frozen_fp)
    watcher = CheckpointWatcher(str(tmp_path), base_params=generator.params)
    mgr = HotSwapManager(engine, watcher)
    res = mgr.poll_once()
    assert res is not None and res["step"] == 1
    assert engine.weight_generation == 1
    assert mgr.poll_once() is None  # nothing newer: idempotent

    after = engine.submit(prompt, GREEDY)
    assert after == before  # same values in, same greedy tokens out
    # the swap re-pointed values only — shapes unchanged, caches warm
    comp = engine.stats_snapshot()["compile"]
    assert comp["total_compiles"] == compiles0, comp
    snap = engine.stats_snapshot()
    assert snap["weight_swaps"] == 1
    assert snap["weight_generation"] == 1
    # the apply landed on the flight-recorder timeline
    kinds = [e["kind"] for e in engine.recorder.events()]
    assert "weight_swap_begin" in kinds and "weight_swap" in kinds


@pytest.mark.parametrize("kind", ["continuous", "paged"])
def test_inflight_request_finishes_on_old_generation(generator, kind):
    engine = _make(generator, kind)
    trainable, _ = _split(generator)
    prompt = _prompt("stream across the swap boundary")

    req_box = {}
    started = threading.Event()

    def run():
        it = engine.stream(prompt, LONG, timeout=60)
        toks = []
        for t in it:
            toks.append(t)
            started.set()
        req_box["tokens"] = toks

    th = threading.Thread(target=run)
    th.start()
    assert started.wait(30)
    res = engine.request_weight_swap(
        {k: v + 0.25 for k, v in trainable.items()},
        fingerprint="changed", step=1, timeout=60,
    )
    th.join(60)
    assert not th.is_alive()
    # the stream got every token it asked for — nothing dropped mid-swap
    assert len(req_box["tokens"]) == LONG.max_new_tokens
    assert res["weight_generation"] == 1
    # a request admitted AFTER the swap settles stamped with the new one
    done = engine.submit_full(prompt, GREEDY)
    assert done.weight_generation == 1


def test_prefix_cache_flushes_on_real_change_then_rebuilds(generator):
    engine = _make(generator, "paged")
    trainable, _ = _split(generator)
    # long shared prompt: > block_len so full blocks land in the cache
    # (but within the 96-position buffer alongside GREEDY's new tokens)
    prompt = _prompt("the quick brown fox jumps over the lazy dog")

    def reused_delta(fn):
        a = engine.stats_snapshot()["prefix_tokens_reused"]
        fn()
        return engine.stats_snapshot()["prefix_tokens_reused"] - a

    engine.submit(prompt, GREEDY)  # seeds the cache
    assert reused_delta(lambda: engine.submit(prompt, GREEDY)) > 0

    # the FIRST swap always flushes: boot weights carry no publish digest,
    # so the resident fingerprint is unknown and stale KV cannot be ruled
    # out (engine.request_weight_swap docstring)
    engine.request_weight_swap(
        {k: np.asarray(v) for k, v in trainable.items()},
        fingerprint="fp-same", step=1, timeout=60,
    )
    assert reused_delta(lambda: engine.submit(prompt, GREEDY)) == 0
    assert reused_delta(lambda: engine.submit(prompt, GREEDY)) > 0

    # identity republish (same fingerprint): the cache SURVIVES the swap
    engine.request_weight_swap(
        {k: np.asarray(v) for k, v in trainable.items()},
        fingerprint="fp-same", step=2, timeout=60,
    )
    assert reused_delta(lambda: engine.submit(prompt, GREEDY)) > 0

    # real change: stale KV must not serve — hit rate drops to zero...
    engine.request_weight_swap(
        {k: v + 0.25 for k, v in trainable.items()},
        fingerprint="fp-new", step=3, timeout=60,
    )
    assert reused_delta(lambda: engine.submit(prompt, GREEDY)) == 0
    # ...and the very next identical prompt rebuilds against new weights
    assert reused_delta(lambda: engine.submit(prompt, GREEDY)) > 0
    flushes = [
        e for e in engine.recorder.events()
        if e["kind"] == "prefix_cache_invalidated"
    ]
    assert len(flushes) == 2 and all(f["entries"] > 0 for f in flushes)


def test_rollback_restores_prior_outputs(generator, tmp_path):
    fleet = EngineFleet(
        [_make(generator, "paged") for _ in range(2)], routing="prefix"
    )
    prompt = _prompt()
    base = fleet.submit(prompt, GREEDY)

    trainable, frozen_fp = _split(generator)
    pub = CheckpointPublisher(str(tmp_path))
    pub.publish(1, trainable, frozen_fp=frozen_fp)
    watcher = CheckpointWatcher(str(tmp_path), base_params=generator.params)
    mgr = HotSwapManager(fleet, watcher)
    assert mgr.poll_once()["step"] == 1
    assert fleet.submit(prompt, GREEDY) == base  # same values

    pub.publish(2, {k: v + 0.25 for k, v in trainable.items()},
                frozen_fp=frozen_fp)
    res = mgr.poll_once()
    assert res["step"] == 2 and res["cache_invalidated"]
    changed = fleet.submit(prompt, GREEDY)
    assert changed != base

    rb = mgr.rollback()
    assert rb["kind"] == "rollback" and rb["step"] == 1
    assert fleet.submit(prompt, GREEDY) == base  # bit-identical restore
    # every replica advanced IN LOCKSTEP (a rollback is a forward swap)
    assert [e.weight_generation for e in fleet.replicas] == [3, 3]
    snap = fleet.stats_snapshot()
    assert snap["weight_rollbacks"] == len(fleet.replicas)
    assert snap["weight_generation"] == 3
    # the poller must NOT redeploy the generation the rollback fled
    assert mgr.poll_once() is None
    # a manager that never swapped has nothing buffered to restore
    with pytest.raises(RuntimeError):
        HotSwapManager(_make(generator, "continuous"), watcher).rollback()


def test_crash_during_swap_recovers_consistent(generator):
    engine = _make(generator, "continuous")
    trainable, _ = _split(generator)
    prompt = _prompt("crash mid drain")

    started = threading.Event()
    errors = []

    def run():
        try:
            it = engine.stream(prompt, LONG, timeout=60)
            for _ in it:
                started.set()
        except Exception as e:  # the injected crash fails this in-flight
            started.set()
            errors.append(e)

    th = threading.Thread(target=run)
    th.start()
    assert started.wait(30)
    # the NEXT decode tick — which is the swap's drain tick — blows up
    engine.faults.fail_decode_next(1)
    res = engine.request_weight_swap(
        {k: v + 0.25 for k, v in trainable.items()},
        fingerprint="post-crash", step=1, timeout=60,
    )
    th.join(60)
    # the staged swap survived the in-process restart and applied EXACTLY
    # once, on the rebuilt worker, at a (trivially) drained boundary
    assert res["weight_generation"] == 1
    assert engine.weight_generation == 1
    assert engine.healthy
    assert engine.stats_snapshot()["weight_swaps"] == 1
    # and the engine serves the post-swap weights
    assert engine.submit(prompt, GREEDY)


def test_swap_rejected_on_terminal_engine(generator):
    engine = _make(generator, "continuous", circuit_threshold=1)
    trainable, _ = _split(generator)
    engine.faults.fail_decode_next(10)
    with pytest.raises(Exception):
        engine.submit(_prompt(), GREEDY)
    deadline = 50
    while engine.healthy and deadline:
        import time
        time.sleep(0.05)
        deadline -= 1
    assert not engine.healthy
    with pytest.raises(Exception):
        engine.request_weight_swap(
            {k: np.asarray(v) for k, v in trainable.items()}, timeout=5
        )


def test_swap_under_concurrent_load_drops_nothing(generator, tmp_path):
    """16 clients hammer a 2-replica fleet while a rolling identity-valued
    swap lands: zero failed requests, both replicas on the new generation,
    zero post-warmup recompiles."""
    fleet = EngineFleet(
        [_make(generator, "paged") for _ in range(2)], routing="prefix"
    )
    prompts = [_prompt(f"client {i} says hi") for i in range(16)]
    for p in prompts:  # compile every prompt bucket the load will use
        fleet.submit(p, GREEDY)
    compiles0 = fleet.replicas[0].stats_snapshot()["compile"]["total_compiles"]

    trainable, frozen_fp = _split(generator)
    pub = CheckpointPublisher(str(tmp_path))
    pub.publish(1, trainable, frozen_fp=frozen_fp)
    mgr = HotSwapManager(
        fleet, CheckpointWatcher(str(tmp_path), base_params=generator.params)
    )

    errors = []
    done = []

    def client(i):
        try:
            for _ in range(3):
                out = fleet.submit(prompts[i], GREEDY, timeout=120)
                assert len(out) == GREEDY.max_new_tokens
            done.append(i)
        except Exception as e:  # noqa: BLE001 — the assertion below reports
            errors.append((i, e))

    threads = [threading.Thread(target=client, args=(i,)) for i in range(16)]
    for t in threads:
        t.start()
    swap_res = mgr.poll_once()  # rolling swap rides under the load
    for t in threads:
        t.join(180)
    assert not errors, errors
    assert len(done) == 16
    assert swap_res is not None and swap_res["step"] == 1
    assert [e.weight_generation for e in fleet.replicas] == [1, 1]
    snap = fleet.stats_snapshot()
    assert snap["requests_failed"] == 0
    # the rolling swap added zero compiles (shared ledger: one read covers
    # both replicas — the jit caches live on the Generator)
    comp = fleet.replicas[0].stats_snapshot()["compile"]
    assert comp["total_compiles"] == compiles0, comp


# ----------------------------------------- quantized-resident swap (ISSUE 12)


@pytest.fixture(scope="module")
def int8_generator():
    mc = get_preset("tiny")
    params = init_params(jax.random.PRNGKey(0), mc, dtype=jnp.float32)
    from llm_fine_tune_distributed_tpu.ops.int8 import maybe_quantize

    return Generator(
        maybe_quantize(params, "int8"), mc, ByteChatMLTokenizer(),
        compute_dtype=jnp.float32, eos_token_ids=[],
    )


def _quantized_kernel_paths(generator):
    """Flat paths the trainer would publish (plain .../kernel) whose
    resident form is quantized (kernel_int8 / kernel_nf4 siblings)."""
    mc = get_preset("tiny")
    base = init_params(jax.random.PRNGKey(0), mc, dtype=jnp.float32)
    flat = flatten_dict(base)
    return flat, sorted(
        k for k in flat
        if "/layers/0/" in k and k.endswith("/kernel") and "gate" not in k
    )


def test_swap_requantizes_into_resident_int8(int8_generator):
    """A trainer publishes plain bf16 kernels; the int8-serving engine
    re-quantizes them into the resident format at the drain boundary —
    shapes preserved, so the swap keeps the zero-recompile guarantee, and
    the resident codes are exactly quantize_int8 of the published array."""
    from llm_fine_tune_distributed_tpu.ops.int8 import quantize_int8

    engine = _make(int8_generator, "paged", kv_quant="int8")
    prompt = _prompt()
    assert engine.submit(prompt, GREEDY)
    engine.mark_compile_warm()

    flat, qkeys = _quantized_kernel_paths(int8_generator)
    published = {k: np.asarray(flat[k]) * 1.5 for k in qkeys[:2]}
    res = engine.request_weight_swap(
        published, fingerprint="fp-requant", step=1, timeout=60
    )
    assert res["weight_generation"] == 1
    assert engine.compile_ledger.recompiles_after_warmup == 0

    resident = flatten_dict(engine._params)
    for path, arr in published.items():
        want = quantize_int8(jnp.asarray(arr))
        np.testing.assert_array_equal(
            np.asarray(resident[f"{path}_int8"]), np.asarray(want["int8"])
        )
        np.testing.assert_allclose(
            np.asarray(resident[f"{path}_int8_scale"]),
            np.asarray(want["int8_scale"]), rtol=1e-6,
        )
    assert engine.submit(prompt, GREEDY)  # still serving on the new codes


def test_swap_rejects_unreconcilable_published_leaf(int8_generator):
    """A published leaf that cannot be re-quantized into the resident
    layout fails the swap with a message naming --quantize-weights; the
    engine keeps the old generation and stays healthy."""
    engine = _make(int8_generator, "paged", kv_quant="int8")
    prompt = _prompt()
    assert engine.submit(prompt, GREEDY)
    _, qkeys = _quantized_kernel_paths(int8_generator)
    with pytest.raises(RuntimeError, match="--quantize-weights int8"):
        engine.request_weight_swap(
            {qkeys[0]: np.zeros((8, 8), np.float32)},
            fingerprint="fp-bad", step=1, timeout=60,
        )
    assert engine.weight_generation == 0
    assert engine.healthy
    assert engine.submit(prompt, GREEDY)


def test_swap_requantizes_into_resident_nf4():
    """Same translation for an NF4-resident server: the published bf16
    kernel lands as packed NF4 codes at the resident block size."""
    from llm_fine_tune_distributed_tpu.ops.int8 import maybe_quantize
    from llm_fine_tune_distributed_tpu.ops.nf4 import quantize_nf4

    mc = get_preset("tiny")
    base = init_params(jax.random.PRNGKey(0), mc, dtype=jnp.float32)
    gen = Generator(
        maybe_quantize(base, "nf4"), mc, ByteChatMLTokenizer(),
        compute_dtype=jnp.float32, eos_token_ids=[],
    )
    engine = _make(gen, "continuous")
    prompt = _prompt()
    assert engine.submit(prompt, GREEDY)

    flat = flatten_dict(init_params(jax.random.PRNGKey(0), mc, dtype=jnp.float32))
    path = sorted(
        k for k in flat
        if "/layers/0/" in k and k.endswith("/kernel") and "gate" not in k
    )[0]
    arr = np.asarray(flat[path]) * 1.5
    res = engine.request_weight_swap(
        {path: arr}, fingerprint="fp-nf4", step=1, timeout=60
    )
    assert res["weight_generation"] == 1
    resident = flatten_dict(engine._params)
    want = quantize_nf4(jnp.asarray(arr))
    np.testing.assert_array_equal(
        np.asarray(resident[f"{path}_nf4"]), np.asarray(want["nf4"])
    )
    assert engine.submit(prompt, GREEDY)
