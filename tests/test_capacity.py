"""Capacity observatory + elastic fleet (observe/capacity.py, infer/fleet.py).

What this file pins, layer by layer:

- ``LoadForecaster`` is deterministic pure arithmetic under a synthetic
  clock: constant load converges to the true rate, a ramp yields a
  positive trend that ``forecast`` extrapolates, decay never forecasts
  below zero, and zero-dt / counter-reset samples are harmless;
- ``SaturationModel`` turns measured decode-tick time into sustainable
  throughput (cold replica = unknown, not zero capacity) and derates
  near the roofline ceiling;
- ``recommend_replicas`` holds inside the hysteresis band, and a full
  ramp-hold-decay-hold load sweep crosses each band EXACTLY once per
  direction — no flapping at a plateau, no down-then-up oscillation;
- ``Autoscaler`` on a scripted fleet: dry-run records without acting,
  ``on`` applies one bounded step per tick under the cooldown, factory
  failures are captured without wedging the loop;
- on the real tiny model: the engine's tick-clock forecaster feed and
  ``capacity_snapshot`` carry live signal, goodput/waste classification
  balances against ``tokens_served``, scale-up-then-retire keeps greedy
  output bit-identical to solo decode, a 3->1 scale-down never moves a
  fleet ``/metrics`` total backwards, and retiring a replica purges its
  intent-map entries.
"""

import math
import re
import threading
import time

import jax
import jax.numpy as jnp
import pytest

from llm_fine_tune_distributed_tpu.data.tokenizer import ByteChatMLTokenizer
from llm_fine_tune_distributed_tpu.infer import (
    EngineFleet,
    GenerationConfig,
    Generator,
)
from llm_fine_tune_distributed_tpu.infer.engine import (
    ContinuousBatchingEngine,
    PagedContinuousBatchingEngine,
)
from llm_fine_tune_distributed_tpu.infer.errors import DeadlineExceededError
from llm_fine_tune_distributed_tpu.models.configs import get_preset
from llm_fine_tune_distributed_tpu.models.transformer import init_params
from llm_fine_tune_distributed_tpu.observe.capacity import (
    Autoscaler,
    LoadForecaster,
    SaturationModel,
    capacity_report,
    recommend_replicas,
    report_from_capacity_snapshots,
)
from llm_fine_tune_distributed_tpu.observe.metrics import (
    prometheus_exposition,
)
from llm_fine_tune_distributed_tpu.observe.tracing import FlightRecorder

GREEDY = GenerationConfig(max_new_tokens=6, do_sample=False)


@pytest.fixture(scope="module")
def generator():
    mc = get_preset("tiny")
    params = init_params(jax.random.PRNGKey(0), mc, dtype=jnp.float32)
    return Generator(
        params, mc, ByteChatMLTokenizer(), compute_dtype=jnp.float32,
        eos_token_ids=[],
    )


def _prompts():
    tok = ByteChatMLTokenizer()
    return [tok.encode(t) for t in ("alpha", "beta bravo", "the quick brown fox")]


# ------------------------------------------------------------ LoadForecaster


def test_forecaster_seeds_then_converges_to_constant_rate():
    fc = LoadForecaster(short_tau_s=10.0, long_tau_s=100.0)
    fc.update(0.0, arrivals=0, admitted=0, tokens=0)
    assert fc.samples == 0  # first call only seeds the counter baselines
    assert fc.rate("token_rate") == 0.0
    # 50 tokens/s, sampled every second for ten short time constants
    for i in range(1, 101):
        fc.update(
            float(i), arrivals=2 * i, admitted=2 * i, tokens=50 * i,
            queue_depth=3, queue_wait_s=0.5, live_slots=4,
        )
    assert fc.samples == 100
    assert math.isclose(fc.rate("token_rate", "short"), 50.0, rel_tol=1e-3)
    assert math.isclose(fc.rate("arrival_rate", "short"), 2.0, rel_tol=1e-3)
    assert math.isclose(fc.rate("token_rate", "long"), 50.0, rel_tol=0.3)
    # steady state: no trend, forecast == current rate
    assert abs(fc.trend_tokens_per_s2) < 0.5
    assert math.isclose(fc.forecast(60.0), 50.0, rel_tol=0.05)
    assert math.isclose(fc.queue_depth, 3.0, rel_tol=1e-3)
    assert math.isclose(fc.live_slots_mean, 4.0, rel_tol=1e-3)
    snap = fc.snapshot()
    assert set(snap["rates_short"]) == set(LoadForecaster.RATES)
    assert snap["samples"] == 100 and snap["short_tau_s"] == 10.0


def test_forecaster_ramp_trend_extrapolates_decay_floors_at_zero():
    fc = LoadForecaster(short_tau_s=10.0, long_tau_s=60.0)
    fc.update(0.0, arrivals=0, admitted=0, tokens=0)
    # ramp: token rate grows 1 tok/s every second (10, 11, 12, ...)
    total = 0
    for i in range(1, 61):
        total += 10 + i
        fc.update(float(i), arrivals=i, admitted=i, tokens=total)
    assert fc.trend_tokens_per_s2 > 0.3
    assert fc.forecast(60.0) > fc.rate("token_rate", "short")
    # decay to zero traffic: trend flips negative, forecast never < 0
    for i in range(61, 181):
        fc.update(float(i), arrivals=60, admitted=60, tokens=total)
    assert fc.trend_tokens_per_s2 < 0.0
    assert fc.rate("token_rate", "short") < 1.0
    assert fc.forecast(600.0) == 0.0


def test_forecaster_zero_dt_and_counter_reset_are_harmless():
    fc = LoadForecaster()
    fc.update(0.0, arrivals=0, admitted=0, tokens=0)
    fc.update(1.0, arrivals=5, admitted=5, tokens=100)
    before = fc.snapshot()
    fc.update(1.0, arrivals=9, admitted=9, tokens=999)  # same stamp: skip
    assert fc.snapshot() == before
    # a restarted replica resets its counters: the negative delta clamps
    # to zero rate instead of poisoning the EWMA
    fc.update(2.0, arrivals=0, admitted=0, tokens=0)
    assert fc.rate("token_rate", "short") >= 0.0
    assert fc.samples == 2


# ----------------------------------------------------------- SaturationModel


def test_saturation_model_measured_ticks_and_derate():
    m = SaturationModel()
    # cold replica: no tick timed yet -> unknown, not zero capacity
    assert m.sustainable_tokens_per_s(slots=4, mean_decode_tick_s=0.0) == 0.0
    assert m.sustainable_tokens_per_s(slots=0, mean_decode_tick_s=0.1) == 0.0
    # plain decode: 4 slots x 1 token per tick / 50ms tick = 80 tok/s
    assert m.sustainable_tokens_per_s(
        slots=4, mean_decode_tick_s=0.05
    ) == pytest.approx(80.0)
    # accepted speculation: 2 tokens per live slot per tick doubles it
    assert m.sustainable_tokens_per_s(
        slots=4, mean_decode_tick_s=0.05,
        mean_tokens_per_step=6.0, live_slots_mean=3.0,
    ) == pytest.approx(160.0)
    # per-slot rate floors at 1.0 (a nearly idle engine's low tokens-per-
    # step reflects empty slots, not a slow device)
    assert m.sustainable_tokens_per_s(
        slots=4, mean_decode_tick_s=0.05,
        mean_tokens_per_step=1.0, live_slots_mean=4.0,
    ) == pytest.approx(80.0)
    # past the roofline knee the estimate is shaved linearly
    derated = m.sustainable_tokens_per_s(
        slots=4, mean_decode_tick_s=0.05, hbm_bw_util=0.9
    )
    assert derated == pytest.approx(80.0 * 0.9)
    assert m.sustainable_tokens_per_s(
        slots=4, mean_decode_tick_s=0.05, mfu=0.5, hbm_bw_util=0.5
    ) == pytest.approx(80.0)  # below the knee: no derate


# -------------------------------------------------------- recommend_replicas


def test_recommend_replicas_hysteresis_band():
    per = 100.0
    # inside [down, up] utilization: hold
    assert recommend_replicas(60.0, per, 1) == 1
    assert recommend_replicas(130.0, per, 2) == 2
    # above up: jump straight to ceil(demand / (target * per)) > current
    assert recommend_replicas(90.0, per, 1) == 2
    assert recommend_replicas(400.0, per, 1) == 7  # ceil(400/65)
    # below down: shrink straight to the target count (actuation pacing
    # is the Autoscaler's job, one replica step per tick)
    assert recommend_replicas(30.0, per, 3) == 1
    assert recommend_replicas(110.0, per, 4) == 2  # ceil(110/65)
    assert recommend_replicas(0.0, per, 2) == 1
    # never below one replica, capacity unknown = no move
    assert recommend_replicas(0.0, per, 1) == 1
    assert recommend_replicas(500.0, 0.0, 2) == 2
    assert recommend_replicas(5.0, per, 0) == 1


def test_recommend_replicas_down_never_triggers_immediate_up():
    """The oscillation guard: a shrink is only recommended when the
    shrunken fleet would still sit at or under the up band — util 0.44 at
    2 replicas is below ``down`` but 0.88 at 1 replica would breach
    ``up``, so the recommendation holds."""
    per = 100.0
    assert recommend_replicas(88.0, per, 2) == 2
    # and once demand is genuinely low, the step down happens
    assert recommend_replicas(40.0, per, 2) == 1


def test_recommendation_crosses_each_band_exactly_once_per_direction():
    """Ramp -> plateau -> decay -> plateau, recommendation applied each
    step: every change during the ramp is up, every change during the
    decay is down, and both plateaus hold a constant count."""
    per = 100.0
    ramp = [10.0 * i for i in range(1, 61)]          # 10 .. 600 tok/s
    plateau_hi = [600.0] * 30
    decay = [600.0 - 10.0 * i for i in range(1, 60)]  # 590 .. 10
    plateau_lo = [10.0] * 30
    current = 1
    changes = []  # (phase, direction)
    for phase, series in (
        ("ramp", ramp), ("hold_hi", plateau_hi),
        ("decay", decay), ("hold_lo", plateau_lo),
    ):
        for demand in series:
            rec = recommend_replicas(demand, per, current)
            if rec != current:
                changes.append((phase, "up" if rec > current else "down"))
                current = rec
    assert all(d == "up" for p, d in changes if p == "ramp")
    assert all(d == "down" for p, d in changes if p == "decay")
    assert not [c for c in changes if c[0] in ("hold_hi", "hold_lo")]
    assert current == 1  # decayed all the way back down


# ------------------------------------------------------------ capacity_report


def _forecast_dict(token_rate, queue_depth=0.0, live_slots=0.0, trend=0.0):
    return {
        "rates_short": {
            "arrival_rate": token_rate / 10.0,
            "admit_rate": token_rate / 10.0,
            "token_rate": token_rate,
        },
        "trend_tokens_per_s2": trend,
        "queue_depth": queue_depth,
        "queue_wait_s": 0.0,
        "live_slots_mean": live_slots,
    }


def test_capacity_report_backlog_inflates_demand():
    """A saturated fleet's token rate EQUALS its capacity by definition;
    the queue is where unmet demand shows. Deep backlog therefore inflates
    demand past the measured token rate and flips the recommendation up."""
    calm = capacity_report(
        [_forecast_dict(100.0, queue_depth=2.0, live_slots=4.0)],
        [200.0], 1,
    )
    assert calm["current_load"]["backlog_factor"] == 1.0
    assert calm["recommended_replicas"] == 1
    jammed = capacity_report(
        [_forecast_dict(180.0, queue_depth=20.0, live_slots=4.0)],
        [200.0], 1,
    )
    assert jammed["current_load"]["backlog_factor"] == pytest.approx(5.0)
    assert jammed["forecast"]["demand_tokens_per_s"] == pytest.approx(900.0)
    assert jammed["recommended_replicas"] > 1
    assert jammed["headroom"]["tokens_per_s"] < 0.0


def test_capacity_report_unknown_capacity_and_bounds():
    # no replica has timed a tick: no signal, recommend no change
    rep = capacity_report([_forecast_dict(500.0)], [0.0], 2)
    assert rep["capacity"]["replicas_measured"] == 0
    assert rep["recommended_replicas"] == 2
    # bounds clamp the recommendation, and ride along in the report
    rep = capacity_report(
        [_forecast_dict(900.0)], [100.0], 2, max_replicas=3,
    )
    assert rep["recommended_replicas"] == 3
    rep = capacity_report([_forecast_dict(0.0)], [100.0], 2, min_replicas=2)
    assert rep["recommended_replicas"] == 2
    # no ceiling configured: recommendation unclamped above, bounds say so
    assert rep["bounds"] == {"min_replicas": 2, "max_replicas": None}
    for key in ("replicas", "current_load", "forecast", "capacity",
                "headroom", "recommended_replicas", "bands", "bounds"):
        assert key in rep


def test_report_from_capacity_snapshots_maps_saturation():
    snap = {
        "slots": 4,
        "mean_decode_tick_s": 0.05,
        "mean_tokens_per_step": 0.0,
        "live_slots_mean": 2.0,
        "model_flops_utilization": 0.0,
        "hbm_bandwidth_utilization": 0.0,
        "forecaster": _forecast_dict(40.0, live_slots=2.0),
    }
    rep = report_from_capacity_snapshots([snap, snap], 2)
    assert rep["capacity"]["per_replica_tokens_per_s"] == pytest.approx(80.0)
    assert rep["capacity"]["total_tokens_per_s"] == pytest.approx(160.0)
    assert rep["current_load"]["token_rate"] == pytest.approx(80.0)  # summed
    assert rep["recommended_replicas"] == 2  # util 0.5: inside the band


# ------------------------------------------------------ Autoscaler (scripted)


class _ScriptedFleet:
    """The exact surface Autoscaler reads off a fleet, with a scripted
    demand signal routed through the REAL pure report."""

    def __init__(self, replicas=1, demand=0.0, per_replica=100.0):
        self.n = replicas
        self.demand = demand
        self.per_replica = per_replica
        self.recorder = FlightRecorder(64)
        self.adds = 0
        self.retires = 0
        self.fail_add = False

    def capacity_report(self, horizon_s=60.0, min_replicas=1,
                        max_replicas=None):
        return capacity_report(
            [_forecast_dict(self.demand, live_slots=4.0)],
            [self.per_replica] * self.n, self.n,
            horizon_s=horizon_s, min_replicas=min_replicas,
            max_replicas=max_replicas,
        )

    def add_replica(self):
        if self.fail_add:
            raise RuntimeError("replica factory failure")
        self.n += 1
        self.adds += 1
        return self.n - 1, object()

    def retire_replica(self, rid=None, timeout_s=60.0):
        if self.n <= 1:
            raise ValueError("cannot retire the last replica")
        self.n -= 1
        self.retires += 1
        return rid


def test_autoscaler_dry_run_records_without_acting():
    fleet = _ScriptedFleet(replicas=1, demand=300.0)
    scaler = Autoscaler(fleet, mode="dry-run", max_replicas=8, cooldown_s=0.0)
    d = scaler.tick(0.0)
    assert d["direction"] == "up" and d["applied"] is False
    assert fleet.n == 1 and fleet.adds == 0  # observed, never touched
    events = fleet.recorder.events()
    assert [e["kind"] for e in events] == ["scale_decision"]
    assert events[0]["mode"] == "dry-run" and events[0]["applied"] is False
    assert scaler.decisions() == [d]


def test_autoscaler_on_applies_one_step_under_cooldown():
    fleet = _ScriptedFleet(replicas=1, demand=500.0)
    scaler = Autoscaler(
        fleet, mode="on", max_replicas=8, cooldown_s=30.0,
    )
    d1 = scaler.tick(0.0)
    assert d1["applied"] is True and fleet.n == 2  # ONE step, not to target
    d2 = scaler.tick(10.0)  # still wants more, but inside the cooldown
    assert d2["cooldown"] is True and d2["applied"] is False and fleet.n == 2
    d3 = scaler.tick(31.0)  # cooldown over: next step lands
    assert d3["applied"] is True and fleet.n == 3
    # demand collapses: after the cooldown the fleet steps back down
    fleet.demand = 10.0
    assert scaler.tick(62.0)["direction"] == "down"
    assert fleet.n == 2 and fleet.retires == 1


def test_autoscaler_bounds_hold_and_off_does_nothing():
    fleet = _ScriptedFleet(replicas=2, demand=10_000.0)
    scaler = Autoscaler(fleet, mode="on", max_replicas=2, cooldown_s=0.0)
    assert scaler.tick(0.0) is None  # report clamps to max: no move wanted
    fleet.demand = 0.0
    scaler2 = Autoscaler(fleet, mode="on", min_replicas=2, max_replicas=4,
                         cooldown_s=0.0)
    assert scaler2.tick(0.0) is None  # min bound holds the floor
    off = Autoscaler(_ScriptedFleet(demand=10_000.0), mode="off",
                     max_replicas=8)
    assert off.tick(0.0) is None and off.decisions() == []


def test_autoscaler_captures_factory_failure_and_retries():
    fleet = _ScriptedFleet(replicas=1, demand=500.0)
    fleet.fail_add = True
    scaler = Autoscaler(fleet, mode="on", max_replicas=4, cooldown_s=30.0)
    d = scaler.tick(0.0)
    assert d["applied"] is False and "RuntimeError" in d["error"]
    # the failure did NOT start the cooldown: the next tick retries
    fleet.fail_add = False
    assert scaler.tick(1.0)["applied"] is True and fleet.n == 2


def test_autoscaler_rejects_unknown_mode_and_bounds_history():
    with pytest.raises(ValueError):
        Autoscaler(_ScriptedFleet(), mode="auto")
    fleet = _ScriptedFleet(replicas=1, demand=500.0)
    scaler = Autoscaler(fleet, mode="dry-run", max_replicas=8,
                        cooldown_s=0.0, history=4)
    for i in range(10):
        scaler.tick(float(i))
    assert len(scaler.decisions(limit=64)) == 4
    assert len(scaler.decisions(limit=2)) == 2


# --------------------------------------------------- real-engine observatory


def _elastic_fleet(generator, n=1, routing="prefix", **kw):
    """Growable fleet of paged replicas: same shape as tests/test_fleet.py
    plus the replica factory add_replica builds from."""
    kw.setdefault("restart_backoff_s", 0.01)
    kw.setdefault("restart_backoff_max_s", 0.02)

    def factory(rid):
        return PagedContinuousBatchingEngine(
            generator, slots=4, buf_len=96, prompt_bucket=16,
            block_len=16, prefill_chunk=32, **kw,
        )

    return EngineFleet(
        [factory(i) for i in range(n)], routing=routing,
        replica_factory=factory,
    )


def _settled(fleet, timeout_s=10.0):
    deadline = time.monotonic() + timeout_s
    while any(r.queue_depth or r.live_slots for r in fleet.replicas):
        assert time.monotonic() < deadline, "fleet never went idle"
        time.sleep(0.005)


def test_engine_capacity_snapshot_carries_live_signal(generator):
    """The tick-clock feed end to end: serving traffic populates the
    forecaster (zero extra clock reads — it rides ``_sample_slo``) and
    ``capacity_snapshot`` carries measured tick time the saturation model
    turns into a positive capacity estimate."""
    eng = PagedContinuousBatchingEngine(
        generator, slots=4, buf_len=96, prompt_bucket=16, block_len=16,
        prefill_chunk=32, slo_sample_interval_s=0.01,
    )
    for p in _prompts():
        eng.submit(p, GREEDY, timeout=240)
    snap = eng.capacity_snapshot()
    assert snap["slots"] == 4
    assert snap["decode_ticks"] > 0 and snap["mean_decode_tick_s"] > 0.0
    assert snap["mean_tokens_per_step"] > 0.0
    fc = snap["forecaster"]
    assert fc["samples"] >= 1
    assert set(fc["rates_short"]) == set(LoadForecaster.RATES)
    # a fleet-of-one report from the same snapshot: capacity known and the
    # recommendation well-formed. The exact count depends on how much of the
    # just-served burst still sits in the short-tau EWMA (timing-sensitive on
    # a loaded machine), so pin the bounds, not the value.
    rep = report_from_capacity_snapshots([snap], 1, max_replicas=4)
    assert rep["capacity"]["per_replica_tokens_per_s"] > 0.0
    assert 1 <= rep["recommended_replicas"] <= 4


def test_goodput_accounting_balances_tokens_served(generator):
    """Settle-time classification: clean traffic is 100% goodput; a
    mid-decode deadline cancel charges EXACTLY the partial tokens the 504
    carried to the "deadline" waste reason — and goodput + waste always
    equals tokens_served."""
    eng = ContinuousBatchingEngine(
        generator, slots=2, buf_len=1024, prompt_bucket=16
    )
    tok = ByteChatMLTokenizer()
    prompt = tok.encode("beta bravo")
    eng.submit(prompt, GREEDY, timeout=240)  # warm + clean traffic
    snap = eng.stats_snapshot()
    assert snap["goodput_tokens"] == snap["tokens_served"] > 0
    assert sum(snap["wasted_tokens_by_reason"].values()) == 0
    assert snap["goodput_fraction"] == 1.0
    long_cfg = GenerationConfig(max_new_tokens=900, do_sample=False)
    with pytest.raises(DeadlineExceededError) as ei:
        eng.submit(prompt, long_cfg, deadline_s=0.25, timeout=240)
    partial = len(ei.value.tokens)
    snap = eng.stats_snapshot()
    waste = snap["wasted_tokens_by_reason"]
    assert waste["deadline"] == partial
    assert {k: v for k, v in waste.items() if k != "deadline"} == {
        "abandoned": 0, "failover": 0, "shed": 0,
    }
    assert snap["goodput_tokens"] + sum(waste.values()) == snap["tokens_served"]
    assert snap["goodput_fraction"] == pytest.approx(
        snap["goodput_tokens"] / snap["tokens_served"]
    )


def test_scale_up_then_retire_bit_identical_and_recorded(generator):
    """The actuation contract: growing the fleet mid-traffic and retiring
    back down changes WHERE requests run, never WHAT they return — every
    greedy output is bit-identical to solo decode — and both transitions
    land on the fleet flight recorder."""
    prompts = _prompts()
    solo = [generator.generate_ids(p, GREEDY) for p in prompts]
    fleet = _elastic_fleet(generator, n=1)
    outs = [fleet.submit(prompts[0], GREEDY, timeout=240)]
    rid, rep = fleet.add_replica()
    assert rid == 1 and len(fleet.replicas) == 2
    assert rep is fleet.replicas[1]
    for p in prompts[1:]:
        _settled(fleet)
        outs.append(fleet.submit(p, GREEDY, timeout=240))
    assert fleet.retire_replica(timeout_s=60.0) == 1
    assert len(fleet.replicas) == 1
    outs.append(fleet.submit(prompts[0], GREEDY, timeout=240))
    assert outs == solo + [solo[0]]
    kinds = [e["kind"] for e in fleet.recorder.events()]
    assert kinds.count("scale_up") == 1 and kinds.count("scale_down") == 1
    snap = fleet.stats_snapshot()
    assert snap["replicas"] == 1 and snap["replicas_retired"] == 1
    assert snap["tokens_served"] == 4 * GREEDY.max_new_tokens
    with pytest.raises(ValueError):
        fleet.retire_replica()  # never below one replica


def _metric_total(text, name):
    m = re.search(rf"^{name}(?:{{}})? (\S+)$", text, re.MULTILINE)
    assert m, f"{name} missing from exposition"
    return float(m.group(1))


def test_scale_down_3_to_1_mid_traffic_totals_monotone(generator):
    """THE regression the retired accumulator exists for: scaling 3 -> 1
    while requests are in flight folds every retired replica's counters
    and histograms into the fleet totals BEFORE teardown, so no fleet
    ``/metrics`` total ever decreases across a scale-down."""
    fleet = _elastic_fleet(generator, n=3, routing="round-robin")
    prompts = _prompts()
    solo = [generator.generate_ids(p, GREEDY) for p in prompts]
    for p in prompts:  # spread warm traffic across all three replicas
        fleet.submit(p, GREEDY, timeout=240)
    before = fleet.stats_snapshot()
    assert set(before["per_replica"]) == {"0", "1", "2"}

    def _expo(snap):
        s = dict(snap)
        s.pop("per_replica", None)
        return prometheus_exposition(
            s, fleet.merged_histograms(),
            tenant_histograms=fleet.merged_tenant_histograms(),
        )

    before_total = _metric_total(_expo(before), "serving_tokens_served_total")
    outcomes = [None] * len(prompts)

    def ask(i):
        try:
            outcomes[i] = ("ok", fleet.submit(prompts[i], GREEDY, timeout=240))
        except BaseException as e:  # noqa: BLE001 - recording outcome
            outcomes[i] = ("err", e)

    threads = [
        threading.Thread(target=ask, args=(i,)) for i in range(len(prompts))
    ]
    for t in threads:
        t.start()
    fleet.retire_replica(timeout_s=60.0)
    fleet.retire_replica(timeout_s=60.0)
    for t in threads:
        t.join(timeout=240)
    assert all(not t.is_alive() for t in threads), "a waiter hung"
    assert [o[0] for o in outcomes] == ["ok"] * len(prompts), outcomes
    assert [o[1] for o in outcomes] == solo  # failover kept answers exact
    after = fleet.stats_snapshot()
    assert after["replicas"] == 1 and after["replicas_retired"] == 2
    assert set(after["per_replica"]) == {"0"}
    for key in ("tokens_served", "requests_completed", "prompt_tokens",
                "goodput_tokens", "requests_admitted"):
        assert after[key] >= before[key], key
    assert after["tokens_served"] == 2 * len(prompts) * GREEDY.max_new_tokens
    # histogram mass survives the fold too
    assert (
        after["histograms"]["ttft_s"]["count"]
        == before["histograms"]["ttft_s"]["count"] + len(prompts)
    )
    after_total = _metric_total(_expo(after), "serving_tokens_served_total")
    assert after_total >= before_total


def test_retire_purges_intent_map_and_reroutes(generator):
    """Satellite fix: intent-map entries pointing at a retired replica are
    dropped with it — repeats of the retired home's prefix re-route to a
    live replica instead of dereferencing a dead id."""
    fleet = _elastic_fleet(generator, n=2)
    tok = ByteChatMLTokenizer()
    prompt = tok.encode("the quick brown fox jumps over the lazy dog")
    first = fleet.submit(prompt, GREEDY, timeout=240)
    _settled(fleet)
    fleet.submit(prompt, GREEDY, timeout=240)
    home = fleet.recent_placements()[-1][0]
    assert home in dict(fleet.replica_items())
    assert home in set(fleet._prefix_home.values())
    fleet.retire_replica(rid=home, timeout_s=60.0)
    assert home not in set(fleet._prefix_home.values())
    _settled(fleet)
    assert fleet.submit(prompt, GREEDY, timeout=240) == first
    survivor = fleet.recent_placements()[-1][0]
    assert survivor != home and survivor in dict(fleet.replica_items())


def test_fleet_capacity_report_end_to_end(generator):
    fleet = _elastic_fleet(generator, n=2, slo_sample_interval_s=0.01)
    for p in _prompts():
        fleet.submit(p, GREEDY, timeout=240)
    rep = fleet.capacity_report(min_replicas=1, max_replicas=4)
    assert rep["replicas"] == 2
    assert rep["capacity"]["replicas_measured"] >= 1
    assert rep["capacity"]["per_replica_tokens_per_s"] > 0.0
    assert 1 <= rep["recommended_replicas"] <= 4
    assert rep["bounds"] == {"min_replicas": 1, "max_replicas": 4}
    # an idle fleet is over-provisioned by definition: the autoscaler in
    # dry-run records that without touching the replica set
    scaler = Autoscaler(fleet, mode="dry-run", max_replicas=4,
                        cooldown_s=0.0)
    scaler.tick(time.monotonic())
    assert len(fleet.replicas) == 2
