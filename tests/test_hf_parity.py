"""Numerics parity against the HF torch implementation (SURVEY.md §7.3 risk #1).

Builds a tiny randomly-initialized HF SmolLM3 (and Llama/Mistral) torch model,
round-trips its state dict through our safetensors bridge, and asserts logits
match in float32. This gates RoPE convention (rotate_half), the NoPE layer
pattern, GQA, RMSNorm semantics, and weight transposition all at once.
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from llm_fine_tune_distributed_tpu.models.configs import from_hf_config  # noqa: E402
from llm_fine_tune_distributed_tpu.models.hf_io import hf_state_dict_to_pytree  # noqa: E402
from llm_fine_tune_distributed_tpu.models.transformer import forward  # noqa: E402


def _torch_state_to_numpy(model):
    state = {}
    for k, v in model.state_dict().items():
        if k.endswith("rotary_emb.inv_freq"):
            continue
        state[k.replace("model.model.", "model.")] = v.detach().to(torch.float32).numpy()
    return state


def _compare(hf_model, hf_config, seq=12, atol=2e-4):
    cfg = from_hf_config(hf_config)
    state = _torch_state_to_numpy(hf_model)
    params = hf_state_dict_to_pytree(state, cfg, dtype=np.float32)

    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, size=(2, seq))

    with torch.no_grad():
        ref = hf_model(torch.tensor(ids)).logits.to(torch.float32).numpy()

    ours, _ = forward(params, jnp.asarray(ids, jnp.int32), cfg, compute_dtype=jnp.float32)
    ours = np.asarray(ours)

    np.testing.assert_allclose(ours, ref, rtol=1e-4, atol=atol)


def test_smollm3_tiny_logit_parity():
    hf_cfg = transformers.SmolLM3Config(
        vocab_size=256,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=5,  # includes one NoPE layer (layer idx 3)
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=128,
        tie_word_embeddings=True,
        rope_theta=10000.0,
        pad_token_id=0, bos_token_id=1, eos_token_id=2,
    )
    torch.manual_seed(0)
    model = transformers.SmolLM3ForCausalLM(hf_cfg).eval()
    _compare(model, hf_cfg)


def test_llama_tiny_logit_parity():
    hf_cfg = transformers.LlamaConfig(
        vocab_size=256,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=3,
        num_attention_heads=4,
        num_key_value_heads=4,
        max_position_embeddings=128,
        tie_word_embeddings=False,
        rope_theta=10000.0,
        attention_bias=False,
        pad_token_id=0, bos_token_id=1, eos_token_id=2,
    )
    torch.manual_seed(1)
    model = transformers.LlamaForCausalLM(hf_cfg).eval()
    _compare(model, hf_cfg)


def test_mistral_tiny_logit_parity_with_sliding_window():
    hf_cfg = transformers.MistralConfig(
        vocab_size=256,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=3,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=128,
        tie_word_embeddings=False,
        rope_theta=10000.0,
        sliding_window=8,
        pad_token_id=0, bos_token_id=1, eos_token_id=2,
    )
    torch.manual_seed(2)
    model = transformers.MistralForCausalLM(hf_cfg).eval()
    _compare(model, hf_cfg, seq=16)


def test_mixtral_tiny_logit_parity():
    """MoE routing semantics vs HF Mixtral: softmax-then-top-k-renormalize,
    per-expert SwiGLU, weighted combine. HF computes every selected expert
    (dropless), so our forward runs with ample capacity to match."""
    import dataclasses

    hf_cfg = transformers.MixtralConfig(
        vocab_size=256,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=3,
        num_attention_heads=4,
        num_key_value_heads=2,
        num_local_experts=4,
        num_experts_per_tok=2,
        max_position_embeddings=128,
        tie_word_embeddings=False,
        rope_theta=10000.0,
        sliding_window=None,
        pad_token_id=0, bos_token_id=1, eos_token_id=2,
    )
    torch.manual_seed(3)
    model = transformers.MixtralForCausalLM(hf_cfg).eval()

    cfg = from_hf_config(hf_cfg)
    assert cfg.num_experts == 4 and cfg.num_experts_per_tok == 2
    cfg = dataclasses.replace(cfg, capacity_factor=8.0)  # dropless like HF
    state = _torch_state_to_numpy(model)
    params = hf_state_dict_to_pytree(state, cfg, dtype=np.float32)

    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, size=(2, 12))
    with torch.no_grad():
        ref = model(torch.tensor(ids)).logits.to(torch.float32).numpy()
    ours, _ = forward(params, jnp.asarray(ids, jnp.int32), cfg, compute_dtype=jnp.float32)
    np.testing.assert_allclose(np.asarray(ours), ref, rtol=1e-4, atol=2e-4)


def test_hf_zero_aux_coef_respected():
    """An explicit router_aux_loss_coef=0.0 in the HF config must survive
    import (0.0 is 'aux disabled', not 'use the default')."""
    hf_cfg = transformers.MixtralConfig(
        vocab_size=64, hidden_size=16, intermediate_size=32,
        num_hidden_layers=1, num_attention_heads=2, num_key_value_heads=2,
        num_local_experts=4, num_experts_per_tok=2,
        router_aux_loss_coef=0.0,
    )
    assert from_hf_config(hf_cfg).router_aux_coef == 0.0


def test_qwen2_tiny_logit_parity():
    """Qwen2 family: qkv bias WITHOUT o_proj bias (attention_out_bias=False)
    — gates the bias-leaf init/IO asymmetry against HF Qwen2Attention."""
    hf_cfg = transformers.Qwen2Config(
        vocab_size=256,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=3,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=128,
        tie_word_embeddings=False,
        rope_theta=10000.0,
        use_sliding_window=False,
        pad_token_id=0, bos_token_id=1, eos_token_id=2,
    )
    torch.manual_seed(0)
    model = transformers.Qwen2ForCausalLM(hf_cfg).eval()
    # HF initializes biases to zero; perturb them so parity actually
    # exercises the bias path
    with torch.no_grad():
        for name, p in model.named_parameters():
            if name.endswith("bias"):
                p.add_(torch.randn_like(p) * 0.1)
    cfg = from_hf_config(hf_cfg)
    assert cfg.attention_bias and not cfg.attention_out_bias
    _compare(model, hf_cfg)


def test_qwen3_tiny_logit_parity():
    """Qwen3 family: per-head q/k RMSNorm (qk_norm), no attention bias —
    gates norm placement (post-projection, pre-RoPE) against HF
    Qwen3Attention."""
    hf_cfg = transformers.Qwen3Config(
        vocab_size=256,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=3,
        num_attention_heads=4,
        num_key_value_heads=2,
        head_dim=16,
        max_position_embeddings=128,
        tie_word_embeddings=False,
        rope_theta=10000.0,
        use_sliding_window=False,
        pad_token_id=0, bos_token_id=1, eos_token_id=2,
    )
    torch.manual_seed(0)
    model = transformers.Qwen3ForCausalLM(hf_cfg).eval()
    # q_norm/k_norm init to ones; perturb so parity exercises the norm scale
    with torch.no_grad():
        for name, p in model.named_parameters():
            if "q_norm" in name or "k_norm" in name:
                p.add_(torch.randn_like(p) * 0.1)
    cfg = from_hf_config(hf_cfg)
    assert cfg.qk_norm and not cfg.attention_bias
    _compare(model, hf_cfg)


def test_qwen3_preset_param_count():
    """qwen3_8b preset num_params matches init arithmetic incl. the per-head
    q/k norm leaves (8.19B, HF Qwen3-8B)."""
    from llm_fine_tune_distributed_tpu.models.configs import get_preset
    from llm_fine_tune_distributed_tpu.models.transformer import init_params
    from llm_fine_tune_distributed_tpu.utils.tree import count_params

    mc = get_preset("qwen3_8b")
    assert 8.0e9 < mc.num_params < 8.4e9
    tiny = mc.replace(
        vocab_size=128, hidden_size=32, intermediate_size=64, num_layers=2,
        num_heads=4, num_kv_heads=2, head_dim=16,
    )
    params = init_params(jax.random.PRNGKey(0), tiny, dtype=jnp.float32)
    assert count_params(params) == tiny.num_params
    attn = params["model"]["layers"]["0"]["self_attn"]
    assert attn["q_norm"]["weight"].shape == (16,)
    assert attn["k_norm"]["weight"].shape == (16,)


def test_qwen2_preset_param_count():
    """qwen2_7b preset num_params matches the arch arithmetic with the
    o-bias excluded (7.62B, HF Qwen2-7B)."""
    from llm_fine_tune_distributed_tpu.models.configs import get_preset
    from llm_fine_tune_distributed_tpu.models.transformer import init_params
    from llm_fine_tune_distributed_tpu.utils.tree import count_params

    mc = get_preset("qwen2_7b")
    assert 7.5e9 < mc.num_params < 7.8e9
    tiny = mc.replace(
        vocab_size=128, hidden_size=32, intermediate_size=64, num_layers=2,
        num_heads=4, num_kv_heads=2, head_dim=None,
    )
    params = init_params(jax.random.PRNGKey(0), tiny, dtype=jnp.float32)
    assert count_params(params) == tiny.num_params
    # o_proj carries no bias leaf
    assert "bias" not in params["model"]["layers"]["0"]["self_attn"]["o_proj"]
    assert "bias" in params["model"]["layers"]["0"]["self_attn"]["q_proj"]


def test_llama31_rope_scaling_logit_parity():
    """Llama-3.1 'llama3' smoothed-NTK rope scaling — gates rope_inv_freq's
    wavelength-banded rescale against HF _compute_llama3_parameters."""
    hf_cfg = transformers.LlamaConfig(
        vocab_size=256,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=256,
        tie_word_embeddings=False,
        rope_theta=10000.0,
        rope_scaling={
            "rope_type": "llama3",
            "factor": 8.0,
            "low_freq_factor": 1.0,
            "high_freq_factor": 4.0,
            "original_max_position_embeddings": 32,
        },
        pad_token_id=0, bos_token_id=1, eos_token_id=2,
    )
    torch.manual_seed(0)
    model = transformers.LlamaForCausalLM(hf_cfg).eval()
    cfg = from_hf_config(hf_cfg)
    assert cfg.rope_scaling_type == "llama3"
    assert cfg.rope_scaling_factor == 8.0
    # seq past original_max_position so the slowed long wavelengths matter
    _compare(model, hf_cfg, seq=48)


def test_linear_rope_scaling_logit_parity():
    hf_cfg = transformers.LlamaConfig(
        vocab_size=256,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=256,
        tie_word_embeddings=False,
        rope_theta=10000.0,
        rope_scaling={"rope_type": "linear", "factor": 4.0},
        pad_token_id=0, bos_token_id=1, eos_token_id=2,
    )
    torch.manual_seed(0)
    model = transformers.LlamaForCausalLM(hf_cfg).eval()
    assert from_hf_config(hf_cfg).rope_scaling_type == "linear"
    _compare(model, hf_cfg, seq=40)


def test_unsupported_rope_scaling_rejected_at_load():
    """yarn/longrope/dynamic must fail at config load, not inside the first
    forward's jit trace after weights are already in HBM."""
    hf_cfg = transformers.LlamaConfig(
        vocab_size=64, hidden_size=32, intermediate_size=64,
        num_hidden_layers=1, num_attention_heads=2, num_key_value_heads=2,
        rope_scaling={"rope_type": "yarn", "factor": 4.0},
    )
    with pytest.raises(ValueError, match="unsupported rope_scaling"):
        from_hf_config(hf_cfg)


def test_gemma2_tiny_logit_parity():
    """Gemma2 family: GeGLU, sandwich norms, zero-centered RMSNorm, scaled
    embeddings, q/final softcaps, query_pre_attn_scalar, alternating
    local/global sliding window — all gated against HF Gemma2ForCausalLM
    (eager attention, the impl that honors softcapping)."""
    hf_cfg = transformers.Gemma2Config(
        vocab_size=256,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=4,  # layers 0/2 sliding, 1/3 global
        num_attention_heads=4,
        num_key_value_heads=2,
        head_dim=16,
        max_position_embeddings=128,
        sliding_window=8,
        query_pre_attn_scalar=16.0,
        attn_logit_softcapping=50.0,
        final_logit_softcapping=30.0,
        rope_theta=10000.0,
        pad_token_id=0, bos_token_id=1, eos_token_id=2,
    )
    hf_cfg._attn_implementation = "eager"
    torch.manual_seed(0)
    model = transformers.Gemma2ForCausalLM(hf_cfg).eval()
    # zero-centered norms init at 0; perturb so (1+w) != 1 everywhere
    with torch.no_grad():
        for name, p in model.named_parameters():
            if "layernorm" in name or name.endswith("norm.weight"):
                p.add_(torch.randn_like(p) * 0.1)
    cfg = from_hf_config(hf_cfg)
    assert cfg.sandwich_norms and cfg.zero_centered_norm and cfg.embed_scale
    assert cfg.hidden_act == "gelu_tanh"
    assert cfg.attn_logit_softcap == 50.0 and cfg.final_logit_softcap == 30.0
    assert cfg.alternating_sliding_window and cfg.sliding_window == 8
    # seq > window so the local/global alternation actually differs
    _compare(model, hf_cfg, seq=24, atol=5e-4)


def test_llama32_presets_param_counts():
    """Llama-3.2 1B/3B presets: tied embeddings + llama3 rope factor 32 —
    published HF sizes 1.24B / 3.21B."""
    from llm_fine_tune_distributed_tpu.models.configs import get_preset

    p1 = get_preset("llama3_2_1b")
    assert 1.2e9 < p1.num_params < 1.3e9
    assert p1.tie_word_embeddings and p1.rope_scaling_factor == 32.0
    p3 = get_preset("llama3_2_3b")
    assert 3.1e9 < p3.num_params < 3.3e9


def test_exact_gelu_logit_parity():
    """hidden_act='gelu' (exact erf GeLU) against HF — LlamaConfig with the
    mlp activation swapped, the one non-tanh GeLU family path."""
    hf_cfg = transformers.LlamaConfig(
        vocab_size=256,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=128,
        tie_word_embeddings=False,
        rope_theta=10000.0,
        hidden_act="gelu",
        pad_token_id=0, bos_token_id=1, eos_token_id=2,
    )
    torch.manual_seed(0)
    model = transformers.LlamaForCausalLM(hf_cfg).eval()
    assert from_hf_config(hf_cfg).hidden_act == "gelu"
    _compare(model, hf_cfg)


def test_gemma_hidden_act_precedence_and_moe_act_guard():
    """(a) Gemma-family configs resolve the activation from
    hidden_activation with a gelu_pytorch_tanh default — a stale
    hidden_act='gelu' (early gemma configs) must NOT select exact GeLU.
    (b) MoE + non-silu activation is rejected at config construction."""
    import types

    cfg = from_hf_config(types.SimpleNamespace(
        model_type="gemma", vocab_size=64, hidden_size=32,
        intermediate_size=64, num_hidden_layers=1, num_attention_heads=2,
        num_key_value_heads=2, hidden_act="gelu",
    ))
    assert cfg.hidden_act == "gelu_tanh"

    from llm_fine_tune_distributed_tpu.config import ModelConfig

    with pytest.raises(ValueError, match="silu"):
        ModelConfig(
            name="bad", vocab_size=64, hidden_size=32, intermediate_size=64,
            num_layers=1, num_heads=2, num_kv_heads=2, num_experts=4,
            hidden_act="gelu_tanh",
        )


def test_saved_config_round_trips_exactly_for_every_preset():
    """to_hf_dict -> from_hf_config must be the identity for this
    framework's own saves (ADVICE r4: a gemma-family model trained with
    exact hidden_act='gelu' reloaded as 'gelu_tanh' because only hidden_act
    was written while the gemma branch reads hidden_activation). Pinned for
    ALL presets plus the exact-GeLU gemma corner."""
    import types

    from llm_fine_tune_distributed_tpu.models.configs import PRESETS, to_hf_dict

    cases = list(PRESETS.values()) + [
        PRESETS["tiny_gemma2"].replace(name="gemma2_tuned", hidden_act="gelu"),
    ]
    for mc in cases:
        restored = from_hf_config(types.SimpleNamespace(**to_hf_dict(mc)))
        assert restored == mc, (
            f"{mc.name}: save/load round-trip drifted: "
            f"{[(f, getattr(mc, f), getattr(restored, f)) for f in mc.__dataclass_fields__ if getattr(mc, f) != getattr(restored, f)]}"
        )


def test_unvalidated_gemma_qwen_model_types_rejected():
    """Adjacent family members (gemma3*, qwen2_moe, ...) match the
    model_type-prefix heuristics but differ architecturally — they must be
    rejected at config-load time, before weights load (ADVICE r4), while
    validated types and this framework's own saves still load."""
    import types

    base = dict(
        vocab_size=64, hidden_size=32, intermediate_size=64,
        num_hidden_layers=1, num_attention_heads=2, num_key_value_heads=2,
    )
    for bad in ("gemma3_text", "gemma3", "qwen2_moe", "qwen2_vl", "qwen3_moe"):
        with pytest.raises(ValueError, match="model_type"):
            from_hf_config(types.SimpleNamespace(model_type=bad, **base))
    # validated HF types still load
    for ok in ("gemma", "gemma2", "qwen2", "qwen3"):
        from_hf_config(types.SimpleNamespace(model_type=ok, **base))
    # framework saves carry explicit keys -> accepted under any name
    from llm_fine_tune_distributed_tpu.models.configs import get_preset, to_hf_dict

    d = to_hf_dict(get_preset("tiny_gemma2").replace(name="gemma3_style_tuned"))
    assert from_hf_config(types.SimpleNamespace(**d)).sandwich_norms
