"""SLO engine (ISSUE 13, observe/slo.py): metric ring, per-generation
slices, burn-rate objectives, canary-scored deploys, plus the satellite
guards that ride the same PR:

- ``MetricRing`` samples on the caller's clock stamps (no clock reads of
  its own), delta-decodes histograms exactly, and answers windowed
  counter/histogram/series queries including the wrap/baseline edge cases;
- ``GenerationSlices`` keys latency/error accounting by weight
  generation, prunes to ``keep``, and its delta/merge math is exact;
- ``SloPolicy`` burn rates follow the SRE convention (burn = bad fraction
  / budget), a breach needs EVERY window hot with ``min_events``, and
  ``observe_transitions`` edge-detects breach/recovery flight events;
- ``CanaryJudge`` verdicts (pass / regression / insufficient traffic /
  no siblings) from per-generation deltas under live-ish traffic, and
  ``HotSwapManager`` blocks + rolls back a canary-rejected deploy without
  advancing the deployed step;
- ``CheckpointWatcher`` eval gate: a publish whose manifest metrics
  regress vs the resident generation is skipped with a
  ``publish_rejected_eval`` flight event (satellite 3);
- ``TraceJsonlWriter`` size-based rotation keeps the last K segments
  (satellite 1).

Everything here is host-side (stub stats, fake engines, no model), so the
whole file runs jax-free.
"""

import json
import os
import threading
import time

import pytest

from llm_fine_tune_distributed_tpu.observe.metrics import ServingStats
from llm_fine_tune_distributed_tpu.observe.slo import (
    RING_COUNTERS,
    RING_GAUGES,
    CanaryJudge,
    GenerationSlices,
    MetricRing,
    SloPolicy,
    _frac_above,
)
from llm_fine_tune_distributed_tpu.observe.tracing import (
    FlightRecorder,
    Histogram,
    TraceJsonlWriter,
)


# ------------------------------------------------------------- MetricRing


def test_ring_due_and_sample_cadence():
    ring = MetricRing(capacity=8, interval_s=1.0)
    stats = ServingStats(slots=4)
    assert ring.due(10.0)  # first sample is always due
    ring.sample(10.0, stats)
    assert not ring.due(10.5)
    assert ring.due(11.0)
    ring.sample(11.0, stats)
    assert len(ring) == 2


def test_ring_window_counters_baselines():
    ring = MetricRing(capacity=8, interval_s=1.0)
    stats = ServingStats(slots=4)
    for t in (10.0, 11.0, 12.0):
        stats.incr("tokens_served", 5)
        ring.sample(t, stats)
    # cumulative at samples: 5, 10, 15. A 1.5s window from t=12 baselines
    # at the t=10.5-or-older sample (t=10, value 5) -> delta 10.
    assert ring.window_counters(1.5, now=12.0)["tokens_served"] == 10
    # a window wider than the (unwrapped) history baselines at engine
    # start (zero): the full cumulative value counts
    assert ring.window_counters(100.0, now=12.0)["tokens_served"] == 15


def test_ring_window_counters_wrapped_baseline():
    ring = MetricRing(capacity=2, interval_s=1.0)
    stats = ServingStats(slots=4)
    for t in (10.0, 11.0, 12.0):  # first sample falls off the ring
        stats.incr("tokens_served", 5)
        ring.sample(t, stats)
    # wrapped: the oldest RETAINED sample (t=11, cum 10) is the honest
    # baseline — not zero, which would double-count the evicted history
    assert ring.window_counters(100.0, now=12.0)["tokens_served"] == 5


def test_ring_histogram_deltas_are_exact():
    ring = MetricRing(capacity=8, interval_s=1.0)
    stats = ServingStats(slots=4)
    stats.observe("ttft_s", 0.1)
    ring.sample(10.0, stats)
    stats.observe("ttft_s", 0.2)
    stats.observe("ttft_s", 0.2)
    ring.sample(11.0, stats)
    stats.observe("ttft_s", 0.4)
    ring.sample(12.0, stats)
    # trailing 1.5s from t=12 covers the t=11 and t=12 samples: 3 obs
    counts, total, s = ring.window_histogram("ttft_s", 1.5, now=12.0)
    assert total == 3
    assert sum(counts) == 3
    assert s == pytest.approx(0.8)
    # full history: all 4
    _, total, s = ring.window_histogram("ttft_s", 100.0, now=12.0)
    assert total == 4
    assert s == pytest.approx(0.9)


def test_ring_series_counter_and_gauge():
    ring = MetricRing(capacity=8, interval_s=1.0)
    stats = ServingStats(slots=4)
    for t, depth in ((10.0, 2), (11.0, 7)):
        stats.incr("requests_admitted", 3)
        ring.sample(t, stats, gauges={"queue_depth": depth})
    series = ring.series("requests_admitted", now=11.0)
    assert series["kind"] == "counter"
    assert [p["value"] for p in series["samples"]] == [3, 6]
    assert [p["delta"] for p in series["samples"]] == [0, 3]
    assert [p["age_s"] for p in series["samples"]] == [1.0, 0.0]
    series = ring.series("queue_depth", now=11.0)
    assert series["kind"] == "gauge"
    assert [p["value"] for p in series["samples"]] == [2, 7]
    with pytest.raises(ValueError):
        ring.series("not_a_metric")


def test_ring_metric_names_cover_counters_and_gauges():
    ring = MetricRing()
    assert set(ring.metrics()) == set(RING_COUNTERS) | set(RING_GAUGES)


def test_frac_above_interpolates():
    h = Histogram.exponential()
    for v in (0.01, 0.02, 0.04, 10.0):
        h.observe(v)
    counts, total, _ = h._state()
    # everything above a tiny threshold; past the last finite bound only
    # the overflow bucket counts (10.0 < 400 lives in a finite bucket)
    assert _frac_above(h.bounds, counts, total, 1e-6) == pytest.approx(1.0)
    assert _frac_above(h.bounds, counts, total, 1e6) == pytest.approx(0.0)
    # one of four observations sits above 1.0
    assert _frac_above(h.bounds, counts, total, 1.0) == pytest.approx(
        0.25, abs=0.05
    )


# ------------------------------------------------------ GenerationSlices


def test_generation_slices_settle_and_summaries():
    slices = GenerationSlices(keep=4)
    s0 = slices.slice_for(0)
    s0.ttft.observe(0.1)
    s0.inter_token.observe(0.02)
    slices.note_settled(0, failed=False)
    slices.note_settled(0, failed=True)
    out = slices.summaries()
    assert set(out) == {"0"}
    assert out["0"]["completed"] == 1
    assert out["0"]["failed"] == 1
    assert out["0"]["error_rate"] == pytest.approx(0.5)
    assert out["0"]["ttft"]["count"] == 1


def test_generation_slices_prune_to_keep():
    slices = GenerationSlices(keep=2)
    for gen in range(5):
        slices.slice_for(gen)
    assert slices.generations() == [3, 4]
    # a late settle into a long-pruned generation (swap storm straggler)
    # must not crash and must not grow the slice set past ``keep``
    slices.note_settled(0, failed=False)
    assert slices.generations() == [3, 4]


def test_generation_slices_delta_and_merge():
    slices = GenerationSlices()
    s = slices.slice_for(1)
    s.ttft.observe(0.1)
    slices.note_settled(1, failed=False)
    then = slices.state(1)
    s.ttft.observe(0.4)
    s.ttft.observe(0.4)
    slices.note_settled(1, failed=False)
    slices.note_settled(1, failed=True)
    d = GenerationSlices.delta(slices.state(1), then)
    assert d["completed"] == 1 and d["failed"] == 1
    assert d["error_rate"] == pytest.approx(0.5)
    assert d["ttft"]["count"] == 2  # only the post-snapshot observations
    assert d["ttft"]["mean"] == pytest.approx(0.4, rel=0.01)

    other = GenerationSlices()
    o = other.slice_for(1)
    o.ttft.observe(0.2)
    other.note_settled(1, failed=False)
    merged = GenerationSlices.merge_states(
        [slices.state(1), other.state(1)]
    )
    assert merged["completed"] == 3 and merged["failed"] == 1
    assert merged["ttft"][1] == 4  # histogram totals sum

    fleet = GenerationSlices.merged_summaries([slices, other])
    assert fleet["1"]["completed"] == 3
    assert fleet["1"]["ttft"]["count"] == 4


# ------------------------------------------------------------- SloPolicy


def _ring_with_errors(n_ok, n_bad, window_t=(10.0, 660.0, 700.0)):
    """A ring whose history shows n_ok completions / n_bad failures landed
    inside BOTH the fast (60s) and slow (600s) windows as of t=700: the
    baseline sample at t=10 predates both cutoffs, the activity samples
    sit inside them."""
    ring = MetricRing(capacity=16, interval_s=1.0)
    stats = ServingStats(slots=4)
    ring.sample(window_t[0], stats)
    stats.incr("requests_completed", n_ok)
    stats.incr("requests_failed", n_bad)
    for t in window_t[1:]:
        ring.sample(t, stats)
    return ring


def test_slo_error_rate_burn_math():
    policy = SloPolicy(
        error_rate=0.01, fast_window_s=60.0, slow_window_s=600.0,
        min_events=8,
    )
    # 10% failures against a 1% budget -> burn 10 on every window
    report = policy.evaluate(_ring_with_errors(90, 10), now=700.0)
    obj = report["objectives"]["error_rate"]
    assert not obj["compliant"]
    assert not report["compliant"]
    for w in obj["windows"].values():
        assert w["burn_rate"] == pytest.approx(10.0)
        assert w["events"] == 100
    # zero failures: compliant, zero burn
    report = policy.evaluate(_ring_with_errors(100, 0), now=700.0)
    assert report["compliant"]
    assert report["objectives"]["error_rate"]["windows"]["fast"][
        "burn_rate"
    ] == 0.0


def test_slo_breach_needs_every_window_hot():
    """Failures entirely OUTSIDE the fast window burn only the slow one;
    the multi-window conjunction keeps the objective compliant (the blip
    already passed) — the suppression multi-window burn exists for."""
    ring = MetricRing(capacity=16, interval_s=1.0)
    stats = ServingStats(slots=4)
    ring.sample(10.0, stats)
    stats.incr("requests_completed", 50)
    stats.incr("requests_failed", 50)
    ring.sample(200.0, stats)  # the bad minute: in the slow window only
    stats.incr("requests_completed", 20)
    ring.sample(690.0, stats)  # fast window sees only clean traffic
    policy = SloPolicy(error_rate=0.01, fast_window_s=60.0,
                       slow_window_s=600.0, min_events=8)
    report = policy.evaluate(ring, now=695.0)
    obj = report["objectives"]["error_rate"]
    assert obj["windows"]["slow"]["burn_rate"] > 1.0
    assert obj["windows"]["fast"]["burn_rate"] == 0.0
    assert obj["compliant"]


def test_slo_min_events_suppresses_thin_traffic():
    # 1 failure out of 2 requests is a 50% error rate but only 2 events:
    # below min_events on every window, so no breach
    policy = SloPolicy(error_rate=0.01, min_events=8)
    report = policy.evaluate(_ring_with_errors(1, 1), now=700.0)
    assert report["compliant"]


def test_slo_latency_objective_from_histogram_windows():
    ring = MetricRing(capacity=16, interval_s=1.0)
    stats = ServingStats(slots=4)
    ring.sample(10.0, stats)
    for _ in range(20):
        stats.observe("ttft_s", 10.0)  # every first token way over target
    ring.sample(660.0, stats)  # delta lands inside both windows at t=700
    ring.sample(700.0, stats)
    policy = SloPolicy(ttft_p99_s=2.0, min_events=8)
    report = policy.evaluate(ring, now=700.0)
    obj = report["objectives"]["ttft_p99"]
    assert not obj["compliant"]
    assert obj["windows"]["fast"]["bad_fraction"] == pytest.approx(1.0)
    assert obj["windows"]["slow"]["bad_fraction"] == pytest.approx(1.0)


def test_slo_availability_counts_sheds():
    ring = MetricRing(capacity=16, interval_s=1.0)
    stats = ServingStats(slots=4)
    ring.sample(10.0, stats)
    stats.incr("requests_admitted", 80)
    stats.incr("requests_shed_overflow", 15)
    stats.incr("requests_shed_deadline", 5)
    ring.sample(660.0, stats)
    ring.sample(700.0, stats)
    policy = SloPolicy(availability=0.999, min_events=8)
    report = policy.evaluate(ring, now=700.0)
    obj = report["objectives"]["availability"]
    # 20 turned away of 100 offered = 20% bad vs a 0.1% budget
    assert obj["windows"]["fast"]["bad_fraction"] == pytest.approx(0.2)
    assert not obj["compliant"]


def test_slo_observe_transitions_edges():
    policy = SloPolicy(error_rate=0.01, min_events=8)
    bad = policy.evaluate(_ring_with_errors(90, 10), now=700.0)
    events = policy.observe_transitions(bad)
    assert [k for k, _ in events] == ["slo_breach"]
    assert events[0][1]["objective"] == "error_rate"
    # still breached: no duplicate event
    assert policy.observe_transitions(bad) == []
    good = policy.evaluate(_ring_with_errors(100, 0), now=700.0)
    events = policy.observe_transitions(good)
    assert [k for k, _ in events] == ["slo_recovered"]
    assert policy.observe_transitions(good) == []


def test_slo_merge_reports_takes_hottest_replica():
    policy = SloPolicy(error_rate=0.01, min_events=8)
    hot = policy.evaluate(_ring_with_errors(90, 10), now=700.0)
    cold = policy.evaluate(_ring_with_errors(100, 0), now=700.0)
    merged = SloPolicy.merge_reports([hot, cold])
    assert not merged["compliant"]
    w = merged["objectives"]["error_rate"]["windows"]["fast"]
    assert w["burn_rate"] == pytest.approx(10.0)  # max across replicas
    assert w["events"] == 200  # events sum
    assert SloPolicy.merge_reports([])["compliant"]


# ------------------------------------------------------------ CanaryJudge


class _FakeEngine:
    """The surface CanaryJudge and HotSwapManager touch: slo_slices,
    weight_generation, recorder, stats, _params, request_weight_swap."""

    def __init__(self, params=None):
        self.slo_slices = GenerationSlices()
        self.weight_generation = 0
        self.recorder = FlightRecorder(capacity=64)
        self.stats = ServingStats(slots=2)
        self._params = params if params is not None else {}
        self.swaps = []

    def request_weight_swap(self, weights, fingerprint=None, step=None,
                            timeout=None):
        self.swaps.append((dict(weights), fingerprint, step))
        for k, v in weights.items():
            self._params[k] = v
        self.weight_generation += 1
        return {
            "weight_generation": self.weight_generation,
            "cache_invalidated": False,
        }


def _feed(engine, gen, ttfts, inter=0.01, failed=0, delay=0.03):
    """Feed settled traffic into one engine's generation slice after a
    short delay — lands inside the judge's confirmation window."""

    def run():
        time.sleep(delay)
        s = engine.slo_slices.slice_for(gen)
        for t in ttfts:
            s.ttft.observe(t)
            s.inter_token.observe(inter)
            engine.slo_slices.note_settled(gen, failed=False)
        for _ in range(failed):
            engine.slo_slices.note_settled(gen, failed=True)

    th = threading.Thread(target=run)
    th.start()
    return th


def test_canary_pass_and_flight_events():
    judge = CanaryJudge(window_s=0.25, min_requests=4, poll_s=0.02,
                        ttft_ratio=2.0, min_baseline_s=0.001)
    canary, sib = _FakeEngine(), _FakeEngine()
    canary.weight_generation = 1
    threads = [
        _feed(canary, 1, [0.05] * 6),
        _feed(sib, 0, [0.05] * 6),
    ]
    verdict = judge.judge(canary, [sib], generation=1)
    for t in threads:
        t.join()
    assert verdict["verdict"] == "pass"
    assert verdict["canary_requests"] == 6
    assert verdict["baseline_requests"] == 6
    kinds = [e["kind"] for e in canary.recorder.events()]
    assert "canary_begin" in kinds and "canary_verdict" in kinds


def test_canary_latency_regression_verdict():
    judge = CanaryJudge(window_s=0.25, min_requests=4, poll_s=0.02,
                        ttft_ratio=2.0, min_baseline_s=0.001)
    canary, sib = _FakeEngine(), _FakeEngine()
    canary.weight_generation = 1
    threads = [
        _feed(canary, 1, [0.5] * 6),  # 10x the sibling baseline
        _feed(sib, 0, [0.05] * 6),
    ]
    verdict = judge.judge(canary, [sib], generation=1)
    for t in threads:
        t.join()
    assert verdict["verdict"] == "regression"
    assert "ttft" in verdict["reason"]


def test_canary_error_rate_regression_verdict():
    judge = CanaryJudge(window_s=0.25, min_requests=4, poll_s=0.02,
                        max_error_rate=0.25)
    canary, sib = _FakeEngine(), _FakeEngine()
    canary.weight_generation = 1
    threads = [
        _feed(canary, 1, [0.05] * 4, failed=4),  # 50% errors
        _feed(sib, 0, [0.05] * 6),
    ]
    verdict = judge.judge(canary, [sib], generation=1)
    for t in threads:
        t.join()
    assert verdict["verdict"] == "regression"
    assert "error rate" in verdict["reason"]


def test_canary_insufficient_traffic_and_no_siblings():
    judge = CanaryJudge(window_s=0.05, min_requests=4, poll_s=0.01)
    canary, sib = _FakeEngine(), _FakeEngine()
    assert judge.judge(canary, [], generation=1)["verdict"] == "no_siblings"
    verdict = judge.judge(canary, [sib], generation=1)
    assert verdict["verdict"] == "insufficient_traffic"


# -------------------------------------- HotSwapManager canary integration


def _publish(tmp_path, step, value, metrics=None):
    import numpy as np

    from llm_fine_tune_distributed_tpu.train.publish import (
        CheckpointPublisher,
    )

    return CheckpointPublisher(str(tmp_path), keep_last=8).publish(
        step, {"w": np.full(3, float(value), np.float32)}, frozen_fp={},
        metrics=metrics,
    )


def _manager(tmp_path, engines, judge):
    from llm_fine_tune_distributed_tpu.infer.deploy import (
        CheckpointWatcher,
        HotSwapManager,
    )

    return HotSwapManager(
        type("Fleet", (), {"replicas": engines})(),
        CheckpointWatcher(str(tmp_path), verify_frozen=False),
        canary=judge,
    )


def test_manager_blocks_canary_regression(tmp_path):
    import numpy as np

    engines = [
        _FakeEngine({"w": np.zeros(3, np.float32)}) for _ in range(2)
    ]
    judge = CanaryJudge(window_s=0.25, min_requests=4, poll_s=0.02,
                        ttft_ratio=2.0, min_baseline_s=0.001)
    mgr = _manager(tmp_path, engines, judge)
    _publish(tmp_path, 1, 1.0)
    threads = [
        _feed(engines[0], 1, [0.5] * 6),  # canary regresses after the swap
        _feed(engines[1], 0, [0.05] * 6),
    ]
    res = mgr.poll_once()
    for t in threads:
        t.join()
    assert res["kind"] == "canary_rejected"
    assert res["canary"]["verdict"] == "regression"
    # the canary swapped then rolled back; the sibling never swapped
    assert engines[0].weight_generation == 2
    assert engines[1].weight_generation == 0
    assert len(engines[1].swaps) == 0
    # the deployed step did not advance and the step is held
    assert mgr.deployed_step == -1
    assert mgr.poll_once() is None  # rejected publish is not retried
    kinds = [e["kind"] for e in engines[0].recorder.events()]
    assert "canary_rollback" in kinds
    # status surfaces the verdict for /v1/deploy readers
    assert mgr.status()["last_canary"]["verdict"] == "regression"


def test_manager_rolls_fleet_on_canary_pass(tmp_path):
    import numpy as np

    engines = [
        _FakeEngine({"w": np.zeros(3, np.float32)}) for _ in range(2)
    ]
    judge = CanaryJudge(window_s=0.2, min_requests=4, poll_s=0.02,
                        ttft_ratio=3.0, min_baseline_s=0.001)
    mgr = _manager(tmp_path, engines, judge)
    _publish(tmp_path, 1, 1.0)
    threads = [
        _feed(engines[0], 1, [0.05] * 6),
        _feed(engines[1], 0, [0.05] * 6),
    ]
    res = mgr.poll_once()
    for t in threads:
        t.join()
    assert res["kind"] == "deploy"
    assert res["canary"]["verdict"] == "pass"
    assert [e.weight_generation for e in engines] == [1, 1]
    assert mgr.deployed_step == 1


def test_manager_insufficient_traffic_passes_through(tmp_path):
    """A canary window with no traffic cannot verdict; the roll proceeds
    (the error-rate backstop still guards) rather than wedging deploys."""
    import numpy as np

    engines = [
        _FakeEngine({"w": np.zeros(3, np.float32)}) for _ in range(2)
    ]
    judge = CanaryJudge(window_s=0.05, min_requests=4, poll_s=0.01)
    mgr = _manager(tmp_path, engines, judge)
    _publish(tmp_path, 1, 1.0)
    res = mgr.poll_once()
    assert res["kind"] == "deploy"
    assert res["canary"]["verdict"] == "insufficient_traffic"
    assert [e.weight_generation for e in engines] == [1, 1]


# ------------------------------------------- eval-gated promotion (sat. 3)


def test_watcher_eval_gate_rejects_regressing_publish(tmp_path):
    from llm_fine_tune_distributed_tpu.infer.deploy import CheckpointWatcher

    recorder = FlightRecorder(capacity=16)
    watcher = CheckpointWatcher(
        str(tmp_path), verify_frozen=False, recorder=recorder
    )
    _publish(tmp_path, 1, 1.0, metrics={"eval_loss": 0.5})
    dep = watcher.check()
    assert dep["step"] == 1
    watcher.note_deployed(dep["manifest"]["metrics"])

    # a worse eval_loss is skipped — repeatedly, with ONE flight event
    _publish(tmp_path, 2, 2.0, metrics={"eval_loss": 0.9})
    assert watcher.check(min_step=1) is None
    assert watcher.check(min_step=1) is None
    rejected = [
        e for e in recorder.events() if e["kind"] == "publish_rejected_eval"
    ]
    assert len(rejected) == 1
    assert rejected[0]["step"] == 2
    assert rejected[0]["candidate"] == pytest.approx(0.9)
    assert rejected[0]["resident"] == pytest.approx(0.5)

    # an improving publish deploys
    _publish(tmp_path, 3, 3.0, metrics={"eval_loss": 0.4})
    assert watcher.check(min_step=1)["step"] == 3


def test_watcher_eval_gate_needs_both_sides(tmp_path):
    """Metric-less publishes (smoke tests, ad hoc rolls) bypass the gate
    in BOTH directions: no resident baseline, or no candidate metric."""
    from llm_fine_tune_distributed_tpu.infer.deploy import CheckpointWatcher

    watcher = CheckpointWatcher(str(tmp_path), verify_frozen=False)
    # no resident metrics yet: anything deploys
    _publish(tmp_path, 1, 1.0, metrics={"eval_loss": 0.5})
    assert watcher.check()["step"] == 1
    watcher.note_deployed({"eval_loss": 0.5})
    # candidate without metrics: deploys despite a resident baseline
    _publish(tmp_path, 2, 2.0)
    assert watcher.check(min_step=1)["step"] == 2


def test_watcher_eval_gate_max_mode(tmp_path):
    from llm_fine_tune_distributed_tpu.infer.deploy import CheckpointWatcher

    watcher = CheckpointWatcher(
        str(tmp_path), verify_frozen=False,
        eval_gate_metric="accuracy", eval_gate_mode="max",
    )
    watcher.note_deployed({"accuracy": 0.8})
    _publish(tmp_path, 1, 1.0, metrics={"accuracy": 0.7})
    assert watcher.check() is None  # lower accuracy regresses under max
    _publish(tmp_path, 2, 2.0, metrics={"accuracy": 0.9})
    assert watcher.check()["step"] == 2
    with pytest.raises(ValueError):
        CheckpointWatcher(str(tmp_path), eval_gate_mode="sideways")


# --------------------------------------------- trace log rotation (sat. 1)


def test_trace_writer_rotates_and_keeps_last_k(tmp_path):
    path = str(tmp_path / "trace.jsonl")
    writer = TraceJsonlWriter(path, max_bytes=300, keep=2)
    for i in range(50):
        writer.write(
            {"event": "request_done", "request_id": f"req-{i:04d}",
             "tokens": i}
        )
    writer.close()
    # live file plus at most ``keep`` rotated segments
    files = sorted(os.listdir(tmp_path))
    assert "trace.jsonl" in files
    assert "trace.jsonl.1" in files
    assert set(files) <= {"trace.jsonl", "trace.jsonl.1", "trace.jsonl.2"}
    # every surviving segment stays line-valid JSONL under rotation
    newest_ids = []
    for name in files:
        with open(tmp_path / name) as f:
            for line in f:
                rec = json.loads(line)
                assert rec["event"] == "request_done"
                if name == "trace.jsonl":
                    newest_ids.append(rec["request_id"])
    # the newest events live in the live file
    assert newest_ids and newest_ids[-1] == "req-0049"
    # no rotated segment exceeds the cap (the live file may briefly)
    assert os.path.getsize(tmp_path / "trace.jsonl.1") <= 400


def test_trace_writer_unbounded_by_default(tmp_path):
    path = str(tmp_path / "trace.jsonl")
    writer = TraceJsonlWriter(path)
    for i in range(100):
        writer.write({"event": "e", "i": i})
    writer.close()
    assert os.listdir(tmp_path) == ["trace.jsonl"]
