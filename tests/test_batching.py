"""Dynamic request batching (infer/batching.py): concurrent same-config
requests group into one device batch with unchanged (greedy) results;
mixed-config traffic still resolves correctly."""

import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llm_fine_tune_distributed_tpu.data.tokenizer import ByteChatMLTokenizer
from llm_fine_tune_distributed_tpu.infer import GenerationConfig, Generator
from llm_fine_tune_distributed_tpu.infer.batching import BatchingEngine, _pad_batch_size
from llm_fine_tune_distributed_tpu.models.configs import get_preset
from llm_fine_tune_distributed_tpu.models.transformer import init_params


def _make_generator():
    mc = get_preset("tiny")
    params = init_params(jax.random.PRNGKey(0), mc, dtype=jnp.float32)
    return Generator(
        params, mc, ByteChatMLTokenizer(), compute_dtype=jnp.float32, eos_token_ids=[]
    )


def test_pad_batch_size():
    assert [_pad_batch_size(n, 8) for n in (1, 2, 3, 5, 8, 9)] == [1, 2, 4, 8, 8, 8]


@pytest.mark.slow
def test_concurrent_requests_match_solo():
    gen = _make_generator()
    tok = ByteChatMLTokenizer()
    cfg = GenerationConfig(max_new_tokens=5, do_sample=False, repetition_penalty=1.0)
    prompts = [tok.encode(t) for t in ("alpha", "beta bravo", "the quick brown fox")]
    solo = [gen.generate_ids(p, cfg) for p in prompts]

    engine = BatchingEngine(gen, max_batch=4, window_ms=200.0)
    results = [None] * len(prompts)

    def worker(i):
        results[i] = engine.submit(prompts[i], cfg)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(len(prompts))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert results == solo


def test_mixed_configs_all_resolve():
    gen = _make_generator()
    tok = ByteChatMLTokenizer()
    cfg_a = GenerationConfig(max_new_tokens=4, do_sample=False, repetition_penalty=1.0)
    cfg_b = GenerationConfig(max_new_tokens=6, do_sample=False, repetition_penalty=1.0)
    engine = BatchingEngine(gen, max_batch=4, window_ms=50.0)
    prompts = [tok.encode("one"), tok.encode("two"), tok.encode("three")]
    cfgs = [cfg_a, cfg_b, cfg_a]
    results = [None] * 3

    def worker(i):
        results[i] = engine.submit(prompts[i], cfgs[i])

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=180)
    for i in range(3):
        assert results[i] is not None
        assert len(results[i]) == cfgs[i].max_new_tokens


def test_generation_error_propagates_to_waiters():
    class Boom:
        def generate_batch(self, *a, **kw):
            raise RuntimeError("boom")

    engine = BatchingEngine(Boom(), max_batch=2, window_ms=5.0)
    try:
        engine.submit([1, 2, 3], GenerationConfig(max_new_tokens=2))
        raise AssertionError("expected RuntimeError")
    except RuntimeError as e:
        assert "boom" in str(e)


def test_sampled_requests_keep_solo_seeding():
    """Sampled requests never co-batch: each concurrent request reproduces
    exactly what a solo run with its (config, seed) produces."""
    gen = _make_generator()
    tok = ByteChatMLTokenizer()
    cfg = GenerationConfig(max_new_tokens=5, do_sample=True, temperature=1.0)
    prompts = [tok.encode("alpha"), tok.encode("beta")]
    seeds = [3, 7]
    solo = [gen.generate_ids(p, cfg, seed=s) for p, s in zip(prompts, seeds)]

    engine = BatchingEngine(gen, max_batch=4, window_ms=100.0)
    results = [None, None]

    def worker(i):
        results[i] = engine.submit(prompts[i], cfg, seed=seeds[i])

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=180)
    assert results == solo


def test_submit_timeout_sheds_load():
    """A wedged device must not block handler threads forever (ADVICE r1):
    submit raises TimeoutError after the configured wait."""
    import pytest

    class Wedged:
        def generate_batch(self, *a, **kw):
            import time

            time.sleep(60)

    engine = BatchingEngine(Wedged(), max_batch=2, window_ms=1.0)
    with pytest.raises(TimeoutError):
        engine.submit([1, 2, 3], GenerationConfig(max_new_tokens=2), timeout=0.2)


def test_deferred_requests_keep_fifo_order():
    """An incompatible request drained during another group's window is
    serviced on the NEXT cycle, before requests that arrived after it
    (ADVICE r1: no re-enqueue-at-tail reordering)."""
    import time

    order = []
    lock = threading.Lock()

    class Recorder:
        def generate_batch(self, prompts, gen, seed=0, live_rows=None):
            with lock:
                order.extend(tuple(p) for p in prompts)
            return [[0] * gen.max_new_tokens for _ in prompts]

    greedy_a = GenerationConfig(max_new_tokens=2, do_sample=False)
    sampled = GenerationConfig(max_new_tokens=2, do_sample=True)
    greedy_b = GenerationConfig(max_new_tokens=3, do_sample=False)
    engine = BatchingEngine(Recorder(), max_batch=4, window_ms=150.0)

    # greedy_a opens a 150ms window; a sampled request arrives inside the
    # window (incompatible -> deferred), then an also-incompatible greedy_b
    # request arrives after it. The old re-enqueue-at-tail behavior served
    # greedy_b first; the deferred list must serve the sampled one first.
    threads = []

    def submit_after(delay, prompt, cfg):
        def run():
            time.sleep(delay)
            engine.submit(prompt, cfg)

        t = threading.Thread(target=run)
        t.start()
        return t

    threads.append(submit_after(0.0, [1], greedy_a))
    threads.append(submit_after(0.03, [2], sampled))
    threads.append(submit_after(0.06, [3], greedy_b))
    for t in threads:
        t.join(timeout=30)
    assert order.index((2,)) < order.index((3,)), order
