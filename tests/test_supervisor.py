"""Self-healing serving (infer/supervisor.py + the supervision loop in
infer/engine.py). Pins the recovery contracts:

- an injected retryable decode failure fails every IN-FLIGHT request fast
  with RetryableEngineError (no waiter ever hangs), the worker rebuilds
  device state in-process, and the NEXT greedy request is bit-identical to
  solo ``generate_ids`` — on both the dense and the paged engine;
- repeated failures inside the sliding window open the circuit breaker:
  the engine goes terminally unhealthy and everything (queued, in-flight,
  and later submits) resolves with CircuitOpenError;
- bounded admission sheds overflow with a 429-mapped QueueOverflowError
  carrying a FINITE Retry-After, and queue-wait deadlines shed stale
  waiters before prefill;
- graceful drain closes admission while in-flight work finishes;
- the decode worker pokes the step watchdog (runtime/watchdog.py) every
  device round-trip and pauses it while legitimately idle.
"""

import threading
import time

import jax
import jax.numpy as jnp
import pytest

from llm_fine_tune_distributed_tpu.data.tokenizer import ByteChatMLTokenizer
from llm_fine_tune_distributed_tpu.infer import GenerationConfig, Generator
from llm_fine_tune_distributed_tpu.infer.engine import (
    ContinuousBatchingEngine,
    PagedContinuousBatchingEngine,
)
from llm_fine_tune_distributed_tpu.infer.errors import (
    CircuitOpenError,
    DrainingError,
    QueueDeadlineError,
    QueueOverflowError,
    RetryableEngineError,
    ServingError,
    error_payload,
    is_retryable_failure,
)
from llm_fine_tune_distributed_tpu.infer.supervisor import (
    EngineSupervisor,
    FaultInjector,
)
from llm_fine_tune_distributed_tpu.models.configs import get_preset
from llm_fine_tune_distributed_tpu.models.transformer import init_params

GREEDY = GenerationConfig(max_new_tokens=6, do_sample=False)


@pytest.fixture(scope="module")
def generator():
    mc = get_preset("tiny")
    params = init_params(jax.random.PRNGKey(0), mc, dtype=jnp.float32)
    return Generator(
        params, mc, ByteChatMLTokenizer(), compute_dtype=jnp.float32, eos_token_ids=[]
    )


def _prompts():
    tok = ByteChatMLTokenizer()
    return [tok.encode(t) for t in ("alpha", "beta bravo", "the quick brown fox")]


def _make(generator, kind, **kw):
    """Fresh engine with test-speed supervision defaults."""
    kw.setdefault("restart_backoff_s", 0.01)
    kw.setdefault("restart_backoff_max_s", 0.02)
    if kind == "paged":
        return PagedContinuousBatchingEngine(
            generator, slots=4, buf_len=96, prompt_bucket=16,
            block_len=16, prefill_chunk=32, **kw,
        )
    return ContinuousBatchingEngine(
        generator, slots=4, buf_len=96, prompt_bucket=16, **kw
    )


# ------------------------------------------------------------------ policy


def test_supervisor_policy_window_and_backoff():
    sup = EngineSupervisor(
        restart_backoff_s=0.5, restart_backoff_max_s=2.0,
        circuit_threshold=3, circuit_window_s=10.0,
    )
    assert sup.record_failure(now=0.0) == "restart"
    assert sup.backoff_delay() == 0.5
    assert sup.record_failure(now=1.0) == "restart"
    assert sup.backoff_delay() == 1.0  # doubled
    sup.restarted()
    assert sup.generation == 1
    # the first two failures age OUT of the 10s window: count resets to 1
    assert sup.record_failure(now=11.5) == "restart"
    assert sup.failure_count == 1
    # three failures INSIDE the window trip the breaker
    assert sup.record_failure(now=12.0) == "restart"
    assert sup.record_failure(now=12.5) == "open"
    assert sup.circuit_open
    # backoff is capped
    assert sup.backoff_delay() == 2.0


def test_fault_injector_self_disarms():
    fi = FaultInjector()
    fi.fail_decode_next(2)
    fi.fail_decode_at(7)
    for step in (1, 2):
        with pytest.raises(Exception):
            fi.maybe_fail_decode(step)
    fi.maybe_fail_decode(3)  # healed
    with pytest.raises(Exception):
        fi.maybe_fail_decode(7)  # absolute-index arm
    fi.maybe_fail_decode(7)
    fi.maybe_fail_prefill()  # inert unless armed
    fi.fail_prefill_next(1)
    with pytest.raises(Exception):
        fi.maybe_fail_prefill()
    fi.maybe_fail_prefill()


def test_error_taxonomy_statuses_and_payloads():
    assert error_payload(QueueOverflowError("full", retry_after_s=3.0))[0] == 429
    assert error_payload(RetryableEngineError("x"))[0] == 503
    assert error_payload(CircuitOpenError("x"))[0] == 503
    assert error_payload(DrainingError("x"))[0] == 503
    status, payload, retry = error_payload(
        QueueOverflowError("full", retry_after_s=3.0)
    )
    assert payload["error"]["kind"] == "queue_overflow"
    assert payload["error"]["retryable"] is True
    assert retry == 3.0
    # generic exceptions: retryable unless on the fatal allowlist
    assert is_retryable_failure(RuntimeError("transient"))
    assert not is_retryable_failure(MemoryError("oom"))
    assert not is_retryable_failure(NotImplementedError("no kernel"))


# ------------------------------------------------------- crash -> recover


@pytest.mark.parametrize("kind", ["continuous", "paged"])
def test_decode_crash_recovers_bit_identical(generator, kind):
    """The acceptance gate: injected retryable decode failure -> every
    in-flight waiter resolves with RetryableEngineError (none hang), the
    engine restarts in-process, and the next greedy request reproduces
    solo generate_ids bit-for-bit."""
    prompts = _prompts()
    solo = [generator.generate_ids(p, GREEDY) for p in prompts]
    engine = _make(generator, kind)
    # warm: prove the engine decodes correctly before the chaos
    assert engine.submit(prompts[0], GREEDY, timeout=240) == solo[0]

    engine.faults.fail_decode_next(1)
    outcomes = [None] * len(prompts)

    def ask(i):
        try:
            outcomes[i] = ("ok", engine.submit(prompts[i], GREEDY, timeout=60))
        except BaseException as e:  # noqa: BLE001 - recording outcome
            outcomes[i] = ("err", e)

    threads = [threading.Thread(target=ask, args=(i,)) for i in range(len(prompts))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert all(not t.is_alive() for t in threads), "a waiter hung"
    # at least one request rode the failed generation and got the retryable
    # error; anything that reports ok must still be bit-exact
    errs = [o[1] for o in outcomes if o[0] == "err"]
    assert errs, outcomes
    assert all(isinstance(e, RetryableEngineError) for e in errs)
    assert all(e.retry_after_s > 0 for e in errs)

    # recovered: same prompts, bit-identical to solo decode
    after = [engine.submit(p, GREEDY, timeout=240) for p in prompts]
    assert after == solo
    snap = engine.stats_snapshot()
    assert snap["engine_restarts"] >= 1
    assert snap["engine_generation"] >= 1
    assert snap["circuit_state"] == "closed"
    assert engine.healthy


@pytest.mark.parametrize("kind", ["continuous", "paged"])
def test_crash_during_speculation_recovers_bit_identical(generator, kind):
    """PR 3 recovery semantics are unchanged by speculation: a decode crash
    on a speculative tick fails the in-flight waiter retryable, the rebuilt
    engine (fresh target AND draft state) reproduces solo speculative decode
    bit-for-bit, and the jitted spec programs survive on the Generator."""
    tok = ByteChatMLTokenizer()
    rep = tok.encode("water water water water water")  # drafting engages
    spec = GenerationConfig(
        max_new_tokens=8, do_sample=False, speculative_lookup=4
    )
    solo = generator.generate_ids(rep, spec)
    engine = _make(generator, kind, speculative_k=4)
    warm = engine.submit_full(rep, spec, timeout=240)
    assert warm.result == solo  # warm: speculation correct before the chaos
    assert warm.draft_tokens_proposed > 0  # the crash hits a REAL spec tick

    engine.faults.fail_decode_next(1)
    with pytest.raises(RetryableEngineError):
        engine.submit(rep, spec, timeout=60)

    after = engine.submit_full(rep, spec, timeout=240)
    assert after.result == solo
    assert after.draft_tokens_proposed > 0
    snap = engine.stats_snapshot()
    assert snap["engine_restarts"] >= 1
    assert engine.healthy


@pytest.mark.parametrize("kind", ["continuous", "paged"])
def test_prefill_crash_recovers(generator, kind):
    """A device failure during prefill takes the same supervision path. On
    the dense engine the not-yet-committed request is requeued and retried
    transparently (the waiter never sees the blip)."""
    prompts = _prompts()
    solo = generator.generate_ids(prompts[1], GREEDY)
    engine = _make(generator, kind)
    assert engine.submit(prompts[0], GREEDY, timeout=240) is not None  # warm
    engine.faults.fail_prefill_next(1)
    if kind == "continuous":
        # nothing host-side is committed before the dense prefill call, so
        # the request retries against the rebuilt state transparently
        assert engine.submit(prompts[1], GREEDY, timeout=60) == solo
    else:
        # paged prefill runs AFTER blocks are mapped: the in-flight request
        # fails retryable, but the engine heals for the next one
        try:
            engine.submit(prompts[1], GREEDY, timeout=60)
        except RetryableEngineError:
            pass
        assert engine.submit(prompts[1], GREEDY, timeout=240) == solo
    assert engine.stats_snapshot()["engine_restarts"] >= 1
    assert engine.healthy


def test_circuit_opens_after_repeated_failures(generator):
    """Failures beyond the threshold stop the restart loop: the engine goes
    terminally unhealthy, in-flight work resolves with CircuitOpenError,
    and later submits are rejected at admission."""
    prompts = _prompts()
    engine = _make(generator, "continuous", circuit_threshold=2,
                   circuit_window_s=60.0)
    assert engine.submit(prompts[0], GREEDY, timeout=240) is not None  # warm
    engine.faults.fail_decode_next(10)  # keeps failing across restarts

    with pytest.raises(RetryableEngineError):
        engine.submit(prompts[0], GREEDY, timeout=60)  # failure 1: restart
    with pytest.raises((RetryableEngineError, CircuitOpenError)):
        engine.submit(prompts[1], GREEDY, timeout=60)  # failure 2: open

    deadline = time.monotonic() + 10
    while engine.healthy and time.monotonic() < deadline:
        try:
            engine.submit(prompts[2], GREEDY, timeout=10)
        except ServingError:
            pass
    assert not engine.healthy
    assert engine.circuit_state == "open"
    assert isinstance(engine.terminal_error, CircuitOpenError)
    with pytest.raises(CircuitOpenError):
        engine.submit(prompts[0], GREEDY, timeout=10)  # shed at admission
    snap = engine.stats_snapshot()
    assert snap["circuit_state"] == "open"


# ------------------------------------------------------------- admission


def test_queue_overflow_sheds_429_with_finite_retry_after(generator):
    prompts = _prompts()
    engine = ContinuousBatchingEngine(
        generator, slots=1, buf_len=96, prompt_bucket=16, max_queue_depth=1,
    )
    long_cfg = GenerationConfig(max_new_tokens=48, do_sample=False)
    occupier = threading.Thread(
        target=lambda: engine.submit(prompts[0], long_cfg, timeout=240)
    )
    occupier.start()
    time.sleep(0.1)  # occupant takes the only slot
    waiter = threading.Thread(
        target=lambda: engine.submit(prompts[1], long_cfg, timeout=240)
    )
    waiter.start()
    time.sleep(0.1)  # waiter fills the depth-1 queue
    with pytest.raises(QueueOverflowError) as exc:
        engine.submit(prompts[2], GREEDY, timeout=30)
    assert exc.value.status == 429
    assert exc.value.retry_after_s is not None
    assert 0.0 < exc.value.retry_after_s < 600.0 + 1e-9
    occupier.join(timeout=240)
    waiter.join(timeout=240)
    assert engine.stats_snapshot()["requests_shed_overflow"] == 1


def test_queue_deadline_sheds_before_prefill(generator):
    prompts = _prompts()
    # buf_len=112 is unique to this test: the occupier's jits compile fresh
    # INSIDE its admission, so the waiter below reliably outlives its
    # deadline while still queued (same trick as the abandonment test in
    # tests/test_engine.py)
    engine = ContinuousBatchingEngine(
        generator, slots=1, buf_len=112, prompt_bucket=16, queue_deadline_s=0.3,
    )
    long_cfg = GenerationConfig(max_new_tokens=64, do_sample=False)
    occupier = threading.Thread(
        target=lambda: engine.submit(prompts[0], long_cfg, timeout=240)
    )
    occupier.start()
    # wait for the occupier to actually be ADMITTED (not a fixed sleep: under
    # full-suite load a slow pickup would shed the occupier on its own
    # deadline and hand the waiter the free slot); its fresh compile + 64
    # greedy tokens then hold the slot far past the waiter's 0.3s deadline
    deadline = time.monotonic() + 30
    while (
        engine.stats_snapshot()["requests_admitted"] < 1
        and time.monotonic() < deadline
    ):
        time.sleep(0.005)
    with pytest.raises(QueueDeadlineError):
        engine.submit(prompts[1], GREEDY, timeout=240)
    occupier.join(timeout=240)
    snap = engine.stats_snapshot()
    assert snap["requests_shed_deadline"] == 1
    # the shed request was never admitted (no prefill for a gone waiter)
    assert snap["requests_admitted"] == 1


@pytest.mark.parametrize("kind", ["continuous", "paged"])
def test_drain_finishes_in_flight(generator, kind):
    prompts = _prompts()
    solo = generator.generate_ids(
        prompts[0], GenerationConfig(max_new_tokens=24, do_sample=False)
    )
    engine = _make(generator, kind)
    engine.submit(prompts[0], GREEDY, timeout=240)  # warm the jit caches
    result = []
    inflight = threading.Thread(
        target=lambda: result.append(
            engine.submit(
                prompts[0],
                GenerationConfig(max_new_tokens=24, do_sample=False),
                timeout=240,
            )
        )
    )
    inflight.start()
    time.sleep(0.1)
    engine.begin_drain()
    with pytest.raises(DrainingError) as exc:
        engine.submit(prompts[1], GREEDY, timeout=30)
    assert exc.value.retry_after_s is not None
    assert engine.wait_drained(timeout_s=120.0)
    inflight.join(timeout=240)
    assert result == [solo]  # the in-flight request finished, unharmed
    assert engine.draining


def test_no_hung_waiter_under_crash_storm(generator):
    """Many concurrent submits racing an injected failure: every single one
    resolves (result or ServingError) — the no-hung-waiter invariant."""
    prompts = _prompts()
    engine = _make(generator, "continuous")
    engine.submit(prompts[0], GREEDY, timeout=240)  # warm
    engine.faults.fail_decode_next(1)
    outcomes = [None] * 8

    def ask(i):
        try:
            outcomes[i] = ("ok", engine.submit(
                prompts[i % len(prompts)], GREEDY, timeout=90
            ))
        except BaseException as e:  # noqa: BLE001 - recording outcome
            outcomes[i] = ("err", e)

    threads = [threading.Thread(target=ask, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=180)
    assert all(not t.is_alive() for t in threads), "a waiter hung"
    assert all(o is not None for o in outcomes)
    for tag, val in outcomes:
        if tag == "err":
            assert isinstance(val, ServingError), val
        else:
            assert isinstance(val, list)
    with engine._plock:
        assert engine._pending == 0  # ledger balanced: one settle per submit


# -------------------------------------------------------------- watchdog


class _RecordingWatchdog:
    """StepWatchdog-shaped probe: counts pokes/pauses instead of aborting."""

    def __init__(self):
        self.pokes = 0
        self.pauses = 0
        self.stopped = False

    def poke(self, step):
        self.pokes += 1

    def pause(self):
        self.pauses += 1

    def stop(self):
        self.stopped = True


def test_decode_worker_pokes_watchdog(generator):
    wd = _RecordingWatchdog()
    engine = ContinuousBatchingEngine(
        generator, slots=2, buf_len=96, prompt_bucket=16, watchdog=wd,
    )
    engine.submit(_prompts()[0], GREEDY, timeout=240)
    # one poke per prefill + one per decode sync; >= max_new_tokens total
    assert wd.pokes >= GREEDY.max_new_tokens
    time.sleep(0.2)  # worker goes idle -> watchdog paused, not poked
    assert wd.pauses >= 1


# ------------------------------------------------------ multi-tenant recovery


def _mk_tenant_adapter(base_params, outdir, seed):
    """PEFT adapter dir with a non-zero, seed-distinct B so each tenant's
    delta is non-trivial and distinguishable."""
    from llm_fine_tune_distributed_tpu.config import TrainConfig
    from llm_fine_tune_distributed_tpu.parallel.lora import (
        add_lora_params,
        save_lora_adapter,
    )

    params = add_lora_params(
        base_params, jax.random.PRNGKey(seed), rank=4, alpha=8.0
    )

    def bump(node):
        if isinstance(node, dict):
            if "lora_b" in node:
                node = dict(node)
                node["lora_b"] = jnp.ones_like(node["lora_b"]) * (0.01 * seed)
                return node
            return {k: bump(v) for k, v in node.items()}
        return node

    save_lora_adapter(
        bump(params), outdir,
        TrainConfig(freeze_strategy="lora", lora_rank=4, lora_alpha=8.0),
    )


@pytest.mark.parametrize("kind", ["continuous", "paged"])
def test_multitenant_crash_restores_residents_bit_identical(
    generator, kind, tmp_path
):
    """A crash mid-multi-tenant decode keeps PR-3 recovery semantics AND
    the adapter pool: in-flight waiters fail retryable, the supervised
    restart restores the RESIDENT adapter set (``_startup`` ->
    ``registry.rebuild()``), and post-recovery greedy decode per tenant is
    bit-identical to that tenant's adapter merged into the weights solo."""
    from llm_fine_tune_distributed_tpu.infer.adapters import AdapterRegistry
    from llm_fine_tune_distributed_tpu.parallel.lora import (
        load_lora_adapter,
        merge_lora,
    )

    base = generator.params
    tok = ByteChatMLTokenizer()
    for name, seed in (("t1", 1), ("t2", 2)):
        _mk_tenant_adapter(base, str(tmp_path / name), seed)
    reg = AdapterRegistry(base, str(tmp_path), max_adapters=4)
    engine = _make(generator, kind, adapters=reg)
    prompts = _prompts()
    merged = {
        name: Generator(
            merge_lora(load_lora_adapter(base, str(tmp_path / name))),
            generator.config, tok,
            compute_dtype=jnp.float32, eos_token_ids=[],
        )
        for name in ("t1", "t2")
    }
    solo = {
        "t1": merged["t1"].generate_ids(prompts[0], GREEDY),
        "t2": merged["t2"].generate_ids(prompts[1], GREEDY),
    }
    # warm both tenants: adapted decode is correct before the chaos
    assert engine.submit(prompts[0], GREEDY, timeout=240, adapter="t1") == solo["t1"]
    assert engine.submit(prompts[1], GREEDY, timeout=240, adapter="t2") == solo["t2"]
    assert sorted(reg.resident()) == ["t1", "t2"]

    engine.faults.fail_decode_next(1)
    outcomes = [None, None]

    def ask(i, name):
        try:
            outcomes[i] = (
                "ok", engine.submit(prompts[i], GREEDY, timeout=60, adapter=name)
            )
        except BaseException as e:  # noqa: BLE001 - recording outcome
            outcomes[i] = ("err", e)

    threads = [
        threading.Thread(target=ask, args=(0, "t1")),
        threading.Thread(target=ask, args=(1, "t2")),
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert all(not t.is_alive() for t in threads), "a waiter hung"
    errs = [o[1] for o in outcomes if o[0] == "err"]
    assert errs, outcomes
    assert all(isinstance(e, RetryableEngineError) for e in errs)
    # every crashed request still released its pin (the single-settle path)
    assert reg.refcount("t1") == 0 and reg.refcount("t2") == 0
    # the resident set SURVIVED the restart (rebuild() in _startup)
    assert sorted(reg.resident()) == ["t1", "t2"]

    # post-recovery: each tenant is bit-identical to its merged-solo run
    assert engine.submit(prompts[0], GREEDY, timeout=240, adapter="t1") == solo["t1"]
    assert engine.submit(prompts[1], GREEDY, timeout=240, adapter="t2") == solo["t2"]
    snap = engine.stats_snapshot()
    assert snap["engine_restarts"] >= 1
    assert snap["adapters_resident"] == 2
    assert snap["per_tenant"]["t1"]["queue_depth"] == 0
    assert engine.healthy
