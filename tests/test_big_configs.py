"""Shape-level tracing of the BASELINE.json big-model configs.

Materializing Llama-3-8B/70B on CPU is impossible, but ``jax.eval_shape``
traces the FULL training step — forward, remat, chunked loss, backward,
optimizer — through abstract arrays, proving the model definitions, sharding
rules, freezing policies, and step builders are consistent at real scale
(dims, dtypes, param counts) without allocating anything.

Covers: config #3 (Llama-3-8B SFT, fsdp mesh), config #5 (Llama-3-70B QLoRA,
fsdp x tensor mesh), and Mistral-7B DPO (config #4) at the abstract level.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from llm_fine_tune_distributed_tpu.config import MeshConfig, TrainConfig
from llm_fine_tune_distributed_tpu.models.configs import get_preset
from llm_fine_tune_distributed_tpu.models.transformer import init_params
from llm_fine_tune_distributed_tpu.parallel.freeze import trainable_mask
from llm_fine_tune_distributed_tpu.parallel.optimizer import build_optimizer
from llm_fine_tune_distributed_tpu.parallel.sharding import param_spec
from llm_fine_tune_distributed_tpu.train.state import TrainState
from llm_fine_tune_distributed_tpu.train.step import build_train_step
from llm_fine_tune_distributed_tpu.utils.tree import split_by_mask


def _abstract_params(model_config, dtype=jnp.float32):
    """ShapeDtypeStruct pytree with the real init structure (via eval_shape —
    no memory is allocated for the 8B/70B weights)."""
    return jax.eval_shape(
        lambda: init_params(jax.random.PRNGKey(0), model_config, dtype=dtype)
    )


def _abstract_step_metrics(model_config, train_config, batch_size=2, accum=2):
    params = _abstract_params(model_config)
    mask = trainable_mask(params, model_config, train_config)
    trainable, frozen = split_by_mask(params, mask)
    if train_config.freeze_strategy == "qlora":
        # abstract analog of trainer QLoRA prep: adapters on, base quantized
        from llm_fine_tune_distributed_tpu.parallel.lora import add_lora_from_config

        params = jax.eval_shape(
            lambda: add_lora_from_config(
                jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), params),
                jax.random.PRNGKey(0),
                train_config,
            )
        )
        mask = trainable_mask(params, model_config, train_config)
        trainable, frozen = split_by_mask(params, mask)
        from llm_fine_tune_distributed_tpu.parallel.qlora import (
            quantize_frozen_abstract,
        )

        frozen = quantize_frozen_abstract(
            {k: jax.ShapeDtypeStruct(v.shape, v.dtype) for k, v in frozen.items()},
            train_config.quant_block_size,
            train_config.quant_double_quant,
        )
    optimizer = build_optimizer(train_config, None, total_steps=10, data_parallel_size=1)
    opt_state = jax.eval_shape(optimizer.init, trainable)
    state = TrainState(
        step=jax.ShapeDtypeStruct((), jnp.int32),
        trainable=trainable,
        frozen=frozen,
        opt_state=opt_state,
    )
    seq = train_config.max_seq_length
    batch = {
        "input_ids": jax.ShapeDtypeStruct((accum, batch_size, seq), jnp.int32),
        "loss_mask": jax.ShapeDtypeStruct((accum, batch_size, seq), jnp.float32),
        "attention_mask": jax.ShapeDtypeStruct((accum, batch_size, seq), jnp.int32),
    }
    step = build_train_step(model_config, train_config, optimizer)
    new_state, metrics = jax.eval_shape(step, state, batch)
    return state, new_state, metrics


def test_llama3_8b_fsdp_step_traces():
    mc = get_preset("llama3_8b")
    assert mc.num_params == pytest.approx(8.03e9, rel=0.01)
    tc = TrainConfig(
        model_preset="llama3_8b",
        remat_policy="full",  # memory-limited recipe: minimum-HBM remat
        max_seq_length=1024,
        gradient_accumulation_steps=2,
        loss_chunk_size=512,
        attention_impl="xla",
        mesh=MeshConfig(data=1, fsdp=8, tensor=1, seq=1),
    )
    state, new_state, metrics = _abstract_step_metrics(mc, tc)
    assert metrics["loss"].shape == ()
    assert jax.tree.structure(new_state.trainable) == jax.tree.structure(state.trainable)


@pytest.mark.slow
def test_llama3_70b_qlora_step_traces():
    mc = get_preset("llama3_70b")
    assert mc.num_params == pytest.approx(70.55e9, rel=0.01)
    tc = TrainConfig(
        model_preset="llama3_70b",
        remat_policy="full",  # memory-limited recipe: minimum-HBM remat
        max_seq_length=1024,
        gradient_accumulation_steps=2,
        loss_chunk_size=512,
        attention_impl="xla",
        freeze_strategy="qlora",
        lora_rank=16,
        quant_matmul_impl="xla",
        mesh=MeshConfig(data=1, fsdp=16, tensor=8, seq=1),
    )
    state, new_state, metrics = _abstract_step_metrics(mc, tc)
    assert metrics["loss"].shape == ()
    # only adapters are trainable at 70B
    assert all(k.endswith(("lora_a", "lora_b")) for k in state.trainable)
    # quantized base: packed codes are int32 at 1/8 the rows
    nf4 = [k for k in state.frozen if k.endswith("kernel_nf4")]
    assert len(nf4) == 7 * 80  # 7 projections x 80 layers
    k0 = "model/layers/0/self_attn/q_proj/kernel_nf4"
    assert state.frozen[k0].shape == (8192 // 8, 8192)
    assert state.frozen[k0].dtype == jnp.int32


@pytest.mark.slow
def test_mistral_7b_dpo_step_traces():
    from llm_fine_tune_distributed_tpu.train.dpo import build_dpo_train_step

    mc = get_preset("mistral_7b")
    assert mc.num_params == pytest.approx(7.24e9, rel=0.01)
    tc = TrainConfig(
        model_preset="mistral_7b",
        objective="dpo",
        remat_policy="full",  # memory-limited recipe: minimum-HBM remat
        max_seq_length=512,
        gradient_accumulation_steps=2,
        loss_chunk_size=256,
        attention_impl="xla",
        freeze_strategy="lora",
        mesh=MeshConfig(data=1, fsdp=8, tensor=1, seq=1),
    )
    params = _abstract_params(mc)
    from llm_fine_tune_distributed_tpu.parallel.lora import add_lora_from_config

    params = jax.eval_shape(
        lambda: add_lora_from_config(
            jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), params),
            jax.random.PRNGKey(0),
            tc,
        )
    )
    mask = trainable_mask(params, mc, tc)
    trainable, frozen = split_by_mask(params, mask)
    optimizer = build_optimizer(tc, None, total_steps=10, data_parallel_size=1)
    opt_state = jax.eval_shape(optimizer.init, trainable)
    state = TrainState(
        step=jax.ShapeDtypeStruct((), jnp.int32),
        trainable=trainable,
        frozen=frozen,
        opt_state=opt_state,
    )
    ref = {k: jax.ShapeDtypeStruct(v.shape, jnp.bfloat16) for k, v in trainable.items()}
    b, s, accum = 2, tc.max_seq_length, tc.gradient_accumulation_steps
    batch = {}
    for side in ("chosen", "rejected"):
        batch[f"{side}_input_ids"] = jax.ShapeDtypeStruct((accum, b, s), jnp.int32)
        batch[f"{side}_loss_mask"] = jax.ShapeDtypeStruct((accum, b, s), jnp.float32)
        batch[f"{side}_attention_mask"] = jax.ShapeDtypeStruct((accum, b, s), jnp.float32)
    step = build_dpo_train_step(mc, tc, optimizer)
    new_state, metrics = jax.eval_shape(step, state, ref, batch)
    assert metrics["loss"].shape == ()
    assert metrics["rewards_accuracy"].shape == ()


def test_abstract_quantize_matches_real():
    """quantize_frozen_abstract must mirror quantize_frozen exactly (the
    70B trace relies on it)."""
    from llm_fine_tune_distributed_tpu.parallel.qlora import (
        quantize_frozen,
        quantize_frozen_abstract,
    )

    rng = np.random.RandomState(0)
    frozen = {
        "model/layers/0/self_attn/q_proj/kernel": rng.randn(128, 64).astype(np.float32),
        "model/layers/0/mlp/down_proj/kernel": rng.randn(192, 64).astype(np.float32),
        "model/layers/0/input_layernorm/weight": np.ones((64,), np.float32),
    }
    for dq in (False, True):
        real = quantize_frozen(frozen, 64, dq)
        abstract = quantize_frozen_abstract(
            {k: jax.ShapeDtypeStruct(v.shape, jnp.float32) for k, v in frozen.items()},
            64,
            dq,
        )
        assert set(real) == set(abstract)
        for k in real:
            assert tuple(np.asarray(real[k]).shape) == tuple(abstract[k].shape), k
            assert np.asarray(real[k]).dtype == abstract[k].dtype, k


def test_sharding_rules_cover_all_big_model_params():
    """Every 2-D param of every preset gets a non-degenerate PartitionSpec
    from the path rules (no silent replication of an 8 GB matrix)."""
    for preset in ("llama3_8b", "llama3_70b", "mistral_7b", "smollm3_3b"):
        mc = get_preset(preset)
        params = _abstract_params(mc)
        from llm_fine_tune_distributed_tpu.utils.tree import flatten_dict

        for path, leaf in flatten_dict(params).items():
            if getattr(leaf, "ndim", 0) == 2 and leaf.shape[0] * leaf.shape[1] > 1e6:
                spec = param_spec(path, 2)
                assert any(ax is not None for ax in spec), (
                    f"{preset}: large matrix {path} has fully-replicated spec"
                )


@pytest.mark.parametrize("impl", ["ring", "ulysses"])
@pytest.mark.slow
def test_smollm3_long_context_seq_parallel_traces(impl, eight_devices):
    """Long-context capability at flagship scale: the FULL train step traces
    at seq 32768 with the sequence dim sharded 4-ways (ring / ulysses).
    eval_shape proves shape/dtype consistency of the seq-parallel paths
    through remat, chunked loss, backward, and optimizer without allocating
    the 3B model (SURVEY.md §5.7 — the capability the reference lacks)."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mc = get_preset("smollm3_3b")
    tc = TrainConfig(
        model_preset="smollm3_3b",
        max_seq_length=32768,
        per_device_batch_size=2,
        gradient_accumulation_steps=2,
        loss_chunk_size=1024,
        attention_impl=impl,
        mesh=MeshConfig(data=1, fsdp=2, tensor=1, seq=4),
    )
    mesh = Mesh(
        np.array(eight_devices).reshape(1, 2, 1, 4), ("data", "fsdp", "tensor", "seq")
    )
    act = NamedSharding(mesh, P(("data", "fsdp"), "seq", None))

    params = _abstract_params(mc)
    mask = trainable_mask(params, mc, tc)
    trainable, frozen = split_by_mask(params, mask)
    optimizer = build_optimizer(tc, None, total_steps=10, data_parallel_size=2)
    opt_state = jax.eval_shape(optimizer.init, trainable)
    state = TrainState(
        step=jax.ShapeDtypeStruct((), jnp.int32),
        trainable=trainable,
        frozen=frozen,
        opt_state=opt_state,
    )
    seq, accum, b = tc.max_seq_length, 2, 2
    batch = {
        "input_ids": jax.ShapeDtypeStruct((accum, b, seq), jnp.int32),
        "loss_mask": jax.ShapeDtypeStruct((accum, b, seq), jnp.float32),
        "attention_mask": jax.ShapeDtypeStruct((accum, b, seq), jnp.int32),
    }
    step = build_train_step(mc, tc, optimizer, activation_sharding=act)
    from llm_fine_tune_distributed_tpu.parallel.diagnostics import assert_seq_parallel

    with assert_seq_parallel(impl), mesh:
        new_state, metrics = jax.eval_shape(step, state, batch)
    assert metrics["loss"].shape == ()
    assert jax.tree.structure(new_state.trainable) == jax.tree.structure(state.trainable)
