"""Golden-question harness (infer/golden.py): the programmatic form of the
reference's manual 5-question comparison (reference README.md:15-21)."""

import jax
import jax.numpy as jnp
import pytest

from llm_fine_tune_distributed_tpu.data.tokenizer import ByteChatMLTokenizer
from llm_fine_tune_distributed_tpu.infer import Generator
from llm_fine_tune_distributed_tpu.infer.golden import (
    GOLDEN_QUESTIONS,
    compare_golden,
    run_golden_eval,
    save_report,
)
from llm_fine_tune_distributed_tpu.models.configs import get_preset
from llm_fine_tune_distributed_tpu.models.transformer import init_params


def _generator(seed):
    mc = get_preset("tiny")
    params = init_params(jax.random.PRNGKey(seed), mc, dtype=jnp.float32)
    return Generator(params, mc, ByteChatMLTokenizer(), compute_dtype=jnp.float32)


def test_golden_questions_are_the_reference_five():
    # the exact "Good Questions for Testing" list, reference README.md:15-21
    assert GOLDEN_QUESTIONS == [
        "How many cups in a gallon?",
        "How do I treat a nosebleed?",
        "What are the advantages of a mirrorless DSLR camera?",
        "What is the easiest loop knot to tie?",
        "I have a whistle, what is the right way to signal for help?",
    ]


def test_run_and_compare(tmp_path):
    tuned = run_golden_eval(
        _generator(0), questions=GOLDEN_QUESTIONS[:2], max_new_tokens=6
    )
    assert len(tuned) == 2
    assert all(a.n_chars == len(a.answer) for a in tuned)
    assert all(a.question in GOLDEN_QUESTIONS for a in tuned)

    report = compare_golden(tuned, tuned)
    assert report["n_questions"] == 2
    save_report(report, str(tmp_path / "r.json"))
    assert (tmp_path / "r.json").exists()


def test_compare_flags_divergence():
    from llm_fine_tune_distributed_tpu.infer.golden import GoldenAnswer

    a = [GoldenAnswer("q1", "tuned answer", 2, 12), GoldenAnswer("q2", "same", 1, 4)]
    b = [GoldenAnswer("q1", "base answer", 2, 11), GoldenAnswer("q2", "same", 1, 4)]
    report = compare_golden(a, b)
    assert report["n_answers_differ"] == 1
    assert report["rows"][0]["answers_differ"] is True
    assert report["rows"][1]["answers_differ"] is False


@pytest.mark.slow
def test_same_model_answers_identical():
    a = run_golden_eval(_generator(0), questions=GOLDEN_QUESTIONS[:1], max_new_tokens=6)
    b = run_golden_eval(_generator(0), questions=GOLDEN_QUESTIONS[:1], max_new_tokens=6)
    report = compare_golden(a, b)
    assert report["n_answers_differ"] == 0


@pytest.mark.slow
def test_cli_tuned_only_writes_report(tmp_path):
    """eval_golden.py single-model mode archives the answers as JSON (not
    just stdout) so run reports can attach the eval artifact."""
    import json
    import os
    import sys

    from llm_fine_tune_distributed_tpu.models.hf_io import save_hf_checkpoint

    mc = get_preset("tiny")
    params = init_params(jax.random.PRNGKey(0), mc, dtype=jnp.float32)
    mdir = tmp_path / "model"
    save_hf_checkpoint(params, str(mdir))
    # config.json + tokenizer marker so load_model_dir can rebuild
    with open(mdir / "config.json", "w") as f:
        json.dump({
            "model_type": mc.name, "vocab_size": mc.vocab_size,
            "hidden_size": mc.hidden_size,
            "intermediate_size": mc.intermediate_size,
            "num_hidden_layers": mc.num_layers,
            "num_attention_heads": mc.num_heads,
            "num_key_value_heads": mc.num_kv_heads,
            "rope_theta": mc.rope_theta,
            "max_position_embeddings": mc.max_position_embeddings,
            "rms_norm_eps": mc.rms_norm_eps,
            "tie_word_embeddings": mc.tie_word_embeddings,
            "no_rope_layers": list(mc.no_rope_layers),
        }, f)
    ByteChatMLTokenizer().save_pretrained(str(mdir))

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, repo_root)
    import eval_golden

    report = tmp_path / "golden.json"
    rc = eval_golden.main([
        "--tuned-dir", str(mdir), "--max-new-tokens", "4",
        "--report", str(report),
    ])
    assert rc == 0
    data = json.loads(report.read_text())
    assert data["mode"] == "tuned-only"
    assert len(data["answers"]) == 5
    assert all(a["question"] for a in data["answers"])
