"""Golden-question harness (infer/golden.py): the programmatic form of the
reference's manual 5-question comparison (reference README.md:15-21)."""

import jax
import jax.numpy as jnp
import pytest

from llm_fine_tune_distributed_tpu.data.tokenizer import ByteChatMLTokenizer
from llm_fine_tune_distributed_tpu.infer import Generator
from llm_fine_tune_distributed_tpu.infer.golden import (
    GOLDEN_QUESTIONS,
    compare_golden,
    run_golden_eval,
    save_report,
)
from llm_fine_tune_distributed_tpu.models.configs import get_preset
from llm_fine_tune_distributed_tpu.models.transformer import init_params


def _generator(seed):
    mc = get_preset("tiny")
    params = init_params(jax.random.PRNGKey(seed), mc, dtype=jnp.float32)
    return Generator(params, mc, ByteChatMLTokenizer(), compute_dtype=jnp.float32)


def test_golden_questions_are_the_reference_five():
    # the exact "Good Questions for Testing" list, reference README.md:15-21
    assert GOLDEN_QUESTIONS == [
        "How many cups in a gallon?",
        "How do I treat a nosebleed?",
        "What are the advantages of a mirrorless DSLR camera?",
        "What is the easiest loop knot to tie?",
        "I have a whistle, what is the right way to signal for help?",
    ]


def test_run_and_compare(tmp_path):
    tuned = run_golden_eval(
        _generator(0), questions=GOLDEN_QUESTIONS[:2], max_new_tokens=6
    )
    assert len(tuned) == 2
    assert all(a.n_chars == len(a.answer) for a in tuned)
    assert all(a.question in GOLDEN_QUESTIONS for a in tuned)

    report = compare_golden(tuned, tuned)
    assert report["n_questions"] == 2
    save_report(report, str(tmp_path / "r.json"))
    assert (tmp_path / "r.json").exists()


def test_compare_flags_divergence():
    from llm_fine_tune_distributed_tpu.infer.golden import GoldenAnswer

    a = [GoldenAnswer("q1", "tuned answer", 2, 12), GoldenAnswer("q2", "same", 1, 4)]
    b = [GoldenAnswer("q1", "base answer", 2, 11), GoldenAnswer("q2", "same", 1, 4)]
    report = compare_golden(a, b)
    assert report["n_answers_differ"] == 1
    assert report["rows"][0]["answers_differ"] is True
    assert report["rows"][1]["answers_differ"] is False


@pytest.mark.slow
def test_same_model_answers_identical():
    a = run_golden_eval(_generator(0), questions=GOLDEN_QUESTIONS[:1], max_new_tokens=6)
    b = run_golden_eval(_generator(0), questions=GOLDEN_QUESTIONS[:1], max_new_tokens=6)
    report = compare_golden(a, b)
    assert report["n_answers_differ"] == 0
