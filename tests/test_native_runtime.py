"""Native C++ runtime: prefetching batch pipeline + heartbeat failure
detector (native/loader.cc, native/heartbeat.cc). The reference gets these
capabilities from torch DataLoader workers and Kubernetes restart policy
(SURVEY.md §2.3, §5.3); here they are first-party and therefore tested."""

import time

import numpy as np
import pytest

from llm_fine_tune_distributed_tpu.runtime import native

pytestmark = pytest.mark.skipif(
    not native.available(), reason=f"native build unavailable: {native.build_error()}"
)


def _arrays(n=64, seq=16, seed=0):
    rng = np.random.RandomState(seed)
    return {
        "input_ids": rng.randint(0, 1000, (n, seq)).astype(np.int32),
        "loss_mask": np.ones((n, seq), np.int32),
        "attention_mask": np.ones((n, seq), np.int32),
    }


def _make(arrays, **kw):
    from llm_fine_tune_distributed_tpu.data.native_loader import NativeBatchLoader

    defaults = dict(per_device_batch_size=2, grad_accum_steps=2, data_parallel_size=2)
    defaults.update(kw)
    return NativeBatchLoader(arrays, **defaults)


def test_shapes_and_steps():
    arrays = _arrays()
    loader = _make(arrays)
    assert loader.steps_per_epoch == 64 // 8
    batches = list(loader.epoch(0))
    assert len(batches) == 8
    for b in batches:
        assert b["input_ids"].shape == (2, 4, 16)  # [accum, bs*dp/hosts, seq]
    loader.close()


def test_epoch_covers_every_sample_once():
    arrays = _arrays()
    loader = _make(arrays)
    seen = []
    for b in loader.epoch(3):
        seen.extend(b["input_ids"].reshape(-1, 16).tolist())
    rows = {tuple(r) for r in seen}
    all_rows = {tuple(r) for r in arrays["input_ids"].tolist()}
    assert rows == all_rows
    loader.close()


def test_deterministic_across_instances():
    arrays = _arrays()
    a, b = _make(arrays, seed=7), _make(arrays, seed=7)
    assert np.array_equal(a.epoch_order(5), b.epoch_order(5))
    ba = [x["input_ids"] for x in a.epoch(2)]
    bb = [x["input_ids"] for x in b.epoch(2)]
    for x, y in zip(ba, bb):
        assert np.array_equal(x, y)
    assert not np.array_equal(a.epoch_order(0), a.epoch_order(1))  # reshuffles
    a.close(); b.close()


def test_host_shards_are_disjoint_and_complete():
    """Two 'hosts' with the same seed see disjoint halves of each global batch
    — the DistributedSampler property (reference
    docs/single-vs-distributed-comparison.md:395-407)."""
    arrays = _arrays()
    h0 = _make(arrays, process_index=0, process_count=2)
    h1 = _make(arrays, process_index=1, process_count=2)
    for b0, b1 in zip(h0.epoch(0), h1.epoch(0)):
        r0 = {tuple(r) for r in b0["input_ids"].reshape(-1, 16).tolist()}
        r1 = {tuple(r) for r in b1["input_ids"].reshape(-1, 16).tolist()}
        assert not (r0 & r1)
        assert len(r0) == len(r1) == 4
    h0.close(); h1.close()


def test_matches_python_loader_unshuffled():
    """With shuffle off the two engines must emit identical batches."""
    from llm_fine_tune_distributed_tpu.data.loader import SFTBatchLoader

    arrays = _arrays()
    kw = dict(
        per_device_batch_size=2, grad_accum_steps=2, data_parallel_size=2,
        shuffle=False,
    )
    nat = _make(arrays, shuffle=False)
    py = SFTBatchLoader(arrays, **kw)
    for bn, bp in zip(nat.epoch(0), py.epoch(0)):
        for k in ("input_ids", "loss_mask", "attention_mask"):
            assert np.array_equal(bn[k], np.asarray(bp[k], np.int32)), k
    nat.close()


def test_packed_keys_ride_native_pipeline():
    """Packed batches (segment_ids/positions + float32 masks) gather through
    the C++ pipeline with exact parity to the Python loader — dtypes
    included (VERDICT r3 #7: no more Python-loader fallback for packing)."""
    from llm_fine_tune_distributed_tpu.data.loader import SFTBatchLoader

    rng = np.random.RandomState(1)
    n, seq = 32, 16
    arrays = {
        "input_ids": rng.randint(0, 1000, (n, seq)).astype(np.int32),
        "loss_mask": rng.randint(0, 2, (n, seq)).astype(np.float32),
        "attention_mask": rng.randint(0, 2, (n, seq)).astype(np.float32),
        "segment_ids": rng.randint(0, 4, (n, seq)).astype(np.int32),
        "positions": rng.randint(0, seq, (n, seq)).astype(np.int32),
        "lengths": np.full((n,), seq, np.int32),  # stripped by both engines
    }
    kw = dict(per_device_batch_size=2, grad_accum_steps=2, data_parallel_size=2)
    nat = _make(arrays, shuffle=False)
    py = SFTBatchLoader(arrays, shuffle=False, **kw)
    n_batches = 0
    for bn, bp in zip(nat.epoch(0), py.epoch(0)):
        assert set(bn) == set(bp) == {
            "input_ids", "loss_mask", "attention_mask", "segment_ids", "positions"
        }
        for k in bn:
            assert bn[k].dtype == bp[k].dtype, k
            assert np.array_equal(bn[k], np.asarray(bp[k])), k
        n_batches += 1
    assert n_batches == nat.steps_per_epoch
    # shuffled epochs still cover every row exactly once
    seen = []
    for b in nat.epoch(1):
        seen.extend(b["input_ids"].reshape(-1, seq).tolist())
    assert {tuple(r) for r in seen} == {tuple(r) for r in arrays["input_ids"].tolist()}
    nat.close()


def test_heartbeat_detects_dead_and_alive():
    from llm_fine_tune_distributed_tpu.runtime.failure import FailureDetector

    # Coordinator (rank 0) + one worker (rank 1) of an expected world of 3:
    # rank 2 never starts and must show up dead.
    coord = FailureDetector(rank=0, world_size=3, port=0, interval_ms=50, timeout_ms=400)
    w1 = FailureDetector(
        rank=1, world_size=3, coordinator_host="127.0.0.1", port=coord.port,
        interval_ms=50, timeout_ms=400,
    )
    try:
        deadline = time.time() + 5.0
        while time.time() < deadline and coord.dead_ranks() != [2]:
            time.sleep(0.05)
        assert coord.dead_ranks() == [2]
        assert coord.rank_age_ms(0) >= 0
        assert coord.rank_age_ms(1) >= 0
        assert coord.rank_age_ms(2) == -1

        # Kill rank 1's beater; it must go dead within the timeout.
        w1.stop()
        deadline = time.time() + 5.0
        while time.time() < deadline and 1 not in coord.dead_ranks():
            time.sleep(0.05)
        assert 1 in coord.dead_ranks()
    finally:
        w1.stop()
        coord.stop()


def test_workers_report_no_dead_ranks():
    from llm_fine_tune_distributed_tpu.runtime.failure import FailureDetector

    coord = FailureDetector(rank=0, world_size=2, port=0, interval_ms=50)
    w = FailureDetector(rank=1, world_size=2, port=coord.port, interval_ms=50)
    try:
        assert w.dead_ranks() == []  # only the coordinator judges liveness
    finally:
        w.stop()
        coord.stop()
