"""Overload control (infer/engine.py): deadline propagation, priority
admission with anti-starvation aging, KV-pressure preemption, and the
staged brownout controller — plus the fleet-level tier shed
(infer/fleet.py, infer/routing.py).

The headline invariants pinned here:

- a preempted-then-resumed GREEDY request emits exactly the tokens of an
  uninterrupted run, on BOTH slot engines, with live sampled neighbors,
  using only already-compiled programs (zero post-warmup recompiles);
- a queued lower tier waits a BOUNDED time under a higher-tier flood
  (aging promotes its ordering tier), and without aging it goes last;
- an expired client deadline cancels the request wherever it is, and the
  504 carries the greedy prefix decoded so far — never garbage tokens.
"""

import threading
import time

import jax
import jax.numpy as jnp
import pytest

from llm_fine_tune_distributed_tpu.data.tokenizer import ByteChatMLTokenizer
from llm_fine_tune_distributed_tpu.infer import GenerationConfig, Generator
from llm_fine_tune_distributed_tpu.infer.engine import (
    ContinuousBatchingEngine,
    PagedContinuousBatchingEngine,
)
from llm_fine_tune_distributed_tpu.infer.errors import (
    BrownoutShedError,
    DeadlineExceededError,
    QueueOverflowError,
)
from llm_fine_tune_distributed_tpu.infer.fleet import EngineFleet
from llm_fine_tune_distributed_tpu.infer.routing import ReplicaView, choose_replica
from llm_fine_tune_distributed_tpu.models.configs import get_preset
from llm_fine_tune_distributed_tpu.models.transformer import init_params
from llm_fine_tune_distributed_tpu.observe.metrics import ServingStats

GREEDY4 = GenerationConfig(max_new_tokens=4, do_sample=False)
SAMPLED = GenerationConfig(max_new_tokens=6, do_sample=True, temperature=1.0)


@pytest.fixture(scope="module")
def generator():
    mc = get_preset("tiny")
    params = init_params(jax.random.PRNGKey(0), mc, dtype=jnp.float32)
    return Generator(
        params, mc, ByteChatMLTokenizer(), compute_dtype=jnp.float32, eos_token_ids=[]
    )


def _enc(text):
    return ByteChatMLTokenizer().encode(text)


def _wait(cond, timeout=120.0, poll=0.005):
    deadline = time.monotonic() + timeout
    while not cond():
        if time.monotonic() > deadline:
            raise AssertionError("condition not reached in time")
        time.sleep(poll)


# ------------------------------------------------------------- retry jitter


def test_retry_after_jitter_deterministic_and_bounded(generator):
    """Retry-After carries a ±20% deterministic jitter over the EWMA
    estimate: same engine state -> same hint sequence (reproducible), but
    consecutive sheds get different hints (no retry lockstep)."""
    engines = [
        ContinuousBatchingEngine(generator, slots=2, buf_len=64, prompt_bucket=16)
        for _ in range(2)
    ]
    seqs = [[e._retry_after() for _ in range(8)] for e in engines]
    assert seqs[0] == seqs[1]  # deterministic in engine state + shed index
    # idle engine: backlog 1 over 2 slots at the 1.0s EWMA seed -> 0.5s
    # base estimate, jittered to [0.4, 0.6] then floored at the 0.5s clamp
    assert all(0.5 <= v <= 0.6 for v in seqs[0])
    assert len(set(seqs[0])) > 1  # the jitter actually decorrelates


def test_priority_validation(generator):
    with pytest.raises(ValueError, match="priority_default"):
        ContinuousBatchingEngine(
            generator, slots=1, buf_len=64, prompt_bucket=16,
            priority_default="bogus",
        )
    eng = ContinuousBatchingEngine(generator, slots=1, buf_len=64, prompt_bucket=16)
    with pytest.raises(ValueError, match="priority"):
        eng.submit(_enc("hi"), GREEDY4, priority="urgent", timeout=240)


# ------------------------------------------------------ deadline propagation


def test_deadline_expired_while_queued_is_504_with_no_tokens(generator):
    """A deadline that expires before prefill cancels the request at
    admission: 504, zero partial tokens, engine unharmed."""
    eng = ContinuousBatchingEngine(generator, slots=2, buf_len=96, prompt_bucket=16)
    prompt = _enc("alpha")
    # a zero budget is expired the moment the worker looks at it — the
    # admission check always wins, no race against a warm prefill cache
    with pytest.raises(DeadlineExceededError) as ei:
        eng.submit(prompt, GREEDY4, deadline_s=0.0, timeout=240)
    e = ei.value
    assert e.status == 504 and not e.retryable
    assert e.tokens == [] and e.to_dict()["tokens_generated"] == 0
    kinds = [ev["kind"] for ev in eng.recorder.events()]
    assert "deadline_cancel" in kinds
    # the engine keeps serving, and correctly
    assert eng.submit(prompt, GREEDY4, timeout=240) == generator.generate_ids(
        prompt, GREEDY4
    )


def test_deadline_mid_decode_returns_greedy_prefix(generator):
    """The tentpole deadline contract: expiry mid-decode cancels at a
    scheduler tick, frees the slot, and the 504 carries the tokens decoded
    so far — which for greedy are an exact PREFIX of the uninterrupted
    run's tokens."""
    eng = ContinuousBatchingEngine(generator, slots=2, buf_len=1024, prompt_bucket=16)
    prompt = _enc("beta bravo")
    long_cfg = GenerationConfig(max_new_tokens=900, do_sample=False)
    solo = generator.generate_ids(prompt, long_cfg)
    eng.submit(prompt, GREEDY4, timeout=240)  # warm the programs first
    before = eng.stats_snapshot()
    with pytest.raises(DeadlineExceededError) as ei:
        eng.submit(prompt, long_cfg, deadline_s=0.25, timeout=240)
    e = ei.value
    assert len(e.tokens) < 900
    assert e.tokens == solo[: len(e.tokens)]
    after = eng.stats_snapshot()
    assert (
        after["requests_shed_deadline_decode"]
        - before["requests_shed_deadline_decode"]
    ) == 1
    # slot + pending ledger freed the same tick: the engine drains clean
    assert eng.wait_drained(30)
    assert eng.submit(prompt, GREEDY4, timeout=240) == generator.generate_ids(
        prompt, GREEDY4
    )


# ------------------------------------------------------- priority admission


def _completion_order(eng, jobs):
    """Submit ``jobs`` = [(priority, prompt)] concurrently (in list order)
    and return each job's completion timestamp."""
    done_t = [None] * len(jobs)
    errs = [None] * len(jobs)

    def run(i, priority, prompt):
        try:
            eng.submit(prompt, GREEDY4, priority=priority, timeout=240)
            done_t[i] = time.monotonic()
        except BaseException as e:  # surfaced by the caller's asserts
            errs[i] = e

    threads = []
    for i, (priority, prompt) in enumerate(jobs):
        t = threading.Thread(target=run, args=(i, priority, prompt))
        t.start()
        threads.append(t)
        time.sleep(0.02)  # deterministic arrival ids
    for t in threads:
        t.join()
    assert errs == [None] * len(jobs), errs
    return done_t


def test_priority_orders_admission_not_fifo(generator):
    """With one slot occupied, three waiters admitted in REVERSE of their
    arrival order because admission sorts by tier, not arrival."""
    eng = ContinuousBatchingEngine(
        generator, slots=1, buf_len=256, prompt_bucket=16, age_promote_s=60.0
    )
    # a LONG occupier: once programs are warm a short one retires before
    # the waiters below have even been submitted, and admission order
    # degenerates to arrival order
    occupier = threading.Thread(
        target=lambda: eng.submit(
            _enc("occupier"),
            GenerationConfig(max_new_tokens=160, do_sample=True, temperature=1.0),
            seed=5, timeout=240,
        )
    )
    occupier.start()
    _wait(lambda: eng.live_slots == 1)
    done_t = _completion_order(
        eng,
        [
            ("best_effort", _enc("last")),
            ("batch", _enc("middle")),
            ("interactive", _enc("first")),
        ],
    )
    occupier.join()
    assert done_t[2] < done_t[1] < done_t[0]


def test_aging_bounds_lower_tier_wait(generator):
    """Anti-starvation: a batch request queued behind an interactive flood
    is promoted while it waits, and completes BEFORE the flood — while
    with aging disabled the same arrival pattern serves it dead last."""
    for age_s, batch_first in ((0.05, True), (0.0, False)):
        eng = ContinuousBatchingEngine(
            generator, slots=1, buf_len=256, prompt_bucket=16,
            age_promote_s=age_s,
        )
        # long occupier for the same reason as above: every waiter must be
        # queued while the slot is still held
        occupier = threading.Thread(
            target=lambda: eng.submit(
                _enc("occupier"),
                GenerationConfig(max_new_tokens=160, do_sample=True, temperature=1.0),
                seed=5, timeout=240,
            )
        )
        occupier.start()
        _wait(lambda: eng.live_slots == 1)
        # batch arrives FIRST, then the interactive flood piles in; the
        # occupier (cold-start compile) runs long past the aging horizon
        done_t = _completion_order(
            eng,
            [("batch", _enc("starved"))]
            + [("interactive", _enc(f"flood {i}")) for i in range(3)],
        )
        occupier.join()
        if batch_first:
            assert done_t[0] < min(done_t[1:]), done_t
        else:
            assert done_t[0] > max(done_t[1:]), done_t


# -------------------------------------------------- KV-pressure preemption


def _preempt_resume(generator, eng, victim_prompt):
    """Shared preempt/resume driver: a sampled occupier holds one slot, a
    best_effort greedy victim streams in the other; once its first tokens
    arrive, an interactive arrival forces the preemption (both slots busy,
    victim is the worst live tier). Returns the victim's full token list
    and the engine's preemption count."""
    victim_cfg = GenerationConfig(max_new_tokens=48, do_sample=False)
    # warm every program + prompt bucket the test will touch (including
    # bucket 128, in case the victim banks enough tokens to spill past 64)
    eng.submit(victim_prompt, victim_cfg, priority="best_effort", timeout=240)
    eng.submit(_enc("interactive warm"), SAMPLED, seed=3, timeout=240)
    eng.submit(_enc("x" * 70), GREEDY4, timeout=240)
    eng.mark_compile_warm()
    recompiles0 = eng.compile_ledger.recompiles_after_warmup

    # 64 keeps the occupier's context inside the block-count bucket the
    # warmup already compiled (paged_step specializes per power-of-two
    # bucket) while still holding its slot for the whole preempt dance
    occupier = threading.Thread(
        target=lambda: eng.submit(
            _enc("long sampled occupier"),
            GenerationConfig(max_new_tokens=64, do_sample=True, temperature=1.0),
            seed=9, timeout=240,
        )
    )
    occupier.start()
    _wait(lambda: eng.live_slots >= 1)
    stream = eng.stream(victim_prompt, victim_cfg, priority="best_effort", timeout=240)
    tokens = [next(stream), next(stream)]  # victim is decoding now

    trigger_result = []
    trigger = threading.Thread(
        target=lambda: trigger_result.append(
            eng.submit(
                _enc("interactive arrival"),
                GenerationConfig(max_new_tokens=8, do_sample=True, temperature=1.0),
                seed=4, timeout=240,
            )
        )
    )
    trigger.start()
    tokens.extend(stream)  # banked tokens were already streamed; only the
    trigger.join()         # resumed suffix arrives after the preemption
    occupier.join()
    assert len(trigger_result) == 1 and len(trigger_result[0]) == 8
    assert eng.compile_ledger.recompiles_after_warmup == recompiles0
    return tokens, eng.stats_snapshot()


def test_preempt_resume_bit_identical_dense(generator):
    """A preempted-then-resumed greedy request on the DENSE engine emits
    exactly the uninterrupted run's tokens, with a live sampled neighbor
    the whole time and zero post-warmup recompiles."""
    eng = ContinuousBatchingEngine(generator, slots=2, buf_len=256, prompt_bucket=64)
    prompt = _enc("preempt me please")
    solo = generator.generate_ids(
        prompt, GenerationConfig(max_new_tokens=48, do_sample=False)
    )
    tokens, snap = _preempt_resume(generator, eng, prompt)
    assert snap["preemptions"] >= 1
    assert tokens == solo
    assert any(ev["kind"] == "preempt" for ev in eng.recorder.events())


def test_preempt_resume_bit_identical_paged_and_banks_blocks(generator):
    """Same invariant on the PAGED engine — and the preemption banks the
    victim's full context blocks into the prefix cache, so the resume
    re-prefills only the unbanked tail (prefix_tokens_reused grows)."""
    eng = PagedContinuousBatchingEngine(
        generator, slots=2, buf_len=256, prompt_bucket=64,
        block_len=16, prefill_chunk=256,
    )
    # >= 2 full 16-token blocks, so the preemption has blocks to bank
    prompt = _enc("a forty-ish token victim prompt for block banking")
    assert len(prompt) >= 32
    solo = generator.generate_ids(
        prompt, GenerationConfig(max_new_tokens=48, do_sample=False)
    )
    tokens, snap = _preempt_resume(generator, eng, prompt)
    assert snap["preemptions"] >= 1
    assert tokens == solo
    # the resume matched banked blocks instead of re-prefilling everything
    assert snap["prefix_tokens_reused"] > 0


# ------------------------------------------------------------------ brownout


def test_brownout_stages_escalate_and_deescalate_with_hysteresis(generator):
    """White-box controller check: pressure drives the stage up through
    the thresholds, and the hysteresis band holds the stage until pressure
    falls clearly below the threshold that raised it. Every transition is
    one flight-recorder event and moves the gauge."""
    eng = ContinuousBatchingEngine(
        generator, slots=2, buf_len=64, prompt_bucket=16,
        brownout_queue_wait_s=1.0,  # pressure == queue-wait EWMA, directly
    )
    # idle worker is parked on the queue; driving the controller from the
    # test thread is the same single-writer discipline the worker has.
    # _update_brownout first decays the EWMA by 0.8 (empty queue), so each
    # target pressure p is injected as p / 0.8.
    stages = []
    for p in (0.80, 0.96, 0.88, 0.80, 0.0):
        eng._queue_wait_ewma = p / 0.8
        eng._update_brownout()
        stages.append(eng.brownout_stage)
    # 0.80 -> stage 1; 0.96 -> straight to 3; 0.88 holds 3 (>= 0.95 - 0.1);
    # 0.80 drops to 2 but holds there (>= 0.85 - 0.1); 0.0 -> healthy
    assert stages == [1, 3, 3, 2, 0]
    trans = [
        (ev["prev"], ev["stage"])
        for ev in eng.recorder.events()
        if ev["kind"] == "brownout"
    ]
    assert trans == [(0, 1), (1, 3), (3, 2), (2, 0)]
    assert eng.stats_snapshot()["brownout_stage"] == 0


def test_stage3_sheds_best_effort_only_and_idle_guard(generator):
    """Stage 3 rejects best_effort at admission with a tier-labelled 429
    while interactive still serves; an IDLE engine never sheds against a
    stale stage (the guard that keeps a best_effort-only client alive
    after a burst drains)."""
    # a microscopic drain budget pins pressure >= stage 3 whenever
    # anything is live, without needing a real overload
    eng = ContinuousBatchingEngine(
        generator, slots=2, buf_len=96, prompt_bucket=16,
        brownout_drain_s=1e-9,
    )
    prompt = _enc("alpha")
    solo = generator.generate_ids(prompt, GREEDY4)
    occupier = threading.Thread(
        target=lambda: eng.submit(
            _enc("occupier"),
            GenerationConfig(max_new_tokens=96, do_sample=True, temperature=1.0),
            seed=5, timeout=240,
        )
    )
    occupier.start()
    _wait(lambda: eng.brownout_stage >= 3)
    with pytest.raises(BrownoutShedError) as ei:
        eng.submit(prompt, GREEDY4, priority="best_effort", timeout=240)
    e = ei.value
    assert e.status == 429 and e.retryable and e.tier == "best_effort"
    assert isinstance(e, QueueOverflowError)  # rides the fleet's reroute
    assert e.retry_after_s is not None and 0.5 <= e.retry_after_s <= 600.0
    assert e.to_dict()["tier"] == "best_effort"
    # interactive traffic rides through the brownout untouched
    assert eng.submit(prompt, GREEDY4, priority="interactive", timeout=240) == solo
    occupier.join()
    snap = eng.stats_snapshot()
    assert snap["requests_shed_by_tier"]["best_effort"] == 1
    assert snap["requests_shed_by_tier"]["interactive"] == 0
    assert any(ev["kind"] == "shed_brownout" for ev in eng.recorder.events())
    # idle guard: a stale stage on a drained engine must NOT shed — the
    # admission passes it through and the worker re-evaluates the stage
    idle = ContinuousBatchingEngine(generator, slots=2, buf_len=96, prompt_bucket=16)
    idle._brownout_stage = 3  # simulate a burst that drained while browned
    assert idle.submit(prompt, GREEDY4, priority="best_effort", timeout=240) == solo


# ------------------------------------------------------------- fleet surface


def test_router_filters_stage3_for_best_effort_only():
    views = [
        ReplicaView(index=0, brownout_stage=3),
        ReplicaView(index=1, brownout_stage=3),
    ]
    assert choose_replica("least-loaded", views, best_effort=True) is None
    assert choose_replica("least-loaded", views, best_effort=False) is not None
    views[1].brownout_stage = 2
    placed = choose_replica("least-loaded", views, best_effort=True)
    assert placed is not None and placed.index == 1


class _FakeReplica:
    """The surface EngineFleet reads, with a settable brownout stage and
    kwarg capture (so the deadline/priority plumbing is observable)."""

    block_len = 0

    def __init__(self, index, stage=0, drain_s=3.0):
        self.index = index
        self.slot_count = 2
        self.healthy = True
        self.draining = False
        self.recovering = False
        self.queue_depth = 0
        self.live_slots = 0
        self.brownout_stage = stage
        self.drain_s = drain_s
        self.circuit_state = "closed"
        self.stats = ServingStats(slots=2)
        self.seen_kwargs = None

    def predicted_drain_s(self):
        return self.drain_s

    def prefix_match_len(self, keys):
        return 0

    def stats_snapshot(self):
        return self.stats.snapshot()

    def submit_full(self, prompt_ids, gen, seed=0, timeout=None, **kwargs):
        self.seen_kwargs = dict(kwargs, timeout=timeout)

        class _R:
            result = list(prompt_ids) + [self.index]

        return _R()


def test_fleet_sheds_best_effort_fleet_wide_when_all_browned_out():
    """Every healthy replica at stage 3 -> best_effort gets ONE fleet-wide
    tier-labelled 429 quoting the soonest predicted drain, without burning
    a per-replica rejection round-trip; other tiers route normally."""
    a, b = _FakeReplica(0, stage=3, drain_s=7.0), _FakeReplica(1, stage=3, drain_s=2.0)
    fleet = EngineFleet([a, b], routing="round-robin")
    with pytest.raises(BrownoutShedError) as ei:
        fleet.submit([1, 2], GREEDY4, priority="best_effort", timeout=5)
    assert ei.value.tier == "best_effort"
    assert ei.value.retry_after_s == 2.0  # soonest drain across the fleet
    assert a.seen_kwargs is None and b.seen_kwargs is None  # never dispatched
    assert fleet.stats_snapshot()["requests_shed_fleet_brownout"] == 1
    # interactive traffic still places onto a browned-out replica
    assert fleet.submit([1, 2], GREEDY4, priority="interactive", timeout=5) in (
        [1, 2, 0], [1, 2, 1],
    )
    # one replica recovering to stage < 3 re-opens best_effort service
    b.brownout_stage = 2
    assert fleet.submit([1, 2], GREEDY4, priority="best_effort", timeout=5) == [
        1, 2, 1,
    ]


def test_fleet_deadline_caps_failover_budget():
    """``deadline_s`` bounds the WHOLE fleet attempt: the dispatch timeout
    shrinks to the deadline plus a fixed grace, and the replica receives the
    remaining budget (so failover hops cannot stack full timeouts past the
    client's SLO). The grace keeps the fleet-side wait a hang backstop: the
    replica's own deadline machinery must win the race at the deadline and
    surface DeadlineExceededError, never a bare stream-starved timeout."""
    from llm_fine_tune_distributed_tpu.infer.fleet import (
        DEADLINE_TIMEOUT_GRACE_S,
    )

    rep = _FakeReplica(0)
    fleet = EngineFleet([rep], routing="round-robin")
    fleet.submit([7], GREEDY4, priority="batch", deadline_s=5.0, timeout=600.0)
    assert rep.seen_kwargs["priority"] == "batch"
    assert 5.0 < rep.seen_kwargs["timeout"] <= 5.0 + DEADLINE_TIMEOUT_GRACE_S
    assert 0 < rep.seen_kwargs["deadline_s"] <= 5.0
