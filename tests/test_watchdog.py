"""Step watchdog (runtime/watchdog.py): the single-process wedged-link
detector. Born from a real failure: a tunneled flagship run wedged
PERMANENTLY between two train steps with a healthy-looking process (r5,
outputs/flagship_r5_run4.log) — nothing restarted it, resume never ran."""

import time

import pytest

from llm_fine_tune_distributed_tpu.runtime.watchdog import StepWatchdog


def test_trips_after_silence_and_rearms():
    wd = StepWatchdog(timeout_s=0.3, action="warn", poll_s=0.05)
    try:
        wd.poke(1)
        time.sleep(0.15)
        assert wd.trips == 0  # still inside the window
        time.sleep(0.6)
        assert wd.trips >= 1  # silence tripped it
        first = wd.trips
        wd.poke(2)
        time.sleep(0.15)
        assert wd.trips == first  # poke re-armed
    finally:
        wd.stop()


def test_pause_suppresses_and_resume_rearms():
    wd = StepWatchdog(timeout_s=0.2, action="warn", poll_s=0.05)
    try:
        wd.pause()
        time.sleep(0.5)
        assert wd.trips == 0  # paused: long silence is fine (slow save/export)
        wd.resume()
        time.sleep(0.1)
        assert wd.trips == 0  # resume re-timestamps
        time.sleep(0.5)
        assert wd.trips >= 1  # armed again
    finally:
        wd.stop()


def test_abort_action_fires_hook_instead_of_exit():
    fired = []
    wd = StepWatchdog(
        timeout_s=0.2, action="abort", poll_s=0.05, on_trip=lambda: fired.append(1)
    )
    try:
        time.sleep(0.6)
        assert fired == [1]  # abort path taken exactly once (thread exits)
    finally:
        wd.stop()


def test_rejects_unknown_action():
    with pytest.raises(ValueError, match="warn|abort"):
        StepWatchdog(timeout_s=1, action="explode")


def test_trainer_runs_clean_with_watchdog(tmp_path):
    """A normal training run with the watchdog armed never false-trips —
    the loop pokes per step and pauses around sync saves."""
    from test_train_e2e import make_config  # noqa: F401
    import json

    import numpy as np

    from llm_fine_tune_distributed_tpu.data.convert import convert_jsonl_to_parquet
    from llm_fine_tune_distributed_tpu.train.trainer import SFTTrainer

    jsonl = tmp_path / "qa.jsonl"
    with open(jsonl, "w") as f:
        for i in range(48):
            f.write(json.dumps({
                "topic": "Knots", "question": f"q {i}?",
                "answer": f"a {i}: pull the loop.",
            }) + "\n")
    convert_jsonl_to_parquet(str(jsonl), str(tmp_path / "qa_dataset.parquet"), verbose=False)
    cfg = make_config(
        tmp_path / "out", tmp_path, "qa_dataset.parquet", epochs=1,
        save_steps=5, use_native_loader=False,
        watchdog_timeout_s=300.0, watchdog_action="abort",
    )
    trainer = SFTTrainer(cfg)
    summary = trainer.train()  # abort would os._exit(42) and fail the test
    assert np.isfinite(summary["final_train_loss"])


def test_start_paused_arms_on_first_poke():
    """Trainer usage: disarmed through resume fast-forward + first compile,
    armed from the first step's poke (r5 review finding)."""
    wd = StepWatchdog(timeout_s=0.2, action="warn", poll_s=0.05, start_paused=True)
    try:
        time.sleep(0.5)
        assert wd.trips == 0  # startup silence never trips
        wd.poke(1)
        time.sleep(0.5)
        assert wd.trips >= 1  # armed after the first poke
    finally:
        wd.stop()
