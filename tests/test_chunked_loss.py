"""Chunked cross-entropy (loss_chunk_size) must match the full-logits loss
bit-for-bit in value and gradients — it is a pure memory-layout optimization
(train/step.py:chunked_ce_sum)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from llm_fine_tune_distributed_tpu.config import TrainConfig
from llm_fine_tune_distributed_tpu.models.configs import get_preset
from llm_fine_tune_distributed_tpu.models.transformer import init_params
from llm_fine_tune_distributed_tpu.parallel.freeze import trainable_mask
from llm_fine_tune_distributed_tpu.train.step import make_loss_fn
from llm_fine_tune_distributed_tpu.utils.tree import split_by_mask


@pytest.mark.parametrize("chunk", [40, 96, 128])  # non-divisor, divisor, > seq
@pytest.mark.slow
def test_chunked_ce_matches_full(chunk):
    mc = get_preset("tiny")
    common = dict(model_preset="tiny", max_seq_length=96, compute_dtype="float32")
    tc_full = TrainConfig(loss_chunk_size=None, **common)
    tc_chunk = TrainConfig(loss_chunk_size=chunk, **common)

    params = init_params(jax.random.PRNGKey(0), mc)
    trainable, frozen = split_by_mask(params, trainable_mask(params, mc, tc_full))
    rng = np.random.RandomState(0)
    batch = {
        "input_ids": rng.randint(0, mc.vocab_size, (2, 96)).astype(np.int32),
        "loss_mask": (rng.rand(2, 96) > 0.3).astype(np.float32),
        "attention_mask": np.ones((2, 96), np.int32),
    }

    loss_full, tok_full = make_loss_fn(mc, tc_full)(trainable, frozen, batch)
    loss_chunk, tok_chunk = make_loss_fn(mc, tc_chunk)(trainable, frozen, batch)
    assert float(tok_full) == float(tok_chunk)
    assert abs(float(loss_full) - float(loss_chunk)) < 1e-5

    g_full = jax.grad(lambda t: make_loss_fn(mc, tc_full)(t, frozen, batch)[0])(trainable)
    g_chunk = jax.grad(lambda t: make_loss_fn(mc, tc_chunk)(t, frozen, batch)[0])(trainable)
    diff = max(
        float(jnp.max(jnp.abs(a - b)))
        for a, b in zip(jax.tree.leaves(g_full), jax.tree.leaves(g_chunk))
    )
    assert diff < 1e-5


@pytest.mark.parametrize("vchunk", [128, 256])  # tiny vocab_size=512
@pytest.mark.slow
def test_vocab_streamed_ce_matches_full(vchunk):
    """Vocab-streamed CE (loss_vocab_chunk, online logsumexp) must match the
    full-logits loss in value AND gradients — a traffic optimization, not a
    semantic change (train/step.vocab_chunked_ce_sum)."""
    mc = get_preset("tiny")
    common = dict(model_preset="tiny", max_seq_length=96, compute_dtype="float32")
    tc_full = TrainConfig(**common)
    tc_v = TrainConfig(loss_vocab_chunk=vchunk, **common)

    params = init_params(jax.random.PRNGKey(0), mc)
    trainable, frozen = split_by_mask(params, trainable_mask(params, mc, tc_full))
    rng = np.random.RandomState(0)
    batch = {
        "input_ids": rng.randint(0, mc.vocab_size, (2, 96)).astype(np.int32),
        "loss_mask": (rng.rand(2, 96) > 0.3).astype(np.float32),
        "attention_mask": np.ones((2, 96), np.int32),
    }

    loss_full, tok_full = make_loss_fn(mc, tc_full)(trainable, frozen, batch)
    loss_v, tok_v = make_loss_fn(mc, tc_v)(trainable, frozen, batch)
    assert float(tok_full) == float(tok_v)
    assert abs(float(loss_full) - float(loss_v)) < 1e-5

    g_full = jax.grad(lambda t: make_loss_fn(mc, tc_full)(t, frozen, batch)[0])(trainable)
    g_v = jax.grad(lambda t: make_loss_fn(mc, tc_v)(t, frozen, batch)[0])(trainable)
    for k in g_full:
        np.testing.assert_allclose(
            np.asarray(g_v[k]), np.asarray(g_full[k]), atol=2e-5, err_msg=k
        )


def test_vocab_chunk_validations():
    mc = get_preset("tiny")
    with pytest.raises(ValueError, match="mutually exclusive"):
        make_loss_fn(mc, TrainConfig(model_preset="tiny", loss_chunk_size=64,
                                     loss_vocab_chunk=128))
    tc_bad = TrainConfig(model_preset="tiny", loss_vocab_chunk=100)  # 512 % 100
    params = init_params(jax.random.PRNGKey(0), mc)
    trainable, frozen = split_by_mask(params, trainable_mask(params, mc, tc_bad))
    batch = {
        "input_ids": np.zeros((1, 16), np.int32),
        "loss_mask": np.ones((1, 16), np.float32),
        "attention_mask": np.ones((1, 16), np.int32),
    }
    with pytest.raises(ValueError, match="not divisible"):
        make_loss_fn(mc, tc_bad)(trainable, frozen, batch)


def test_softcap_streams_through_both_chunking_schemes():
    """Gemma2 final_logit_softcap must produce the SAME loss from the full
    path, seq-chunked CE, and vocab-streamed CE (elementwise cap streams)."""
    mc = get_preset("tiny_gemma2")
    common = dict(
        model_preset="tiny_gemma2", max_seq_length=64, compute_dtype="float32"
    )
    params = init_params(jax.random.PRNGKey(0), mc)
    tc_full = TrainConfig(loss_chunk_size=None, **common)
    trainable, frozen = split_by_mask(params, trainable_mask(params, mc, tc_full))
    rng = np.random.RandomState(0)
    batch = {
        "input_ids": rng.randint(0, mc.vocab_size, (2, 64)).astype(np.int32),
        "loss_mask": np.ones((2, 64), np.float32),
        "attention_mask": np.ones((2, 64), np.int32),
    }
    loss_full, _ = make_loss_fn(mc, tc_full)(trainable, frozen, batch)
    loss_seq, _ = make_loss_fn(mc, TrainConfig(loss_chunk_size=32, **common))(
        trainable, frozen, batch
    )
    loss_voc, _ = make_loss_fn(mc, TrainConfig(loss_vocab_chunk=128, **common))(
        trainable, frozen, batch
    )
    assert abs(float(loss_full) - float(loss_seq)) < 1e-5
    assert abs(float(loss_full) - float(loss_voc)) < 1e-5


@pytest.mark.parametrize("kind", ["full", "seq_chunk", "vocab_chunk"])
def test_dual_mask_eval_metrics_agree_across_ce_paths(kind):
    """The answer-only eval metric (completion_mask in the batch) must come
    out identical from every CE implementation, computed from ONE unembed
    per path (no doubled eval pause — r5 review finding)."""
    mc = get_preset("tiny")
    kw = {"seq_chunk": dict(loss_chunk_size=40),
          "vocab_chunk": dict(loss_vocab_chunk=128)}.get(kind, {})
    tc = TrainConfig(model_preset="tiny", max_seq_length=96,
                     compute_dtype="float32", **kw)
    params = init_params(jax.random.PRNGKey(0), mc)
    trainable, frozen = split_by_mask(params, trainable_mask(params, mc, tc))
    rng = np.random.RandomState(1)
    lm = (rng.rand(2, 96) > 0.2).astype(np.float32)
    cm = lm * (rng.rand(2, 96) > 0.5).astype(np.float32)  # strict subset
    batch = {
        "input_ids": rng.randint(0, mc.vocab_size, (2, 96)).astype(np.int32),
        "loss_mask": lm,
        "attention_mask": np.ones((2, 96), np.int32),
        "completion_mask": cm,
    }
    loss, tokens, ans_ce, ans_tok = make_loss_fn(mc, tc)(trainable, frozen, batch)
    # reference: full-logits path with the completion mask AS the loss mask
    ref_batch = dict(batch, loss_mask=cm)
    ref_batch.pop("completion_mask")
    ref_loss, ref_tok = make_loss_fn(mc, TrainConfig(
        model_preset="tiny", max_seq_length=96, compute_dtype="float32"
    ))(trainable, frozen, ref_batch)
    assert float(ans_tok) == float(ref_tok)
    np.testing.assert_allclose(
        float(ans_ce) / float(ans_tok), float(ref_loss), rtol=2e-5
    )
    # and the primary loss is unaffected by the extra mask
    plain = dict(batch)
    plain.pop("completion_mask")
    loss_plain, _ = make_loss_fn(mc, tc)(trainable, frozen, plain)
    np.testing.assert_allclose(float(loss), float(loss_plain), rtol=1e-6)
