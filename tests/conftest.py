"""Test environment: 8 virtual CPU devices (the JAX-native 'fake backend' the
reference lacks — SURVEY.md §4). Must run before jax initializes."""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["HF_HUB_OFFLINE"] = "1"
os.environ["TRANSFORMERS_OFFLINE"] = "1"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    flags = (flags + " --xla_force_host_platform_device_count=8").strip()
# XLA CPU bug: the AllReducePromotion pass check-fails ("Invalid binary
# instruction opcode copy") cloning the bf16 expert-axis all-reduces the
# pipe x EP backward emits. CPU-only pass, CPU-only workaround — the TPU
# pipeline never runs it.
if "xla_disable_hlo_passes" not in flags:
    flags = (flags + " --xla_disable_hlo_passes=all-reduce-promotion").strip()
os.environ["XLA_FLAGS"] = flags

import jax  # noqa: E402

# In some environments a sitecustomize imports jax at interpreter startup and
# pins JAX_PLATFORMS to a hardware plugin; the config update below overrides
# it even then (the env assignment above only helps fresh interpreters).
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_default_matmul_precision", "highest")

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line("markers", "slow: long-running integration test")


@pytest.fixture(scope="session")
def eight_devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual cpu devices, got {len(devs)}"
    return devs
