"""Fused NF4 Pallas matmul numerics under the Pallas TPU interpreter
(hardware-free CI analog; the same kernel runs compiled on the real chip —
see bench.py / the verify drives)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.experimental.pallas import tpu as pltpu

from llm_fine_tune_distributed_tpu.ops.nf4 import dequantize_nf4, quantize_nf4
from llm_fine_tune_distributed_tpu.ops.nf4_pallas import nf4_matmul_pallas


@pytest.mark.parametrize("double_quant", [False, True])
def test_pallas_matches_xla_dequant(double_quant):
    rng = np.random.RandomState(0)
    K, N, M = 512, 256, 24  # M deliberately not a multiple of 16 (pad path)
    w = rng.randn(K, N).astype(np.float32)
    x = (rng.randn(M, K) * 0.5).astype(np.float32)
    q = {k: jnp.asarray(v) for k, v in quantize_nf4(w, 64, double_quant).items()}

    with pltpu.force_tpu_interpret_mode():
        y = nf4_matmul_pallas(jnp.asarray(x), q, compute_dtype=jnp.float32)

    ref = np.asarray(x).astype(np.float32) @ np.asarray(dequantize_nf4(q, jnp.float32))
    assert y.shape == (M, N)
    # kernel computes in bf16 operands + f32 accumulate
    rel = np.abs(np.asarray(y) - ref).max() / max(np.abs(ref).max(), 1e-6)
    assert rel < 0.03, rel


def test_pallas_batched_leading_dims():
    rng = np.random.RandomState(1)
    K, N = 512, 128
    w = rng.randn(K, N).astype(np.float32)
    x = rng.randn(2, 8, K).astype(np.float32)
    q = {k: jnp.asarray(v) for k, v in quantize_nf4(w, 64, True).items()}
    with pltpu.force_tpu_interpret_mode():
        y = nf4_matmul_pallas(jnp.asarray(x), q, compute_dtype=jnp.float32)
    assert y.shape == (2, 8, N)
    ref = np.asarray(x).reshape(16, K) @ np.asarray(dequantize_nf4(q, jnp.float32))
    rel = np.abs(np.asarray(y).reshape(16, N) - ref).max() / np.abs(ref).max()
    assert rel < 0.03, rel


def test_pallas_grad_through_x():
    """QLoRA training differentiates THROUGH frozen quantized matmuls (dx must
    reach upstream adapters); the kernel's custom_vjp supplies g @ W^T."""
    rng = np.random.RandomState(3)
    K, N = 512, 128
    w = rng.randn(K, N).astype(np.float32)
    x = jnp.asarray(rng.randn(16, K).astype(np.float32))
    q = {k: jnp.asarray(v) for k, v in quantize_nf4(w, 64, False).items()}

    with pltpu.force_tpu_interpret_mode():
        g = jax.grad(lambda x: nf4_matmul_pallas(x, q, compute_dtype=jnp.float32).sum())(x)
    ref = np.ones((16, N), np.float32) @ np.asarray(dequantize_nf4(q, jnp.float32)).T
    rel = np.abs(np.asarray(g) - ref).max() / np.abs(ref).max()
    assert rel < 0.03, rel


def test_unsupported_shapes_raise():
    rng = np.random.RandomState(2)
    w = rng.randn(256, 128).astype(np.float32)  # K=256 not divisible by 512
    q = {k: jnp.asarray(v) for k, v in quantize_nf4(w, 64, False).items()}
    with pytest.raises(ValueError, match="512"):
        with pltpu.force_tpu_interpret_mode():
            nf4_matmul_pallas(jnp.ones((16, 256)), q)
