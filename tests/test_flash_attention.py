"""Pallas flash attention vs the reference XLA attention — forward and
backward, with GQA and right-padding. Runs the kernels in interpret mode on
the CPU test backend (compiled-mode coverage comes from bench.py on TPU)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from llm_fine_tune_distributed_tpu.ops.attention import xla_attention
from llm_fine_tune_distributed_tpu.ops.flash_attention import pallas_flash_attention


def make_qkv(rng, b, s, hq, hkv, d, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(rng, 3)
    q = jax.random.normal(kq, (b, s, hq, d), dtype)
    k = jax.random.normal(kk, (b, s, hkv, d), dtype)
    v = jax.random.normal(kv, (b, s, hkv, d), dtype)
    return q, k, v


@pytest.mark.parametrize("hq,hkv", [(4, 4), (4, 2), (8, 2)])
def test_forward_matches_xla(hq, hkv):
    rng = jax.random.PRNGKey(0)
    q, k, v = make_qkv(rng, 2, 256, hq, hkv, 32)
    out_flash = pallas_flash_attention(q, k, v, interpret=True)
    out_xla = xla_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out_flash), np.asarray(out_xla), atol=2e-5, rtol=2e-5)


def test_forward_with_padding_lengths():
    rng = jax.random.PRNGKey(1)
    b, s = 3, 256
    q, k, v = make_qkv(rng, b, s, 4, 2, 32)
    lengths = np.asarray([256, 100, 17], np.int32)
    padding_mask = (np.arange(s)[None, :] < lengths[:, None]).astype(np.float32)
    out_flash = pallas_flash_attention(q, k, v, padding_mask=jnp.asarray(padding_mask), interpret=True)
    out_xla = xla_attention(q, k, v, padding_mask=jnp.asarray(padding_mask), causal=True)
    # only positions < length matter (padded query rows are dropped by the
    # loss mask downstream)
    for i, L in enumerate(lengths):
        np.testing.assert_allclose(
            np.asarray(out_flash)[i, :L], np.asarray(out_xla)[i, :L], atol=2e-5, rtol=2e-5
        )


@pytest.mark.parametrize("hq,hkv", [(4, 4), (4, 2)])
def test_gradients_match_xla(hq, hkv):
    rng = jax.random.PRNGKey(2)
    b, s, d = 2, 256, 32
    q, k, v = make_qkv(rng, b, s, hq, hkv, d)
    lengths = np.asarray([256, 192], np.int32)
    padding_mask = jnp.asarray(
        (np.arange(s)[None, :] < lengths[:, None]).astype(np.float32)
    )
    cot = jax.random.normal(jax.random.PRNGKey(3), (b, s, hq, d), jnp.float32)
    # zero the cotangent on padded query rows: those outputs are undefined
    # garbage in both impls and masked by the loss downstream
    row_ok = (np.arange(s)[None, :] < lengths[:, None]).astype(np.float32)
    cot = cot * jnp.asarray(row_ok)[:, :, None, None]

    def loss_flash(q, k, v):
        return jnp.sum(pallas_flash_attention(q, k, v, padding_mask=padding_mask, interpret=True) * cot)

    def loss_xla(q, k, v):
        return jnp.sum(xla_attention(q, k, v, padding_mask=padding_mask, causal=True) * cot)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gx = jax.grad(loss_xla, argnums=(0, 1, 2))(q, k, v)
    for a, b_, name in zip(gf, gx, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b_), atol=5e-5, rtol=5e-5,
            err_msg=f"grad mismatch for {name}",
        )


def test_train_step_with_flash_impl_runs():
    """attention(impl='flash') on CPU falls back to xla (backend check) —
    the config default attention_impl='flash' must be safe everywhere."""
    from llm_fine_tune_distributed_tpu.ops.attention import attention

    rng = jax.random.PRNGKey(0)
    q, k, v = make_qkv(rng, 1, 64, 4, 2, 16)
    out = attention(q, k, v, impl="flash", causal=True)
    ref = xla_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-6)


def _segments(b, s, rng):
    """Random packed layout: 2-4 segments per row + a pad tail (seg 0)."""
    out = np.zeros((b, s), np.int32)
    for r in range(b):
        n_seg = rng.randint(2, 5)
        cuts = np.sort(rng.choice(np.arange(16, s - 16), n_seg - 1, replace=False))
        bounds = [0, *cuts.tolist(), s - rng.randint(0, 32)]
        for sid in range(n_seg):
            out[r, bounds[sid] : bounds[sid + 1]] = sid + 1
    return out


def test_forward_segments_match_xla():
    """Packed (segment-masked) flash == segment-masked XLA at real positions."""
    rng = jax.random.PRNGKey(1)
    q, k, v = make_qkv(rng, 3, 256, 4, 2, 32)
    seg = jnp.asarray(_segments(3, 256, np.random.RandomState(0)))
    out_flash = pallas_flash_attention(q, k, v, segment_ids=seg, interpret=True)
    out_xla = xla_attention(q, k, v, segment_ids=seg, causal=True)
    real = np.asarray(seg) > 0
    np.testing.assert_allclose(
        np.asarray(out_flash)[real], np.asarray(out_xla)[real], atol=2e-5, rtol=2e-5
    )


def test_backward_segments_match_xla():
    rng = jax.random.PRNGKey(2)
    q, k, v = make_qkv(rng, 2, 256, 4, 2, 32)
    seg_np = _segments(2, 256, np.random.RandomState(1))
    seg = jnp.asarray(seg_np)
    cot = jax.random.normal(jax.random.PRNGKey(3), q.shape, q.dtype)
    # zero cotangent at pad rows, like a loss mask would
    cot = cot * jnp.asarray((seg_np > 0)[:, :, None, None].astype(np.float32))

    def f_flash(q, k, v):
        return jnp.sum(pallas_flash_attention(q, k, v, segment_ids=seg, interpret=True) * cot)

    def f_xla(q, k, v):
        return jnp.sum(xla_attention(q, k, v, segment_ids=seg, causal=True) * cot)

    g_flash = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    g_xla = jax.grad(f_xla, argnums=(0, 1, 2))(q, k, v)
    for a, b_, name in zip(g_flash, g_xla, "qkv"):
        real = (seg_np > 0)[:, :, None, None]
        np.testing.assert_allclose(
            np.asarray(a) * real, np.asarray(b_) * real, atol=5e-5, rtol=5e-5,
            err_msg=f"d{name} mismatch",
        )
