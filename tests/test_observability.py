"""Observability primitives (observe/tracing.py) and their engine wiring:
mergeable fixed-bucket histograms under concurrent mutation, per-request
lifecycle traces, the trace JSONL export, and the crash flight recorder —
including the acceptance gate that an injected decode crash produces a
flight-recorder artifact holding both the pre-crash tick events AND the
restart transition.
"""

import json
import os
import threading

import jax
import jax.numpy as jnp
import pytest

from llm_fine_tune_distributed_tpu.data.tokenizer import ByteChatMLTokenizer
from llm_fine_tune_distributed_tpu.infer import GenerationConfig, Generator
from llm_fine_tune_distributed_tpu.infer.engine import (
    ContinuousBatchingEngine,
    PagedContinuousBatchingEngine,
)
from llm_fine_tune_distributed_tpu.infer.errors import RetryableEngineError
from llm_fine_tune_distributed_tpu.infer.supervisor import EngineSupervisor
from llm_fine_tune_distributed_tpu.models.configs import get_preset
from llm_fine_tune_distributed_tpu.models.transformer import init_params
from llm_fine_tune_distributed_tpu.observe.metrics import ServingStats
from llm_fine_tune_distributed_tpu.observe.tracing import (
    FlightRecorder,
    Histogram,
    RequestTrace,
    TraceJsonlWriter,
)

GREEDY = GenerationConfig(max_new_tokens=6, do_sample=False)


@pytest.fixture(scope="module")
def generator():
    mc = get_preset("tiny")
    params = init_params(jax.random.PRNGKey(0), mc, dtype=jnp.float32)
    return Generator(
        params, mc, ByteChatMLTokenizer(), compute_dtype=jnp.float32, eos_token_ids=[]
    )


# ------------------------------------------------------------- histograms


def test_histogram_bucketing_and_percentiles():
    h = Histogram([0.001, 0.01, 0.1, 1.0])
    for v in (0.0005, 0.005, 0.005, 0.05, 0.5):
        h.observe(v)
    assert h.total == 5
    assert h.counts == [1, 2, 1, 1, 0]
    assert h.sum == pytest.approx(0.5605)
    # p50 lands in the (0.001, 0.01] bucket, interpolated inside it
    assert 0.001 < h.percentile(50) <= 0.01
    assert 0.1 < h.percentile(99) <= 1.0
    s = h.summary()
    assert s["count"] == 5
    assert s["mean"] == pytest.approx(0.1121)


def test_histogram_empty_and_overflow():
    h = Histogram([1.0, 2.0])
    assert h.percentile(50) == 0.0
    assert h.summary() == {
        "count": 0, "mean": 0.0, "p50": 0.0, "p90": 0.0, "p99": 0.0,
    }
    h.observe(100.0)  # overflow bucket
    assert h.counts == [0, 0, 1]
    # overflow reports the last finite bound — a floor, not an invention
    assert h.percentile(99) == 2.0


def test_histogram_merge_requires_same_bounds():
    a = Histogram([1.0, 2.0])
    b = Histogram([1.0, 2.0])
    a.observe(0.5)
    b.observe(1.5)
    b.observe(9.0)
    a.merge(b)
    assert a.total == 3
    assert a.counts == [1, 1, 1]
    assert a.sum == pytest.approx(11.0)
    with pytest.raises(ValueError):
        a.merge(Histogram([1.0, 3.0]))


def test_histogram_factories():
    e = Histogram.exponential(lo=1e-4, hi=400.0, factor=2.0)
    assert e.bounds[0] == pytest.approx(1e-4)
    assert e.bounds[-1] <= 400.0 * 2.0
    assert all(b2 / b1 == pytest.approx(2.0) for b1, b2 in zip(e.bounds, e.bounds[1:]))
    lin = Histogram.linear(0.0, 16.0, 1.0)
    assert lin.bounds == tuple(float(i) for i in range(17))
    with pytest.raises(ValueError):
        Histogram([])
    with pytest.raises(ValueError):
        Histogram([2.0, 1.0])


def test_histogram_prometheus_lines_cumulative():
    h = Histogram([0.1, 1.0])
    for v in (0.05, 0.5, 5.0):
        h.observe(v)
    lines = h.prometheus_lines("x_seconds")
    assert lines[0] == "# TYPE x_seconds histogram"
    assert 'x_seconds_bucket{le="0.1"} 1' in lines
    assert 'x_seconds_bucket{le="1"} 2' in lines
    assert 'x_seconds_bucket{le="+Inf"} 3' in lines
    assert "x_seconds_count 3" in lines


def test_histogram_concurrent_mutation_exact_totals():
    """Writer threads hammer observe() while readers take summaries; the
    final counts are exact — no lost updates."""
    h = Histogram.exponential()
    per_thread, writers = 2000, 4
    stop = threading.Event()

    def write():
        for i in range(per_thread):
            h.observe(0.0001 * (1 + i % 50))

    def read():
        while not stop.is_set():
            s = h.summary()
            assert 0 <= s["count"] <= per_thread * writers

    readers = [threading.Thread(target=read) for _ in range(2)]
    threads = [threading.Thread(target=write) for _ in range(writers)]
    for t in readers + threads:
        t.start()
    for t in threads:
        t.join()
    stop.set()
    for t in readers:
        t.join()
    assert h.total == per_thread * writers
    assert sum(h.counts) == per_thread * writers


def test_serving_stats_concurrent_mutation():
    """Counters + histograms mutated from several threads while snapshots
    are taken concurrently: final totals are exact and every snapshot is
    internally consistent."""
    stats = ServingStats(slots=4, total_blocks=8)
    per_thread, writers = 1000, 4
    stop = threading.Event()

    def write():
        for _ in range(per_thread):
            stats.incr("tokens_served")
            stats.observe("inter_token_s", 0.01)

    def read():
        while not stop.is_set():
            snap = stats.snapshot()
            assert snap["tokens_served"] <= per_thread * writers
            assert snap["histograms"]["inter_token_s"]["count"] <= (
                per_thread * writers
            )

    readers = [threading.Thread(target=read) for _ in range(2)]
    threads = [threading.Thread(target=write) for _ in range(writers)]
    for t in readers + threads:
        t.start()
    for t in threads:
        t.join()
    stop.set()
    for t in readers:
        t.join()
    snap = stats.snapshot()
    assert snap["tokens_served"] == per_thread * writers
    assert snap["histograms"]["inter_token_s"]["count"] == per_thread * writers
    assert snap["uptime_s"] > 0.0
    assert snap["tokens_per_s_1m"] >= 0.0


# ----------------------------------------------------------------- traces


def test_request_trace_marks_and_dict():
    tr = RequestTrace(request_id=7, t0=100.0)
    tr.mark("received", t=100.0)
    tr.mark("queued", t=100.0)
    tr.mark("admitted", t=100.5)
    tr.mark("completed", t=101.25)
    d = tr.to_dict()
    assert d["request_id"] == 7
    assert [e["span"] for e in d["events"]] == [
        "received", "queued", "admitted", "completed",
    ]
    assert d["events"][2]["t_s"] == pytest.approx(0.5)
    assert d["total_s"] == pytest.approx(1.25)


def test_trace_jsonl_writer(tmp_path):
    path = str(tmp_path / "sub" / "traces.jsonl")
    w = TraceJsonlWriter(path)
    w.write({"request_id": 1, "total_s": 0.5})
    w.write({"request_id": 2, "total_s": 0.7})
    w.close()
    with open(path) as f:
        records = [json.loads(line) for line in f]
    assert [r["request_id"] for r in records] == [1, 2]


# -------------------------------------------------------- flight recorder


def test_flight_recorder_bounded_ring():
    rec = FlightRecorder(capacity=4)
    for i in range(10):
        rec.record("tick", step=i)
    assert len(rec) == 4
    events = rec.events()
    assert [e["step"] for e in events] == [6, 7, 8, 9]
    assert all(e["kind"] == "tick" and e["t_s"] >= 0.0 for e in events)


def test_supervisor_dump_flight(tmp_path):
    sup = EngineSupervisor(flight_dir=str(tmp_path / "flight"))
    rec = FlightRecorder(capacity=8)
    rec.record("tick", step=1)
    rec.record("crash", step=2, error="boom")
    path = sup.dump_flight(rec, "crash_restart", error="boom")
    assert path is not None and os.path.exists(path)
    with open(path) as f:
        payload = json.load(f)
    assert payload["reason"] == "crash_restart"
    assert payload["error"] == "boom"
    assert [e["kind"] for e in payload["events"]] == ["tick", "crash"]
    # no flight_dir configured -> dump is a no-op, never an error
    assert EngineSupervisor().dump_flight(rec, "crash_restart") is None


# ----------------------------------------------------- engine integration


def _prompts():
    tok = ByteChatMLTokenizer()
    return [tok.encode(t) for t in ("alpha", "beta bravo", "the quick brown fox")]


@pytest.mark.parametrize("kind", ["continuous", "paged"])
def test_engine_request_trace_spans(generator, kind, tmp_path):
    """A served request carries a full lifecycle trace (received -> queued
    -> admitted -> prefill -> first_token -> completed, in time order), the
    latency histograms fill, and the settled trace lands in the JSONL log."""
    trace_log = str(tmp_path / "traces.jsonl")
    kw = dict(slots=4, buf_len=96, prompt_bucket=16, trace_log=trace_log)
    if kind == "paged":
        engine = PagedContinuousBatchingEngine(
            generator, block_len=16, prefill_chunk=32, **kw
        )
    else:
        engine = ContinuousBatchingEngine(generator, **kw)
    req = engine.submit_full(_prompts()[0], GREEDY, timeout=240)
    assert req.result is not None
    spans = [s for s, _ in req.trace.events]
    for expected in ("received", "queued", "admitted", "first_token", "completed"):
        assert expected in spans, spans
    assert any(s.startswith("prefill") for s in spans)
    times = [t for _, t in req.trace.events]
    assert times == sorted(times)  # lifecycle is time-ordered

    snap = engine.stats_snapshot()
    hists = snap["histograms"]
    assert hists["ttft_s"]["count"] == 1
    # 6 new tokens -> 5 inter-token gaps
    assert hists["inter_token_s"]["count"] == GREEDY.max_new_tokens - 1
    assert hists["queue_wait_s"]["count"] == 1
    assert hists["decode_tick_s"]["count"] >= 1
    assert hists["prefill_chunk_s"]["count"] >= 1
    with open(trace_log) as f:
        records = [json.loads(line) for line in f]
    assert len(records) == 1
    assert records[0]["request_id"] == req.id
    assert records[0]["generated_tokens"] == GREEDY.max_new_tokens
    assert records[0]["error"] is None
    assert {e["span"] for e in records[0]["events"]} >= {
        "received", "admitted", "first_token", "completed",
    }


@pytest.mark.parametrize("kind", ["continuous", "paged"])
def test_crash_dumps_flight_with_restart_transition(generator, kind, tmp_path):
    """The acceptance gate: an injected decode crash dumps a flight artifact
    containing pre-crash tick events AND the crash -> restart transition."""
    flight_dir = str(tmp_path / "flight")
    kw = dict(
        slots=4, buf_len=96, prompt_bucket=16,
        restart_backoff_s=0.01, restart_backoff_max_s=0.02,
        flight_dir=flight_dir,
    )
    if kind == "paged":
        engine = PagedContinuousBatchingEngine(
            generator, block_len=16, prefill_chunk=32, **kw
        )
    else:
        engine = ContinuousBatchingEngine(generator, **kw)
    prompts = _prompts()
    assert engine.submit(prompts[0], GREEDY, timeout=240) is not None  # warm
    engine.faults.fail_decode_next(1)
    with pytest.raises(RetryableEngineError):
        engine.submit(prompts[1], GREEDY, timeout=60)
    assert engine.submit(prompts[1], GREEDY, timeout=240) is not None  # healed

    dumps = sorted(os.listdir(flight_dir))
    assert len(dumps) == 1 and dumps[0].startswith("flight_crash_restart")
    with open(os.path.join(flight_dir, dumps[0])) as f:
        payload = json.load(f)
    kinds = [e["kind"] for e in payload["events"]]
    assert "tick" in kinds          # pre-crash decode activity
    assert "crash" in kinds
    assert "restart" in kinds       # the recovery transition made the dump
    assert kinds.index("crash") < kinds.index("restart")
    assert payload["reason"] == "crash_restart"
    restart = next(e for e in payload["events"] if e["kind"] == "restart")
    assert restart["generation"] >= 1
    crash = next(e for e in payload["events"] if e["kind"] == "crash")
    assert "injected decode failure" in crash["error"]
