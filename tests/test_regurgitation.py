"""Decode == train consistency (VERDICT r4 #2).

A model whose teacher-forced train loss is ~0 on a memorized dataset MUST
greedily regurgitate the memorized answers through the production inference
path (infer/generate.py -> best_model artifact -> Generator.chat). The r4
flagship's eval_loss 0.0045 next to pure decode babble went unreconciled —
the cause was a data bug (every row truncated to the same prompt prefix, so
no answer token was ever trained; see trainer._attach_completion_mask), but
nothing PINNED the property that training and decode agree. This test pins
it forever: overfit tiny on 20 samples, assert near-exact greedy
regurgitation of the training answers end-to-end.
"""

import difflib
import json
import os

import numpy as np
import pytest

import jax

from llm_fine_tune_distributed_tpu.config import MeshConfig, TrainConfig
from llm_fine_tune_distributed_tpu.data.convert import convert_jsonl_to_parquet

SYS = "Be brief."

_WORDS = [
    "river", "stone", "papaya", "gallon", "maple", "knot", "ember", "cliff",
    "lantern", "moss", "falcon", "cedar", "pearl", "quartz", "willow",
    "ridge", "fern", "slate", "harbor", "thistle",
]
# distinct, low-interference answers: one unique lead word per item
ANSWERS = [f"item {i} is {_WORDS[i]} {_WORDS[(i + 7) % 20]}." for i in range(20)]


@pytest.fixture(scope="module")
def memorize_setup(tmp_path_factory):
    """Overfit tiny on 20 distinct QA pairs until near-zero train loss,
    exporting best_model/ through the standard artifact contract."""
    from llm_fine_tune_distributed_tpu.train.trainer import SFTTrainer

    tmp = tmp_path_factory.mktemp("regurg")
    jsonl = tmp / "qa.jsonl"
    with open(jsonl, "w") as f:
        for i, a in enumerate(ANSWERS):
            f.write(json.dumps({
                "topic": "Memory",
                "question": f"what is item {i}?",
                "answer": a,
            }) + "\n")
    convert_jsonl_to_parquet(str(jsonl), str(tmp / "qa_dataset.parquet"), verbose=False)

    out = tmp / "out"
    cfg = TrainConfig(
        model_name="tiny-random",
        model_preset="tiny",
        tokenizer_path="byte-chatml",
        system_prompt=SYS,
        data_dir=str(tmp),
        dataset_file="qa_dataset.parquet",
        output_dir=str(out),
        epochs=150,
        per_device_batch_size=2,
        gradient_accumulation_steps=1,
        learning_rate=2e-3,
        lr_schedule="cosine",         # settles to 0 so memorization sticks
        warmup_ratio=0.02,
        # loss on answer bytes only: the full-sequence loss carries the
        # IRREDUCIBLE entropy of the item number inside the user prompt
        # (~0.04 here), which would mask whether the answers are memorized
        completion_only_loss=True,
        max_seq_length=160,
        freeze_strategy="none",       # memorization needs full capacity
        validation_fraction=0.1,      # 18 train / 2 val
        eval_steps=0,
        logging_steps=50,
        save_steps=0,
        gradient_checkpointing=False,
        use_native_loader=False,
        mesh=MeshConfig(data=1, fsdp=2, tensor=1, seq=1),
    )
    trainer = SFTTrainer(cfg)
    summary = trainer.train()
    # the premise of the reconciliation: teacher-forced loss is ~0
    assert summary["final_train_loss"] < 0.02, summary["final_train_loss"]
    # regurgitation is a claim about TRAINING rows only: reproduce the 90/10
    # split and probe with the exact "For {topic}, {question}" prompt text
    # the trainer saw (data/convert.py concatenation)
    from llm_fine_tune_distributed_tpu.data.dataset import (
        load_qa_dataset,
        train_validation_split,
    )

    rows = load_qa_dataset(str(tmp / "qa_dataset.parquet"))
    tr_rows, _ = train_validation_split(
        rows, test_size=cfg.validation_fraction, seed=cfg.split_seed
    )
    train_rows = [{"q": r["full-question"], "a": r["answer"]} for r in tr_rows]
    return str(out / "best_model"), train_rows, summary


@pytest.mark.slow
def test_overfit_model_greedily_regurgitates_training_answers(memorize_setup):
    from llm_fine_tune_distributed_tpu.infer import (
        Generator,
        GenerationConfig,
        load_model_dir,
        load_tokenizer_dir,
    )

    best_dir, train_rows, summary = memorize_setup
    params, mc = load_model_dir(best_dir, dtype=np.float32)
    tok = load_tokenizer_dir(best_dir)
    gen = Generator(params, mc, tok, compute_dtype=np.float32)

    overlaps, exact = [], 0
    for row in train_rows[:10]:
        got = gen.chat(
            [
                {"role": "system", "content": SYS},
                {"role": "user", "content": row["q"]},
            ],
            GenerationConfig(max_new_tokens=len(row["a"]) + 24, do_sample=False),
        )
        ratio = difflib.SequenceMatcher(None, got, row["a"]).ratio()
        overlaps.append(ratio)
        exact += int(got.strip() == row["a"].strip())

    mean_overlap = float(np.mean(overlaps))
    # near-total byte overlap: loss ~0 must imply decode reproduces training
    # text; anything else is an inference-path (template/position/tokenizer)
    # mismatch — the exact failure mode VERDICT r4 #2 demands be detectable
    assert mean_overlap > 0.9, (mean_overlap, overlaps)
    assert exact >= 7, (exact, overlaps)
