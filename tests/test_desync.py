"""Desync monitor (runtime/desync.py): the systematic replacement for the
reference's hand-run gradient-desync runbook (SURVEY.md §5.2)."""

import jax.numpy as jnp
import numpy as np
import pytest

from llm_fine_tune_distributed_tpu.runtime.desync import DesyncMonitor, check_param_sync


def test_finite_params_pass():
    ok, sums = check_param_sync({"a": jnp.ones((4, 4)), "b": jnp.zeros((3,))})
    assert ok
    assert len(sums) == 1


def test_nan_fails():
    bad = {"a": jnp.array([1.0, float("nan")])}
    ok, _ = check_param_sync(bad)
    assert not ok


def test_inf_fails():
    bad = {"a": jnp.array([1.0, float("inf")])}
    ok, _ = check_param_sync(bad)
    assert not ok


def test_monitor_cadence_and_raise():
    mon = DesyncMonitor(every_n_steps=2)
    good = {"a": jnp.ones((2,))}
    bad = {"a": jnp.array([float("nan")])}
    assert mon.maybe_check(1, bad)  # off-cadence: not checked
    assert mon.maybe_check(2, good)
    with pytest.raises(RuntimeError, match="desync"):
        mon.maybe_check(4, bad)


def test_monitor_disabled():
    mon = DesyncMonitor(every_n_steps=0)
    assert mon.maybe_check(1, {"a": jnp.array([float("nan")])})
