"""Int8 weight-only inference quantization (ops/int8.py): round-trip error
bounds, matmul parity, selective param conversion, and generation through
the quantized model."""

import numpy as np

import jax
import jax.numpy as jnp

from llm_fine_tune_distributed_tpu.models.configs import get_preset
from llm_fine_tune_distributed_tpu.models.transformer import forward, init_params
from llm_fine_tune_distributed_tpu.ops.int8 import (
    dequantize_int8,
    int8_matmul,
    quantize_int8,
    quantize_params_int8,
)
from llm_fine_tune_distributed_tpu.utils.tree import flatten_dict


def test_roundtrip_error_bounded():
    rng = np.random.RandomState(0)
    w = jnp.asarray(rng.randn(64, 32).astype(np.float32))
    q = quantize_int8(w)
    assert q["int8"].dtype == jnp.int8 and q["int8"].shape == (64, 32)
    assert q["int8_scale"].shape == (32,)
    back = np.asarray(dequantize_int8(q, dtype=jnp.float32))
    # symmetric per-channel: error <= scale/2 per element
    bound = np.asarray(q["int8_scale"])[None, :] / 2 + 1e-7
    assert np.all(np.abs(back - np.asarray(w)) <= bound)


def test_matmul_matches_dequant():
    rng = np.random.RandomState(1)
    w = jnp.asarray(rng.randn(32, 16).astype(np.float32))
    x = jnp.asarray(rng.randn(4, 32).astype(np.float32))
    q = quantize_int8(w)
    ref = x @ dequantize_int8(q, dtype=jnp.float32)
    out = int8_matmul(x, q, compute_dtype=jnp.float32)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_quantize_params_selective():
    """Block linears convert; embeddings, norms, and lm_head stay exact."""
    config = get_preset("tiny_mistral")  # untied -> has lm_head
    params = init_params(jax.random.PRNGKey(0), config, dtype=jnp.float32)
    qp = flatten_dict(quantize_params_int8(params))
    assert "model/layers/0/self_attn/q_proj/kernel_int8" in qp
    assert "model/layers/0/self_attn/q_proj/kernel" not in qp
    assert "model/embed_tokens/weight" in qp  # full precision, untouched
    assert "model/embed_tokens/weight_int8" not in qp
    assert "model/layers/0/input_layernorm/weight" in qp  # 1-D untouched
    assert "lm_head/kernel" in qp  # full precision


def test_forward_close_to_full_precision():
    """Logits through the int8 model stay close to full precision — close
    enough that greedy decode rarely flips (tolerance, not bit-parity)."""
    config = get_preset("tiny")
    params = init_params(jax.random.PRNGKey(0), config, dtype=jnp.float32)
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 512, (2, 16)), jnp.int32)
    ref, _ = forward(params, ids, config, compute_dtype=jnp.float32)
    out, _ = forward(
        quantize_params_int8(params), ids, config, compute_dtype=jnp.float32
    )
    err = np.abs(np.asarray(out) - np.asarray(ref)).max()
    assert err < 0.15, f"int8 logit drift {err} too large"


def test_generate_through_int8():
    from llm_fine_tune_distributed_tpu.data.tokenizer import ByteChatMLTokenizer
    from llm_fine_tune_distributed_tpu.infer import GenerationConfig, Generator

    config = get_preset("tiny")
    params = init_params(jax.random.PRNGKey(0), config, dtype=jnp.float32)
    gen = Generator(
        quantize_params_int8(params),
        config,
        ByteChatMLTokenizer(),
        compute_dtype=jnp.float32,
        eos_token_ids=[],
    )
    out = gen.generate_ids(
        ByteChatMLTokenizer().encode("hello"),
        GenerationConfig(max_new_tokens=5, do_sample=False),
    )
    assert len(out) == 5 and all(0 <= t < 512 for t in out)


def test_moe_int8_quantizes_experts_and_runs():
    """On MoE models the attention linears AND the stacked experts quantize
    (per-expert per-channel scales); the router gate stays exact (it is read
    directly by ops/moe.py, and 8-bit rounding there would flip routing).
    The quantized model must execute with bounded logit drift."""
    config = get_preset("tiny_moe")
    params = init_params(jax.random.PRNGKey(0), config, dtype=jnp.float32)
    qparams = quantize_params_int8(params)
    qp = flatten_dict(qparams)
    assert "model/layers/0/block_sparse_moe/experts/w1_int8" in qp
    assert qp["model/layers/0/block_sparse_moe/experts/w1_int8"].shape == (4, 64, 128)
    assert qp["model/layers/0/block_sparse_moe/experts/w1_int8_scale"].shape == (4, 128)
    assert "model/layers/0/block_sparse_moe/experts/w1" not in qp
    assert "model/layers/0/block_sparse_moe/gate/kernel" in qp  # exact router
    assert "model/layers/0/self_attn/q_proj/kernel_int8" in qp

    ids = jnp.asarray(np.random.RandomState(0).randint(0, 512, (2, 16)), jnp.int32)
    ref, _ = forward(params, ids, config, compute_dtype=jnp.float32)
    out, _ = forward(qparams, ids, config, compute_dtype=jnp.float32)
    assert np.abs(np.asarray(out) - np.asarray(ref)).max() < 0.15


def test_stacked_int8_roundtrip():
    from llm_fine_tune_distributed_tpu.ops.int8 import (
        dequantize_int8_stacked,
        quantize_int8_stacked,
    )

    rng = np.random.RandomState(2)
    w = jnp.asarray(rng.randn(3, 16, 8).astype(np.float32))
    q = quantize_int8_stacked(w)
    back = np.asarray(dequantize_int8_stacked(q, dtype=jnp.float32))
    bound = np.asarray(q["int8_scale"])[:, None, :] / 2 + 1e-7
    assert np.all(np.abs(back - np.asarray(w)) <= bound)


def test_predicate_mismatch_is_loud():
    import pytest

    config = get_preset("tiny")
    params = init_params(jax.random.PRNGKey(0), config, dtype=jnp.float32)
    with pytest.raises(ValueError, match="predicate matched"):
        quantize_params_int8(params, predicate=lambda p: p.endswith("embed_tokens/weight"))
