"""Trainable-only + non-blocking checkpointing (VERDICT r4 #1).

The flagship checkpoint was 7.4 GB of which ~5.3 GB were frozen bf16 leaves
byte-reconstructible from the base checkpoint/seed; saves blocked the train
loop 359-680 s each on the tunneled link. These tests pin the lean payload
(frozen params NOT persisted, fingerprint-verified at restore), the
background snapshot save, and cross-mode resume compatibility.
"""

import os

import numpy as np
import pytest

import jax

from llm_fine_tune_distributed_tpu.config import MeshConfig, TrainConfig

from test_train_e2e import make_config, qa_parquet  # noqa: F401 (fixture)


def _du(path):
    total = 0
    for root, _, files in os.walk(path):
        for f in files:
            total += os.path.getsize(os.path.join(root, f))
    return total


def _train(cfg, rng_seed=None):
    from llm_fine_tune_distributed_tpu.train.trainer import SFTTrainer

    trainer = SFTTrainer(cfg, rng_seed=rng_seed)
    trainer.train()
    return trainer


def test_trainable_only_checkpoint_roundtrip_and_size(qa_parquet, tmp_path):  # noqa: F811
    data_dir, dataset_file = qa_parquet

    full_cfg = make_config(
        tmp_path / "full", data_dir, dataset_file, epochs=1, save_steps=5,
        use_native_loader=False, checkpoint_trainable_only=False,
        checkpoint_async_snapshot=False,
    )
    full = _train(full_cfg)

    lean_cfg = make_config(
        tmp_path / "lean", data_dir, dataset_file, epochs=1, save_steps=5,
        use_native_loader=False, checkpoint_trainable_only=True,
        checkpoint_async_snapshot=False,
    )
    lean = _train(lean_cfg)

    # identical training trajectory (payload mode is storage-only)
    f_losses = [h["loss"] for h in full.metrics.history if "loss" in h]
    l_losses = [h["loss"] for h in lean.metrics.history if "loss" in h]
    np.testing.assert_allclose(l_losses, f_losses, rtol=1e-6)

    # the lean checkpoint drops the frozen leaves: tiny's freeze policy keeps
    # ~59% trainable, so expect a measurable (not 3.5x — that ratio is the
    # flagship's 13.62% trainable) size cut
    full_size = _du(tmp_path / "full" / "checkpoints")
    lean_size = _du(tmp_path / "lean" / "checkpoints")
    assert lean_size < full_size, (lean_size, full_size)

    # resume the lean run: bit-identical trainable/opt state + step
    resume_cfg = make_config(
        tmp_path / "lean", data_dir, dataset_file, epochs=1, save_steps=5,
        use_native_loader=False, checkpoint_trainable_only=True,
        checkpoint_async_snapshot=False, resume_from_checkpoint="latest",
    )
    from llm_fine_tune_distributed_tpu.train.trainer import SFTTrainer
    from llm_fine_tune_distributed_tpu.train.checkpoints import CheckpointManager

    resumed = SFTTrainer(resume_cfg)
    ckpt = CheckpointManager(
        str(tmp_path / "lean" / "checkpoints"), trainable_only=True
    )
    step = ckpt.latest_step
    assert step is not None
    abstract = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding),
        resumed.state,
    ).replace(frozen=resumed.state.frozen)
    restored = ckpt.restore(step, abstract)
    assert int(restored.step) == step
    for k, v in restored.trainable.items():
        assert np.asarray(v).dtype == np.asarray(resumed.state.trainable[k]).dtype
    # frozen carried through unchanged (same objects)
    for k in restored.frozen:
        np.testing.assert_array_equal(
            np.asarray(restored.frozen[k]), np.asarray(resumed.state.frozen[k])
        )
    ckpt.close()


def test_fingerprint_rejects_changed_base_weights(qa_parquet, tmp_path):  # noqa: F811
    """Resuming a trainable-only checkpoint against DIFFERENT frozen params
    (wrong base checkpoint / wrong init seed) must be a hard error, not
    silent corruption."""
    from llm_fine_tune_distributed_tpu.train.trainer import SFTTrainer
    from llm_fine_tune_distributed_tpu.train.checkpoints import CheckpointManager

    data_dir, dataset_file = qa_parquet
    cfg = make_config(
        tmp_path / "a", data_dir, dataset_file, epochs=1, save_steps=5,
        use_native_loader=False, checkpoint_trainable_only=True,
        checkpoint_async_snapshot=False,
    )
    _train(cfg)

    other = SFTTrainer(
        make_config(
            tmp_path / "b", data_dir, dataset_file, epochs=1,
            use_native_loader=False, checkpoint_trainable_only=True,
        ),
        rng_seed=123,  # different init -> different frozen leaves
    )
    ckpt = CheckpointManager(str(tmp_path / "a" / "checkpoints"), trainable_only=True)
    abstract = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding),
        other.state,
    ).replace(frozen=other.state.frozen)
    with pytest.raises(RuntimeError, match="does not match"):
        ckpt.restore(ckpt.latest_step, abstract)
    ckpt.close()

    # the TRAINER resume path must surface the same diagnosis — not bury it
    # under cross-mode/cross-layout fallbacks (r5 review finding)
    from llm_fine_tune_distributed_tpu.train.checkpoints import FingerprintMismatch

    bad_resume = SFTTrainer(
        make_config(
            tmp_path / "a", data_dir, dataset_file, epochs=2,
            use_native_loader=False, checkpoint_trainable_only=True,
            resume_from_checkpoint="latest",
        ),
        rng_seed=123,
    )
    with pytest.raises(FingerprintMismatch, match="does not match"):
        bad_resume.train()


def test_async_snapshot_save_matches_sync(qa_parquet, tmp_path):  # noqa: F811
    """Background snapshot saves must produce the same resumable payload as
    synchronous saves (the train loop keeps the state buffers via donation
    while the snapshot drains — any aliasing bug shows up as corrupted
    trainable leaves here)."""
    from llm_fine_tune_distributed_tpu.train.checkpoints import CheckpointManager

    data_dir, dataset_file = qa_parquet

    trainers = {}
    for name, async_snap in (("sync", False), ("async", True)):
        cfg = make_config(
            tmp_path / name, data_dir, dataset_file, epochs=1, save_steps=3,
            use_native_loader=False, checkpoint_trainable_only=True,
            checkpoint_async_snapshot=async_snap,
        )
        trainers[name] = _train(cfg)

    for name in trainers:
        ckpt = CheckpointManager(
            str(tmp_path / name / "checkpoints"), trainable_only=True
        )
        tr = trainers[name]
        abstract = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding),
            tr.state,
        ).replace(frozen=tr.state.frozen)
        restored = ckpt.restore(ckpt.latest_step, abstract)
        trainers[name] = (tr, restored)
        ckpt.close()

    (_, sync_restored), (_, async_restored) = trainers["sync"], trainers["async"]
    for k in sync_restored.trainable:
        np.testing.assert_array_equal(
            np.asarray(sync_restored.trainable[k]),
            np.asarray(async_restored.trainable[k]),
            err_msg=k,
        )
    assert int(sync_restored.step) == int(async_restored.step)


def test_cross_mode_resume_both_directions(qa_parquet, tmp_path):  # noqa: F811
    """A full checkpoint resumes into a trainable-only run and vice versa —
    flipping the config knob must never strand an existing run."""
    from llm_fine_tune_distributed_tpu.train.trainer import SFTTrainer

    data_dir, dataset_file = qa_parquet
    for first, then in ((False, True), (True, False)):
        out = tmp_path / f"mode_{int(first)}"
        cfg = make_config(
            out, data_dir, dataset_file, epochs=1, save_steps=5,
            use_native_loader=False, checkpoint_trainable_only=first,
            checkpoint_async_snapshot=False,
        )
        _train(cfg)
        resume_cfg = make_config(
            out, data_dir, dataset_file, epochs=2, save_steps=5,
            use_native_loader=False, checkpoint_trainable_only=then,
            checkpoint_async_snapshot=False,
            resume_from_checkpoint="latest",
        )
        trainer = SFTTrainer(resume_cfg)
        # drive the real resume path through train(): it must pick up the
        # other-mode checkpoint and continue to epoch 2
        summary = trainer.train()
        assert summary["final_train_loss"] is not None
        losses = [h["loss"] for h in trainer.metrics.history if "loss" in h]
        assert losses, "resumed run logged no steps"


def test_parallel_device_get_matches_serial():
    """Concurrent leaf fetch (utils/transfer.py) is a pure transport
    optimization: values identical to np.asarray, including the big-leaf
    row-split reassembly path."""
    import jax.numpy as jnp

    from llm_fine_tune_distributed_tpu.utils.transfer import parallel_device_get

    rng = np.random.RandomState(0)
    tree = {
        "small": jnp.asarray(rng.rand(7, 5).astype(np.float32)),
        "scalar": jnp.asarray(np.float32(3.5)),
        "big": jnp.asarray(rng.rand(64, 333).astype(np.float32)),
        "ints": jnp.asarray(rng.randint(0, 100, (11,), dtype=np.int32)),
    }
    # force the split path for "big" with a tiny split threshold
    got = parallel_device_get(tree, workers=3, split_bytes=8 * 333 * 4)
    for k, v in tree.items():
        np.testing.assert_array_equal(got[k], np.asarray(v), err_msg=k)
        assert got[k].dtype == np.asarray(v).dtype


def test_checkpoint_mode_best_restore_on_divergence(qa_parquet, tmp_path, capsys):  # noqa: F811
    """best_model_tracking="checkpoint": when the run DIVERGES after a good
    early checkpoint, the trainer restores the best saved step at end of run
    (the save-aligned HF load_best_model_at_end semantics) — no per-eval HBM
    snapshot involved."""
    from llm_fine_tune_distributed_tpu.train.checkpoints import CheckpointManager
    from llm_fine_tune_distributed_tpu.train.trainer import SFTTrainer

    data_dir, dataset_file = qa_parquet
    cfg = make_config(
        tmp_path / "div", data_dir, dataset_file, epochs=1,
        learning_rate=2.0,           # Adam at lr 2.0 diverges immediately
        eval_steps=3, save_steps=3,  # aligned so saves carry the metric
        use_native_loader=False, checkpoint_trainable_only=True,
        checkpoint_async_snapshot=False,
        best_model_tracking="checkpoint",
    )
    trainer = SFTTrainer(cfg)
    trainer.train()
    out = capsys.readouterr().out
    mgr = CheckpointManager(str(tmp_path / "div" / "checkpoints"), trainable_only=True)
    best, latest = mgr.best_step, mgr.latest_step
    mgr.close()
    assert best is not None
    if best != latest:
        # divergence happened as engineered: the restore branch must have run
        assert "Restored best checkpoint step" in out
    evals = [h["eval_loss"] for h in trainer.metrics.history if "eval_loss" in h]
    assert evals[-1] > evals[0] or best == latest  # sanity: it did diverge


def test_checkpoint_mode_rejects_unaligned_save_eval_cadence(qa_parquet, tmp_path):  # noqa: F811
    """checkpoint-mode best selection stamps saves with the LAST eval's
    metric; unaligned cadences would restore weights credited with a stale
    metric — rejected at train() start (r5 review finding)."""
    from llm_fine_tune_distributed_tpu.train.trainer import SFTTrainer

    data_dir, dataset_file = qa_parquet
    cfg = make_config(
        tmp_path / "bad", data_dir, dataset_file, epochs=1,
        eval_steps=4, save_steps=6,  # 6 % 4 != 0
        use_native_loader=False, best_model_tracking="checkpoint",
    )
    trainer = SFTTrainer(cfg)
    with pytest.raises(ValueError, match="multiple of eval_steps"):
        trainer.train()


def test_fingerprint_rejects_permuted_base_weights():
    """Order-insensitive sums were blind to a permuted/transposed base
    checkpoint (r5 advisor): same elements, same |x| and x^2 sums, but
    shuffled weights. The position-weighted component must catch it."""
    import jax.numpy as jnp

    from llm_fine_tune_distributed_tpu.train.checkpoints import (
        FingerprintMismatch,
        frozen_fingerprint,
        verify_fingerprint,
    )

    rng = np.random.default_rng(0)
    w = rng.standard_normal((8, 16)).astype(np.float32)
    good = {"w": jnp.asarray(w)}
    saved = frozen_fingerprint(good)

    # identical weights pass
    verify_fingerprint(saved, frozen_fingerprint({"w": jnp.asarray(w.copy())}))

    # reversed element order: every order-insensitive sum is EXACTLY equal
    reversed_fp = frozen_fingerprint({"w": jnp.asarray(w[::-1, ::-1].copy())})
    np.testing.assert_allclose(saved["w"][:2], reversed_fp["w"][:2], rtol=1e-6)
    with pytest.raises(FingerprintMismatch, match="does not match"):
        verify_fingerprint(saved, reversed_fp)

    # transposed layout (same shape via reshape) fails too
    transposed = {"w": jnp.asarray(np.ascontiguousarray(w.T).reshape(w.shape))}
    with pytest.raises(FingerprintMismatch, match="does not match"):
        verify_fingerprint(saved, frozen_fingerprint(transposed))


def test_fingerprint_tolerance_scales_with_leaf_count():
    """Cross-platform reduction-order drift grows ~sqrt(n)*eps: a relative
    drift that is legitimate noise on a 100M-element leaf must pass, while
    the SAME relative drift on a tiny leaf (where it can only mean changed
    weights) must fail."""
    from llm_fine_tune_distributed_tpu.train.checkpoints import (
        FingerprintMismatch,
        verify_fingerprint,
    )

    drift = 1 + 3e-4
    big_n = 1e8  # rtol = 2e-7 * sqrt(1e8) = 2e-3 > drift
    saved_big = {"w": np.array([5.0e7, 1.0e8, 2.5e7, big_n], np.float32)}
    drifted_big = {
        "w": np.array(
            [5.0e7 * drift, 1.0e8 * drift, 2.5e7 * drift, big_n], np.float32
        )
    }
    verify_fingerprint(saved_big, drifted_big)  # no raise

    small_n = 100.0  # rtol floor 1e-4 < drift
    saved_small = {"w": np.array([50.0, 100.0, 25.0, small_n], np.float32)}
    drifted_small = {
        "w": np.array(
            [50.0 * drift, 100.0 * drift, 25.0 * drift, small_n], np.float32
        )
    }
    with pytest.raises(FingerprintMismatch, match="does not match"):
        verify_fingerprint(saved_small, drifted_small)

    # changed element COUNT is exact, never tolerance-absorbed
    with pytest.raises(FingerprintMismatch, match="changed size"):
        verify_fingerprint(
            saved_small,
            {"w": np.array([50.0, 100.0, 25.0, 101.0], np.float32)},
        )


def test_sync_save_and_restore_join_pending_background_snapshot(tmp_path):
    """A sync save (or restore) issued while a background snapshot is still
    serializing must JOIN it first — two concurrent ocp.CheckpointManager.save
    calls on one manager race (r5 advisor). Pinned with a slow fake snapshot
    thread: the manager operation must not start until it finishes."""
    import threading
    import time

    import jax.numpy as jnp

    from llm_fine_tune_distributed_tpu.train.checkpoints import CheckpointManager
    from llm_fine_tune_distributed_tpu.train.state import TrainState

    state = TrainState(
        step=jnp.int32(1),
        trainable={"w": jnp.ones((4,), jnp.float32)},
        frozen={"f": jnp.zeros((4,), jnp.float32)},
        opt_state={"m": jnp.zeros((4,), jnp.float32)},
    )
    mgr = CheckpointManager(str(tmp_path / "ck"), max_to_keep=2, metric_name="")

    finished = threading.Event()

    def slow_snapshot():
        time.sleep(0.5)
        finished.set()

    for op in ("save", "restore"):
        t = threading.Thread(target=slow_snapshot)
        mgr._snapshot_thread = t
        t.start()
        if op == "save":
            mgr.save(1, state)
        else:
            abstract = jax.tree.map(
                lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), state
            )
            mgr.restore(1, abstract)
        assert finished.is_set(), f"{op}() ran without joining the snapshot"
        assert mgr._snapshot_thread is None
        finished.clear()

    # a pending background ERROR surfaces on the next save, not silently
    mgr._snapshot_error = RuntimeError("disk full")
    with pytest.raises(RuntimeError, match="disk full"):
        mgr.save(2, state)
    mgr.close()
