"""Pipeline parallelism wired into SFTTrainer (VERDICT r1 #3): a `pipe` mesh
axis trains end-to-end with loss parity against the flat mesh, composes with
data parallelism, honors the freezing policy via the per-layer gradient
mask, and exports the identical per-layer artifact contract."""

import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from llm_fine_tune_distributed_tpu.config import MeshConfig, TrainConfig
from llm_fine_tune_distributed_tpu.parallel.pipeline import (
    STACKED_PREFIX,
    bubble_fraction,
    layer_trainable_vector,
    stack_flat_layer_leaves,
    unstack_flat_layer_leaves,
)

from tests.test_train_e2e import make_config, qa_parquet  # noqa: F401 (fixture)


def test_stack_unstack_roundtrip():
    from llm_fine_tune_distributed_tpu.models.configs import get_preset
    from llm_fine_tune_distributed_tpu.models.transformer import init_params
    from llm_fine_tune_distributed_tpu.utils.tree import flatten_dict

    mc = get_preset("tiny")
    flat = flatten_dict(init_params(jax.random.PRNGKey(0), mc, dtype=jnp.float32))
    stacked = stack_flat_layer_leaves(flat, mc.num_layers)
    stacked_keys = [k for k in stacked if k.startswith(STACKED_PREFIX)]
    assert stacked_keys, "no stacked leaves produced"
    for k in stacked_keys:
        assert stacked[k].shape[0] == mc.num_layers
    back = unstack_flat_layer_leaves(stacked)
    assert set(back) == set(flat)
    for k in flat:
        np.testing.assert_array_equal(np.asarray(back[k]), np.asarray(flat[k]))


def test_layer_trainable_vector_last_two():
    from llm_fine_tune_distributed_tpu.models.configs import get_preset
    from llm_fine_tune_distributed_tpu.models.transformer import init_params
    from llm_fine_tune_distributed_tpu.parallel.freeze import trainable_mask
    from llm_fine_tune_distributed_tpu.utils.tree import flatten_dict

    mc = get_preset("tiny")  # 4 layers
    params = init_params(jax.random.PRNGKey(0), mc, dtype=jnp.float32)
    cfg = TrainConfig(model_preset="tiny")  # default last-2+head freezing
    vec = layer_trainable_vector(flatten_dict(trainable_mask(params, mc, cfg)), mc.num_layers)
    np.testing.assert_array_equal(np.asarray(vec), [0.0, 0.0, 1.0, 1.0])


def test_bubble_fraction():
    assert bubble_fraction(1, 4) == pytest.approx(3 / 4)
    assert bubble_fraction(8, 2) == pytest.approx(1 / 9)
    assert bubble_fraction(16, 1) == 0.0


def test_schedule_tick_count():
    """The compiled schedule is a scan of exactly M + S - 1 ticks (the GPipe
    timetable) — pinned so a schedule regression is loud."""
    from llm_fine_tune_distributed_tpu.models.configs import get_preset
    from llm_fine_tune_distributed_tpu.models.transformer import init_params
    from llm_fine_tune_distributed_tpu.parallel.pipeline import (
        pipeline_forward,
        stack_stage_params,
        stage_sharding,
    )
    from jax.sharding import Mesh

    mc = get_preset("tiny")
    params = init_params(jax.random.PRNGKey(0), mc, dtype=jnp.float32)
    mesh = Mesh(np.array(jax.devices()[:2]), ("pipe",))
    stacked = jax.device_put(stack_stage_params(params, mc, 2), stage_sharding(mesh))
    ids = jnp.zeros((4, 16), jnp.int32)  # M=4 microbatches of 1
    jaxpr = str(
        jax.make_jaxpr(
            lambda p, st, i: pipeline_forward(
                p, st, i, mc, mesh, 4, compute_dtype=jnp.float32
            )
        )(params, stacked, ids)
    )
    M, S = 4, 2
    assert f"length={M + S - 1}" in jaxpr, "GPipe timetable length changed"


@pytest.mark.slow
def test_pipe_trainer_e2e_loss_parity(qa_parquet, tmp_path):  # noqa: F811
    """MESH_PIPE-style run: same tiny recipe on (a) a flat 1-device mesh and
    (b) a pipe=4 mesh; first-step loss agrees (same init, same data), both
    decrease, and the pipeline's exported best_model/ has the same per-layer
    safetensors contract."""
    from llm_fine_tune_distributed_tpu.train.trainer import SFTTrainer

    data_dir, dataset_file = qa_parquet

    flat_cfg = make_config(
        tmp_path / "flat", data_dir, dataset_file,
        epochs=1,
        mesh=MeshConfig(data=1, fsdp=1, tensor=1, seq=1),
    )
    pipe_cfg = make_config(
        tmp_path / "pipe", data_dir, dataset_file,
        epochs=1,
        mesh=MeshConfig(data=1, fsdp=1, tensor=1, seq=1, pipe=4),
    )

    flat = SFTTrainer(flat_cfg)
    flat_summary = flat.train()
    pipe = SFTTrainer(pipe_cfg)
    pipe_summary = pipe.train()

    flat_losses = [h["loss"] for h in flat.metrics.history if "loss" in h]
    pipe_losses = [h["loss"] for h in pipe.metrics.history if "loss" in h]
    assert len(flat_losses) >= 3 and len(pipe_losses) >= 3
    # same initial params + same first batch: the first logged loss must
    # agree up to the mean-of-means vs global-token-mean difference
    assert pipe_losses[0] == pytest.approx(flat_losses[0], rel=2e-2)
    assert pipe_losses[-1] < pipe_losses[0], "pipeline run did not learn"
    # end-of-training losses in the same neighborhood
    assert pipe_losses[-1] == pytest.approx(flat_losses[-1], rel=0.15)
    assert np.isfinite(pipe_summary["final_train_loss"])

    # artifact contract identical to the flat run (per-layer keys, no
    # @stacked leak)
    from safetensors import safe_open

    def keys(out_dir):
        with safe_open(
            os.path.join(out_dir, "best_model", "model.safetensors"), "np"
        ) as f:
            return set(f.keys())

    k_flat, k_pipe = keys(str(tmp_path / "flat")), keys(str(tmp_path / "pipe"))
    assert k_flat == k_pipe
    assert not any("@stacked" in k for k in k_pipe)

    # freezing parity: frozen layers (0, 1) bit-identical to init in the
    # exported pipeline model is covered by test_train_e2e for the flat
    # path; here assert the summary reports the same trainable fraction
    assert pipe_summary["trainable_params"] == flat_summary["trainable_params"]


@pytest.mark.slow
def test_pipe_composes_with_dp(qa_parquet, tmp_path):  # noqa: F811
    """pipe=2 x fsdp=2 mesh: microbatch columns shard over fsdp inside the
    schedule; training runs and learns."""
    from llm_fine_tune_distributed_tpu.train.trainer import SFTTrainer

    data_dir, dataset_file = qa_parquet
    cfg = make_config(
        tmp_path / "pipedp", data_dir, dataset_file,
        epochs=1,
        mesh=MeshConfig(data=1, fsdp=2, tensor=1, seq=1, pipe=2),
    )
    trainer = SFTTrainer(cfg)
    summary = trainer.train()
    losses = [h["loss"] for h in trainer.metrics.history if "loss" in h]
    assert losses[-1] < losses[0]
    assert np.isfinite(summary["final_train_loss"])


def test_pipe_rejects_unsupported_combos(qa_parquet, tmp_path):  # noqa: F811
    from llm_fine_tune_distributed_tpu.train.trainer import SFTTrainer

    data_dir, dataset_file = qa_parquet
    for bad in (
        {"packing": True},
        # ring/ulysses compose with pipe — but not on MoE presets
        {"attention_impl": "ring", "model_preset": "tiny_moe",
         "freeze_strategy": "none"},
        {"attention_impl": "ulysses", "model_preset": "tiny_moe",
         "freeze_strategy": "none"},
        # Gemma2's local/global window alternation needs per-layer masks the
        # pipeline layer-scan cannot express
        {"model_preset": "tiny_gemma2", "freeze_strategy": "none"},
    ):
        cfg = make_config(
            tmp_path / "bad", data_dir, dataset_file,
            mesh=MeshConfig(data=1, fsdp=1, tensor=1, seq=1, pipe=2),
            **bad,
        )
        with pytest.raises(ValueError, match="pipe mesh axis"):
            SFTTrainer(cfg)


def test_pipeline_state_split_lora():
    """Under LoRA, only adapters are trainable in pipe mode: stacked base
    kernels land in `frozen` (no optimizer state, like the flat path) and the
    per-layer mask is all-ones (every layer has trainable adapters)."""
    from llm_fine_tune_distributed_tpu.models.configs import get_preset
    from llm_fine_tune_distributed_tpu.models.transformer import init_params
    from llm_fine_tune_distributed_tpu.parallel.freeze import trainable_mask
    from llm_fine_tune_distributed_tpu.parallel.lora import add_lora_params
    from llm_fine_tune_distributed_tpu.parallel.pipeline import (
        build_pipeline_state_leaves,
    )
    from llm_fine_tune_distributed_tpu.utils.tree import flatten_dict, split_by_mask

    mc = get_preset("tiny")
    cfg = TrainConfig(model_preset="tiny", freeze_strategy="lora")
    params = add_lora_params(params=init_params(jax.random.PRNGKey(0), mc, dtype=jnp.float32), rng=jax.random.PRNGKey(1))
    mask = trainable_mask(params, mc, cfg)
    trainable, frozen = split_by_mask(params, mask)
    t, f, vec = build_pipeline_state_leaves(
        trainable, frozen, flatten_dict(mask), mc.num_layers
    )
    stacked_t = [k for k in t if k.startswith(STACKED_PREFIX)]
    assert stacked_t and all(k.endswith(("lora_a", "lora_b")) for k in stacked_t)
    assert any(k.endswith("/kernel") for k in f if k.startswith(STACKED_PREFIX))
    assert any(k.endswith("lora_scale") for k in f if k.startswith(STACKED_PREFIX))
    np.testing.assert_array_equal(np.asarray(vec), np.ones(mc.num_layers))


@pytest.mark.slow
def test_pipe_lora_loss_parity(qa_parquet, tmp_path):  # noqa: F811
    """pipe=2 x LoRA trains with loss parity vs the flat LoRA run, keeps the
    optimizer state at adapter size, and exports the PEFT adapter +
    merged model exactly like the flat path (VERDICT r2 #3)."""
    from llm_fine_tune_distributed_tpu.train.trainer import SFTTrainer

    data_dir, dataset_file = qa_parquet
    flat_cfg = make_config(
        tmp_path / "flat", data_dir, dataset_file,
        epochs=1, freeze_strategy="lora",
        mesh=MeshConfig(data=1, fsdp=1, tensor=1, seq=1),
    )
    pipe_cfg = make_config(
        tmp_path / "pipe", data_dir, dataset_file,
        epochs=1, freeze_strategy="lora",
        mesh=MeshConfig(data=1, fsdp=2, tensor=1, seq=1, pipe=2),
    )
    flat = SFTTrainer(flat_cfg)
    flat_summary = flat.train()
    pipe = SFTTrainer(pipe_cfg)
    pipe_summary = pipe.train()

    flat_losses = [h["loss"] for h in flat.metrics.history if "loss" in h]
    pipe_losses = [h["loss"] for h in pipe.metrics.history if "loss" in h]
    assert pipe_losses[0] == pytest.approx(flat_losses[0], rel=2e-2)
    assert pipe_losses[-1] < pipe_losses[0], "pipe x lora did not learn"
    assert pipe_summary["trainable_params"] == flat_summary["trainable_params"]

    # optimizer state covers ONLY adapter leaves (the LoRA memory win)
    assert all(
        k.endswith(("lora_a", "lora_b")) for k in pipe.state.trainable
    ), sorted(pipe.state.trainable)[:5]
    # adapter + merged exports both present, no stacked leak
    assert (tmp_path / "pipe" / "adapter" / "adapter_model.safetensors").exists()
    from safetensors import safe_open

    with safe_open(
        os.path.join(tmp_path / "pipe", "best_model", "model.safetensors"), "np"
    ) as f:
        keys = set(f.keys())
    assert not any("@stacked" in k or "lora" in k for k in keys)


@pytest.mark.slow
def test_pipe_qlora_trains(qa_parquet, tmp_path):  # noqa: F811
    """pipe=2 x QLoRA: stacked [L, in, out] base kernels quantize to NF4
    (packed along the per-layer in dim), training learns, and the export
    decodes back to plain per-layer bf16 safetensors."""
    from llm_fine_tune_distributed_tpu.train.trainer import SFTTrainer

    data_dir, dataset_file = qa_parquet
    cfg = make_config(
        tmp_path / "qlora_pipe", data_dir, dataset_file,
        epochs=1, freeze_strategy="qlora",
        mesh=MeshConfig(data=1, fsdp=1, tensor=1, seq=1, pipe=2),
    )
    trainer = SFTTrainer(cfg)
    summary = trainer.train()
    # the stacked frozen base really is NF4 at rest
    assert any(k.endswith("kernel_nf4") for k in trainer.state.frozen)
    losses = [h["loss"] for h in trainer.metrics.history if "loss" in h]
    assert losses[-1] < losses[0], f"no learning: {losses[0]} -> {losses[-1]}"
    assert np.isfinite(summary["final_train_loss"])
    from safetensors import safe_open

    with safe_open(
        os.path.join(tmp_path / "qlora_pipe", "best_model", "model.safetensors"),
        "np",
    ) as f:
        keys = set(f.keys())
    assert not any("@stacked" in k or "nf4" in k or "lora" in k for k in keys)


@pytest.mark.slow
def test_pipe_qlora_moe_quantizes_experts(qa_parquet, tmp_path):  # noqa: F811
    """qlora x pipe x MoE (VERDICT r3 #4): the pipe-stacked 4-D expert
    weights — the dominant bytes of an MoE model — are NF4 at rest, training
    learns through the dequantizing stage scan, and the export decodes back
    to plain safetensors."""
    from llm_fine_tune_distributed_tpu.train.trainer import SFTTrainer

    data_dir, dataset_file = qa_parquet
    cfg = make_config(
        tmp_path / "qlora_moe_pipe", data_dir, dataset_file,
        epochs=1,
        model_preset="tiny_moe",
        freeze_strategy="qlora",
        mesh=MeshConfig(data=1, fsdp=2, tensor=1, seq=1, expert=2, pipe=2),
    )
    trainer = SFTTrainer(cfg)
    # expert leaves are NF4 at rest, with the [L, E, ...] layout the
    # schedule's per-layer scan slices, sharded over pipe AND expert
    expert_nf4 = [
        k for k in trainer.state.frozen
        if "/experts/" in k and k.endswith("_nf4")
    ]
    assert expert_nf4, "pipe-stacked experts were not quantized"
    for k in expert_nf4:
        leaf = trainer.state.frozen[k]
        assert leaf.ndim == 4, (k, leaf.shape)
        spec = leaf.sharding.spec
        assert spec[0] == "pipe" and spec[1] == "expert", (k, spec)
    # no bf16 expert weight remains
    assert not any(
        k.endswith(("w1", "w2", "w3")) for k in trainer.state.frozen
        if "/experts/" in k
    )
    summary = trainer.train()
    losses = [h["loss"] for h in trainer.metrics.history if "loss" in h]
    assert losses[-1] < losses[0], f"no learning: {losses[0]} -> {losses[-1]}"
    assert np.isfinite(summary["final_train_loss"])
    from safetensors import safe_open

    with safe_open(
        os.path.join(tmp_path / "qlora_moe_pipe", "best_model", "model.safetensors"),
        "np",
    ) as f:
        keys = set(f.keys())
    assert not any("@stacked" in k or "nf4" in k or "lora" in k for k in keys)
    assert any("experts" in k for k in keys)


@pytest.mark.slow
def test_pipe_trainer_moe(qa_parquet, tmp_path):  # noqa: F811
    """MoE + pipeline at the TRAINER level: stacked expert leaves shard over
    pipe, router aux rides the schedule, training learns."""
    from llm_fine_tune_distributed_tpu.train.trainer import SFTTrainer

    data_dir, dataset_file = qa_parquet
    cfg = make_config(
        tmp_path / "moe_pipe", data_dir, dataset_file,
        epochs=1,
        model_preset="tiny_moe",
        freeze_strategy="none",
        mesh=MeshConfig(data=1, fsdp=1, tensor=1, seq=1, pipe=2),
    )
    trainer = SFTTrainer(cfg)
    summary = trainer.train()
    losses = [h["loss"] for h in trainer.metrics.history if "loss" in h]
    assert losses[-1] < losses[0], f"no learning: {losses[0]} -> {losses[-1]}"
    assert np.isfinite(summary["final_train_loss"])


@pytest.mark.slow
def test_pipe_trainer_moe_expert_parallel(qa_parquet, tmp_path):  # noqa: F811
    """pipe x EP (VERDICT r2 #4): on a pipe=2 x expert=2 x fsdp=2 mesh the
    stacked expert weights shard over pipe AND expert (the memory win both
    axes exist for), the schedule keeps EP inside each stage, and training
    learns."""
    from jax.sharding import PartitionSpec as P

    from llm_fine_tune_distributed_tpu.train.trainer import SFTTrainer

    data_dir, dataset_file = qa_parquet
    cfg = make_config(
        tmp_path / "moe_ep_pipe", data_dir, dataset_file,
        epochs=1,
        model_preset="tiny_moe",
        freeze_strategy="none",
        mesh=MeshConfig(data=1, fsdp=2, tensor=1, seq=1, expert=2, pipe=2),
    )
    trainer = SFTTrainer(cfg)

    # the stacked expert leaves really are expert-sharded at rest
    expert_keys = [
        k for k in trainer.state.trainable
        if STACKED_PREFIX in k and "/experts/" in k and k.endswith(("w1", "w2", "w3"))
    ]
    assert expert_keys, "no stacked expert leaves in pipe-mode state"
    for k in expert_keys:
        spec = trainer.state.trainable[k].sharding.spec
        assert len(spec) >= 2 and spec[0] == "pipe" and spec[1] == "expert", (
            f"{k} not pipe+expert sharded: {spec}"
        )

    summary = trainer.train()
    losses = [h["loss"] for h in trainer.metrics.history if "loss" in h]
    assert losses[-1] < losses[0], f"no learning: {losses[0]} -> {losses[-1]}"
    assert np.isfinite(summary["final_train_loss"])


@pytest.mark.slow
@pytest.mark.parametrize("impl", ["ring", "ulysses"])
def test_pipe_seq_parallel_attention_trains(qa_parquet, tmp_path, impl):  # noqa: F811
    """pipe x sequence parallelism inside the schedule (both impls): a
    pipe=2 x seq=2 x fsdp=2 mesh trains — stages go manual over seq and
    call the local ring/ulysses kernel — with first-step loss parity
    against the flat seq-parallel mesh."""
    from llm_fine_tune_distributed_tpu.train.trainer import SFTTrainer

    data_dir, dataset_file = qa_parquet
    flat_cfg = make_config(
        tmp_path / f"flat_{impl}", data_dir, dataset_file,
        epochs=1, attention_impl=impl,
        mesh=MeshConfig(data=1, fsdp=2, tensor=1, seq=2),
    )
    pipe_cfg = make_config(
        tmp_path / f"pipe_{impl}", data_dir, dataset_file,
        epochs=1, attention_impl=impl,
        mesh=MeshConfig(data=1, fsdp=2, tensor=1, seq=2, pipe=2),
    )
    from llm_fine_tune_distributed_tpu.parallel.diagnostics import assert_seq_parallel

    with assert_seq_parallel(impl):
        flat = SFTTrainer(flat_cfg)
        flat.train()
    with assert_seq_parallel(f"{impl}_manual"):
        pipe = SFTTrainer(pipe_cfg)
        pipe.train()

    flat_losses = [h["loss"] for h in flat.metrics.history if "loss" in h]
    pipe_losses = [h["loss"] for h in pipe.metrics.history if "loss" in h]
    assert pipe_losses[0] == pytest.approx(flat_losses[0], rel=2e-2)
    assert pipe_losses[-1] < pipe_losses[0], f"pipe x {impl} did not learn"
    assert pipe_losses[-1] == pytest.approx(flat_losses[-1], rel=0.15)
