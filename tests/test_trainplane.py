"""Training control plane + train→serve lineage (ISSUE 14).

Fast tier: anomaly sentinels (non-finite hard sentinel, EWMA-band spike /
explosion detectors, publish-window gate), the TrainTelemetry boundary
hooks, the primary-host-only HTTP plane on an ephemeral port, crash-safe
atomic history flushes, the non-primary no-write guarantee, StepProfiler
and watchdog flight events, manifest lineage keys, and HotSwapManager's
generation→run_id lineage records over a real tiny engine.

Slow tier: a short CPU training run serving live /metrics +
/v1/train/status while stepping; an injected non-finite loss landing as a
flight event + anomaly counter and flipping the publish manifest's
``anomaly_clean`` (or suppressing the publish under
``publish_require_clean``); and the full train→publish→serve→deploy→
``GET /v1/lineage`` round trip.
"""

import json
import os
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from llm_fine_tune_distributed_tpu.observe.trainplane import (
    ANOMALY_KINDS,
    TRAIN_COUNTERS,
    AnomalySentinels,
    TrainControlPlane,
    TrainTelemetry,
    hparams_digest,
    new_run_id,
    trainer_exposition,
)

from tests.test_train_e2e import make_config, qa_parquet  # noqa: F401


def _get(port, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}", timeout=10) as r:
        body = r.read().decode()
        ctype = r.headers.get("Content-Type", "")
    return body, ctype


def _get_json(port, path):
    body, _ = _get(port, path)
    return json.loads(body)


# ------------------------------------------------------------- sentinels


def test_non_finite_fires_from_observation_one():
    s = AnomalySentinels()
    assert s.observe(1, loss=float("nan")) == ["non_finite"]
    assert s.observe(2, grad_norm=float("inf")) == ["non_finite"]
    snap = s.snapshot()
    assert snap["counts"]["non_finite"] == 2
    assert snap["last_step"]["non_finite"] == 2
    assert snap["last_anomaly_step"] == 2


def test_loss_spike_needs_warmup_then_fires():
    s = AnomalySentinels(band_sigma=6.0, warmup=8)
    rng = np.random.RandomState(0)
    for i in range(1, 21):
        assert s.observe(i, loss=1.0 + 0.01 * rng.randn()) == []
    assert s.observe(21, loss=100.0) == ["loss_spike"]
    # the anomalous value was NOT folded into the band: a normal value
    # right after is still normal, and a repeat spike still fires
    assert s.observe(22, loss=1.0) == []
    assert s.observe(23, loss=100.0) == ["loss_spike"]
    assert s.snapshot()["counts"]["loss_spike"] == 2


def test_wild_value_before_warmup_does_not_fire():
    # the first loss of a run IS wild (and the band is meaningless until
    # warmed) — it must seed the band, not fire it
    s = AnomalySentinels(warmup=8)
    assert s.observe(1, loss=50.0) == []
    assert s.snapshot()["total"] == 0


def test_grad_explosion_band():
    s = AnomalySentinels(warmup=4)
    for i in range(1, 6):
        assert s.observe(i, grad_norm=0.5) == []
    assert s.observe(6, grad_norm=500.0) == ["grad_explosion"]


def test_flat_warmup_does_not_make_noise_anomalous():
    # perfectly constant warmup -> zero variance; the std floor must keep
    # ordinary jitter from reading as a 6-sigma event
    s = AnomalySentinels(warmup=4)
    for i in range(1, 8):
        assert s.observe(i, loss=2.0) == []
    assert s.observe(8, loss=2.001) == []


def test_clean_since_is_the_publish_gate():
    s = AnomalySentinels()
    s.observe(10, loss=float("nan"))
    assert not s.clean_since(5)
    assert not s.clean_since(10)
    assert s.clean_since(11)


def test_band_sigma_must_be_positive():
    with pytest.raises(ValueError):
        AnomalySentinels(band_sigma=0.0)


# ------------------------------------------------------------- telemetry


def test_on_step_feeds_flight_status_and_eval_counter():
    t = TrainTelemetry(hparams={"lr": 1e-4})
    t.update(total_steps=100, epochs=2)
    assert t.on_step(5, {"loss": 1.5, "grad_norm": 0.3, "learning_rate": 1e-4}) == []
    t.on_step(10, {"loss": 1.4, "eval_loss": 1.3, "steps_per_second": 2.0})
    st = t.status()
    assert st["step"] == 10
    assert st["loss"] == 1.4
    assert st["counters"]["evals"] == 1
    assert st["eta_s"] == pytest.approx(45.0)
    kinds = [e["kind"] for e in t.recorder.events()]
    assert kinds.count("step") == 2
    assert "eval" in kinds


def test_anomaly_rides_flight_and_window_gate():
    t = TrainTelemetry(hparams={}, anomaly_window_steps=10)
    assert t.on_step(3, {"loss": float("nan")}) == ["non_finite"]
    assert [e for e in t.recorder.events() if e["kind"] == "anomaly"]
    assert not t.publish_clean(3)
    assert not t.publish_clean(12)  # step 3 still inside the 10-step window
    assert t.publish_clean(13)


def test_publish_notes_and_skip_counterpart():
    t = TrainTelemetry(hparams={})
    t.note_publish(8, clean=True, fingerprint="abc")
    t.note_publish(16, clean=False, skipped=True)
    st = t.status()
    assert st["counters"]["publishes"] == 1
    assert st["counters"]["publishes_skipped_dirty"] == 1
    assert st["publishes"][0]["anomaly_clean"] is True
    assert st["publishes"][1]["skipped"] is True
    kinds = [e["kind"] for e in t.recorder.events()]
    assert "publish" in kinds and "publish_skipped_dirty" in kinds


def test_hparams_digest_is_order_insensitive_and_discriminating():
    a = hparams_digest({"lr": 1e-4, "bs": 8})
    b = hparams_digest({"bs": 8, "lr": 1e-4})
    c = hparams_digest({"bs": 8, "lr": 2e-4})
    assert a == b != c
    assert len(a) == 16
    assert new_run_id() != new_run_id()


# ------------------------------------------------------------ exposition


def test_exposition_seeds_every_anomaly_kind():
    text = trainer_exposition(TrainTelemetry(hparams={}), memory={})
    for kind in ANOMALY_KINDS:
        assert f'training_anomalies_total{{kind="{kind}"}} 0' in text
    assert text.count("# TYPE training_anomalies_total counter") == 1


def test_exposition_counts_match_sentinels():
    t = TrainTelemetry(hparams={})
    t.on_step(1, {"loss": float("inf")})
    text = trainer_exposition(t, memory={})
    assert 'training_anomalies_total{kind="non_finite"} 1' in text


# ------------------------------------------------------------ HTTP plane


def test_control_plane_endpoints(tmp_path):
    t = TrainTelemetry(hparams={"x": 1})
    t.update(total_steps=20, epochs=1)
    t.on_step(4, {"loss": 2.0, "grad_norm": 0.1})
    plane = TrainControlPlane(t, 0)
    try:
        assert plane.start()
        assert plane.port > 0
        body, ctype = _get(plane.port, "/metrics")
        assert ctype.startswith("text/plain")
        assert "\ntraining_loss 2\n" in body
        st = _get_json(plane.port, "/v1/train/status")
        assert st["run_id"] == t.run_id
        assert st["step"] == 4
        fl = _get_json(plane.port, "/v1/train/flight?limit=1")
        assert len(fl["events"]) == 1
        with pytest.raises(urllib.error.HTTPError) as e:
            _get(plane.port, "/v1/train/flight?limit=0")
        assert e.value.code == 400
        with pytest.raises(urllib.error.HTTPError) as e:
            _get(plane.port, "/nope")
        assert e.value.code == 404
        # profiling disabled (no profile_dir): POST is a 404, not a crash
        req = urllib.request.Request(
            f"http://127.0.0.1:{plane.port}/v1/train/profile",
            data=b"{}", method="POST",
        )
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(req, timeout=10)
        assert e.value.code == 404
    finally:
        plane.stop()
    # idempotent stop
    plane.stop()


def test_control_plane_noop_off_primary(monkeypatch):
    import llm_fine_tune_distributed_tpu.observe.trainplane as tp

    monkeypatch.setattr(tp, "is_primary_host", lambda: False)
    plane = TrainControlPlane(TrainTelemetry(hparams={}), 0)
    assert plane.start() is False
    assert plane._server is None
    plane.stop()


# --------------------------------------------- metric sinks / history


def test_non_primary_host_writes_nothing(tmp_path, monkeypatch):
    import llm_fine_tune_distributed_tpu.observe.metrics as metrics_mod

    monkeypatch.setattr(metrics_mod, "is_primary_host", lambda: False)
    ml = metrics_mod.MetricLogger(str(tmp_path), stdout=False)
    ml.log(1, 0.1, {"loss": 1.0})
    ml.save_history(str(tmp_path / "training_history.json"))
    ml.close()
    # history still accumulates in memory (every host computes it)...
    assert len(ml.history) == 1
    # ...but NOTHING hits disk off the primary host
    assert os.listdir(tmp_path) == []


def test_save_history_is_atomic_and_litter_free(tmp_path):
    from llm_fine_tune_distributed_tpu.observe.metrics import MetricLogger

    ml = MetricLogger(str(tmp_path), stdout=False)
    path = str(tmp_path / "training_history.json")
    ml.log(1, 0.1, {"loss": 2.0})
    ml.save_history(path)
    ml.log(2, 0.2, {"loss": 1.5})
    ml.save_history(path)  # boundary reflush: replace, never truncate+write
    with open(path) as f:
        hist = json.load(f)
    assert [h["step"] for h in hist] == [1, 2]
    assert not [f for f in os.listdir(tmp_path) if ".tmp" in f]
    ml.close()


# ------------------------------------------- watchdog / profiler flights


def test_watchdog_records_trip_and_rearm_events():
    from llm_fine_tune_distributed_tpu.observe.tracing import FlightRecorder
    from llm_fine_tune_distributed_tpu.runtime.watchdog import StepWatchdog

    rec = FlightRecorder(64)
    wd = StepWatchdog(timeout_s=0.15, action="warn", poll_s=0.03, recorder=rec)
    try:
        wd.poke(1)
        deadline = 5.0
        import time as _time

        t0 = _time.monotonic()
        while wd.trips == 0 and _time.monotonic() - t0 < deadline:
            _time.sleep(0.02)
        assert wd.trips >= 1
        trips = [e for e in rec.events() if e["kind"] == "watchdog_trip"]
        assert trips and trips[0]["last_step"] == 1
        wd.pause()
        wd.poke(2)  # paused->armed boundary: exactly here a rearm lands
        rearms = [e for e in rec.events() if e["kind"] == "watchdog_rearm"]
        assert rearms and rearms[-1]["step"] == 2
        n = len(rearms)
        wd.poke(3)  # already armed: the hot-path poke records NOTHING
        assert len([e for e in rec.events() if e["kind"] == "watchdog_rearm"]) == n
    finally:
        wd.stop()


def test_step_profiler_flight_events(tmp_path, monkeypatch):
    from llm_fine_tune_distributed_tpu.observe.profiler import StepProfiler
    from llm_fine_tune_distributed_tpu.observe.tracing import FlightRecorder

    calls = []
    monkeypatch.setattr(jax.profiler, "start_trace", lambda d: calls.append(("start", d)))
    monkeypatch.setattr(jax.profiler, "stop_trace", lambda: calls.append(("stop", None)))
    rec = FlightRecorder(16)
    prof = StepProfiler(str(tmp_path), start_step=2, num_steps=2, recorder=rec)
    for step in (1, 2, 3, 4, 5):
        prof.step(step)
    prof.close()
    assert [c[0] for c in calls] == ["start", "stop"]
    kinds = [e["kind"] for e in rec.events()]
    assert kinds == ["profile_start", "profile_stop"]
    assert rec.events()[0]["step"] == 2
    assert rec.events()[1]["step"] == 4


def test_step_profiler_close_stops_midflight(tmp_path, monkeypatch):
    from llm_fine_tune_distributed_tpu.observe.profiler import StepProfiler
    from llm_fine_tune_distributed_tpu.observe.tracing import FlightRecorder

    calls = []
    monkeypatch.setattr(jax.profiler, "start_trace", lambda d: calls.append("start"))
    monkeypatch.setattr(jax.profiler, "stop_trace", lambda: calls.append("stop"))
    rec = FlightRecorder(16)
    prof = StepProfiler(str(tmp_path), start_step=1, num_steps=100, recorder=rec)
    prof.step(1)
    prof.close()  # run ended inside the window: close must stop the trace
    assert calls == ["start", "stop"]
    assert [e["kind"] for e in rec.events()] == ["profile_start", "profile_stop"]


def test_profiler_disabled_without_dir():
    from llm_fine_tune_distributed_tpu.observe.profiler import (
        StepProfiler,
        device_memory_report,
    )

    prof = StepProfiler(None)
    prof.step(3)  # no-op, no trace machinery touched
    prof.close()
    report = device_memory_report()
    assert isinstance(report, dict)  # {} on CPU, per-device dicts on TPU


# -------------------------------------------------------- manifest lineage


def test_manifest_carries_lineage_stamps(tmp_path):
    from llm_fine_tune_distributed_tpu.train.publish import (
        CheckpointPublisher,
        load_manifest,
    )

    pub = CheckpointPublisher(str(tmp_path))
    trainable = {"a/kernel": np.ones((2, 2), np.float32)}
    path = pub.publish(
        5, trainable, frozen_fp={"b": np.zeros(2, np.float32)},
        metrics={"eval_loss": 1.25},
        run_id="runabc", hparams_digest="d1" * 8, anomaly_clean=False,
    )
    m = load_manifest(path)
    assert m["run_id"] == "runabc"
    assert m["hparams_digest"] == "d1" * 8
    assert m["anomaly_clean"] is False
    assert m["metrics"]["eval_loss"] == 1.25


def test_manifest_lineage_keys_stay_optional(tmp_path):
    from llm_fine_tune_distributed_tpu.train.publish import (
        CheckpointPublisher,
        load_manifest,
    )

    pub = CheckpointPublisher(str(tmp_path))
    path = pub.publish(
        1, {"a/kernel": np.ones((2, 2), np.float32)},
        frozen_fp={"b": np.zeros(2, np.float32)},
    )
    m = load_manifest(path)  # pre-lineage manifests must keep loading
    assert m is not None
    assert "run_id" not in m and "anomaly_clean" not in m


# --------------------------------------------------- serve-side lineage


@pytest.fixture(scope="module")
def generator():
    from llm_fine_tune_distributed_tpu.data.tokenizer import ByteChatMLTokenizer
    from llm_fine_tune_distributed_tpu.infer.generate import Generator
    from llm_fine_tune_distributed_tpu.models.configs import get_preset
    from llm_fine_tune_distributed_tpu.models.transformer import init_params

    mc = get_preset("tiny")
    params = init_params(jax.random.PRNGKey(0), mc, dtype=jnp.float32)
    return Generator(
        params, mc, ByteChatMLTokenizer(), compute_dtype=jnp.float32,
        eos_token_ids=[],
    )


def _split(generator, n_trainable=2):
    from llm_fine_tune_distributed_tpu.train.checkpoints import frozen_fingerprint
    from llm_fine_tune_distributed_tpu.utils.tree import flatten_dict

    flat = flatten_dict(generator.params)
    keys = sorted(k for k in flat if k.endswith("kernel"))[:n_trainable]
    trainable = {k: np.asarray(flat[k]) for k in keys}
    frozen = {k: v for k, v in flat.items() if k not in trainable}
    return trainable, frozen_fingerprint(frozen)


def test_lineage_maps_generation_to_run(generator, tmp_path):
    from llm_fine_tune_distributed_tpu.infer.deploy import (
        CheckpointWatcher,
        HotSwapManager,
    )
    from llm_fine_tune_distributed_tpu.infer.engine import ContinuousBatchingEngine
    from llm_fine_tune_distributed_tpu.train.publish import CheckpointPublisher

    engine = ContinuousBatchingEngine(
        generator, slots=4, buf_len=96, prompt_bucket=16,
        restart_backoff_s=0.01, restart_backoff_max_s=0.02,
    )
    trainable, frozen_fp = _split(generator)
    pub = CheckpointPublisher(str(tmp_path))
    pub.publish(
        3, trainable, frozen_fp=frozen_fp, metrics={"eval_loss": 0.9},
        run_id="run-lineage", hparams_digest="hp" * 8, anomaly_clean=True,
    )
    watcher = CheckpointWatcher(str(tmp_path), base_params=generator.params)
    mgr = HotSwapManager(engine, watcher)
    res = mgr.poll_once()
    assert res["run_id"] == "run-lineage"
    assert res["anomaly_clean"] is True

    lin = mgr.lineage()
    gen = str(res["weight_generation"])
    assert lin["resident_generation"] == res["weight_generation"]
    rec = lin["generations"][gen]
    assert rec["run_id"] == "run-lineage"
    assert rec["hparams_digest"] == "hp" * 8
    assert rec["step"] == 3
    assert rec["anomaly_clean"] is True
    assert rec["metrics"]["eval_loss"] == 0.9
    assert lin["history"][-1]["kind"] == "deploy"

    # a second publish displaces the first; the rollback then lands as its
    # own lineage record pointing back at the ORIGINAL run identity
    pub.publish(
        6, {k: v + 0.5 for k, v in trainable.items()}, frozen_fp=frozen_fp,
        metrics={"eval_loss": 0.8},
        run_id="run-lineage", hparams_digest="hp" * 8, anomaly_clean=True,
    )
    res2 = mgr.poll_once()
    assert res2["step"] == 6
    back = mgr.rollback()
    assert back["kind"] == "rollback"
    assert back["step"] == 3
    assert back["run_id"] == "run-lineage"
    lin = mgr.lineage()
    assert [r["kind"] for r in lin["history"]] == ["deploy", "deploy", "rollback"]
    assert lin["generations"][str(back["weight_generation"])]["step"] == 3


def test_lineage_without_manifest_is_recorded_unknown(generator, tmp_path):
    from llm_fine_tune_distributed_tpu.infer.deploy import (
        CheckpointWatcher,
        HotSwapManager,
    )
    from llm_fine_tune_distributed_tpu.infer.engine import ContinuousBatchingEngine
    from llm_fine_tune_distributed_tpu.train.publish import CheckpointPublisher

    engine = ContinuousBatchingEngine(
        generator, slots=4, buf_len=96, prompt_bucket=16,
        restart_backoff_s=0.01, restart_backoff_max_s=0.02,
    )
    trainable, frozen_fp = _split(generator)
    CheckpointPublisher(str(tmp_path)).publish(
        1, trainable, frozen_fp=frozen_fp,  # pre-lineage publish: no stamps
    )
    mgr = HotSwapManager(
        engine, CheckpointWatcher(str(tmp_path), base_params=generator.params)
    )
    res = mgr.poll_once()
    assert res["run_id"] is None
    rec = mgr.lineage()["generations"][str(res["weight_generation"])]
    assert rec["run_id"] is None and rec["anomaly_clean"] is None


# ----------------------------------------------------- trainer e2e (slow)


def _wait_plane(trainer, timeout=120.0):
    import time as _time

    t0 = _time.monotonic()
    while _time.monotonic() - t0 < timeout:
        plane = getattr(trainer, "train_plane", None)
        if plane is not None and plane.port > 0 and plane._server is not None:
            return plane
        _time.sleep(0.05)
    raise AssertionError("control plane never came up")


@pytest.mark.slow
def test_train_serves_live_plane_and_clean_lineage(qa_parquet, tmp_path):  # noqa: F811
    from llm_fine_tune_distributed_tpu.train.publish import (
        list_published,
        load_manifest,
    )
    from llm_fine_tune_distributed_tpu.train.trainer import SFTTrainer

    data_dir, dataset_file = qa_parquet
    out = tmp_path / "out"
    publish_dir = str(tmp_path / "publish")
    config = make_config(
        out, data_dir, dataset_file,
        epochs=1, train_port=0, publish_dir=publish_dir,
    )
    trainer = SFTTrainer(config)
    box = {}

    def run():
        box["summary"] = trainer.train()

    th = threading.Thread(target=run)
    th.start()
    try:
        plane = _wait_plane(trainer)
        # live scrape WHILE stepping
        seen_step = 0
        import time as _time

        t0 = _time.monotonic()
        while _time.monotonic() - t0 < 300 and th.is_alive():
            st = _get_json(plane.port, "/v1/train/status")
            seen_step = max(seen_step, int(st["step"]))
            if seen_step >= 2:
                break
            _time.sleep(0.2)
        assert seen_step >= 2, "never observed live progress over HTTP"
        body, ctype = _get(plane.port, "/metrics")
        assert ctype.startswith("text/plain")
        assert "# TYPE training_loss gauge" in body
        assert "training_step_seconds_bucket" in body
        assert 'training_anomalies_total{kind="non_finite"} 0' in body
        fl = _get_json(plane.port, "/v1/train/flight?limit=512")
        assert any(e["kind"] == "step" for e in fl["events"])
    finally:
        th.join(600)
    assert not th.is_alive()
    assert "summary" in box
    # the boundary flushes left a readable history even mid-run artifacts
    with open(out / "training_history.json") as f:
        assert json.load(f)
    # every publish of this healthy run is stamped clean with this run's id
    pubs = list_published(publish_dir)
    assert pubs, "no publish landed"
    for _, path in pubs:
        m = load_manifest(path)
        assert m["run_id"] == trainer.telemetry.run_id
        assert m["hparams_digest"] == trainer.telemetry.hparams_digest
        assert m["anomaly_clean"] is True


def _nan_at_step(trainer, bad_step):
    """Wrap the jitted train step so one step's loss comes back NaN —
    divergence injection without touching the model."""
    real = trainer.train_step
    holder = {"n": 0}

    def wrapped(state, batch):
        state, metrics = real(state, batch)
        holder["n"] += 1
        if holder["n"] == bad_step:
            metrics = dict(metrics)
            metrics["loss"] = jnp.float32(float("nan"))
        return state, metrics

    trainer.train_step = wrapped


@pytest.mark.slow
def test_injected_nan_flips_anomaly_clean(qa_parquet, tmp_path):  # noqa: F811
    from llm_fine_tune_distributed_tpu.train.publish import (
        list_published,
        load_manifest,
    )
    from llm_fine_tune_distributed_tpu.train.trainer import SFTTrainer

    data_dir, dataset_file = qa_parquet
    publish_dir = str(tmp_path / "publish")
    config = make_config(
        tmp_path / "out", data_dir, dataset_file,
        epochs=1, save_steps=4, eval_steps=100, logging_steps=2,
        publish_dir=publish_dir, anomaly_window_steps=100,
    )
    trainer = SFTTrainer(config)
    _nan_at_step(trainer, 2)  # lands on a logging boundary (logging_steps=2)
    trainer.train()
    snap = trainer.telemetry.sentinels.snapshot()
    assert snap["counts"]["non_finite"] >= 1
    assert any(
        e["kind"] == "anomaly" and e["anomaly"] == "non_finite"
        for e in trainer.telemetry.recorder.events()
    )
    pubs = list_published(publish_dir)
    assert pubs
    assert load_manifest(pubs[0][1])["anomaly_clean"] is False


@pytest.mark.slow
def test_publish_require_clean_suppresses_dirty_publish(qa_parquet, tmp_path):  # noqa: F811
    from llm_fine_tune_distributed_tpu.train.publish import list_published
    from llm_fine_tune_distributed_tpu.train.trainer import SFTTrainer

    data_dir, dataset_file = qa_parquet
    publish_dir = str(tmp_path / "publish")
    config = make_config(
        tmp_path / "out", data_dir, dataset_file,
        epochs=1, save_steps=4, eval_steps=100, logging_steps=2,
        publish_dir=publish_dir, anomaly_window_steps=1000,
        publish_require_clean=True,
    )
    trainer = SFTTrainer(config)
    _nan_at_step(trainer, 2)
    trainer.train()
    assert list_published(publish_dir) == []
    st = trainer.telemetry.status()
    assert st["counters"]["publishes_skipped_dirty"] >= 1
    assert st["counters"]["publishes"] == 0


@pytest.mark.slow
def test_lineage_endpoint_after_train_and_deploy(qa_parquet, tmp_path):  # noqa: F811
    """The full loop: train+publish, boot a server watching the publish
    dir, deploy over HTTP, then GET /v1/lineage maps the resident weight
    generation back to the producing run."""
    from llm_fine_tune_distributed_tpu.train.publish import (
        list_published,
        load_manifest,
    )
    from llm_fine_tune_distributed_tpu.train.trainer import SFTTrainer
    from tests.test_server import _start_server

    data_dir, dataset_file = qa_parquet
    out = tmp_path / "out"
    publish_dir = str(tmp_path / "publish")
    config = make_config(
        out, data_dir, dataset_file,
        epochs=1, eval_steps=100, save_steps=100, publish_dir=publish_dir,
    )
    trainer = SFTTrainer(config)
    trainer.train()
    pubs = list_published(publish_dir)
    assert pubs
    manifest = load_manifest(pubs[-1][1])
    assert manifest["run_id"] == trainer.telemetry.run_id

    base = _start_server(
        str(out / "best_model"),
        publish_watch_dir=publish_dir,
        publish_poll_s=3600.0,  # deploy on demand via POST, not the poller
    )
    req = urllib.request.Request(f"{base}/v1/deploy", data=b"{}", method="POST")
    with urllib.request.urlopen(req, timeout=600) as r:
        dep = json.loads(r.read())
    assert dep.get("kind") == "deploy", dep
    assert dep["run_id"] == trainer.telemetry.run_id
    with urllib.request.urlopen(f"{base}/v1/lineage", timeout=30) as r:
        lin = json.loads(r.read())
    gen = str(lin["resident_generation"])
    rec = lin["generations"][gen]
    assert rec["run_id"] == trainer.telemetry.run_id
    assert rec["step"] == manifest["step"]
    assert rec["anomaly_clean"] is True
    assert rec["metrics"]
