"""The sharded train step must compile without GSPMD resharding fallbacks.

VERDICT round 1 flagged "Involuntary full rematerialization" warnings
(spmd_partitioner.cc) in the 8-device dryrun: the embedding-lookup gather's
output was hidden-sharded (fsdp) and XLA could only reach the batch/seq
activation layout by replicating the whole tensor. models/transformer.py now
constrains the lookup table (and the unembed weight) so the gather lands on
the activation layout directly; these tests pin that property for the dryrun
meshes and for the plain DP x FSDP mesh.

The warning is emitted by XLA's C++ logger straight to stderr at compile
time, so the checks run in subprocesses and grep stderr — for the dryrun,
the exact artifact the driver executes for MULTICHIP_r{N}.json.
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_PROBE = r"""
import os, sys
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
os.environ["JAX_PLATFORMS"] = "cpu"
import jax, jax.numpy as jnp, numpy as np
# a sitecustomize may have pinned a hardware platform at interpreter startup;
# the config update overrides it as long as the backend isn't initialized yet
jax.config.update("jax_platforms", "cpu")
from jax.sharding import NamedSharding, PartitionSpec as P
from llm_fine_tune_distributed_tpu.config import MeshConfig, TrainConfig
from llm_fine_tune_distributed_tpu.models.configs import get_preset
from llm_fine_tune_distributed_tpu.models.transformer import init_params
from llm_fine_tune_distributed_tpu.parallel.freeze import trainable_mask
from llm_fine_tune_distributed_tpu.parallel.optimizer import build_optimizer
from llm_fine_tune_distributed_tpu.parallel.sharding import _validate_spec, param_spec
from llm_fine_tune_distributed_tpu.runtime.mesh import data_parallel_size, make_mesh
from llm_fine_tune_distributed_tpu.train.state import TrainState
from llm_fine_tune_distributed_tpu.train.step import build_train_step, jit_train_step
from llm_fine_tune_distributed_tpu.utils.tree import split_by_mask

shape = dict(zip(("data", "fsdp", "tensor", "seq"), map(int, sys.argv[1].split(","))))
mesh = make_mesh(MeshConfig(**shape), jax.devices())
dp = data_parallel_size(mesh)
mc = get_preset("tiny")
tc = TrainConfig(model_preset="tiny", per_device_batch_size=1,
                 gradient_accumulation_steps=2, max_seq_length=64,
                 gradient_checkpointing=True,
                 attention_impl="ring" if shape["seq"] > 1 else "xla")
params = init_params(jax.random.PRNGKey(0), mc, dtype=jnp.float32)
mask = trainable_mask(params, mc, tc)
trainable, frozen = split_by_mask(params, mask)
frozen = {k: v.astype(jnp.bfloat16) for k, v in frozen.items()}
def put(flat):
    return {k: jax.device_put(v, NamedSharding(mesh, _validate_spec(
        param_spec(k, v.ndim), v.shape, mesh))) for k, v in flat.items()}
trainable, frozen = put(trainable), put(frozen)
opt = build_optimizer(tc, None, total_steps=4, data_parallel_size=dp)
state = TrainState(
    step=jax.device_put(jnp.zeros((), jnp.int32), NamedSharding(mesh, P())),
    trainable=trainable, frozen=frozen, opt_state=jax.jit(opt.init)(trainable))
seq_ax = "seq" if shape["seq"] > 1 else None
act = NamedSharding(mesh, P(("data", "fsdp"), seq_ax, None))
step = jit_train_step(build_train_step(mc, tc, opt, activation_sharding=act))
bs = NamedSharding(mesh, P(None, ("data", "fsdp"), seq_ax))
rng = np.random.RandomState(0)
n = tc.per_device_batch_size * dp
batch = {"input_ids": jax.device_put(
             rng.randint(0, mc.vocab_size, (2, n, 64)).astype(np.int32), bs),
         "loss_mask": jax.device_put(np.ones((2, n, 64), np.float32), bs),
         "attention_mask": jax.device_put(np.ones((2, n, 64), np.int32), bs)}
_, m = step(state, batch)
jax.block_until_ready(m)
assert np.isfinite(float(m["loss"]))
print(f"PROBE OK mesh={shape}")
"""


def _run(args, timeout=900):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    return subprocess.run(
        args, capture_output=True, text=True, timeout=timeout, cwd=REPO, env=env
    )


@pytest.mark.slow
def test_dryrun_emits_no_involuntary_rematerialization():
    r = _run([sys.executable, "-c", "import __graft_entry__ as g; g.dryrun_multichip(8)"])
    assert r.returncode == 0, r.stderr[-4000:]
    assert "dryrun_multichip OK" in r.stdout
    assert "Involuntary full rematerialization" not in r.stderr, (
        "GSPMD replicate-then-repartition fallback is back on the train-step "
        "hot path:\n" + r.stderr[-4000:]
    )


_DPO_PROBE = _PROBE.replace(
    'from llm_fine_tune_distributed_tpu.train.step import build_train_step, jit_train_step',
    'from llm_fine_tune_distributed_tpu.train.dpo import build_dpo_train_step',
).replace(
    """tc = TrainConfig(model_preset="tiny", per_device_batch_size=1,
                 gradient_accumulation_steps=2, max_seq_length=64,
                 gradient_checkpointing=True,
                 attention_impl="ring" if shape["seq"] > 1 else "xla")""",
    """tc = TrainConfig(model_preset="tiny", per_device_batch_size=1,
                 gradient_accumulation_steps=2, max_seq_length=64,
                 gradient_checkpointing=True, objective="dpo",
                 attention_impl="ring" if shape["seq"] > 1 else "xla")""",
).replace(
    """step = jit_train_step(build_train_step(mc, tc, opt, activation_sharding=act))""",
    """ref = {k: v.astype(jnp.bfloat16) for k, v in trainable.items()}
step = jax.jit(build_dpo_train_step(mc, tc, opt, activation_sharding=act),
               donate_argnums=(0,))""",
).replace(
    """batch = {"input_ids": jax.device_put(
             rng.randint(0, mc.vocab_size, (2, n, 64)).astype(np.int32), bs),
         "loss_mask": jax.device_put(np.ones((2, n, 64), np.float32), bs),
         "attention_mask": jax.device_put(np.ones((2, n, 64), np.int32), bs)}
_, m = step(state, batch)""",
    """batch = {}
for side in ("chosen", "rejected"):
    batch[side + "_input_ids"] = jax.device_put(
        rng.randint(0, mc.vocab_size, (2, n, 64)).astype(np.int32), bs)
    batch[side + "_loss_mask"] = jax.device_put(np.ones((2, n, 64), np.float32), bs)
    batch[side + "_attention_mask"] = jax.device_put(np.ones((2, n, 64), np.int32), bs)
_, m = step(state, ref, batch)""",
)


# the replace-chain above silently no-ops if the SFT probe's text drifts;
# these assertions make that loud instead of testing the wrong objective
assert "build_dpo_train_step" in _DPO_PROBE
assert 'objective="dpo"' in _DPO_PROBE
assert '("chosen", "rejected")' in _DPO_PROBE
assert "jit_train_step" not in _DPO_PROBE


@pytest.mark.slow
@pytest.mark.parametrize("mesh", ["2,4,1,1", "1,2,2,2"])
def test_dpo_mesh_emits_no_involuntary_rematerialization(mesh):
    """The DPO step (policy + frozen reference forwards, chunked logprobs)
    is reshard-clean too — the embed/unembed constraints thread through
    train/dpo.py's loss."""
    r = _run([sys.executable, "-c", _DPO_PROBE, mesh])
    assert r.returncode == 0, r.stderr[-4000:]
    assert "PROBE OK" in r.stdout
    assert "Involuntary full rematerialization" not in r.stderr, r.stderr[-4000:]


@pytest.mark.slow
@pytest.mark.parametrize("mesh", ["2,4,1,1", "1,8,1,1"])
def test_dp_fsdp_mesh_emits_no_involuntary_rematerialization(mesh):
    """data>1 meshes hit a different GSPMD fallback (the unembed/lookup weight
    pulling batch-sharded activations to its hidden-fsdp layout); pinned
    clean separately from the dryrun mesh."""
    r = _run([sys.executable, "-c", _PROBE, mesh])
    assert r.returncode == 0, r.stderr[-4000:]
    assert "PROBE OK" in r.stdout
    assert "Involuntary full rematerialization" not in r.stderr, r.stderr[-4000:]
