"""Mesh-sharded slot engines (ISSUE 17 tentpole): tensor-parallel
continuous/paged batching under pjit.

Pins the acceptance contract on the conftest-forced 8-CPU mesh: a tp=2
engine's greedy output is bit-identical to the mesh=None engine — plain
greedy, speculative K>0, int8 KV pools, a resident LoRA adapter, a weight
hot-swap, and a preempt-resume — with ZERO post-warmup recompiles (mesh
placement must reach a sharding fixed point at the first compile, or every
tick would re-specialize). Also pins the placement itself: KV/pool leaves
shard their kv-head dim over ``tensor``, int8 scale siblings shard the
same head dim, sampler state stays replicated, and ``make_tp_mesh`` warns
(instead of exploding inside ``shard_params``) when tp does not divide the
model's kv-head count.
"""

import os
import threading
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llm_fine_tune_distributed_tpu.data.tokenizer import ByteChatMLTokenizer
from llm_fine_tune_distributed_tpu.infer import GenerationConfig, Generator
from llm_fine_tune_distributed_tpu.infer.adapters import AdapterRegistry
from llm_fine_tune_distributed_tpu.infer.engine import (
    ContinuousBatchingEngine,
    PagedContinuousBatchingEngine,
)
from llm_fine_tune_distributed_tpu.infer.generate import make_tp_mesh
from llm_fine_tune_distributed_tpu.models.configs import get_preset
from llm_fine_tune_distributed_tpu.models.transformer import init_params
from llm_fine_tune_distributed_tpu.parallel.lora import (
    load_lora_adapter,
    merge_lora,
)

from tests.test_adapters import _make_adapter

CFG = get_preset("tiny")
GREEDY = GenerationConfig(max_new_tokens=12, do_sample=False)
TOK = ByteChatMLTokenizer()

pytestmark = pytest.mark.skipif(
    jax.device_count() < 2, reason="needs the forced multi-device CPU mesh"
)


def _enc(text: str):
    return TOK.encode(text)


def _prompts():
    return [_enc("alpha"), _enc("beta bravo"), _enc("the quick brown fox")]


@pytest.fixture(scope="module")
def base_params():
    return init_params(jax.random.PRNGKey(0), CFG, dtype=jnp.float32)


@pytest.fixture(scope="module")
def mesh():
    return make_tp_mesh(2, CFG)


@pytest.fixture(scope="module")
def solo_gen(base_params):
    return Generator(
        base_params, CFG, TOK, compute_dtype=jnp.float32, eos_token_ids=[]
    )


@pytest.fixture(scope="module")
def tp_gen(base_params, mesh):
    return Generator(
        base_params, CFG, TOK, mesh=mesh, compute_dtype=jnp.float32,
        eos_token_ids=[],
    )


def _make_engine(gen, paged, **kw):
    if paged:
        return PagedContinuousBatchingEngine(
            gen, slots=4, buf_len=128, prompt_bucket=16, block_len=16,
            prefill_chunk=32, **kw,
        )
    return ContinuousBatchingEngine(
        gen, slots=4, buf_len=128, prompt_bucket=16, **kw
    )


def _serve_all(eng, cfg=GREEDY, **submit_kw):
    """Three prompts served twice: the second pass exercises the paged
    engine's prefix-HIT admission path, whose programs also need warming
    before a recompile gate means anything."""
    out = [
        eng.submit_full(p, cfg, seed=0, timeout=240, **submit_kw).result
        for p in _prompts()
    ]
    out += [
        eng.submit_full(p, cfg, seed=0, timeout=240, **submit_kw).result
        for p in _prompts()
    ]
    return out


# ---------------------------------------------------------------- placement


def test_kv_cache_leaves_shard_head_dim_state_replicated(tp_gen, mesh):
    cache, state = tp_gen.init_slot_state(4, 128)
    k = cache["layers"]["0"]["k"]
    assert k.shape[2] == CFG.num_kv_heads
    shard = k.addressable_shards[0].data
    # kv-head dim split 2-way over tensor; every other dim intact
    assert shard.shape[2] * 2 == k.shape[2]
    assert shard.shape[0] == k.shape[0] and shard.shape[1] == k.shape[1]
    # sampler state must stay replicated: every shard is the full leaf
    for leaf in jax.tree.leaves(state):
        assert leaf.addressable_shards[0].data.shape == leaf.shape


def test_int8_pool_scales_shard_head_dim(tp_gen):
    pool, _ = tp_gen.init_paged_state(4, 32, 16, "int8")
    layer = pool["layers"]["0"]
    ks = layer["k_scale"]
    assert ks.addressable_shards[0].data.shape[1] * 2 == ks.shape[1]
    kq = layer["k"]
    assert kq.addressable_shards[0].data.shape[2] * 2 == kq.shape[2]


def test_make_tp_mesh_warns_on_kv_head_fallback():
    # tiny has 2 kv heads: tp=4 cannot shard them and must say so (weights
    # still shard — head replication is a capacity statement, not an error)
    if jax.device_count() < 4:
        pytest.skip("needs 4 devices")
    with pytest.warns(UserWarning, match="head replication"):
        make_tp_mesh(4, CFG)
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        make_tp_mesh(2, CFG)  # divides: silent


# ------------------------------------------------------------------- parity


@pytest.mark.parametrize(
    "paged,kw",
    [
        (False, {}),
        (True, {}),
        (False, {"speculative_k": 2}),
        (True, {"speculative_k": 2}),
        (True, {"kv_quant": "int8"}),
    ],
    ids=["dense", "paged", "dense-spec", "paged-spec", "paged-int8"],
)
def test_tp_engine_greedy_bit_identical_zero_recompiles(
    base_params, solo_gen, tp_gen, paged, kw
):
    cfg = GREEDY
    if kw.get("speculative_k"):
        cfg = GenerationConfig(
            max_new_tokens=12, do_sample=False, speculative_lookup=2
        )
    ref_eng = _make_engine(solo_gen, paged, **kw)
    ref = _serve_all(ref_eng, cfg)

    eng = _make_engine(tp_gen, paged, **kw)
    got = _serve_all(eng, cfg)  # warms cold AND prefix-hit paths
    eng.mark_compile_warm()
    # the ledger is the (module-shared) generator's: assert on the DELTA
    recompiles0 = eng.compile_ledger.recompiles_after_warmup
    got += _serve_all(eng, cfg)
    assert got[:6] == ref and got[6:] == ref
    assert eng.compile_ledger.recompiles_after_warmup == recompiles0


def test_tp_adapter_rows_match_merged_solo(base_params, tp_gen, mesh, tmp_path):
    """A resident LoRA adapter decoding on the tp=2 engine (pool leaves
    placed under the mesh rules) emits the merged-weights solo tokens."""
    _make_adapter(base_params, str(tmp_path / "t1"), seed=1, rank=4)
    reg = AdapterRegistry(
        tp_gen.params, str(tmp_path), max_adapters=4, mesh=mesh
    )
    eng = _make_engine(tp_gen, True, adapters=reg)
    merged = Generator(
        merge_lora(
            load_lora_adapter(base_params, os.path.join(str(tmp_path), "t1"))
        ),
        CFG, TOK, compute_dtype=jnp.float32, eos_token_ids=[],
    )
    for p in _prompts():
        ref = merged.generate_ids(p, GREEDY)
        got = eng.submit_full(p, GREEDY, timeout=240, adapter="t1").result
        assert got == ref
    # base rows co-batch through pool slot 0 bit-identically too
    base_ref = Generator(
        base_params, CFG, TOK, compute_dtype=jnp.float32, eos_token_ids=[]
    ).generate_ids(_prompts()[0], GREEDY)
    assert eng.submit_full(_prompts()[0], GREEDY, timeout=240).result == base_ref


def test_tp_hot_swap_bit_identical_zero_recompiles(base_params, tp_gen):
    """A weight hot-swap on the sharded engine re-places updates over the
    resident NamedSharding (not plain device_put to one chip) and keeps
    the warm jit caches: post-swap greedy equals a from-scratch engine on
    the swapped weights, with zero recompiles across the swap."""
    eng = _make_engine(tp_gen, False)
    _serve_all(eng)
    eng.mark_compile_warm()
    recompiles0 = eng.compile_ledger.recompiles_after_warmup
    new_embed = (
        np.asarray(base_params["model"]["embed_tokens"]["weight"]) * 1.25
    )
    eng.request_weight_swap(
        {"model/embed_tokens/weight": new_embed}, fingerprint="x", timeout=240
    )
    got = _serve_all(eng)
    assert eng.compile_ledger.recompiles_after_warmup == recompiles0
    swapped = dict(base_params)
    swapped["model"] = dict(base_params["model"])
    swapped["model"]["embed_tokens"] = {"weight": jnp.asarray(new_embed)}
    ref_gen = Generator(
        swapped, CFG, TOK, compute_dtype=jnp.float32, eos_token_ids=[]
    )
    assert got == _serve_all(_make_engine(ref_gen, False))


def test_tp_preempt_resume_bit_identical(tp_gen):
    """KV-pressure preemption + resume on the sharded paged engine: the
    preempted greedy victim's full token list equals the uninterrupted
    solo run (banked tokens + re-prefilled suffix over sharded pools)."""
    eng = PagedContinuousBatchingEngine(
        tp_gen, slots=2, buf_len=256, prompt_bucket=64, block_len=16,
        prefill_chunk=64,
    )
    prompt = _enc("preempt me please")
    victim_cfg = GenerationConfig(max_new_tokens=48, do_sample=False)
    solo = tp_gen.generate_ids(prompt, victim_cfg)
    sampled = GenerationConfig(max_new_tokens=64, do_sample=True, temperature=1.0)
    # warm everything the dance touches
    eng.submit(prompt, victim_cfg, priority="best_effort", timeout=240)
    eng.submit(_enc("interactive warm"), sampled, seed=3, timeout=240)

    occupier = threading.Thread(
        target=lambda: eng.submit(
            _enc("long sampled occupier"), sampled, seed=9, timeout=240
        )
    )
    occupier.start()
    deadline = 240
    import time as _t

    t0 = _t.monotonic()
    while eng.live_slots < 1 and _t.monotonic() - t0 < deadline:
        _t.sleep(0.005)
    stream = eng.stream(
        prompt, victim_cfg, priority="best_effort", timeout=240
    )
    tokens = [next(stream), next(stream)]  # victim is decoding
    trigger = threading.Thread(
        target=lambda: eng.submit(
            _enc("interactive arrival"),
            GenerationConfig(max_new_tokens=8, do_sample=True, temperature=1.0),
            seed=4, timeout=240,
        )
    )
    trigger.start()
    tokens.extend(stream)
    trigger.join()
    occupier.join()
    assert tokens == solo
    assert eng.stats_snapshot()["preemptions"] >= 1
