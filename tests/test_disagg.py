"""Disaggregated prefill/decode pools (infer/routing.py roles +
infer/engine.py handoff + infer/fleet.py placement + observe/capacity.py
ratio autoscaling).

What this file pins, layer by layer:

- ``choose_replica`` stage filtering: new requests never land on a
  decode-only replica, handoffs never land on a prefill-only one, and a
  filter that would empty the candidate set is DROPPED (an all-decode
  fleet degrades to mixed placement instead of going dead);
- a prefill-role replica runs the prompt to first token and hands the
  live request to a decode replica through the shared host tier — the
  original stream iterator finishes there, greedy output bit-identical
  to a mixed fleet and to solo ``generate_ids``;
- EVERY handoff failure (injected fault, no decode sibling) degrades to
  decode-on-the-prefill-replica with IDENTICAL greedy output — slower,
  never a drop;
- handoff placement prefers the sibling sharing the source's host block
  tier (its restore path already holds the spilled blocks);
- ``prefill_tokens``/``decode_tokens`` split ``tokens_served`` by stage
  (first tokens ride the prefill forward and land in neither split);
- the forecaster's read-side staleness decay: an idle replica's frozen
  peak rates decay toward zero at read, so the scale-down band can fire
  on a starved runner whose engines stopped ticking (the PR 17
  SERVE_ELASTIC failure);
- ``capacity_report`` grows per-role demand/capacity/headroom sections,
  and the ratio-mode ``Autoscaler`` grows the starved role, trades away
  a surplus dedicated replica at max, and stamps the role into its
  ``scale_decision`` events.
"""

import math
import time

import jax
import jax.numpy as jnp
import pytest

from llm_fine_tune_distributed_tpu.data.tokenizer import ByteChatMLTokenizer
from llm_fine_tune_distributed_tpu.infer import (
    EngineFleet,
    GenerationConfig,
    Generator,
)
from llm_fine_tune_distributed_tpu.infer.engine import (
    ContinuousBatchingEngine,
    PagedContinuousBatchingEngine,
)
from llm_fine_tune_distributed_tpu.infer.paged import HostBlockTier
from llm_fine_tune_distributed_tpu.infer.routing import (
    REPLICA_ROLES,
    ReplicaView,
    choose_replica,
)
from llm_fine_tune_distributed_tpu.models.configs import get_preset
from llm_fine_tune_distributed_tpu.models.transformer import init_params
from llm_fine_tune_distributed_tpu.observe.capacity import (
    Autoscaler,
    LoadForecaster,
    report_from_capacity_snapshots,
)
from llm_fine_tune_distributed_tpu.observe.tracing import FlightRecorder

GREEDY = GenerationConfig(max_new_tokens=6, do_sample=False)
GREEDY48 = GenerationConfig(max_new_tokens=48, do_sample=False)


@pytest.fixture(scope="module")
def generator():
    mc = get_preset("tiny")
    params = init_params(jax.random.PRNGKey(0), mc, dtype=jnp.float32)
    return Generator(
        params, mc, ByteChatMLTokenizer(), compute_dtype=jnp.float32,
        eos_token_ids=[],
    )


def _enc(text):
    return ByteChatMLTokenizer().encode(text)


def _paged(generator, tier, role="mixed", **kw):
    kw.setdefault("slots", 4)
    kw.setdefault("buf_len", 256)
    kw.setdefault("prompt_bucket", 64)
    kw.setdefault("block_len", 16)
    kw.setdefault("prefill_chunk", 256)
    kw.setdefault("restart_backoff_s", 0.01)
    kw.setdefault("restart_backoff_max_s", 0.02)
    return PagedContinuousBatchingEngine(
        generator, host_tier=tier, role=role, **kw
    )


def _role_fleet(generator, roles, tier=None):
    """Fleet with per-replica roles sharing ONE HostBlockTier — the
    sharing is the handoff transport (server.py wires it the same way)."""
    tier = tier if tier is not None else HostBlockTier(64 << 20)
    return EngineFleet(
        [_paged(generator, tier, role=r) for r in roles], routing="prefix"
    ), tier


# a prompt spanning >= 2 full 16-token blocks, so handoffs move blocks
VICTIM_TEXT = "a forty-ish token victim prompt for prefill handoffs"


# ------------------------------------------------------------ role routing


def test_choose_replica_stage_filters_roles():
    views = [
        ReplicaView(0, role="decode"),
        ReplicaView(1, role="prefill"),
        ReplicaView(2, role="mixed"),
    ]
    for policy in ("prefix", "least-loaded", "round-robin"):
        for seq in range(8):
            # new requests: never on the decode-only replica
            p = choose_replica(policy, views, rr_seq=seq)
            assert p is not None and p.index in (1, 2)
            # post-prefill handoffs: never on the prefill-only replica
            p = choose_replica(policy, views, rr_seq=seq, stage="decode")
            assert p is not None and p.index in (0, 2)


def test_choose_replica_role_filter_degrades_not_dead():
    # an all-decode fleet still places new requests (filter dropped)...
    views = [ReplicaView(0, role="decode"), ReplicaView(1, role="decode")]
    assert choose_replica("prefix", views).index in (0, 1)
    # ...and an all-prefill fleet still accepts handoffs
    assert choose_replica(
        "prefix", [ReplicaView(0, role="prefill")], stage="decode"
    ).index == 0
    # an unknown stage is a caller bug, not a degradation
    with pytest.raises(ValueError):
        choose_replica("prefix", views, stage="verify")


def test_engine_rejects_unknown_role(generator):
    assert REPLICA_ROLES == ("mixed", "prefill", "decode")
    with pytest.raises(ValueError):
        ContinuousBatchingEngine(
            generator, slots=1, buf_len=64, role="verifier"
        )


def test_all_decode_fleet_warns_and_serves(generator):
    """A role mix with no prefill-capable replica is almost certainly a
    misconfiguration: the fleet says so ONCE at startup, then degrades to
    mixed placement instead of going dead."""
    tier = HostBlockTier(64 << 20)
    with pytest.warns(RuntimeWarning, match="no prefill-capable"):
        fleet = EngineFleet(
            [_paged(generator, tier, role="decode") for _ in range(2)],
            routing="prefix",
        )
    assert any(
        ev["kind"] == "role_degraded" and ev["missing"] == "prefill"
        for ev in fleet.recorder.events()
    )
    prompt = _enc(VICTIM_TEXT)
    solo = generator.generate_ids(prompt, GREEDY)
    assert fleet.submit(prompt, GREEDY, timeout=240) == solo


# ------------------------------------------------------- handoff (tentpole)


def test_prefill_to_decode_handoff_bit_identical(generator):
    """The disaggregated path end-to-end: routing lands the new request on
    the prefill replica, the first token triggers the handoff, the decode
    replica adopts through the shared tier, and the ORIGINAL stream
    iterator finishes there — tokens bit-identical to solo decode."""
    fleet, _tier = _role_fleet(generator, ["prefill", "decode"])
    prompt = _enc(VICTIM_TEXT)
    solo = generator.generate_ids(prompt, GREEDY48)
    stream = fleet.stream(prompt, GREEDY48, timeout=240)
    tokens = list(stream)
    assert tokens == solo
    pre, dec = fleet.replicas
    psnap, dsnap = pre.stats_snapshot(), dec.stats_snapshot()
    # the prefill replica ingested the prompt, emitted the first token,
    # handed off, and never ran a decode tick for this request
    assert psnap["requests_handed_off"] == 1
    assert psnap["requests_handoff_failed"] == 0
    assert psnap["requests_completed"] == 0
    assert psnap["prefill_tokens"] >= len(prompt) - 1
    assert psnap["decode_tokens"] == 0
    # the decode replica adopted and settled it — exactly once, fleet-wide
    assert dsnap["slots_migrated"] == 1
    assert dsnap["requests_completed"] == 1
    assert dsnap["decode_tokens"] > 0
    kinds = [ev["kind"] for ev in fleet.recorder.events()]
    assert "handoff" in kinds
    # both engines' traces carry the hop
    assert any(ev["kind"] == "handoff" for ev in pre.recorder.events())
    # fleet rollups: the role split and the per-role capacity sections
    fsnap = fleet.stats_snapshot()
    by_role = fsnap["tokens_by_role"]
    assert by_role["prefill"]["replicas"] == 1
    assert by_role["decode"]["replicas"] == 1
    assert by_role["prefill"]["prefill_tokens"] >= len(prompt) - 1
    assert by_role["prefill"]["decode_tokens"] == 0
    assert by_role["decode"]["decode_tokens"] > 0
    assert fsnap["role"] == "disaggregated"
    report = fleet.capacity_report()
    assert set(report["roles"]) == {"prefill", "decode"}
    for sec in report["roles"].values():
        assert sec["replicas"] == 1
        for key in (
            "demand_tokens_per_s", "forecast_demand_tokens_per_s",
            "capacity_tokens_per_s", "headroom_tokens_per_s",
            "utilization", "recommended_replicas", "dedicated_replicas",
        ):
            assert key in sec


def test_handoff_fault_degrades_to_decode_in_place(generator):
    """An injected handoff fault fires BEFORE anything leaves the prefill
    replica: the slot stays live, decode continues in place, and greedy
    output is bit-identical — the disaggregation win is lost for that one
    request, nothing else."""
    fleet, _tier = _role_fleet(generator, ["prefill", "decode"])
    pre, dec = fleet.replicas
    prompt = _enc(VICTIM_TEXT)
    solo = generator.generate_ids(prompt, GREEDY48)
    pre.faults.fail_handoff_next(1)
    assert fleet.submit(prompt, GREEDY48, timeout=240) == solo
    psnap = pre.stats_snapshot()
    assert psnap["requests_handoff_failed"] == 1
    assert psnap["requests_handed_off"] == 0
    assert psnap["requests_completed"] == 1
    assert dec.stats_snapshot()["requests_completed"] == 0
    assert dec.stats_snapshot()["slots_migrated"] == 0
    failed = [
        ev for ev in pre.recorder.events() if ev["kind"] == "handoff_failed"
    ]
    assert failed and failed[-1]["where"] == "spill"
    # the fault self-disarmed: the next request hands off normally
    prompt2 = _enc("a different long prompt that should hand off cleanly")
    solo2 = generator.generate_ids(prompt2, GREEDY48)
    assert fleet.submit(prompt2, GREEDY48, timeout=240) == solo2
    assert pre.stats_snapshot()["requests_handed_off"] == 1
    assert dec.stats_snapshot()["requests_completed"] == 1


def test_handoff_without_decode_sibling_decodes_in_place(generator):
    """No adoptable decode replica (the only one is draining): the spill
    already ran, so the request re-enters the LOCAL queue and re-admission
    resumes from the locally cached blocks — identical output, counted as
    a handoff failure at the adopt step."""
    fleet, _tier = _role_fleet(generator, ["prefill", "decode"])
    pre, dec = fleet.replicas
    dec.begin_drain()
    prompt = _enc(VICTIM_TEXT)
    solo = generator.generate_ids(prompt, GREEDY48)
    assert fleet.submit(prompt, GREEDY48, timeout=240) == solo
    psnap = pre.stats_snapshot()
    assert psnap["requests_handoff_failed"] == 1
    assert psnap["requests_completed"] == 1
    assert dec.stats_snapshot()["requests_completed"] == 0
    failed = [
        ev for ev in pre.recorder.events() if ev["kind"] == "handoff_failed"
    ]
    assert failed and failed[-1]["where"] == "adopt"


def test_handoff_prefers_tier_sharing_sibling(generator):
    """Two decode candidates, one sharing the source's host tier: the
    sharer wins even with a later id — its restore path already holds the
    spilled blocks; any other tier means a full re-prefill."""
    tier = HostBlockTier(64 << 20)
    far_tier = HostBlockTier(64 << 20)
    reps = [
        _paged(generator, tier, role="prefill"),
        _paged(generator, far_tier, role="decode"),  # id 1: different tier
        _paged(generator, tier, role="decode"),      # id 2: shares the tier
    ]
    fleet = EngineFleet(reps, routing="prefix")
    prompt = _enc(VICTIM_TEXT)
    solo = generator.generate_ids(prompt, GREEDY48)
    assert fleet.submit(prompt, GREEDY48, timeout=240) == solo
    assert reps[2].stats_snapshot()["slots_migrated"] == 1
    assert reps[1].stats_snapshot()["slots_migrated"] == 0
    assert reps[2].stats_snapshot()["requests_completed"] == 1


# ------------------------------------------------- token-split attribution


@pytest.mark.parametrize("kind", ["continuous", "paged"])
def test_token_split_attribution(generator, kind):
    """``prefill_tokens`` counts prompt positions actually ingested by
    prefill forwards; ``decode_tokens`` counts decode-tick emissions. The
    first token rides the prefill forward and lands in NEITHER split, so
    tokens_served = decode_tokens + completed first tokens."""
    if kind == "paged":
        eng = _paged(generator, HostBlockTier(64 << 20))
    else:
        eng = ContinuousBatchingEngine(
            generator, slots=4, buf_len=256, prompt_bucket=64,
        )
    prompt = _enc(VICTIM_TEXT)
    out = eng.submit(prompt, GREEDY, timeout=240)
    snap = eng.stats_snapshot()
    assert snap["tokens_served"] == len(out) == 6
    assert snap["decode_tokens"] == 5
    assert snap["prefill_tokens"] in (len(prompt) - 1, len(prompt))
    if kind == "paged":
        assert snap["prompt_tokens"] == len(prompt)
        # a repeat prompt reuses cached full blocks: only the tail is
        # ingested again, and the split reflects the work actually done
        eng.submit(prompt, GREEDY, timeout=240)
        snap2 = eng.stats_snapshot()
        assert snap2["decode_tokens"] == 10
        assert snap2["prefix_tokens_reused"] > 0
        assert (
            snap2["prefill_tokens"]
            < snap["prefill_tokens"] + len(prompt)
        )


# ------------------------------------------- forecaster staleness decay


def test_forecaster_staleness_decay_reads_idle_as_idle():
    """``update`` only runs when the engine ticks, so an idle replica's
    EWMAs freeze at the last busy tick's rates. Reads that pass ``now``
    decay by exp(-gap/tau) — the continuous limit of feeding zero-rate
    samples over the gap — so a quiet phase can actually fire the
    scale-down band (the SERVE_ELASTIC guard failure on starved runners).
    """
    fc = LoadForecaster(short_tau_s=10.0, long_tau_s=100.0)
    for i in range(40):
        fc.update(
            float(i), arrivals=10 * i, admitted=10 * i, tokens=100 * i,
            queue_depth=4, live_slots=4,
            prefill_tokens=40 * i, decode_tokens=60 * i,
        )
    rate0 = fc.rate("token_rate")
    assert rate0 == pytest.approx(100.0, rel=0.05)
    # no ``now`` (or a read at the last sample) is byte-identical to the
    # raw EWMAs — every existing caller is unchanged
    assert fc.rate("token_rate", now=39.0) == rate0
    assert fc.rate("token_rate", now=20.0) == rate0  # never amplifies
    # one short tau of silence decays the short read by e^-1
    assert fc.rate("token_rate", now=49.0) == pytest.approx(
        rate0 * math.exp(-1.0)
    )
    # the split rates decay the same way
    assert fc.rate("prefill_token_rate", now=49.0) == pytest.approx(
        fc.rate("prefill_token_rate") * math.exp(-1.0)
    )
    # a long-idle forecaster reads as (essentially) zero demand
    assert fc.rate("token_rate", now=4000.0) < 1e-6
    assert fc.forecast(60.0, now=4000.0) < 1e-6
    snap = fc.snapshot(now=139.0)  # gap 100 = 10 short taus, 1 long tau
    assert snap["rates_short"]["token_rate"] == pytest.approx(
        rate0 * math.exp(-10.0)
    )
    assert snap["rates_long"]["token_rate"] == pytest.approx(
        fc.rate("token_rate", "long") * math.exp(-1.0)
    )
    assert snap["queue_depth"] == pytest.approx(
        fc.queue_depth * math.exp(-10.0)
    )
    # snapshot without ``now`` stays the raw view
    assert fc.snapshot()["rates_short"]["token_rate"] == rate0


# ------------------------------------------------- per-role capacity model


def _role_snap(role, prefill_rate, decode_rate, tick_s=0.05):
    return {
        "slots": 4,
        "role": role,
        "mean_decode_tick_s": tick_s,
        "mean_tokens_per_step": 0.0,
        "live_slots_mean": 2.0,
        "model_flops_utilization": 0.0,
        "hbm_bandwidth_utilization": 0.0,
        "forecaster": {
            "rates_short": {
                "arrival_rate": 1.0,
                "admit_rate": 1.0,
                "token_rate": prefill_rate + decode_rate,
                "prefill_token_rate": prefill_rate,
                "decode_token_rate": decode_rate,
            },
            "trend_tokens_per_s2": 0.0,
            "queue_depth": 0.0,
            "queue_wait_s": 0.0,
            "live_slots_mean": 2.0,
        },
    }


def test_report_role_sections_split_demand_and_capacity():
    # per-replica capacity: 4 slots / 0.05s tick = 80 tokens/s
    snaps = [
        _role_snap("prefill", 70.0, 0.0),
        _role_snap("decode", 0.0, 30.0),
    ]
    rep = report_from_capacity_snapshots(snaps, 2)
    roles = rep["roles"]
    assert set(roles) == {"prefill", "decode"}
    pre, dec = roles["prefill"], roles["decode"]
    assert pre["replicas"] == 1 and pre["dedicated_replicas"] == 1
    assert pre["demand_tokens_per_s"] == pytest.approx(70.0)
    assert pre["capacity_tokens_per_s"] == pytest.approx(80.0)
    assert pre["utilization"] == pytest.approx(70.0 / 80.0)
    # 87.5% > the up band: the prefill pool wants another replica
    assert pre["recommended_replicas"] == 2
    assert dec["demand_tokens_per_s"] == pytest.approx(30.0)
    assert dec["headroom_tokens_per_s"] == pytest.approx(50.0)
    assert dec["recommended_replicas"] == 1
    # a mixed replica is capable of BOTH stages
    snaps.append(_role_snap("mixed", 10.0, 10.0))
    roles = report_from_capacity_snapshots(snaps, 3)["roles"]
    assert roles["prefill"]["replicas"] == 2
    assert roles["decode"]["replicas"] == 2
    assert roles["prefill"]["dedicated_replicas"] == 1
    assert roles["prefill"]["demand_tokens_per_s"] == pytest.approx(80.0)
    assert roles["decode"]["demand_tokens_per_s"] == pytest.approx(40.0)


# ---------------------------------------------------- ratio autoscaling


class _RoleScriptedFleet:
    """The role-aware surface Autoscaler reads, with scripted per-stage
    demand routed through the REAL pure report."""

    def __init__(self, roles, prefill_demand=0.0, decode_demand=0.0):
        self.roles = list(roles)
        self.prefill_demand = prefill_demand
        self.decode_demand = decode_demand
        self.recorder = FlightRecorder(64)
        self.added: list = []
        self.retired: list = []

    def capacity_report(self, horizon_s=60.0, min_replicas=1,
                        max_replicas=None):
        # spread each stage's demand over its dedicated replicas (the
        # split is summed fleet-wide, so the spread doesn't matter)
        snaps = []
        n_pre = max(1, sum(1 for r in self.roles if r != "decode"))
        n_dec = max(1, sum(1 for r in self.roles if r != "prefill"))
        for r in self.roles:
            snaps.append(_role_snap(
                r,
                self.prefill_demand / n_pre if r != "decode" else 0.0,
                self.decode_demand / n_dec if r != "prefill" else 0.0,
            ))
        return report_from_capacity_snapshots(
            snaps, len(self.roles),
            horizon_s=horizon_s, min_replicas=min_replicas,
            max_replicas=max_replicas,
        )

    def add_replica(self, role=None):
        self.roles.append(role or "mixed")
        self.added.append(role)
        return len(self.roles) - 1, object()

    def retire_replica(self, rid=None, timeout_s=60.0, migrate=None,
                       role=None):
        if len(self.roles) <= 1:
            raise ValueError("cannot retire the last replica")
        self.retired.append(role)
        if role is not None:
            self.roles.remove(role)
        else:
            self.roles.pop()
        return rid


def test_ratio_autoscaler_grows_starved_role_in_band():
    """Fleet totals inside the hysteresis band, prefill pool starved:
    ratio mode takes an up step aimed at the prefill role; without ratio
    mode the same report produces NO decision."""
    fleet = _RoleScriptedFleet(
        ["prefill", "decode"], prefill_demand=95.0, decode_demand=25.0,
    )  # fleet util 120/160 = 0.75: in band; prefill util 95/80 > up
    plain = Autoscaler(fleet, mode="on", max_replicas=4, cooldown_s=0.0)
    assert plain.tick(0.0) is None
    scaler = Autoscaler(
        fleet, mode="on", max_replicas=4, cooldown_s=0.0, ratio=True,
    )
    d = scaler.tick(0.0)
    assert d is not None and d["applied"] is True
    assert d["direction"] == "up" and d["role"] == "prefill"
    assert d["role_demand_tokens_per_s"]["prefill"] == pytest.approx(95.0)
    assert fleet.added == ["prefill"]
    assert fleet.roles == ["prefill", "decode", "prefill"]
    # the decision is visible in the flight recorder with its role
    evs = [
        ev for ev in fleet.recorder.events() if ev["kind"] == "scale_decision"
    ]
    assert evs and evs[-1]["role"] == "prefill"
    assert evs[-1]["applied"] is True


def test_ratio_autoscaler_trades_surplus_role_at_max():
    """At max replicas with a starved prefill pool and an over-provisioned
    decode pool: ratio mode trades a dedicated decode replica away so the
    next tick's count recovery can re-add it where it's needed."""
    fleet = _RoleScriptedFleet(
        ["prefill", "decode", "decode"],
        prefill_demand=95.0, decode_demand=60.0,
    )  # fleet util 155/240 = 0.65: in band; prefill starved, decode cold
    scaler = Autoscaler(
        fleet, mode="on", max_replicas=3, cooldown_s=0.0, ratio=True,
    )
    d = scaler.tick(0.0)
    assert d is not None and d["applied"] is True
    assert d["direction"] == "down" and d["role"] == "decode"
    assert fleet.retired == ["decode"]
    assert fleet.roles == ["prefill", "decode"]


def test_ratio_autoscaler_count_step_picks_pressured_role():
    """A count-driven scale-up under ratio mode grows the most-utilized
    role instead of a default mixed replica."""
    fleet = _RoleScriptedFleet(
        ["prefill", "decode"], prefill_demand=190.0, decode_demand=30.0,
    )  # fleet util 220/160 > up: count wants more replicas
    scaler = Autoscaler(
        fleet, mode="on", max_replicas=4, cooldown_s=0.0, ratio=True,
    )
    d = scaler.tick(0.0)
    assert d["direction"] == "up" and d["applied"] is True
    assert d["role"] == "prefill"
    assert fleet.added == ["prefill"]


def test_ratio_mode_off_keeps_decisions_role_free():
    fleet = _RoleScriptedFleet(
        ["prefill", "decode"], prefill_demand=190.0, decode_demand=30.0,
    )
    scaler = Autoscaler(fleet, mode="dry-run", max_replicas=4, cooldown_s=0.0)
    d = scaler.tick(0.0)
    assert d is not None and "role" not in d
    assert fleet.added == [] and fleet.retired == []


def test_fleet_add_and_retire_replica_by_role(generator):
    """The fleet ends of the ratio dimension: add_replica(role=...) builds
    and wires that role, retire_replica(role=...) takes the NEWEST replica
    of the role, and both stamp the role into their scale events."""
    tier = HostBlockTier(64 << 20)

    def factory(rid, role=None):
        return _paged(generator, tier, role=role or "mixed")

    fleet = EngineFleet(
        [_paged(generator, tier, role="prefill"),
         _paged(generator, tier, role="decode")],
        routing="prefix", replica_factory=factory,
    )
    rid, rep = fleet.add_replica(role="decode")
    assert rep.role == "decode" and len(fleet.replicas) == 3
    ups = [ev for ev in fleet.recorder.events() if ev["kind"] == "scale_up"]
    assert ups and ups[-1]["role"] == "decode"
    retired = fleet.retire_replica(role="decode", timeout_s=30)
    assert retired == rid  # newest decode replica, not the original
    downs = [
        ev for ev in fleet.recorder.events() if ev["kind"] == "scale_down"
    ]
    assert downs and downs[-1]["role"] == "decode"
    with pytest.raises(KeyError):
        fleet.retire_replica(role="mixed")
    # a grown prefill replica gets the handoff hook wired on the spot
    rid2, rep2 = fleet.add_replica(role="prefill")
    assert rep2.role == "prefill" and rep2.handoff is not None
