"""Mesh construction — in particular the multi-slice hybrid path: when the
device pool spans slices, ``make_mesh`` must route ONLY the data axis over
DCN (``mesh_utils.create_hybrid_device_mesh``) and reject any shape that
would put fsdp/tensor/seq/pipe/expert traffic on the slow cross-slice links
(runtime/mesh.py docstring; DCN ~6 GB/s/chip vs ICI ~90 GB/s —
observe/scaling.py:V5E).

Real multi-slice hardware is unavailable here, so the slice topology is
faked: wrapper devices carry a ``slice_index`` and jax's hybrid constructor
(upstream-tested) is monkeypatched to capture the ici/dcn shapes it is
handed and return the plain device grid.
"""

import numpy as np
import pytest

import jax

from llm_fine_tune_distributed_tpu.config import MeshConfig
from llm_fine_tune_distributed_tpu.runtime.mesh import MESH_AXES, make_mesh


class _SliceDevice:
    """A real device with a faked slice_index (attribute shadowing only)."""

    def __init__(self, device, slice_index):
        self._device = device
        self.slice_index = slice_index

    def __getattr__(self, name):
        return getattr(self._device, name)


def _fake_two_slices(devices):
    half = len(devices) // 2
    return [
        _SliceDevice(d, 0 if i < half else 1) for i, d in enumerate(devices)
    ]


@pytest.fixture
def capture_hybrid(monkeypatch, eight_devices):
    """Capture create_hybrid_device_mesh calls; return the real-device grid."""
    from jax.experimental import mesh_utils

    calls = []

    def fake(ici_shape, dcn_shape, devices=None, **kw):
        calls.append((tuple(ici_shape), tuple(dcn_shape)))
        real = [getattr(d, "_device", d) for d in devices]
        shape = tuple(i * d for i, d in zip(ici_shape, dcn_shape))
        return np.asarray(real).reshape(shape)

    monkeypatch.setattr(mesh_utils, "create_hybrid_device_mesh", fake)
    return calls


def test_single_slice_unaffected(eight_devices):
    mesh = make_mesh(MeshConfig(data=2, fsdp=4), eight_devices)
    assert dict(mesh.shape)["data"] == 2 and dict(mesh.shape)["fsdp"] == 4


def test_hybrid_mesh_routes_data_over_dcn(capture_hybrid, eight_devices):
    fakes = _fake_two_slices(eight_devices)
    mesh = make_mesh(MeshConfig(data=2, fsdp=4), fakes)
    assert len(capture_hybrid) == 1
    ici, dcn = capture_hybrid[0]
    # data axis split across the 2 slices; every other axis within a slice
    assert dcn == tuple(2 if a == "data" else 1 for a in MESH_AXES)
    assert ici[MESH_AXES.index("data")] == 1
    assert ici[MESH_AXES.index("fsdp")] == 4
    assert mesh.shape["data"] == 2 and mesh.shape["fsdp"] == 4
    # the mesh is usable: a jitted sharded add runs on it
    from jax.sharding import NamedSharding, PartitionSpec as P

    x = jax.device_put(
        np.arange(8, dtype=np.float32), NamedSharding(mesh, P(("data", "fsdp")))
    )
    np.testing.assert_allclose(np.asarray(jax.jit(lambda v: v + 1)(x)), np.arange(8) + 1)


def test_hybrid_mesh_data_must_cover_slices(capture_hybrid, eight_devices):
    fakes = _fake_two_slices(eight_devices)
    # data=1 cannot span 2 slices -> fsdp would have to cross DCN: refuse
    with pytest.raises(ValueError, match="only the pure data axis"):
        make_mesh(MeshConfig(data=1, fsdp=8), fakes)


def test_hybrid_mesh_data_larger_than_slice_count(capture_hybrid, eight_devices):
    """data may exceed the slice count: the surplus stays ICI-local."""
    fakes = _fake_two_slices(eight_devices)
    mesh = make_mesh(MeshConfig(data=4, fsdp=2), fakes)
    ici, dcn = capture_hybrid[0]
    assert dcn[MESH_AXES.index("data")] == 2
    assert ici[MESH_AXES.index("data")] == 2  # 2 data replicas inside each slice
    assert mesh.shape["data"] == 4 and mesh.shape["fsdp"] == 2
