"""Schema regression guard for the observability surfaces.

Pins the key set of ``/v1/stats`` (engine ``stats_snapshot()`` plus the
server-added fields) and the metric-name set of ``/metrics`` (Prometheus
text exposition), so a rename or an accidentally dropped counter breaks a
test instead of a dashboard. Uses a stub generator — the engines' stats
plumbing is host-side only, so no model is needed to read an idle
engine's schema.
"""

import re

import pytest

from llm_fine_tune_distributed_tpu.infer.engine import (
    ContinuousBatchingEngine,
    PagedContinuousBatchingEngine,
)
from llm_fine_tune_distributed_tpu.observe.metrics import (
    PROMETHEUS_CONTENT_TYPE,
    ServingStats,
    prometheus_exposition,
)


class _StubGenerator:
    """Just enough surface for engine construction; the worker idles on an
    empty queue and never touches the device."""

    _multihost = False
    eos_token_ids = ()
    has_draft = False


def _make(kind):
    if kind == "paged":
        return PagedContinuousBatchingEngine(
            _StubGenerator(), slots=2, buf_len=64, prompt_bucket=16,
            block_len=16, prefill_chunk=32,
        )
    return ContinuousBatchingEngine(
        _StubGenerator(), slots=2, buf_len=64, prompt_bucket=16
    )


# The /v1/stats contract: engine snapshot keys + the fields infer/server.py
# adds ("engine", "device_memory"). Grow-only: extend this set when adding
# telemetry; removals/renames are breaking changes to scrapers.
SNAPSHOT_KEYS = {
    # counters
    "tokens_served", "requests_admitted", "requests_completed",
    "requests_abandoned", "decode_steps",
    "prompt_tokens", "prefix_tokens_reused", "prefill_chunks",
    "engine_restarts", "requests_failed",
    "requests_shed_overflow", "requests_shed_deadline",
    "draft_tokens_proposed", "draft_tokens_accepted",
    # gauges
    "queue_depth", "live_slots", "engine_generation",
    "blocks_in_use", "peak_blocks_in_use", "prefix_cache_blocks",
    # derived
    "tokens_per_s_1m", "uptime_s", "slots", "slot_occupancy",
    "prefix_hit_rate", "draft_acceptance_rate", "mean_tokens_per_step",
    "histograms",
    # supervision (engine.stats_snapshot)
    "circuit_state", "draining",
}
PAGED_ONLY_KEYS = {
    "total_blocks", "block_pool_occupancy", "peak_block_pool_occupancy",
}
HISTOGRAM_KEYS = {
    "ttft_s", "inter_token_s", "queue_wait_s",
    "decode_tick_s", "prefill_chunk_s", "spec_run_len",
}
SUMMARY_KEYS = {"count", "mean", "p50", "p90", "p99"}


@pytest.mark.parametrize("kind", ["continuous", "paged"])
def test_stats_snapshot_key_schema(kind):
    snap = _make(kind).stats_snapshot()
    expected = SNAPSHOT_KEYS - {"engine", "device_memory"}
    if kind == "paged":
        expected = expected | PAGED_ONLY_KEYS
    assert set(snap) == expected
    assert set(snap["histograms"]) == HISTOGRAM_KEYS
    for name in HISTOGRAM_KEYS:
        assert set(snap["histograms"][name]) == SUMMARY_KEYS


# The /metrics contract: every # TYPE line the exposition emits for a paged
# engine snapshot + live histograms + a (fake) two-device memory report.
EXPECTED_METRICS = {
    ("serving_info", "gauge"),
    # counters
    ("serving_tokens_served_total", "counter"),
    ("serving_requests_admitted_total", "counter"),
    ("serving_requests_completed_total", "counter"),
    ("serving_requests_abandoned_total", "counter"),
    ("serving_decode_steps_total", "counter"),
    ("serving_prompt_tokens_total", "counter"),
    ("serving_prefix_tokens_reused_total", "counter"),
    ("serving_prefill_chunks_total", "counter"),
    ("serving_engine_restarts_total", "counter"),
    ("serving_requests_failed_total", "counter"),
    ("serving_requests_shed_overflow_total", "counter"),
    ("serving_requests_shed_deadline_total", "counter"),
    ("serving_draft_tokens_proposed_total", "counter"),
    ("serving_draft_tokens_accepted_total", "counter"),
    # gauges
    ("serving_queue_depth", "gauge"),
    ("serving_live_slots", "gauge"),
    ("serving_engine_generation", "gauge"),
    ("serving_blocks_in_use", "gauge"),
    ("serving_peak_blocks_in_use", "gauge"),
    ("serving_prefix_cache_blocks", "gauge"),
    ("serving_tokens_per_s_1m", "gauge"),
    ("serving_uptime_seconds", "gauge"),
    ("serving_slots", "gauge"),
    ("serving_slot_occupancy", "gauge"),
    ("serving_total_blocks", "gauge"),
    ("serving_block_pool_occupancy", "gauge"),
    ("serving_peak_block_pool_occupancy", "gauge"),
    ("serving_prefix_hit_rate", "gauge"),
    ("serving_draft_acceptance_rate", "gauge"),
    ("serving_mean_tokens_per_step", "gauge"),
    ("serving_draining", "gauge"),
    # histograms (trailing _s -> _seconds; spec_run_len is unitless)
    ("serving_ttft_seconds", "histogram"),
    ("serving_inter_token_seconds", "histogram"),
    ("serving_queue_wait_seconds", "histogram"),
    ("serving_decode_tick_seconds", "histogram"),
    ("serving_prefill_chunk_seconds", "histogram"),
    ("serving_spec_run_len", "histogram"),
    # per-device HBM
    ("device_hbm_bytes_in_use", "gauge"),
    ("device_hbm_peak_bytes_in_use", "gauge"),
    ("device_hbm_bytes_limit", "gauge"),
}

FAKE_MEMORY = {
    "0": {"bytes_in_use": 10, "peak_bytes_in_use": 20, "bytes_limit": 100},
    "1": {"bytes_in_use": 11, "peak_bytes_in_use": 21, "bytes_limit": 100},
}


def test_metrics_exposition_schema():
    engine = _make("paged")
    snap = {"engine": "paged", **engine.stats_snapshot()}
    text = prometheus_exposition(snap, engine.stats.hist, memory=FAKE_MEMORY)
    typed = {
        (m.group(1), m.group(2))
        for m in re.finditer(r"^# TYPE (\S+) (\S+)$", text, re.M)
    }
    assert typed == EXPECTED_METRICS
    assert "version=0.0.4" in PROMETHEUS_CONTENT_TYPE


def test_metrics_exposition_well_formed():
    """Every non-comment line parses as ``name{labels} value`` with a finite
    numeric value — the shape a Prometheus scraper requires."""
    engine = _make("paged")
    engine.stats.incr("tokens_served", 5)
    engine.stats.observe("ttft_s", 0.12)
    snap = {"engine": "paged", **engine.stats_snapshot()}
    text = prometheus_exposition(snap, engine.stats.hist, memory=FAKE_MEMORY)
    assert text.endswith("\n")
    sample = re.compile(
        r'^[a-zA-Z_][a-zA-Z0-9_]*(\{[a-zA-Z_][a-zA-Z0-9_]*="[^"]*"'
        r'(,[a-zA-Z_][a-zA-Z0-9_]*="[^"]*")*\})? [^ ]+$'
    )
    for line in text.splitlines():
        if line.startswith("#"):
            continue
        assert sample.match(line), line
        value = line.rsplit(" ", 1)[1]
        if value != "+Inf":
            float(value)
    assert "serving_tokens_served_total 5" in text
    assert 'serving_info{' in text and 'engine="paged"' in text
    assert 'device_hbm_bytes_in_use{device="0"} 10' in text
    # the served TTFT observation landed in a cumulative bucket
    assert re.search(r'serving_ttft_seconds_bucket\{le="0\.1024"\} 0', text)
    assert re.search(r'serving_ttft_seconds_bucket\{le="0\.2048"\} 1', text)
    assert "serving_ttft_seconds_count 1" in text


def test_window_fallback_exposition():
    """The window engine has no ServingStats; the server's reduced snapshot
    still renders a valid exposition (no histograms, no paged keys)."""
    text = prometheus_exposition(
        {"engine": "window", "queue_depth": 0, "max_batch": 8}, None, memory={}
    )
    assert 'serving_info{engine="window"} 1' in text
    assert "serving_queue_depth 0" in text
    assert "histogram" not in text
