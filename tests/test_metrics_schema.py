"""Schema regression guard for the observability surfaces.

Pins the key set of ``/v1/stats`` (engine ``stats_snapshot()`` plus the
server-added fields) and the metric-name set of ``/metrics`` (Prometheus
text exposition), so a rename or an accidentally dropped counter breaks a
test instead of a dashboard. Uses a stub generator — the engines' stats
plumbing is host-side only, so no model is needed to read an idle
engine's schema.
"""

import re

import pytest

from llm_fine_tune_distributed_tpu.infer.engine import (
    ContinuousBatchingEngine,
    PagedContinuousBatchingEngine,
)
from llm_fine_tune_distributed_tpu.infer.fleet import EngineFleet
from llm_fine_tune_distributed_tpu.observe.metrics import (
    FLEET_COUNTERS,
    PROMETHEUS_CONTENT_TYPE,
    ServingStats,
    prometheus_exposition,
)


class _StubGenerator:
    """Just enough surface for engine construction; the worker idles on an
    empty queue and never touches the device."""

    _multihost = False
    eos_token_ids = ()
    has_draft = False


def _make(kind):
    if kind == "paged":
        return PagedContinuousBatchingEngine(
            _StubGenerator(), slots=2, buf_len=64, prompt_bucket=16,
            block_len=16, prefill_chunk=32,
        )
    return ContinuousBatchingEngine(
        _StubGenerator(), slots=2, buf_len=64, prompt_bucket=16
    )


# The /v1/stats contract: engine snapshot keys + the fields infer/server.py
# adds ("engine", "device_memory"). Grow-only: extend this set when adding
# telemetry; removals/renames are breaking changes to scrapers.
SNAPSHOT_KEYS = {
    # counters
    "tokens_served", "requests_admitted", "requests_completed",
    "requests_abandoned", "decode_steps",
    "prompt_tokens", "prefix_tokens_reused", "prefill_chunks",
    "engine_restarts", "requests_failed",
    "requests_shed_overflow", "requests_shed_deadline",
    "draft_tokens_proposed", "draft_tokens_accepted",
    "adapter_loads", "adapter_evictions", "requests_shed_tenant_quota",
    # live deployment (infer/deploy.py): applied hot-swaps / rollback swaps
    "weight_swaps", "weight_rollbacks",
    # overload control (infer/engine.py): KV-pressure slot preemptions and
    # client-deadline cancellations that had already consumed decode work
    "preemptions", "requests_shed_deadline_decode",
    # overload control: tier name -> requests shed from that tier
    "requests_shed_by_tier",
    # capacity observatory (observe/capacity.py): tokens that reached a
    # successful settle, the reason-keyed waste map for the rest, and the
    # derived goodput/(goodput+waste) ratio
    "goodput_tokens", "wasted_tokens_by_reason", "goodput_fraction",
    # tiered KV (infer/paged.HostBlockTier): spill/discard split on
    # eviction, restore hit/miss split at admission, adopted migrations
    "prefix_blocks_spilled", "prefix_blocks_discarded",
    "host_tier_restore_hits", "host_tier_restore_misses",
    "slots_migrated",
    # disaggregated prefill/decode (infer/engine.py): the prompt/decode
    # token attribution split and the handoff outcome counters
    "prefill_tokens", "decode_tokens",
    "requests_handed_off", "requests_handoff_failed",
    # gauges
    "queue_depth", "live_slots", "engine_generation", "weight_generation",
    # overload control: the brownout controller's current stage (0-3)
    "brownout_stage",
    "blocks_in_use", "peak_blocks_in_use", "prefix_cache_blocks",
    "adapters_resident",
    # quantized serving: resident weight bytes and KV-pool bytes (the full
    # breakdown with scale overhead rides /v1/stats device_memory_report)
    "weight_bytes", "kv_pool_bytes",
    # tiered KV: bytes resident in the (process-shared) host block tier
    "host_tier_bytes",
    # multi-tenant LoRA: tenant -> {requests, tokens, queue_depth}
    "per_tenant",
    # derived
    "tokens_per_s_1m", "uptime_s", "slots", "slot_occupancy",
    "prefix_hit_rate", "draft_acceptance_rate", "mean_tokens_per_step",
    "histograms",
    # supervision (engine.stats_snapshot)
    "circuit_state", "draining",
    # disaggregation: this replica's pool role (mixed/prefill/decode) —
    # a string, so it rides the info/replica_info label lines
    "role",
    # XLA introspection (engine.stats_snapshot): the compile-ledger
    # sub-snapshot and the roofline utilization gauges
    "compile", "model_flops_utilization", "hbm_bandwidth_utilization",
    # SLO engine (observe/slo.py): the burn-rate report over the metric
    # ring, and settled-request latency/error slices keyed by the weight
    # generation the request resolved under
    "slo", "per_generation",
}
PAGED_ONLY_KEYS = {
    "total_blocks", "block_pool_occupancy", "peak_block_pool_occupancy",
}
HISTOGRAM_KEYS = {
    "ttft_s", "inter_token_s", "queue_wait_s",
    "decode_tick_s", "prefill_chunk_s", "spec_run_len",
}
SUMMARY_KEYS = {"count", "mean", "p50", "p90", "p99"}


@pytest.mark.parametrize("kind", ["continuous", "paged"])
def test_stats_snapshot_key_schema(kind):
    snap = _make(kind).stats_snapshot()
    expected = SNAPSHOT_KEYS - {"engine", "device_memory"}
    if kind == "paged":
        expected = expected | PAGED_ONLY_KEYS
    assert set(snap) == expected
    assert set(snap["histograms"]) == HISTOGRAM_KEYS
    for name in HISTOGRAM_KEYS:
        assert set(snap["histograms"][name]) == SUMMARY_KEYS


# The /metrics contract: every # TYPE line the exposition emits for a paged
# engine snapshot + live histograms + a (fake) two-device memory report.
EXPECTED_METRICS = {
    ("serving_info", "gauge"),
    # counters
    ("serving_tokens_served_total", "counter"),
    ("serving_requests_admitted_total", "counter"),
    ("serving_requests_completed_total", "counter"),
    ("serving_requests_abandoned_total", "counter"),
    ("serving_decode_steps_total", "counter"),
    ("serving_prompt_tokens_total", "counter"),
    ("serving_prefix_tokens_reused_total", "counter"),
    ("serving_prefill_chunks_total", "counter"),
    ("serving_engine_restarts_total", "counter"),
    ("serving_requests_failed_total", "counter"),
    ("serving_requests_shed_overflow_total", "counter"),
    ("serving_requests_shed_deadline_total", "counter"),
    ("serving_draft_tokens_proposed_total", "counter"),
    ("serving_draft_tokens_accepted_total", "counter"),
    ("serving_adapter_loads_total", "counter"),
    ("serving_adapter_evictions_total", "counter"),
    ("serving_requests_shed_tenant_quota_total", "counter"),
    ("serving_weight_swaps_total", "counter"),
    ("serving_weight_rollbacks_total", "counter"),
    # overload control (tier="..." labels on the shed-by-tier counter; TYPE
    # lines emitted even at stage 0 so the schema is load-independent)
    ("serving_preemptions_total", "counter"),
    ("serving_requests_shed_deadline_decode_total", "counter"),
    ("serving_requests_shed_tier_total", "counter"),
    # capacity observatory: goodput vs reason-labelled waste split and the
    # replica-count gauge (1 for a single engine — a fleet of one)
    ("serving_goodput_tokens_total", "counter"),
    ("serving_wasted_tokens_total", "counter"),
    ("serving_goodput_fraction", "gauge"),
    ("serving_replica_count", "gauge"),
    # tiered KV: spill/discard counters, the raw hit/miss counters plus
    # their result="hit|miss" rollup, migration adoptions, resident bytes
    ("serving_prefix_blocks_spilled_total", "counter"),
    ("serving_prefix_blocks_discarded_total", "counter"),
    ("serving_host_tier_restore_hits_total", "counter"),
    ("serving_host_tier_restore_misses_total", "counter"),
    ("serving_host_tier_restores_total", "counter"),
    ("serving_slots_migrated_total", "counter"),
    ("serving_host_tier_bytes", "gauge"),
    # disaggregated prefill/decode: token attribution split and handoff
    # outcome counters
    ("serving_prefill_tokens_total", "counter"),
    ("serving_decode_tokens_total", "counter"),
    ("serving_requests_handed_off_total", "counter"),
    ("serving_requests_handoff_failed_total", "counter"),
    # per-tenant series (tenant="name" labels; TYPE lines are emitted even
    # with zero tenants so the schema is load-independent)
    ("serving_tenant_requests_total", "counter"),
    ("serving_tenant_tokens_total", "counter"),
    ("serving_tenant_queue_depth", "gauge"),
    # gauges
    ("serving_queue_depth", "gauge"),
    ("serving_live_slots", "gauge"),
    ("serving_engine_generation", "gauge"),
    ("serving_weight_generation", "gauge"),
    ("serving_adapters_resident", "gauge"),
    ("serving_blocks_in_use", "gauge"),
    ("serving_peak_blocks_in_use", "gauge"),
    ("serving_prefix_cache_blocks", "gauge"),
    ("serving_tokens_per_s_1m", "gauge"),
    ("serving_uptime_seconds", "gauge"),
    ("serving_slots", "gauge"),
    ("serving_slot_occupancy", "gauge"),
    ("serving_total_blocks", "gauge"),
    ("serving_block_pool_occupancy", "gauge"),
    ("serving_peak_block_pool_occupancy", "gauge"),
    ("serving_prefix_hit_rate", "gauge"),
    ("serving_draft_acceptance_rate", "gauge"),
    ("serving_mean_tokens_per_step", "gauge"),
    ("serving_draining", "gauge"),
    ("serving_brownout_stage", "gauge"),
    ("serving_weight_bytes", "gauge"),
    ("serving_kv_pool_bytes", "gauge"),
    # XLA introspection: per-program compile counters (program="..."
    # labels; TYPE lines emitted even with an empty ledger) + roofline
    # utilization gauges
    ("serving_compiles_total", "counter"),
    ("serving_compile_seconds_total", "counter"),
    ("serving_recompiles_after_warmup_total", "counter"),
    ("serving_model_flops_utilization", "gauge"),
    ("serving_hbm_bandwidth_utilization", "gauge"),
    # SLO engine: overall compliance + one burn-rate sample per
    # {objective, window}; per-generation settled counts and latency p99s
    ("serving_slo_compliant", "gauge"),
    ("serving_slo_burn_rate", "gauge"),
    ("serving_generation_requests_completed_total", "counter"),
    ("serving_generation_requests_failed_total", "counter"),
    ("serving_generation_ttft_p99_seconds", "gauge"),
    ("serving_generation_inter_token_p99_seconds", "gauge"),
    # per-tenant latency histograms (tenant="name" bucket series; TYPE
    # lines emitted whenever a tenant-histogram map is passed, even empty)
    ("serving_tenant_ttft_seconds", "histogram"),
    ("serving_tenant_inter_token_seconds", "histogram"),
    # histograms (trailing _s -> _seconds; spec_run_len is unitless)
    ("serving_ttft_seconds", "histogram"),
    ("serving_inter_token_seconds", "histogram"),
    ("serving_queue_wait_seconds", "histogram"),
    ("serving_decode_tick_seconds", "histogram"),
    ("serving_prefill_chunk_seconds", "histogram"),
    ("serving_spec_run_len", "histogram"),
    # per-device HBM
    ("device_hbm_bytes_in_use", "gauge"),
    ("device_hbm_peak_bytes_in_use", "gauge"),
    ("device_hbm_bytes_limit", "gauge"),
}

FAKE_MEMORY = {
    "0": {"bytes_in_use": 10, "peak_bytes_in_use": 20, "bytes_limit": 100},
    "1": {"bytes_in_use": 11, "peak_bytes_in_use": 21, "bytes_limit": 100},
}


def test_metrics_exposition_schema():
    engine = _make("paged")
    snap = {"engine": "paged", **engine.stats_snapshot()}
    text = prometheus_exposition(
        snap, engine.stats.hist, memory=FAKE_MEMORY,
        tenant_histograms=engine.stats.tenant_histograms(),
    )
    typed = {
        (m.group(1), m.group(2))
        for m in re.finditer(r"^# TYPE (\S+) (\S+)$", text, re.M)
    }
    assert typed == EXPECTED_METRICS
    assert "version=0.0.4" in PROMETHEUS_CONTENT_TYPE


def test_metrics_exposition_well_formed():
    """Every non-comment line parses as ``name{labels} value`` with a finite
    numeric value — the shape a Prometheus scraper requires."""
    engine = _make("paged")
    engine.stats.incr("tokens_served", 5)
    engine.stats.observe("ttft_s", 0.12)
    engine.stats.tenant_observe("acme", "ttft_s", 0.12)
    snap = {"engine": "paged", **engine.stats_snapshot()}
    text = prometheus_exposition(
        snap, engine.stats.hist, memory=FAKE_MEMORY,
        tenant_histograms=engine.stats.tenant_histograms(),
    )
    assert text.endswith("\n")
    sample = re.compile(
        r'^[a-zA-Z_][a-zA-Z0-9_]*(\{[a-zA-Z_][a-zA-Z0-9_]*="[^"]*"'
        r'(,[a-zA-Z_][a-zA-Z0-9_]*="[^"]*")*\})? [^ ]+$'
    )
    for line in text.splitlines():
        if line.startswith("#"):
            continue
        assert sample.match(line), line
        value = line.rsplit(" ", 1)[1]
        if value != "+Inf":
            float(value)
    assert "serving_tokens_served_total 5" in text
    assert 'serving_info{' in text and 'engine="paged"' in text
    assert 'device_hbm_bytes_in_use{device="0"} 10' in text
    # the served TTFT observation landed in a cumulative bucket
    assert re.search(r'serving_ttft_seconds_bucket\{le="0\.1024"\} 0', text)
    assert re.search(r'serving_ttft_seconds_bucket\{le="0\.2048"\} 1', text)
    assert "serving_ttft_seconds_count 1" in text
    # overload control: every tier has a shed sample even with zero sheds,
    # and the brownout gauge reports stage 0 on a healthy engine
    assert 'serving_requests_shed_tier_total{tier="interactive"} 0' in text
    assert 'serving_requests_shed_tier_total{tier="batch"} 0' in text
    assert 'serving_requests_shed_tier_total{tier="best_effort"} 0' in text
    assert "serving_brownout_stage 0" in text
    # capacity observatory: every waste reason has a sample even with zero
    # waste, goodput reads 1.0 at zero traffic ("no waste yet" is the
    # healthy reading), and a single engine is a fleet of one
    for reason in ServingStats.WASTE_REASONS:
        assert f'serving_wasted_tokens_total{{reason="{reason}"}} 0' in text
    assert "serving_goodput_fraction 1" in text
    assert "serving_replica_count 1" in text


# The fleet /v1/stats contract: everything a single paged engine reports,
# aggregated, plus the router-level keys and the per-replica map.
FLEET_EXTRA_KEYS = {
    "replicas", "routing", "healthy_replicas", "available_replicas",
    "per_replica",
    # elastic fleet: replicas retired so far (their final counters live on
    # in the aggregate via the retired accumulator)
    "replicas_retired",
    # router counters (EngineFleet.ROUTER_COUNTERS == metrics.FLEET_COUNTERS)
    "requests_routed_prefix_affinity", "requests_routed_adapter_affinity",
    "requests_routed_least_loaded",
    "requests_routed_round_robin", "requests_failed_over",
    "requests_rerouted_overflow", "requests_shed_fleet_saturated",
    "requests_shed_fleet_brownout",
    # disaggregation: role -> {replicas, prefill_tokens, decode_tokens}
    # aggregation (fleet-only; single engines have no role mix to report)
    "tokens_by_role",
}

# The fleet /metrics contract: the single-engine TYPE set plus the router
# counters, the replica-count gauges, and the per-replica info line. The
# per-replica samples reuse the SAME metric names with a replica label, so
# they add no TYPE lines beyond these.
FLEET_EXPECTED_METRICS = EXPECTED_METRICS | {
    ("serving_replica_info", "gauge"),
    ("serving_replicas", "gauge"),
    ("serving_replicas_retired", "gauge"),
    ("serving_healthy_replicas", "gauge"),
    ("serving_available_replicas", "gauge"),
    ("serving_requests_routed_prefix_affinity_total", "counter"),
    ("serving_requests_routed_adapter_affinity_total", "counter"),
    ("serving_requests_routed_least_loaded_total", "counter"),
    ("serving_requests_routed_round_robin_total", "counter"),
    ("serving_requests_failed_over_total", "counter"),
    ("serving_requests_rerouted_overflow_total", "counter"),
    ("serving_requests_shed_fleet_saturated_total", "counter"),
    ("serving_requests_shed_fleet_brownout_total", "counter"),
    # disaggregation: role-labelled token split + per-role replica counts
    ("serving_role_prefill_tokens_total", "counter"),
    ("serving_role_decode_tokens_total", "counter"),
    ("serving_role_replicas", "gauge"),
}


def test_fleet_counter_lists_agree():
    """The router counters live in two modules by design (the fleet owns
    them, the exposition types them); they must never drift."""
    assert set(FLEET_COUNTERS) == set(EngineFleet.ROUTER_COUNTERS)


def test_fleet_stats_snapshot_key_schema():
    fleet = EngineFleet([_make("paged"), _make("paged")], routing="prefix")
    snap = fleet.stats_snapshot()
    single = SNAPSHOT_KEYS | PAGED_ONLY_KEYS
    assert set(snap) == single | FLEET_EXTRA_KEYS
    assert set(snap["per_replica"]) == {"0", "1"}
    # each per-replica entry is EXACTLY a single-engine snapshot + its label
    for label, rsnap in snap["per_replica"].items():
        assert set(rsnap) == single | {"replica"}
        assert rsnap["replica"] == int(label)
    assert set(snap["histograms"]) == HISTOGRAM_KEYS


def test_fleet_metrics_exposition_replica_labels():
    """Fleet /metrics: one TYPE line per metric name, an aggregate sample,
    then the same metric with replica="i" per replica — counters, gauges,
    and histogram buckets alike."""
    fleet = EngineFleet([_make("paged"), _make("paged")], routing="prefix")
    fleet.replicas[0].stats.incr("tokens_served", 3)
    fleet.replicas[1].stats.incr("tokens_served", 4)
    fleet.replicas[0].stats.observe("ttft_s", 0.12)
    snap = {"engine": "paged", **fleet.stats_snapshot()}
    per = snap.pop("per_replica")  # mirrors the infer/server.py handler
    series = [
        (label, per[label], fleet.replicas[int(label)].stats.hist)
        for label in sorted(per, key=int)
    ]
    text = prometheus_exposition(
        snap, fleet.merged_histograms(), memory=FAKE_MEMORY, replicas=series,
        tenant_histograms=fleet.merged_tenant_histograms(),
    )
    typed = {
        (m.group(1), m.group(2))
        for m in re.finditer(r"^# TYPE (\S+) (\S+)$", text, re.M)
    }
    assert typed == FLEET_EXPECTED_METRICS
    # aggregate sample + one labelled sample per replica, counters...
    assert "serving_tokens_served_total 7" in text
    assert 'serving_tokens_served_total{replica="0"} 3' in text
    assert 'serving_tokens_served_total{replica="1"} 4' in text
    # ...gauges, histogram buckets/sums, and the per-replica info line
    assert 'serving_slots{replica="0"} 2' in text
    assert re.search(
        r'serving_ttft_seconds_bucket\{replica="0",le="0\.2048"\} 1', text
    )
    assert 'serving_ttft_seconds_count{replica="1"} 0' in text
    assert "serving_ttft_seconds_count 1" in text  # merged aggregate
    assert 'serving_replica_info{replica="0",circuit_state="closed"' in text
    # exactly one TYPE line per metric name (the format forbids repeats)
    names = re.findall(r"^# TYPE (\S+) ", text, re.M)
    assert len(names) == len(set(names))


def test_tenant_series_schema_and_labels():
    """Multi-tenant telemetry: the per-tenant key set is pinned
    (ServingStats.TENANT_KEYS), tenant samples carry tenant="name" labels,
    and the TYPE lines exist even with ZERO tenants (schema must not
    depend on traffic)."""
    assert ServingStats.TENANT_KEYS == ("requests", "tokens", "queue_depth")
    engine = _make("paged")
    # zero tenants: TYPE lines present, no samples
    snap = {"engine": "paged", **engine.stats_snapshot()}
    assert snap["per_tenant"] == {}
    text = prometheus_exposition(snap, engine.stats.hist, memory=FAKE_MEMORY)
    assert "# TYPE serving_tenant_requests_total counter" in text
    assert "# TYPE serving_tenant_tokens_total counter" in text
    assert "# TYPE serving_tenant_queue_depth gauge" in text
    assert "serving_tenant_requests_total{" not in text
    # two tenants: labelled samples under the same TYPE lines
    engine.stats.tenant_incr("acme", "requests")
    engine.stats.tenant_incr("acme", "tokens", 42)
    engine.stats.tenant_incr("beta", "requests")
    snap = {"engine": "paged", **engine.stats_snapshot()}
    assert set(snap["per_tenant"]) == {"acme", "beta"}
    assert set(snap["per_tenant"]["acme"]) == set(ServingStats.TENANT_KEYS)
    text = prometheus_exposition(snap, engine.stats.hist, memory=FAKE_MEMORY)
    assert 'serving_tenant_requests_total{tenant="acme"} 1' in text
    assert 'serving_tenant_tokens_total{tenant="acme"} 42' in text
    assert 'serving_tenant_queue_depth{tenant="acme"} 0' in text
    assert 'serving_tenant_requests_total{tenant="beta"} 1' in text
    # tenant_incr floors at zero (double-release guard)
    engine.stats.tenant_incr("acme", "queue_depth", -5)
    assert engine.stats_snapshot()["per_tenant"]["acme"]["queue_depth"] == 0


def test_tenant_histogram_series_labels():
    """Per-tenant latency histograms: TYPE lines appear whenever a map is
    passed (even empty), and a tenant's observations render as
    tenant-labelled cumulative buckets under them."""
    engine = _make("paged")
    snap = {"engine": "paged", **engine.stats_snapshot()}
    # zero tenants: bare TYPE lines, no bucket samples
    text = prometheus_exposition(
        snap, engine.stats.hist, memory=FAKE_MEMORY, tenant_histograms={}
    )
    assert "# TYPE serving_tenant_ttft_seconds histogram" in text
    assert "# TYPE serving_tenant_inter_token_seconds histogram" in text
    assert "serving_tenant_ttft_seconds_bucket{" not in text
    # observed tenants get labelled buckets; an engine-level histogram
    # observation must NOT leak into the tenant series
    engine.stats.observe("ttft_s", 0.12)
    engine.stats.tenant_observe("acme", "ttft_s", 0.12)
    engine.stats.tenant_observe("acme", "inter_token_s", 0.01)
    engine.stats.tenant_observe("beta", "ttft_s", 3.0)
    snap = {"engine": "paged", **engine.stats_snapshot()}
    text = prometheus_exposition(
        snap, engine.stats.hist, memory=FAKE_MEMORY,
        tenant_histograms=engine.stats.tenant_histograms(),
    )
    assert re.search(
        r'serving_tenant_ttft_seconds_bucket\{tenant="acme",le="0\.2048"\} 1',
        text,
    )
    assert 'serving_tenant_ttft_seconds_count{tenant="acme"} 1' in text
    assert 'serving_tenant_ttft_seconds_count{tenant="beta"} 1' in text
    assert 'serving_tenant_inter_token_seconds_count{tenant="acme"} 1' in text
    assert 'serving_tenant_inter_token_seconds_count{tenant="beta"} 0' in text


def test_slo_and_generation_exposition_samples():
    """SLO engine surfaces: an idle engine reports a compliant SLO over
    the four pinned objectives, a generation-0 slice exists from boot, and
    both render as the pinned gauge/series names."""
    engine = _make("paged")
    snap = {"engine": "paged", **engine.stats_snapshot()}
    assert snap["slo"]["compliant"] is True
    assert set(snap["slo"]["objectives"]) == {
        "ttft_p99", "inter_token_p99", "error_rate", "availability",
    }
    for obj in snap["slo"]["objectives"].values():
        assert set(obj["windows"]) == {"fast", "slow"}
    assert "0" in snap["per_generation"]
    text = prometheus_exposition(
        snap, engine.stats.hist, memory=FAKE_MEMORY,
        tenant_histograms=engine.stats.tenant_histograms(),
    )
    assert "serving_slo_compliant 1" in text
    assert (
        'serving_slo_burn_rate{objective="error_rate",window="fast"} 0'
        in text
    )
    assert (
        'serving_slo_burn_rate{objective="ttft_p99",window="slow"} 0'
        in text
    )
    assert (
        'serving_generation_requests_completed_total{generation="0"} 0'
        in text
    )
    assert (
        'serving_generation_ttft_p99_seconds{generation="0"} 0' in text
    )


def test_every_stats_counter_and_gauge_is_exported():
    """Coverage guard: every ServingStats counter renders as a typed
    ``serving_<name>_total`` counter and every gauge as a typed gauge in
    the exposition — adding a stat without exporting it breaks here, not
    on a dashboard."""
    from llm_fine_tune_distributed_tpu.observe.metrics import _prom_name

    engine = _make("paged")
    snap = {"engine": "paged", **engine.stats_snapshot()}
    text = prometheus_exposition(
        snap, engine.stats.hist, memory=FAKE_MEMORY,
        tenant_histograms=engine.stats.tenant_histograms(),
    )
    for name in ServingStats.COUNTERS:
        prom = _prom_name(name, "serving")
        assert f"# TYPE {prom}_total counter" in text, name
    for name in ServingStats.GAUGES:
        prom = _prom_name(name, "serving")
        assert f"# TYPE {prom} gauge" in text, name
    for name in ServingStats.HISTOGRAM_SPECS:
        prom = _prom_name(name, "serving")
        assert f"# TYPE {prom} histogram" in text, name


def test_fleet_merges_per_tenant_across_replicas():
    """A tenant's counters sum across the replicas its traffic landed on."""
    fleet = EngineFleet([_make("paged"), _make("paged")], routing="prefix")
    fleet.replicas[0].stats.tenant_incr("acme", "tokens", 3)
    fleet.replicas[1].stats.tenant_incr("acme", "tokens", 4)
    fleet.replicas[1].stats.tenant_incr("beta", "requests")
    snap = fleet.stats_snapshot()
    assert snap["per_tenant"]["acme"]["tokens"] == 7
    assert snap["per_tenant"]["beta"]["requests"] == 1


def test_window_fallback_exposition():
    """The window engine has no ServingStats; the server's reduced snapshot
    still renders a valid exposition (no histograms, no paged keys)."""
    text = prometheus_exposition(
        {"engine": "window", "queue_depth": 0, "max_batch": 8}, None, memory={}
    )
    assert 'serving_info{engine="window"} 1' in text
    assert "serving_queue_depth 0" in text
    assert "histogram" not in text


# --------------------------------------------------------------------------
# Trainer exposition (observe/trainplane.trainer_exposition): the /metrics
# surface of the training control plane. Same drift-guard contract as the
# serving set above: pin every # TYPE line, grow-only.
#
# The tenant / shed-tier / compile TYPE lines below are NOT trainer metrics
# — prometheus_exposition emits them unconditionally (load-independence
# contract), so they appear under the ``training_`` prefix too, bare.
TRAINER_EXPECTED_METRICS = {
    ("training_info", "gauge"),
    # counters (trainplane.TRAIN_COUNTERS)
    ("training_evals_total", "counter"),
    ("training_checkpoints_saved_total", "counter"),
    ("training_publishes_total", "counter"),
    ("training_publishes_skipped_dirty_total", "counter"),
    ("training_watchdog_trips_total", "counter"),
    # kind-labelled anomaly counter, every kind seeded at 0
    ("training_anomalies_total", "counter"),
    # gauges (trainplane.TRAIN_GAUGES)
    ("training_step", "gauge"),
    ("training_total_steps", "gauge"),
    ("training_epoch", "gauge"),
    ("training_epochs", "gauge"),
    ("training_loss", "gauge"),
    ("training_learning_rate", "gauge"),
    ("training_grad_norm", "gauge"),
    ("training_eval_loss", "gauge"),
    ("training_best_eval", "gauge"),
    ("training_samples_per_second", "gauge"),
    ("training_samples_per_second_per_chip", "gauge"),
    ("training_steps_per_second", "gauge"),
    ("training_tokens_per_second_per_chip", "gauge"),
    ("training_real_tokens_per_second_per_chip", "gauge"),
    ("training_packing_efficiency", "gauge"),
    ("training_preempted", "gauge"),
    ("training_model_flops_utilization", "gauge"),
    ("training_hbm_bandwidth_utilization", "gauge"),
    # unconditional exposition-machinery TYPE lines (no trainer samples)
    ("training_tenant_requests_total", "counter"),
    ("training_tenant_tokens_total", "counter"),
    ("training_tenant_queue_depth", "gauge"),
    ("training_requests_shed_tier_total", "counter"),
    # compile-ledger series (program="..." labels)
    ("training_compiles_total", "counter"),
    ("training_compile_seconds_total", "counter"),
    ("training_recompiles_after_warmup_total", "counter"),
    # phase histograms (train-loop phase_hist; _s -> _seconds)
    ("training_data_wait_seconds", "histogram"),
    ("training_step_seconds", "histogram"),
    ("training_checkpoint_seconds", "histogram"),
}


def _make_telemetry():
    from llm_fine_tune_distributed_tpu.observe.tracing import Histogram
    from llm_fine_tune_distributed_tpu.observe.trainplane import (
        TRAIN_HIST_KEYS,
        TrainTelemetry,
    )
    from llm_fine_tune_distributed_tpu.observe.xla import CompileLedger

    telemetry = TrainTelemetry(run_id="run-schema", hparams={"lr": 1e-4})
    telemetry.attach(
        phase_hist={k: Histogram.exponential() for k in TRAIN_HIST_KEYS},
        compile_ledger=CompileLedger(),
    )
    return telemetry


def test_trainer_exposition_schema():
    from llm_fine_tune_distributed_tpu.observe.trainplane import (
        trainer_exposition,
    )

    text = trainer_exposition(_make_telemetry(), memory={})
    typed = {
        (m.group(1), m.group(2))
        for m in re.finditer(r"^# TYPE (\S+) (\S+)$", text, re.M)
    }
    assert typed == TRAINER_EXPECTED_METRICS
    # exactly one TYPE line per metric name (the format forbids repeats)
    names = re.findall(r"^# TYPE (\S+) ", text, re.M)
    assert len(names) == len(set(names))
    # load-independence: every anomaly kind is seeded on a healthy run
    from llm_fine_tune_distributed_tpu.observe.trainplane import ANOMALY_KINDS

    for kind in ANOMALY_KINDS:
        assert f'training_anomalies_total{{kind="{kind}"}} 0' in text


def test_trainer_exposition_every_counter_and_gauge_exported():
    """Coverage guard: every TRAIN_COUNTERS entry renders as a typed
    ``training_<name>_total`` counter with a sample, and every TRAIN_GAUGES
    entry as a typed gauge with a sample — adding trainer telemetry without
    exporting it breaks here, not on a dashboard."""
    from llm_fine_tune_distributed_tpu.observe.metrics import _prom_name
    from llm_fine_tune_distributed_tpu.observe.trainplane import (
        TRAIN_COUNTERS,
        TRAIN_GAUGES,
        trainer_exposition,
    )

    text = trainer_exposition(_make_telemetry(), memory={})
    for name in TRAIN_COUNTERS:
        prom = _prom_name(name, "training")
        assert f"# TYPE {prom}_total counter" in text, name
        assert re.search(rf"^{prom}_total \d", text, re.M), name
    for name in TRAIN_GAUGES:
        prom = _prom_name(name, "training")
        assert f"# TYPE {prom} gauge" in text, name
        assert re.search(rf"^{prom} ", text, re.M), name
    # identity strings collapse into the info line
    assert 'run_id="run-schema"' in text
    assert 'hparams_digest="' in text and 'state="' in text


def test_trainer_exposition_well_formed_and_live_values():
    """Same scraper-shape contract as the serving exposition, over a
    telemetry that has actually seen steps, counters, and an anomaly."""
    telemetry = _make_telemetry()
    telemetry.on_step(10, {"loss": float("nan")})
    telemetry.on_step(12, {"loss": 2.0, "learning_rate": 1e-4,
                           "grad_norm": 1.5})
    telemetry.incr("checkpoints_saved")
    telemetry.phase_hist["step"].observe(0.05)
    from llm_fine_tune_distributed_tpu.observe.trainplane import (
        trainer_exposition,
    )

    text = trainer_exposition(telemetry, memory=FAKE_MEMORY)
    assert text.endswith("\n")
    sample = re.compile(
        r'^[a-zA-Z_][a-zA-Z0-9_]*(\{[a-zA-Z_][a-zA-Z0-9_]*="[^"]*"'
        r'(,[a-zA-Z_][a-zA-Z0-9_]*="[^"]*")*\})? [^ ]+$'
    )
    for line in text.splitlines():
        if line.startswith("#"):
            continue
        assert sample.match(line), line
        value = line.rsplit(" ", 1)[1]
        if value != "+Inf":
            float(value)
    assert "\ntraining_loss 2\n" in text
    assert "\ntraining_step 12\n" in text
    assert "training_checkpoints_saved_total 1" in text
    assert 'training_anomalies_total{kind="non_finite"} 1' in text
    assert "training_step_seconds_count 1" in text
    assert 'device_hbm_bytes_in_use{device="0"} 10' in text
