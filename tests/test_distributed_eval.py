"""Distributed eval (VERDICT r1 #8): validation work is sharded over the
data-parallel axes — per-device FLOPs shrink ~1/dp — while the token-weighted
eval loss stays equal to the single-device result, and the whole sweep runs
as one staged scan program."""

import numpy as np
import pytest

import jax

from llm_fine_tune_distributed_tpu.config import MeshConfig

from tests.test_train_e2e import make_config, qa_parquet  # noqa: F401 (fixture)


@pytest.fixture(scope="module")
def trainers(qa_parquet, tmp_path_factory):  # noqa: F811
    from llm_fine_tune_distributed_tpu.train.trainer import SFTTrainer

    data_dir, dataset_file = qa_parquet
    tmp = tmp_path_factory.mktemp("eval_out")
    solo = SFTTrainer(
        make_config(tmp / "solo", data_dir, dataset_file,
                    mesh=MeshConfig(data=1, fsdp=1, tensor=1, seq=1))
    )
    sharded = SFTTrainer(
        make_config(tmp / "shard", data_dir, dataset_file,
                    mesh=MeshConfig(data=2, fsdp=4, tensor=1, seq=1))
    )
    return solo, sharded


def test_eval_loss_equal_across_meshes(trainers):
    solo, sharded = trainers
    l1 = solo.evaluate()
    l8 = sharded.evaluate()
    assert np.isfinite(l1)
    # same params (same init seed), same data -> same token-weighted loss up
    # to reduction order (~1e-4 on this jax/XLA's f32 cross-device reduce;
    # a real weighting bug would shift the ~6.3 loss by orders more)
    assert l8 == pytest.approx(l1, abs=5e-4)
    # staged slabs were built exactly once and reused
    assert solo._staged_eval is not None
    again = sharded.evaluate()
    assert again == pytest.approx(l8, abs=0)


def test_eval_work_shards_over_dp(trainers):
    """Per-device validation work on the dp=8 mesh is ~1/dp: each device
    holds (and, under SPMD, computes on) only its shard of the staged
    batches, and the compiled program carries the cross-device all-reduce
    that sums (ce, tokens)."""
    solo, sharded = trainers
    solo.evaluate()
    sharded.evaluate()

    def rows_per_device(trainer):
        ids = trainer._staged_eval["input_ids"]  # [nb, bs, seq]
        shard = ids.addressable_shards[0].data
        return shard.shape[0] * shard.shape[1]

    r1, r8 = rows_per_device(solo), rows_per_device(sharded)
    # 10 val rows: solo stages 5x2 rows on one device; the dp=8 mesh pads to
    # 16 and gives each device 2 — a 1/5 cut (1/dp up to tail padding)
    assert r8 * 4 <= r1, f"per-device eval rows {r8} vs single-device {r1}"

    compiled = sharded._eval_all.lower(
        sharded.state, sharded._staged_eval
    ).compile().as_text()
    assert "all-reduce" in compiled, (
        "sharded eval program has no cross-device reduction — the "
        "(ce, tokens) sums are not being psum'd"
    )
