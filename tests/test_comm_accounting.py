"""Collective-byte accounting of the compiled sharded train step, per mesh.

The v5e-16 scaling claim (BASELINE.md) cannot be wall-clocked here (one real
chip), so its evidence is compiled-program facts: for each target mesh, the
optimized HLO's per-step collective bytes must match the analytic cost of the
parallelism strategy. ``observe/comm_accounting.py`` extracts the bytes (with
loop trip-count multipliers); these tests pin them against the expectations:

- DP        : gradient all-reduce on the data axis ~ 2 x (g-1)/g x trainable
              bytes per accumulation microbatch, and nothing else.
- FSDP      : param all-gathers on the fsdp axis, bounded by fwd+bwd per
              microbatch + optimizer re-gather; grad sync on fsdp (XLA's CPU
              partitioner emits it as all-reduce + slice; TPU lowers the same
              pattern to reduce-scatter — the accounted bytes are the upper
              bound of the two).
- TP        : activation psums on the tensor axis, ~2 per block per direction
              per microbatch (Megatron pairing).
- SP (ring) : K/V collective-permutes on the seq axis every attention step.
- PP        : exactly 2 x (M + S - 1) stage-boundary ppermutes per step
              (GPipe fwd + its transposed bwd), plus the output psum-scatter.
- EP        : dispatch/combine all-reduces on the expert axis.

Every collective must also *attribute* to a mesh axis (no "?" rows): an
unattributable replica group means the partitioner built groups that cross
axes in ways the design does not predict — exactly the regression this file
exists to catch.

Baseline being beaten: the reference pays one NCCL ring all-reduce of ALL
trainable grads per step on 4 GPUs (reference ``training.py:285``,
``deploy/pytorchjob.yaml:51-64``) with no sharding, no overlap accounting.
"""

import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from llm_fine_tune_distributed_tpu.observe.comm_accounting import (
    account_compiled,
    account_text,
)
from llm_fine_tune_distributed_tpu.observe.scaling import abstract_train_setup
from llm_fine_tune_distributed_tpu.utils.compat import (
    make_mesh as compat_make_mesh,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _leaf_bytes(leaf) -> int:
    return int(np.prod(leaf.shape)) * leaf.dtype.itemsize


def _bytes_where(flat: dict, axis: str) -> int:
    """Bytes of leaves whose sharding spec mentions ``axis``."""
    total = 0
    for leaf in flat.values():
        spec = getattr(leaf.sharding, "spec", ())
        flat_axes = set()
        for entry in spec:
            for a in (entry if isinstance(entry, tuple) else (entry,)):
                flat_axes.add(a)
        if axis in flat_axes:
            total += _leaf_bytes(leaf)
    return total


def _ar(bytes_, g):
    return 2 * bytes_ * (g - 1) / g


# ------------------------------------------------------------------ unit: parser


def test_parser_exact_on_known_program(eight_devices):
    """A hand-built FSDP matmul step with a 3-trip scan: the parser must
    recover the exact collective set, axis attribution, and trip counts."""
    mesh = compat_make_mesh((2, 4), ("data", "fsdp"))
    W = jax.ShapeDtypeStruct(
        (512, 512), jnp.float32, sharding=NamedSharding(mesh, P("fsdp", None))
    )
    xs = jax.ShapeDtypeStruct(
        (3, 16, 512),
        jnp.float32,
        sharding=NamedSharding(mesh, P(None, ("data", "fsdp"), None)),
    )

    def step(w, xs):
        def body(carry, x):
            g = jax.grad(lambda w, x: jnp.mean((x @ w) ** 2))(w, x)
            return carry + g, ()

        acc, _ = jax.lax.scan(body, jnp.zeros_like(w), xs)
        return w - 0.1 * acc

    rep = account_compiled(jax.jit(step).lower(W, xs).compile(), mesh)
    by = {}
    for c in rep.collectives:
        by.setdefault((c.kind, c.axes), []).append(c)

    # weight all-gather: loop-invariant, hoisted out (count 1), full W bytes
    (ag,) = by[("all-gather", ("fsdp",))]
    assert ag.count == 1
    assert ag.result_bytes == 512 * 512 * 4
    assert ag.wire_bytes == pytest.approx(512 * 512 * 4 * 3 / 4)
    # grad sync inside the scan: count 3 (known_trip_count multiplier)
    for c in by[("all-reduce", ("fsdp",))] + by[("all-reduce", ("data",))]:
        assert c.count == 3
    assert ("?",) not in {c.axes for c in rep.collectives}


def test_iota_replica_group_decode():
    """The [ng,gs]<=[dims]T(perm) notation decodes to real device groups."""
    from llm_fine_tune_distributed_tpu.observe.comm_accounting import (
        _parse_replica_groups,
    )

    assert _parse_replica_groups("replica_groups={{0,1},{2,3}}") == [[0, 1], [2, 3]]
    assert _parse_replica_groups("replica_groups=[2,4]<=[8]") == [
        [0, 1, 2, 3],
        [4, 5, 6, 7],
    ]
    assert _parse_replica_groups("replica_groups=[4,2]<=[2,4]T(1,0)") == [
        [0, 4],
        [1, 5],
        [2, 6],
        [3, 7],
    ]


@pytest.mark.slow
def test_trip_count_multiplier_scales_with_accum(eight_devices):
    """Doubling grad accumulation must ~double loop-body collective bytes —
    the direct check that the known_trip_count multiplier is applied."""
    w2 = abstract_train_setup({"data": 8}, accum=2).comm_report().total_wire_bytes()
    w4 = abstract_train_setup({"data": 8}, accum=4).comm_report().total_wire_bytes()
    assert 1.7 < w4 / w2 < 2.3


# ------------------------------------------------------------- per-mesh volumes


def test_dp_mesh_volume(eight_devices):
    """Pure DP: only gradient all-reduces, only on the data axis."""
    s = abstract_train_setup({"data": 8}, accum=2)
    rep = s.comm_report()
    assert {c.axes for c in rep.collectives} == {("data",)}
    assert set(rep.wire_bytes_by_kind()) == {"all-reduce"}
    # per-microbatch grad AR (the scan's carry sync; TPU's all-reduce-sinking
    # pass can only shrink this) + the embedding-gather grad scatter
    lo = _ar(s.trainable_bytes, 8)
    hi = 2 * _ar(s.trainable_bytes, 8) * 1.5
    assert lo <= rep.total_wire_bytes() <= hi


@pytest.mark.slow
def test_dp_fsdp_mesh_volume(eight_devices):
    s = abstract_train_setup({"data": 2, "fsdp": 4}, accum=2)
    rep = s.comm_report()
    assert ("?",) not in {c.axes for c in rep.collectives}

    sharded = _bytes_where(s.state.trainable, "fsdp") + _bytes_where(
        s.state.frozen, "fsdp"
    )
    ag = rep.filter(kind="all-gather", axes=("fsdp",))
    # params gathered >= once and <= (fwd+bwd) x accum + optimizer re-gather
    assert sharded * 3 / 4 <= ag.total_wire_bytes() <= sharded * 3 / 4 * (2 * 2 + 1)

    # grad sync on fsdp: all-reduce (CPU partitioner) or reduce-scatter (TPU)
    sync = rep.filter(kind="all-reduce", axes=("fsdp",)).total_wire_bytes()
    sync += rep.filter(kind="reduce-scatter", axes=("fsdp",)).total_wire_bytes()
    assert sync > 0
    # data-axis AR moves the fsdp-scattered grad shard per microbatch
    dp_ar = rep.filter(kind="all-reduce", axes=("data",)).total_wire_bytes()
    assert _ar(s.trainable_bytes / 4, 2) * 0.5 <= dp_ar <= _ar(s.trainable_bytes, 2) * 2 * 1.5


@pytest.mark.slow
def test_fsdp_tp_mesh_volume(eight_devices):
    s = abstract_train_setup({"fsdp": 4, "tensor": 2}, accum=2)
    rep = s.comm_report()
    assert ("?",) not in {c.axes for c in rep.collectives}

    # Megatron psums on tensor: >= 2 per block per microbatch (fwd), with bwd
    # and remat adding at most 3x more
    L = s.model_config.num_layers
    tp_ar = rep.filter(kind="all-reduce", axes=("tensor",))
    n_psums = sum(c.count for c in tp_ar.collectives)
    assert n_psums >= 2 * L * 2
    # activation psum bytes: [rows_local, seq, h] each, f32 activations
    dp = 4
    rows = s.batch["input_ids"].shape[1] // dp
    seq = s.batch["input_ids"].shape[2]
    h = s.model_config.hidden_size
    one = 2 * rows * seq * h * 4 * (2 - 1) / 2  # AR cost of one [rows,seq,h] f32
    assert tp_ar.total_wire_bytes() <= one * 2 * L * 2 * 4  # <= 4x fwd count
    ag = rep.filter(kind="all-gather", axes=("fsdp",))
    assert ag.total_wire_bytes() > 0


@pytest.mark.slow
def test_seq_mesh_has_ring_permutes(eight_devices):
    s = abstract_train_setup(
        {"fsdp": 2, "tensor": 2, "seq": 2},
        accum=2,
        train_kwargs={"attention_impl": "ring"},
    )
    rep = s.comm_report()
    assert ("?",) not in {c.axes for c in rep.collectives}
    perm = rep.filter(kind="collective-permute", axes=("seq",))
    L = s.model_config.num_layers
    # ring rotation: (seq_axis - 1) = 1 K/V rotation per attention, per layer,
    # per microbatch, fwd + bwd(remat recompute + transpose)
    n = sum(c.count for c in perm.collectives)
    assert n >= L * 2 * 2
    assert perm.total_wire_bytes() > 0


@pytest.mark.slow
def test_pipeline_mesh_exact_permute_schedule(eight_devices):
    M, S = 4, 2
    s = abstract_train_setup({"pipe": S, "fsdp": 4}, accum=M)
    rep = s.comm_report()
    assert ("?",) not in {c.axes for c in rep.collectives}

    perm = rep.filter(kind="collective-permute", axes=("pipe",))
    # GPipe: M + S - 1 ticks forward; jax.grad's transpose replays them
    # backward -> exactly 2(M + S - 1) boundary ppermutes per step
    assert sum(c.count for c in perm.collectives) == 2 * (M + S - 1)
    # each moves exactly one [mb_local, seq, h] boundary activation (dtype is
    # the compiled program's choice: bf16 on TPU, f32 where XLA keeps the
    # residual stream wide — infer the itemsize rather than assume)
    rows = s.batch["input_ids"].shape[1] // 4
    seq = s.batch["input_ids"].shape[2]
    h = s.model_config.hidden_size
    itemsize = perm.collectives[0].result_bytes // (rows * seq * h)
    assert itemsize in (2, 4)
    assert perm.total_wire_bytes() == pytest.approx(
        2 * (M + S - 1) * rows * seq * h * itemsize, rel=0.01
    )
    # last-stage output collection: psum-scatter + transpose's all-gather
    assert rep.filter(kind="reduce-scatter", axes=("pipe",)).total_wire_bytes() > 0
    assert rep.filter(kind="all-gather", axes=("pipe",)).total_wire_bytes() > 0


@pytest.mark.slow
def test_ep_mesh_volume(eight_devices):
    s = abstract_train_setup(
        {"data": 2, "expert": 4},
        preset="tiny_moe",
        accum=2,
        train_kwargs={"freeze_strategy": "none"},
    )
    rep = s.comm_report()
    assert ("?",) not in {c.axes for c in rep.collectives}
    # GShard einsum dispatch/combine: psums on the expert axis both directions
    ep_ar = rep.filter(kind="all-reduce", axes=("expert",))
    assert sum(c.count for c in ep_ar.collectives) >= 2 * 2  # >= dispatch+combine per microbatch
    assert ep_ar.total_wire_bytes() > 0
    # gradient sync still rides data
    assert rep.filter(kind="all-reduce", axes=("data",)).total_wire_bytes() > 0


@pytest.mark.slow
def test_pipe_ep_mesh_has_both_axes(eight_devices):
    """pipe x EP: the compiled schedule keeps expert parallelism ACTIVE
    inside stages — expert-axis psums appear alongside the pipe ppermutes
    (were experts gathered/replicated at shard_map entry, the expert axis
    would carry only the trivial top-k gathers)."""
    s = abstract_train_setup(
        {"pipe": 2, "expert": 2, "fsdp": 2},
        preset="tiny_moe",
        accum=4,
        train_kwargs={"freeze_strategy": "none"},
    )
    rep = s.comm_report()
    assert ("?",) not in {c.axes for c in rep.collectives}
    ep_ar = rep.filter(kind="all-reduce", axes=("expert",))
    assert sum(c.count for c in ep_ar.collectives) >= 2 * 4  # dispatch+combine per tick
    perm = rep.filter(kind="collective-permute", axes=("pipe",))
    assert sum(c.count for c in perm.collectives) == 2 * (4 + 2 - 1)
    # the expert weights are never all-gathered whole (EP's memory win): any
    # expert-axis gather traffic stays far below one full gather of the
    # stacked expert bytes
    expert_bytes = sum(
        int(np.prod(v.shape)) * v.dtype.itemsize
        for k, v in s.state.trainable.items()
        if "/experts/" in k and k.endswith(("w1", "w2", "w3"))
    )
    ag = rep.filter(kind="all-gather", axes=("expert",)).total_wire_bytes()
    assert ag < expert_bytes / 4, (ag, expert_bytes)


@pytest.mark.slow
def test_pipe_ring_mesh_has_both_rings(eight_devices):
    """pipe x ring: the compiled schedule carries BOTH permute families —
    stage-boundary ppermutes on pipe (exactly 2(M+S-1), now of seq-chunked
    activations) and K/V rotation permutes on seq (per layer per tick)."""
    M, S = 4, 2
    s = abstract_train_setup(
        {"pipe": S, "fsdp": 2, "seq": 2},
        accum=M,
        train_kwargs={"attention_impl": "ring"},
    )
    rep = s.comm_report()
    assert ("?",) not in {c.axes for c in rep.collectives}
    pipe_perm = rep.filter(kind="collective-permute", axes=("pipe",))
    assert sum(c.count for c in pipe_perm.collectives) == 2 * (M + S - 1)
    # boundary activations are seq-chunked: [mb_local, seq/2, h]
    rows = s.batch["input_ids"].shape[1] // 2
    seq_local = s.batch["input_ids"].shape[2] // 2
    h = s.model_config.hidden_size
    itemsize = pipe_perm.collectives[0].result_bytes // (rows * seq_local * h)
    assert itemsize in (2, 4)
    seq_perm = rep.filter(kind="collective-permute", axes=("seq",))
    L = s.model_config.num_layers
    # (seq-1)=1 K/V rotation per layer per tick, fwd + bwd replay
    assert sum(c.count for c in seq_perm.collectives) >= L * (M + S - 1)


# ------------------------------------------------------------- 16-device probe

_PROBE_16 = r"""
import os, sys
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=16"
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
from llm_fine_tune_distributed_tpu.observe.scaling import abstract_train_setup

def ar(b, g):
    return 2 * b * (g - 1) / g

# dp x fsdp at v5e-16 scale
s = abstract_train_setup({"data": 2, "fsdp": 8}, accum=2)
rep = s.comm_report()
assert ("?",) not in {c.axes for c in rep.collectives}
ag = rep.filter(kind="all-gather", axes=("fsdp",)).total_wire_bytes()
assert ag > 0
sync = rep.filter(kind="all-reduce", axes=("fsdp",)).total_wire_bytes() + \
       rep.filter(kind="reduce-scatter", axes=("fsdp",)).total_wire_bytes()
assert sync > 0
print("PROBE16 dpxfsdp OK", int(rep.total_wire_bytes()))

# fsdp x tp at v5e-16 scale
s2 = abstract_train_setup({"fsdp": 8, "tensor": 2}, accum=2)
rep2 = s2.comm_report()
assert ("?",) not in {c.axes for c in rep2.collectives}
assert rep2.filter(kind="all-reduce", axes=("tensor",)).total_wire_bytes() > 0
print("PROBE16 fsdpxtp OK", int(rep2.total_wire_bytes()))
"""


@pytest.mark.slow
def test_16_device_meshes_account_clean():
    """The v5e-16-sized meshes (16 virtual devices need their own process)
    compile and account with full axis attribution."""
    proc = subprocess.run(
        [sys.executable, "-c", _PROBE_16],
        capture_output=True,
        text=True,
        timeout=900,
        cwd=REPO,
        env={**os.environ, "PYTHONPATH": REPO},
    )
    assert proc.returncode == 0, proc.stderr[-3000:]
    assert "PROBE16 dpxfsdp OK" in proc.stdout
    assert "PROBE16 fsdpxtp OK" in proc.stdout


def test_projection_math():
    """project_step_time: compute term from the measured single-chip rate,
    comm term from wire bytes over the link model, efficiencies consistent."""
    from llm_fine_tune_distributed_tpu.observe.comm_accounting import (
        Collective,
        CommReport,
    )
    from llm_fine_tune_distributed_tpu.observe.scaling import project_step_time

    # one FSDP all-gather of 90 GB wire -> exactly 1 s at the 90 GB/s ring
    rep = CommReport([
        Collective(
            kind="all-gather", computation="main", result_bytes=0,
            group_size=16, axes=("fsdp",), count=1,
        )
    ])
    rep.collectives[0].result_bytes = int(90e9 * 16 / 15)  # wire = b*(g-1)/g
    proj = project_step_time(
        rep, {"fsdp": 16},
        single_chip_samples_per_sec=10.0, samples_per_step=160,
    )
    assert proj.compute_s == pytest.approx(1.0)      # 160 / (10 x 16)
    assert proj.exposed_comm_s == pytest.approx(1.0, rel=1e-6)
    assert proj.step_s == pytest.approx(2.0)
    assert proj.samples_per_sec == pytest.approx(80.0)
    assert proj.scaling_efficiency == pytest.approx(0.5)

    # full overlap hides all communication
    proj_ovl = project_step_time(
        rep, {"fsdp": 16},
        single_chip_samples_per_sec=10.0, samples_per_step=160,
        overlap_fraction=1.0,
    )
    assert proj_ovl.samples_per_sec == pytest.approx(160.0)

    # a data axis marked as DCN uses the slow link
    rep2 = CommReport([
        Collective(
            kind="collective-permute", computation="main",
            result_bytes=int(6.25e9), group_size=2, axes=("data",), count=1,
        )
    ])
    proj_dcn = project_step_time(
        rep2, {"data": 16},
        single_chip_samples_per_sec=10.0, samples_per_step=160,
        dcn_axes=("data",),
    )
    assert proj_dcn.exposed_comm_s == pytest.approx(1.0, rel=1e-6)
