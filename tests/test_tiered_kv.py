"""Tiered KV resilience (infer/paged.py HostBlockTier + infer/engine.py
spill/restore + infer/fleet.py live slot migration).

What this file pins, layer by layer:

- ``HostBlockTier`` is a byte-bounded LRU: the bound holds across every
  put, a get refreshes recency, an oversized or disabled put is refused
  (never raises), and entries spilled under a different weight
  fingerprint read as misses — stale KV is never data;
- a spill -> host -> restore round trip is BIT-exact for both fp and
  int8 pools (codes and their scale siblings travel as one entry);
- prefix-cache eviction spills through the product path and a later
  admission restores from the tier instead of re-prefilling, greedy
  bit-identical to solo ``generate_ids``;
- every restore failure — injected fault, cleared tier — degrades to
  re-prefill with IDENTICAL greedy output (slower, never wrong), and an
  injected spill fault degrades to a counted discard;
- ``export_requests`` banks a live request preempt-style and
  ``adopt_request`` resumes it, end-to-end tokens bit-identical;
- fleet ``migrate_slot`` moves a mid-flight stream to a sibling replica
  (the waiter never reconnects), settled on EXACTLY one replica;
- an injected migrate fault re-adopts on the source (no drop, no double
  settle, no hung waiter) and ``retire_replica`` falls back to
  drain-wait instead of raising;
- ``retire_replica`` with migration empties a replica without waiting
  for its longest request.
"""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llm_fine_tune_distributed_tpu.data.tokenizer import ByteChatMLTokenizer
from llm_fine_tune_distributed_tpu.infer import (
    EngineFleet,
    GenerationConfig,
    Generator,
)
from llm_fine_tune_distributed_tpu.infer.engine import PagedContinuousBatchingEngine
from llm_fine_tune_distributed_tpu.infer.paged import HostBlockTier
from llm_fine_tune_distributed_tpu.models.configs import get_preset
from llm_fine_tune_distributed_tpu.models.transformer import init_params

GREEDY = GenerationConfig(max_new_tokens=6, do_sample=False)
GREEDY48 = GenerationConfig(max_new_tokens=48, do_sample=False)


@pytest.fixture(scope="module")
def generator():
    mc = get_preset("tiny")
    params = init_params(jax.random.PRNGKey(0), mc, dtype=jnp.float32)
    return Generator(
        params, mc, ByteChatMLTokenizer(), compute_dtype=jnp.float32, eos_token_ids=[]
    )


def _enc(text):
    return ByteChatMLTokenizer().encode(text)


def _wait(cond, timeout=30.0):
    deadline = time.monotonic() + timeout
    while not cond():
        if time.monotonic() > deadline:
            raise TimeoutError("condition not reached")
        time.sleep(0.005)


def _tiered(generator, tier=None, **kw):
    kw.setdefault("slots", 2)
    kw.setdefault("buf_len", 256)
    kw.setdefault("prompt_bucket", 64)
    kw.setdefault("block_len", 16)
    kw.setdefault("prefill_chunk", 256)
    return PagedContinuousBatchingEngine(
        generator, host_tier=tier if tier is not None else HostBlockTier(64 << 20),
        **kw,
    )


def _tiered_fleet(generator, n=2, **fleet_kw):
    """Fleet of paged replicas sharing ONE HostBlockTier — the sharing IS
    the migration transport (server.py wires it the same way)."""
    tier = HostBlockTier(64 << 20)
    return EngineFleet(
        [
            _tiered(
                generator, tier=tier, slots=4,
                restart_backoff_s=0.01, restart_backoff_max_s=0.02,
            )
            for _ in range(n)
        ],
        routing="prefix",
        **fleet_kw,
    ), tier


# a prompt spanning >= 2 full 16-token blocks, so spills have blocks to move
VICTIM_TEXT = "a forty-ish token victim prompt for host tier spills"


# ------------------------------------------------------------- tier unit


def test_host_tier_lru_byte_bound_and_refresh():
    row = lambda fill: [np.full(128, fill, np.uint8), np.full(128, fill, np.uint8)]
    tier = HostBlockTier(1024)  # exactly 4 entries of 256 bytes
    keys = [bytes([i]) for i in range(5)]
    for i, k in enumerate(keys[:4]):
        assert tier.put(k, row(i))
    assert len(tier) == 4 and tier.bytes_used == 1024
    tier.get(keys[0])  # refresh: k0 is now most-recent
    assert tier.put(keys[4], row(4))  # evicts LRU = k1, NOT k0
    assert tier.bytes_used <= 1024
    assert tier.get(keys[1]) is None
    assert tier.get(keys[0]) is not None and tier.get(keys[4]) is not None
    # re-put refreshes in place (no double-count of bytes)
    assert tier.put(keys[0], row(9))
    assert tier.bytes_used <= 1024
    assert int(tier.get(keys[0])[0][0]) == 9
    # an entry that alone exceeds capacity is refused, pool untouched
    before = tier.bytes_used
    assert not tier.put(b"huge", [np.zeros(4096, np.uint8)])
    assert tier.bytes_used == before
    # disabled tier refuses everything
    assert not HostBlockTier(0).put(b"k", row(0))
    tier.discard(keys[0])
    assert tier.get(keys[0]) is None
    tier.clear()
    assert len(tier) == 0 and tier.bytes_used == 0


def test_host_tier_fingerprint_stale_reads_as_miss():
    tier = HostBlockTier(1 << 20)
    rows = [np.arange(8, dtype=np.float32)]
    assert tier.put(b"k1", rows, fingerprint=b"gen1")
    assert tier.put(b"k2", rows, fingerprint=b"gen1")
    # the right fingerprint restores; any other — including None — misses
    assert tier.get(b"k1", fingerprint=b"gen1") is not None
    assert tier.get(b"k1", fingerprint=b"gen2") is None
    assert tier.get(b"k1") is None
    assert tier.resident_run([b"k1", b"k2"], fingerprint=b"gen1") == 2
    assert tier.resident_run([b"k1", b"k2"], fingerprint=b"gen2") == 0
    # resident_run counts the LEADING restorable run only
    assert tier.put(b"k3", rows, fingerprint=b"gen2")
    assert tier.resident_run([b"k1", b"k3"], fingerprint=b"gen1") == 1


# ---------------------------------------------------- device round trip


@pytest.mark.parametrize("kv_quant", ["none", "int8"])
def test_spill_restore_round_trip_bit_exact(generator, kv_quant):
    """gather -> host tier -> scatter into FRESH pool rows -> gather again
    reproduces every pool leaf bit-for-bit. For int8 the entry carries the
    code blocks AND their scale siblings as a unit, so a restored block is
    identical including its quantization history."""
    eng = _tiered(generator, kv_quant=kv_quant)
    prompt = _enc(VICTIM_TEXT)
    assert len(prompt) >= 32
    eng.submit(prompt, GREEDY, timeout=240)  # fills pool + prefix cache
    keys = eng._prefix.block_keys(prompt)
    bids = eng._prefix.match(keys, limit=len(keys))
    assert len(bids) == len(keys) >= 2
    orig = eng._gather_blocks(bids)
    if kv_quant == "int8":
        # code + scale siblings really are distinct leaves of one entry
        dtypes = {r.dtype for r in orig[0]}
        assert np.dtype(np.int8) in dtypes and len(dtypes) >= 2
    eng._spill_to_tier(list(zip(keys, bids)))
    snap = eng.stats_snapshot()
    assert snap["prefix_blocks_spilled"] == len(keys)
    assert snap["prefix_blocks_discarded"] == 0
    entries = [
        eng._host_tier.get(k, fingerprint=eng._weight_fingerprint) for k in keys
    ]
    assert all(e is not None for e in entries)
    fresh = eng._allocator.alloc(len(keys))
    eng._scatter_blocks(fresh, entries)
    for back_rows, orig_rows in zip(eng._gather_blocks(fresh), orig):
        assert len(back_rows) == len(orig_rows)
        for b, o in zip(back_rows, orig_rows):
            assert b.dtype == o.dtype
            np.testing.assert_array_equal(b, o)
    for bid in list(fresh) + list(bids):
        eng._allocator.free(bid)


def test_spill_fault_degrades_to_counted_discard(generator):
    eng = _tiered(generator)
    prompt = _enc(VICTIM_TEXT)
    eng.submit(prompt, GREEDY, timeout=240)
    keys = eng._prefix.block_keys(prompt)
    bids = eng._prefix.match(keys, limit=len(keys))
    eng.faults.fail_spill_next(1)
    eng._spill_to_tier(list(zip(keys, bids)))  # must NOT raise
    snap = eng.stats_snapshot()
    assert snap["prefix_blocks_spilled"] == 0
    assert snap["prefix_blocks_discarded"] == len(keys)
    assert len(eng._host_tier) == 0
    # fault self-disarms: the next spill lands
    eng._spill_to_tier(list(zip(keys, bids)))
    assert eng.stats_snapshot()["prefix_blocks_spilled"] == len(keys)
    for bid in bids:
        eng._allocator.free(bid)


# --------------------------------------------- evict -> restore -> decode


def _evict_and_spill(eng):
    """The exact product sequence from ``_plan`` under block pressure:
    evict the HBM prefix cache collecting the dropped (key, block) pairs,
    then spill them to the host tier before any reallocation."""
    dropped = []
    eng._prefix.evict(eng._num_blocks, collect=dropped)
    eng._spill_to_tier(dropped)
    return len(dropped)


def test_evicted_prefix_restores_from_tier_bit_identical(generator):
    eng = _tiered(generator)
    prompt = _enc(VICTIM_TEXT)
    solo = generator.generate_ids(prompt, GREEDY)
    assert eng.submit(prompt, GREEDY, timeout=240) == solo
    assert _evict_and_spill(eng) >= 2
    assert len(eng._prefix) == 0 and len(eng._host_tier) >= 2
    # re-admission restores from the tier instead of re-prefilling, and
    # decodes over the restored KV to the identical greedy tokens
    assert eng.submit(prompt, GREEDY, timeout=240) == solo
    snap = eng.stats_snapshot()
    assert snap["host_tier_restore_hits"] >= 2
    assert snap["host_tier_restore_misses"] == 0
    assert snap["host_tier_bytes"] > 0


def test_restore_fault_and_tier_miss_fall_back_to_reprefill(generator):
    eng = _tiered(generator)
    prompt = _enc(VICTIM_TEXT)
    solo = generator.generate_ids(prompt, GREEDY)
    assert eng.submit(prompt, GREEDY, timeout=240) == solo
    # injected scatter fault: restore aborts, blocks are returned to the
    # pool, the plan re-prefills — identical output, misses counted
    _evict_and_spill(eng)
    eng.faults.fail_restore_next(1)
    assert eng.submit(prompt, GREEDY, timeout=240) == solo
    snap = eng.stats_snapshot()
    assert snap["host_tier_restore_misses"] >= 2
    assert snap["host_tier_restore_hits"] == 0
    assert any(ev["kind"] == "restore_failed" for ev in eng.recorder.events())
    # total miss (tier emptied out from under the cache): plain re-prefill
    _evict_and_spill(eng)
    eng._host_tier.clear()
    assert eng.submit(prompt, GREEDY, timeout=240) == solo


# ------------------------------------------------------- export / adopt


def test_export_banks_and_adopt_resumes_bit_identical(generator):
    """export_requests on a mid-decode stream banks preempt-style (tokens
    + spilled context blocks) without settling; adopt_request resumes it
    on the SAME engine and the ORIGINAL stream iterator runs to the solo
    greedy tokens — the waiter never reconnects."""
    eng = _tiered(generator)
    prompt = _enc(VICTIM_TEXT)
    solo = generator.generate_ids(prompt, GREEDY48)
    stream = eng.stream(prompt, GREEDY48, timeout=240)
    tokens = [next(stream), next(stream)]
    exported = eng.export_requests(timeout=30)
    assert len(exported) == 1
    assert eng.live_slots == 0 and eng.queue_depth == 0
    assert len(exported[0].preempted_tokens) >= 2
    # the banked context spilled: full ingested blocks are in the tier
    assert len(eng._host_tier) >= 2
    assert eng.stats_snapshot()["prefix_blocks_spilled"] >= 2
    assert any(ev["kind"] == "export" for ev in eng.recorder.events())
    eng.adopt_request(exported[0])
    tokens.extend(stream)
    assert tokens == solo
    snap = eng.stats_snapshot()
    assert snap["requests_completed"] == 1
    assert snap["requests_failed"] == 0


def test_export_with_nothing_live_returns_empty(generator):
    eng = _tiered(generator)
    assert eng.export_requests(timeout=30) == []


# --------------------------------------------------------- live migration


def test_migrate_slot_moves_stream_settles_on_exactly_one_replica(generator):
    fleet, _tier = _tiered_fleet(generator, migrate_on_retire=True)
    prompt = _enc(VICTIM_TEXT)
    solo = generator.generate_ids(prompt, GREEDY48)
    stream = fleet.stream(prompt, GREEDY48, timeout=240)
    tokens = [next(stream), next(stream)]
    src = next(rid for rid, rep in fleet.replica_items() if rep.live_slots > 0)
    assert fleet.migrate_slot(src) == 1
    tokens.extend(stream)  # the SAME iterator finishes on the sibling
    assert tokens == solo
    src_rep = dict(fleet.replica_items())[src]
    tgt_rid, tgt_rep = next(
        (rid, rep) for rid, rep in fleet.replica_items() if rid != src
    )
    # settled on exactly one replica: the target completed it, the source
    # kept nothing in flight and counted the adoption nowhere
    assert tgt_rep.stats_snapshot()["slots_migrated"] == 1
    assert tgt_rep.stats_snapshot()["requests_completed"] == 1
    assert src_rep.stats_snapshot()["requests_completed"] == 0
    assert src_rep.live_slots == 0 and src_rep.queue_depth == 0
    # migration re-pins the prompt's prefix affinity onto the target
    assert tgt_rid in set(fleet._prefix_home.values())
    with pytest.raises(ValueError):
        fleet.migrate_slot(src, target_rid=src)
    with pytest.raises(KeyError):
        fleet.migrate_slot(9999)


def test_migrate_fault_readopts_no_drop_no_double_settle(generator):
    """Injected crash mid-migration: the source re-adopts the request,
    the stream completes bit-identical, and EXACTLY one replica settles
    it — no drop, no double count, no hung waiter."""
    fleet, _tier = _tiered_fleet(generator, migrate_on_retire=True)
    prompt = _enc(VICTIM_TEXT)
    solo = generator.generate_ids(prompt, GREEDY48)
    stream = fleet.stream(prompt, GREEDY48, timeout=240)
    tokens = [next(stream), next(stream)]
    src = next(rid for rid, rep in fleet.replica_items() if rep.live_slots > 0)
    reps = dict(fleet.replica_items())
    reps[src].faults.fail_migrate_next(1)
    with pytest.raises(RuntimeError):
        fleet.migrate_slot(src)
    tokens.extend(stream)  # completes locally after the re-adopt
    assert tokens == solo
    completed = [
        rep.stats_snapshot()["requests_completed"] for rep in reps.values()
    ]
    assert sorted(completed) == [0, 1]
    assert all(rep.stats_snapshot()["requests_failed"] == 0 for rep in reps.values())
    assert all(rep.stats_snapshot()["slots_migrated"] == 0 for rep in reps.values())
    # the fault self-disarmed and nothing is stuck: fresh traffic decodes
    assert fleet.submit(_enc("after the storm"), GREEDY, timeout=240)


def test_retire_with_migrate_fault_falls_back_to_drain_wait(generator):
    """retire_replica never propagates a migration failure: the export
    fault re-adopts on the source and retirement degrades to the plain
    drain-wait — slower, never a drop."""
    fleet, _tier = _tiered_fleet(generator, migrate_on_retire=True)
    prompt = _enc(VICTIM_TEXT)
    solo = generator.generate_ids(prompt, GREEDY48)
    stream = fleet.stream(prompt, GREEDY48, timeout=240)
    tokens = [next(stream), next(stream)]
    src = next(rid for rid, rep in fleet.replica_items() if rep.live_slots > 0)
    dict(fleet.replica_items())[src].faults.fail_migrate_next(1)
    fleet.retire_replica(rid=src, timeout_s=120)  # must NOT raise
    assert len(fleet.replicas) == 1
    tokens.extend(stream)
    assert tokens == solo


def test_retire_replica_migrates_active_stream_off(generator):
    """Retirement with migration does not wait for the live request: the
    stream moves to the survivor (slots_migrated proves the path taken —
    a drain-wait would leave it at 0) and completes bit-identical."""
    fleet, _tier = _tiered_fleet(generator, migrate_on_retire=True)
    prompt = _enc(VICTIM_TEXT)
    solo = generator.generate_ids(prompt, GREEDY48)
    stream = fleet.stream(prompt, GREEDY48, timeout=240)
    tokens = [next(stream), next(stream)]
    src = next(rid for rid, rep in fleet.replica_items() if rep.live_slots > 0)
    survivor = next(rep for rid, rep in fleet.replica_items() if rid != src)
    fleet.retire_replica(rid=src, timeout_s=120)
    assert len(fleet.replicas) == 1
    tokens.extend(stream)
    assert tokens == solo
    snap = survivor.stats_snapshot()
    assert snap["slots_migrated"] == 1
    assert snap["requests_completed"] == 1
    # fleet rollup carries the migration and the shared tier's bytes
    fsnap = fleet.stats_snapshot()
    assert fsnap["slots_migrated"] == 1
    assert "host_tier_bytes" in fsnap


def test_migration_with_concurrent_neighbors_all_complete(generator):
    """Evacuating a replica carrying SEVERAL live requests places every
    one of them; all streams finish with their solo greedy tokens."""
    fleet, _tier = _tiered_fleet(generator, migrate_on_retire=True)
    prompts = [
        _enc(VICTIM_TEXT),
        _enc("a second long-context request riding the same replica here"),
    ]
    cfg = GenerationConfig(max_new_tokens=24, do_sample=False)
    solos = [generator.generate_ids(p, cfg) for p in prompts]
    results = [None] * len(prompts)

    def ask(i):
        results[i] = fleet.submit(prompts[i], cfg, timeout=240)

    threads = [threading.Thread(target=ask, args=(i,)) for i in range(len(prompts))]
    for t in threads:
        t.start()
    _wait(lambda: sum(rep.live_slots for rep in fleet.replicas) >= 1)
    # routing may have split them; evacuate whichever replica is busiest
    src = max(fleet.replica_items(), key=lambda kv: kv[1].live_slots)[0]
    moved = fleet.migrate_slot(src)
    assert moved >= 0  # every export either placed or re-adopted
    for t in threads:
        t.join(timeout=240)
    assert results == solos
