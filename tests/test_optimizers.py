"""Optimizer selection: adamw (reference parity) / adafactor / lion all
converge on the tiny model, and adafactor's factored state actually delivers
the optimizer-memory win it exists for."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from llm_fine_tune_distributed_tpu.config import TrainConfig
from llm_fine_tune_distributed_tpu.models.configs import get_preset
from llm_fine_tune_distributed_tpu.models.transformer import init_params
from llm_fine_tune_distributed_tpu.parallel.freeze import trainable_mask
from llm_fine_tune_distributed_tpu.parallel.optimizer import build_optimizer
from llm_fine_tune_distributed_tpu.train.state import TrainState
from llm_fine_tune_distributed_tpu.train.step import build_train_step
from llm_fine_tune_distributed_tpu.utils.tree import split_by_mask


def _run_steps(optimizer_name, n_steps=6):
    config = get_preset("tiny")
    tc = TrainConfig(
        model_preset="tiny",
        optimizer=optimizer_name,
        per_device_batch_size=4,
        gradient_accumulation_steps=1,
        max_seq_length=32,
        learning_rate=3e-3,
        lr_schedule="constant",
        freeze_strategy="none",
        gradient_checkpointing=False,
        attention_impl="xla",
    )
    params = init_params(jax.random.PRNGKey(0), config, dtype=jnp.float32)
    mask = trainable_mask(params, config, tc)
    trainable, frozen = split_by_mask(params, mask)
    optimizer = build_optimizer(tc, None, total_steps=n_steps, data_parallel_size=1)
    state = TrainState(
        step=jnp.zeros((), jnp.int32),
        trainable=trainable,
        frozen=frozen,
        opt_state=optimizer.init(trainable),
    )
    step = jax.jit(build_train_step(config, tc, optimizer))
    rng = np.random.RandomState(0)
    batch = {
        "input_ids": jnp.asarray(rng.randint(0, 512, (1, 4, 32)), jnp.int32),
        "loss_mask": jnp.ones((1, 4, 32), jnp.float32),
        "attention_mask": jnp.ones((1, 4, 32), jnp.int32),
    }
    losses = []
    for _ in range(n_steps):
        state, metrics = step(state, batch)
        losses.append(float(metrics["loss"]))
    return losses, state


@pytest.mark.parametrize("name", ["adamw", "adafactor", "lion"])
def test_optimizer_converges(name):
    losses, _ = _run_steps(name)
    assert all(np.isfinite(losses)), losses
    assert losses[-1] < losses[0], f"{name}: loss did not decrease: {losses}"


def test_adafactor_state_is_factored():
    """Adafactor's second-moment state must be much smaller than the params
    (rows + cols per matrix, not rows * cols). Factoring engages at
    dims >= 128, so check on a realistically-sized matrix."""

    def state_bytes(tree):
        return sum(
            int(np.prod(l.shape)) * l.dtype.itemsize
            for l in jax.tree.leaves(tree)
            if hasattr(l, "shape") and l.ndim > 0
        )

    params = {"w": jnp.zeros((512, 2048), jnp.float32)}
    params_bytes = state_bytes(params)

    ada = build_optimizer(
        TrainConfig(optimizer="adafactor"), None, total_steps=10, data_parallel_size=1
    )
    adam = build_optimizer(
        TrainConfig(optimizer="adamw"), None, total_steps=10, data_parallel_size=1
    )
    ada_bytes = state_bytes(ada.init(params))
    adam_bytes = state_bytes(adam.init(params))
    assert adam_bytes >= 2 * params_bytes * 0.9  # adamw: mu + nu, full size
    assert ada_bytes < params_bytes * 0.05, (
        f"adafactor state {ada_bytes}B not factored vs params {params_bytes}B"
    )


def test_unknown_optimizer_rejected():
    tc = TrainConfig(optimizer="sgd")
    with pytest.raises(ValueError, match="unknown optimizer"):
        build_optimizer(tc, None, total_steps=10, data_parallel_size=1)
