"""Generation engine tests: KV-cache decode vs full-sequence forward parity,
greedy determinism, EOS stopping, repetition penalty, sampling shape, and the
model-dir round trip that backs ask_tuned_model.py."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from llm_fine_tune_distributed_tpu.data.tokenizer import ByteChatMLTokenizer
from llm_fine_tune_distributed_tpu.infer import GenerationConfig, Generator
from llm_fine_tune_distributed_tpu.infer.sampling import apply_repetition_penalty, sample_token
from llm_fine_tune_distributed_tpu.models.configs import get_preset
from llm_fine_tune_distributed_tpu.models.transformer import forward, init_params


@pytest.fixture(scope="module")
def tiny_setup():
    mc = get_preset("tiny")
    params = init_params(jax.random.PRNGKey(0), mc, dtype=jnp.float32)
    tok = ByteChatMLTokenizer()
    return mc, params, tok


@pytest.mark.slow
def test_greedy_decode_matches_full_forward(tiny_setup):
    """Token t from the KV-cache loop == token t from re-running the whole
    prefix through the cache-free forward (numerical parity of the cache)."""
    mc, params, tok = tiny_setup
    gen = Generator(params, mc, tok, compute_dtype=jnp.float32, eos_token_ids=[])
    prompt = tok.encode("the quick brown fox")
    cfg = GenerationConfig(max_new_tokens=8, do_sample=False, repetition_penalty=1.0)
    out = gen.generate_ids(prompt, cfg)
    assert len(out) == 8

    seq = list(prompt)
    for tok_id in out:
        logits, _ = forward(
            params, jnp.asarray([seq], jnp.int32), mc, compute_dtype=jnp.float32
        )
        expect = int(jnp.argmax(logits[0, -1]))
        assert expect == tok_id
        seq.append(tok_id)


def test_greedy_is_deterministic(tiny_setup):
    mc, params, tok = tiny_setup
    gen = Generator(params, mc, tok, compute_dtype=jnp.float32, eos_token_ids=[])
    cfg = GenerationConfig(max_new_tokens=6, do_sample=False)
    a = gen.generate_ids(tok.encode("hello"), cfg, seed=0)
    b = gen.generate_ids(tok.encode("hello"), cfg, seed=7)  # seed irrelevant for greedy
    assert a == b


def test_eos_stops_generation(tiny_setup):
    """Force the first sampled token to be EOS by making eos the argmax."""
    mc, params, tok = tiny_setup
    cfg = GenerationConfig(max_new_tokens=16, do_sample=False, repetition_penalty=1.0)
    prompt = tok.encode("x")
    logits, _ = forward(params, jnp.asarray([prompt], jnp.int32), mc, compute_dtype=jnp.float32)
    forced_eos = int(jnp.argmax(logits[0, -1]))
    gen_forced = Generator(
        params, mc, tok, compute_dtype=jnp.float32, eos_token_ids=[forced_eos]
    )
    out = gen_forced.generate_ids(prompt, cfg)
    assert out == []  # first token was the stop token -> empty continuation


def test_sampled_generation_reproducible_and_in_vocab(tiny_setup):
    mc, params, tok = tiny_setup
    gen = Generator(params, mc, tok, compute_dtype=jnp.float32, eos_token_ids=[])
    cfg = GenerationConfig(max_new_tokens=10, do_sample=True, temperature=0.8, top_k=20)
    a = gen.generate_ids(tok.encode("abc"), cfg, seed=3)
    b = gen.generate_ids(tok.encode("abc"), cfg, seed=3)
    c = gen.generate_ids(tok.encode("abc"), cfg, seed=4)
    assert a == b
    assert all(0 <= t < mc.vocab_size for t in a)
    assert len(c) == 10


def test_repetition_penalty_semantics():
    logits = jnp.asarray([[2.0, -2.0, 1.0]])
    seen = jnp.asarray([[True, True, False]])
    out = apply_repetition_penalty(logits, seen, 2.0)
    np.testing.assert_allclose(np.asarray(out), [[1.0, -4.0, 1.0]])


def test_top_p_keeps_first_token():
    """Even with a tiny top_p, the most probable token must stay samplable."""
    rng = jax.random.PRNGKey(0)
    logits = jnp.asarray([[10.0, 0.0, -1.0, -2.0]])
    seen = jnp.zeros((1, 4), bool)
    cfg = GenerationConfig(do_sample=True, temperature=1.0, top_p=0.01, top_k=4,
                           repetition_penalty=1.0)
    t = sample_token(rng, logits, seen, cfg)
    assert int(t[0]) == 0


def test_chat_roundtrip_and_model_dir(tiny_setup, tmp_path):
    """save_hf_checkpoint -> load_model_dir -> chat() returns text (the
    artifact contract ask_tuned_model.py consumes)."""
    import json

    from llm_fine_tune_distributed_tpu.infer import load_model_dir, load_tokenizer_dir
    from llm_fine_tune_distributed_tpu.models.hf_io import save_hf_checkpoint

    mc, params, tok = tiny_setup
    d = tmp_path / "best_model"
    save_hf_checkpoint(params, str(d))
    tok.save_pretrained(str(d))
    with open(d / "config.json", "w") as f:
        json.dump(
            {
                "model_type": mc.name,
                "vocab_size": mc.vocab_size,
                "hidden_size": mc.hidden_size,
                "intermediate_size": mc.intermediate_size,
                "num_hidden_layers": mc.num_layers,
                "num_attention_heads": mc.num_heads,
                "num_key_value_heads": mc.num_kv_heads,
                "rope_theta": mc.rope_theta,
                "max_position_embeddings": mc.max_position_embeddings,
                "rms_norm_eps": mc.rms_norm_eps,
                "tie_word_embeddings": mc.tie_word_embeddings,
                "no_rope_layers": list(mc.no_rope_layers),
            },
            f,
        )
    params2, mc2 = load_model_dir(str(d))
    assert mc2.num_layers == mc.num_layers
    tok2 = load_tokenizer_dir(str(d))
    gen = Generator(params2, mc2, tok2, compute_dtype=jnp.float32)
    text = gen.chat(
        [{"role": "user", "content": "hi"}],
        GenerationConfig(max_new_tokens=5, do_sample=False),
    )
    assert isinstance(text, str)


def test_batched_ragged_matches_single(tiny_setup):
    """generate_batch on ragged prompts == generate_ids per prompt, token
    for token: per-row cache slots keep the slot == position invariant, so
    batching is numerically transparent (greedy)."""
    mc, params, tok = tiny_setup
    gen = Generator(params, mc, tok, compute_dtype=jnp.float32, eos_token_ids=[])
    cfg = GenerationConfig(max_new_tokens=6, do_sample=False, repetition_penalty=1.0)
    prompts = [
        tok.encode("the quick brown fox"),
        tok.encode("hi"),
        tok.encode("water purification methods in the wild"),
    ]
    batched = gen.generate_batch(prompts, cfg)
    for p, got in zip(prompts, batched):
        assert got == gen.generate_ids(p, cfg), f"prompt {p} diverged"


def test_batched_eos_stops_rows_independently(tiny_setup):
    """A row hitting EOS stops early (output trimmed) without truncating
    the other rows."""
    mc, params, tok = tiny_setup
    # find what greedy emits first for a prompt, then declare THAT token eos
    probe = Generator(params, mc, tok, compute_dtype=jnp.float32, eos_token_ids=[])
    cfg = GenerationConfig(max_new_tokens=5, do_sample=False, repetition_penalty=1.0)
    p1, p2 = tok.encode("abc"), tok.encode("the quick brown fox")
    first_tok = probe.generate_ids(p1, cfg)[0]
    other = probe.generate_ids(p2, cfg)
    if first_tok in other:
        other = other[: other.index(first_tok)]

    gen = Generator(params, mc, tok, compute_dtype=jnp.float32, eos_token_ids=[first_tok])
    out = gen.generate_batch([p1, p2], cfg)
    assert out[0] == []  # first emission was eos -> trimmed to empty
    assert out[1] == other


@pytest.mark.slow
def test_speculative_greedy_exact_equivalence(tiny_setup):
    """Prompt-lookup speculative decode must emit EXACTLY the plain greedy
    sequence — incl. evolving repetition penalty — on normal and highly
    repetitive prompts (where drafting actually engages)."""
    mc, params, tok = tiny_setup
    gen = Generator(params, mc, tok, compute_dtype=jnp.float32, eos_token_ids=[])
    for text in (
        "the quick brown fox",
        "water water water water water water",
        "abc abc abc abc abc abc abc abc",
    ):
        prompt = tok.encode(text)
        for rp in (1.0, 1.1):
            plain = gen.generate_ids(
                prompt,
                GenerationConfig(
                    max_new_tokens=12, do_sample=False, repetition_penalty=rp
                ),
            )
            spec = gen.generate_ids(
                prompt,
                GenerationConfig(
                    max_new_tokens=12, do_sample=False, repetition_penalty=rp,
                    speculative_lookup=4,
                ),
            )
            assert spec == plain, f"{text!r} rp={rp}: {spec} != {plain}"


def test_speculative_eos_stops(tiny_setup):
    mc, params, tok = tiny_setup
    probe = Generator(params, mc, tok, compute_dtype=jnp.float32, eos_token_ids=[])
    cfg = GenerationConfig(max_new_tokens=8, do_sample=False, repetition_penalty=1.0)
    prompt = tok.encode("the quick brown fox")
    plain = probe.generate_ids(prompt, cfg)
    eos_tok = plain[3]  # declare the 4th emission to be eos
    expect = plain[: plain.index(eos_tok)]

    gen = Generator(params, mc, tok, compute_dtype=jnp.float32, eos_token_ids=[eos_tok])
    spec_cfg = GenerationConfig(
        max_new_tokens=8, do_sample=False, repetition_penalty=1.0, speculative_lookup=4
    )
    assert gen.generate_ids(prompt, spec_cfg) == expect


@pytest.mark.slow
def test_speculative_batched_per_row_equivalence(tiny_setup):
    """Batched speculation (VERDICT r2 #6): every row of a speculative batch
    emits exactly the plain greedy sequence for ITS prompt — rows draft from
    their own contexts and desynchronize as acceptance diverges."""
    mc, params, tok = tiny_setup
    gen = Generator(params, mc, tok, compute_dtype=jnp.float32, eos_token_ids=[])
    prompts = [
        tok.encode("the quick brown fox"),
        tok.encode("water water water water water water"),
        tok.encode("abc abc abc abc abc abc abc abc"),
    ]
    plain_cfg = GenerationConfig(
        max_new_tokens=10, do_sample=False, repetition_penalty=1.0
    )
    spec_cfg = GenerationConfig(
        max_new_tokens=10, do_sample=False, repetition_penalty=1.0,
        speculative_lookup=4,
    )
    plain = [gen.generate_ids(p, plain_cfg) for p in prompts]
    batched = gen.generate_batch(prompts, spec_cfg)
    assert batched == plain
    assert gen.last_spec_steps is not None  # the batch really speculated
    assert gen.last_acceptance_rate is not None
    # the repetitive rows accept drafts, so the batch finishes in fewer
    # sequential forwards than tokens generated
    assert gen.last_acceptance_rate > 0

    # sampled batched speculation: seeded-deterministic, valid tokens
    sampled = GenerationConfig(max_new_tokens=4, do_sample=True, speculative_lookup=4)
    out = gen.generate_batch(prompts[:2], sampled, seed=1)
    assert all(0 <= t < mc.vocab_size for row in out for t in row)
    assert out == gen.generate_batch(prompts[:2], sampled, seed=1)
    assert gen.last_acceptance_rate is not None


@pytest.mark.slow
def test_speculative_accepts_on_repetitive_output(tiny_setup):
    """When greedy output repeats a bigram, drafting must accept multiple
    tokens per forward: sequential steps < generated tokens."""
    mc, params, tok = tiny_setup
    gen = Generator(params, mc, tok, compute_dtype=jnp.float32, eos_token_ids=[])
    spec_cfg = GenerationConfig(
        max_new_tokens=16, do_sample=False, repetition_penalty=1.0,
        speculative_lookup=4,
    )
    # find a prompt whose greedy continuation contains a repeated bigram
    plain_cfg = GenerationConfig(
        max_new_tokens=16, do_sample=False, repetition_penalty=1.0
    )
    for text in ("a", "the", "x y z", "hello world"):
        prompt = tok.encode(text)
        out = gen.generate_ids(prompt, plain_cfg)
        bigrams = list(zip(out, out[1:]))
        if len(set(bigrams)) < len(bigrams):  # some bigram repeats
            spec = gen.generate_ids(prompt, spec_cfg)
            assert spec == out
            assert gen.last_spec_steps is not None
            assert gen.last_spec_steps < len(spec), (
                f"no multi-accepts: {gen.last_spec_steps} steps for "
                f"{len(spec)} tokens"
            )
            return
    raise AssertionError("no repetitive greedy continuation found to test with")



@pytest.mark.slow
def test_sampled_speculative_near_greedy_temperature_matches(tiny_setup):
    """At a temperature low enough that the warped distribution is a point
    mass, rejection-sampling speculation must reproduce the deterministic
    plain-sampling output exactly (accept probability q(argmax) == 1)."""
    mc, params, tok = tiny_setup
    gen = Generator(params, mc, tok, compute_dtype=jnp.float32, eos_token_ids=[])
    prompt = tok.encode("hello world")
    base = dict(max_new_tokens=12, do_sample=True, temperature=1e-4,
                top_k=40, top_p=0.95, repetition_penalty=1.1)
    plain = GenerationConfig(**base)
    spec = GenerationConfig(**base, speculative_lookup=3)
    for seed in range(3):
        assert gen.generate_ids(prompt, spec, seed=seed) == gen.generate_ids(
            prompt, plain, seed=seed
        )


@pytest.mark.slow
def test_sampled_speculative_matches_plain_distribution(tiny_setup):
    """Rejection-sampling verification preserves the sampling distribution:
    over many seeds, the marginal token distribution at each position matches
    plain sampling's within the null noise level (calibrated by comparing
    two disjoint plain-sampling seed ranges against each other)."""
    mc, params, tok = tiny_setup
    gen = Generator(params, mc, tok, compute_dtype=jnp.float32, eos_token_ids=[])
    prompt = tok.encode("ab ab ab ab")  # repeated bigrams -> drafts fire
    n_pos = 3
    base = dict(max_new_tokens=n_pos, do_sample=True, temperature=1.0,
                top_k=20, top_p=0.95, repetition_penalty=1.1)
    plain = GenerationConfig(**base)
    spec = GenerationConfig(**base, speculative_lookup=3)

    n = 400
    from collections import Counter

    plain_a = [Counter() for _ in range(n_pos)]
    plain_b = [Counter() for _ in range(n_pos)]
    spec_c = [Counter() for _ in range(n_pos)]
    accepted_any = False
    for seed in range(n):
        a = gen.generate_ids(prompt, plain, seed=seed)
        b = gen.generate_ids(prompt, plain, seed=n + seed)
        c = gen.generate_ids(prompt, spec, seed=seed)
        accepted_any = accepted_any or (gen.last_acceptance_rate or 0) > 0
        for j in range(n_pos):
            plain_a[j][a[j]] += 1
            plain_b[j][b[j]] += 1
            spec_c[j][c[j]] += 1
    assert accepted_any, "no draft was ever accepted - the test has no power"

    def tv(x, y):
        support = set(x) | set(y)
        return 0.5 * sum(abs(x[t] / n - y[t] / n) for t in support)

    # position 0 precedes any speculation and shares the rng split layout:
    # bit-identical draws
    assert tv(plain_a[0], spec_c[0]) == 0.0
    for j in range(1, n_pos):
        null = tv(plain_a[j], plain_b[j])  # pure sampling noise at this n
        got = tv(plain_a[j], spec_c[j])
        assert got < 2.0 * null + 0.05, (
            f"position {j}: TV(plain, spec) = {got:.3f} vs plain-vs-plain "
            f"null {null:.3f} - speculative sampling skews the distribution"
        )


def test_generate_stream_matches_plain_decode(tiny_setup):
    """Streaming decode yields EXACTLY the plain decode's tokens, greedy and
    sampled (same sampler, same rng split sequence, chunked host readout)."""
    mc, params, tok = tiny_setup
    gen = Generator(params, mc, tok, compute_dtype=jnp.float32, eos_token_ids=[])
    prompt = tok.encode("the quick brown fox")
    for cfg in (
        GenerationConfig(max_new_tokens=11, do_sample=False, repetition_penalty=1.1),
        GenerationConfig(max_new_tokens=11, do_sample=True, temperature=0.8),
    ):
        plain = gen.generate_ids(prompt, cfg, seed=3)
        streamed = []
        for piece in gen.generate_stream(prompt, cfg, seed=3, chunk=4):
            streamed.extend(piece)
        assert streamed == plain, (cfg.do_sample, streamed, plain)


def test_generate_stream_stops_at_eos(tiny_setup):
    mc, params, tok = tiny_setup
    probe = Generator(params, mc, tok, compute_dtype=jnp.float32, eos_token_ids=[])
    cfg = GenerationConfig(max_new_tokens=10, do_sample=False, repetition_penalty=1.0)
    plain = probe.generate_ids(tok.encode("the quick brown fox"), cfg)
    eos_tok = plain[4]
    gen = Generator(
        params, mc, tok, compute_dtype=jnp.float32, eos_token_ids=[eos_tok]
    )
    streamed = []
    for piece in gen.generate_stream(tok.encode("the quick brown fox"), cfg, chunk=3):
        streamed.extend(piece)
    # the stream stops at the FIRST occurrence of the eos token (which may
    # be earlier than index 4 if the greedy sequence repeats tokens)
    assert streamed == plain[: plain.index(eos_tok)]
    assert eos_tok not in streamed


@pytest.mark.slow
def test_draft_model_speculation_exact_and_accepting(tiny_setup):
    """Draft-MODEL speculation: greedy output is exactly the plain greedy
    sequence regardless of the draft's quality; a perfect draft (the target
    itself) accepts every proposal, finishing in far fewer sequential
    forwards than tokens."""
    mc, params, tok = tiny_setup
    prompt = tok.encode("the quick brown fox")
    plain_cfg = GenerationConfig(max_new_tokens=12, do_sample=False, repetition_penalty=1.1)
    spec_cfg = GenerationConfig(
        max_new_tokens=12, do_sample=False, repetition_penalty=1.1,
        speculative_lookup=4,
    )
    base = Generator(params, mc, tok, compute_dtype=jnp.float32, eos_token_ids=[])
    plain = base.generate_ids(prompt, plain_cfg)

    # an unrelated (differently-initialized) draft: exactness must survive
    bad_draft = init_params(jax.random.PRNGKey(9), mc, dtype=jnp.float32)
    g_bad = Generator(
        params, mc, tok, compute_dtype=jnp.float32, eos_token_ids=[],
        draft_params=bad_draft, draft_config=mc,
    )
    assert g_bad.generate_ids(prompt, spec_cfg) == plain
    assert g_bad.last_acceptance_rate is not None

    # the target as its own draft: greedy proposals == greedy choices, so
    # every draft is accepted and steps collapse. max_new=11 = 1 (prefill)
    # + 2 steps x (1 + 4 drafts), so no draft is wasted on the max_new cap
    # and the acceptance rate is exactly 1.
    g_self = Generator(
        params, mc, tok, compute_dtype=jnp.float32, eos_token_ids=[],
        draft_params=params, draft_config=mc,
    )
    exact_cfg = GenerationConfig(
        max_new_tokens=11, do_sample=False, repetition_penalty=1.1,
        speculative_lookup=4,
    )
    plain11 = base.generate_ids(prompt, GenerationConfig(
        max_new_tokens=11, do_sample=False, repetition_penalty=1.1,
    ))
    assert g_self.generate_ids(prompt, exact_cfg) == plain11
    assert g_self.last_acceptance_rate == pytest.approx(1.0)
    assert g_self.last_spec_steps == 1 + 2  # prefill + 2 fully-accepted steps

    # sampled verify stays seeded-deterministic with a draft model
    sampled = GenerationConfig(max_new_tokens=6, do_sample=True, speculative_lookup=3)
    a = g_bad.generate_ids(prompt, sampled, seed=5)
    assert a == g_bad.generate_ids(prompt, sampled, seed=5)
    assert all(0 <= t < mc.vocab_size for t in a)


def test_draft_model_validation():
    from llm_fine_tune_distributed_tpu.models.configs import get_preset

    mc = get_preset("tiny")
    params = init_params(jax.random.PRNGKey(0), mc, dtype=jnp.float32)
    with pytest.raises(ValueError, match="come together"):
        Generator(params, mc, ByteChatMLTokenizer(), draft_params=params)
