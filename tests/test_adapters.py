"""Multi-tenant LoRA serving (infer/adapters.py + the pooled-gather branch
in models/transformer._linear, threaded through infer/engine.py).

Pins the tentpole contracts: slot 0 of the pool is an EXACT identity (base
rows co-batch bit-identically); each tenant's greedy tokens under
co-batched decode are bit-identical to serving that tenant's adapter
MERGED into the weights solo (``merge_lora``), with live neighbors, on
both slot engines, including speculative ticks; registry lifecycle is
refcount + LRU with pinned slots never evicted; adapter imports validate
``adapter_config.json`` against the model with errors naming the field;
tenant admission quotas shed with a tenant-scoped 429."""

import json
import os
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llm_fine_tune_distributed_tpu.config import TrainConfig
from llm_fine_tune_distributed_tpu.data.tokenizer import ByteChatMLTokenizer
from llm_fine_tune_distributed_tpu.infer import GenerationConfig, Generator
from llm_fine_tune_distributed_tpu.infer.adapters import AdapterRegistry
from llm_fine_tune_distributed_tpu.infer.engine import (
    ContinuousBatchingEngine,
    PagedContinuousBatchingEngine,
)
from llm_fine_tune_distributed_tpu.infer.errors import (
    AdapterPoolFullError,
    TenantQuotaError,
    UnknownAdapterError,
)
from llm_fine_tune_distributed_tpu.models.configs import get_preset
from llm_fine_tune_distributed_tpu.models.transformer import forward, init_params
from llm_fine_tune_distributed_tpu.parallel.lora import (
    add_lora_params,
    load_lora_adapter,
    merge_lora,
    save_lora_adapter,
    validate_adapter_config,
)

CFG = get_preset("tiny")
GREEDY = GenerationConfig(max_new_tokens=6, do_sample=False)
SAMPLED = GenerationConfig(max_new_tokens=24, do_sample=True, temperature=1.0)


def _make_adapter(base, outdir, seed, rank=4, alpha=8.0):
    """A PEFT-layout adapter directory with NON-ZERO B (fresh LoRA init has
    B=0, which would make every tenant's delta trivially identical)."""
    params = add_lora_params(base, jax.random.PRNGKey(seed), rank=rank, alpha=alpha)
    counter = [seed]

    def bump(node):
        if isinstance(node, dict):
            if "lora_b" in node:
                node = dict(node)
                rs = np.random.RandomState(counter[0])
                counter[0] += 1
                node["lora_b"] = jnp.asarray(
                    rs.normal(0.0, 0.02, node["lora_b"].shape), jnp.float32
                )
                return node
            return {k: bump(v) for k, v in node.items()}
        return node

    params = bump(params)
    cfg = TrainConfig(freeze_strategy="lora", lora_rank=rank, lora_alpha=alpha)
    save_lora_adapter(params, outdir, cfg)
    return params


@pytest.fixture(scope="module")
def base_params():
    return init_params(jax.random.PRNGKey(0), CFG, dtype=jnp.float32)


@pytest.fixture(scope="module")
def adapter_dir(base_params, tmp_path_factory):
    """Three tenants: t1/t2 at rank 4, t3 at rank 2 (exercises pool-rank
    zero-padding on the same pool)."""
    root = tmp_path_factory.mktemp("adapters")
    _make_adapter(base_params, str(root / "t1"), seed=1, rank=4)
    _make_adapter(base_params, str(root / "t2"), seed=2, rank=4)
    _make_adapter(base_params, str(root / "t3"), seed=3, rank=2)
    return str(root)


@pytest.fixture(scope="module")
def generator(base_params):
    return Generator(
        base_params, CFG, ByteChatMLTokenizer(),
        compute_dtype=jnp.float32, eos_token_ids=[],
    )


@pytest.fixture(scope="module")
def merged_refs(base_params, adapter_dir):
    """Per-tenant merged-weight solo generators — THE baseline co-batched
    serving must reproduce bit-for-bit."""
    tok = ByteChatMLTokenizer()
    out = {}
    for name in ("t1", "t2", "t3"):
        merged = merge_lora(
            load_lora_adapter(base_params, os.path.join(adapter_dir, name))
        )
        out[name] = Generator(
            merged, CFG, tok, compute_dtype=jnp.float32, eos_token_ids=[]
        )
    return out


def _prompts():
    tok = ByteChatMLTokenizer()
    return [tok.encode(t) for t in ("alpha", "beta bravo", "the quick brown fox")]


def _ids():
    return jnp.asarray(
        np.random.RandomState(1).randint(0, CFG.vocab_size, (2, 16)), jnp.int32
    )


# ------------------------------------------------------------------ registry


def test_pool_view_shapes_and_identity_slot(base_params, adapter_dir):
    reg = AdapterRegistry(base_params, adapter_dir, max_adapters=4)
    q = reg.params["model"]["layers"]["0"]["self_attn"]["q_proj"]
    assert q["lora_a_pool"].shape == (4, CFG.hidden_size, reg.rank)
    assert q["lora_b_pool"].shape[0] == 4 and q["lora_b_pool"].shape[1] == reg.rank
    assert q["lora_scale_pool"].shape == (4,)
    # pool rank = max rank across the adapters on disk
    assert reg.rank == 4
    # slot 0 (identity) produces EXACTLY the base forward — not approximately
    ids = _ids()
    ref, _ = forward(base_params, ids, CFG, compute_dtype=jnp.float32)
    idx0 = jnp.zeros((ids.shape[0],), jnp.int32)
    out, _ = forward(
        reg.params, ids, CFG, compute_dtype=jnp.float32, adapter_idx=idx0
    )
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_resident_adapter_matches_merged_forward(base_params, adapter_dir):
    """A loaded slot's pooled-gather forward equals the merged-weight
    forward — including the rank-2 adapter zero-padded into the rank-4
    pool (padding must be an exact no-op on the delta)."""
    reg = AdapterRegistry(base_params, adapter_dir, max_adapters=4)
    ids = _ids()
    for name in ("t1", "t3"):
        slot = reg.acquire(name)
        merged = merge_lora(
            load_lora_adapter(base_params, os.path.join(adapter_dir, name))
        )
        ref, _ = forward(merged, ids, CFG, compute_dtype=jnp.float32)
        idx = jnp.full((ids.shape[0],), slot, jnp.int32)
        out, _ = forward(
            reg.params, ids, CFG, compute_dtype=jnp.float32, adapter_idx=idx
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=1e-5
        )
        # and the delta is non-trivial (the fixture bumped B)
        base_out, _ = forward(base_params, ids, CFG, compute_dtype=jnp.float32)
        assert np.abs(np.asarray(out) - np.asarray(base_out)).max() > 1e-4
        reg.release(name)


def test_acquire_release_refcount_and_lru_eviction(base_params, adapter_dir):
    reg = AdapterRegistry(base_params, adapter_dir, max_adapters=3)  # 2 slots
    s1 = reg.acquire("t1")
    assert s1 != 0 and reg.slot_of("t1") == s1 and reg.refcount("t1") == 1
    assert reg.acquire("t1") == s1 and reg.refcount("t1") == 2
    s2 = reg.acquire("t2")
    assert s2 not in (0, s1)
    # both pinned: a third tenant cannot load
    with pytest.raises(AdapterPoolFullError) as ei:
        reg.acquire("t3")
    assert ei.value.status == 429
    # released-but-resident adapters stay warm...
    reg.release("t2")
    assert reg.is_resident("t2") and reg.refcount("t2") == 0
    assert reg.acquire("t2") == s2  # re-acquire hits the warm slot, no load
    reg.release("t2")
    # ...and only the IDLE one is evicted when t3 needs a slot (t1 is
    # still pinned twice)
    s3 = reg.acquire("t3")
    assert s3 == s2
    assert not reg.is_resident("t2")
    assert reg.is_resident("t1") and reg.refcount("t1") == 2
    snap_resident = sorted(reg.resident())
    assert snap_resident == ["t1", "t3"]


def test_unknown_adapter_rejected_with_known_list(base_params, adapter_dir):
    reg = AdapterRegistry(base_params, adapter_dir, max_adapters=4)
    with pytest.raises(UnknownAdapterError) as ei:
        reg.acquire("nope")
    assert ei.value.status == 404
    assert set(ei.value.known) == {"t1", "t2", "t3"}
    assert set(ei.value.to_dict()["known_adapters"]) == {"t1", "t2", "t3"}
    # path traversal is an unknown name, not a filesystem walk
    with pytest.raises(UnknownAdapterError):
        reg.acquire(f"..{os.sep}t1")


def test_rebuild_restores_resident_slots(base_params, adapter_dir):
    """The crash-recovery path: after the pools are clobbered (what a
    fresh-state restart simulates), ``rebuild()`` restores every resident
    adapter's slot values exactly from the host copies."""
    reg = AdapterRegistry(base_params, adapter_dir, max_adapters=4)
    slot = reg.acquire("t1")
    site = reg.params["model"]["layers"]["0"]["self_attn"]["q_proj"]
    before = np.asarray(site["lora_a_pool"])
    assert np.abs(before[slot]).max() > 0
    for s in reg._sites.values():
        s["lora_a_pool"] = jnp.zeros_like(s["lora_a_pool"])
        s["lora_b_pool"] = jnp.zeros_like(s["lora_b_pool"])
        s["lora_scale_pool"] = jnp.zeros_like(s["lora_scale_pool"])
    reg.rebuild()
    np.testing.assert_array_equal(np.asarray(site["lora_a_pool"]), before)
    assert reg.slot_of("t1") == slot


# ------------------------------------------- adapter_config.json validation


def _valid_acfg(rank=4):
    return {
        "r": rank,
        "lora_alpha": 8.0,
        "target_modules": ["q_proj", "v_proj"],
    }


def test_validate_config_names_the_bad_field(base_params):
    for bad, field in [
        ({**_valid_acfg(), "r": 0}, "'r'"),
        ({**_valid_acfg(), "r": "four"}, "'r'"),
        ({**_valid_acfg(), "lora_alpha": -1}, "'lora_alpha'"),
        ({**_valid_acfg(), "lora_alpha": None}, "'lora_alpha'"),
        ({**_valid_acfg(), "target_modules": []}, "'target_modules'"),
        ({**_valid_acfg(), "target_modules": ["made_up_proj"]}, "'target_modules'"),
    ]:
        with pytest.raises(ValueError) as ei:
            validate_adapter_config(bad, base_params)
        assert field in str(ei.value), f"{bad} -> {ei.value}"
    # the unknown-module error lists what the model DOES have
    with pytest.raises(ValueError, match="q_proj"):
        validate_adapter_config(
            {**_valid_acfg(), "target_modules": ["made_up_proj"]}, base_params
        )
    validate_adapter_config(_valid_acfg(), base_params)  # sanity: valid passes


def test_config_tensor_rank_mismatch_names_r(base_params, adapter_dir, tmp_path):
    """A config whose 'r' disagrees with the saved tensors fails naming the
    field, not with a reshape error inside the tree merge."""
    import shutil

    bad = tmp_path / "bad_r"
    shutil.copytree(os.path.join(adapter_dir, "t1"), bad)
    cfg_path = bad / "adapter_config.json"
    acfg = json.loads(cfg_path.read_text())
    acfg["r"] = 8  # tensors were saved at rank 4
    cfg_path.write_text(json.dumps(acfg))
    with pytest.raises(ValueError, match="'r'"):
        load_lora_adapter(base_params, str(bad))


def test_registry_rejects_adapter_above_pool_rank(base_params, adapter_dir, tmp_path):
    reg = AdapterRegistry(base_params, adapter_dir, max_adapters=4, rank=2)
    with pytest.raises(ValueError, match="pool rank"):
        reg.acquire("t1")  # rank 4 > forced pool rank 2


# ------------------------------------------------------- engine integration


def _engine(generator, reg, kind, **kw):
    if kind == "paged":
        return PagedContinuousBatchingEngine(
            generator, slots=4, buf_len=96, prompt_bucket=16, block_len=16,
            prefill_chunk=32, adapters=reg, **kw,
        )
    return ContinuousBatchingEngine(
        generator, slots=4, buf_len=96, prompt_bucket=16, adapters=reg, **kw
    )


@pytest.mark.parametrize("kind", ["continuous", "paged"])
def test_cobatched_tenants_bit_identical_to_merged_solo(
    generator, base_params, adapter_dir, merged_refs, kind
):
    """THE tentpole guarantee: tenants t1/t2/base co-batched in ONE decode
    dispatch (plus a live sampled neighbor) each produce exactly the tokens
    of their adapter merged into the weights and served solo."""
    reg = AdapterRegistry(base_params, adapter_dir, max_adapters=4)
    eng = _engine(generator, reg, kind)
    prompts = _prompts()
    want = {
        "t1": merged_refs["t1"].generate_ids(prompts[0], GREEDY),
        "t2": merged_refs["t2"].generate_ids(prompts[1], GREEDY),
        "base": generator.generate_ids(prompts[2], GREEDY),
    }
    results = {}

    def occupy():  # a live sampled base-model neighbor in the same batch
        eng.submit(prompts[0], SAMPLED, seed=11, timeout=240)

    def ask(key, prompt, adapter):
        results[key] = eng.submit(prompt, GREEDY, timeout=240, adapter=adapter)

    occupier = threading.Thread(target=occupy)
    occupier.start()
    time.sleep(0.05)
    threads = [
        threading.Thread(target=ask, args=("t1", prompts[0], "t1")),
        threading.Thread(target=ask, args=("t2", prompts[1], "t2")),
        threading.Thread(target=ask, args=("base", prompts[2], None)),
    ]
    for t in threads:
        t.start()
    for t in threads + [occupier]:
        t.join(timeout=240)
    assert results == want
    # the tenants' outputs are genuinely adapted (differ from base)
    assert results["t1"] != generator.generate_ids(prompts[0], GREEDY)
    # pins were released at settle; both adapters stay warm
    assert reg.refcount("t1") == 0 and reg.refcount("t2") == 0
    assert sorted(reg.resident()) == ["t1", "t2"]
    # per-tenant accounting: one request and max_new_tokens tokens each
    snap = eng.stats_snapshot()
    for name in ("t1", "t2"):
        assert snap["per_tenant"][name]["requests"] == 1
        assert snap["per_tenant"][name]["tokens"] == GREEDY.max_new_tokens
        assert snap["per_tenant"][name]["queue_depth"] == 0
    assert snap["adapters_resident"] == 2
    assert snap["adapter_loads"] == 2


@pytest.mark.parametrize("kind", ["continuous", "paged"])
def test_speculative_cobatch_bit_identical_per_tenant(
    generator, base_params, adapter_dir, merged_refs, kind
):
    """Adapters compose with the fused draft+verify tick: greedy
    speculative output per tenant equals that tenant's plain merged-solo
    greedy decode (speculation may change step count, never tokens)."""
    reg = AdapterRegistry(base_params, adapter_dir, max_adapters=4)
    eng = _engine(generator, reg, kind, speculative_k=4)
    tok = ByteChatMLTokenizer()
    # repetitive prompts so prompt-lookup actually drafts (same trick as
    # tests/test_engine_speculative.py)
    prompts = [tok.encode("water water water water water"),
               tok.encode("abc abc abc abc abc")]
    cfg = GenerationConfig(
        max_new_tokens=12, do_sample=False, speculative_lookup=4
    )
    plain = GenerationConfig(max_new_tokens=12, do_sample=False)
    want = {
        "t1": merged_refs["t1"].generate_ids(prompts[0], plain),
        "t2": merged_refs["t2"].generate_ids(prompts[1], plain),
    }
    results = {}

    def ask(key, prompt, adapter):
        results[key] = eng.submit(prompt, cfg, timeout=240, adapter=adapter)

    threads = [
        threading.Thread(target=ask, args=("t1", prompts[0], "t1")),
        threading.Thread(target=ask, args=("t2", prompts[1], "t2")),
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=240)
    assert results == want


def test_engine_without_registry_rejects_adapter(generator):
    eng = ContinuousBatchingEngine(
        generator, slots=2, buf_len=96, prompt_bucket=16
    )
    with pytest.raises(UnknownAdapterError, match="--adapter-dir"):
        eng.submit(_prompts()[0], GREEDY, timeout=30, adapter="t1")


def test_unknown_adapter_through_engine(generator, base_params, adapter_dir):
    reg = AdapterRegistry(base_params, adapter_dir, max_adapters=4)
    eng = ContinuousBatchingEngine(
        generator, slots=2, buf_len=96, prompt_bucket=16, adapters=reg
    )
    with pytest.raises(UnknownAdapterError) as ei:
        eng.submit(_prompts()[0], GREEDY, timeout=30, adapter="ghost")
    assert ei.value.status == 404 and "t1" in ei.value.known


def test_tenant_quota_sheds_with_429(generator, base_params, adapter_dir):
    """--adapter-capacity: tenant t1's second concurrent request is shed
    with a tenant-scoped retryable 429 while t2 is still admitted; the
    quota slot frees at settle."""
    reg = AdapterRegistry(base_params, adapter_dir, max_adapters=4)
    eng = ContinuousBatchingEngine(
        generator, slots=4, buf_len=96, prompt_bucket=16,
        adapters=reg, adapter_quota=1,
    )
    prompts = _prompts()
    long_cfg = GenerationConfig(max_new_tokens=64, do_sample=False)
    t = threading.Thread(
        target=lambda: eng.submit(prompts[0], long_cfg, timeout=240, adapter="t1")
    )
    t.start()
    deadline = time.monotonic() + 30
    while eng.stats_snapshot()["per_tenant"].get("t1", {}).get("requests", 0) < 1:
        assert time.monotonic() < deadline, "t1 request never admitted"
        time.sleep(0.01)
    with pytest.raises(TenantQuotaError) as ei:
        eng.submit(prompts[1], GREEDY, timeout=30, adapter="t1")
    assert ei.value.status == 429 and ei.value.retryable
    assert ei.value.retry_after_s is not None
    # a DIFFERENT tenant is unaffected by t1's quota
    assert (
        eng.submit(prompts[1], GREEDY, timeout=240, adapter="t2") is not None
    )
    t.join(timeout=240)
    # quota slot released at settle: t1 admits again
    assert eng.submit(prompts[0], GREEDY, timeout=240, adapter="t1") is not None
    snap = eng.stats_snapshot()
    assert snap["requests_shed_tenant_quota"] == 1
    assert snap["per_tenant"]["t1"]["requests"] == 2  # shed one never counted
