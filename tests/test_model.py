"""Model core tests: shapes, param counts, KV-cache consistency, presets."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llm_fine_tune_distributed_tpu.models import get_preset, init_params
from llm_fine_tune_distributed_tpu.models.transformer import forward, init_cache
from llm_fine_tune_distributed_tpu.utils.tree import count_params


@pytest.fixture(scope="module")
def tiny():
    cfg = get_preset("tiny")
    params = init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def test_param_count_matches_formula(tiny):
    cfg, params = tiny
    assert count_params(params) == cfg.num_params


def test_smollm3_param_count_is_3b():
    # claude.md:243 reports 3.075B total params for SmolLM3-3B.
    cfg = get_preset("smollm3_3b")
    assert abs(cfg.num_params - 3.075e9) / 3.075e9 < 0.01


def test_forward_shapes_and_dtype(tiny):
    cfg, params = tiny
    ids = jnp.array([[1, 2, 3, 4, 5, 6, 7, 8]], dtype=jnp.int32)
    logits, cache = forward(params, ids, cfg, compute_dtype=jnp.float32)
    assert logits.shape == (1, 8, cfg.vocab_size)
    assert logits.dtype == jnp.float32
    assert cache is None
    assert np.isfinite(np.asarray(logits)).all()


def test_padding_mask_changes_nothing_for_valid_tokens(tiny):
    """Causal attention: masking out future padding must not change logits of
    real positions."""
    cfg, params = tiny
    ids_full = jnp.array([[5, 6, 7, 8, 1, 1, 1, 1]], dtype=jnp.int32)
    mask = jnp.array([[1, 1, 1, 1, 0, 0, 0, 0]], dtype=jnp.int32)
    lg_masked, _ = forward(params, ids_full, cfg, padding_mask=mask, compute_dtype=jnp.float32)
    lg_plain, _ = forward(params, ids_full[:, :4], cfg, compute_dtype=jnp.float32)
    np.testing.assert_allclose(
        np.asarray(lg_masked[:, :4]), np.asarray(lg_plain), rtol=1e-5, atol=1e-5
    )


@pytest.mark.slow
def test_kv_cache_decode_matches_full_forward(tiny):
    """Prefill + one-token-at-a-time decode must reproduce the full forward
    pass logits (the correctness gate for infer/generate.py)."""
    cfg, params = tiny
    ids = jax.random.randint(jax.random.PRNGKey(1), (2, 10), 0, cfg.vocab_size)
    full_logits, _ = forward(params, ids, cfg, compute_dtype=jnp.float32)

    cache = init_cache(cfg, batch_size=2, max_len=16, dtype=jnp.float32)
    prefill_len = 6
    lg, cache = forward(
        params, ids[:, :prefill_len], cfg, cache=cache, cache_pos=0, compute_dtype=jnp.float32
    )
    np.testing.assert_allclose(
        np.asarray(lg), np.asarray(full_logits[:, :prefill_len]), rtol=2e-4, atol=2e-4
    )
    for t in range(prefill_len, 10):
        lg, cache = forward(
            params, ids[:, t : t + 1], cfg, cache=cache, cache_pos=t, compute_dtype=jnp.float32
        )
        np.testing.assert_allclose(
            np.asarray(lg[:, 0]), np.asarray(full_logits[:, t]), rtol=2e-4, atol=2e-4
        )


@pytest.mark.slow
def test_remat_matches_no_remat(tiny):
    cfg, params = tiny
    ids = jnp.array([[1, 2, 3, 4]], dtype=jnp.int32)

    def loss(p, remat):
        lg, _ = forward(p, ids, cfg, compute_dtype=jnp.float32, remat=remat)
        return jnp.mean(lg**2)

    g1 = jax.grad(lambda p: loss(p, False))(params)
    g2 = jax.grad(lambda p: loss(p, True))(params)
    flat1 = jax.tree_util.tree_leaves(g1)
    flat2 = jax.tree_util.tree_leaves(g2)
    for a, b in zip(flat1, flat2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)


def test_untied_and_sliding_window_preset():
    cfg = get_preset("tiny_mistral")
    params = init_params(jax.random.PRNGKey(0), cfg)
    assert "lm_head" in params
    ids = jnp.ones((1, 8), dtype=jnp.int32)
    logits, _ = forward(params, ids, cfg, compute_dtype=jnp.float32)
    assert logits.shape == (1, 8, cfg.vocab_size)


def test_smollm3_nope_pattern():
    cfg = get_preset("smollm3_3b")
    # every 4th layer (1-indexed) has NO rope — HF SmolLM3Config convention.
    assert not cfg.uses_rope(3) and not cfg.uses_rope(7) and not cfg.uses_rope(35)
    assert cfg.uses_rope(0) and cfg.uses_rope(34)
    assert sum(cfg.no_rope_layers) == 27


def test_qk_norm_cache_decode_and_grad():
    """Qwen3-style qk_norm: cached decode matches the full forward, and the
    norm weights receive gradient (they sit inside the attention block)."""
    cfg = get_preset("tiny").replace(qk_norm=True, name="tiny_qwen3")
    params = init_params(jax.random.PRNGKey(0), cfg)
    ids = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0, cfg.vocab_size)
    full_logits, _ = forward(params, ids, cfg, compute_dtype=jnp.float32)

    cache = init_cache(cfg, batch_size=2, max_len=8, dtype=jnp.float32)
    lg, cache = forward(params, ids[:, :5], cfg, cache=cache, cache_pos=0,
                        compute_dtype=jnp.float32)
    for t in range(5, 8):
        lg, cache = forward(params, ids[:, t:t + 1], cfg, cache=cache,
                            cache_pos=t, compute_dtype=jnp.float32)
        np.testing.assert_allclose(
            np.asarray(lg[:, 0]), np.asarray(full_logits[:, t]),
            rtol=2e-4, atol=2e-4,
        )

    def loss(p):
        out, _ = forward(p, ids, cfg, compute_dtype=jnp.float32)
        return jnp.mean(out**2)

    g = jax.jit(jax.grad(loss))(params)
    gq = g["model"]["layers"]["0"]["self_attn"]["q_norm"]["weight"]
    assert float(jnp.abs(gq).sum()) > 0.0


def test_auto_remat_policy_by_size_and_seq():
    """Auto remat resolution: measured-fastest per (model size, seq) cell —
    BASELINE.md 'Long-context single-chip series'."""
    from llm_fine_tune_distributed_tpu.config import TrainConfig

    small, big = get_preset("smollm3_3b"), get_preset("llama3_8b")
    assert TrainConfig(max_seq_length=1024).resolved_remat_policy(small) == "dots_no_batch"
    assert TrainConfig(max_seq_length=4096).resolved_remat_policy(small) == "mlp"
    assert TrainConfig(max_seq_length=8192).resolved_remat_policy(small) == "full"
    # seq-parallel: the ledger keys on PER-CHIP seq — global 8k over a
    # 4-chip seq axis is 2k/chip, back to the fastest policy
    assert (
        TrainConfig(max_seq_length=8192).resolved_remat_policy(small, seq_parallel_size=4)
        == "dots_no_batch"
    )
    assert (
        TrainConfig(max_seq_length=8192).resolved_remat_policy(small, seq_parallel_size=2)
        == "mlp"
    )
    assert TrainConfig(max_seq_length=1024).resolved_remat_policy(big) == "full"
    assert (
        TrainConfig(max_seq_length=4096, remat_policy="dots").resolved_remat_policy(small)
        == "dots"
    )


def test_static_seq_parallel_size_gates_on_live_seq_path(eight_devices):
    """The auto remat policy must key on the seq sharding that ACTUALLY
    applies (ADVICE r4): a provisioned seq axis counts only when the
    attention impl is ring/ulysses AND the static preconditions hold —
    otherwise runtime falls back to full per-chip sequences and a divided
    policy would under-remat and OOM."""
    from jax.sharding import Mesh

    from llm_fine_tune_distributed_tpu.config import MeshConfig, TrainConfig
    from llm_fine_tune_distributed_tpu.runtime.mesh import make_mesh
    from llm_fine_tune_distributed_tpu.train.step import static_seq_parallel_size

    small = get_preset("smollm3_3b")
    mesh = make_mesh(MeshConfig(data=1, fsdp=2, tensor=1, seq=4), eight_devices)

    # live seq axis + ring + divisible -> the axis counts
    tc = TrainConfig(max_seq_length=8192, attention_impl="ring")
    assert static_seq_parallel_size(small, tc, mesh) == 4
    # seq axis provisioned but attention_impl is not sequence-parallel:
    # runtime never shards the sequence -> full per-chip seq
    tc = TrainConfig(max_seq_length=8192, attention_impl="flash")
    assert static_seq_parallel_size(small, tc, mesh) == 1
    # indivisible seq length -> runtime fallback -> full per-chip seq
    tc = TrainConfig(max_seq_length=8190, attention_impl="ring")
    assert static_seq_parallel_size(small, tc, mesh) == 1
    # ulysses capped by kv heads: smollm3 has 4 kv heads, seq=4 divides ->
    # live; a model with 2 kv heads on seq=4 falls back
    tc = TrainConfig(max_seq_length=8192, attention_impl="ulysses")
    assert static_seq_parallel_size(small, tc, mesh) == 4
    assert static_seq_parallel_size(get_preset("tiny"), tc, mesh) == 1
    # sliding-window models: seq-parallel impls reject windows
    tc = TrainConfig(max_seq_length=8192, attention_impl="ring")
    assert static_seq_parallel_size(get_preset("mistral_7b").replace(
        sliding_window=4096), tc, mesh) == 1
    # no mesh -> 1
    assert static_seq_parallel_size(small, tc, None) == 1


def test_gemma2_preset_param_count_and_decode():
    """gemma2_9b preset arithmetic (9.24B, HF google/gemma-2-9b) and
    KV-cache decode self-consistency for the full Gemma2 feature set
    (sandwich norms, softcaps, alternating local/global window)."""
    cfg9 = get_preset("gemma2_9b")
    assert 9.0e9 < cfg9.num_params < 9.5e9
    # local/global alternation
    assert cfg9.layer_sliding_window(0) == 4096
    assert cfg9.layer_sliding_window(1) is None

    tiny = cfg9.replace(
        vocab_size=128, hidden_size=32, intermediate_size=64, num_layers=4,
        num_heads=4, num_kv_heads=2, head_dim=16, sliding_window=6,
        query_pre_attn_scalar=16.0, max_position_embeddings=64,
    )
    params = init_params(jax.random.PRNGKey(0), tiny, dtype=jnp.float32)
    assert count_params(params) == tiny.num_params
    l0 = params["model"]["layers"]["0"]
    assert "pre_feedforward_layernorm" in l0
    assert float(l0["input_layernorm"]["weight"].sum()) == 0.0  # zero-centered

    ids = jax.random.randint(jax.random.PRNGKey(1), (2, 12), 0, tiny.vocab_size)
    full_logits, _ = forward(params, ids, tiny, compute_dtype=jnp.float32)

    cache = init_cache(tiny, batch_size=2, max_len=12, dtype=jnp.float32)
    lg, cache = forward(params, ids[:, :7], tiny, cache=cache, cache_pos=0,
                        compute_dtype=jnp.float32)
    np.testing.assert_allclose(
        np.asarray(lg), np.asarray(full_logits[:, :7]), rtol=2e-4, atol=2e-4
    )
    for t in range(7, 12):
        lg, cache = forward(params, ids[:, t:t + 1], tiny, cache=cache,
                            cache_pos=t, compute_dtype=jnp.float32)
        np.testing.assert_allclose(
            np.asarray(lg[:, 0]), np.asarray(full_logits[:, t]),
            rtol=2e-4, atol=2e-4,
        )


def test_new_families_shard_on_mesh():
    """Qwen3 (qk_norm) and Gemma2 (sandwich norms etc.) param trees shard
    and train-step on the 8-device mesh: the new 1-D leaves replicate, the
    jitted fwd+grad matches the unsharded forward."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from llm_fine_tune_distributed_tpu.config import MeshConfig
    from llm_fine_tune_distributed_tpu.parallel.sharding import param_sharding_rules
    from llm_fine_tune_distributed_tpu.runtime.mesh import make_mesh

    mesh = make_mesh(MeshConfig(data=2, fsdp=2, tensor=2, seq=1))
    for preset, tweak in (
        ("tiny", dict(qk_norm=True, name="tiny_qwen3")),
        ("tiny_gemma2", {}),
    ):
        cfg = get_preset(preset).replace(**tweak)
        params = init_params(jax.random.PRNGKey(0), cfg, dtype=jnp.float32)
        ids = jnp.ones((4, 16), jnp.int32)

        def loss(p, sharding=None):
            lg, _ = forward(p, ids, cfg, compute_dtype=jnp.float32,
                            activation_sharding=sharding)
            return lg.mean(), lg

        (_, ref_logits), ref_grads = jax.value_and_grad(loss, has_aux=True)(params)
        sharded = jax.tree_util.tree_map(
            jax.device_put, params, param_sharding_rules(params, mesh)
        )
        act = NamedSharding(mesh, P(("data", "fsdp"), None, None))
        (_, lg), g = jax.jit(
            jax.value_and_grad(lambda p: loss(p, act), has_aux=True)
        )(sharded)
        np.testing.assert_allclose(
            np.asarray(lg), np.asarray(ref_logits), rtol=2e-4, atol=2e-4
        )
        for a, b in zip(
            jax.tree_util.tree_leaves(ref_grads), jax.tree_util.tree_leaves(g)
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-5
            )
